"""Benchmark: covering-index build throughput (rows/sec/chip).

Generates a TPC-H-lineitem-like table, builds a covering index through the
full API (decode -> device hash+sort kernel -> bucketed parquet write), and
reports end-to-end build throughput per chip.

Baseline (BASELINE.md): >= 1,000,000 rows/sec/chip; ``vs_baseline`` is
value / 1e6.

Prints exactly ONE JSON line.

``--serve`` runs the serving-runtime benchmark instead (plan-cache-on vs off
throughput through a QueryServer) and also writes BENCH_serving.json.

``--obs-overhead`` runs the observability-overhead benchmark: the standard
serving workload with span tracing off vs on, plus a disabled-path span
microbenchmark; writes BENCH_obs.json. The acceptance bar is <= 3% throughput
regression with tracing DISABLED (the instrumentation points are
unconditional; only their cost must vanish).

``--scan-pipeline`` runs the pipelined scan engine benchmark (cold-cache
streamed filter scan, pipelined vs serial, byte-identity and XLA-compile-count
checks) and writes BENCH_scan_pipeline.json. Bar: >= 1.4x. The same run also
measures native-vs-pyarrow cold-cache decode on uncompressed files (bar:
>= 2x GB/s) and writes BENCH_native.json.

``--slo-serve`` runs the SLO-aware serving benchmark (interactive p99 under a
heavy flood, FIFO vs cost-aware scheduler, plus result-cache vs
plan-cache-only throughput) and writes BENCH_slo.json. Bars: >= 2x p99, >= 3x
hit-path throughput at >= 95% hit rate.

``--mesh`` runs the mesh-sharded execution benchmark: the q1-shaped grouped
aggregate under ``hyperspace.parallel.enabled`` at emulated mesh sizes
{1, 2, 4, 8} (one subprocess per size, each forcing
``--xla_force_host_platform_device_count=N``), reporting rows/sec/chip per
size and the flatness ratio (8-way per-chip / 1-way per-chip). The bar on
real hardware is >= 0.7x; the JSON's ``platform`` field says honestly when
the "chips" are emulated host devices sharing one CPU, where per-chip
throughput necessarily divides. Writes BENCH_mesh.json.

``--check-overhead`` prices the hscheck runtime hook: the disabled
``maybe_verify`` per-call cost as a percentage of a mean program-cache fill
(bar: <= 1%), with the enabled once-per-executable verify cost reported for
context. Writes BENCH_check.json.

``--join`` runs the streaming join engine benchmark: a q3-shaped 3-table
chain (fact joined through two broadcast dimensions, filter + projection on
top) streamed cold-cache with the prefetch pipeline on vs off, byte-identity
and probe-executable-count checks, plus shared-build-side hit counting under
micro-batched serving. Bar: >= 1.5x pipelined/serial. Writes BENCH_join.json.

``--fusion`` runs the whole-plan fusion compiler benchmark: a q3-shaped
Filter -> Join -> Agg chain streamed chunk-by-chunk, the fused
one-program-per-chunk path vs the per-family dispatch sequence it replaces
(hash-probe + post-join filter + grouped chunk + merge), reporting chunk
throughput, `hs_xla_compiles_total` and `hs_device_dispatches_total` deltas,
and the `hs_device_peak_bytes` high-water mark. Hard checks (any backend):
results match, >= 3x dispatch reduction, zero warm-run compiles. Bar
(chip only): >= 1.5x chunk throughput. Writes BENCH_fusion.json. `--groupby`
and `--topk` exercise their fused device paths (`hyperspace.exec.fusion.enabled`)
so those JSONs price the same programs.

``--refresh`` runs the lifecycle benchmark: serving latency percentiles while
the refresh manager commits incremental refreshes concurrently vs a quiesced
baseline, with every served result checked for staleness/torn visibility
(the count must be zero). Writes BENCH_refresh.json.

``--faults`` runs the reliability benchmark: the serving workload clean vs
under a 1% injected transient-fault rate at the decode seam with the retry
policy on, cold decode every query (io cache disabled) so the seam is
actually exercised. Every served result is compared against a clean oracle
digest. Bars: zero wrong answers, zero unclassified errors, faulted p99
<= 3x clean p99. Writes BENCH_faults.json.

``--failover`` runs the fabric crash-tolerance benchmark: 3 fabric worker
processes behind a health-aware FrontDoor, one SIGKILLed under client load.
Every request is checked against the expected answer. Bars: zero requests
lost, zero wrong answers, dead-worker ejection within 2 heartbeat
intervals. Writes BENCH_failover.json.
"""

from __future__ import annotations

import json
import os
import shutil
import sys
import tempfile
import time

import numpy as np


def make_lineitem_like(root: str, num_rows: int, num_files: int = 8) -> None:
    import pyarrow as pa
    import pyarrow.parquet as pq

    rng = np.random.default_rng(0)
    per = num_rows // num_files
    base = np.datetime64("1992-01-01")
    for i in range(num_files):
        table = pa.table(
            {
                "l_orderkey": rng.integers(0, num_rows // 4, per).astype(np.int64),
                "l_partkey": rng.integers(0, 200_000, per).astype(np.int64),
                "l_quantity": rng.integers(1, 50, per).astype(np.int64),
                "l_extendedprice": rng.uniform(900.0, 105000.0, per),
                "l_discount": rng.uniform(0.0, 0.1, per),
                "l_shipdate": base + rng.integers(0, 2500, per).astype("timedelta64[D]"),
            }
        )
        pq.write_table(table, os.path.join(root, f"part-{i:05d}.parquet"))


def _honor_cpu_request() -> None:
    """The axon sitecustomize sets jax_platforms on the config object at
    interpreter startup, silently overriding a JAX_PLATFORMS=cpu env request
    (smoke runs without the chip); enforce the env on the config object."""
    if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
        import jax

        jax.config.update("jax_platforms", "cpu")


def _backend_watchdog(timeout_s: float = 75.0, retries: int = 3, emit=None) -> None:
    """``jax.devices()`` hangs indefinitely when the TPU tunnel is down (a
    flaky tunnel once burned a whole capture window); probe the backend in a
    subprocess with a hard timeout so an unreachable chip fails FAST with a
    diagnostic instead of hanging. Retries cover transient tunnel blips.
    No-op under JAX_PLATFORMS=cpu (nothing to tunnel). ``emit(reason)``
    customizes the failure line (benchmarks/run.py emits its own schema)."""
    if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
        return
    import subprocess

    last = "unknown"
    for attempt in range(1, retries + 1):
        try:
            r = subprocess.run(
                [sys.executable, "-c", "import jax; print(len(jax.devices()))"],
                timeout=timeout_s,
                capture_output=True,
                text=True,
            )
            if r.returncode == 0 and r.stdout.strip().isdigit():
                return
            last = (r.stderr or r.stdout).strip().splitlines()[-1:] or ["empty output"]
            last = last[0]
        except subprocess.TimeoutExpired:
            last = f"jax.devices() hung >{timeout_s:.0f}s (TPU tunnel down?)"
        if attempt < retries:
            time.sleep(10 * attempt)
    reason = f"backend unreachable after {retries} probes: {last}"
    if emit is not None:
        emit(reason)
    else:
        print(
            json.dumps(
                {
                    "metric": "covering_index_build_rows_per_sec_per_chip",
                    "value": 0,
                    "unit": "rows/s/chip",
                    "vs_baseline": 0,
                    "error": reason,
                }
            )
        )
    sys.exit(1)


def serve_main() -> None:
    """``python bench.py --serve``: serving-runtime benchmark.

    Repeated same-structure queries (16 literal variants of an indexed filter)
    through a QueryServer with the plan cache on vs off; reports throughput,
    speedup, hit rates, and latency percentiles to stdout AND
    BENCH_serving.json (one schema, both places).
    """
    _honor_cpu_request()
    _backend_watchdog()
    num_rows = int(os.environ.get("BENCH_SERVE_ROWS", 8_000))
    reps = max(1, int(os.environ.get("BENCH_SERVE_REPS", 30)))
    tmp = tempfile.mkdtemp(prefix="hs_bench_serve_")
    try:
        import pyarrow as pa
        import pyarrow.parquet as pq

        import hyperspace_tpu as hst
        from hyperspace_tpu.serving import QueryServer

        data_dir = os.path.join(tmp, "sales")
        sys_dir = os.path.join(tmp, "indexes")
        os.makedirs(data_dir)
        os.makedirs(sys_dir)
        names = list("abcdefgh")
        cols = {
            c: (np.arange(num_rows, dtype=np.int64) * (3 + i)) % (997 + 131 * i)
            for i, c in enumerate(names)
        }
        cols["v"] = (np.arange(num_rows, dtype=np.int64) * 31) % 10_000
        pq.write_table(pa.table(cols), os.path.join(data_dir, "part-0.parquet"))

        sess = hst.Session(conf={hst.keys.SYSTEM_PATH: sys_dir, hst.keys.NUM_BUCKETS: 8})
        hst.set_session(sess)
        hs = hst.Hyperspace(sess)
        df = sess.read_parquet(data_dir)
        df.create_or_replace_temp_view("sales")
        k = 0
        for i in range(8):
            for j in range(3):
                indexed = [names[i]] if j == 0 else [names[i], names[(i + j) % 8]]
                hs.create_index(df, hst.CoveringIndexConfig(f"ix{k}", indexed, ["v"]))
                k += 1
        sess.enable_hyperspace()

        plans = [
            sess.sql(f"SELECT a, v FROM sales WHERE b > {300 + i} AND c > 5 AND d < 900").plan
            for i in range(16)
        ]

        def run(enabled: bool):
            srv = QueryServer(
                sess, workers=2, plan_cache_enabled=enabled, queue_depth=65536
            ).start()
            try:
                for p in plans:  # warm: compile + io cache
                    srv.submit(p)
                srv.stats()
                futs = []
                t0 = time.perf_counter()
                for _ in range(reps):
                    for p in plans:
                        futs.append(srv.submit(p))
                for f in futs:
                    f.result(timeout=300)
                dt = time.perf_counter() - t0
                return len(futs) / dt, srv.stats()
            finally:
                srv.shutdown()

        qps_off, stats_off = run(False)
        qps_on, stats_on = run(True)
        out = {
            "metric": "serving_cached_queries_per_sec",
            "value": round(qps_on, 1),
            "unit": "queries/s",
            "vs_baseline": round(qps_on / qps_off / 3.0, 4),  # baseline: 3x uncached
            "uncached_qps": round(qps_off, 1),
            "speedup": round(qps_on / qps_off, 2),
            "plan_cache": stats_on["planCache"],
            "bucket_cache_hit_rate": stats_on["bucketCache"]["hitRate"],
            "micro_batches": stats_on["batches"],
            "batched_requests": stats_on["batchedRequests"],
            "latency_seconds": stats_on["latencySeconds"],
            "uncached_latency_seconds": stats_off["latencySeconds"],
        }
        line = json.dumps(out)
        with open("BENCH_serving.json", "w") as f:
            f.write(line + "\n")
        print(line)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def slo_serve_main() -> None:
    """``python bench.py --slo-serve``: SLO-aware serving benchmark.

    Two measurements, one JSON line (stdout AND BENCH_slo.json):

    - **scheduler**: a burst of heavy group-by queries from a flooding
      ``batch`` tenant followed immediately by interactive point filters from
      a ``web`` tenant, served FIFO vs by the cost-aware scheduler (cost model
      warmed first so the classes are confident). Bar: interactive-class p99
      latency >= 2x better under the scheduler at equal total throughput.
    - **result cache**: the same repeated-query workload with the result
      cache on vs plan-cache-only. Bar: >= 3x hit-path throughput at a
      >= 95% hit rate.
    """
    _honor_cpu_request()
    _backend_watchdog()
    num_rows = int(os.environ.get("BENCH_SLO_ROWS", 120_000))
    n_heavy = max(4, int(os.environ.get("BENCH_SLO_HEAVY", 48)))
    n_inter = max(4, int(os.environ.get("BENCH_SLO_INTERACTIVE", 24)))
    rc_reps = max(2, int(os.environ.get("BENCH_SLO_CACHE_REPS", 20)))
    tmp = tempfile.mkdtemp(prefix="hs_bench_slo_")
    try:
        import pyarrow as pa
        import pyarrow.parquet as pq

        import hyperspace_tpu as hst
        from hyperspace_tpu.serving import QueryServer

        data_dir = os.path.join(tmp, "sales")
        os.makedirs(data_dir)
        names = list("abcdefgh")
        cols = {
            c: (np.arange(num_rows, dtype=np.int64) * (3 + i)) % (997 + 131 * i)
            for i, c in enumerate(names)
        }
        cols["v"] = (np.arange(num_rows, dtype=np.int64) * 31) % 10_000
        pq.write_table(pa.table(cols), os.path.join(data_dir, "part-0.parquet"))

        sess = hst.Session()
        hst.set_session(sess)
        sess.read_parquet(data_dir).create_or_replace_temp_view("sales")

        heavy_q = "SELECT b, SUM(v), SUM(a), SUM(c) FROM sales GROUP BY b"
        inter_qs = [
            f"SELECT a, v FROM sales WHERE b > {300 + i} AND c > 5 AND d < 900"
            for i in range(4)
        ]

        def burst(sched: bool):
            """Interactive-class p99 seconds + total qps for one mixed burst."""
            srv = QueryServer(
                sess, workers=2, sched_enabled=sched, queue_depth=65536,
                # class thresholds scaled to this workload (CPU smoke runs
                # measure milliseconds, not the production half-second)
                sched_interactive_ms=10.0, sched_heavy_ms=40.0,
            ).start()
            try:
                # warm: io cache AND the cost model (the scheduler needs
                # confident per-class estimates to beat FIFO)
                for _ in range(25):
                    srv.query(heavy_q)
                    for q in inter_qs:
                        srv.query(q)
                lat: dict = {}

                def done_cb(i, t_sub):
                    def cb(_f, i=i, t_sub=t_sub):
                        lat[i] = time.perf_counter() - t_sub

                    return cb

                futs = []
                t0 = time.perf_counter()
                for i in range(n_heavy):  # the flood arrives first
                    futs.append(srv.submit(heavy_q, tenant="batch"))
                for i in range(n_inter):
                    f = srv.submit(inter_qs[i % len(inter_qs)], tenant="web")
                    f.add_done_callback(done_cb(i, time.perf_counter()))
                    futs.append(f)
                for f in futs:
                    f.result(timeout=600)
                dt = time.perf_counter() - t0
                p99 = float(np.percentile(sorted(lat.values()), 99))
                return p99, len(futs) / dt
            finally:
                srv.shutdown()

        fifo_p99, fifo_qps = burst(sched=False)
        sched_p99, sched_qps = burst(sched=True)

        def cache_run(result_cache: bool):
            srv = QueryServer(
                sess, workers=2, result_cache_enabled=result_cache, queue_depth=65536
            ).start()
            try:
                for q in inter_qs:  # warm: every later rep is a potential hit
                    srv.query(q)
                futs = []
                t0 = time.perf_counter()
                for _ in range(rc_reps):
                    for q in inter_qs:
                        futs.append(srv.submit(q))
                for f in futs:
                    f.result(timeout=600)
                dt = time.perf_counter() - t0
                stats = srv.stats()
                hit_rate = stats.get("resultCache", {}).get("hitRate", 0.0)
                return len(futs) / dt, hit_rate
            finally:
                srv.shutdown()

        plan_qps, _ = cache_run(result_cache=False)
        rc_qps, rc_hit_rate = cache_run(result_cache=True)

        p99_speedup = fifo_p99 / max(sched_p99, 1e-9)
        out = {
            "metric": "slo_serving_interactive_p99_speedup",
            "value": round(p99_speedup, 2),
            "unit": "x",
            "vs_baseline": round(p99_speedup / 2.0, 4),  # bar: >= 2x
            "interactive_p99_s": {"fifo": round(fifo_p99, 4), "sched": round(sched_p99, 4)},
            "total_qps": {"fifo": round(fifo_qps, 1), "sched": round(sched_qps, 1)},
            "result_cache": {
                "qps": round(rc_qps, 1),
                "plan_cache_only_qps": round(plan_qps, 1),
                "speedup": round(rc_qps / plan_qps, 2),  # bar: >= 3x
                "hit_rate": round(rc_hit_rate, 4),  # bar: >= 0.95
            },
        }
        line = json.dumps(out)
        with open("BENCH_slo.json", "w") as f:
            f.write(line + "\n")
        print(line)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def obs_main() -> None:
    """``python bench.py --obs-overhead``: observability overhead benchmark.

    Four measurements on the --serve workload shape:

    - ``qps_off``   — tracing disabled, the **default production stance**:
      the query intelligence layer (fingerprint profile history + SLO
      accounting, both on by default) folds every completion;
    - ``qps_on``    — tracing enabled (every request grows a full span tree);
    - ``qps_bare``  — tracing off AND intelligence off (history disabled,
      SLO target 0), isolating the enabled-path cost of the per-request
      history/SLO folds;
    - ``null_span_ns`` — nanoseconds per ``spans.span(...)`` enter/exit on the
      disabled path (the cost each instrumentation point adds to untraced
      code).

    ``overhead_disabled`` compares qps_off against the same workload run a
    second time (A/B of identical configs) so run-to-run noise is visible;
    the acceptance bar (<= 3%) is ``vs_baseline >= 0.97`` where vs_baseline =
    qps_off / qps_off_again — i.e. tracing-off throughput is indistinguishable
    from itself, and the *enabled* costs (span trees; intelligence folds) are
    reported separately for honesty.

    The **fabric leg** then routes the same queries through a FrontDoor over
    two HTTP ``WorkerEndpoint`` workers and reports routed p99 latency with
    distributed tracing fully on (traceparent propagation + span-tree
    stitching) vs fully off (byte-identical legacy wire format). The ≤3% bar
    applies to ``fabric.overhead_fraction``; on a loopback 2-worker box the
    HTTP round-trip dominates, so run-to-run noise at p99 can exceed the
    measured delta — the repeated-off p99 is reported alongside so that
    noise is visible rather than laundered into a pass.
    """
    _honor_cpu_request()
    _backend_watchdog()
    num_rows = int(os.environ.get("BENCH_SERVE_ROWS", 8_000))
    reps = max(1, int(os.environ.get("BENCH_SERVE_REPS", 30)))
    tmp = tempfile.mkdtemp(prefix="hs_bench_obs_")
    try:
        import jax
        import pyarrow as pa
        import pyarrow.parquet as pq

        import hyperspace_tpu as hst
        from hyperspace_tpu.obs import spans
        from hyperspace_tpu.serving import QueryServer

        data_dir = os.path.join(tmp, "sales")
        sys_dir = os.path.join(tmp, "indexes")
        os.makedirs(data_dir)
        os.makedirs(sys_dir)
        names = list("abcdefgh")
        cols = {
            c: (np.arange(num_rows, dtype=np.int64) * (3 + i)) % (997 + 131 * i)
            for i, c in enumerate(names)
        }
        cols["v"] = (np.arange(num_rows, dtype=np.int64) * 31) % 10_000
        pq.write_table(pa.table(cols), os.path.join(data_dir, "part-0.parquet"))

        sess = hst.Session(conf={hst.keys.SYSTEM_PATH: sys_dir, hst.keys.NUM_BUCKETS: 8})
        hst.set_session(sess)
        df = sess.read_parquet(data_dir)
        df.create_or_replace_temp_view("sales")
        queries = [
            f"SELECT a, v FROM sales WHERE b > {300 + i} AND c > 5 AND d < 900"
            for i in range(16)
        ]

        def run(tracing: bool, intelligence: bool = True):
            sess.conf.set(hst.keys.OBS_TRACING_ENABLED, tracing)
            sess.conf.set(hst.keys.OBS_HISTORY_ENABLED, intelligence)
            sess.conf.set(hst.keys.OBS_SLO_TARGET_MS, 1000.0 if intelligence else 0.0)
            srv = QueryServer(sess, workers=2, queue_depth=65536).start()
            try:
                for q in queries:  # warm compile + io cache
                    srv.submit(q)
                srv.stats()
                futs = []
                t0 = time.perf_counter()
                for _ in range(reps):
                    for q in queries:
                        futs.append(srv.submit(q))
                for f in futs:
                    f.result(timeout=300)
                qps = len(futs) / (time.perf_counter() - t0)
                profs = srv.last_profiles()
                span_counts = [p.root.trace.count for p in profs if p.root.trace]
                return qps, (sum(span_counts) / len(span_counts) if span_counts else 0.0)
            finally:
                srv.shutdown()
                sess.conf.set(hst.keys.OBS_TRACING_ENABLED, False)

        qps_off, _ = run(False)
        qps_on, spans_per_request = run(True)
        qps_off_again, _ = run(False)
        qps_bare, _ = run(False, intelligence=False)

        # disabled-path microbench: one contextvar read + shared null CM —
        # the cost each instrumentation point adds to an untraced query
        n = 2_000_000
        t0 = time.perf_counter()
        for _ in range(n):
            with spans.span("x"):
                pass
        null_span_ns = (time.perf_counter() - t0) / n * 1e9

        # fabric leg: routed p99 across 2 HTTP workers, tracing+stitching
        # on vs off (the off path must be the byte-identical legacy wire)
        from hyperspace_tpu.fabric import FrontDoor
        from hyperspace_tpu.fabric.frontdoor import WorkerEndpoint

        fabric_reps = max(1, int(os.environ.get("BENCH_OBS_FABRIC_REPS", 40)))

        def fabric_leg(fabric_on: bool) -> float:
            # tracing stays ON in both legs: the local-span cost is priced by
            # the single-process bar above. This leg isolates the FABRIC
            # delta — traceparent/x-hs-stitch headers, the worker's wire
            # serialization, and the router-side graft.
            sess.conf.set(hst.keys.OBS_TRACING_ENABLED, True)
            sess.conf.set(hst.keys.OBS_FABRIC_PROPAGATE, fabric_on)
            sess.conf.set(hst.keys.OBS_FABRIC_STITCH_ENABLED, fabric_on)
            srvs = [QueryServer(sess, workers=2, queue_depth=65536).start() for _ in range(2)]
            eps = [WorkerEndpoint(s).start() for s in srvs]
            try:
                fd = FrontDoor([ep.url for ep in eps], conf=sess.conf)
                for t in ("t0", "t1"):  # warm both workers
                    for q in queries:
                        fd.query(q, tenant=t)
                lats = []
                for _ in range(fabric_reps):
                    for i, q in enumerate(queries):
                        t0 = time.perf_counter()
                        fd.query(q, tenant=f"t{i % 2}")
                        lats.append(time.perf_counter() - t0)
                return float(np.percentile(np.asarray(lats), 99))
            finally:
                for ep in eps:
                    ep.close()
                for s in srvs:
                    s.shutdown()
                sess.conf.set(hst.keys.OBS_TRACING_ENABLED, False)
                sess.conf.set(hst.keys.OBS_FABRIC_PROPAGATE, True)
                sess.conf.set(hst.keys.OBS_FABRIC_STITCH_ENABLED, False)

        fabric_p99_off = fabric_leg(False)
        fabric_p99_on = fabric_leg(True)
        fabric_p99_off_again = fabric_leg(False)

        best_off = max(qps_off, qps_off_again)
        worst_off = min(qps_off, qps_off_again)
        # fraction of wall time an untraced request spends in instrumentation:
        # (instrumentation points hit per request, counted by a traced run) x
        # (disabled-path cost per point) x (requests per second). This
        # attributes overhead to the instrumentation itself, which A/B qps
        # comparisons on a 2-worker box cannot resolve below run-to-run noise.
        disabled_overhead = spans_per_request * (null_span_ns * 1e-9) * best_off
        out = {
            "metric": "obs_overhead_disabled_fraction",
            "value": round(disabled_overhead, 5),
            "unit": "fraction",
            # baseline: the <= 3% acceptance bar
            "vs_baseline": round((0.03 - disabled_overhead) / 0.03, 4),
            "qps_tracing_off": round(qps_off, 1),
            "qps_tracing_off_repeat": round(qps_off_again, 1),
            "off_run_noise": round(1.0 - worst_off / best_off, 4),
            "qps_tracing_on": round(qps_on, 1),
            "tracing_on_overhead": round(1.0 - qps_on / best_off, 4),
            # enabled-path cost of the default-on intelligence layer: the
            # per-request history/SLO folds vs the same run with both off
            "qps_intelligence_off": round(qps_bare, 1),
            "intelligence_on_overhead": round(1.0 - best_off / max(qps_bare, best_off), 4),
            "spans_per_request": round(spans_per_request, 1),
            "null_span_ns": round(null_span_ns, 1),
            "fabric": {
                "p99_off_s": round(fabric_p99_off, 5),
                "p99_on_s": round(fabric_p99_on, 5),
                "p99_off_repeat_s": round(fabric_p99_off_again, 5),
                "overhead_fraction": round(
                    fabric_p99_on / max(fabric_p99_off, fabric_p99_off_again) - 1.0, 4
                ),
                "off_run_noise": round(
                    abs(fabric_p99_off - fabric_p99_off_again)
                    / max(fabric_p99_off, fabric_p99_off_again),
                    4,
                ),
                "bar": 0.03,
                "workers": 2,
                "transport": "http-loopback",
            },
            "platform": jax.default_backend(),
            "cpus": os.cpu_count(),
        }
        line = json.dumps(out)
        with open("BENCH_obs.json", "w") as f:
            f.write(line + "\n")
        print(line)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def scan_pipeline_main() -> None:
    """``python bench.py --scan-pipeline``: pipelined scan engine benchmark.

    Cold-cache multi-chunk filter scan, pipelined vs serial (same session,
    ``hyperspace.exec.pipeline.enabled`` toggled; io + device caches cleared
    before each run). Reports rows/s both ways, verifies byte-identical
    results, and samples ``hs_xla_compiles_total`` after every chunk — shape
    bucketing means the count must be flat after the first two chunks.
    Baseline: >= 1.4x pipelined/serial; writes BENCH_scan_pipeline.json.
    """
    _honor_cpu_request()
    _backend_watchdog()
    num_files = int(os.environ.get("BENCH_SCAN_FILES", 12))
    rows_per = int(os.environ.get("BENCH_SCAN_ROWS_PER_FILE", 400_000))
    tmp = tempfile.mkdtemp(prefix="hs_bench_scan_")
    try:
        import pyarrow as pa
        import pyarrow.parquet as pq

        import hyperspace_tpu as hst
        from hyperspace_tpu.exec import batch as B
        from hyperspace_tpu.exec.device import clear_device_cache
        from hyperspace_tpu.exec.io import clear_io_cache
        from hyperspace_tpu.obs.metrics import REGISTRY

        data_dir = os.path.join(tmp, "events")
        sys_dir = os.path.join(tmp, "indexes")
        os.makedirs(data_dir)
        os.makedirs(sys_dir)
        rng = np.random.default_rng(7)
        for i in range(num_files):
            # a decode-heavy mix (strings dominate parquet decode, like real
            # event tables) filtered on a numeric key (device path)
            pq.write_table(
                pa.table(
                    {
                        "k": rng.integers(0, 1_000_000, rows_per).astype(np.int64),
                        "v": rng.uniform(0.0, 1.0, rows_per),
                        "w": rng.integers(0, 1 << 40, rows_per).astype(np.int64),
                        "x": rng.uniform(-1.0, 1.0, rows_per),
                        "tag": np.char.add(
                            "session-", rng.integers(0, 10_000_000, rows_per).astype(str)
                        ),
                    }
                ),
                os.path.join(data_dir, f"part-{i:05d}.parquet"),
                compression="zstd",
            )

        sess = hst.Session(
            conf={
                hst.keys.SYSTEM_PATH: sys_dir,
                hst.keys.EXEC_STREAM_CHUNK_BYTES: 1,  # one file per chunk
                hst.keys.TPU_QUERY_DEVICE_MIN_ROWS: 1,  # exercise the device path
            }
        )
        hst.set_session(sess)
        q = sess.read_parquet(data_dir).filter(hst.col("k") < 500_000)
        compiles = REGISTRY.counter(
            "hs_xla_compiles_total", "first-time XLA compilations (program x shape bucket)"
        )

        import hashlib

        def digest(batch) -> str:
            """Order-sensitive content hash of a chunk: equal digests per chunk
            position == byte-identical streamed results."""
            h = hashlib.sha1()
            for name in sorted(batch):
                a = np.asarray(batch[name])
                h.update(name.encode())
                if a.dtype == object:
                    h.update("\x00".join(map(str, a.tolist())).encode())
                else:
                    h.update(np.ascontiguousarray(a).tobytes())
            return h.hexdigest()

        def run(pipelined: bool):
            # chunks are digested and DROPPED, like a real streaming consumer —
            # retaining millions of decoded objects would measure the Python
            # GC's reaction to the pile, not the scan engine
            sess.conf.set(hst.keys.EXEC_PIPELINE_ENABLED, pipelined)
            clear_io_cache()
            clear_device_cache()
            digests = []
            counts = []
            rows = 0
            t0 = time.perf_counter()
            for chunk in q.to_local_iterator():
                rows += B.num_rows(chunk)
                digests.append(digest(chunk))
                counts.append(int(compiles.value))
            dt = time.perf_counter() - t0
            return digests, rows, dt, counts

        run(True)  # warm jit (process-wide by design) so neither timed run bills compile
        d_serial, rows_serial, dt_serial, _ = run(False)
        d_pipe, rows_pipe, dt_pipe, counts = run(True)

        identical = d_serial == d_pipe and rows_serial == rows_pipe
        src_rows = num_files * rows_per
        speedup = dt_serial / dt_pipe
        out = {
            "metric": "scan_pipeline_speedup",
            "value": round(speedup, 3),
            "unit": "x vs serial",
            "vs_baseline": round(speedup / 1.4, 4),  # baseline: 1.4x
            "pipelined_rows_per_sec": round(src_rows / dt_pipe, 1),
            "serial_rows_per_sec": round(src_rows / dt_serial, 1),
            "chunks": num_files,
            "result_rows": int(rows_pipe),
            "byte_identical": bool(identical),
            "xla_compiles_after_chunk": counts,
            "compiles_flat_after_first_two": bool(counts[-1] == counts[min(1, len(counts) - 1)]),
        }
        line = json.dumps(out)
        with open("BENCH_scan_pipeline.json", "w") as f:
            f.write(line + "\n")
        print(line)

        _native_decode_legs(tmp)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def _native_decode_legs(tmp: str) -> None:
    """Native-vs-pyarrow decode legs of ``--scan-pipeline``.

    The same cold-cache batch read (uncompressed files — the decode-bound
    case, no codec time diluting the comparison) with the native row-group
    fast path on vs native decode off entirely. Reports decode GB/s both
    ways from the parquet byte volume (identical numerator, so the ratio is
    honest), verifies byte-identical batches, and writes BENCH_native.json.
    Bar: >= 2x native/pyarrow on uncompressed files.
    """
    import hashlib

    import jax
    import pyarrow as pa
    import pyarrow.parquet as pq

    from hyperspace_tpu.exec import io as hio
    from hyperspace_tpu.exec.io import clear_io_cache, read_parquet_batch

    num_files = int(os.environ.get("BENCH_NATIVE_FILES", 6))
    rows_per = int(os.environ.get("BENCH_NATIVE_ROWS_PER_FILE", 600_000))
    reps = max(1, int(os.environ.get("BENCH_NATIVE_REPS", 3)))
    d = os.path.join(tmp, "native_legs")
    os.makedirs(d)
    rng = np.random.default_rng(3)
    files = []
    for i in range(num_files):
        # the event-table mix: numeric measures + bounded-cardinality
        # categorical strings (session/event/status tags), the shape real
        # event/clickstream lakes take. Categoricals keep parquet dictionary
        # encoding (their natural layout); the high-cardinality numerics are
        # written plain — dictionary-encoding near-unique int64/double only
        # bloats files past the dict-page cap and is disabled by tuned writers
        pq.write_table(
            pa.table(
                {
                    "k": rng.integers(0, 1_000_000, rows_per).astype(np.int64),
                    "v": rng.uniform(0.0, 1.0, rows_per),
                    "tag": np.char.add(
                        "session-", rng.integers(0, 4000, rows_per).astype(str)
                    ),
                    "evt": np.char.add(
                        "evt-", rng.integers(0, 300, rows_per).astype(str)
                    ),
                    "status": np.char.add(
                        "st-", rng.integers(0, 16, rows_per).astype(str)
                    ),
                }
            ),
            os.path.join(d, f"part-{i:05d}.parquet"),
            compression="NONE",
            row_group_size=131072,
            use_dictionary=["tag", "evt", "status"],
        )
        files.append(os.path.join(d, f"part-{i:05d}.parquet"))
    file_bytes = sum(os.path.getsize(f) for f in files)
    cols = ["k", "v", "tag", "evt", "status"]

    def digest(batch) -> str:
        h = hashlib.sha1()
        for name in sorted(batch):
            a = np.asarray(batch[name])
            h.update(name.encode())
            if a.dtype == object:
                h.update("\x00".join(map(str, a.tolist())).encode())
            else:
                h.update(np.ascontiguousarray(a).tobytes())
        return h.hexdigest()

    def leg(native_on: bool, n: int):
        hio.set_native_options(enabled=native_on, rowgroup=native_on)
        best = float("inf")
        b = None
        for _ in range(n):
            clear_io_cache()
            t0 = time.perf_counter()
            b = read_parquet_batch(list(files), cols)
            best = min(best, time.perf_counter() - t0)
        return best, b

    try:
        leg(True, 1)  # warm the page cache so both legs read warm files
        dt_native, b_native = leg(True, reps)
        dt_arrow, b_arrow = leg(False, reps)
    finally:
        hio.set_native_options(enabled=True, rowgroup=True)

    identical = digest(b_native) == digest(b_arrow)
    gbps_native = file_bytes / 1e9 / dt_native
    gbps_arrow = file_bytes / 1e9 / dt_arrow
    speedup = dt_arrow / dt_native
    out = {
        "metric": "native_decode_speedup",
        "value": round(speedup, 3),
        "unit": "x vs pyarrow",
        "bar": ">= 2x on uncompressed files",
        "vs_baseline": round(speedup / 2.0, 4),
        "native_decode_gb_per_sec": round(gbps_native, 3),
        "pyarrow_decode_gb_per_sec": round(gbps_arrow, 3),
        "parquet_bytes": int(file_bytes),
        "files": num_files,
        "rows": num_files * rows_per,
        "codec": "uncompressed",
        "byte_identical": bool(identical),
        "platform": jax.default_backend(),
        "devices": len(jax.devices()),
        "cpus": len(os.sched_getaffinity(0)),
    }
    line = json.dumps(out)
    with open("BENCH_native.json", "w") as f:
        f.write(line + "\n")
    print(line)


def topk_main() -> None:
    """``python bench.py --topk``: streaming device top-k benchmark.

    ORDER BY k, v LIMIT 100 over key-clustered lake data (k sorted within
    each file, the usual layout for time- or key-partitioned ingestion),
    streamed device top-k vs the host materialize-and-sort path. Each
    measured run uses a FRESH session (cold scan cache; the OS page cache is
    warmed for both sides by a priming run) because the point of the top-k
    fold is exactly to avoid materializing the scan: the device path decodes
    only the row groups the running k-th-value threshold cannot prune, while
    the host path decodes everything and stable-sorts two keys. Asserts the
    top-k path actually dispatched (trace), byte-identical results, and zero
    warm-run compiles. Baseline: >= 1.5x; writes BENCH_topk.json.
    """
    _honor_cpu_request()
    _backend_watchdog()
    num_files = int(os.environ.get("BENCH_TOPK_FILES", 8))
    rows_per = int(os.environ.get("BENCH_TOPK_ROWS_PER_FILE", 500_000))
    reps = max(1, int(os.environ.get("BENCH_TOPK_REPS", 3)))
    limit_n = int(os.environ.get("BENCH_TOPK_LIMIT", 100))
    tmp = tempfile.mkdtemp(prefix="hs_bench_topk_")
    try:
        import hashlib

        import jax
        import pyarrow as pa
        import pyarrow.parquet as pq

        import hyperspace_tpu as hst
        from hyperspace_tpu.exec import trace
        from hyperspace_tpu.obs.metrics import REGISTRY

        data_dir = os.path.join(tmp, "events")
        sys_dir = os.path.join(tmp, "indexes")
        os.makedirs(data_dir)
        os.makedirs(sys_dir)
        rng = np.random.default_rng(11)
        for i in range(num_files):
            k = np.sort(rng.integers(0, 10_000_000, rows_per)).astype(np.int64)
            pq.write_table(
                pa.table(
                    {
                        "k": k,
                        "v": rng.uniform(0.0, 1e6, rows_per),
                        "w": rng.uniform(0.0, 100.0, rows_per),
                    }
                ),
                os.path.join(data_dir, f"part-{i:05d}.parquet"),
                compression="zstd",
                row_group_size=50_000,
            )

        def run(topk: bool):
            # fresh session per run: the scan cache must stay cold, or both
            # sides skip the decode the top-k fold exists to avoid
            sess = hst.Session(
                conf={
                    hst.keys.SYSTEM_PATH: sys_dir,
                    hst.keys.EXEC_TOPK_ENABLED: topk,
                    hst.keys.EXEC_STREAM_CHUNK_BYTES: 1,  # one file per chunk
                    # fused select+merge per chunk (fused-stage-topk); the
                    # first chunk seeds the state through the classic program
                    hst.keys.EXEC_FUSION_ENABLED: topk,
                }
            )
            hst.set_session(sess)
            q = sess.read_parquet(data_dir).order_by("k", "v").limit(limit_n)
            with trace.recording() as events:
                t0 = time.perf_counter()
                out = q.collect()
                dt = time.perf_counter() - t0
            return out, dt, events

        compiles = REGISTRY.counter(
            "hs_xla_compiles_total", "first-time XLA compilations (program x shape bucket)"
        )
        skipped = REGISTRY.counter("hs_rowgroups_skipped_total", "")
        host_res, _, _ = run(False)  # warms the OS page cache for both sides
        dev_res, cold_dev, ev = run(True)
        if ("topk", "device-topk-stream") not in ev:
            raise SystemExit(f"top-k path did not dispatch: {trace.summarize(ev)}")
        fused = REGISTRY.counter(
            "hs_device_dispatches_total", "", program="fused-stage-topk"
        )
        c0, s0, f0 = compiles.value, skipped.value, fused.value
        dev_times = [run(True)[1] for _ in range(reps)]
        warm_compile_delta = compiles.value - c0
        rg_skipped = (skipped.value - s0) / reps
        fused_per_run = (fused.value - f0) / reps
        host_times = [run(False)[1] for _ in range(reps)]
        dt_dev, dt_host = min(dev_times), min(host_times)

        def digest(batch) -> str:
            h = hashlib.sha256()
            for c in sorted(batch):
                h.update(c.encode())
                h.update(np.asarray(batch[c]).tobytes())
            return h.hexdigest()

        identical = digest(dev_res) == digest(host_res)
        src_rows = num_files * rows_per
        speedup = dt_host / dt_dev
        out = {
            "metric": "topk_stream_speedup",
            "value": round(speedup, 3),
            "unit": "x vs host sort",
            "vs_baseline": round(speedup / 1.5, 4),  # baseline: 1.5x
            "device_rows_per_sec": round(src_rows / dt_dev, 1),
            "host_rows_per_sec": round(src_rows / dt_host, 1),
            "cold_device_s": round(cold_dev, 4),
            "warm_device_s": round(dt_dev, 4),
            "host_s": round(dt_host, 4),
            "limit": limit_n,
            "source_rows": src_rows,
            "rowgroups_skipped_per_run": round(rg_skipped, 1),
            "byte_identical": bool(identical),
            "warm_compile_delta": int(warm_compile_delta),
            "fused_dispatches_per_run": round(fused_per_run, 1),
            "platform": jax.default_backend(),
        }
        line = json.dumps(out)
        with open("BENCH_topk.json", "w") as f:
            f.write(line + "\n")
        print(line)
        if not identical:
            raise SystemExit("top-k stream and host sort disagree")
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def groupby_main() -> None:
    """``python bench.py --groupby``: device grouped-aggregation benchmark.

    TPC-H q1-shaped query (filter + two group keys + six aggregates) over a
    covering index, device segment-reduction engine vs the host pandas
    aggregation — same session, ``TPU_QUERY_DEVICE_EXECUTION`` toggled, both
    sides reading the same io-cached scan so the comparison is the aggregation
    work itself. The device leg runs the whole-plan fused path
    (``hyperspace.exec.fusion.enabled``): one donated ``fused-stage-agg``
    executable folds each streamed chunk — filter, key packing, and segment
    reduction in a single dispatch — while the host leg stays the materialized
    pandas one-shot. Reports cold (first device run, includes XLA compile) and
    warm (steady-state, min of reps) timings, checks results are
    byte-identical on exact columns (keys, counts, int sums, min/max — float
    reductions differ only in summation order and are checked to tolerance),
    and that warm runs add zero compiles. Baseline: >= 1.5x warm device/host;
    writes BENCH_groupby.json.
    """
    _honor_cpu_request()
    _backend_watchdog()
    num_files = int(os.environ.get("BENCH_GROUPBY_FILES", 8))
    rows_per = int(os.environ.get("BENCH_GROUPBY_ROWS_PER_FILE", 500_000))
    reps = max(1, int(os.environ.get("BENCH_GROUPBY_REPS", 3)))
    tmp = tempfile.mkdtemp(prefix="hs_bench_groupby_")
    try:
        import jax
        import pyarrow as pa
        import pyarrow.parquet as pq

        import hyperspace_tpu as hst
        from hyperspace_tpu.obs.metrics import REGISTRY

        data_dir = os.path.join(tmp, "lineitem")
        sys_dir = os.path.join(tmp, "indexes")
        os.makedirs(data_dir)
        os.makedirs(sys_dir)
        rng = np.random.default_rng(11)
        for i in range(num_files):
            pq.write_table(
                pa.table(
                    {
                        "k": rng.integers(0, 1_000_000, rows_per).astype(np.int64),
                        "g1": rng.integers(0, 25, rows_per).astype(np.int64),
                        "g2": rng.integers(0, 40, rows_per).astype(np.int64),
                        "qty": rng.integers(1, 51, rows_per).astype(np.int64),
                        "price": rng.uniform(900.0, 105_000.0, rows_per),
                        "disc": rng.uniform(0.0, 0.1, rows_per),
                    }
                ),
                os.path.join(data_dir, f"part-{i:05d}.parquet"),
                compression="zstd",
            )

        sess = hst.Session(
            conf={
                hst.keys.SYSTEM_PATH: sys_dir,
                hst.keys.NUM_BUCKETS: 8,
                hst.keys.TPU_QUERY_DEVICE_MIN_ROWS: 1,
                # the device leg streams one file per chunk through the fused
                # fold; the host leg stays a materialized one-shot (the
                # per-leg EXEC_STREAM_AGG_MIN_BYTES toggle in run())
                hst.keys.EXEC_STREAM_CHUNK_BYTES: 1,
                hst.keys.EXEC_FUSION_ENABLED: True,
            }
        )
        hst.set_session(sess)
        hs = hst.Hyperspace(sess)
        df = sess.read_parquet(data_dir)
        hs.create_index(
            df,
            hst.CoveringIndexConfig(
                "gbIdx", ["k"], ["g1", "g2", "qty", "price", "disc"]
            ),
        )
        sess.enable_hyperspace()
        q = (
            df.filter(hst.col("k") < 500_000)
            .group_by("g1", "g2")
            .agg(
                n=("*", "count"),
                sum_qty=("qty", "sum"),
                lo=("qty", "min"),
                hi=("qty", "max"),
                sum_price=("price", "sum"),
                avg_disc=("disc", "avg"),
            )
        )
        compiles = REGISTRY.counter(
            "hs_xla_compiles_total", "first-time XLA compilations (program x shape bucket)"
        )

        def run(device: bool):
            sess.conf.set(hst.keys.TPU_QUERY_DEVICE_EXECUTION, device)
            sess.conf.set(
                hst.keys.EXEC_STREAM_AGG_MIN_BYTES, 1 if device else 1 << 60
            )
            t0 = time.perf_counter()
            out = q.collect()
            return out, time.perf_counter() - t0

        fused = REGISTRY.counter(
            "hs_device_dispatches_total", "", program="fused-stage-agg"
        )
        host_res, _ = run(False)  # warms the io cache for every later run
        c0 = compiles.value
        dev_res, cold_dev = run(True)  # first device run: compile + staging
        cold_compiles = compiles.value - c0
        f0 = fused.value
        dev_times = [run(True)[1] for _ in range(reps)]
        warm_compile_delta = compiles.value - c0 - cold_compiles
        fused_per_run = (fused.value - f0) / reps
        host_times = [run(False)[1] for _ in range(reps)]
        dt_dev, dt_host = min(dev_times), min(host_times)

        exact = ("g1", "g2", "n", "sum_qty", "lo", "hi")
        identical = len(dev_res["n"]) == len(host_res["n"]) and all(
            np.asarray(dev_res[k]).tobytes() == np.asarray(host_res[k]).tobytes()
            for k in exact
        )
        floats_ok = all(
            np.allclose(dev_res[k], host_res[k], rtol=1e-9, equal_nan=True)
            for k in ("sum_price", "avg_disc")
        )
        src_rows = num_files * rows_per
        speedup = dt_host / dt_dev
        out = {
            "metric": "groupby_device_speedup",
            "value": round(speedup, 3),
            "unit": "x vs host",
            "vs_baseline": round(speedup / 1.5, 4),  # baseline: 1.5x
            "device_rows_per_sec": round(src_rows / dt_dev, 1),
            "host_rows_per_sec": round(src_rows / dt_host, 1),
            "cold_device_s": round(cold_dev, 4),
            "warm_device_s": round(dt_dev, 4),
            "host_s": round(dt_host, 4),
            "groups": int(len(dev_res["n"])),
            "byte_identical": bool(identical),
            "floats_within_tolerance": bool(floats_ok),
            "cold_compiles": int(cold_compiles),
            "warm_compile_delta": int(warm_compile_delta),
            "fused_dispatches_per_run": round(fused_per_run, 1),
            "platform": jax.default_backend(),
        }
        line = json.dumps(out)
        with open("BENCH_groupby.json", "w") as f:
            f.write(line + "\n")
        print(line)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def fusion_main() -> None:
    """``python bench.py --fusion``: whole-plan fusion compiler benchmark.

    A q3-shaped chain — fact joined through a broadcast dimension, post-join
    filter, grouped aggregate — streamed one fact file per chunk, two ways
    over the same data:

    - **fused**: the stage compiler's path (``hyperspace.exec.fusion.enabled``)
      — probe + filter + segment-fold in ONE donated executable per chunk
      (``fused-stage-join-agg``).
    - **per-family**: the dispatch sequence the fused program replaces —
      streaming broadcast join (hash-probe + post-join filter programs per
      chunk) feeding the per-family ``GroupedAggStream`` (grouped chunk +
      merge programs per chunk).

    Backend-independent hard checks: results match (exact group keys /
    counts / min / max; float sums to 1e-9 — summation order), dispatch
    reduction >= 3x, and zero warm-run compiles (one executable per
    (skeleton, shape bucket, mesh); the chunk-size sweep is covered by
    ``tests/test_fusion.py``). The >= 1.5x chunk-throughput bar is the chip
    bar: on the CPU backend both legs share host cores with the decode, so
    the saved dispatch overhead is a small slice of wall time and the
    ``platform``/``cpus`` fields say so honestly. Writes BENCH_fusion.json.
    """
    _honor_cpu_request()
    _backend_watchdog()
    num_files = int(os.environ.get("BENCH_FUSION_FILES", 8))
    rows_per = int(os.environ.get("BENCH_FUSION_ROWS_PER_FILE", 300_000))
    build_rows = int(os.environ.get("BENCH_FUSION_BUILD_ROWS", 10_000))
    reps = max(1, int(os.environ.get("BENCH_FUSION_REPS", 3)))
    tmp = tempfile.mkdtemp(prefix="hs_bench_fusion_")
    try:
        import jax

        import hyperspace_tpu as hst
        import pyarrow as pa
        import pyarrow.parquet as pq
        from hyperspace_tpu.exec import device as D
        from hyperspace_tpu.exec import trace
        from hyperspace_tpu.exec.executor import Executor
        from hyperspace_tpu.obs.metrics import REGISTRY

        probe_dir = os.path.join(tmp, "fact")
        build_dir = os.path.join(tmp, "dim")
        os.makedirs(probe_dir)
        os.makedirs(build_dir)
        rng = np.random.default_rng(11)
        for i in range(num_files):
            pq.write_table(
                pa.table(
                    {
                        "k": rng.integers(0, build_rows, rows_per).astype(np.int64),
                        "g": rng.integers(0, 500, rows_per).astype(np.int64),
                        "v": rng.standard_normal(rows_per),
                    }
                ),
                os.path.join(probe_dir, f"part-{i:05d}.parquet"),
                compression="zstd",
            )
        pq.write_table(
            pa.table(
                {
                    "k2": np.arange(build_rows, dtype=np.int64),
                    "w": rng.standard_normal(build_rows),
                }
            ),
            os.path.join(build_dir, "dim.parquet"),
        )

        aggs = [
            ("n", "count", None),
            ("s", "sum", "v"),
            ("a", "avg", "w"),
            ("mn", "min", "v"),
            ("mx", "max", "w"),
        ]

        def mk_session(fused: bool):
            # fresh session per run: cold scan cache on both legs; the
            # process-wide program cache stays warm after the priming runs,
            # which is exactly what the warm_compile_delta field checks
            sess = hst.Session(
                conf={
                    hst.keys.SYSTEM_PATH: os.path.join(tmp, "ix"),
                    hst.keys.TPU_QUERY_DEVICE_EXECUTION: True,
                    hst.keys.TPU_QUERY_DEVICE_MIN_ROWS: 1,
                    hst.keys.EXEC_STREAM_CHUNK_BYTES: 1,  # one fact file per chunk
                    hst.keys.EXEC_FUSION_ENABLED: fused,
                }
            )
            hst.set_session(sess)
            return sess

        def dispatches() -> float:
            snap = REGISTRY.snapshot().get("hs_device_dispatches_total")
            return sum(s["value"] for s in snap["series"]) if snap else 0.0

        def chain(sess):
            probe = sess.read_parquet(probe_dir)
            build = sess.read_parquet(build_dir)
            return probe.join(
                build, on=hst.col("k") == hst.col("k2"), how="inner"
            ).filter(hst.col("v") > -0.5)

        def run_fused():
            sess = mk_session(True)
            q = chain(sess).group_by("g").agg(
                n=("*", "count"), s=("v", "sum"), a=("w", "avg"),
                mn=("v", "min"), mx=("w", "max"),
            )
            with trace.recording() as events:
                t0 = time.perf_counter()
                out = q.collect()
                dt = time.perf_counter() - t0
            if ("agg", "fused-join-agg-stream") not in events:
                raise SystemExit(
                    f"fused path did not dispatch: {trace.summarize(events)}"
                )
            return out, dt

        def run_perfam():
            sess = mk_session(False)
            gs = D.GroupedAggStream(
                sess, ["g"], aggs,
                max_groups=sess.conf.agg_max_groups,
                cap_floor=sess.conf.agg_capacity_floor,
            )
            t0 = time.perf_counter()
            for chunk in Executor(sess).execute_stream(chain(sess).plan):
                gs.update({c: np.asarray(v) for c, v in chunk.items()}, None)
            out = gs.finalize()
            return out, time.perf_counter() - t0

        compiles = REGISTRY.counter(
            "hs_xla_compiles_total", "first-time XLA compilations (program x shape bucket)"
        )
        c0 = compiles.value
        fused_res, cold_fused = run_fused()  # prime: compile + page cache +
        cold_compiles = compiles.value - c0  # group-capacity hint warmup
        perfam_res, _ = run_perfam()
        # dispatch counts come from the warm reps: the cold runs also pay the
        # capacity-hint warmup redos, which are priced by their own fallback
        # counter, not part of the steady-state dispatch sequence
        c0 = compiles.value
        d0 = dispatches()
        fused_times = [run_fused()[1] for _ in range(reps)]
        fused_dispatches = (dispatches() - d0) / reps
        d0 = dispatches()
        perfam_times = [run_perfam()[1] for _ in range(reps)]
        perfam_dispatches = (dispatches() - d0) / reps
        warm_compile_delta = compiles.value - c0
        dt_fused, dt_perfam = min(fused_times), min(perfam_times)

        def by_g(batch):
            order = np.argsort(np.asarray(batch["g"]), kind="stable")
            return {c: np.asarray(v)[order] for c, v in batch.items()}
        a, b = by_g(fused_res), by_g(perfam_res)
        exact = ("g", "n", "mn", "mx")
        identical = len(a["n"]) == len(b["n"]) and all(
            a[k].tobytes() == b[k].tobytes() for k in exact
        )
        floats_ok = all(
            np.allclose(a[k], b[k], rtol=1e-9, equal_nan=True) for k in ("s", "a")
        )
        reduction = perfam_dispatches / max(fused_dispatches, 1.0)
        peak = REGISTRY.gauge(
            "hs_device_peak_bytes",
            "High-water total bytes of live device arrays, sampled after "
            "streamed fold steps",
        ).value
        speedup = dt_perfam / dt_fused
        out = {
            "metric": "fusion_chunk_speedup",
            "value": round(speedup, 3),
            "unit": "x vs per-family dispatch sequence",
            "bar": ">= 1.5x on chip",
            "vs_baseline": round(speedup / 1.5, 4),
            "fused_chunks_per_sec": round(num_files / dt_fused, 2),
            "per_family_chunks_per_sec": round(num_files / dt_perfam, 2),
            "cold_fused_s": round(cold_fused, 4),
            "warm_fused_s": round(dt_fused, 4),
            "warm_per_family_s": round(dt_perfam, 4),
            "chunks": num_files,
            "source_rows": num_files * rows_per,
            "groups": int(len(a["n"])),
            "fused_dispatches_per_run": round(fused_dispatches, 1),
            "per_family_dispatches_per_run": round(perfam_dispatches, 1),
            "dispatch_reduction": round(reduction, 2),
            "cold_compiles": int(cold_compiles),
            "warm_compile_delta": int(warm_compile_delta),
            "peak_device_bytes": int(peak),
            "results_match": bool(identical and floats_ok),
            # an honest platform field: on CPU the dispatch overhead the
            # fusion removes is a sliver of a decode-bound wall clock, so the
            # chip bar does not apply; the dispatch/compile deltas do
            "platform": jax.default_backend(),
            "devices": len(jax.devices()),
            "cpus": len(os.sched_getaffinity(0)),
        }
        line = json.dumps(out)
        with open("BENCH_fusion.json", "w") as f:
            f.write(line + "\n")
        print(line)
        bars = []
        if not (identical and floats_ok):
            bars.append("fused and per-family results disagree")
        if reduction < 3.0:
            bars.append(f"dispatch reduction {reduction:.2f}x < 3x")
        if warm_compile_delta != 0:
            bars.append(f"warm runs compiled {warm_compile_delta} new programs")
        if bars:
            raise SystemExit("fusion bench bars violated: " + "; ".join(bars))
    finally:
        hst.set_session(None)
        shutil.rmtree(tmp, ignore_errors=True)


def _mesh_query(df):
    import hyperspace_tpu as hst

    return (
        df.filter(hst.col("k") < 500_000)
        .group_by("g1", "g2")
        .agg(
            n=("*", "count"),
            sum_qty=("qty", "sum"),
            lo=("qty", "min"),
            hi=("qty", "max"),
            sum_price=("price", "sum"),
            avg_disc=("disc", "avg"),
        )
    )


def mesh_child_main() -> None:
    """Child of ``--mesh``: run the sharded q1-shaped aggregate on however
    many devices XLA_FLAGS gave this process; print one JSON line."""
    _honor_cpu_request()
    import hashlib

    import jax

    import hyperspace_tpu as hst

    data_dir = os.environ["HS_BENCH_MESH_DATA"]
    sys_dir = os.environ["HS_BENCH_MESH_SYS"]
    reps = max(1, int(os.environ.get("BENCH_MESH_REPS", 3)))
    sess = hst.Session(
        conf={
            hst.keys.SYSTEM_PATH: sys_dir,
            hst.keys.PARALLEL_ENABLED: True,
            hst.keys.PARALLEL_MIN_ROWS: 0,
            hst.keys.TPU_QUERY_DEVICE_MIN_ROWS: 1,
            # one-shot on-device aggregation; streaming has its own benchmark
            hst.keys.EXEC_STREAM_AGG_MIN_BYTES: 1 << 60,
        }
    )
    hst.set_session(sess)
    sess.enable_hyperspace()
    q = _mesh_query(sess.read_parquet(data_dir))
    out = q.collect()  # cold: XLA compile + decode + H2D staging
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        out = q.collect()
        times.append(time.perf_counter() - t0)
    # result digest over the exact (order-stable) columns: the parent asserts
    # every mesh size computed the identical table
    h = hashlib.sha256()
    for k in ("g1", "g2", "n", "sum_qty", "lo", "hi"):
        h.update(np.asarray(out[k]).tobytes())
    print(
        json.dumps(
            {
                "devices": len(jax.devices()),
                "seconds": min(times),
                "groups": int(len(out["n"])),
                "digest": h.hexdigest(),
                "platform": jax.default_backend(),
            }
        )
    )


def mesh_main() -> None:
    """``python bench.py --mesh``: mesh scaling benchmark (see module doc)."""
    import subprocess

    sizes = [
        int(s) for s in os.environ.get("BENCH_MESH_SIZES", "1,2,4,8").split(",")
    ]
    num_files = int(os.environ.get("BENCH_MESH_FILES", 8))
    rows_per = int(os.environ.get("BENCH_MESH_ROWS_PER_FILE", 200_000))
    tmp = tempfile.mkdtemp(prefix="hs_bench_mesh_")
    try:
        import pyarrow as pa
        import pyarrow.parquet as pq

        data_dir = os.path.join(tmp, "lineitem")
        sys_dir = os.path.join(tmp, "indexes")
        os.makedirs(data_dir)
        os.makedirs(sys_dir)
        rng = np.random.default_rng(11)
        for i in range(num_files):
            pq.write_table(
                pa.table(
                    {
                        "k": rng.integers(0, 1_000_000, rows_per).astype(np.int64),
                        "g1": rng.integers(0, 25, rows_per).astype(np.int64),
                        "g2": rng.integers(0, 40, rows_per).astype(np.int64),
                        "qty": rng.integers(1, 51, rows_per).astype(np.int64),
                        "price": rng.uniform(900.0, 105_000.0, rows_per),
                        "disc": rng.uniform(0.0, 0.1, rows_per),
                    }
                ),
                os.path.join(data_dir, f"part-{i:05d}.parquet"),
                compression="zstd",
            )

        # build the covering index ONCE in the parent (index content is
        # mesh-independent — the distributed-build tests prove parity) and
        # point every child at it; children only time the query
        def build_index():
            _honor_cpu_request()
            import hyperspace_tpu as hst

            sess = hst.Session(
                conf={hst.keys.SYSTEM_PATH: sys_dir, hst.keys.NUM_BUCKETS: 8}
            )
            hst.Hyperspace(sess).create_index(
                sess.read_parquet(data_dir),
                hst.CoveringIndexConfig(
                    "meshIdx", ["k"], ["g1", "g2", "qty", "price", "disc"]
                ),
            )

        build_index()

        rows = num_files * rows_per
        results = {}
        for n in sizes:
            env = os.environ.copy()
            flags = [
                f
                for f in env.get("XLA_FLAGS", "").split()
                if not f.startswith("--xla_force_host_platform_device_count")
            ]
            flags.append(f"--xla_force_host_platform_device_count={n}")
            env["XLA_FLAGS"] = " ".join(flags)
            env["JAX_PLATFORMS"] = "cpu"
            env["HS_BENCH_MESH_DATA"] = data_dir
            env["HS_BENCH_MESH_SYS"] = sys_dir
            r = subprocess.run(
                [sys.executable, os.path.abspath(__file__), "--mesh-child"],
                env=env,
                capture_output=True,
                text=True,
                timeout=900,
            )
            if r.returncode != 0:
                raise RuntimeError(
                    f"mesh child (n={n}) failed:\n{r.stderr.strip()[-2000:]}"
                )
            results[n] = json.loads(r.stdout.strip().splitlines()[-1])
            assert results[n]["devices"] == n, results[n]

        digests = {c["digest"] for c in results.values()}
        per_sec = {n: rows / c["seconds"] for n, c in results.items()}
        per_chip = {n: per_sec[n] / n for n in results}
        lo, hi = min(sizes), max(sizes)
        flatness = per_chip[hi] / per_chip[lo]
        out = {
            "metric": "mesh_per_chip_flatness",
            "value": round(flatness, 4),
            "unit": f"x per-chip throughput ({hi}-way vs {lo}-way)",
            # bar (real hardware): per-chip throughput stays >= 0.7x at
            # full mesh width; emulated host devices share one CPU, so the
            # honest platform field below qualifies any miss
            "bar": 0.7,
            "vs_baseline": round(flatness / 0.7, 4),
            "rows": rows,
            "rows_per_sec": {str(n): round(v, 1) for n, v in per_sec.items()},
            "rows_per_sec_per_chip": {
                str(n): round(v, 1) for n, v in per_chip.items()
            },
            "groups": results[hi]["groups"],
            "results_identical_across_meshes": len(digests) == 1,
            "platform": results[hi]["platform"],
        }
        line = json.dumps(out)
        with open("BENCH_mesh.json", "w") as f:
            f.write(line + "\n")
        print(line)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def check_overhead_main() -> None:
    """--check-overhead: price the hscheck runtime hook.

    ``maybe_verify`` sits on every program-cache fill in exec/device.py and
    ops/bucketize.py. Its contract is that the DISABLED path (the default:
    ``hyperspace.check.hlo.enabled`` false) is one conf lookup — this measures
    that per-call cost against the mean cost of an actual program-cache fill
    (lower + XLA compile) and holds it under 1%. The enabled path's full
    verify cost is reported alongside for context (it is paid once per new
    executable, never per query). Writes BENCH_check.json.
    """
    _honor_cpu_request()
    _backend_watchdog()
    fills = max(8, int(os.environ.get("BENCH_CHECK_FILLS", 16)))
    calls = max(10_000, int(os.environ.get("BENCH_CHECK_CALLS", 200_000)))

    import jax
    import jax.numpy as jnp

    import hyperspace_tpu as hst
    from hyperspace_tpu.check import hlo_lint
    from hyperspace_tpu.exec import device as _device  # noqa: F401  (registers contracts)

    tmp = tempfile.mkdtemp(prefix="hs_bench_check_")
    try:
        sess = hst.Session(conf={hst.keys.SYSTEM_PATH: tmp})
        hst.set_session(sess)
        assert not sess.conf.check_hlo_enabled

        jitted = jax.jit(lambda x: jnp.cumsum(x * 2 + 1) % 7)

        # mean program-cache fill: lower+compile at distinct shapes so every
        # rep is a genuine fill, not a hit
        fill_times = []
        for i in range(fills):
            x = jnp.zeros((64 + 8 * i,), jnp.float32)
            t0 = time.perf_counter()
            jitted.lower(x).compile()
            fill_times.append(time.perf_counter() - t0)
        mean_fill = sum(fill_times) / len(fill_times)

        # disabled maybe_verify: the exact call the hot path makes
        x = jnp.zeros((64,), jnp.float32)
        t0 = time.perf_counter()
        for _ in range(calls):
            hlo_lint.maybe_verify(sess.conf, "fused-filter", "bench-key", jitted, (x,))
        disabled_per_call = (time.perf_counter() - t0) / calls

        # enabled path, paid once per new executable: verify one program
        hlo_lint.set_default_enabled(True)
        hlo_lint.reset_runtime_state()
        try:
            t0 = time.perf_counter()
            hlo_lint.maybe_verify(None, "fused-filter", "bench-key-on", jitted, (x,))
            enabled_once = time.perf_counter() - t0
        finally:
            hlo_lint.set_default_enabled(False)
            hlo_lint.reset_runtime_state()

        overhead_pct = 100.0 * disabled_per_call / mean_fill
        out = {
            "metric": "hscheck_disabled_hook_pct_of_program_cache_fill",
            "value": round(overhead_pct, 4),
            "unit": "%",
            "bar": "<= 1%",
            "pass": overhead_pct <= 1.0,
            "disabled_hook_ns": round(disabled_per_call * 1e9, 1),
            "mean_program_cache_fill_ms": round(mean_fill * 1e3, 3),
            "enabled_verify_once_ms": round(enabled_once * 1e3, 3),
            "fills": fills,
            "calls": calls,
        }
        print(json.dumps(out))
        with open("BENCH_check.json", "w") as f:
            json.dump(out, f, indent=2)
        if not out["pass"]:
            sys.exit(1)
    finally:
        hst.set_session(None)
        shutil.rmtree(tmp, ignore_errors=True)


def join_main() -> None:
    """``python bench.py --join``: streaming join engine benchmark.

    A q3-shaped chain — a multi-file fact table joined through two small
    dimension tables (both ride the broadcast hash join), a post-join filter
    and a projection on top — streamed chunk-by-chunk with cold io/device
    caches, prefetch pipeline on vs off. The pipeline overlaps the probe
    side's parquet decode with hash-probe/gather compute, so the speedup is
    decode/compute overlap, same physics as ``--scan-pipeline``.

    Checks: byte-identical chunk digests both ways, <= 3 hash-probe
    executables across the whole sweep (sqrt-2 shape buckets), and
    ``hs_join_build_cache_hits_total`` > 0 when the same chain is submitted
    as a micro-batch through a QueryServer (shared build sides). The
    ``platform`` field says honestly what backend ran. Bar: >= 1.5x;
    writes BENCH_join.json.
    """
    _honor_cpu_request()
    _backend_watchdog()
    num_files = int(os.environ.get("BENCH_JOIN_FILES", 8))
    rows_per = int(os.environ.get("BENCH_JOIN_ROWS_PER_FILE", 300_000))
    tmp = tempfile.mkdtemp(prefix="hs_bench_join_")
    try:
        import hashlib

        import jax
        import pyarrow as pa
        import pyarrow.parquet as pq

        import hyperspace_tpu as hst
        from hyperspace_tpu.exec import batch as B
        from hyperspace_tpu.exec import device as D
        from hyperspace_tpu.exec.device import clear_device_cache
        from hyperspace_tpu.exec.io import clear_io_cache
        from hyperspace_tpu.obs.metrics import REGISTRY

        data_dir = os.path.join(tmp, "orders")
        sys_dir = os.path.join(tmp, "indexes")
        os.makedirs(data_dir)
        os.makedirs(sys_dir)
        rng = np.random.default_rng(11)
        n_cust, n_seg = 2_000, 25
        for i in range(num_files):
            # io-heavy fact side: wide incompressible numeric payload, so each
            # chunk's read blocks on real storage (page cache is dropped per
            # run below) while decode itself stays cheap — the regime the
            # prefetch pipeline exists for (hide storage latency behind probe
            # compute), measurable even on a single-core host
            fact_cols = {
                "custkey": rng.integers(0, n_cust, rows_per).astype(np.int64),
                "segkey": rng.integers(0, n_seg, rows_per).astype(np.int64),
                "amount": rng.uniform(0.0, 1000.0, rows_per),
            }
            for j in range(8):
                fact_cols[f"m{j}"] = rng.standard_normal(rows_per)
            pq.write_table(
                pa.table(fact_cols),
                os.path.join(data_dir, f"part-{i:05d}.parquet"),
                compression="zstd",
            )
        dim1_dir = os.path.join(tmp, "customer")
        dim2_dir = os.path.join(tmp, "segment")
        os.makedirs(dim1_dir)
        os.makedirs(dim2_dir)
        pq.write_table(
            pa.table(
                {
                    "ckey": np.arange(n_cust, dtype=np.int64),
                    "cname": np.char.add("cust-", np.arange(n_cust).astype(str)),
                    "nation": rng.integers(0, 25, n_cust).astype(np.int64),
                }
            ),
            os.path.join(dim1_dir, "p.parquet"),
        )
        pq.write_table(
            pa.table(
                {
                    "skey": np.arange(n_seg, dtype=np.int64),
                    "segment": np.array([f"SEG{i}" for i in range(n_seg)]),
                }
            ),
            os.path.join(dim2_dir, "p.parquet"),
        )

        sess = hst.Session(
            conf={
                hst.keys.SYSTEM_PATH: sys_dir,
                hst.keys.EXEC_STREAM_CHUNK_BYTES: 1,  # one fact file per chunk
                hst.keys.EXEC_PIPELINE_DEPTH: 4,  # hide deeper io stalls
            }
        )
        hst.set_session(sess)
        fact = sess.read_parquet(data_dir)
        dim1 = sess.read_parquet(dim1_dir)
        dim2 = sess.read_parquet(dim2_dir)
        q = (
            fact.join(dim1, on=hst.col("custkey") == hst.col("ckey"))
            .join(dim2, on=hst.col("segkey") == hst.col("skey"))
            .filter(hst.col("segment") == "SEG2")
            .select(
                "cname", "segment", "amount",
                "m0", "m1", "m2", "m3", "m4", "m5", "m6", "m7",
            )
        )

        def digest(batch) -> str:
            h = hashlib.sha1()
            for name in sorted(batch):
                a = np.asarray(batch[name])
                h.update(name.encode())
                if a.dtype == object:
                    h.update("\x00".join(map(str, a.tolist())).encode())
                else:
                    h.update(np.ascontiguousarray(a).tobytes())
            return h.hexdigest()

        def drop_page_cache(d: str) -> None:
            # cold-cache means COLD: flush then drop the OS page cache for the
            # source files so every timed read blocks on real storage — that
            # io wait is exactly what the prefetch pipeline overlaps with
            # compute (fadvise skips dirty pages, hence the fsync first)
            for name in os.listdir(d):
                fd = os.open(os.path.join(d, name), os.O_RDONLY)
                try:
                    os.fsync(fd)
                    os.posix_fadvise(fd, 0, 0, os.POSIX_FADV_DONTNEED)
                finally:
                    os.close(fd)

        def run(pipelined: bool):
            sess.conf.set(hst.keys.EXEC_PIPELINE_ENABLED, pipelined)
            sess.conf.set(hst.keys.EXEC_JOIN_PIPELINE_ENABLED, pipelined)
            clear_io_cache()
            clear_device_cache()
            for d in (data_dir, dim1_dir, dim2_dir):
                drop_page_cache(d)
            digests = []
            rows = 0
            t0 = time.perf_counter()
            for chunk in q.to_local_iterator():
                rows += B.num_rows(chunk)
                digests.append(digest(chunk))
            dt = time.perf_counter() - t0
            return digests, rows, dt

        run(True)  # warm jit (process-wide) so neither timed run bills compile
        probe_execs = len(
            {key for key in D._COMPILE_SEEN if key[0] == "hash-probe"}
        )
        reps = max(1, int(os.environ.get("BENCH_JOIN_REPS", 3)))
        d_serial = d_pipe = None
        rows_serial = rows_pipe = 0
        dt_serial = dt_pipe = float("inf")
        for _ in range(reps):
            ds, rs, ts = run(False)
            dp, rp, tp = run(True)
            d_serial, rows_serial, dt_serial = ds, rs, min(dt_serial, ts)
            d_pipe, rows_pipe, dt_pipe = dp, rp, min(dt_pipe, tp)
        identical = d_serial == d_pipe and rows_serial == rows_pipe

        # shared build sides: the same chain submitted as a micro-batch pays
        # one hash-table build per dimension, the rest hit the serving cache
        from hyperspace_tpu.serving import QueryServer

        def hits() -> float:
            snap = REGISTRY.snapshot().get("hs_join_build_cache_hits_total")
            return sum(s["value"] for s in snap["series"]) if snap else 0.0

        hits_before = hits()
        small = (
            fact.join(dim2, on=hst.col("segkey") == hst.col("skey"))
            .filter(hst.col("segment") == "SEG2")
            .select("segment", "amount")
        )
        with QueryServer(sess, workers=2, result_cache_enabled=False) as srv:
            futs = [srv.submit(small, timeout=120) for _ in range(4)]
            for f in futs:
                f.result(timeout=120)
        build_cache_hits = hits() - hits_before

        src_rows = num_files * rows_per
        speedup = dt_serial / dt_pipe
        out = {
            "metric": "join_pipeline_speedup",
            "value": round(speedup, 3),
            "unit": "x vs serial",
            "bar": ">= 1.5x",
            "vs_baseline": round(speedup / 1.5, 4),
            "pipelined_rows_per_sec": round(src_rows / dt_pipe, 1),
            "serial_rows_per_sec": round(src_rows / dt_serial, 1),
            "chunks": num_files,
            "result_rows": int(rows_pipe),
            "byte_identical": bool(identical),
            "probe_executables": int(probe_execs),
            "probe_executables_flat": bool(probe_execs <= 3),
            "build_cache_hits": build_cache_hits,
            # an honest platform field: on the CPU backend the "device" hash
            # probe and the parquet decode share host cores, so the overlap
            # win is a lower bound for real chips with a free host — and with
            # a single host core only true storage io-wait is overlappable
            "platform": jax.default_backend(),
            "devices": len(jax.devices()),
            "cpus": len(os.sched_getaffinity(0)),
        }
        line = json.dumps(out)
        with open("BENCH_join.json", "w") as f:
            f.write(line + "\n")
        print(line)
    finally:
        hst.set_session(None)
        shutil.rmtree(tmp, ignore_errors=True)


def main() -> None:
    _honor_cpu_request()
    _backend_watchdog()
    num_rows = int(os.environ.get("BENCH_ROWS", 4_000_000))
    tmp = tempfile.mkdtemp(prefix="hs_bench_")
    try:
        data_dir = os.path.join(tmp, "lineitem")
        sys_dir = os.path.join(tmp, "indexes")
        os.makedirs(data_dir)
        os.makedirs(sys_dir)
        make_lineitem_like(data_dir, num_rows)

        import jax

        import hyperspace_tpu as hst

        sess = hst.Session(conf={hst.keys.SYSTEM_PATH: sys_dir, hst.keys.NUM_BUCKETS: 64})
        hst.set_session(sess)
        hs = hst.Hyperspace(sess)
        df = sess.read_parquet(data_dir)

        # warm up compile so jit time isn't billed (steady-state throughput is
        # the metric; first-compile is amortized by the persistent XLA cache):
        # a tiny end-to-end build warms every non-sort code path, then the
        # fused sort program is pre-compiled at the main build's size class
        warm_dir = os.path.join(tmp, "warm")
        os.makedirs(warm_dir)
        make_lineitem_like(warm_dir, 10_000, 1)
        warm_df = sess.read_parquet(warm_dir)
        hs.create_index(warm_df, hst.CoveringIndexConfig("warm", ["l_orderkey"], ["l_extendedprice"]))
        from hyperspace_tpu.ops import sort as hs_sort

        # warm every chunk size class the pipelined build will compile:
        # full chunks plus the (possibly smaller) tail chunk
        batch_rows = sess.conf.build_batch_rows
        sizes = {hs_sort.padded_size(min(num_rows, batch_rows))}
        tail = num_rows % batch_rows
        if num_rows > batch_rows and tail:
            sizes.add(hs_sort.padded_size(tail))
        for s in sorted(sizes):
            hs_sort.warm_build(s, ("i",), (np.int32,), 64)

        # steady-state throughput: N timed builds, best wins — the first
        # also warms the OS page cache for the source files, and the min
        # filters ambient dips of the shared tunnel/host (chip sessions have
        # shown 2x run-to-run swings on identical code; the chip's own
        # compute is deterministic)
        reps = max(1, int(os.environ.get("BENCH_BUILD_REPS", 3)))
        times = []
        for i in range(reps):
            t0 = time.perf_counter()
            hs.create_index(
                df,
                hst.CoveringIndexConfig(
                    f"bench_idx_{i}", ["l_orderkey"], ["l_extendedprice", "l_discount"]
                ),
            )
            times.append(time.perf_counter() - t0)
        dt = min(times)

        n_chips = max(1, len(jax.devices()))
        rows_per_sec_per_chip = num_rows / dt / n_chips
        print(
            json.dumps(
                {
                    "metric": "covering_index_build_rows_per_sec_per_chip",
                    "value": round(rows_per_sec_per_chip, 1),
                    "unit": "rows/s/chip",
                    "vs_baseline": round(rows_per_sec_per_chip / 1_000_000.0, 4),
                    "build_times_s": [round(t, 3) for t in times],
                }
            )
        )
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def refresh_main() -> None:
    """``python bench.py --refresh``: serving under concurrent refresh.

    One marker-file dataset behind a covering index and a QueryServer. Phase
    one measures per-query latency quiesced; phase two repeats the identical
    load while a driver thread appends files and commits incremental
    refreshes through the lifecycle ``RefreshManager``. Every served result
    is validated like the soak test: each file's marker rows appear
    all-or-nothing (torn check) and every marker whose refresh committed
    before submission is present (staleness check) — ``staleness_rejections``
    in the JSON must be 0. ``vs_baseline`` is quiesced p99 / under-refresh
    p99 (1.0 = refresh is latency-free).
    """
    _honor_cpu_request()
    _backend_watchdog()
    import threading

    rows_per_file = int(os.environ.get("BENCH_REFRESH_ROWS", 20_000))
    queries = max(8, int(os.environ.get("BENCH_REFRESH_QUERIES", 60)))
    initial_files = 4
    tmp = tempfile.mkdtemp(prefix="hs_bench_refresh_")
    try:
        import jax
        import pyarrow as pa
        import pyarrow.parquet as pq

        import hyperspace_tpu as hst
        from hyperspace_tpu.lifecycle import RefreshManager
        from hyperspace_tpu.serving import QueryServer

        data_dir = os.path.join(tmp, "marked")
        sys_dir = os.path.join(tmp, "indexes")
        os.makedirs(data_dir)
        os.makedirs(sys_dir)

        def write_marked(marker: int) -> None:
            t = pa.table(
                {
                    "c1": (np.arange(rows_per_file, dtype=np.int64) * 13) % 1000,
                    "m": np.full(rows_per_file, marker, dtype=np.int64),
                }
            )
            final = os.path.join(data_dir, f"part-{marker:05d}.parquet")
            pq.write_table(t, final + ".tmp")
            os.replace(final + ".tmp", final)

        for i in range(initial_files):
            write_marked(i)

        sess = hst.Session(conf={hst.keys.SYSTEM_PATH: sys_dir, hst.keys.NUM_BUCKETS: 8})
        hst.set_session(sess)
        sess.conf.set(hst.keys.HYBRID_SCAN_ENABLED, True)
        sess.conf.set(hst.keys.HYBRID_SCAN_MAX_APPENDED_RATIO, 0.95)
        sess.conf.set(hst.keys.HYBRID_SCAN_MAX_DELETED_RATIO, 0.95)
        hs = hst.Hyperspace(sess)
        hs.create_index(
            sess.read_parquet(data_dir), hst.CoveringIndexConfig("bixr", ["c1"], ["m"])
        )
        sess.enable_hyperspace()
        rm = RefreshManager(sess)
        bus = sess.lifecycle_bus

        state_lock = threading.Lock()
        committed = list(range(initial_files))
        violations = []

        def check(res, need):
            vals, cnts = np.unique(res["m"], return_counts=True)
            seen = dict(zip(vals.tolist(), cnts.tolist()))
            for mk, c in seen.items():
                if c != rows_per_file:
                    violations.append(("torn", int(mk), int(c)))
            for mk in need:
                if seen.get(mk) != rows_per_file:
                    violations.append(("stale", int(mk), seen.get(mk)))

        def run_phase(srv, refreshing: bool):
            stop = threading.Event()
            next_marker = [len(committed)]

            def driver():
                while not stop.is_set():
                    marker = next_marker[0]
                    next_marker[0] += 1
                    write_marked(marker)
                    if rm.refresh_index("bixr", "incremental") == "committed":
                        with state_lock:
                            committed.append(marker)

            t = threading.Thread(target=driver) if refreshing else None
            if t is not None:
                t.start()
            lats = []
            try:
                for _ in range(queries):
                    with state_lock:
                        need = list(committed)
                    q = sess.read_parquet(data_dir).filter(hst.col("c1") >= 0).select("m")
                    t0 = time.perf_counter()
                    res = srv.submit(q).result(timeout=300)
                    lats.append(time.perf_counter() - t0)
                    check(res, need)
            finally:
                stop.set()
                if t is not None:
                    t.join(60)
            return lats

        with QueryServer(sess, workers=2, queue_depth=65536) as srv:
            # warm: compile + first decode
            srv.submit(sess.read_parquet(data_dir).filter(hst.col("c1") >= 0).select("m")).result(
                timeout=300
            )
            seq0 = bus.commit_seq
            quiesced = run_phase(srv, refreshing=False)
            refreshed = run_phase(srv, refreshing=True)
            commits = bus.commit_seq - seq0

        def pct(xs, p):
            return float(np.percentile(np.asarray(xs), p))

        p99_q, p99_r = pct(quiesced, 99), pct(refreshed, 99)
        out = {
            "metric": "serving_p99_under_refresh_seconds",
            "value": round(p99_r, 4),
            "unit": "s",
            "vs_baseline": round(p99_q / p99_r, 4) if p99_r > 0 else 1.0,
            "platform": jax.default_backend(),
            "devices": len(jax.devices()),
            "quiesced": {"p50": round(pct(quiesced, 50), 4), "p99": round(p99_q, 4)},
            "under_refresh": {"p50": round(pct(refreshed, 50), 4), "p99": round(p99_r, 4)},
            "refresh_commits": commits,
            "queries_per_phase": queries,
            "staleness_rejections": len(violations),
        }
        line = json.dumps(out)
        with open("BENCH_refresh.json", "w") as f:
            f.write(line + "\n")
        print(line)
        if violations:
            raise SystemExit(f"refresh bench served stale/torn results: {violations[:10]}")
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def faults_main() -> None:
    """``python bench.py --faults``: serving under injected transient faults.

    One indexed dataset and a QueryServer with the retry policy enabled.
    Phase one serves the query mix clean; phase two serves the identical mix
    under ``io.decode:transient:p=0.01`` (seeded, deterministic). The io
    cache is disabled for the whole run so every query really decodes —
    otherwise a warm cache would hide the seam and the fault rate would
    measure nothing. Every successful result is checked against a clean
    oracle digest; every failure must be a typed ``ReliabilityError``.

    Bars (violations raise SystemExit): ``wrong_answers == 0``,
    ``unclassified_errors == 0``, ``p99_faulted <= 3 * p99_clean``.
    ``vs_baseline`` is clean p99 / faulted p99 (1.0 = faults are free).
    """
    # must precede the hyperspace import: exec/io.py sizes its decode LRU
    # from this env var at module import
    os.environ["HS_IO_CACHE_BYTES"] = "0"
    _honor_cpu_request()
    _backend_watchdog()
    num_rows = int(os.environ.get("BENCH_FAULTS_ROWS", 60_000))
    num_files = max(2, int(os.environ.get("BENCH_FAULTS_FILES", 6)))
    reps = max(1, int(os.environ.get("BENCH_FAULTS_REPS", 8)))
    fault_p = float(os.environ.get("BENCH_FAULTS_P", 0.01))
    tmp = tempfile.mkdtemp(prefix="hs_bench_faults_")
    try:
        import jax
        import pyarrow as pa
        import pyarrow.parquet as pq

        import hyperspace_tpu as hst
        from hyperspace_tpu.obs.metrics import REGISTRY
        from hyperspace_tpu.reliability import errors as rerr
        from hyperspace_tpu.reliability.faults import FaultRule, fault_scope
        from hyperspace_tpu.serving import QueryServer

        data_dir = os.path.join(tmp, "sales")
        sys_dir = os.path.join(tmp, "indexes")
        os.makedirs(data_dir)
        os.makedirs(sys_dir)
        per = num_rows // num_files
        for i in range(num_files):
            base = np.arange(i * per, (i + 1) * per, dtype=np.int64)
            pq.write_table(
                pa.table({"b": (base * 7) % 997, "a": base % 211, "v": (base * 31) % 10_000}),
                os.path.join(data_dir, f"part-{i:05d}.parquet"),
            )

        sess = hst.Session(
            conf={
                hst.keys.SYSTEM_PATH: sys_dir,
                hst.keys.NUM_BUCKETS: 8,
                hst.keys.RELIABILITY_RETRY_ENABLED: True,
                hst.keys.RELIABILITY_RETRY_BASE_MS: 1.0,
                hst.keys.RELIABILITY_RETRY_CAP_MS: 20.0,
            }
        )
        hst.set_session(sess)
        hs = hst.Hyperspace(sess)
        df = sess.read_parquet(data_dir)
        hs.create_index(df, hst.CoveringIndexConfig("fix0", ["b"], ["a", "v"]))
        sess.enable_hyperspace()

        plans = [
            sess.read_parquet(data_dir).filter(hst.col("b") > 300 + i).select("a", "v")
            for i in range(16)
        ]

        def digest(res):
            return (
                len(res["a"]),
                int(np.sum(np.asarray(res["a"], dtype=np.int64))),
                int(np.sum(np.asarray(res["v"], dtype=np.int64))),
            )

        oracle = [digest(p.collect()) for p in plans]

        def run(srv, tag):
            lats, ok, wrong, typed, unclassified = [], 0, 0, 0, 0
            t0 = time.perf_counter()
            for _ in range(reps):
                for i, p in enumerate(plans):
                    ts = time.perf_counter()
                    try:
                        res = srv.submit(p).result(timeout=300)
                    except rerr.ReliabilityError:
                        typed += 1
                        continue
                    except Exception:
                        unclassified += 1
                        continue
                    lats.append(time.perf_counter() - ts)
                    if digest(res) == oracle[i]:
                        ok += 1
                    else:
                        wrong += 1
            wall = time.perf_counter() - t0
            return {
                "phase": tag,
                "queries": reps * len(plans),
                "goodput_qps": round(ok / wall, 1),
                "p50_s": round(float(np.percentile(lats, 50)), 4) if lats else None,
                "p99_s": round(float(np.percentile(lats, 99)), 4) if lats else None,
                "wrong_answers": wrong,
                "typed_errors": typed,
                "unclassified_errors": unclassified,
            }

        retries0 = REGISTRY.counter("hs_io_retries_total", op="io.decode", reason="injected").value
        fires0 = REGISTRY.counter(
            "hs_faults_injected_total", site="io.decode", kind="transient"
        ).value
        # serving-layer caches off for the same reason as the io cache: a
        # warm bucket/result cache never re-decodes, and the seam goes dark
        with QueryServer(
            sess,
            workers=2,
            queue_depth=65536,
            bucket_cache_bytes=0,
            prefetch_enabled=False,
            result_cache_enabled=False,
        ) as srv:
            for p in plans:  # warm: compile (decode stays cold by design)
                srv.submit(p).result(timeout=300)
            clean = run(srv, "clean")
            with fault_scope(
                FaultRule("io.decode", "transient", probability=fault_p), seed=17
            ):
                faulted = run(srv, "faulted")
        retries = (
            REGISTRY.counter("hs_io_retries_total", op="io.decode", reason="injected").value
            - retries0
        )
        fires = (
            REGISTRY.counter(
                "hs_faults_injected_total", site="io.decode", kind="transient"
            ).value
            - fires0
        )

        p99_ratio = (
            faulted["p99_s"] / clean["p99_s"] if clean["p99_s"] and faulted["p99_s"] else None
        )
        out = {
            "metric": "faulted_serving_p99_seconds",
            "value": faulted["p99_s"],
            "unit": "s",
            "vs_baseline": round(clean["p99_s"] / faulted["p99_s"], 4)
            if p99_ratio
            else None,
            "platform": jax.default_backend(),
            "devices": len(jax.devices()),
            "fault_rate": fault_p,
            "fault_fires": int(fires),
            "injected_retries": int(retries),
            "clean": clean,
            "faulted": faulted,
            "p99_ratio": round(p99_ratio, 3) if p99_ratio else None,
        }
        line = json.dumps(out)
        with open("BENCH_faults.json", "w") as f:
            f.write(line + "\n")
        print(line)
        bars = []
        for ph in (clean, faulted):
            if ph["wrong_answers"]:
                bars.append(f"{ph['phase']}: {ph['wrong_answers']} wrong answers")
            if ph["unclassified_errors"]:
                bars.append(f"{ph['phase']}: {ph['unclassified_errors']} unclassified errors")
        if p99_ratio is not None and p99_ratio > 3.0:
            bars.append(f"faulted p99 {p99_ratio:.2f}x clean (bar: <= 3x)")
        if fires == 0:
            bars.append("fault harness never fired: the bench measured nothing")
        if bars:
            raise SystemExit("faults bench bars violated: " + "; ".join(bars))
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def fabric_child_main() -> None:
    """Child of ``--fabric``: one fabric serving worker. Fabric-on session
    with a live CommitWatcher, served views re-registered on every
    (replayed) commit, a named QueryServer behind a WorkerEndpoint; prints
    the endpoint URL and serves until the parent closes stdin."""
    _honor_cpu_request()
    import hyperspace_tpu as hst
    from hyperspace_tpu.fabric import WorkerEndpoint
    from hyperspace_tpu.serving import QueryServer

    data_dir = os.environ["HS_BENCH_FABRIC_DATA"]
    sys_dir = os.environ["HS_BENCH_FABRIC_SYS"]
    name = os.environ["HS_BENCH_FABRIC_NAME"]
    poll_s = float(os.environ.get("HS_BENCH_FABRIC_POLL", "0.2"))
    sess = hst.Session(
        conf={
            hst.keys.SYSTEM_PATH: sys_dir,
            hst.keys.FABRIC_ENABLED: True,
            hst.keys.FABRIC_NODE_ID: name,
            hst.keys.FABRIC_POLL_INTERVAL_SECONDS: poll_s,
        }
    )
    sess.enable_hyperspace()

    def refresh_views(event):
        # a DataFrame freezes its source listing at read time; re-resolving
        # served views on every commit is the fabric worker pattern
        sess.register_view("t", sess.read_parquet(data_dir))

    sess.register_view("t", sess.read_parquet(data_dir))
    sess.lifecycle_bus.subscribe(refresh_views)
    with QueryServer(sess, workers=2, name=name) as srv:
        with WorkerEndpoint(srv) as ep:
            print(ep.url, flush=True)
            sys.stdin.readline()  # serve until the parent closes stdin


def fabric_main() -> None:
    """``python bench.py --fabric``: scale-out serving fabric throughput.

    One marker-file dataset behind a covering index, one refresh writer (a
    fabric-on session with the watcher off), and fleets of {1,2,4} fabric
    server subprocesses behind a FrontDoor. While the writer continuously
    appends files and commits incremental refreshes, concurrent clients
    route tenant-affine queries through the FrontDoor; every answer is
    validated like the soak test — each file's marker rows all-or-nothing
    (torn check) and every marker whose commit settled for at least one
    watcher poll interval present (staleness check). ``staleness_reads``
    and ``torn_reads`` in the JSON must be 0 or the bench exits nonzero.
    ``vs_baseline`` is max-fleet QPS / single-process QPS.
    """
    _honor_cpu_request()
    _backend_watchdog()
    import subprocess
    import threading
    from concurrent.futures import ThreadPoolExecutor

    import jax

    import hyperspace_tpu as hst
    from hyperspace_tpu.fabric import FrontDoor
    from hyperspace_tpu.lifecycle import RefreshManager

    sizes = [int(s) for s in os.environ.get("BENCH_FABRIC_SIZES", "1,2,4").split(",")]
    rows_per_file = int(os.environ.get("BENCH_FABRIC_ROWS", 20_000))
    queries_per_fleet = max(8, int(os.environ.get("BENCH_FABRIC_QUERIES", 48)))
    clients = max(2, int(os.environ.get("BENCH_FABRIC_CLIENTS", 8)))
    poll_s = 0.2
    settle_s = poll_s * 3 + 0.3  # staleness bound + scheduling margin
    tmp = tempfile.mkdtemp(prefix="hs_bench_fabric_")
    try:
        import pyarrow as pa
        import pyarrow.parquet as pq

        data_dir = os.path.join(tmp, "marked")
        sys_dir = os.path.join(tmp, "indexes")
        os.makedirs(data_dir)
        os.makedirs(sys_dir)

        def write_marked(marker: int) -> None:
            t = pa.table(
                {
                    "c1": (np.arange(rows_per_file, dtype=np.int64) * 13) % 1000,
                    "m": np.full(rows_per_file, marker, dtype=np.int64),
                }
            )
            final = os.path.join(data_dir, f"part-{marker:05d}.parquet")
            pq.write_table(t, final + ".tmp")
            os.replace(final + ".tmp", final)

        initial = 3
        for i in range(initial):
            write_marked(i)

        writer = hst.Session(
            conf={
                hst.keys.SYSTEM_PATH: sys_dir,
                hst.keys.FABRIC_ENABLED: True,
                hst.keys.FABRIC_NODE_ID: "writer",
                hst.keys.FABRIC_WATCHER_ENABLED: False,  # pure publisher
            }
        )
        hst.Hyperspace(writer).create_index(
            writer.read_parquet(data_dir),
            hst.CoveringIndexConfig("fabBix", ["c1"], ["m"]),
        )
        rm = RefreshManager(writer)

        state_lock = threading.Lock()
        committed = [(i, 0.0) for i in range(initial)]  # (marker, commit time)
        next_marker = [initial]
        violations = []

        def run_query(fd, tenant: str) -> float:
            with state_lock:
                need = [mk for mk, ts in committed if ts <= time.time() - settle_s]
            t0 = time.perf_counter()
            res = fd.query("SELECT m FROM t WHERE c1 >= 0", tenant=tenant)
            lat = time.perf_counter() - t0
            vals, cnts = np.unique(res["m"], return_counts=True)
            seen = dict(zip(vals.tolist(), cnts.tolist()))
            with state_lock:
                for mk, c in seen.items():
                    if c != rows_per_file:
                        violations.append(("torn", int(mk), int(c)))
                for mk in need:
                    if seen.get(mk) != rows_per_file:
                        violations.append(("stale", int(mk), seen.get(mk)))
            return lat

        def run_fleet(n: int) -> dict:
            env = os.environ.copy()
            env["JAX_PLATFORMS"] = "cpu"
            env["HS_BENCH_FABRIC_DATA"] = data_dir
            env["HS_BENCH_FABRIC_SYS"] = sys_dir
            env["HS_BENCH_FABRIC_POLL"] = str(poll_s)
            procs = []
            try:
                for i in range(n):
                    env_i = dict(env, HS_BENCH_FABRIC_NAME=f"qs{i}")
                    procs.append(
                        subprocess.Popen(
                            [sys.executable, os.path.abspath(__file__), "--fabric-child"],
                            env=env_i,
                            stdin=subprocess.PIPE,
                            stdout=subprocess.PIPE,
                            stderr=subprocess.PIPE,
                            text=True,
                        )
                    )
                urls = [p.stdout.readline().strip() for p in procs]
                for p, u in zip(procs, urls):
                    if not u.startswith("http://"):
                        raise RuntimeError(
                            f"fabric child failed to start: {p.stderr.read()[-2000:]}"
                        )
                fd = FrontDoor(urls)
                for t in range(clients):  # warm every worker: compile + decode
                    run_query(fd, f"tenant-{t}")

                stop = threading.Event()
                commits = [0]

                def refresher():
                    while not stop.is_set():
                        marker = next_marker[0]
                        next_marker[0] += 1
                        write_marked(marker)
                        if rm.refresh_index("fabBix", "incremental") == "committed":
                            with state_lock:
                                committed.append((marker, time.time()))
                            commits[0] += 1
                        stop.wait(0.4)

                rt = threading.Thread(target=refresher)
                rt.start()
                lats = []
                t0 = time.perf_counter()
                try:
                    with ThreadPoolExecutor(max_workers=clients) as pool:
                        futs = [
                            pool.submit(run_query, fd, f"tenant-{i % clients}")
                            for i in range(queries_per_fleet)
                        ]
                        lats = [f.result(timeout=300) for f in futs]
                finally:
                    stop.set()
                    rt.join(60)
                wall = time.perf_counter() - t0
                arr = np.asarray(lats)
                return {
                    "qps": round(queries_per_fleet / wall, 2),
                    "p50_s": round(float(np.percentile(arr, 50)), 4),
                    "p99_s": round(float(np.percentile(arr, 99)), 4),
                    "queries": queries_per_fleet,
                    "refresh_commits": commits[0],
                }
            finally:
                for p in procs:
                    try:
                        p.stdin.close()
                    except Exception:
                        pass
                for p in procs:
                    try:
                        p.wait(timeout=30)
                    except Exception:
                        p.kill()

        fleets = {}
        try:
            for n in sizes:
                fleets[n] = run_fleet(n)
        finally:
            writer.fabric.stop()

        lo, hi = min(sizes), max(sizes)
        out = {
            "metric": "fabric_scale_out_qps",
            "value": fleets[hi]["qps"],
            "unit": f"queries/s through {hi} server processes under refresh",
            "vs_baseline": round(fleets[hi]["qps"] / fleets[lo]["qps"], 4)
            if fleets[lo]["qps"] > 0
            else 1.0,
            "fleets": {str(n): fleets[n] for n in sizes},
            "staleness_reads": sum(1 for v in violations if v[0] == "stale"),
            "torn_reads": sum(1 for v in violations if v[0] == "torn"),
            "settle_seconds": round(settle_s, 3),
            "rows_per_file": rows_per_file,
            "platform": jax.default_backend(),
            "cpus": os.cpu_count(),
        }
        line = json.dumps(out)
        with open("BENCH_fabric.json", "w") as f:
            f.write(line + "\n")
        print(line)
        if violations:
            raise SystemExit(f"fabric bench served stale/torn results: {violations[:10]}")
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def failover_main() -> None:
    """``python bench.py --failover``: fabric crash tolerance under load.

    3 fabric worker subprocesses behind a FrontDoor with health tracking
    and failover on (failure threshold 1, heartbeat-paced probing). Client
    threads route tenant-affine queries; a third of the way through, one
    worker is SIGKILLed. Every request's answer is validated against the
    expected marker counts. A monitor thread probes ``/healthz`` every
    heartbeat interval and records how long the dead worker stayed in the
    rendezvous set. Bars (nonzero exit on violation): zero requests lost,
    zero wrong answers, detection within 2 heartbeat intervals.
    """
    _honor_cpu_request()
    _backend_watchdog()
    import signal
    import subprocess
    import threading
    from concurrent.futures import ThreadPoolExecutor

    import jax

    import hyperspace_tpu as hst
    from hyperspace_tpu.fabric import FrontDoor
    from hyperspace_tpu.fabric.health import HealthTracker
    from hyperspace_tpu.obs.metrics import REGISTRY

    workers_n = 3
    rows_per_file = int(os.environ.get("BENCH_FAILOVER_ROWS", 20_000))
    total_queries = max(24, int(os.environ.get("BENCH_FAILOVER_QUERIES", 90)))
    clients = max(2, int(os.environ.get("BENCH_FAILOVER_CLIENTS", 6)))
    hb_s = float(os.environ.get("BENCH_FAILOVER_HEARTBEAT", "0.5"))
    tmp = tempfile.mkdtemp(prefix="hs_bench_failover_")
    try:
        import pyarrow as pa
        import pyarrow.parquet as pq

        data_dir = os.path.join(tmp, "marked")
        sys_dir = os.path.join(tmp, "indexes")
        os.makedirs(data_dir)
        os.makedirs(sys_dir)
        initial = 3
        for marker in range(initial):
            t = pa.table(
                {
                    "c1": (np.arange(rows_per_file, dtype=np.int64) * 13) % 1000,
                    "m": np.full(rows_per_file, marker, dtype=np.int64),
                }
            )
            final = os.path.join(data_dir, f"part-{marker:05d}.parquet")
            pq.write_table(t, final + ".tmp")
            os.replace(final + ".tmp", final)
        expect = {m: rows_per_file for m in range(initial)}

        writer = hst.Session(
            conf={
                hst.keys.SYSTEM_PATH: sys_dir,
                hst.keys.FABRIC_ENABLED: True,
                hst.keys.FABRIC_NODE_ID: "writer",
                hst.keys.FABRIC_WATCHER_ENABLED: False,
            }
        )
        hst.Hyperspace(writer).create_index(
            writer.read_parquet(data_dir),
            hst.CoveringIndexConfig("foIdx", ["c1"], ["m"]),
        )

        env = os.environ.copy()
        env["JAX_PLATFORMS"] = "cpu"
        env["HS_BENCH_FABRIC_DATA"] = data_dir
        env["HS_BENCH_FABRIC_SYS"] = sys_dir
        env["HS_BENCH_FABRIC_POLL"] = "0.5"
        procs = []
        try:
            for i in range(workers_n):
                env_i = dict(env, HS_BENCH_FABRIC_NAME=f"qs{i}")
                procs.append(
                    subprocess.Popen(
                        [sys.executable, os.path.abspath(__file__), "--fabric-child"],
                        env=env_i,
                        stdin=subprocess.PIPE,
                        stdout=subprocess.PIPE,
                        stderr=subprocess.PIPE,
                        text=True,
                    )
                )
            urls = [p.stdout.readline().strip() for p in procs]
            for p, u in zip(procs, urls):
                if not u.startswith("http://"):
                    raise RuntimeError(
                        f"fabric child failed to start: {p.stderr.read()[-2000:]}"
                    )
            health = HealthTracker(
                failure_threshold=1,
                probe_interval_s=3600.0,  # no readmission during the bench
                heartbeat_interval_s=hb_s,
                missed_beats=2,
            )
            fd = FrontDoor(urls, health=health, failover=True)
            dead_wid = next(
                w for w in fd.worker_ids if fd._workers[w] == urls[0].rstrip("/")
            )
            tenants = [f"tenant-{i}" for i in range(clients)]
            for t in tenants:  # warm every worker: compile + decode
                fd.query("SELECT m FROM t WHERE c1 >= 0", tenant=t)

            def retries_sum() -> int:
                return sum(
                    int(
                        REGISTRY.counter(
                            "hs_frontdoor_failover_retries_total", worker=w
                        ).value
                    )
                    for w in fd.worker_ids
                )

            retries0 = retries_sum()
            state_lock = threading.Lock()
            done = [0]
            failed, wrong = [], []
            lat_before, lat_after = [], []
            killed = threading.Event()

            def run_query(i: int) -> None:
                tenant = tenants[i % clients]
                t0 = time.perf_counter()
                try:
                    res = fd.query("SELECT m FROM t WHERE c1 >= 0", tenant=tenant)
                except Exception as exc:
                    with state_lock:
                        failed.append((tenant, type(exc).__name__, str(exc)[:200]))
                        done[0] += 1
                    return
                lat = time.perf_counter() - t0
                vals, cnts = np.unique(res["m"], return_counts=True)
                seen = dict(zip(vals.tolist(), cnts.tolist()))
                with state_lock:
                    (lat_after if killed.is_set() else lat_before).append(lat)
                    if seen != expect:
                        wrong.append((tenant, seen))
                    done[0] += 1

            detect = [None]
            with ThreadPoolExecutor(max_workers=clients) as pool:
                futs = [pool.submit(run_query, i) for i in range(total_queries)]
                while done[0] < total_queries // 3:
                    time.sleep(0.01)
                t_kill = time.perf_counter()
                os.kill(procs[0].pid, signal.SIGKILL)
                killed.set()
                procs[0].wait(timeout=30)
                # the monitor loop: heartbeat-paced /healthz probing is what
                # notices a dead worker even with no client traffic on it.
                # Worst-case phase: the schedule just missed the kill, so the
                # first probe lands a full heartbeat later.
                next_probe = t_kill + hb_s
                deadline = t_kill + 30.0
                while time.perf_counter() < deadline:
                    if health.state_of(dead_wid) == "ejected":
                        detect[0] = time.perf_counter() - t_kill
                        break
                    if time.perf_counter() >= next_probe:
                        fd.probe(timeout=hb_s)
                        next_probe = time.perf_counter() + hb_s
                    time.sleep(0.02)
                for f in futs:
                    f.result(timeout=300)
            rerouted = retries_sum() - retries0
        finally:
            writer.fabric.stop()
            for p in procs:
                try:
                    p.stdin.close()
                except Exception:
                    pass
            for p in procs:
                try:
                    p.wait(timeout=30)
                except Exception:
                    p.kill()

        def p99(lats):
            return round(float(np.percentile(np.asarray(lats), 99)), 4) if lats else None

        out = {
            "metric": "fabric_failover_detection",
            "value": round(detect[0], 4) if detect[0] is not None else None,
            "unit": "seconds from SIGKILL to rendezvous-set ejection",
            "vs_baseline": round(detect[0] / (2 * hb_s), 4)
            if detect[0] is not None
            else None,
            "heartbeat_interval_s": hb_s,
            "workers": workers_n,
            "requests_total": total_queries,
            "requests_failed": len(failed),
            "requests_wrong": len(wrong),
            "requests_rerouted": int(rerouted),
            "steady_p99_s": p99(lat_before),
            "failover_p99_s": p99(lat_after),
            "rows_per_file": rows_per_file,
            "platform": jax.default_backend(),
            "cpus": os.cpu_count(),
        }
        line = json.dumps(out)
        with open("BENCH_failover.json", "w") as f:
            f.write(line + "\n")
        print(line)
        bars = []
        if failed:
            bars.append(f"{len(failed)} requests lost (bar: 0): {failed[:3]}")
        if wrong:
            bars.append(f"{len(wrong)} wrong answers (bar: 0): {wrong[:3]}")
        if detect[0] is None:
            bars.append("dead worker never ejected within 30s")
        elif detect[0] > 2 * hb_s:
            bars.append(
                f"detection {detect[0]:.2f}s > 2 heartbeat intervals ({2 * hb_s:.2f}s)"
            )
        if rerouted == 0:
            bars.append("no request was ever rerouted: the kill measured nothing")
        if bars:
            raise SystemExit("failover bench bars violated: " + "; ".join(bars))
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    if "--serve" in sys.argv[1:]:
        serve_main()
    elif "--slo-serve" in sys.argv[1:]:
        slo_serve_main()
    elif "--obs-overhead" in sys.argv[1:]:
        obs_main()
    elif "--scan-pipeline" in sys.argv[1:]:
        scan_pipeline_main()
    elif "--groupby" in sys.argv[1:]:
        groupby_main()
    elif "--topk" in sys.argv[1:]:
        topk_main()
    elif "--fusion" in sys.argv[1:]:
        fusion_main()
    elif "--mesh-child" in sys.argv[1:]:
        mesh_child_main()
    elif "--mesh" in sys.argv[1:]:
        mesh_main()
    elif "--check-overhead" in sys.argv[1:]:
        check_overhead_main()
    elif "--join" in sys.argv[1:]:
        join_main()
    elif "--refresh" in sys.argv[1:]:
        refresh_main()
    elif "--faults" in sys.argv[1:]:
        faults_main()
    elif "--fabric-child" in sys.argv[1:]:
        fabric_child_main()
    elif "--fabric" in sys.argv[1:]:
        fabric_main()
    elif "--failover" in sys.argv[1:]:
        failover_main()
    else:
        main()
