"""Mutable datasets: appends, deletes, hybrid scan, refresh, and optimize.

Mirrors the reference's "Mutable Datasets" user guide
(docs/_docs/03-ug-mutable-dataset.md in the reference repo): an index stays
usable while the underlying files change, first through query-time Hybrid
Scan, then durably through refreshIndex, with optimizeIndex compacting the
accumulated small files.

    python examples/mutable_data.py
"""

import os
import sys
import tempfile

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import hyperspace_tpu as hst


def batch(seed: int, n: int = 100_000) -> pa.Table:
    rng = np.random.default_rng(seed)
    return pa.table(
        {
            "order_id": rng.integers(0, 1_000_000, n).astype(np.int64),
            "status": np.array(["open", "shipped", "closed"])[rng.integers(0, 3, n)],
            "total": np.round(rng.uniform(5, 500, n), 2),
        }
    )


def main() -> None:
    root = tempfile.mkdtemp(prefix="hs_mutable_")
    data = os.path.join(root, "orders")
    os.makedirs(data)
    pq.write_table(batch(0), os.path.join(data, "part-0.parquet"))

    sess = hst.Session(
        conf={
            hst.keys.SYSTEM_PATH: os.path.join(root, "indexes"),
            hst.keys.NUM_BUCKETS: 32,
            # hybrid scan: use the index over changed data at query time
            hst.keys.HYBRID_SCAN_ENABLED: True,
            # lineage records each row's source file id so deletes can be
            # filtered out of index results
            hst.keys.LINEAGE_ENABLED: True,
        }
    )
    hst.set_session(sess)
    hs = hst.Hyperspace(sess)

    df = sess.read_parquet(data)
    hs.create_index(df, hst.CoveringIndexConfig("ordersByStatus", ["status"], ["total"]))
    sess.enable_hyperspace()

    q = lambda: sess.read_parquet(data).filter(hst.col("status") == "open").select("total")
    print("rows before append:", len(q().collect()["total"]))

    # --- append: hybrid scan unions the index with re-bucketed new files ---
    pq.write_table(batch(1, 20_000), os.path.join(data, "part-1.parquet"))
    plan = q().optimized_plan()
    assert "BucketUnion" in plan.pretty(), plan.pretty()
    print("rows after append (hybrid scan):", len(q().collect()["total"]))

    # --- delete: lineage filters the dropped file's rows out of the index --
    os.remove(os.path.join(data, "part-1.parquet"))
    print("rows after delete (lineage NOT-IN):", len(q().collect()["total"]))

    # --- make it durable: incremental refresh indexes only the delta -------
    pq.write_table(batch(2, 20_000), os.path.join(data, "part-2.parquet"))
    hs.refresh_index("ordersByStatus", "incremental")
    print("index stats after refresh:")
    stats = hs.index("ordersByStatus")
    print("  version dirs:", stats["indexContentPaths"][:1], "...")

    # --- compact the accumulated small per-bucket files --------------------
    hs.optimize_index("ordersByStatus", "full")
    print("files after optimize:", stats_count(hs))

    print("\nexplain:\n", hs.explain(q())[:800])


def stats_count(hs) -> int:
    return len(hs.index("ordersByStatus")["indexContentPaths"])


if __name__ == "__main__":
    main()
