"""Quick start: create a covering index and watch queries use it.

Mirrors the reference's examples/ walkthrough (Hyperspace quick-start docs):
generate a small dataset, index it, run filter/join/aggregate queries with
the optimizer on, and inspect explain/whyNot output.

    python examples/quickstart.py
"""

import os
import sys
import tempfile

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
    # some TPU images pin the platform at interpreter startup; enforce the
    # env request on the config object so the example runs without a chip
    import jax

    jax.config.update("jax_platforms", "cpu")

import hyperspace_tpu as hst


def main() -> None:
    root = tempfile.mkdtemp(prefix="hs_quickstart_")
    data = os.path.join(root, "employees")
    os.makedirs(data)
    rng = np.random.default_rng(0)
    n = 200_000
    pq.write_table(
        pa.table(
            {
                "emp_id": np.arange(n, dtype=np.int64),
                "dept_id": rng.integers(0, 50, n).astype(np.int64),
                "salary": np.round(rng.uniform(40_000, 200_000, n), 2),
            }
        ),
        os.path.join(data, "part-0.parquet"),
    )
    depts = os.path.join(root, "departments")
    os.makedirs(depts)
    pq.write_table(
        pa.table(
            {
                "dept_id": np.arange(50, dtype=np.int64),
                "dept_name": np.array([f"dept_{i}" for i in range(50)]),
            }
        ),
        os.path.join(depts, "part-0.parquet"),
    )

    sess = hst.Session(
        conf={
            hst.keys.SYSTEM_PATH: os.path.join(root, "indexes"),
            hst.keys.NUM_BUCKETS: 16,
            hst.keys.FILTER_RULE_USE_BUCKET_SPEC: True,
        }
    )
    hst.set_session(sess)
    hs = hst.Hyperspace(sess)

    emp = sess.read_parquet(data)
    dept = sess.read_parquet(depts)

    print("== create indexes ==")
    hs.create_index(emp, hst.CoveringIndexConfig("emp_dept", ["dept_id"], ["salary", "emp_id"]))
    hs.create_index(dept, hst.CoveringIndexConfig("dept_pk", ["dept_id"], ["dept_name"]))
    print(hs.indexes(), "\n")

    sess.enable_hyperspace()

    print("== filter query (bucket-pruned index scan) ==")
    q = emp.filter(hst.col("dept_id") == 7).select("emp_id", "salary")
    print(hs.explain(q), "\n")

    print("== shuffle-free indexed join + aggregation ==")
    top = (
        emp.join(dept, on=["dept_id"])
        .group_by("dept_name")
        .agg(headcount=("*", "count"), payroll=("salary", "sum"))
        .order_by("payroll", ascending=False)
        .limit(5)
    )
    for row in top.to_pandas().itertuples(index=False):
        print(f"  {row.dept_name:>10}  headcount={row.headcount:>5}  payroll={row.payroll:>14,.2f}")
    print()

    print("== whyNot: why an index was not used ==")
    q2 = emp.filter(hst.col("salary") > 150_000).select("emp_id")
    print(hs.why_not(q2))


if __name__ == "__main__":
    main()
