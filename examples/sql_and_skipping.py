"""SQL surface + data-skipping indexes over a Delta table.

Covers the reference's Spark SQL usage pattern and its data-skipping index
type: register temp views, query with SQL, and let MinMax/BloomFilter
sketches prune source files before any data is decoded.

    python examples/sql_and_skipping.py
"""

import os
import sys
import tempfile

import numpy as np
import pyarrow as pa

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import hyperspace_tpu as hst
from hyperspace_tpu.sources.delta import write_delta_table


def main() -> None:
    root = tempfile.mkdtemp(prefix="hs_sql_")
    delta = os.path.join(root, "events")
    rng = np.random.default_rng(0)

    # four delta commits: time-ordered event batches, so per-file MinMax
    # ranges on `ts_bucket` are disjoint and skipping prunes hard
    for day in range(4):
        n = 50_000
        write_delta_table(
            pa.table(
                {
                    "ts_bucket": np.full(n, day, dtype=np.int64),
                    "user": rng.integers(0, 10_000, n).astype(np.int64),
                    "value": rng.standard_normal(n),
                }
            ),
            delta,
        )

    sess = hst.Session(conf={hst.keys.SYSTEM_PATH: os.path.join(root, "indexes")})
    hst.set_session(sess)
    hs = hst.Hyperspace(sess)

    events = sess.read_delta(delta)
    events.create_or_replace_temp_view("events")

    hs.create_index(
        events,
        hst.DataSkippingIndexConfig(
            "eventsSkip",
            hst.MinMaxSketch("ts_bucket"),
            hst.BloomFilterSketch("user", expected_items=200_000),
        ),
    )
    sess.enable_hyperspace()

    # MinMax prunes 3 of 4 files; the bloom filter prunes user misses
    q = sess.sql("SELECT value FROM events WHERE ts_bucket = 2 AND user = 4242")
    print(q.optimized_plan().pretty())
    print("rows:", len(q.collect()["value"]))

    agg = sess.sql(
        "SELECT ts_bucket, COUNT(*) AS n, AVG(value) AS mean "
        "FROM events GROUP BY ts_bucket ORDER BY ts_bucket"
    ).collect()
    for b, n, m in zip(agg["ts_bucket"], agg["n"], agg["mean"]):
        print(f"  day {b}: n={n} mean={m:+.4f}")

    print("\nwhyNot for a query the index cannot help:")
    print(hs.why_not(sess.sql("SELECT value FROM events WHERE value > 0"))[:600])


if __name__ == "__main__":
    main()
