"""Maintenance actions: Delete, Restore, Vacuum, Cancel
(ref: HS/actions/DeleteAction.scala:24-48, RestoreAction.scala:24-48,
VacuumAction.scala:24-57, CancelAction.scala:35-67).
"""

from __future__ import annotations

from hyperspace_tpu.actions.base import Action, HyperspaceActionException
from hyperspace_tpu.models import states
from hyperspace_tpu.models.log_entry import IndexLogEntry
from hyperspace_tpu.telemetry.events import (
    CancelActionEvent,
    DeleteActionEvent,
    RestoreActionEvent,
    VacuumActionEvent,
)


class _StableTransitionAction(Action):
    """Shared: validate the latest stable state, carry the entry through."""

    expected_states = frozenset()

    def __init__(self, session, name: str, log_manager, data_manager=None):
        super().__init__(session, log_manager, data_manager)
        self._name = name
        self._entry: IndexLogEntry = None  # type: ignore[assignment]

    @property
    def index_name(self) -> str:
        return self._name

    def validate(self) -> None:
        entry = self.log_manager.get_latest_stable_log()
        if entry is None or entry.state == states.DOESNOTEXIST:
            raise HyperspaceActionException(f"Index {self._name!r} does not exist.")
        if entry.state not in self.expected_states:
            raise HyperspaceActionException(
                f"{type(self).__name__} is not supported in state {entry.state} "
                f"(expected one of {sorted(self.expected_states)})."
            )
        self._entry = entry

    def transient_log_entry(self) -> IndexLogEntry:
        entry = IndexLogEntry.from_dict(self._entry.to_dict())
        entry.state = self.transient_state
        return entry

    def op(self) -> None:
        pass

    def log_entry(self) -> IndexLogEntry:
        return IndexLogEntry.from_dict(self._entry.to_dict())


class DeleteAction(_StableTransitionAction):
    """Soft delete — log state only (ref: DeleteAction.scala:24-48)."""

    transient_state = states.DELETING
    final_state = states.DELETED
    event_class = DeleteActionEvent
    expected_states = frozenset({states.ACTIVE})


class RestoreAction(_StableTransitionAction):
    """Un-delete (ref: RestoreAction.scala:24-48)."""

    transient_state = states.RESTORING
    final_state = states.ACTIVE
    event_class = RestoreActionEvent
    expected_states = frozenset({states.DELETED})


class VacuumAction(_StableTransitionAction):
    """Hard delete of index data (ref: VacuumAction.scala:24-57)."""

    transient_state = states.VACUUMING
    final_state = states.DOESNOTEXIST
    event_class = VacuumActionEvent
    expected_states = frozenset({states.DELETED})

    def op(self) -> None:
        assert self.data_manager is not None
        for version in self.data_manager.get_all_versions():
            self.data_manager.delete_version(version)


class CancelAction(_StableTransitionAction):
    """Recover a stuck index from a transient state back to its last stable
    state (ref: CancelAction.scala:35-67)."""

    transient_state = states.CANCELLING
    event_class = CancelActionEvent
    # final_state is dynamic: the last stable state
    expected_states = frozenset({states.ACTIVE, states.DELETED})

    def validate(self) -> None:
        if self.log_manager.get_latest_id() is None:
            raise HyperspaceActionException(f"Index {self._name!r} does not exist.")
        latest = self.log_manager.get_latest_log()
        if latest is not None and latest.state in states.STABLE_STATES:
            raise HyperspaceActionException(
                f"Cancel is not supported in state {latest.state} — nothing in progress."
            )
        entry = self.log_manager.get_latest_stable_log()
        if entry is None:
            raise HyperspaceActionException(
                f"Index {self._name!r} has no stable state to recover to; vacuum it instead."
            )
        self._entry = entry
        self.final_state = entry.state
