"""Refresh actions: full, incremental, quick
(ref: HS/actions/RefreshActionBase.scala:37-129, RefreshAction.scala:33-64,
RefreshIncrementalAction.scala:45-133, RefreshQuickAction.scala:32-80).

All three share the same preamble: reconstruct the source relation from the
logged metadata, re-list its files, and diff against the files recorded at
index-build time (``FileInfo`` set difference; ref: RefreshActionBase:97-128).
They differ in what they do with the diff:

  - full         — rebuild the entire index from current data
  - incremental  — index only appended files; rows from deleted files are
                   dropped via the lineage column (index data rewritten)
  - quick        — metadata-only: record appended/deleted in the log entry so
                   query-time Hybrid Scan handles them
"""

from __future__ import annotations

from typing import List, Tuple

import pyarrow as pa
import pyarrow.dataset as pads

from hyperspace_tpu import config as C
from hyperspace_tpu.actions.base import Action, HyperspaceActionException, NoChangesException
from hyperspace_tpu.indexes import registry
from hyperspace_tpu.indexes.base import CreateContext
from hyperspace_tpu.models import states
from hyperspace_tpu.models.log_entry import (
    Content,
    FileIdTracker,
    FileInfo,
    IndexLogEntry,
    LogicalPlanFingerprint,
    Signature,
    Source,
)
from hyperspace_tpu.sources.signatures import INDEX_SIGNATURE_PROVIDER, index_signature
from hyperspace_tpu.telemetry.events import (
    RefreshActionEvent,
    RefreshIncrementalActionEvent,
    RefreshQuickActionEvent,
)


class _RefreshActionBase(Action):
    transient_state = states.REFRESHING
    final_state = states.ACTIVE

    def __init__(self, session, name: str, log_manager, data_manager):
        super().__init__(session, log_manager, data_manager)
        self._name = name
        self._entry: IndexLogEntry = None  # type: ignore[assignment]
        self._appended: List[FileInfo] = []
        self._deleted: List[FileInfo] = []
        self._tracker: FileIdTracker = FileIdTracker()
        self._fresh_relation = None  # FileBasedRelation over current source state

    @property
    def index_name(self) -> str:
        return self._name

    def validate(self) -> None:
        entry = self.log_manager.get_latest_stable_log()
        if entry is None or entry.state != states.ACTIVE:
            state = entry.state if entry else states.DOESNOTEXIST
            raise HyperspaceActionException(
                f"Refresh is only supported on an ACTIVE index; {self._name!r} is {state}."
            )
        self._entry = entry
        self._tracker = entry.file_id_tracker()

        # reconstruct the source relation from logged metadata and diff files
        # (ref: RefreshActionBase refresh() :54-76, diffs :97-128)
        metadata = self.session.provider_manager.create_relation_metadata(entry.relation)
        self._fresh_relation = metadata.to_relation_object()
        current = {fi.key: fi for fi in self._fresh_relation.all_file_infos()}
        indexed = {fi.key: fi for fi in self._entry.source_file_infos()}
        self._appended = [current[k] for k in current.keys() - indexed.keys()]
        self._deleted = [indexed[k] for k in indexed.keys() - current.keys()]
        if not self._appended and not self._deleted:
            raise NoChangesException("Refresh aborted as no source data change found.")

    # --- shared helpers ----------------------------------------------------
    def _revived_index(self):
        return registry.index_of_entry(self._entry)

    def _new_version_ctx(self) -> Tuple[CreateContext, int]:
        version = self._allocated_version = self.data_manager.allocate_version()
        ctx = CreateContext(
            session=self.session,
            index_data_path=self.data_manager.version_path(version),
            file_id_tracker=self._tracker,
        )
        return ctx, version

    def _final_entry(self, content: Content, derived_dataset) -> IndexLogEntry:
        relation_meta = self._fresh_relation.create_relation_metadata(self._tracker)
        from hyperspace_tpu.plan.logical import Scan

        sig = index_signature(Scan(self._fresh_relation)) or ""
        return IndexLogEntry(
            name=self._name,
            derived_dataset=derived_dataset,
            content=content,
            source=Source(relation_meta, LogicalPlanFingerprint([Signature(INDEX_SIGNATURE_PROVIDER, sig)])),
            properties=dict(self._entry.properties),
        )


class RefreshFullAction(_RefreshActionBase):
    records_source_version = True
    """Full rebuild (ref: RefreshAction.scala:33-64)."""

    event_class = RefreshActionEvent

    def __init__(self, *args):
        super().__init__(*args)
        self._new_index = None
        self._version = 0

    def op(self) -> None:
        from hyperspace_tpu.plan.dataframe import DataFrame
        from hyperspace_tpu.plan.logical import Scan

        ctx, self._version = self._new_version_ctx()
        df = DataFrame(Scan(self._fresh_relation), self.session)
        index = self._revived_index()
        index.write(ctx, df)
        self._new_index = index

    def log_entry(self) -> IndexLogEntry:
        content = Content.from_directory(self.data_manager.version_path(self._version), self._tracker)
        return self._final_entry(content, self._new_index.to_derived_dataset())


class RefreshIncrementalAction(_RefreshActionBase):
    records_source_version = True
    """Index only the appended files; drop rows of deleted files via lineage
    (ref: RefreshIncrementalAction.scala:45-133)."""

    event_class = RefreshIncrementalActionEvent

    def __init__(self, *args):
        super().__init__(*args)
        self._new_index = None
        self._version = 0
        self._overwrite = False

    def validate(self) -> None:
        super().validate()
        if self._deleted:
            # kind-polymorphic, matching the query-path candidate gate: a
            # covering index needs lineage to drop deleted files' rows; other
            # kinds (data-skipping) handle deletes by rebuilding over current
            # data in op()
            from hyperspace_tpu.indexes import registry

            if not registry.index_of_entry(self._entry).can_handle_deleted_files():
                raise HyperspaceActionException(
                    "Index refresh (incremental) is only supported for deleted files "
                    "when lineage is enabled; use refresh mode 'full' instead."
                )

    def op(self) -> None:
        import numpy as np
        import pyarrow.parquet as pq

        from hyperspace_tpu.indexes.covering import CoveringIndex, write_bucketed
        from hyperspace_tpu.plan.dataframe import DataFrame
        from hyperspace_tpu.plan.logical import Scan
        from hyperspace_tpu.sources.default import DefaultFileBasedRelation

        ctx, self._version = self._new_version_ctx()
        index = self._revived_index()
        if not isinstance(index, CoveringIndex):
            # other index kinds refresh by full rebuild over current data
            df = DataFrame(Scan(self._fresh_relation), self.session)
            index.write(ctx, df)
            self._new_index = index
            self._overwrite = True
            return

        appended_table = None
        if self._appended:
            appended_rel = DefaultFileBasedRelation(
                self._fresh_relation.root_paths,
                self._fresh_relation.physical_format,
                self._fresh_relation.options,
                files=[fi.name for fi in self._appended],
            )
            appended_df = DataFrame(Scan(appended_rel), self.session)
            appended_table = index._index_data_table(ctx, appended_df)

        if self._deleted:
            # read existing index data, drop rows originating from deleted
            # files (NOT-IN on the lineage column), combine with appended rows,
            # rewrite everything into the new version (Overwrite mode)
            # (ref: CoveringIndex.refreshIncremental :105-125)
            deleted_ids = {fi.file_id for fi in self._deleted if fi.file_id != C.UNKNOWN_FILE_ID}
            old = pads.dataset(self._entry.content.files, format="parquet").to_table()
            ids = old.column(C.DATA_FILE_NAME_ID).to_numpy()
            mask = ~np.isin(ids, np.array(sorted(deleted_ids), dtype=ids.dtype))
            kept = old.filter(pa.array(mask))
            combined = (
                pa.concat_tables([kept, appended_table], promote_options="default")
                if appended_table is not None
                else kept
            )
            write_bucketed(combined, index.indexed_columns, index.num_buckets, ctx.index_data_path, batch_rows=ctx.session.conf.build_batch_rows, session=ctx.session)
            # Overwrite mode re-buckets EVERY row with the current hash:
            # stamp the index consistent (covering.BUCKET_HASH_VERSION)
            from hyperspace_tpu.indexes.covering import (
                _BUCKET_HASH_VERSION_PROP,
                BUCKET_HASH_VERSION,
            )

            index._extra[_BUCKET_HASH_VERSION_PROP] = str(BUCKET_HASH_VERSION)
            self._overwrite = True
        else:
            # appended-only: write just the delta, merge content trees
            # (ref: RefreshIncrementalAction merge :115-128, UpdateMode.Merge)
            assert appended_table is not None
            write_bucketed(appended_table, index.indexed_columns, index.num_buckets, ctx.index_data_path, batch_rows=ctx.session.conf.build_batch_rows, session=ctx.session)
            self._overwrite = False
        self._new_index = index

    def log_entry(self) -> IndexLogEntry:
        new_content = Content.from_directory(self.data_manager.version_path(self._version), self._tracker)
        if not self._overwrite:
            new_content = self._entry.content.merge(new_content)
        return self._final_entry(new_content, self._new_index.to_derived_dataset())


class RefreshQuickAction(_RefreshActionBase):
    """Metadata-only refresh: record appended/deleted for query-time Hybrid
    Scan (ref: RefreshQuickAction.scala:32-80)."""

    event_class = RefreshQuickActionEvent

    def op(self) -> None:
        self._tracker.add_files(self._appended)

    def log_entry(self) -> IndexLogEntry:
        entry = self._entry.copy_with_update(self._appended, self._deleted)
        return entry
