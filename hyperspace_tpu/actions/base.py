"""Action FSM.

Every lifecycle operation is an Action sharing one protocol
(ref: HS/actions/Action.scala:34-108):

    run() = validate() -> begin()  [write transient-state entry at base_id+1]
            -> op()                [the actual work]
            -> end()               [write final-state entry at base_id+2,
                                    recreate latestStable]

with telemetry events at start/success/failure. Optimistic concurrency: the
transient-entry write fails if another writer took the id first
(ref: Action.scala:49-55; IndexLogManager.scala:178-194). A failure mid-op
abandons the transient state; CancelAction recovers to the last stable state
(ref: HS/actions/CancelAction.scala:35-67).
"""

from __future__ import annotations

import time
from typing import Optional

from hyperspace_tpu.models import states
from hyperspace_tpu.models.data_manager import IndexDataManager
from hyperspace_tpu.models.log_entry import IndexLogEntry
from hyperspace_tpu.models.log_manager import IndexLogManager
from hyperspace_tpu.telemetry.events import ActionEvent, emit_event


class HyperspaceActionException(Exception):
    pass


class ConcurrentModificationException(HyperspaceActionException):
    pass


class NoChangesException(HyperspaceActionException):
    """Signals a no-op refresh/optimize (ref: HS/actions/NoChangesException.scala)."""


class Action:
    transient_state: str = ""
    final_state: str = ""
    event_class = ActionEvent

    def __init__(self, session, log_manager: IndexLogManager, data_manager: Optional[IndexDataManager] = None):
        self.session = session
        self.log_manager = log_manager
        self.data_manager = data_manager
        self.base_id: int = -1

    # --- to be provided by concrete actions --------------------------------
    @property
    def index_name(self) -> str:
        raise NotImplementedError

    def validate(self) -> None:
        pass

    def op(self) -> None:
        raise NotImplementedError

    def log_entry(self) -> IndexLogEntry:
        """The final-state entry to persist at base_id + 2."""
        raise NotImplementedError

    def transient_log_entry(self) -> IndexLogEntry:
        """The transient entry; default = latest entry with transient state
        (ref: Action.scala begin)."""
        latest = self.log_manager.get_latest_log()
        if latest is None:
            raise HyperspaceActionException(f"Index {self.index_name!r} has no log to transition")
        latest.state = self.transient_state
        return latest

    # Actions whose final entry snapshots a fresh view of the source (create,
    # full/incremental refresh) record a source-version -> log-id history
    # entry; every other action only carries the history forward — recording
    # there would map new log ids onto stale logged versions (e.g. quick
    # refresh copies an entry whose versionAsOf predates the data it covers
    # via hybrid scan).
    records_source_version: bool = False

    def _enrich_final(self, final: IndexLogEntry, final_id: int) -> None:
        """Source-provider property enrichment at commit time (ref:
        CreateActionBase enriched props + DeltaLakeRelationMetadata's
        deltaVersions history)."""
        source = getattr(final, "source", None)
        if source is None or source.relation is None:
            return
        from hyperspace_tpu.sources.manager import HyperspaceException

        try:
            meta = self.session.provider_manager.create_relation_metadata(source.relation)
        except HyperspaceException:
            # no provider answers for this logged relation (e.g. builders
            # reconfigured since the index was created) — nothing to enrich
            return
        if meta is None:
            return
        prev = self.log_manager.get_log(self.base_id) if self.base_id >= 0 else None
        final.properties = meta.enrich_index_properties(
            dict(final.properties),
            log_id=final_id if self.records_source_version else None,
            previous_properties=(prev.properties if prev is not None else None),
        )

    def _cleanup_allocated_version(self) -> None:
        """Best-effort removal of a data version dir claimed by a failed
        action — it was never referenced by a committed log entry, and
        leaving it would permanently bump the version sequence per failure."""
        v = getattr(self, "_allocated_version", None)
        if v is None or self.data_manager is None:
            return
        try:
            self.data_manager.delete_version(v)
        except OSError:
            pass

    # --- protocol ----------------------------------------------------------
    def _emit(self, state: str, message: str = "") -> None:
        emit_event(
            self.session,
            self.event_class(index_name=self.index_name, state=state, message=message),
        )

    def run(self) -> IndexLogEntry:
        self.validate()
        self._emit("Started")
        latest = self.log_manager.get_latest_id()
        self.base_id = latest if latest is not None else -1
        try:
            entry = self.transient_log_entry()
            entry.timestamp = int(time.time() * 1000)
            if not self.log_manager.write_log(self.base_id + 1, entry):
                raise ConcurrentModificationException(
                    f"Another operation is in progress on index {self.index_name!r} "
                    f"(log id {self.base_id + 1} already exists)."
                )
            self.op()
            final = self.log_entry()
            final.state = self.final_state
            final.timestamp = int(time.time() * 1000)
            self._enrich_final(final, self.base_id + 2)
            if not self.log_manager.write_log(self.base_id + 2, final):
                raise ConcurrentModificationException(
                    f"Failed to commit final state for index {self.index_name!r}."
                )
            # the final entry is committed: the allocated data version is now
            # referenced, so a failure past this point (e.g. latestStable
            # write) must NOT delete it — readers fall back to scanning the
            # log and would find the ACTIVE entry pointing at deleted files
            self._allocated_version = None
            self.log_manager.create_latest_stable_log(self.base_id + 2)
        except NoChangesException:
            raise
        except Exception as e:
            self._cleanup_allocated_version()
            self._emit("Failure", str(e))
            raise
        self._emit("Success")
        return final
