"""OptimizeAction: compact small index files per bucket
(ref: HS/actions/OptimizeAction.scala:57-148).

quick mode — only files below ``hyperspace.index.optimize.fileSizeThreshold``;
full mode — all files. Buckets with more than one eligible file get their
files merged (rows re-sorted) into a single file in a new data version; files
left out ("ignored") stay referenced by the merged content tree
(ref: OptimizeAction.scala:96-143).
"""

from __future__ import annotations

import os
from collections import defaultdict
from typing import Dict, List

import pyarrow as pa
import pyarrow.dataset as pads

from hyperspace_tpu import config as C
from hyperspace_tpu.actions.base import Action, HyperspaceActionException, NoChangesException
from hyperspace_tpu.indexes import registry
from hyperspace_tpu.indexes.covering import CoveringIndex, bucket_of_file, write_bucketed
from hyperspace_tpu.models import states
from hyperspace_tpu.models.log_entry import Content, FileIdTracker, FileInfo, IndexLogEntry
from hyperspace_tpu.telemetry.events import OptimizeActionEvent


class OptimizeAction(Action):
    transient_state = states.OPTIMIZING
    final_state = states.ACTIVE
    event_class = OptimizeActionEvent

    def __init__(self, session, name: str, log_manager, data_manager, mode: str):
        super().__init__(session, log_manager, data_manager)
        self._name = name
        self._mode = mode
        self._entry: IndexLogEntry = None  # type: ignore[assignment]
        self._to_optimize: Dict[int, List[FileInfo]] = {}
        self._ignored: List[FileInfo] = []
        self._version = 0
        self._tracker = FileIdTracker()

    @property
    def index_name(self) -> str:
        return self._name

    def validate(self) -> None:
        entry = self.log_manager.get_latest_stable_log()
        if entry is None or entry.state != states.ACTIVE:
            state = entry.state if entry else states.DOESNOTEXIST
            raise HyperspaceActionException(
                f"Optimize is only supported on an ACTIVE index; {self._name!r} is {state}."
            )
        if entry.kind != CoveringIndex.kind:
            raise HyperspaceActionException(f"Optimize is not supported for {entry.kind} indexes.")
        self._entry = entry
        self._tracker = entry.file_id_tracker()

        threshold = self.session.conf.optimize_file_size_threshold
        per_bucket: Dict[int, List[FileInfo]] = defaultdict(list)
        ignored: List[FileInfo] = []
        for fi in entry.content.file_infos():
            bucket = bucket_of_file(fi.name)
            eligible = self._mode == C.OPTIMIZE_MODE_FULL or fi.size < threshold
            if bucket is None or not eligible:
                ignored.append(fi)
            else:
                per_bucket[bucket].append(fi)
        # only buckets with >1 file benefit from compaction (ref: :96-114)
        self._to_optimize = {b: fs for b, fs in per_bucket.items() if len(fs) > 1}
        for b, fs in per_bucket.items():
            if len(fs) <= 1:
                ignored.extend(fs)
        self._ignored = ignored
        if not self._to_optimize:
            raise NoChangesException(
                "Optimize aborted as no optimizable index files "
                f"(multiple files per bucket, mode={self._mode}) found."
            )

    def op(self) -> None:
        import pyarrow.parquet as pq

        index = registry.index_of_entry(self._entry)
        assert isinstance(index, CoveringIndex)
        self._version = self._allocated_version = self.data_manager.allocate_version()
        out_dir = self.data_manager.version_path(self._version)

        # Compaction must leave ONE file per optimized bucket, so chunking by
        # row ranges (which splits buckets into multiple runs and would make
        # repeated optimize calls non-convergent) is not an option here.
        # Device memory is bounded instead by processing whole-bucket GROUPS
        # whose total rows fit the batch budget; a single oversized bucket
        # becomes its own group.
        budget = self.session.conf.build_batch_rows

        def bucket_rows(fis) -> int:
            total = 0
            for fi in fis:
                try:
                    total += pq.read_metadata(fi.name).num_rows
                except OSError:
                    return 1 << 62  # unknown -> force its own group
            return total

        groups: List[List[int]] = []
        cur: List[int] = []
        cur_rows = 0
        for b in sorted(self._to_optimize):
            rows = bucket_rows(self._to_optimize[b])
            if cur and budget > 0 and cur_rows + rows > budget:
                groups.append(cur)
                cur, cur_rows = [], 0
            cur.append(b)
            cur_rows += rows
        if cur:
            groups.append(cur)

        for group in groups:
            files = [fi.name for b in group for fi in self._to_optimize[b]]
            table = pads.dataset(files, format="parquet").to_table()
            # one write_bucketed pass per group re-buckets + re-sorts
            write_bucketed(table, index.indexed_columns, index.num_buckets, out_dir, session=self.session)

    def log_entry(self) -> IndexLogEntry:
        new_content = Content.from_directory(self.data_manager.version_path(self._version), self._tracker)
        if self._ignored:
            new_content = new_content.merge(Content.from_leaf_files(self._ignored))
        entry = IndexLogEntry.from_dict(self._entry.to_dict())
        entry.content = new_content
        return entry
