"""CreateAction: build a new index (CREATING -> ACTIVE)
(ref: HS/actions/CreateAction.scala:29-100, CreateActionBase.scala:30-103).
"""

from __future__ import annotations

import os
from typing import Dict, Optional

from hyperspace_tpu import config as C
from hyperspace_tpu.actions.base import Action, HyperspaceActionException
from hyperspace_tpu.indexes.base import CreateContext, IndexConfig
from hyperspace_tpu.models import states
from hyperspace_tpu.models.log_entry import (
    Content,
    FileIdTracker,
    IndexLogEntry,
    LogicalPlanFingerprint,
    Signature,
    Source,
)
from hyperspace_tpu.plan.logical import Scan
from hyperspace_tpu.plan.resolver import resolve_columns_against_schema
from hyperspace_tpu.sources.signatures import INDEX_SIGNATURE_PROVIDER, index_signature
from hyperspace_tpu.telemetry.events import CreateActionEvent
from hyperspace_tpu.version import INDEX_LOG_VERSION, __version__


class CreateAction(Action):
    records_source_version = True
    transient_state = states.CREATING
    final_state = states.ACTIVE
    event_class = CreateActionEvent

    def __init__(self, session, df, index_config: IndexConfig, log_manager, data_manager, index_path: str):
        super().__init__(session, log_manager, data_manager)
        self.df = df
        self.index_config = index_config
        self.index_path = index_path
        self._index = None
        self._tracker = FileIdTracker()
        self._data_version = 0

    @property
    def index_name(self) -> str:
        return self.index_config.index_name

    def validate(self) -> None:
        """(ref: CreateAction.scala:50-81 — supported relation, resolvable
        columns, no name collision)."""
        if not isinstance(self.df.plan, Scan):
            raise HyperspaceActionException(
                "Only creating index over a supported source scan is allowed; "
                "apply filters/projections at query time instead."
            )
        # columns resolve?
        resolve_columns_against_schema(self.index_config.referenced_columns, self.df.plan.relation.schema)
        # Stable-state check only: a crashed creator's abandoned CREATING
        # transient must not brick the name (the retry's own transient write
        # races on the next log id, and allocate_version() gives every
        # builder an exclusive data dir, so concurrent creators can neither
        # share a version dir nor double-commit) (ref: CreateAction.scala:50-81).
        existing = self.log_manager.get_latest_stable_log()
        if existing is not None and existing.state != states.DOESNOTEXIST:
            raise HyperspaceActionException(
                f"Another index with name {self.index_name!r} already exists (state {existing.state})."
            )

    def transient_log_entry(self) -> IndexLogEntry:
        return self._build_entry(Content.from_leaf_files([]), self.index_config_stub())

    def index_config_stub(self):
        """A pre-build DerivedDataset payload (filled in by op())."""
        from hyperspace_tpu.models.log_entry import DerivedDataset

        return DerivedDataset(
            "CoveringIndex" if "Covering" in type(self.index_config).__name__ else type(self.index_config).__name__,
            {"indexedColumns": self.index_config.referenced_columns},
        )

    def _enriched_properties(self) -> Dict[str, str]:
        """(ref: CreateActionBase enriched props; IndexConstants:118-127)."""
        relation = self.df.plan.relation
        return {
            C.HYPERSPACE_VERSION_PROPERTY: __version__,
            C.INDEX_LOG_VERSION_PROPERTY: INDEX_LOG_VERSION,
            C.HAS_PARQUET_AS_SOURCE_FORMAT_PROPERTY: str(relation.has_parquet_as_source_format()).lower(),
        }

    def op(self) -> None:
        self._data_version = self._allocated_version = self.data_manager.allocate_version()
        data_path = self.data_manager.version_path(self._data_version)
        ctx = CreateContext(
            session=self.session,
            index_data_path=data_path,
            file_id_tracker=self._tracker,
            properties=self._enriched_properties(),
        )
        self._index = self.index_config.create_index(ctx, self.df, self._enriched_properties())

    def _build_entry(self, content: Content, derived_dataset) -> IndexLogEntry:
        relation_meta = self.df.plan.relation.create_relation_metadata(self._tracker)
        sig_value = index_signature(self.df.plan)
        entry = IndexLogEntry(
            name=self.index_name,
            derived_dataset=derived_dataset,
            content=content,
            source=Source(
                relation_meta,
                LogicalPlanFingerprint([Signature(INDEX_SIGNATURE_PROVIDER, sig_value or "")]),
            ),
            properties={},
        )
        return entry

    def log_entry(self) -> IndexLogEntry:
        assert self._index is not None
        data_path = self.data_manager.version_path(self._data_version)
        content = Content.from_directory(data_path, self._tracker)
        return self._build_entry(content, self._index.to_derived_dataset())
