"""Session: configuration + source providers + the optimizer kill-switch.

Plays the role of SparkSession in the reference: carries conf, hosts the
provider manager and the (caching) index collection manager, and owns the
"Hyperspace enabled" flag that installs the optimizer rule
(ref: ``spark.enableHyperspace()``, HS/package.scala:29-69).

Also owns the device mesh used by the TPU execution layer: bucket id ≡ device
shard (SURVEY.md §5.8).
"""

from __future__ import annotations

import contextlib
import contextvars
from typing import Any, Dict, List, Optional

from hyperspace_tpu.config import HyperspaceConf
from hyperspace_tpu.sources.manager import FileBasedSourceProviderManager


class Session:
    def __init__(self, conf: Optional[Dict[str, Any]] = None):
        # multi-process runtimes come up before any device is touched —
        # ONLY when the env explicitly configures one (both HS_NUM_PROCESSES
        # and HS_PROCESS_ID), so Session() stays side-effect-free otherwise
        # (SURVEY §5.8)
        from hyperspace_tpu.parallel.distributed import configured_from_env, initialize_from_env
        from hyperspace_tpu.utils.x64 import ensure_x64

        if configured_from_env():
            initialize_from_env()
        # the device layer needs int64 keys / float64 sketch bounds; enabling
        # x64 here (not at import) keeps `import hyperspace_tpu` free of
        # global JAX side effects — documented in docs/configuration.md
        ensure_x64()
        self.conf = HyperspaceConf(conf)
        # apply the configured decode-pool width (the pool is process-global;
        # the most recently constructed session's conf wins, env overrides)
        from hyperspace_tpu.exec import io as _io

        _io.set_decode_threads(self.conf.io_decode_threads)
        _io.set_native_options(
            enabled=self.conf.io_native_enabled,
            rowgroup=self.conf.io_native_rowgroup,
            max_dict_entries=self.conf.io_native_max_dict_entries,
        )
        # check-layer runtime switches are process-global for the same
        # reason (compile sites without a session in scope consult them).
        # HLO verification: most recent session's conf wins, like decode
        # threads. Lock watching is enable-only: locks wrap at construction,
        # so a later Session with the flag off can't unwrap them anyway.
        from hyperspace_tpu.check import hlo_lint as _hlo_lint
        from hyperspace_tpu.check import locks as _locks

        _hlo_lint.set_default_enabled(self.conf.check_hlo_enabled)
        if self.conf.check_locks_enabled:
            _locks.watcher.enable()
        # reliability registries (fault injection, retry policy, quarantine
        # breakers) are process-global like the decode pool; all default-off
        from hyperspace_tpu import reliability as _reliability

        _reliability.configure(self)
        self.provider_manager = FileBasedSourceProviderManager(self)
        # context-local override beats the session-wide default, so a scoped
        # toggle (with_hyperspace_disabled, a serving worker pinning the flag
        # captured at submit) never leaks into queries racing on other threads
        self._hyperspace_override: contextvars.ContextVar = contextvars.ContextVar(
            "hyperspace_enabled_override", default=None
        )
        self.hyperspace_enabled = False
        self._index_manager = None
        self._lifecycle_bus = None
        self._mesh = None
        self._temp_views: Dict[str, Any] = {}
        # most recent QueryProfile from a traced collect() (obs tracing on)
        self._last_profile = None
        # lazily-built fingerprint-keyed ProfileHistory for ad-hoc queries
        # (QueryServer instances own their own, registry-labeled per server)
        self._profile_history = None
        # scale-out fabric runtime (commit watcher + coherence sidecar) —
        # None at defaults; wired last so its bus subscription and watcher
        # see a fully-constructed session
        from hyperspace_tpu import fabric as _fabric

        self._fabric = _fabric.configure(self)

    # --- reading data ------------------------------------------------------
    def read(self, paths, file_format: str, **options) -> "DataFrame":  # noqa: F821
        from hyperspace_tpu.plan.dataframe import DataFrame
        from hyperspace_tpu.plan.logical import Scan

        if isinstance(paths, str):
            paths = [paths]
        relation = self.provider_manager.create_relation((list(paths), file_format, options))
        return DataFrame(Scan(relation), self)

    def read_parquet(self, *paths, **options) -> "DataFrame":  # noqa: F821
        return self.read(list(paths), "parquet", **options)

    def read_csv(self, *paths, **options) -> "DataFrame":  # noqa: F821
        return self.read(list(paths), "csv", **options)

    def read_json(self, *paths, **options) -> "DataFrame":  # noqa: F821
        return self.read(list(paths), "json", **options)

    def read_orc(self, *paths, **options) -> "DataFrame":  # noqa: F821
        return self.read(list(paths), "orc", **options)

    def read_avro(self, *paths, **options) -> "DataFrame":  # noqa: F821
        return self.read(list(paths), "avro", **options)

    def read_text(self, *paths, **options) -> "DataFrame":  # noqa: F821
        return self.read(list(paths), "text", **options)

    def read_delta(self, path, version: Optional[int] = None) -> "DataFrame":  # noqa: F821
        from hyperspace_tpu.plan.dataframe import DataFrame
        from hyperspace_tpu.plan.logical import Scan
        from hyperspace_tpu.sources.delta import DeltaLakeRelation

        return DataFrame(Scan(DeltaLakeRelation(path, version=version)), self)

    def read_iceberg(self, path, snapshot_id: Optional[int] = None) -> "DataFrame":  # noqa: F821
        from hyperspace_tpu.plan.dataframe import DataFrame
        from hyperspace_tpu.plan.logical import Scan
        from hyperspace_tpu.sources.iceberg import IcebergRelation

        return DataFrame(Scan(IcebergRelation(path, snapshot_id=snapshot_id)), self)

    # --- SQL (the reference's users drive Hyperspace through Spark SQL) ----
    def sql(self, query: str) -> "DataFrame":  # noqa: F821
        from hyperspace_tpu.plan.sql import run_sql

        return run_sql(query, self)

    def register_view(self, name: str, df: "DataFrame") -> None:  # noqa: F821
        self._temp_views[name] = df

    def drop_view(self, name: str) -> None:
        self._temp_views.pop(name, None)

    # --- hyperspace toggle (ref: HS/package.scala:36-43) -------------------
    @property
    def hyperspace_enabled(self) -> bool:
        override = self._hyperspace_override.get()
        return self._hyperspace_default if override is None else override

    @hyperspace_enabled.setter
    def hyperspace_enabled(self, value: bool) -> None:
        self._hyperspace_default = bool(value)

    def enable_hyperspace(self) -> "Session":
        self.hyperspace_enabled = True
        return self

    def disable_hyperspace(self) -> "Session":
        self.hyperspace_enabled = False
        return self

    def is_hyperspace_enabled(self) -> bool:
        return self.hyperspace_enabled

    # reference-API aliases (ref: HS/package.scala:36-43 spark.enableHyperspace());
    # delegating defs so subclass overrides stay authoritative
    def enableHyperspace(self) -> "Session":
        return self.enable_hyperspace()

    def disableHyperspace(self) -> "Session":
        return self.disable_hyperspace()

    def isHyperspaceEnabled(self) -> bool:
        return self.is_hyperspace_enabled()

    @contextlib.contextmanager
    def hyperspace_scope(self, enabled: bool):
        """Pin the hyperspace flag for this thread/context only. Other threads
        (and requests queued behind this one) keep the session default —
        unlike mutating the flag, which raced under concurrent queries."""
        token = self._hyperspace_override.set(bool(enabled))
        try:
            yield self
        finally:
            self._hyperspace_override.reset(token)

    def with_hyperspace_disabled(self):
        return self.hyperspace_scope(False)

    # --- index manager ------------------------------------------------------
    @property
    def index_manager(self):
        if self._index_manager is None:
            from hyperspace_tpu.manager import CachingIndexCollectionManager

            self._index_manager = CachingIndexCollectionManager(self)
        return self._index_manager

    # --- lifecycle commit bus ----------------------------------------------
    @property
    def lifecycle_bus(self):
        """The session's commit/invalidation bus (lazy, one per session).
        Every index mutation publishes here; snapshot pins read its commit
        sequence. See hyperspace_tpu/lifecycle/invalidation.py."""
        if self._lifecycle_bus is None:
            from hyperspace_tpu.lifecycle.invalidation import InvalidationBus

            self._lifecycle_bus = InvalidationBus(self)
        return self._lifecycle_bus

    # --- scale-out fabric ---------------------------------------------------
    @property
    def fabric(self):
        """This session's :class:`~hyperspace_tpu.fabric.FabricRuntime`
        (commit watcher + coherence sidecar), or None while
        ``hyperspace.fabric.enabled`` is off. See docs/scale-out.md."""
        return self._fabric

    # --- query profiles (obs) ----------------------------------------------
    def last_query_profile(self):
        """The ``QueryProfile`` of the most recent traced ``collect()`` on
        this session, or None. Requires ``hyperspace.obs.tracing.enabled``;
        see docs/observability.md."""
        return self._last_profile

    @property
    def profile_history(self):
        """The session's fingerprint-keyed :class:`ProfileHistory` (traced
        ad-hoc ``collect()`` calls fold into it), or None when
        ``hyperspace.obs.history.enabled`` is false."""
        if self._profile_history is None and self.conf.obs_history_enabled:
            from hyperspace_tpu.obs.history import ProfileHistory

            self._profile_history = ProfileHistory(
                max_fingerprints=self.conf.obs_history_max_fingerprints
            )
        return self._profile_history

    def estimate_cost(self, query):
        """Learned latency estimate for a SQL string or DataFrame from this
        session's profile history (see ``ProfileHistory.estimate_cost``);
        None when the history is disabled or the fingerprint is unseen."""
        history = self.profile_history
        if history is None:
            return None
        from hyperspace_tpu.serving.fingerprint import plan_fingerprint

        df = self.sql(query) if isinstance(query, str) else query
        fp = plan_fingerprint(getattr(df, "plan", df))
        return history.estimate_cost(fp.structure)

    def data_version_brand(self, query):
        """The data-version brand a served result of ``query`` (SQL string or
        DataFrame) would be cached under: a digest of the session's ACTIVE
        index roster + rewrite conf + every scan leaf's source snapshot
        signature. None when any source cannot be signed. Two calls returning
        the same brand are guaranteed to observe the same data version — the
        invariant the serving result cache is keyed on (docs/serving.md)."""
        from hyperspace_tpu.serving.result_cache import version_brand

        df = self.sql(query) if isinstance(query, str) else query
        return version_brand(self, getattr(df, "plan", df), bool(self.hyperspace_enabled))

    # --- profiling ----------------------------------------------------------
    # The reference delegates runtime profiling to the Spark UI (SURVEY.md
    # §5.1); here the XLA profiler is the equivalent surface: traces cover the
    # build/query device programs and host stages, viewable in TensorBoard or
    # Perfetto.
    def start_profile(self, log_dir: str) -> None:
        import jax

        jax.profiler.start_trace(log_dir)

    def stop_profile(self) -> None:
        import jax

        jax.profiler.stop_trace()

    @contextlib.contextmanager
    def profile(self, log_dir: str):
        self.start_profile(log_dir)
        try:
            yield
        finally:
            self.stop_profile()

    # --- device mesh --------------------------------------------------------
    @property
    def mesh(self):
        """Lazily created 1-D device mesh over all local devices; the axis name
        comes from conf ``hyperspace.tpu.mesh.axis``."""
        if self._mesh is None:
            import jax
            from jax.sharding import Mesh
            import numpy as np

            devices = np.array(jax.devices())
            self._mesh = Mesh(devices, (self.conf.mesh_axis,))
            self._note_mesh(self._mesh)
        return self._mesh

    def set_mesh(self, mesh) -> "Session":
        self._mesh = mesh
        self._note_mesh(mesh)
        return self

    @staticmethod
    def _note_mesh(mesh) -> None:
        # tell the decode fast path the device-count multiple staged arrays
        # pad to, so its buffers come out device-put-ready (exec/io.py); a
        # stale value only costs the zero-copy handoff, never correctness
        from hyperspace_tpu.exec import io as _io

        _io.set_staging_pad(int(mesh.devices.size))


_current: Optional[Session] = None


def get_session() -> Session:
    global _current
    if _current is None:
        _current = Session()
    return _current


def set_session(session: Optional[Session]) -> None:
    global _current
    _current = session
