"""``process-local-state``: serving/reliability registries must declare scope.

In a fabric deployment (docs/scale-out.md) N server processes share one
lake; anything accumulated in a module-level mutable object — a breaker
map, a counter registry, a memo dict — is silently per-process unless the
coherence sidecar publishes it. This rule makes that choice explicit:
every module-level mutable registry in ``serving/`` and ``reliability/``
must either

- be **fabric-published**: listed by name in the module's
  ``__fabric_published__`` tuple (e.g. ``reliability/degrade.py``'s
  ``QUARANTINE``, whose strikes the sidecar shares), or
- be **annotated as intentionally process-local** with
  ``# hscheck: disable=process-local-state`` on the assignment line (e.g.
  the per-process ``qsN`` server-name counter).

Flagged value shapes: dict/list/set literals and comprehensions, the
standard mutable-container factories (``dict()``, ``defaultdict()``,
``deque()``, ``itertools.count()``, ...), and constructor calls whose
class name ends in a registry-ish suffix (``*Registry``, ``*Cache``,
``*Tracker``, ``*History``, ``*Recorder``, ``*Bus``). Dunder assignments
(``__all__``) are exempt.

Scope: ``hyperspace_tpu/serving/`` and ``hyperspace_tpu/reliability/``
(the layers whose state the fabric must reason about); explicit fixture
paths are checked wherever they live.
"""

from __future__ import annotations

import ast
import os
from typing import List, Optional, Set

from hyperspace_tpu.check.findings import Finding
from hyperspace_tpu.check.rules import Rule

NAME = "process-local-state"

#: directories whose module state the fabric must account for
_SCOPE_DIRS = ("serving", "reliability")

#: callables that build a mutable container
_MUTABLE_FACTORIES = {
    "dict", "list", "set", "defaultdict", "deque", "OrderedDict", "Counter",
    "count",
}

#: class-name suffixes that read as "stateful registry"
_REGISTRY_SUFFIXES = (
    "Registry", "Cache", "Tracker", "History", "Recorder", "Bus",
)


def _in_scope(rel: str) -> bool:
    parts = rel.replace(os.sep, "/").split("/")
    return (
        len(parts) >= 2
        and parts[0] == "hyperspace_tpu"
        and parts[1] in _SCOPE_DIRS
    )


def _fabric_published(tree: ast.Module) -> Set[str]:
    """Names listed in the module's ``__fabric_published__`` tuple/list."""
    out: Set[str] = set()
    for node in tree.body:
        if not isinstance(node, ast.Assign):
            continue
        if not any(
            isinstance(t, ast.Name) and t.id == "__fabric_published__"
            for t in node.targets
        ):
            continue
        if isinstance(node.value, (ast.Tuple, ast.List)):
            for elt in node.value.elts:
                if isinstance(elt, ast.Constant) and isinstance(elt.value, str):
                    out.add(elt.value)
    return out


def _callable_name(func: ast.expr) -> Optional[str]:
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return None


def flag_reason(value: ast.expr) -> Optional[str]:
    """Why this assigned value is module-level mutable state, or None."""
    if isinstance(value, (ast.Dict, ast.DictComp)):
        return "dict"
    if isinstance(value, (ast.List, ast.ListComp)):
        return "list"
    if isinstance(value, (ast.Set, ast.SetComp)):
        return "set"
    if isinstance(value, ast.Call):
        name = _callable_name(value.func)
        if name in _MUTABLE_FACTORIES:
            return f"{name}()"
        if name and name.endswith(_REGISTRY_SUFFIXES):
            return f"{name}()"
    return None


def scan_module(tree: ast.Module) -> List[tuple]:
    """(name, reason, lineno) for every unexempted module-level mutable
    assignment (direct module body only — class/function bodies are
    instance or local state, not process-global)."""
    published = _fabric_published(tree)
    out: List[tuple] = []
    for node in tree.body:
        if isinstance(node, ast.Assign):
            targets, value = node.targets, node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets, value = [node.target], node.value
        else:
            continue
        names = [t.id for t in targets if isinstance(t, ast.Name)]
        if not names:
            continue
        if all(n.startswith("__") and n.endswith("__") for n in names):
            continue  # __all__ and friends
        if all(n in published for n in names):
            continue
        reason = flag_reason(value)
        if reason is not None:
            out.append((names[0], reason, node.lineno))
    return out


def check(ctx) -> List[Finding]:
    findings: List[Finding] = []
    for path in ctx.files:
        rel = ctx.relpath(path)
        if ctx.full_scope and not _in_scope(rel):
            continue
        for name, reason, lineno in scan_module(ctx.ast_of(path)):
            findings.append(
                Finding(
                    rule=NAME,
                    path=rel,
                    line=lineno,
                    message=(
                        f"module-level mutable state {name!r} ({reason}) is "
                        "invisible to fabric peer processes; publish it via "
                        "the coherence sidecar and list it in "
                        "__fabric_published__, or mark it intentionally "
                        "process-local with '# hscheck: "
                        "disable=process-local-state'"
                    ),
                )
            )
    return findings


RULE = Rule(name=NAME, doc=__doc__.strip(), check=check)
