"""``native-fallback``: every pyarrow fallback off the native decoder is
accounted.

The native C decoder (``hyperspace_tpu/native``) is a *fast path*: any
``except`` wrapped around its read entry points — ``native.read_columns``,
the per-row-group handle methods ``read_fixed_rg_into`` / ``read_codes_rg``
/ ``read_dict_rg`` / ``read_binary_rg`` — is by construction a fallback
decision, and an unaccounted fallback is how "native decode silently never
runs" hides: the suite stays green (pyarrow answers byte-identically) while
every scan quietly pays the slow path. Such a handler must do one of:

- re-raise (the typed reliability error or the original), or
- route through the reliability taxonomy (``classify`` /
  ``count_io_error`` / ``note_corrupt``), which attributes the failure even
  when a fallback answers, or
- count the reroute in ``hs_native_fallback_total`` — either through the
  ``_native_fallback_counter(reason)`` helper (exec/io.py) or a literal
  registration of that family, or
- carry an explicit ``# hscheck: disable=native-fallback`` pragma on the
  ``except`` line, making the deliberate swallow visible in review.

Unlike ``io-error-swallow`` this rule flags NARROW handlers too: catching
``NativeUnsupported`` for a clean fallback is exactly the designed shape —
but the reroute still has to be counted, or dialect drift (a writer
upgrade, a new codec) turns the fast path off fleet-wide with no signal.
"""

from __future__ import annotations

import ast
import os
from typing import List

from hyperspace_tpu.check.findings import Finding
from hyperspace_tpu.check.rules import Rule

NAME = "native-fallback"

#: native decode entry points: module-level reader + per-row-group handle
#: methods (names are unique to hyperspace_tpu/native's surface)
_NATIVE_READS = {
    "read_fixed_rg_into",
    "read_codes_rg",
    "read_dict_rg",
    "read_binary_rg",
}

#: handler calls that count as routing through the reliability taxonomy
_CLASSIFIERS = {"classify", "count_io_error", "note_corrupt", "note_ok"}

#: handler calls that count the reroute in hs_native_fallback_total
_FALLBACK_COUNTERS = {"_native_fallback_counter"}

_FALLBACK_FAMILY = "hs_native_fallback_total"


def _in_scope(rel: str) -> bool:
    parts = rel.replace(os.sep, "/").split("/")
    return len(parts) >= 2 and parts[0] == "hyperspace_tpu" and parts[1] == "exec"


def _name_of(node: ast.expr) -> str:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return ""


def _is_native_read(call: ast.Call) -> bool:
    fn = call.func
    if not isinstance(fn, ast.Attribute):
        return False
    if fn.attr in _NATIVE_READS:
        return True
    # module-level reader: specifically native.read_columns(...) — the bare
    # name also appears on pyarrow surfaces, so require the native receiver
    return fn.attr == "read_columns" and _name_of(fn.value) == "native"


def _touches_native(try_body: List[ast.stmt]) -> bool:
    for stmt in try_body:
        for node in ast.walk(stmt):
            if isinstance(node, ast.Call) and _is_native_read(node):
                return True
    return False


def _handler_accounts(handler: ast.ExceptHandler) -> bool:
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Call):
            name = _name_of(node.func)
            if name in _CLASSIFIERS or name in _FALLBACK_COUNTERS:
                return True
            # REGISTRY.counter("hs_native_fallback_total", ...) inline
            if (
                name == "counter"
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and node.args[0].value == _FALLBACK_FAMILY
            ):
                return True
    return False


def scan_tree(tree: ast.Module) -> List[ast.ExceptHandler]:
    """Handlers around native decode calls that neither re-raise, classify,
    nor count the fallback."""
    bad: List[ast.ExceptHandler] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Try):
            continue
        if not _touches_native(node.body):
            continue
        for handler in node.handlers:
            if not _handler_accounts(handler):
                bad.append(handler)
    return bad


def check(ctx) -> List[Finding]:
    findings: List[Finding] = []
    for path in ctx.files:
        rel = ctx.relpath(path)
        if ctx.full_scope and not _in_scope(rel):
            continue
        for handler in scan_tree(ctx.ast_of(path)):
            findings.append(
                Finding(
                    rule=NAME,
                    path=rel,
                    line=handler.lineno,
                    message=(
                        "except around a native decode call is an unaccounted "
                        "pyarrow fallback; re-raise, route through classify()/"
                        "count_io_error()/note_corrupt(), count it in "
                        "hs_native_fallback_total, or carry an explicit pragma"
                    ),
                )
            )
    return findings


RULE = Rule(name=NAME, doc=__doc__.strip(), check=check)
