"""``conf-keys``: every ``hyperspace.*`` conf key is registered and
documented — bidirectionally.

The repo's contract (docs/configuration.md, ``config.keys``) is that the
key namespace is CLOSED: a typo'd ``conf.get("hyperspace.serving.quueDepth")``
silently returns the fallback default forever. Three directions:

1. every ``conf.get/set/unset("hyperspace.…")`` string literal in code must
   be a key registered in ``config.keys``,
2. every registered key must appear (backticked) in docs/configuration.md,
3. every backticked ``hyperspace.…`` token in the docs/README must be a
   registered key (wildcard families like ``hyperspace.serving.*`` and bare
   namespace prefixes are fine).
"""

from __future__ import annotations

import ast
import re
from typing import List

from hyperspace_tpu.check.findings import Finding
from hyperspace_tpu.check.rules import Rule

NAME = "conf-keys"

_DOC_TOKEN = re.compile(r"`(hyperspace\.[A-Za-z0-9_.*]+)`")


def _literal_conf_calls(tree: ast.Module):
    """(line, key) for every conf.get/set/unset call with a literal
    hyperspace.* first argument. The receiver must be named ``conf`` (bare,
    ``self.conf``, ``session.conf``, …) so dict ``.get`` calls don't match."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        if not (isinstance(fn, ast.Attribute) and fn.attr in ("get", "set", "unset")):
            continue
        recv = fn.value
        recv_name = recv.attr if isinstance(recv, ast.Attribute) else (
            recv.id if isinstance(recv, ast.Name) else None
        )
        if recv_name not in ("conf", "_conf"):
            continue
        if not node.args:
            continue
        arg = node.args[0]
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str) and arg.value.startswith("hyperspace."):
            yield node.lineno, arg.value


def check(ctx) -> List[Finding]:
    registered = ctx.registered_conf_keys
    findings: List[Finding] = []

    # 1. code literals -> registry
    for path in ctx.files:
        if path.endswith("config.py") and "hyperspace_tpu" in path:
            continue  # the registry itself
        for line, key in _literal_conf_calls(ctx.ast_of(path)):
            if key not in registered:
                findings.append(
                    Finding(
                        rule=NAME,
                        path=ctx.relpath(path),
                        line=line,
                        message=f"conf key literal {key!r} is not registered in config.keys",
                    )
                )

    if not ctx.full_scope:
        return findings  # doc-drift directions need the whole tree in scope

    # 2. registry -> docs/configuration.md
    conf_doc = ctx.doc("docs/configuration.md")
    for key in sorted(registered):
        if f"`{key}`" not in conf_doc:
            findings.append(
                Finding(
                    rule=NAME,
                    path="docs/configuration.md",
                    line=0,
                    message=f"registered conf key {key!r} is not documented",
                )
            )

    # 3. docs -> registry
    for rel, text in sorted(ctx.docs.items()):
        for m in _DOC_TOKEN.finditer(text):
            token = m.group(1)
            if "*" in token:
                continue  # a documented family, e.g. hyperspace.serving.*
            if token in registered:
                continue
            # bare namespace prefix of some registered key ("the
            # hyperspace.obs keys") reads as prose, not a phantom key
            if any(k.startswith(token + ".") for k in registered):
                continue
            line = text.count("\n", 0, m.start()) + 1
            findings.append(
                Finding(
                    rule=NAME,
                    path=rel,
                    line=line,
                    message=f"doc mentions conf key {token!r} which is not registered in config.keys",
                )
            )
    return findings


RULE = Rule(
    name=NAME,
    doc=__doc__.strip(),
    check=check,
)
