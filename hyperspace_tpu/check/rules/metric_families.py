"""``metric-families``: every ``hs_*`` metric family is literal and
documented — bidirectionally.

The observability contract (docs/observability.md's family table, PR 5's
drift test) only works if registration sites are statically findable: a
family name built at runtime (``REGISTRY.counter(f"hs_{kind}_total")``)
escapes the drift check and the docs. Three directions:

1. every ``counter``/``gauge``/``histogram`` registration call on a registry
   must pass a LITERAL family name,
2. every literal ``hs_*`` family registered in code must appear in
   docs/observability.md,
3. every ``hs_*`` token in docs/observability.md must have a registration
   site (``_bucket``/``_sum``/``_count`` histogram series document their
   base family).
"""

from __future__ import annotations

import ast
import re
from typing import List, Set, Tuple

from hyperspace_tpu.check.findings import Finding
from hyperspace_tpu.check.rules import Rule

NAME = "metric-families"

_REGISTRY_RECV = re.compile(r"registry|reg$", re.IGNORECASE)


def _registration_calls(tree: ast.Module):
    """(line, literal-or-None) for every instrument-factory call on a
    registry-looking receiver (``REGISTRY.counter``, ``self.registry.gauge``,
    ``reg.histogram``)."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        if not (isinstance(fn, ast.Attribute) and fn.attr in ("counter", "gauge", "histogram")):
            continue
        recv = fn.value
        recv_name = recv.attr if isinstance(recv, ast.Attribute) else (
            recv.id if isinstance(recv, ast.Name) else None
        )
        if recv_name is None or not _REGISTRY_RECV.search(recv_name):
            continue
        if not node.args:
            continue
        arg = node.args[0]
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            yield node.lineno, arg.value
        else:
            yield node.lineno, None


def registered_families(ctx) -> Set[str]:
    """Every literal hs_* family name at a registration site in scope."""
    fams: Set[str] = set()
    for path in ctx.files:
        for _, name in _registration_calls(ctx.ast_of(path)):
            if name is not None and name.startswith("hs_"):
                fams.add(name)
    return fams


def check(ctx) -> List[Finding]:
    findings: List[Finding] = []
    fams: Set[str] = set()
    dynamic: List[Tuple[str, int]] = []
    for path in ctx.files:
        for line, name in _registration_calls(ctx.ast_of(path)):
            if name is None:
                dynamic.append((path, line))
            elif name.startswith("hs_"):
                fams.add(name)

    # 1. dynamic family names defeat drift checking
    for path, line in dynamic:
        findings.append(
            Finding(
                rule=NAME,
                path=ctx.relpath(path),
                line=line,
                message="metric family name must be a string literal (dynamic names escape the docs drift check)",
            )
        )

    if not ctx.full_scope:
        return findings  # drift directions need the whole tree in scope

    obs_doc = ctx.doc("docs/observability.md")
    doc_tokens = set(re.findall(r"\bhs_[a-z0-9_]+[a-z0-9]", obs_doc))
    doc_base = {
        re.sub(r"_(bucket|sum|count)$", "", t)
        if re.sub(r"_(bucket|sum|count)$", "", t) in fams
        else t
        for t in doc_tokens
    }

    # 2. registered -> documented
    for fam in sorted(fams - doc_base):
        findings.append(
            Finding(
                rule=NAME,
                path="docs/observability.md",
                line=0,
                message=f"metric family {fam!r} is registered in code but missing from the docs family table",
            )
        )
    # 3. documented -> registered
    for fam in sorted(doc_base - fams):
        findings.append(
            Finding(
                rule=NAME,
                path="docs/observability.md",
                line=0,
                message=f"docs document metric family {fam!r} which no code registers",
            )
        )
    return findings


RULE = Rule(name=NAME, doc=__doc__.strip(), check=check)
