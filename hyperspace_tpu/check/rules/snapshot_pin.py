"""``snapshot-pin``: query-path code must not resolve log versions directly.

A serving request's answer is defined by the :class:`SnapshotHandle` pinned
at admission (hyperspace_tpu/lifecycle/snapshot.py): every index-log
resolution downstream must go through ``session.index_manager`` (whose
reads consult :func:`current_snapshot`) or the handle itself. A call site
in the query path that invokes ``get_latest_stable_log()`` /
``get_latest_log()`` on a log manager directly bypasses the pin — it reads
the *live* log, so a refresh committing mid-flight hands the request a
torn mix of two data versions.

Scope: the query-path packages (``serving/``, ``rules/``, ``exec/``,
``plan/``, ``serve/``). The resolution and mutation layers —
``manager.py``, ``actions/``, ``models/``, ``lifecycle/`` — legitimately
read the live log and are exempt. A rare intentional site suppresses with
``# hscheck: disable=snapshot-pin``.
"""

from __future__ import annotations

import ast
import os
from typing import List

from hyperspace_tpu.check.findings import Finding
from hyperspace_tpu.check.rules import Rule

NAME = "snapshot-pin"

#: direct log-version resolvers (models/log_manager.py API)
_RESOLVERS = {"get_latest_stable_log", "get_latest_log"}

#: package-relative directories whose code runs under a request's pin
_QUERY_PATH_DIRS = ("serving", "rules", "exec", "plan", "serve")


def _in_scope(rel: str) -> bool:
    parts = rel.replace(os.sep, "/").split("/")
    return (
        len(parts) >= 2
        and parts[0] == "hyperspace_tpu"
        and parts[1] in _QUERY_PATH_DIRS
    )


def scan_tree(tree: ast.Module) -> List[ast.Call]:
    """Calls resolving a log version without going through the pin."""
    bad: List[ast.Call] = []
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in _RESOLVERS
        ):
            bad.append(node)
    return bad


def check(ctx) -> List[Finding]:
    findings: List[Finding] = []
    for path in ctx.files:
        rel = ctx.relpath(path)
        if ctx.full_scope and not _in_scope(rel):
            continue
        for call in scan_tree(ctx.ast_of(path)):
            findings.append(
                Finding(
                    rule=NAME,
                    path=rel,
                    line=call.lineno,
                    message=(
                        f"direct {call.func.attr}() call bypasses the request's "
                        "SnapshotHandle pin; resolve through session.index_manager "
                        "(pin-aware) or the pinned handle itself"
                    ),
                )
            )
    return findings


RULE = Rule(name=NAME, doc=__doc__.strip(), check=check)
