"""``cache-branding``: pruning provenance must reach the cache key.

Device-cache entries are branded by ``scan_key`` (immutable file-set
identity, extended with the pushed row-group predicate via
``_pruned_scan_key``). A call site that drops the branding kwarg doesn't
fail — it silently caches under the unpruned key, so a later scan with a
*different* pushed predicate reuses stale device buffers. This rule
enforces the three call-site contracts:

1. ``…._filter_mask(...)`` must pass ``pruned_by=`` explicitly,
2. ``device_filter_mask(...)`` must pass ``scan_key=`` (kwarg or the
   4th positional),
3. ``stage_filter_columns(...)`` must pass ``scan_key`` likewise.

``scan_key=None`` / ``pruned_by=None`` is fine — that is an explicit
"transient batch, don't cache" decision, visible at the call site.
"""

from __future__ import annotations

import ast
from typing import List

from hyperspace_tpu.check.findings import Finding
from hyperspace_tpu.check.rules import Rule

NAME = "cache-branding"

# callee name -> (required kwarg, positional index that also satisfies it)
_CONTRACTS = {
    "_filter_mask": ("pruned_by", None),
    "device_filter_mask": ("scan_key", 3),
    "stage_filter_columns": ("scan_key", 3),
}


def _callee_name(fn: ast.AST):
    if isinstance(fn, ast.Attribute):
        return fn.attr
    if isinstance(fn, ast.Name):
        return fn.id
    return None


def scan_tree(tree: ast.Module) -> List[ast.Call]:
    """Calls in the tree that violate a branding contract."""
    bad: List[ast.Call] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = _callee_name(node.func)
        contract = _CONTRACTS.get(name)
        if contract is None:
            continue
        kwarg, pos = contract
        if any(kw.arg == kwarg for kw in node.keywords):
            continue
        if any(kw.arg is None for kw in node.keywords):
            continue  # **kwargs forwarding — assume the caller threads it
        if pos is not None and len(node.args) > pos:
            continue
        bad.append(node)
    return bad


def check(ctx) -> List[Finding]:
    findings: List[Finding] = []
    for path in ctx.files:
        rel = ctx.relpath(path)
        for call in scan_tree(ctx.ast_of(path)):
            name = _callee_name(call.func)
            kwarg, _ = _CONTRACTS[name]
            findings.append(
                Finding(
                    rule=NAME,
                    path=rel,
                    line=call.lineno,
                    message=(
                        f"call to {name}() drops the cache-branding kwarg {kwarg!r}; "
                        f"pass {kwarg}=... explicitly (None is fine, silence is not)"
                    ),
                )
            )
    return findings


RULE = Rule(name=NAME, doc=__doc__.strip(), check=check)
