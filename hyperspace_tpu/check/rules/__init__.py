"""Rule registry for the AST codebase lint.

A rule is a module-level :class:`Rule` with a unique kebab-case name, a
one-paragraph doc (rendered by ``--list`` and docs/static-analysis.md), and
a ``check(ctx) -> List[Finding]``. Add a rule by dropping a module here,
defining ``RULE = Rule(...)``, and listing it in :data:`_RULE_MODULES` —
the fixture-pair convention in tests/fixtures/check/ (one seeded-violation
file that must fire, one clean file that must not) keeps it honest.
"""

from __future__ import annotations

import importlib
from dataclasses import dataclass
from typing import Callable, Dict, List

from hyperspace_tpu.check.findings import Finding

_RULE_MODULES = (
    "conf_keys",
    "metric_families",
    "lock_blocking",
    "cache_branding",
    "jit_purity",
    "snapshot_pin",
    "io_error_swallow",
    "process_local_state",
    "trace_context_drop",
    "donated_buffer_reuse",
    "native_fallback",
)


@dataclass(frozen=True)
class Rule:
    name: str
    doc: str
    check: Callable[["LintContext"], List[Finding]]  # noqa: F821


def all_rules() -> Dict[str, Rule]:
    out: Dict[str, Rule] = {}
    for mod in _RULE_MODULES:
        m = importlib.import_module(f"hyperspace_tpu.check.rules.{mod}")
        rule = m.RULE
        if rule.name in out:
            raise ValueError(f"duplicate rule name {rule.name!r}")
        out[rule.name] = rule
    return out
