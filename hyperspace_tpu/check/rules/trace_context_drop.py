"""``trace-context-drop``: fabric request paths must carry the trace context.

Distributed traces (docs/observability.md) only stitch into one tree when
every hop carries the ``TraceContext``: the context lives in a contextvar,
so it silently evaporates at exactly two seams — a ``threading.Thread``
(contextvars do not cross thread creation unless the target is wrapped)
and an outbound HTTP hop (the remote process never sees the context unless
a ``traceparent`` header is sent). A dropped context is invisible in tests
that assert on results; it only shows up later as an orphaned worker tree.
This rule makes both seams explicit in ``fabric/`` and ``serving/``:

- **Thread spawn in a request-shaped function**: a ``Thread(...)``
  construction inside a function whose body handles request state (names
  ``sql``, ``query``, ``tenant`` or ``request`` appear) must show a
  propagation marker somewhere in that function — ``spans.attach(...)``,
  ``spans.wrap(...)`` or ``spans.bind_context(...)`` (the hedged-dispatch
  idiom in ``fabric/frontdoor.py``). Lifecycle threads (pollers,
  heartbeats, serve loops) reference no request state and stay clean.
- **``urlopen`` of a ``/query`` URL**: a function that fetches a worker's
  ``/query`` endpoint must reference ``traceparent`` (building the header
  inline), call a ``*trace_headers*`` helper, or call ``to_traceparent()``.
  Metrics/healthz/statusz/profilez fetches carry no request context and
  are out of scope by URL.

Intentionally context-free sites annotate the spawning/fetching line with
``# hscheck: disable=trace-context-drop``.

Scope: ``hyperspace_tpu/fabric/`` and ``hyperspace_tpu/serving/`` (the
layers that move requests between threads and processes); explicit fixture
paths are checked wherever they live.
"""

from __future__ import annotations

import ast
import os
from typing import List, Set, Tuple

from hyperspace_tpu.check.findings import Finding
from hyperspace_tpu.check.rules import Rule

NAME = "trace-context-drop"

#: directories whose request paths must propagate trace context
_SCOPE_DIRS = ("fabric", "serving")

#: names whose presence marks a function as handling request state
_REQUEST_IDENTS = {"sql", "query", "tenant", "request"}

#: attribute/function names that count as context propagation across threads
_THREAD_MARKERS = {"attach", "wrap", "bind_context"}

#: attribute/function names that count as header propagation across HTTP
_HTTP_MARKER_SUBSTRINGS = ("trace_headers", "to_traceparent")


def _in_scope(rel: str) -> bool:
    parts = rel.replace(os.sep, "/").split("/")
    return (
        len(parts) >= 2
        and parts[0] == "hyperspace_tpu"
        and parts[1] in _SCOPE_DIRS
    )


def _call_name(func: ast.expr) -> str:
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return ""


def _outer_functions(tree: ast.Module):
    """Module-level functions and class methods — the scope a spawned
    thread's closure actually shares, nested defs included in the subtree."""
    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node
        elif isinstance(node, ast.ClassDef):
            for sub in node.body:
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    yield sub


def scan_function(fn) -> List[Tuple[str, int]]:
    """(kind, lineno) for every context-dropping seam in this function."""
    thread_lines: List[int] = []
    urlopen_lines: List[int] = []
    idents: Set[str] = set()
    attrs: Set[str] = set()
    strings: List[str] = []
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            name = _call_name(node.func)
            if name == "Thread":
                thread_lines.append(node.lineno)
            elif name == "urlopen":
                urlopen_lines.append(node.lineno)
        if isinstance(node, ast.Name):
            idents.add(node.id)
        elif isinstance(node, ast.arg):
            idents.add(node.arg)
        elif isinstance(node, ast.Attribute):
            attrs.add(node.attr)
        elif isinstance(node, ast.Constant) and isinstance(node.value, str):
            strings.append(node.value)

    out: List[Tuple[str, int]] = []
    request_shaped = bool(_REQUEST_IDENTS & idents)
    thread_propagates = bool(_THREAD_MARKERS & (attrs | idents)) or (
        "TraceContext" in idents or "TraceContext" in attrs
    )
    if request_shaped and not thread_propagates:
        out.extend(("thread", ln) for ln in thread_lines)

    hits_query = any("/query" in s for s in strings)
    http_propagates = (
        any("traceparent" in s for s in strings)
        or any(
            sub in a for a in (attrs | idents) for sub in _HTTP_MARKER_SUBSTRINGS
        )
    )
    if hits_query and not http_propagates:
        out.extend(("http", ln) for ln in urlopen_lines)
    return out


_MESSAGES = {
    "thread": (
        "Thread spawned in a request-handling function without a trace "
        "propagation marker (spans.attach/spans.wrap/spans.bind_context): "
        "the contextvar trace context does not cross thread creation, so "
        "spans on the new thread orphan from the request tree; wrap the "
        "target or mark the spawn '# hscheck: disable=trace-context-drop'"
    ),
    "http": (
        "urlopen of a /query endpoint without a traceparent header: the "
        "remote worker starts a fresh trace instead of joining this one; "
        "send TraceContext.to_traceparent() (or a *trace_headers* helper), "
        "or mark the fetch '# hscheck: disable=trace-context-drop'"
    ),
}


def check(ctx) -> List[Finding]:
    findings: List[Finding] = []
    for path in ctx.files:
        rel = ctx.relpath(path)
        if ctx.full_scope and not _in_scope(rel):
            continue
        for fn in _outer_functions(ctx.ast_of(path)):
            for kind, lineno in scan_function(fn):
                findings.append(
                    Finding(rule=NAME, path=rel, line=lineno, message=_MESSAGES[kind])
                )
    return findings


RULE = Rule(name=NAME, doc=__doc__.strip(), check=check)
