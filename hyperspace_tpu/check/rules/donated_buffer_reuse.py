"""``donated-buffer-reuse``: no reads of a donated device buffer after the
jitted call that donated it.

``donate_argnums`` tells XLA it may alias the argument's memory into the
outputs — after the call, the Python reference still exists but the buffer
is deleted. Reading it raises on TPU and (worse) works by accident on some
backends, so the bug ships silently. This rule tracks names bound to
donation-compiled callables — any call carrying a ``donate_argnums``
keyword, e.g. ``jitted = compile_stage(key, fn, donate_argnums=(0, 1))``
or ``jax.jit(fn, donate_argnums=0)`` — and flags any later read of a name
that was passed in a donated position, until the name is rebound.

Only plain-name positional arguments are tracked (``jitted(*args)`` and
attribute/subscript operands are conservatively skipped); rebinding the
name — idiomatically to the call's own result, ``state = jitted(state)`` —
clears it.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Tuple

from hyperspace_tpu.check.findings import Finding
from hyperspace_tpu.check.rules import Rule

NAME = "donated-buffer-reuse"


def _donate_positions(call: ast.Call) -> Optional[Tuple[int, ...]]:
    """The literal donate_argnums of a call, or None when absent/dynamic."""
    for kw in call.keywords:
        if kw.arg != "donate_argnums":
            continue
        v = kw.value
        if isinstance(v, ast.Constant) and isinstance(v.value, int):
            return (v.value,)
        if isinstance(v, (ast.Tuple, ast.List)) and all(
            isinstance(e, ast.Constant) and isinstance(e.value, int)
            for e in v.elts
        ):
            return tuple(e.value for e in v.elts)
        # dynamic donate_argnums: assume every positional arg may be donated
        return ()
    return None


class _FnScanner:
    """Source-order walk of one function body: track names bound to
    donation-compiled callables, then names passed in donated positions,
    then reads of those names before any rebind."""

    def __init__(self) -> None:
        self.compiled: Dict[str, Tuple[int, ...]] = {}  # callable -> positions
        self.donated: Dict[str, int] = {}  # dead buffer name -> call lineno
        self.hits: List[Tuple[int, str]] = []

    def _note_donating_call(self, call: ast.Call, positions: Tuple[int, ...]) -> None:
        names = []
        for i, arg in enumerate(call.args):
            if isinstance(arg, ast.Starred):
                return  # starred call: positions unknowable, skip the call
            if isinstance(arg, ast.Name) and (not positions or i in positions):
                names.append(arg.id)
        for n in names:
            self.donated[n] = call.lineno

    def visit(self, node: ast.AST) -> None:
        # nested defs get their own scanner pass (scan_tree walks every def)
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            return
        if isinstance(node, ast.Assign):
            # evaluation order: value first, then the target stores — so
            # `state = jitted(state)` re-binds the donated name cleanly
            self.visit(node.value)
            if isinstance(node.value, ast.Call) and _donate_positions(node.value) is not None:
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        self.compiled[tgt.id] = _donate_positions(node.value)
            for tgt in node.targets:
                self.visit(tgt)
            return
        if isinstance(node, ast.Call):
            positions = None
            if isinstance(node.func, ast.Name) and node.func.id in self.compiled:
                positions = self.compiled[node.func.id]
            elif isinstance(node.func, ast.Call):
                # direct form: jax.jit(fn, donate_argnums=0)(state, x)
                positions = _donate_positions(node.func)
            if positions is not None:
                # operands of THIS call are the donation itself, not a reuse
                # (a previously-donated operand still flags, via the child
                # visit below, which runs before the donation is recorded)
                for child in ast.iter_child_nodes(node):
                    self.visit(child)
                self._note_donating_call(node, positions)
                return
        if isinstance(node, ast.Name):
            if isinstance(node.ctx, ast.Store):
                self.donated.pop(node.id, None)
            elif isinstance(node.ctx, ast.Load) and node.id in self.donated:
                self.hits.append((
                    node.lineno,
                    f"{node.id!r} was donated at line {self.donated[node.id]} "
                    f"(donate_argnums) — its buffer is deleted; rebind before reuse",
                ))
        for child in ast.iter_child_nodes(node):
            self.visit(child)


def scan_tree(tree: ast.Module) -> List[Tuple[int, str]]:
    hits: List[Tuple[int, str]] = []
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            scanner = _FnScanner()
            for stmt in node.body:
                scanner.visit(stmt)
            hits.extend(scanner.hits)
    return sorted(set(hits))


def check(ctx) -> List[Finding]:
    findings: List[Finding] = []
    for path in ctx.files:
        rel = ctx.relpath(path)
        for line, msg in scan_tree(ctx.ast_of(path)):
            findings.append(Finding(rule=NAME, path=rel, line=line, message=msg))
    return findings


RULE = Rule(name=NAME, doc=__doc__.strip(), check=check)
