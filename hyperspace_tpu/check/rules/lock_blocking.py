"""``lock-blocking``: no blocking calls while holding a serving/obs mutex.

The serving path holds small mutexes on the request hot path (admission,
plan cache, result cache, scheduler) and the obs layer's registry/history
locks are taken by the telemetry endpoint. A ``time.sleep``, file/socket
IO, or a device sync (``.block_until_ready()``) inside such a critical
section turns a nanosecond mutex into a convoy: every concurrent request
queues behind one slow syscall. This rule walks every ``with`` statement
whose context expression *names* a lock (identifier containing ``lock`` or
``cv``/``cond``) in ``serving/`` and ``obs/`` modules and flags blocking
calls in the guarded block (without descending into nested function
definitions, which execute later, outside the lock).
"""

from __future__ import annotations

import ast
import os
from typing import Iterator, List, Optional, Tuple

from hyperspace_tpu.check.findings import Finding
from hyperspace_tpu.check.rules import Rule

NAME = "lock-blocking"

# Call patterns that block: (dotted-name-suffix-or-exact, description).
_BLOCKING_CALLS = {
    "time.sleep": "sleeps while holding a lock",
    "socket.socket": "opens a socket while holding a lock",
    "socket.create_connection": "opens a socket while holding a lock",
    "os.fsync": "performs file IO while holding a lock",
    "os.replace": "performs file IO while holding a lock",
    "os.rename": "performs file IO while holding a lock",
    "os.remove": "performs file IO while holding a lock",
    "shutil.copy": "performs file IO while holding a lock",
    "shutil.move": "performs file IO while holding a lock",
    "subprocess.run": "spawns a process while holding a lock",
    "subprocess.check_output": "spawns a process while holding a lock",
    "urlopen": "performs network IO while holding a lock",
}
_BLOCKING_BARE = {
    "open": "opens a file while holding a lock",
}
_BLOCKING_ATTRS = {
    "block_until_ready": "synchronizes with the device while holding a lock",
}


def _dotted(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = _dotted(node.value)
        return f"{base}.{node.attr}" if base else node.attr
    return None


def _names_a_lock(expr: ast.AST) -> bool:
    """True when the with-item's context expression is a lock by name:
    ``self._lock``, ``plan_lock``, ``REGISTRY._lock``, ``cv``/``_cond``."""
    dotted = _dotted(expr)
    if dotted is None:
        return False
    leaf = dotted.rsplit(".", 1)[-1].lower().lstrip("_")
    return "lock" in leaf or leaf in ("cv", "cond", "condition")


def _walk_no_defs(body: List[ast.stmt]) -> Iterator[ast.AST]:
    """ast.walk over statements, skipping nested function/class bodies —
    code in a nested def runs later, not under the lock."""
    stack: List[ast.AST] = list(body)
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda, ast.ClassDef)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def _blocking_reason(call: ast.Call) -> Optional[str]:
    dotted = _dotted(call.func)
    if dotted is not None:
        if dotted in _BLOCKING_BARE:
            return _BLOCKING_BARE[dotted]
        for pat, why in _BLOCKING_CALLS.items():
            if dotted == pat or dotted.endswith("." + pat):
                return why
    if isinstance(call.func, ast.Attribute) and call.func.attr in _BLOCKING_ATTRS:
        return _BLOCKING_ATTRS[call.func.attr]
    return None


def scan_tree(tree: ast.Module) -> List[Tuple[int, str]]:
    """(line, reason) for every blocking call under a lock-guarded with."""
    hits: List[Tuple[int, str]] = []
    for node in ast.walk(tree):
        if not isinstance(node, (ast.With, ast.AsyncWith)):
            continue
        if not any(_names_a_lock(item.context_expr) for item in node.items):
            continue
        for inner in _walk_no_defs(node.body):
            if isinstance(inner, ast.Call):
                why = _blocking_reason(inner)
                if why is not None:
                    hits.append((inner.lineno, why))
    return sorted(set(hits))


def check(ctx) -> List[Finding]:
    findings: List[Finding] = []
    for path in ctx.files:
        rel = ctx.relpath(path)
        norm = rel.replace(os.sep, "/")
        if "/serving/" not in norm and "/obs/" not in norm:
            continue
        for line, why in scan_tree(ctx.ast_of(path)):
            findings.append(Finding(rule=NAME, path=rel, line=line, message=why))
    return findings


RULE = Rule(name=NAME, doc=__doc__.strip(), check=check)
