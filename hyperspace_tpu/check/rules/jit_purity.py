"""``jit-purity``: no host-side numpy/time/random calls inside jitted code.

A ``np.*`` call inside a jit-traced function either throws at trace time
(numpy can't handle tracers) or — worse — silently constant-folds against
the example operands and bakes a stale value into the compiled program.
``time.*`` and ``random.*`` always freeze: they run once at trace time and
the compiled executable replays the same value forever. This rule finds
functions that are jitted — decorated with ``jax.jit``/``jit``/
``partial(jax.jit, …)`` or passed by name into a ``*jit*`` wrapper like
``_cached_predicate_jit(key, fn)`` — and flags ``np.``/``numpy.``,
``time.`` and ``random.`` attribute *calls* in their bodies.

Dtype and constant references (``np.int64(n)`` on a concrete python int is
still trace-time, but ``np.float32``/``np.nan``/``np.iinfo`` as dtype
arguments are idiomatic and safe) are whitelisted.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set, Tuple

from hyperspace_tpu.check.findings import Finding
from hyperspace_tpu.check.rules import Rule

NAME = "jit-purity"

_NP_NAMES = ("np", "numpy")
# dtype/constant attributes that are safe as jit-time arguments
_NP_SAFE = {
    "float16", "float32", "float64", "int8", "int16", "int32", "int64",
    "uint8", "uint16", "uint32", "uint64", "bool_", "complex64",
    "dtype", "iinfo", "finfo", "nan", "inf", "pi", "e", "newaxis",
}


def _dotted(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = _dotted(node.value)
        return f"{base}.{node.attr}" if base else None
    return None


def _is_jit_decorator(dec: ast.AST) -> bool:
    dotted = _dotted(dec)
    if dotted in ("jit", "jax.jit"):
        return True
    if isinstance(dec, ast.Call):
        callee = _dotted(dec.func)
        if callee in ("jit", "jax.jit"):
            return True  # @jax.jit(donate_argnums=...)
        if callee in ("partial", "functools.partial") and dec.args:
            return _dotted(dec.args[0]) in ("jit", "jax.jit")
    return False


def _jitted_by_name(tree: ast.Module) -> Set[str]:
    """Function names passed positionally into any ``*jit*``-named wrapper
    (``jax.jit(fn)``, ``_cached_predicate_jit(key, fn)``, …) or into any
    call carrying a ``donate_argnums`` keyword — the stage compiler
    (``compile_stage(skeleton, fn, donate_argnums=...)``) jits exactly like
    ``jax.jit`` does."""
    names: Set[str] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        callee = _dotted(node.func)
        donating = any(kw.arg == "donate_argnums" for kw in node.keywords)
        if not donating and (
            callee is None or "jit" not in callee.rsplit(".", 1)[-1]
        ):
            continue
        for arg in node.args:
            if isinstance(arg, ast.Name):
                names.add(arg.id)
    return names


def _impure_calls(fn: ast.FunctionDef) -> List[Tuple[int, str]]:
    hits: List[Tuple[int, str]] = []
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        dotted = _dotted(node.func)
        if dotted is None or "." not in dotted:
            continue
        head, attr = dotted.split(".", 1)
        leaf = attr.split(".")[0]
        if head in _NP_NAMES and leaf not in _NP_SAFE:
            hits.append((node.lineno, f"host numpy call np.{attr}() inside jitted function {fn.name!r} (use jnp)"))
        elif head == "time":
            hits.append((node.lineno, f"time.{attr}() inside jitted function {fn.name!r} freezes at trace time"))
        elif head == "random" or dotted.startswith(("np.random.", "numpy.random.")):
            hits.append((node.lineno, f"{dotted}() inside jitted function {fn.name!r} freezes at trace time (use jax.random)"))
    return hits


def scan_tree(tree: ast.Module) -> List[Tuple[int, str]]:
    by_name = _jitted_by_name(tree)
    hits: List[Tuple[int, str]] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.FunctionDef):
            continue
        jitted = node.name in by_name or any(_is_jit_decorator(d) for d in node.decorator_list)
        if jitted:
            hits.extend(_impure_calls(node))
    return sorted(set(hits))


def check(ctx) -> List[Finding]:
    findings: List[Finding] = []
    for path in ctx.files:
        rel = ctx.relpath(path)
        for line, msg in scan_tree(ctx.ast_of(path)):
            findings.append(Finding(rule=NAME, path=rel, line=line, message=msg))
    return findings


RULE = Rule(name=NAME, doc=__doc__.strip(), check=check)
