"""``io-error-swallow``: lake IO failures must be classified, never dropped.

A broad ``except`` (bare, ``Exception``, or ``BaseException``) wrapped
around lake IO — file opens, parquet footer/metadata/schema reads, decodes,
directory listings — is how a torn write or a flaky mount silently became
"the index does not exist" (models/log_manager.py pre-reliability) or "no
rows" instead of a typed failure. In the IO-touching packages (``exec/``,
``serving/``, ``models/``) such a handler must do one of:

- re-raise (anything — the typed reliability error, or the original), or
- route through the reliability taxonomy: call
  ``classify``/``count_io_error`` (hyperspace_tpu/reliability/errors.py) or
  a quarantine hook (``note_corrupt``), so the failure is counted and
  attributed even when a fallback answers, or
- carry an explicit ``# hscheck: disable=io-error-swallow`` pragma on the
  ``except`` line, making the deliberate swallow visible in review.

Narrow handlers (``except OSError``, ``except pa.ArrowInvalid``) are not
flagged: catching a *specific* failure mode for a *specific* fallback is
the designed pattern; this rule targets the catch-everything-say-nothing
shape.
"""

from __future__ import annotations

import ast
import os
from typing import List

from hyperspace_tpu.check.findings import Finding
from hyperspace_tpu.check.rules import Rule

NAME = "io-error-swallow"

#: package-relative directories whose code touches the lake
_IO_DIRS = ("exec", "serving", "models")

#: call names (bare or attribute) that mark a try body as lake IO
_IO_CALLS = {
    "open",
    "listdir",
    "stat",
    "read_metadata",
    "read_schema",
    "read_row_groups",
    "read_columns",
    "read_table",
    "read_parquet_batch",
    "unify_schemas",
    "to_table",
    "ParquetFile",
    "from_json",
    "write_atomic",
    "write_atomic_exclusive",
}

#: handler calls that count as routing through the reliability taxonomy
_CLASSIFIERS = {
    "classify",
    "count_io_error",
    "note_corrupt",
    "note_ok",
    "_count_corrupt",
}

_BROAD = {"Exception", "BaseException"}


def _in_scope(rel: str) -> bool:
    parts = rel.replace(os.sep, "/").split("/")
    return len(parts) >= 2 and parts[0] == "hyperspace_tpu" and parts[1] in _IO_DIRS


def _name_of(node: ast.expr) -> str:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return ""


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True  # bare except
    types = t.elts if isinstance(t, ast.Tuple) else [t]
    return any(_name_of(e) in _BROAD for e in types)


def _touches_io(try_body: List[ast.stmt]) -> bool:
    for stmt in try_body:
        for node in ast.walk(stmt):
            if isinstance(node, ast.Call) and _name_of(node.func) in _IO_CALLS:
                return True
    return False


def _handler_classifies(handler: ast.ExceptHandler) -> bool:
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Call) and _name_of(node.func) in _CLASSIFIERS:
            return True
    return False


def scan_tree(tree: ast.Module) -> List[ast.ExceptHandler]:
    """Broad handlers around lake IO that neither re-raise nor classify."""
    bad: List[ast.ExceptHandler] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Try):
            continue
        if not _touches_io(node.body):
            continue
        for handler in node.handlers:
            if _is_broad(handler) and not _handler_classifies(handler):
                bad.append(handler)
    return bad


def check(ctx) -> List[Finding]:
    findings: List[Finding] = []
    for path in ctx.files:
        rel = ctx.relpath(path)
        if ctx.full_scope and not _in_scope(rel):
            continue
        for handler in scan_tree(ctx.ast_of(path)):
            findings.append(
                Finding(
                    rule=NAME,
                    path=rel,
                    line=handler.lineno,
                    message=(
                        "broad except around lake IO swallows the failure "
                        "unclassified; re-raise a typed reliability error, "
                        "route through classify()/count_io_error()/"
                        "note_corrupt(), or carry an explicit pragma"
                    ),
                )
            )
    return findings


RULE = Rule(name=NAME, doc=__doc__.strip(), check=check)
