"""``hyperspace_tpu.check`` — static program-contract and codebase-invariant
analysis.

Three passes, one stance: the repo's correctness claims are *mechanically
checkable*, so check them mechanically instead of re-reading the code.

- :mod:`hyperspace_tpu.check.hlo_lint` — compiled-program contracts. Each
  device-program family (fused filter, bucketed SMJ span, grouped-agg chunk,
  sharded grouped merge, index-build exchange) *declares* its collective
  budget and forbidden-op patterns where the program is built; the engine
  verifies compiled HLO text against the declaration, either offline (tests,
  ``__graft_entry__.dryrun_multichip``) or at program-cache-fill time behind
  ``hyperspace.check.hlo.enabled``.
- :mod:`hyperspace_tpu.check.lint` + :mod:`hyperspace_tpu.check.rules` —
  AST rules encoding repo contracts and past-bug patterns (conf-key/doc
  drift, metric-family drift, lock-hold blocking calls, dropped
  cache-branding kwargs, host ops inside jitted programs). CLI:
  ``python -m hyperspace_tpu.check`` (nonzero exit on findings).
- :mod:`hyperspace_tpu.check.locks` — a runtime lock-order watcher
  (default-off, ``hyperspace.check.locks``) that records the cross-thread
  lock acquisition graph and reports cycles as potential deadlocks.

This ``__init__`` stays import-light on purpose: ``session.py`` imports the
runtime hooks at construction time, and pulling the AST lint (ast parsing of
the whole tree) into that path would tax every session start.
"""

from __future__ import annotations

__all__ = ["Finding"]

from hyperspace_tpu.check.findings import Finding
