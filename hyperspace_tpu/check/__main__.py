"""``python -m hyperspace_tpu.check`` — run the codebase lint.

Exit codes: 0 clean, 1 findings, 2 usage/internal error. Designed for CI:
``run-tests`` invokes it before pytest, and ``--json`` emits a
machine-readable findings array for tooling.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m hyperspace_tpu.check",
        description="Static program-contract and codebase-invariant lint.",
    )
    parser.add_argument("paths", nargs="*", help="files to lint (default: the package tree)")
    parser.add_argument("--root", default=None, help="repo root (default: auto-detected)")
    parser.add_argument(
        "--rules",
        default=None,
        help="comma-separated rule names to run (default: all)",
    )
    parser.add_argument("--json", action="store_true", help="emit findings as JSON")
    parser.add_argument("--list", action="store_true", help="list registered rules and exit")
    args = parser.parse_args(argv)

    from hyperspace_tpu.check.lint import run_lint
    from hyperspace_tpu.check.rules import all_rules

    if args.list:
        for name, rule in sorted(all_rules().items()):
            first = rule.doc.splitlines()[0]
            print(f"{name}: {first}")
        return 0

    rules = [r.strip() for r in args.rules.split(",") if r.strip()] if args.rules else None
    try:
        findings = run_lint(root=args.root, paths=args.paths or None, rules=rules)
    except KeyError as exc:
        print(f"error: {exc.args[0]}", file=sys.stderr)
        return 2

    if args.json:
        print(json.dumps([f.as_dict() for f in findings], indent=2))
    else:
        for f in findings:
            print(f.render())
        if findings:
            print(f"\n{len(findings)} finding(s)", file=sys.stderr)
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
