"""Compiled-HLO program-contract lint.

The framework's headline claims are *compiled-program properties* (SURVEY.md
§2.9, mirroring the reference's shuffle-freedom guarantee, ref:
HS/index/covering/JoinIndexRule.scala:604-618): the bucketed SMJ span program
is collective-free, the sharded grouped aggregate all-gathers fixed-size
partial tables and never rows, the distributed index build exchanges rows
with exactly ONE all-to-all. ``parallel/hlo_check.py`` asserted two of these
for two hand-built programs; this module generalizes it into a rule engine:

- each device-program family **declares** its collective budget and
  forbidden-op patterns at registration (:func:`register_contract`, called
  from ``exec/device.py`` / ``ops/bucketize.py`` next to the program
  builders),
- :func:`verify_hlo` checks any compiled HLO text against a declared
  contract and returns :class:`~hyperspace_tpu.check.findings.Finding`s,
- :func:`maybe_verify` is the runtime hook: default-off behind
  ``hyperspace.check.hlo.enabled``, it verifies every *newly compiled*
  executable (once per (program-cache key, shape signature)) at
  program-cache-fill time, bumping ``hs_check_violations_total{rule,program}``
  and ``hs_check_programs_verified_total{program}``.

The disabled path is one conf-dict lookup — bench.py ``--check-overhead``
pins it at <= 1% of a program-cache fill.
"""

from __future__ import annotations

import re
import threading
import warnings
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from hyperspace_tpu.check.findings import Finding

# --------------------------------------------------------------------------
# HLO text scanning (moved here from parallel/hlo_check.py; that module is
# now a compat shim re-exporting these names)
# --------------------------------------------------------------------------

COLLECTIVE_OPS = (
    "all-to-all",
    "all-gather",
    "collective-permute",
    "all-reduce",
    "reduce-scatter",
)

# an HLO op application site: ` op-name(` or ` op-name-start(` — the result
# type before it may be a tuple containing spaces, so key on the call itself;
# operand mentions like `get-tuple-element(%all-to-all)` don't match (no
# following paren), and metadata op_name strings use underscores, not dashes.
# Async pairs (op-start/op-done) count once at -start.
_INSTR = re.compile(
    r"[\s)](" + "|".join(COLLECTIVE_OPS) + r")(-start|-done)?(?:\.\d+)?\("
)


def collective_counts(hlo_text: str) -> Dict[str, int]:
    """Occurrences of each collective op in compiled HLO text (async
    start/done pairs counted once)."""
    counts = {k: 0 for k in COLLECTIVE_OPS}
    for m in _INSTR.finditer(hlo_text):
        if m.group(2) == "-done":
            continue
        counts[m.group(1)] += 1
    return counts


def assert_collectives(hlo_text: str, expect: Dict[str, int], context: str = "") -> None:
    """Assert exact counts for the ops named in ``expect`` and ZERO for every
    other collective op."""
    got = collective_counts(hlo_text)
    for op in COLLECTIVE_OPS:
        want = expect.get(op, 0)
        assert got[op] == want, (
            f"{context or 'program'}: expected {want} x {op} in compiled HLO, "
            f"found {got[op]} (all counts: {got})"
        )


# ops that move row data between devices: their absence is the reference's
# shuffle-freedom claim (ref: JoinIndexRule.scala:604-618). all-reduce stays
# out of this set — a scalar reduction is not a data shuffle.
SHUFFLE_OPS = ("all-to-all", "all-gather", "collective-permute", "reduce-scatter")


def assert_shuffle_free(hlo_text: str, context: str = "") -> None:
    """Assert the compiled program exchanges NO row data between devices
    (no all-to-all / all-gather / collective-permute / reduce-scatter)."""
    got = collective_counts(hlo_text)
    bad = {op: got[op] for op in SHUFFLE_OPS if got[op]}
    assert not bad, (
        f"{context or 'program'}: expected a shuffle-free program but the "
        f"compiled HLO contains data-movement collectives {bad} "
        f"(all counts: {got})"
    )


def hlo_text_of(jitted, *args, **kwargs) -> str:
    """Compiled HLO text of a jitted callable for the given example
    arguments — the artifact the rules inspect."""
    return jitted.lower(*args, **kwargs).compile().as_text()


# --------------------------------------------------------------------------
# Forbidden-op text rules (apply to every family unless opted out)
# --------------------------------------------------------------------------

#: (rule name, compiled regex, human description). These encode device-program
#: hygiene independent of the collective story: a device program must never
#: round-trip through the host mid-flight (python callbacks, infeed/outfeed),
#: must not silently double an array's HBM footprint by upcasting f32 data to
#: f64, and must not carry bounded-dynamic dimensions (``s32[<=N]``), whose
#: shape-dependent control flow defeats the one-executable-per-bucket design.
FORBIDDEN_PATTERNS: Tuple[Tuple[str, "re.Pattern", str], ...] = (
    (
        "host-callback",
        re.compile(
            r"\binfeed\(|\boutfeed\(|custom_call_target=\"[^\"]*(?:python|host_callback|callback)[^\"]*\""
        ),
        "host round-trip (infeed/outfeed/python callback custom-call) inside a device program",
    ),
    (
        "f64-upcast",
        re.compile(r"f64\[\d[^\]]*\]\S* convert\(f32\["),
        "whole-array f32->f64 convert (doubles HBM footprint; stage f64 or compute in f32)",
    ),
    (
        "dynamic-shape",
        re.compile(r"\[<=\d"),
        "bounded-dynamic dimension (recompile/slow-path hazard; pad to a shape bucket instead)",
    ),
)


# --------------------------------------------------------------------------
# Contracts
# --------------------------------------------------------------------------

_ANY = (0, None)


@dataclass(frozen=True)
class ProgramContract:
    """Declared collective budget for one device-program family.

    ``collectives`` maps op name -> (min, max) occurrences in the compiled
    HLO (``max=None`` = unbounded). Ops not listed must not appear at all —
    a contract says everything it permits. ``forbid`` names which
    :data:`FORBIDDEN_PATTERNS` rules apply (default: all).

    ``single_fusion`` asserts the whole-plan-fusion guarantee: the family
    compiles to ONE executable — exactly one ``HloModule`` with exactly one
    ``ENTRY`` computation in the compiled text. (Backends still split an
    entry into internal ``fusion`` computations; the per-stage promise is
    one module and one entry, i.e. one dispatch, not one backend kernel.)
    """

    family: str
    collectives: Dict[str, Tuple[int, Optional[int]]] = field(default_factory=dict)
    forbid: Tuple[str, ...] = tuple(name for name, _, _ in FORBIDDEN_PATTERNS)
    description: str = ""
    single_fusion: bool = False


_CONTRACTS: Dict[str, ProgramContract] = {}
_CONTRACTS_LOCK = threading.Lock()


def register_contract(
    family: str,
    collectives: Optional[Dict[str, Tuple[int, Optional[int]]]] = None,
    forbid: Optional[Tuple[str, ...]] = None,
    description: str = "",
    single_fusion: bool = False,
) -> ProgramContract:
    """Declare (or re-declare, idempotently) a program family's contract.
    Called next to the program builders so the budget lives with the code it
    constrains."""
    c = ProgramContract(
        family=family,
        collectives=dict(collectives or {}),
        forbid=tuple(forbid) if forbid is not None else tuple(n for n, _, _ in FORBIDDEN_PATTERNS),
        description=description,
        single_fusion=bool(single_fusion),
    )
    with _CONTRACTS_LOCK:
        _CONTRACTS[family] = c
    return c


def contract_for(family: str) -> Optional[ProgramContract]:
    with _CONTRACTS_LOCK:
        return _CONTRACTS.get(family)


def registered_contracts() -> Dict[str, ProgramContract]:
    with _CONTRACTS_LOCK:
        return dict(_CONTRACTS)


def verify_hlo(family: str, hlo_text: str, program: str = "") -> List[Finding]:
    """Check compiled HLO text against ``family``'s declared contract.
    Returns one Finding per violated rule (empty = conformant). Raises
    KeyError for an undeclared family — an unknown family is a lint bug,
    not a clean program."""
    contract = contract_for(family)
    if contract is None:
        raise KeyError(
            f"no contract registered for program family {family!r} "
            f"(registered: {sorted(_CONTRACTS)})"
        )
    label = program or family
    findings: List[Finding] = []
    got = collective_counts(hlo_text)
    for op in COLLECTIVE_OPS:
        lo, hi = contract.collectives.get(op, (0, 0))
        n = got[op]
        if n < lo or (hi is not None and n > hi):
            budget = f"exactly {lo}" if lo == hi else (
                f">= {lo}" if hi is None else f"{lo}..{hi}"
            )
            findings.append(
                Finding(
                    rule=f"collective-budget:{op}",
                    path=f"hlo:{label}",
                    line=0,
                    message=(
                        f"{family}: {n} x {op} in compiled HLO, contract allows "
                        f"{budget} (all counts: {got})"
                    ),
                    detail={"family": family, "op": op, "count": n},
                )
            )
    if contract.single_fusion:
        n_mod = len(re.findall(r"^HloModule\b", hlo_text, flags=re.MULTILINE))
        n_entry = len(re.findall(r"^ENTRY\b", hlo_text, flags=re.MULTILINE))
        if n_mod != 1 or n_entry != 1:
            findings.append(
                Finding(
                    rule="single-fusion",
                    path=f"hlo:{label}",
                    line=0,
                    message=(
                        f"{family}: whole-plan-fusion contract expects ONE "
                        f"executable (1 HloModule / 1 ENTRY), compiled text "
                        f"has {n_mod} module(s) / {n_entry} entry computation(s)"
                    ),
                    detail={"family": family, "modules": n_mod, "entries": n_entry},
                )
            )
    active = {name for name in contract.forbid}
    for name, pat, desc in FORBIDDEN_PATTERNS:
        if name not in active:
            continue
        m = pat.search(hlo_text)
        if m:
            findings.append(
                Finding(
                    rule=f"forbidden-op:{name}",
                    path=f"hlo:{label}",
                    line=0,
                    message=f"{family}: {desc} (matched {m.group(0)!r})",
                    detail={"family": family, "match": m.group(0)},
                )
            )
    return findings


def assert_contract(family: str, hlo_text: str, program: str = "") -> None:
    """Rule-engine flavor of the old ``assert_collectives``: raise
    AssertionError listing every violation."""
    findings = verify_hlo(family, hlo_text, program)
    assert not findings, "HLO contract violations:\n" + "\n".join(
        f.render() for f in findings
    )


# --------------------------------------------------------------------------
# Runtime hook: verify at program-cache-fill time
# --------------------------------------------------------------------------

#: module-level default for call sites with no session conf in reach (the
#: index-build exchange); the most recently constructed Session's conf wins,
#: same stance as exec/io.py's decode-thread pool width.
_default_enabled = False

_VERIFIED_SEEN: set = set()
_SEEN_LOCK = threading.Lock()
_VIOLATIONS: List[Finding] = []

_CONF_KEY = "hyperspace.check.hlo.enabled"


def set_default_enabled(on: bool) -> None:
    global _default_enabled
    _default_enabled = bool(on)


def reset_runtime_state() -> None:
    """Forget which executables were verified and the violation log (tests)."""
    with _SEEN_LOCK:
        _VERIFIED_SEEN.clear()
        del _VIOLATIONS[:]


def runtime_violations() -> List[Finding]:
    with _SEEN_LOCK:
        return list(_VIOLATIONS)


def _enabled(conf) -> bool:
    if conf is None:
        return _default_enabled
    return bool(conf.get(_CONF_KEY))


def maybe_verify(conf, family: str, key, jitted, args, kwargs=None) -> None:
    """Verify ``jitted``'s compiled HLO for ``args`` against ``family``'s
    contract — once per (program-cache key, shape signature), only when
    ``hyperspace.check.hlo.enabled`` (or the module default, for sites with
    no conf in reach) is on.

    Violations are counted in ``hs_check_violations_total{rule,program}``,
    kept readable via :func:`runtime_violations`, and surfaced as a warning —
    never an exception: a production query must not die because a compiler
    upgrade re-shaped its HLO; the metric is the alarm.
    """
    if not _enabled(conf):
        return
    import jax

    sig = tuple(
        tuple(a.shape) if hasattr(a, "shape") else repr(type(a))
        for a in jax.tree_util.tree_leaves((args, kwargs or {}))
    )
    seen_key = (key, sig)
    with _SEEN_LOCK:
        if seen_key in _VERIFIED_SEEN:
            return
        _VERIFIED_SEEN.add(seen_key)
    try:
        text = hlo_text_of(jitted, *args, **(kwargs or {}))
    except Exception as exc:  # lowering quirks must not take the query down
        warnings.warn(f"hscheck: could not lower {family} program for verification: {exc}")
        return
    findings = verify_hlo(family, text, program=str(key))
    from hyperspace_tpu.obs.metrics import REGISTRY

    REGISTRY.counter(
        "hs_check_programs_verified_total",
        "Compiled executables verified against their declared HLO contract",
        program=family,
    ).inc()
    for f in findings:
        REGISTRY.counter(
            "hs_check_violations_total",
            "Program-contract and invariant violations detected by hscheck",
            rule=f.rule,
            program=family,
        ).inc()
        warnings.warn(f"hscheck HLO contract violation: {f.render()}")
    if findings:
        with _SEEN_LOCK:
            _VIOLATIONS.extend(findings)
