"""AST codebase lint: repo contracts and past-bug patterns as rules.

Each rule lives in :mod:`hyperspace_tpu.check.rules` and receives a
:class:`LintContext` — parsed ASTs for every file in scope plus the doc
texts and the registered conf-key set — and returns Findings. The default
scope is the package tree plus the repo-root drivers (``bench.py``,
``__graft_entry__.py``); tests and fixtures are deliberately outside it
(seeded-violation fixtures MUST fire when pointed at directly, and must not
fail the repo run).

Suppression: a line containing ``# hscheck: disable=<rule>`` (or a bare
``# hscheck: disable``) suppresses findings anchored to that line — for the
rare site where the flagged pattern is the point (e.g. a lock whose purpose
is serializing file IO). Every suppression is visible in the diff, which is
the point.
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

from hyperspace_tpu.check.findings import Finding

_PRAGMA = "# hscheck: disable"


def default_root() -> str:
    """The repo root: parent of the installed package directory."""
    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return os.path.dirname(pkg)


def default_paths(root: str) -> List[str]:
    """Lint scope: every package .py plus the repo-root driver scripts."""
    out: List[str] = []
    pkg = os.path.join(root, "hyperspace_tpu")
    for dirpath, dirnames, filenames in os.walk(pkg):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for f in sorted(filenames):
            if f.endswith(".py"):
                out.append(os.path.join(dirpath, f))
    for extra in ("bench.py", "__graft_entry__.py"):
        p = os.path.join(root, extra)
        if os.path.exists(p):
            out.append(p)
    return out


@dataclass
class LintContext:
    root: str
    files: List[str]
    #: True when linting the whole default scope. The bidirectional doc-drift
    #: directions (registered-but-undocumented / documented-but-unregistered)
    #: only make sense against the full tree — on an explicit file list every
    #: documented family would look unregistered — so rules gate them on this.
    full_scope: bool = True
    _sources: Dict[str, str] = field(default_factory=dict)
    _asts: Dict[str, ast.Module] = field(default_factory=dict)
    _docs: Optional[Dict[str, str]] = None

    def source(self, path: str) -> str:
        got = self._sources.get(path)
        if got is None:
            with open(path, encoding="utf-8") as f:
                got = self._sources[path] = f.read()
        return got

    def ast_of(self, path: str) -> ast.Module:
        got = self._asts.get(path)
        if got is None:
            got = self._asts[path] = ast.parse(self.source(path), filename=path)
        return got

    def relpath(self, path: str) -> str:
        rel = os.path.relpath(path, self.root)
        return path if rel.startswith("..") else rel

    @property
    def docs(self) -> Dict[str, str]:
        """{repo-relative path: text} for every markdown doc the drift rules
        read (docs/*.md + README.md). Missing files read as empty."""
        if self._docs is None:
            self._docs = {}
            docs_dir = os.path.join(self.root, "docs")
            if os.path.isdir(docs_dir):
                for f in sorted(os.listdir(docs_dir)):
                    if f.endswith(".md"):
                        p = os.path.join(docs_dir, f)
                        with open(p, encoding="utf-8") as fh:
                            self._docs[os.path.join("docs", f)] = fh.read()
            readme = os.path.join(self.root, "README.md")
            if os.path.exists(readme):
                with open(readme, encoding="utf-8") as fh:
                    self._docs["README.md"] = fh.read()
        return self._docs

    def doc(self, rel: str) -> str:
        return self.docs.get(rel, "")

    @property
    def registered_conf_keys(self) -> set:
        from hyperspace_tpu import config

        return {
            v
            for k, v in vars(config.keys).items()
            if not k.startswith("_") and isinstance(v, str)
        }

    def suppressed(self, path: str, line: int, rule: str) -> bool:
        if line <= 0:
            return False
        lines = self.source(path).splitlines()
        if line > len(lines):
            return False
        text = lines[line - 1]
        i = text.find(_PRAGMA)
        if i < 0:
            return False
        rest = text[i + len(_PRAGMA):].strip()
        if not rest.startswith("="):
            return True  # bare disable: everything on this line
        names = {n.strip() for n in rest[1:].split(",")}
        return rule in names


def run_lint(
    root: Optional[str] = None,
    paths: Optional[Sequence[str]] = None,
    rules: Optional[Sequence[str]] = None,
) -> List[Finding]:
    """Run the named rules (default: all) over ``paths`` (default: the
    package scope) and return pragma-filtered findings sorted by location."""
    from hyperspace_tpu.check.rules import all_rules

    root = root or default_root()
    file_list = [os.path.abspath(p) for p in paths] if paths else default_paths(root)
    ctx = LintContext(root=root, files=file_list, full_scope=paths is None)
    selected = all_rules()
    if rules:
        unknown = set(rules) - set(selected)
        if unknown:
            raise KeyError(f"unknown lint rules: {sorted(unknown)} (have: {sorted(selected)})")
        selected = {k: v for k, v in selected.items() if k in rules}
    findings: List[Finding] = []
    for name in sorted(selected):
        for f in selected[name].check(ctx):
            abspath = os.path.join(ctx.root, f.path)
            if os.path.exists(abspath) and ctx.suppressed(abspath, f.line, f.rule):
                continue
            findings.append(f)
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings
