"""The one result type every check pass emits."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict


@dataclass(frozen=True)
class Finding:
    """A single violation: which rule fired, where, and why.

    ``path`` is repo-relative for AST findings and a program label (e.g.
    ``hlo:grouped-agg-chunk``) for compiled-program findings; ``line`` is 0
    when a finding has no meaningful source line (doc drift, HLO contracts).
    """

    rule: str
    path: str
    line: int
    message: str
    detail: Dict[str, Any] = field(default_factory=dict, compare=False)

    def render(self) -> str:
        loc = f"{self.path}:{self.line}" if self.line else self.path
        return f"{loc}: [{self.rule}] {self.message}"

    def as_dict(self) -> Dict[str, Any]:
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "message": self.message,
            "detail": dict(self.detail),
        }
