"""Runtime lock-order watcher (default-off, ``hyperspace.check.locks``).

The serving/obs layer is a seven-module thread soup (admission queue, plan
cache, bucket-prefetch LRU, result cache, scheduler, profile history, metrics
registry) where every module owns a mutex. Individual modules are careful,
but lock-ORDER hazards only exist across modules, where no one test looks.
This watcher records the cross-thread lock acquisition graph while real
workloads run (the existing concurrency stress tests) and reports cycles —
the necessary condition for ABBA deadlock.

Zero-overhead stance: locks are created through :func:`named_lock`, which
returns a plain ``threading.Lock`` unless the watcher was enabled FIRST
(``watcher.enable()``, or a ``Session`` constructed with
``hyperspace.check.locks`` true). Instrumentation is opt-in per process and
decided at lock construction, so the default path adds nothing — not even an
``if`` — to acquire/release.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Set, Tuple


class LockWatcher:
    """Records held-before edges between named locks across all threads."""

    def __init__(self):
        self._enabled = False
        self._graph_lock = threading.Lock()
        # (held, acquiring) -> count of observations
        self._edges: Dict[Tuple[str, str], int] = {}
        self._held = threading.local()

    # -- lifecycle -----------------------------------------------------------
    def enable(self) -> None:
        self._enabled = True

    def disable(self) -> None:
        self._enabled = False

    @property
    def enabled(self) -> bool:
        return self._enabled

    def reset(self) -> None:
        with self._graph_lock:
            self._edges.clear()

    # -- recording -----------------------------------------------------------
    def _held_stack(self) -> List[str]:
        stack = getattr(self._held, "stack", None)
        if stack is None:
            stack = self._held.stack = []
        return stack

    def note_acquired(self, name: str) -> None:
        stack = self._held_stack()
        if stack:
            edges = [(h, name) for h in stack if h != name]
            if edges:
                with self._graph_lock:
                    for e in edges:
                        self._edges[e] = self._edges.get(e, 0) + 1
        stack.append(name)

    def note_released(self, name: str) -> None:
        stack = self._held_stack()
        # remove the innermost matching hold (re-entrant same-name nesting of
        # DISTINCT lock objects is legal; pop the right frame)
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] == name:
                del stack[i]
                return

    # -- reporting -----------------------------------------------------------
    def edges(self) -> Dict[Tuple[str, str], int]:
        with self._graph_lock:
            return dict(self._edges)

    def cycles(self) -> List[List[str]]:
        """Elementary cycles in the held-before graph — each is a potential
        ABBA deadlock (lock A held while taking B on one thread, B held while
        taking A on another). Deduplicated by rotation."""
        with self._graph_lock:
            adj: Dict[str, Set[str]] = {}
            for a, b in self._edges:
                adj.setdefault(a, set()).add(b)
        out: List[List[str]] = []
        seen: Set[Tuple[str, ...]] = set()

        def dfs(start: str, node: str, path: List[str], visited: Set[str]):
            for nxt in adj.get(node, ()):
                if nxt == start:
                    cyc = path[:]
                    k = min(range(len(cyc)), key=lambda i: cyc[i])
                    canon = tuple(cyc[k:] + cyc[:k])
                    if canon not in seen:
                        seen.add(canon)
                        out.append(list(canon))
                elif nxt not in visited and nxt > start:
                    # only explore nodes ordered after start: each cycle is
                    # found exactly once, from its smallest member
                    visited.add(nxt)
                    dfs(start, nxt, path + [nxt], visited)
                    visited.discard(nxt)

        for start in sorted(adj):
            dfs(start, start, [start], {start})
        return out

    def report(self) -> List[List[str]]:
        """Cycles, also counted into ``hs_check_violations_total`` so a
        scrape sees lock-order hazards the same way it sees HLO ones."""
        cycs = self.cycles()
        if cycs:
            from hyperspace_tpu.obs.metrics import REGISTRY

            for c in cycs:
                REGISTRY.counter(
                    "hs_check_violations_total",
                    "Program-contract and invariant violations detected by hscheck",
                    rule="lock-order-cycle",
                    program=" -> ".join(c + [c[0]]),
                ).inc()
        return cycs


#: process-wide watcher instance
watcher = LockWatcher()


class WatchedLock:
    """A ``threading.Lock`` that reports acquire/release to the watcher.
    Supports the context-manager and acquire/release protocols the serving
    and obs modules use; it is NOT suitable as a Condition's underlying lock
    (``Condition.wait`` releases behind the wrapper's back)."""

    __slots__ = ("_inner", "name")

    def __init__(self, name: str):
        self._inner = threading.Lock()
        self.name = name

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        got = self._inner.acquire(blocking, timeout)
        if got:
            watcher.note_acquired(self.name)
        return got

    def release(self) -> None:
        watcher.note_released(self.name)
        self._inner.release()

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()


def named_lock(name: str):
    """The serving/obs lock constructor: a plain ``threading.Lock`` when the
    watcher is off (the default — zero added overhead), a :class:`WatchedLock`
    when it was enabled before construction. Enabling mid-run only affects
    locks created afterwards; stress harnesses enable first, then build the
    server."""
    if watcher.enabled:
        return WatchedLock(name)
    return threading.Lock()
