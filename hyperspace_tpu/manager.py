"""Index collection management.

``IndexCollectionManager`` routes each API call to the right Action with
per-index log/data managers (ref: HS/index/IndexCollectionManager.scala:28-196);
``CachingIndexCollectionManager`` adds a TTL cache of all log entries,
invalidated by any mutating call
(ref: HS/index/CachingIndexCollectionManager.scala:38-173).
"""

from __future__ import annotations

from typing import List, Optional

from hyperspace_tpu import config as C
from hyperspace_tpu.actions.base import HyperspaceActionException
from hyperspace_tpu.actions.create import CreateAction
from hyperspace_tpu.actions.maintenance import CancelAction, DeleteAction, RestoreAction, VacuumAction
from hyperspace_tpu.models import states
from hyperspace_tpu.models.data_manager import IndexDataManager, IndexDataManagerFactory
from hyperspace_tpu.models.log_entry import IndexLogEntry
from hyperspace_tpu.models.log_manager import IndexLogManager, IndexLogManagerFactory
from hyperspace_tpu.models.path_resolver import PathResolver
from hyperspace_tpu.lifecycle.snapshot import current_snapshot
from hyperspace_tpu.utils.cache import TTLCache


class IndexCollectionManager:
    def __init__(
        self,
        session,
        log_manager_factory: Optional[IndexLogManagerFactory] = None,
        data_manager_factory: Optional[IndexDataManagerFactory] = None,
    ):
        self.session = session
        self.path_resolver = PathResolver(session.conf)
        self.log_factory = log_manager_factory or IndexLogManagerFactory()
        self.data_factory = data_manager_factory or IndexDataManagerFactory()

    def _managers(self, name: str):
        path = self.path_resolver.get_index_path(name)
        return self.log_factory.create(path), self.data_factory.create(path), path

    # --- mutations (ref: IndexCollectionManager.scala:36-101) --------------
    def create(self, df, index_config) -> IndexLogEntry:
        log_m, data_m, path = self._managers(index_config.index_name)
        return CreateAction(self.session, df, index_config, log_m, data_m, path).run()

    def delete(self, name: str) -> IndexLogEntry:
        log_m, data_m, _ = self._managers(name)
        return DeleteAction(self.session, name, log_m, data_m).run()

    def restore(self, name: str) -> IndexLogEntry:
        log_m, data_m, _ = self._managers(name)
        return RestoreAction(self.session, name, log_m, data_m).run()

    def vacuum(self, name: str) -> IndexLogEntry:
        log_m, data_m, _ = self._managers(name)
        return VacuumAction(self.session, name, log_m, data_m).run()

    def cancel(self, name: str) -> IndexLogEntry:
        log_m, data_m, _ = self._managers(name)
        return CancelAction(self.session, name, log_m, data_m).run()

    def refresh(self, name: str, mode: str = C.REFRESH_MODE_FULL) -> IndexLogEntry:
        from hyperspace_tpu.actions.refresh import (
            RefreshFullAction,
            RefreshIncrementalAction,
            RefreshQuickAction,
        )

        log_m, data_m, _ = self._managers(name)
        mode = mode.lower()
        if mode == C.REFRESH_MODE_FULL:
            action = RefreshFullAction(self.session, name, log_m, data_m)
        elif mode == C.REFRESH_MODE_INCREMENTAL:
            action = RefreshIncrementalAction(self.session, name, log_m, data_m)
        elif mode == C.REFRESH_MODE_QUICK:
            action = RefreshQuickAction(self.session, name, log_m, data_m)
        else:
            raise HyperspaceActionException(f"Unsupported refresh mode {mode!r}")
        return action.run()

    def optimize(self, name: str, mode: str = C.OPTIMIZE_MODE_QUICK) -> IndexLogEntry:
        from hyperspace_tpu.actions.optimize import OptimizeAction

        log_m, data_m, _ = self._managers(name)
        if mode.lower() not in C.OPTIMIZE_MODES:
            raise HyperspaceActionException(f"Unsupported optimize mode {mode!r}")
        return OptimizeAction(self.session, name, log_m, data_m, mode.lower()).run()

    # --- reads (ref: IndexCollectionManager.scala indexes) -----------------
    # Both reads consult the lifecycle snapshot pin first: inside a
    # snapshot_scope every roster resolution returns the version captured at
    # admission, so a refresh committing mid-flight cannot change a running
    # query's answer (lifecycle/snapshot.py has the invariant).
    def get_index(self, name: str) -> Optional[IndexLogEntry]:
        pin = current_snapshot()
        if pin is not None:
            return pin.get_index(name)
        log_m, _, _ = self._managers(name)
        return log_m.get_latest_stable_log()

    def get_indexes(self, accepted_states: Optional[List[str]] = None) -> List[IndexLogEntry]:
        pin = current_snapshot()
        if pin is not None:
            return pin.get_indexes(accepted_states)
        accepted = set(accepted_states or states.STABLE_STATES)
        out = []
        for path in self.path_resolver.all_index_paths():
            entry = self.log_factory.create(path).get_latest_stable_log()
            if entry is not None and entry.state in accepted:
                out.append(entry)
        return out

    def index_stats(self, name: str, extended: bool = False):
        from hyperspace_tpu.stats import index_statistics

        entry = self.get_index(name)
        if entry is None:
            raise HyperspaceActionException(f"Index {name!r} does not exist.")
        return index_statistics(self.session, entry, extended)

    def indexes(self):
        """Summary of all indexes as a pandas DataFrame; vacuumed
        (DOESNOTEXIST) entries are filtered out
        (ref: IndexCollectionManager.scala:109-118)."""
        import pandas as pd

        from hyperspace_tpu.stats import index_statistics

        rows = [
            index_statistics(self.session, e, False)
            for e in self.get_indexes(list(states.STABLE_STATES))
            if e.state != states.DOESNOTEXIST
        ]
        return pd.DataFrame(rows)


class CachingIndexCollectionManager(IndexCollectionManager):
    """TTL cache over get_indexes (default 300 s), invalidated on any
    mutating API (ref: HS/index/CachingIndexCollectionManager.scala:38-126)."""

    def __init__(self, session, **kwargs):
        super().__init__(session, **kwargs)
        self._cache: TTLCache = TTLCache(lambda: self.session.conf.cache_expiry_seconds)

    def clear_cache(self) -> None:
        self._cache.clear()

    def get_indexes(self, accepted_states: Optional[List[str]] = None) -> List[IndexLogEntry]:
        # pin check BEFORE the TTL cache: a pinned request must not read the
        # cache (its version may be newer than the pin) and, worse, a cache
        # miss under a pin would store the *pinned* roster for everyone else
        pin = current_snapshot()
        if pin is not None:
            return pin.get_indexes(accepted_states)
        cached = self._cache.get()
        if cached is None:
            cached = super().get_indexes(list(states.STABLE_STATES))
            self._cache.set(cached)
        accepted = set(accepted_states or states.STABLE_STATES)
        return [e for e in cached if e.state in accepted]

    def _invalidating(self, fn, *args, **kwargs):
        try:
            return fn(*args, **kwargs)
        finally:
            self.clear_cache()

    # --- lifecycle commit publication --------------------------------------
    def _pre_mutation_entry(self, name):
        """The entry as it stands before a mutation — read straight from the
        log (not the TTL cache, not any snapshot pin) so the commit event
        names exactly the files the mutation superseded."""
        try:
            log_m, _, _ = self._managers(name)
            return log_m.get_latest_stable_log()
        except Exception:
            return None

    def _publish_commit(self, kind, name, old, new):
        """Publish one CommitEvent on the session bus after a successful
        mutation. Affected files = the previous entry's index data files
        (superseded/rewritten) + source files the commit dropped from
        coverage — the set whose cached derivatives are now stale."""
        affected = []
        try:
            if old is not None:
                affected.extend(old.content.files)
                old_src = {fi.name for fi in old.source_file_infos()}
                new_src = (
                    {fi.name for fi in new.source_file_infos()}
                    if new is not None
                    else set()
                )
                affected.extend(sorted(old_src - new_src))
        except Exception:
            affected = []  # defensive: a malformed entry must not fail the commit
        from hyperspace_tpu.lifecycle.invalidation import CommitEvent

        event = CommitEvent(name, getattr(new, "id", None), kind, affected)
        self.session.lifecycle_bus.publish(event)

    def _published(self, kind, name, fn, *args, **kwargs):
        old = self._pre_mutation_entry(name)
        entry = self._invalidating(fn, *args, **kwargs)
        # only successful mutations publish: an exception above (including
        # NoChangesException from an idempotent refresh retry) propagates
        # before any event is emitted, so commit_seq counts real commits
        self._publish_commit(kind, name, old, entry)
        return entry

    def create(self, df, index_config):
        return self._published("create", index_config.index_name, super().create, df, index_config)

    def delete(self, name):
        return self._published("delete", name, super().delete, name)

    def restore(self, name):
        return self._published("restore", name, super().restore, name)

    def vacuum(self, name):
        return self._published("vacuum", name, super().vacuum, name)

    def cancel(self, name):
        return self._published("cancel", name, super().cancel, name)

    def refresh(self, name, mode=C.REFRESH_MODE_FULL):
        return self._published(f"refresh-{mode}", name, super().refresh, name, mode)

    def optimize(self, name, mode=C.OPTIMIZE_MODE_QUICK):
        return self._published(f"optimize-{mode}", name, super().optimize, name, mode)
