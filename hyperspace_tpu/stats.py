"""Index statistics (ref: HS/index/IndexStatistics.scala:41-96)."""

from __future__ import annotations

from typing import Any, Dict

from hyperspace_tpu.models.log_entry import IndexLogEntry


def _index_location(entry: IndexLogEntry, infos) -> str:
    """Common directory of the index's data files (after incremental refresh
    the content can span several v__=N version dirs; their parent is the
    index root — ref: IndexStatistics commonPrefix, IndexStatistics.scala:70-96)."""
    import os

    if not infos:
        return entry.content.root.name
    return os.path.commonpath([os.path.dirname(fi.name) for fi in infos])


def index_statistics(session, entry: IndexLogEntry, extended: bool = False) -> Dict[str, Any]:
    infos = entry.content.file_infos()
    row: Dict[str, Any] = {
        "name": entry.name,
        "indexedColumns": entry.derived_dataset.properties.get("indexedColumns", []),
        "includedColumns": entry.derived_dataset.properties.get("includedColumns", []),
        "numBuckets": entry.derived_dataset.properties.get("numBuckets"),
        "schema": entry.derived_dataset.properties.get("schemaJson", ""),
        "indexLocation": _index_location(entry, infos),
        "state": entry.state,
        "kind": entry.kind,
    }
    if extended:
        row.update(
            {
                "numIndexFiles": len(infos),
                "sizeInBytes": entry.content.total_size,
                "logVersion": entry.id,
                "appendedFiles": [f.name for f in entry.appended_files()],
                "deletedFiles": [f.name for f in entry.deleted_files()],
                "indexContentPaths": entry.content.files,
            }
        )
    return row
