"""hyperspace_tpu — a TPU-native data-lake indexing framework.

A brand-new framework with the capabilities of Microsoft Hyperspace (reference:
``/root/reference``, Scala/Spark): users create *indexes* (derived datasets) over
Parquet/Delta data-lake files, index data plus a versioned operation log live on
storage next to the data, and a query optimizer transparently rewrites filter and
equi-join plans to scan pre-bucketed, pre-sorted index data instead of source files.

Unlike the reference, the execution substrate is JAX/XLA on TPU: hash-bucketing
lowers to on-device hashing + all-to-all over ICI, sorting to ``jax.lax.sort``,
bucketed joins run shuffle-free per device shard, and bucket-union is a
sharding-preserving concatenation.

Layer map (mirrors SURVEY.md §1):
  - ``models/``    metadata model + operation-log persistence   (ref: HS/index/IndexLogEntry.scala)
  - ``sources/``   pluggable source providers                   (ref: HS/index/sources/)
  - ``plan/``      relational IR, expressions, DataFrame API    (ref: Spark Catalyst, subset)
  - ``indexes/``   index implementations (covering, skipping)   (ref: HS/index/covering, dataskipping)
  - ``actions/``   lifecycle actions FSM                        (ref: HS/actions/)
  - ``rules/``     optimizer integration, plan rewriting        (ref: HS/index/rules/)
  - ``ops/``       TPU compute kernels (hash, sort, join, scan)
  - ``parallel/``  device mesh / sharding layer                 (replaces Spark shuffle)
  - ``exec/``      physical execution of (rewritten) plans
  - ``analysis/``  explain / whyNot introspection               (ref: HS/index/plananalysis/)
  - ``telemetry/`` structured event taxonomy                    (ref: HS/telemetry/)
"""

import os as _os

# Persistent XLA compilation cache: index builds re-run the same fused sort
# program per size class across processes; without this every fresh process
# pays a tens-of-seconds TPU compile. Opt out with HS_JAX_CACHE_DIR="".
_cache_dir = _os.environ.get(
    "HS_JAX_CACHE_DIR", _os.path.join(_os.path.expanduser("~"), ".cache", "hyperspace_tpu", "xla")
)
if _cache_dir and not _os.environ.get("JAX_COMPILATION_CACHE_DIR"):
    try:
        import jax as _jax

        # respect a cache dir the user already configured programmatically
        if not _jax.config.jax_compilation_cache_dir:
            _jax.config.update("jax_compilation_cache_dir", _cache_dir)
            _jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:  # pragma: no cover - cache is best-effort
        pass

from hyperspace_tpu.version import __version__
from hyperspace_tpu.config import HyperspaceConf, keys
from hyperspace_tpu.session import Session, get_session, set_session
from hyperspace_tpu.plan.expr import col, lit, input_file_name
from hyperspace_tpu.plan.dataframe import DataFrame
from hyperspace_tpu.indexes.covering import CoveringIndexConfig
from hyperspace_tpu.indexes.dataskipping import (
    DataSkippingIndexConfig,
    MinMaxSketch,
    BloomFilterSketch,
    ValueListSketch,
)
from hyperspace_tpu.hyperspace import Hyperspace
from hyperspace_tpu.serving import AdmissionRejected, QueryServer, RequestTimeout

__all__ = [
    "__version__",
    "HyperspaceConf",
    "keys",
    "Session",
    "get_session",
    "set_session",
    "col",
    "lit",
    "input_file_name",
    "DataFrame",
    "CoveringIndexConfig",
    "DataSkippingIndexConfig",
    "MinMaxSketch",
    "BloomFilterSketch",
    "ValueListSketch",
    "Hyperspace",
    "QueryServer",
    "AdmissionRejected",
    "RequestTimeout",
]
