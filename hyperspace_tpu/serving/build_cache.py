"""Shared join build sides: version-branded hash tables under serving.

Micro-batched requests joining against the same dimension table should pay
ONE device hash-table build, not one per request. This cache keys built
broadcast sides (``exec/join_stream.BuildSide``) by (build-plan identity,
data-version brand) in a byte-budgeted LRU next to ``bucket_cache.py``.

Staleness follows ``result_cache.py``'s discipline exactly: the brand is
:func:`~hyperspace_tpu.serving.result_cache.version_brand` over the build
plan, computed by the caller per lookup, and the first observation of a new
brand for a structure purges the structure's stale-version entries wholesale
(counted in ``hs_join_build_cache_invalidations_total``). An unsignable
build plan gets no brand and bypasses the cache — a stale build side is
never an option.

The builder runs OUTSIDE the cache lock: a build executes a whole plan
(scan locks, device compiles), and holding ``serving.joinBuildCache``
across that would pin a broad lock order. Two racing requests may both
build; the second put wins harmlessly — the same tolerance the bucket
cache extends to racing prefetches.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Callable, Dict, List, Tuple

from hyperspace_tpu.check.locks import named_lock


# metric names are literal at each call site so the hscheck metric-families
# drift rule can match them against docs/observability.md


def _count_hit() -> None:
    from hyperspace_tpu.obs.metrics import REGISTRY

    REGISTRY.counter(
        "hs_join_build_cache_hits_total",
        "broadcast-join build sides served from the shared cache",
    ).inc()


def _count_miss() -> None:
    from hyperspace_tpu.obs.metrics import REGISTRY

    REGISTRY.counter(
        "hs_join_build_cache_misses_total",
        "broadcast-join build sides built because the shared cache missed",
    ).inc()


def _count_invalidations(n: int) -> None:
    from hyperspace_tpu.obs.metrics import REGISTRY

    REGISTRY.counter(
        "hs_join_build_cache_invalidations_total",
        "build sides purged because a new data-version brand was observed",
    ).inc(n)


class _Entry:
    __slots__ = ("value", "nbytes", "structure", "brand")

    def __init__(self, value, nbytes: int, structure, brand: str):
        self.value = value
        self.nbytes = int(nbytes)
        self.structure = structure
        self.brand = brand


class JoinBuildCache:
    """Byte-budgeted LRU of built join build sides with brand invalidation."""

    def __init__(self, max_bytes: int = 256 * 1024 * 1024):
        self.max_bytes = int(max_bytes)
        self._lock = named_lock("serving.joinBuildCache")
        self._entries: "OrderedDict[Tuple, _Entry]" = OrderedDict()
        # structure -> {brand -> [keys]}: a new brand purges the structure's
        # entries under every other (stale) brand
        self._by_struct: Dict[object, Dict[str, List[Tuple]]] = {}
        self.bytes = 0
        self.hits = 0
        self.misses = 0
        self.invalidations = 0
        self.evictions = 0

    def get_or_build(
        self,
        structure,
        brand: str,
        builder: Callable[[], object],
        weigh: Callable[[object], int],
    ):
        """The cached build side for (structure, brand), or ``builder()``'s
        result, cached. ``weigh`` prices a freshly built value in bytes."""
        key = (structure, brand)
        with self._lock:
            self._note_brand_locked(structure, brand)
            entry = self._entries.get(key)
            if entry is not None:
                self._entries.move_to_end(key)
                self.hits += 1
            else:
                self.misses += 1
        if entry is not None:
            _count_hit()
            return entry.value
        _count_miss()
        value = builder()
        nbytes = int(weigh(value))
        if nbytes > self.max_bytes:
            return value  # over budget: serve it, don't cache it
        entry = _Entry(value, nbytes, structure, brand)
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self.bytes -= old.nbytes
            self._entries[key] = entry
            self.bytes += nbytes
            keys = self._by_struct.setdefault(structure, {}).setdefault(brand, [])
            if key not in keys:
                keys.append(key)
            while self.bytes > self.max_bytes and self._entries:
                k, e = self._entries.popitem(last=False)
                self.bytes -= e.nbytes
                self.evictions += 1
                self._unindex_locked(k, e)
        return value

    # -- invalidation --------------------------------------------------------
    def _note_brand_locked(self, structure, brand: str) -> None:
        brands = self._by_struct.get(structure)
        if not brands:
            return
        stale = [b for b in brands if b != brand]
        purged = 0
        for b in stale:
            for k in brands.pop(b):
                e = self._entries.pop(k, None)
                if e is not None:
                    self.bytes -= e.nbytes
                    purged += 1
        if purged:
            self.invalidations += purged
            _count_invalidations(purged)

    def _unindex_locked(self, key: Tuple, entry: _Entry) -> None:
        brands = self._by_struct.get(entry.structure)
        if brands is not None:
            keys = brands.get(entry.brand)
            if keys is not None and key in keys:
                keys.remove(key)

    def clear(self) -> int:
        with self._lock:
            n = len(self._entries)
            self._entries.clear()
            self._by_struct.clear()
            self.bytes = 0
            return n

    # -- observability -------------------------------------------------------
    def bind_registry(self, registry, **labels) -> None:
        registry.gauge(
            "hs_join_build_cache_bytes", "bytes resident in the join build cache",
            fn=lambda: self.bytes, **labels,
        )
        registry.gauge(
            "hs_join_build_cache_entries", "build sides resident in the join build cache",
            fn=self.__len__, **labels,
        )

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def stats(self) -> dict:
        with self._lock:
            total = self.hits + self.misses
            return {
                "entries": len(self._entries),
                "bytes": self.bytes,
                "capBytes": self.max_bytes,
                "hits": self.hits,
                "misses": self.misses,
                "invalidations": self.invalidations,
                "evictions": self.evictions,
                "hitRate": (self.hits / total) if total else 0.0,
            }
