"""Semantic result cache: version-branded query results above the plan cache.

The plan cache proved repeated-*shape* traffic dominates served workloads;
this layer closes the loop for repeated-*result* traffic. A byte-budgeted LRU
keyed by (version brand, plan fingerprint, literal bindings) serves:

- **exact hits** — the same query (same structure, same literals) against the
  same data version returns the cached batch without touching the executor;
- **subsumed-predicate hits** — a request whose predicate provably *implies*
  a cached superset predicate (``price > 7`` against a cached ``price > 5``)
  re-filters the cached batch instead of re-scanning. Subsumption is only
  attempted on simple Project/Filter chains over one scan leaf whose
  conjuncts are all column-vs-literal comparisons (``plan.expr
  comparison_atom``); anything else is exact-only — conservatism over reach.

**Staleness is impossible by construction.** The brand —
:func:`version_brand` — folds the session's compilation token (hyperspace
flag + ACTIVE index name/log-version roster + rewrite conf) with every scan
leaf's source-snapshot ``relation.signature()`` (file path/mtime/size
digest). It is computed at *submit time*, before the request is admitted, and
both ``get`` and ``put`` key on it: a result can only be served to a request
whose observed data version matches the version the result was computed
from. A refresh committing a new index-log version (or files
appearing/changing under a source) changes the brand, so stale entries
become unreachable immediately — and are purged wholesale (counted in
``hs_result_cache_invalidations_total``) the first time the new brand is
observed for that structure. An unsignable source yields brand ``None`` and
the request bypasses the cache entirely.
"""

from __future__ import annotations

import hashlib
import threading
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from hyperspace_tpu.plan import logical as L
from hyperspace_tpu.plan.expr import as_bool_mask, comparison_atom, split_conjuncts
from hyperspace_tpu.serving.fingerprint import Fingerprint, _lit_token

from hyperspace_tpu.check.locks import named_lock

__all__ = ["ResultCache", "version_brand", "chain_atoms", "atoms_imply"]


def version_brand(session, plan, enabled: bool) -> Optional[str]:
    """Hash of everything that decides *which data version* ``plan`` reads:
    the session compilation token (hyperspace flag, ACTIVE index name + log
    id roster, rewrite conf) plus each raw scan leaf's source snapshot
    signature. None when any leaf cannot be signed — the caller must then
    bypass the cache (serving possibly-stale bytes is never an option)."""
    from hyperspace_tpu.serving.plan_cache import session_token

    token = session_token(session, enabled)
    sigs: List[str] = []
    for leaf in L.collect(plan, lambda p: isinstance(p, L.Scan)):
        try:
            sigs.append(str(leaf.relation.signature()))
        except Exception:
            return None
    h = hashlib.sha1(repr((token, sorted(sigs))).encode()).hexdigest()
    return h


def chain_atoms(plan) -> Optional[Tuple[List, List]]:
    """``(filter conditions, normalized atoms)`` when ``plan`` is a simple
    Project*/Filter* chain over one scan leaf whose every conjunct is a
    column-vs-literal comparison; None otherwise (no subsumption — Rename,
    Compute, joins, aggregates, and opaque predicates are out of scope)."""
    conds = []
    p = plan
    while True:
        if isinstance(p, L.Project):
            p = p.child
        elif isinstance(p, L.Filter):
            conds.append(p.condition)
            p = p.child
        elif isinstance(p, (L.Scan, L.IndexScan, L.FileScan)):
            break
        else:
            return None
    atoms = []
    for c in conds:
        for conj in split_conjuncts(c):
            a = comparison_atom(conj)
            if a is None:
                return None
            atoms.append(a)
    return conds, atoms


def _implies(req, cached) -> bool:
    """Does request atom ``req`` imply cached atom ``cached`` (same column)?"""
    _, rop, rv = req
    _, cop, cv = cached
    try:
        if cop == ">":
            return (rop == ">" and rv >= cv) or (rop == ">=" and rv > cv)
        if cop == ">=":
            return rop in (">", ">=") and rv >= cv
        if cop == "<":
            return (rop == "<" and rv <= cv) or (rop == "<=" and rv < cv)
        if cop == "<=":
            return rop in ("<", "<=") and rv <= cv
        if cop == "=":
            return (rop == "=" and rv == cv) or (rop == "in" and rv <= {cv})
        if cop == "!=":
            return (rop == "!=" and rv == cv) or (rop == "=" and rv != cv)
        if cop == "in":
            return (rop == "=" and rv in cv) or (rop == "in" and rv <= cv)
    except TypeError:
        return False  # incomparable value types: no implication claimed
    return False


def atoms_imply(request_atoms: List, cached_atoms: List) -> bool:
    """True when the conjunction of ``request_atoms`` implies the conjunction
    of ``cached_atoms`` — i.e. the cached batch is a superset of the request's
    rows. Every cached atom must be implied by some request atom on the same
    column; extra request atoms only narrow further."""
    for cached in cached_atoms:
        if not any(req[0] == cached[0] and _implies(req, cached) for req in request_atoms):
            return False
    return True


def _batch_nbytes(batch: Dict[str, np.ndarray]) -> int:
    total = 0
    for a in batch.values():
        total += int(a.nbytes)
        if a.dtype == object:
            # nbytes counts pointers only; approximate the payload
            total += sum(len(str(v)) for v in a[: min(len(a), 1024)]) * max(
                1, len(a) // max(1, min(len(a), 1024))
            )
    return total


class _Entry:
    __slots__ = ("batch", "output_columns", "atoms", "nbytes", "structure", "brand")

    def __init__(self, batch, output_columns, atoms, nbytes, structure, brand):
        self.batch = batch
        self.output_columns = output_columns
        self.atoms = atoms
        self.nbytes = nbytes
        self.structure = structure
        self.brand = brand


class ResultCache:
    """Byte-budgeted LRU of served result batches with brand invalidation."""

    def __init__(
        self,
        max_bytes: int = 256 * 1024 * 1024,
        max_entry_bytes: int = 16 * 1024 * 1024,
        subsumption: bool = True,
    ):
        self.max_bytes = int(max_bytes)
        self.max_entry_bytes = int(max_entry_bytes)
        self.subsumption = bool(subsumption)
        self._lock = named_lock("serving.resultCache")
        self._entries: "OrderedDict[Tuple, _Entry]" = OrderedDict()
        # (structure) -> {brand -> [exact keys]} so a new brand can purge the
        # structure's stale-version entries wholesale
        self._by_struct: Dict[str, Dict[str, List[Tuple]]] = {}
        self.bytes = 0
        self.hits = 0
        self.misses = 0
        self.subsumed_hits = 0
        self.invalidations = 0
        self.evictions = 0
        self._hits_c = self._misses_c = self._sub_c = self._inv_c = None

    # -- keys ----------------------------------------------------------------
    @staticmethod
    def _key(brand: str, fp: Fingerprint) -> Tuple:
        return (brand, fp.structure, tuple(_lit_token(v) for v in fp.literals))

    # -- lookup --------------------------------------------------------------
    def get(self, fp: Fingerprint, brand: str, plan=None) -> Optional[Dict[str, np.ndarray]]:
        """The cached batch for this request (already relabeled to the
        request's output aliases), or None. ``plan`` (the raw request plan)
        enables subsumed-predicate matching."""
        key = self._key(brand, fp)
        with self._lock:
            self._note_brand_locked(fp.structure, brand)
            entry = self._entries.get(key)
            if entry is not None:
                self._entries.move_to_end(key)
                self.hits += 1
                if self._hits_c is not None:
                    self._hits_c.inc()
                return self._relabel(entry.batch, entry.output_columns, fp)
            candidates = []
            if self.subsumption and plan is not None:
                for k in self._by_struct.get(fp.structure, {}).get(brand, []):
                    e = self._entries.get(k)
                    if e is not None and e.atoms is not None:
                        candidates.append((k, e))
        if candidates:
            req = chain_atoms(plan)
            if req is not None:
                conds, request_atoms = req
                for k, e in candidates:
                    got = self._try_subsume(e, conds, request_atoms, fp)
                    if got is not None:
                        with self._lock:
                            if k in self._entries:
                                self._entries.move_to_end(k)
                            self.hits += 1
                            self.subsumed_hits += 1
                            if self._sub_c is not None:
                                self._sub_c.inc()
                            if self._hits_c is not None:
                                self._hits_c.inc()
                        return got
        with self._lock:
            self.misses += 1
            if self._misses_c is not None:
                self._misses_c.inc()
        return None

    def _try_subsume(self, entry: _Entry, conds, request_atoms, fp: Fingerprint):
        """Re-filter ``entry``'s superset batch with the request's full
        predicate; None unless implication holds and every referenced column
        is present in the cached batch."""
        if not atoms_imply(request_atoms, entry.atoms):
            return None
        if len(entry.output_columns) != len(fp.output_columns):
            return None
        for c in conds:
            if not c.references() <= set(entry.batch):
                return None
        from hyperspace_tpu.exec.batch import mask_rows

        batch = entry.batch
        for c in conds:
            mask = as_bool_mask(c.eval(batch))
            batch = mask_rows(batch, mask)
        return self._relabel(batch, entry.output_columns, fp)

    @staticmethod
    def _relabel(batch, stored_columns, fp: Fingerprint):
        """Positional relabel from the stored aliases to the request's (the
        structure hash is alias-invariant, so positions correspond — the same
        discipline ``QueryServer._finish`` applies to plan-cache templates)."""
        if tuple(stored_columns) == tuple(fp.output_columns):
            return dict(batch)
        return {
            want: batch[have] for want, have in zip(fp.output_columns, stored_columns)
        }

    # -- store ---------------------------------------------------------------
    def put(self, fp: Fingerprint, brand: str, batch: Dict[str, np.ndarray], plan=None) -> bool:
        """Store a served result under its submit-time brand. Arrays are
        frozen (read-only) — a mutation of a served result must raise, not
        corrupt the cache. Returns False when the entry is over budget."""
        nbytes = _batch_nbytes(batch)
        if nbytes > self.max_entry_bytes or nbytes > self.max_bytes:
            return False
        frozen = {}
        for name, a in batch.items():
            a = np.asarray(a)
            a.flags.writeable = False
            frozen[name] = a
        atoms = None
        if self.subsumption and plan is not None:
            got = chain_atoms(plan)
            if got is not None:
                atoms = got[1]
        key = self._key(brand, fp)
        entry = _Entry(frozen, tuple(fp.output_columns), atoms, nbytes, fp.structure, brand)
        with self._lock:
            self._note_brand_locked(fp.structure, brand)
            old = self._entries.pop(key, None)
            if old is not None:
                self.bytes -= old.nbytes
            self._entries[key] = entry
            self.bytes += nbytes
            self._by_struct.setdefault(fp.structure, {}).setdefault(brand, [])
            if key not in self._by_struct[fp.structure][brand]:
                self._by_struct[fp.structure][brand].append(key)
            while self.bytes > self.max_bytes and self._entries:
                k, e = self._entries.popitem(last=False)
                self.bytes -= e.nbytes
                self.evictions += 1
                self._unindex_locked(k, e)
        return True

    # -- invalidation --------------------------------------------------------
    def _note_brand_locked(self, structure: str, brand: str) -> None:
        """First observation of a new brand for a structure purges every
        entry the structure holds under other (stale) brands."""
        brands = self._by_struct.get(structure)
        if not brands:
            return
        stale = [b for b in brands if b != brand]
        for b in stale:
            for k in brands.pop(b):
                e = self._entries.pop(k, None)
                if e is not None:
                    self.bytes -= e.nbytes
                    self.invalidations += 1
                    if self._inv_c is not None:
                        self._inv_c.inc()

    def _unindex_locked(self, key: Tuple, entry: _Entry) -> None:
        brands = self._by_struct.get(entry.structure)
        if brands is not None:
            keys = brands.get(entry.brand)
            if keys is not None and key in keys:
                keys.remove(key)

    def invalidate_all(self) -> int:
        with self._lock:
            n = len(self._entries)
            self._entries.clear()
            self._by_struct.clear()
            self.bytes = 0
            self.invalidations += n
            if self._inv_c is not None:
                self._inv_c.inc(n)
            return n

    # -- observability -------------------------------------------------------
    def bind_registry(self, registry, **labels) -> None:
        self._hits_c = registry.counter(
            "hs_result_cache_hits_total", "result-cache hits (exact + subsumed)", **labels
        )
        self._misses_c = registry.counter(
            "hs_result_cache_misses_total", "result-cache misses", **labels
        )
        self._sub_c = registry.counter(
            "hs_result_cache_subsumed_hits_total",
            "result-cache hits served by re-filtering a cached superset predicate",
            **labels,
        )
        self._inv_c = registry.counter(
            "hs_result_cache_invalidations_total",
            "entries purged because a new data-version brand was observed",
            **labels,
        )
        registry.gauge(
            "hs_result_cache_bytes", "bytes resident in the result cache",
            fn=lambda: self.bytes, **labels,
        )
        registry.gauge(
            "hs_result_cache_entries", "entries resident in the result cache",
            fn=self.__len__, **labels,
        )

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def stats(self) -> dict:
        with self._lock:
            total = self.hits + self.misses
            return {
                "entries": len(self._entries),
                "bytes": self.bytes,
                "hits": self.hits,
                "misses": self.misses,
                "subsumedHits": self.subsumed_hits,
                "invalidations": self.invalidations,
                "evictions": self.evictions,
                "hitRate": (self.hits / total) if total else 0.0,
            }
