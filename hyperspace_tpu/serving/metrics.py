"""Serving metrics: counters + latency percentiles, exported to telemetry.

Latencies keep a bounded reservoir (most recent N) so long-running servers
report *current* tail behavior without unbounded memory. ``snapshot`` merges
in the queue / plan-cache / bucket-cache stats so one call yields the whole
serving picture; ``QueryServer.stats(emit=True)`` wraps it in a
``ServingStatsEvent`` on the session's telemetry sink.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Dict, Optional

import numpy as np


class ServingMetrics:
    def __init__(self, latency_window: int = 4096):
        self._lock = threading.Lock()
        self._lat = deque(maxlen=int(latency_window))
        self.completed = 0
        self.errors = 0
        self.batches = 0
        self.batched_requests = 0

    def observe(self, latency_s: float, error: bool = False) -> None:
        with self._lock:
            self._lat.append(float(latency_s))
            if error:
                self.errors += 1
            else:
                self.completed += 1

    def observe_batch(self, n_requests: int) -> None:
        with self._lock:
            self.batches += 1
            self.batched_requests += int(n_requests)

    def latency_percentiles(self) -> Dict[str, Optional[float]]:
        with self._lock:
            lat = list(self._lat)
        if not lat:
            return {"p50": None, "p95": None, "p99": None}
        p50, p95, p99 = np.percentile(np.asarray(lat), [50, 95, 99])
        return {"p50": float(p50), "p95": float(p95), "p99": float(p99)}

    def snapshot(self, admission=None, plan_cache=None, bucket_cache=None) -> dict:
        with self._lock:
            out = {
                "completed": self.completed,
                "errors": self.errors,
                "batches": self.batches,
                "batchedRequests": self.batched_requests,
            }
        out["latencySeconds"] = self.latency_percentiles()
        if admission is not None:
            out["queue"] = admission.stats()
        if plan_cache is not None:
            out["planCache"] = plan_cache.stats()
        if bucket_cache is not None:
            out["bucketCache"] = bucket_cache.stats()
        return out
