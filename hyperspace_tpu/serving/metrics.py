"""Serving metrics, backed by the process-wide observability registry.

``ServingMetrics`` keeps its original surface (``observe`` /
``observe_batch`` / ``latency_percentiles`` / ``snapshot`` with the same key
schema) but stores everything in :mod:`hyperspace_tpu.obs.metrics`
instruments: completion/error/batch counters and one latency histogram,
labeled per server. ``snapshot`` therefore *reads the registry* — its fields
and a Prometheus scrape of the same process cannot disagree, because they are
the same store (tests/test_obs_serving.py pins this).

A ``registry=None`` default gives each instance a private registry, so
constructing a bare ``ServingMetrics`` (tests, tools) never pollutes the
global one; ``QueryServer`` passes the global registry plus its server label.
"""

from __future__ import annotations

from typing import Dict, Optional

from hyperspace_tpu.obs.metrics import MetricsRegistry


class ServingMetrics:
    def __init__(
        self,
        latency_window: int = 4096,
        registry: Optional[MetricsRegistry] = None,
        server: str = "",
    ):
        self.registry = registry if registry is not None else MetricsRegistry()
        labels = {"server": server} if server else {}
        self._completed = self.registry.counter(
            "hs_serving_completed_total", "requests completed", **labels
        )
        self._errors = self.registry.counter(
            "hs_serving_errors_total", "requests failed", **labels
        )
        self._batches = self.registry.counter(
            "hs_serving_batches_total", "shared-scan micro-batches executed", **labels
        )
        self._batched = self.registry.counter(
            "hs_serving_batched_requests_total", "requests served via micro-batches", **labels
        )
        self._latency = self.registry.histogram(
            "hs_serving_latency_seconds",
            "submit-to-result latency",
            window=int(latency_window),
            **labels,
        )
        self._labels = labels

    # original counter surface, preserved for existing callers/tests
    @property
    def completed(self) -> int:
        return int(self._completed.value)

    @property
    def errors(self) -> int:
        return int(self._errors.value)

    @property
    def batches(self) -> int:
        return int(self._batches.value)

    @property
    def batched_requests(self) -> int:
        return int(self._batched.value)

    def observe(self, latency_s: float, error: bool = False, tenant: Optional[str] = None) -> None:
        self._latency.observe(float(latency_s))
        (self._errors if error else self._completed).inc()
        if tenant:
            # per-tenant attribution rides separate label series so the
            # unlabeled totals above stay cheap and cardinality-stable
            self.registry.counter(
                "hs_serving_tenant_requests_total",
                "requests completed, by tenant and outcome",
                tenant=tenant,
                outcome="error" if error else "ok",
                **self._labels,
            ).inc()

    def observe_batch(self, n_requests: int) -> None:
        self._batches.inc()
        self._batched.inc(int(n_requests))

    def latency_percentiles(self) -> Dict[str, Optional[float]]:
        return self._latency.percentiles()

    def snapshot(self, admission=None, plan_cache=None, bucket_cache=None) -> dict:
        out = {
            "completed": self.completed,
            "errors": self.errors,
            "batches": self.batches,
            "batchedRequests": self.batched_requests,
        }
        out["latencySeconds"] = self.latency_percentiles()
        if admission is not None:
            out["queue"] = admission.stats()
        if plan_cache is not None:
            out["planCache"] = plan_cache.stats()
        if bucket_cache is not None:
            out["bucketCache"] = bucket_cache.stats()
        return out
