"""Micro-batching: coalesce compatible filter-scan requests into one scan.

Requests sharing a parameterized template whose shape is a linear
Project/Filter chain over one scan leaf (Scan / FileScan / IndexScan — the
canonical index-filter-scan shape) execute as ONE batch: the leaf is decoded
once, then each request applies its own bound predicates as masks over the
shared in-memory batch. N concurrent point-lookups against the same covering
index cost one bucket decode instead of N.

Requests that don't fit the shape (joins, aggregates, subqueries,
``input_file_name()`` predicates) simply execute individually — batching is
an optimization, never a semantic gate.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from hyperspace_tpu.exec import batch as B
from hyperspace_tpu.plan import logical as L
from hyperspace_tpu.plan.expr import as_bool_mask


def shared_scan_ops(template: L.LogicalPlan) -> Optional[Tuple[List[tuple], L.LogicalPlan]]:
    """Decompose ``template`` into (root->leaf op list, scan leaf) when it is
    a batchable linear chain with at least one Filter; None otherwise."""
    ops: List[tuple] = []
    p = template
    n_filters = 0
    while True:
        if isinstance(p, (L.Scan, L.FileScan, L.IndexScan)):
            if n_filters == 0:
                return None  # nothing literal-varying to share
            return ops, p
        if isinstance(p, L.Project):
            ops.append(("project", list(p.columns)))
            p = p.child
        elif isinstance(p, L.Filter):
            ops.append(("filter", None))
            n_filters += 1
            p = p.child
        else:
            return None


def _bound_conditions(bound_plan: L.LogicalPlan) -> List:
    """Filter conditions of a bound chain, root->leaf order (mirrors the op
    list from ``shared_scan_ops``)."""
    out = []
    p = bound_plan
    while not isinstance(p, (L.Scan, L.FileScan, L.IndexScan)):
        if isinstance(p, L.Filter):
            out.append(p.condition)
        p = p.child
    return out


def execute_shared_scan(
    session,
    ops: List[tuple],
    leaf: L.LogicalPlan,
    bound_plans: List[L.LogicalPlan],
) -> List[B.Batch]:
    """One leaf decode, then per-request mask/project over the shared batch.
    Returns one result batch per bound plan, in order."""
    from hyperspace_tpu.exec.executor import Executor

    base = Executor(session).execute(leaf, prepruned=True)
    results = []
    for bound in bound_plans:
        conds = _bound_conditions(bound)
        ci = len(conds)
        batch = base
        for kind, payload in reversed(ops):  # leaf -> root
            if kind == "filter":
                ci -= 1
                batch = B.mask_rows(batch, as_bool_mask(conds[ci].eval(batch)))
            else:
                batch = B.select(batch, payload)
        results.append(batch)
    return results
