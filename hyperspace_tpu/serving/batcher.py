"""Micro-batching: coalesce compatible filter-scan requests into one scan.

Requests sharing a parameterized template whose shape is a linear
Project/Filter chain over one scan leaf (Scan / FileScan / IndexScan — the
canonical index-filter-scan shape) execute as ONE batch: the leaf is decoded
once, then each request applies its own bound predicates as masks over the
shared in-memory batch. N concurrent point-lookups against the same covering
index cost one bucket decode instead of N.

Requests that don't fit the shape (joins, aggregates, subqueries,
``input_file_name()`` predicates) simply execute individually — batching is
an optimization, never a semantic gate.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from hyperspace_tpu.exec import batch as B
from hyperspace_tpu.plan import logical as L
from hyperspace_tpu.plan.expr import as_bool_mask


def shared_scan_ops(template: L.LogicalPlan) -> Optional[Tuple[List[tuple], L.LogicalPlan]]:
    """Decompose ``template`` into (root->leaf op list, scan leaf) when it is
    a batchable linear chain with at least one Filter; None otherwise.

    One ``Aggregate`` may cap the chain (only Projects above it): grouped
    dashboard queries against the same covering index then share the scan
    decode and aggregate their own masked rows afterwards — via the device
    grouped-aggregation engine when it applies. A Filter above the Aggregate
    (HAVING) would mask aggregated rows with conditions the per-chunk walk
    below the aggregate cannot evaluate, so that shape stays unbatched."""
    ops: List[tuple] = []
    p = template
    n_filters = 0
    seen_agg = False
    # a root ORDER BY ... LIMIT cap batches too: the shared scan decodes
    # once, each request top-k's its own masked rows afterwards
    if isinstance(p, L.Limit) and isinstance(p.child, L.Sort) and p.child.keys:
        ops.append(("topk", (int(p.n), [(str(c), bool(a)) for c, a in p.child.keys])))
        p = p.child.child
    while True:
        if isinstance(p, (L.Scan, L.FileScan, L.IndexScan)):
            if n_filters == 0:
                return None  # nothing literal-varying to share
            return ops, p
        if isinstance(p, L.Project):
            ops.append(("project", list(p.columns)))
            p = p.child
        elif isinstance(p, L.Filter):
            ops.append(("filter", None))
            n_filters += 1
            p = p.child
        elif isinstance(p, L.Aggregate) and not seen_agg and n_filters == 0:
            ops.append(("aggregate", (list(p.keys), list(p.aggs))))
            seen_agg = True
            p = p.child
        else:
            return None


def _bound_conditions(bound_plan: L.LogicalPlan) -> List:
    """Filter conditions of a bound chain, root->leaf order (mirrors the op
    list from ``shared_scan_ops``)."""
    out = []
    p = bound_plan
    while not isinstance(p, (L.Scan, L.FileScan, L.IndexScan)):
        if isinstance(p, L.Filter):
            out.append(p.condition)
        p = p.child
    return out


def execute_shared_scan(
    session,
    ops: List[tuple],
    leaf: L.LogicalPlan,
    bound_plans: List[L.LogicalPlan],
) -> List[B.Batch]:
    """One streamed leaf decode, then per-request mask/project over each
    shared chunk. Returns one result batch per bound plan, in order.

    The leaf streams through ``execute_stream`` (so multi-chunk leaves ride
    the prefetch pipeline: chunk k+1 decodes while chunk k's request masks
    evaluate); every op below an Aggregate is row-wise, so per-chunk
    application followed by concatenation is exactly the materialized
    result. An Aggregate op (and any Projects above it) applies once per
    request over its concatenated masked rows, dispatching through
    ``aggregate_batch`` so grouped shapes hit the device segment-reduction
    engine."""
    from hyperspace_tpu.exec.executor import Executor, aggregate_batch

    topk = None
    if ops and ops[0][0] == "topk":
        topk, ops = ops[0][1], ops[1:]
    split = next((i for i, (kind, _) in enumerate(ops) if kind == "aggregate"), None)
    above = ops[:split] if split is not None else []
    agg = ops[split][1] if split is not None else None
    below = ops[split + 1:] if split is not None else ops

    per_request_conds = [_bound_conditions(bound) for bound in bound_plans]
    pieces: List[List[B.Batch]] = [[] for _ in bound_plans]
    for base in Executor(session).execute_stream(leaf):
        for r, conds in enumerate(per_request_conds):
            ci = len(conds)
            batch = base
            for kind, payload in reversed(below):  # leaf -> root
                if kind == "filter":
                    ci -= 1
                    batch = B.mask_rows(batch, as_bool_mask(conds[ci].eval(batch)))
                else:
                    batch = B.select(batch, payload)
            pieces[r].append(batch)
    results = [ps[0] if len(ps) == 1 else B.concat(ps) for ps in pieces]
    if agg is not None:
        keys, aggs = agg
        out = []
        for batch in results:
            batch = aggregate_batch(session, keys, aggs, batch)
            for kind, payload in reversed(above):  # projects over the result
                batch = B.select(batch, payload)
            out.append(batch)
        results = out
    if topk is not None:
        n, keys = topk
        results = [_topk_batch(b, keys, n) for b in results]
    return results


def _topk_batch(batch: B.Batch, keys: List[tuple], n: int) -> B.Batch:
    """Host ORDER BY + LIMIT over one request's (already masked, in-memory)
    batch — the same stable composite order as the executor's Sort node."""
    import numpy as np

    from hyperspace_tpu.exec.executor import _key_codes
    from hyperspace_tpu.plan.expr import get_column

    order = np.arange(B.num_rows(batch))
    for name, asc in reversed(keys):
        arr = get_column(batch, name)
        if arr is None:
            raise KeyError(f"Sort key {name!r} not found")
        codes = _key_codes(np.asarray(arr)[order], asc)
        order = order[np.argsort(codes, kind="stable")]
    take = order[:n]
    return {c: np.asarray(v)[take] for c, v in batch.items()}
