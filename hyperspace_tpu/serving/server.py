"""QueryServer: the concurrent serving front-end over one Session.

Request life cycle::

    submit(sql or DataFrame)          caller thread
      parse (text-memoized) -> fingerprint -> admission (bounded queue,
      reject on overflow) -> prefetch hint for a known template's buckets
    worker thread
      drain a micro-batch -> plan-cache lookup (exact / parameterized bind)
      or compile+insert -> execute (shared-scan batch when compatible)
      -> relabel to the request's aliases -> resolve the Future

Results are identical to ``session.sql(q).collect()`` — the cache and the
batcher are throughput optimizations, never semantic changes. Each request
captures the session's hyperspace flag at submit time and workers pin it via
``session.hyperspace_scope`` so a toggle racing the queue can't leak into
requests admitted before it.
"""

from __future__ import annotations

import itertools
import threading
import time
from collections import deque
from concurrent.futures import Future
from typing import Any, Dict, List, Optional

from hyperspace_tpu.obs import metrics as obs_metrics
from hyperspace_tpu.obs import spans
from hyperspace_tpu.obs.profile import build_profile
from hyperspace_tpu.serving.admission import (
    AdmissionController,
    AdmissionRejected,
    RequestTimeout,
    ServerClosed,
)
from hyperspace_tpu.serving.batcher import execute_shared_scan, shared_scan_ops
from hyperspace_tpu.serving.bucket_cache import BucketCache
from hyperspace_tpu.serving.fingerprint import Fingerprint, plan_fingerprint
from hyperspace_tpu.serving.metrics import ServingMetrics
from hyperspace_tpu.serving.plan_cache import CompiledPlan, PlanCache, session_token
from hyperspace_tpu.serving.result_cache import ResultCache, version_brand
from hyperspace_tpu.serving.scheduler import CostAwareScheduler, classify_cost

from hyperspace_tpu.check.locks import named_lock
from hyperspace_tpu.lifecycle.snapshot import SnapshotHandle, snapshot_scope

__all__ = ["QueryServer", "AdmissionRejected", "RequestTimeout", "ServerClosed"]

# distinguishes concurrent QueryServers' series in the process-wide registry;
# intentionally process-local — cross-process label uniqueness comes from the
# explicit ``name`` option (fabric workers pass one)
_server_seq = itertools.count()  # hscheck: disable=process-local-state


class _Request:
    __slots__ = (
        "plan", "fp", "token", "enabled", "future", "deadline", "submitted_at",
        "root", "tenant", "query_text", "cost_class", "brand", "dequeued_at",
        "sched_charge", "snapshot",
    )

    def __init__(self, plan, fp: Fingerprint, token, enabled: bool, deadline, root=None,
                 tenant: str = "default", query_text: str = "",
                 cost_class: str = "unknown", brand: Optional[str] = None,
                 snapshot=None):
        self.plan = plan
        self.fp = fp
        self.token = token
        self.enabled = enabled
        self.future: "Future" = Future()
        self.deadline = deadline
        self.submitted_at = time.monotonic()
        # per-request span-tree root (None when obs tracing is off); workers
        # attach() it so each request's spans land in its own disjoint tree
        self.root = root
        self.tenant = tenant
        self.query_text = query_text
        # scheduling/caching context: predicted cost class for priority and
        # wait-time labels, the submit-time data-version brand for the result
        # cache, and the dispatch bookkeeping the fair scheduler corrects
        # against at completion
        self.cost_class = cost_class
        self.brand = brand
        self.dequeued_at: Optional[float] = None
        self.sched_charge = 0.0
        # admission-time SnapshotHandle (None when pinning is off): workers
        # enter snapshot_scope(self.snapshot) so every log-version resolution
        # sees the roster this request was admitted against
        self.snapshot = snapshot

    def expired(self) -> bool:
        return self.deadline is not None and time.monotonic() > self.deadline

    @property
    def group_key(self):
        return (self.token, self.fp.structure)


class QueryServer:
    """Concurrent query-serving runtime over a :class:`Session`.

    Constructor keyword overrides (each defaulting to its
    ``hyperspace.serving.*`` conf key): ``queue_depth``, ``workers``,
    ``default_timeout``, ``plan_cache_enabled``, ``plan_cache_max_entries``,
    ``micro_batch_enabled``, ``micro_batch_max_requests``,
    ``micro_batch_max_wait_ms``, ``bucket_cache_bytes``,
    ``prefetch_enabled``, ``prefetch_workers``, ``sched_enabled``,
    ``sched_interactive_ms``, ``sched_heavy_ms``, ``sched_min_confidence``,
    ``sched_max_queued_seconds``, ``sched_tenant_weights``,
    ``sched_tenant_rate``, ``sched_tenant_burst``, ``sched_burn_threshold``,
    ``sched_burn_factor``, ``result_cache_enabled``, ``result_cache_bytes``,
    ``result_cache_max_entry_bytes``, ``result_cache_subsumption``; plus
    ``name`` (explicit metrics ``server=`` label, defaulting to the
    process-sequential ``qsN``).
    """

    def __init__(self, session, **overrides):
        conf = session.conf
        self.session = session

        def opt(name, conf_value):
            v = overrides.pop(name, None)
            return conf_value if v is None else v

        self.workers_n = int(opt("workers", conf.serving_workers))
        self.plan_cache_enabled = bool(opt("plan_cache_enabled", conf.serving_plan_cache_enabled))
        self.micro_batch_enabled = bool(opt("micro_batch_enabled", conf.serving_micro_batch_enabled))
        self.micro_batch_max = int(opt("micro_batch_max_requests", conf.serving_micro_batch_max_requests))
        self.micro_batch_wait_s = float(opt("micro_batch_max_wait_ms", conf.serving_micro_batch_max_wait_ms)) / 1000.0
        self.prefetch_enabled = bool(opt("prefetch_enabled", conf.serving_prefetch_enabled))

        depth = int(opt("queue_depth", conf.serving_queue_depth))
        default_timeout = opt("default_timeout", conf.serving_default_timeout_seconds)
        self.sched_enabled = bool(opt("sched_enabled", conf.serving_sched_enabled))
        self._interactive_s = float(opt("sched_interactive_ms", conf.serving_sched_interactive_ms)) / 1000.0
        self._heavy_s = float(opt("sched_heavy_ms", conf.serving_sched_heavy_ms)) / 1000.0
        self._min_confidence = float(opt("sched_min_confidence", conf.serving_sched_min_confidence))
        sched_max_queued_s = float(opt("sched_max_queued_seconds", conf.serving_sched_max_queued_seconds))
        sched_weights = opt("sched_tenant_weights", conf.serving_sched_tenant_weights)
        sched_rate = float(opt("sched_tenant_rate", conf.serving_sched_tenant_rate))
        sched_burst = float(opt("sched_tenant_burst", conf.serving_sched_tenant_burst))
        sched_burn_threshold = float(opt("sched_burn_threshold", conf.serving_sched_burn_threshold))
        sched_burn_factor = float(opt("sched_burn_factor", conf.serving_sched_burn_factor))
        if self.sched_enabled:
            self.admission: AdmissionController = CostAwareScheduler(
                depth=depth,
                default_timeout=default_timeout,
                interactive_s=self._interactive_s,
                heavy_s=self._heavy_s,
                min_confidence=self._min_confidence,
                max_queued_seconds=sched_max_queued_s,
                tenant_weights=sched_weights,
                tenant_rate=sched_rate,
                tenant_burst=sched_burst,
                burn_threshold=sched_burn_threshold,
                burn_factor=sched_burn_factor,
                cost_fn=self._sched_cost,
                burn_rate_fn=self._sched_burn,
            )
        else:
            self.admission = AdmissionController(depth=depth, default_timeout=default_timeout)
        # eagerly-expired queued requests still get their telemetry sealed
        self.admission.on_expired = self._expire_seal
        self.result_cache = None
        rc_enabled = bool(opt("result_cache_enabled", conf.serving_result_cache_enabled))
        rc_bytes = int(opt("result_cache_bytes", conf.serving_result_cache_bytes))
        rc_entry_bytes = int(opt("result_cache_max_entry_bytes", conf.serving_result_cache_max_entry_bytes))
        rc_subsumption = bool(opt("result_cache_subsumption", conf.serving_result_cache_subsumption))
        if rc_enabled:
            self.result_cache = ResultCache(
                max_bytes=rc_bytes,
                max_entry_bytes=rc_entry_bytes,
                subsumption=rc_subsumption,
            )
        self.plan_cache = PlanCache(int(opt("plan_cache_max_entries", conf.serving_plan_cache_max_entries)))
        self.bucket_cache = BucketCache(
            int(opt("bucket_cache_bytes", conf.serving_bucket_cache_bytes)),
            prefetch_workers=int(opt("prefetch_workers", conf.serving_prefetch_workers)),
        )
        # shared broadcast-join build sides (exec/join_stream.py consults
        # session.join_build_cache while this server is attached)
        from hyperspace_tpu.serving.build_cache import JoinBuildCache

        self.join_build_cache = JoinBuildCache(
            int(opt("join_build_cache_bytes", conf.join_build_cache_max_bytes))
        )
        # every server labels its series in the process-wide registry (a
        # private registry when metrics are conf'd off, so accounting still
        # works but nothing is published); an explicit name keeps labels
        # distinct ACROSS processes too (every process counts from qs0), so
        # a fabric FrontDoor can aggregate /metrics without collisions
        self.server_name = str(opt("name", "") or "") or f"qs{next(_server_seq)}"
        self.registry = (
            obs_metrics.REGISTRY if conf.obs_metrics_enabled else obs_metrics.MetricsRegistry()
        )
        self.metrics = ServingMetrics(registry=self.registry, server=self.server_name)
        self.admission.bind_registry(self.registry, server=self.server_name)
        self.plan_cache.bind_registry(self.registry, server=self.server_name)
        self.bucket_cache.bind_registry(self.registry, server=self.server_name)
        self.join_build_cache.bind_registry(self.registry, server=self.server_name)
        if self.result_cache is not None:
            self.result_cache.bind_registry(self.registry, server=self.server_name)
        self.tracing_enabled = bool(conf.obs_tracing_enabled)
        self._trace_max_spans = conf.obs_trace_max_spans
        self._profiles: "deque" = deque(maxlen=max(1, conf.obs_profile_history))
        # identity facts every exposition should carry: the always-1 build
        # gauge makes merged/federated scrapes attributable to a version and
        # fabric node, and the commit-seq gauge puts each process's applied
        # log position beside its serving series
        from hyperspace_tpu.fabric.records import local_node_id
        from hyperspace_tpu.version import __version__

        self.node_id = local_node_id(conf)
        self.registry.gauge(
            "hs_build_info",
            "always 1; the labels carry the build version, fabric node, and "
            "server identity of this exposition",
            version=__version__, node=self.node_id, server=self.server_name,
        ).set(1.0)
        if conf.fabric_enabled:
            # fabric-off keeps the exposition free of hs_fabric_* families
            # (the default-off byte-identity contract in docs/scale-out.md)
            bus_ref = self.session.lifecycle_bus
            self.registry.gauge(
                "hs_fabric_commit_seq",
                "last-applied commit sequence of this process's invalidation bus",
                fn=lambda: float(getattr(bus_ref, "commit_seq", 0) or 0),
                server=self.server_name, node=self.node_id,
            )

        # query intelligence: fingerprint history, SLO tracking, slow-query
        # flight recorder, optional HTTP telemetry endpoint (obs/history.py,
        # obs/slo.py, obs/export.py) — each behind its own conf key
        self.history = None
        if conf.obs_history_enabled:
            from hyperspace_tpu.obs.history import ProfileHistory

            self.history = ProfileHistory(
                max_fingerprints=conf.obs_history_max_fingerprints,
                persist_path=self._telemetry_path("profile_history.jsonl")
                if conf.obs_history_persist else None,
                registry=self.registry,
                server=self.server_name,
            )
        self.slo = None
        if conf.obs_slo_target_ms > 0:
            from hyperspace_tpu.obs.slo import SloTracker

            self.slo = SloTracker(
                target_ms=conf.obs_slo_target_ms,
                objective=conf.obs_slo_objective,
                windows_s=conf.obs_slo_windows_seconds,
                registry=self.registry,
                server=self.server_name,
            )
        self.flight = None
        self._slow_s = None
        if conf.obs_slow_query_ms > 0:
            from hyperspace_tpu.obs.history import FlightRecorder

            self._slow_s = conf.obs_slow_query_ms / 1000.0
            slow_dir = conf.obs_slow_query_dir
            if slow_dir is None:
                slow_dir = self._telemetry_path("slow")
            self.flight = FlightRecorder(
                max_entries=conf.obs_slow_query_max_entries,
                directory=slow_dir or None,
                registry=self.registry,
                server=self.server_name,
            )
        self.telemetry = None
        self._telemetry_port = conf.obs_http_port
        self._telemetry_host = conf.obs_http_host
        if overrides:
            raise TypeError(f"Unknown QueryServer options: {sorted(overrides)}")

        self._sql_memo_lock = named_lock("serving.sqlMemo")
        self._sql_memo: Dict[str, tuple] = {}
        self._threads: List[threading.Thread] = []
        self._stop = threading.Event()
        self._started = False
        self._closed = False
        self._prev_bucket_cache = None
        self._prev_join_build_cache = None

    def _telemetry_path(self, *parts) -> Optional[str]:
        """A path under ``<system.path>/_telemetry`` (the index log
        directory's sibling telemetry area), or None without a system path."""
        import os

        base = self.session.conf.system_path
        if not base:
            return None
        return os.path.join(base, "_telemetry", *parts)

    # -- scheduler wiring ----------------------------------------------------
    def _sched_cost(self, item):
        """Scheduler cost hook: the fingerprint history's learned estimate
        for the request's structure (None without history / unseen shape)."""
        if self.history is None:
            return None
        return self.history.estimate_cost(item.fp.structure)

    def _sched_burn(self, tenant: str) -> float:
        """Scheduler burn hook: the tenant's SLO burn rate over the shortest
        configured window (the fastest-reacting signal)."""
        if self.slo is None:
            return 0.0
        return self.slo.burn_rate(min(self.slo.windows_s), tenant)

    def _expire_seal(self, r: "_Request") -> None:
        self._seal(r, error="RequestTimeout")

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "QueryServer":
        if self._started:
            return self
        self._started = True
        if self._telemetry_port is not None and self.telemetry is None:
            self.serve_telemetry(port=self._telemetry_port, host=self._telemetry_host)
        # the process-global dispatch recorder cannot disambiguate concurrent
        # requests — exec.trace.recording() refuses to start while we serve
        from hyperspace_tpu.exec import trace as exec_trace

        exec_trace.server_started()
        # executor-side scans consult session.bucket_cache when present
        self._prev_bucket_cache = getattr(self.session, "bucket_cache", None)
        self.session.bucket_cache = self.bucket_cache
        self._prev_join_build_cache = getattr(self.session, "join_build_cache", None)
        self.session.join_build_cache = self.join_build_cache
        # fabric coherence: the sidecar publishes/merges this server's SLO
        # and token-bucket accounting while it serves
        fabric = getattr(self.session, "_fabric", None)
        if fabric is not None:
            fabric.attach_server(self)
        # any commit (local, or a remote one replayed by the fabric watcher)
        # drops the SQL-text memo: its entries embed each scan's source
        # listing, so a memoized plan would keep serving the pre-commit file
        # set. Commits are rare; re-parsing after one is cheap.
        self.session.lifecycle_bus.subscribe(self._on_commit_event)
        for i in range(self.workers_n):
            t = threading.Thread(target=self._worker, name=f"hs-serve-{i}", daemon=True)
            t.start()
            self._threads.append(t)
        return self

    def shutdown(self, wait: bool = True) -> None:
        if self._closed:
            return
        self._closed = True
        self._stop.set()
        if wait:
            for t in self._threads:
                t.join(timeout=10)
        # drain anything still queued so no future is left dangling
        while True:
            req = self.admission.take_nowait()
            if req is None:
                break
            if not req.future.done():
                req.future.set_exception(ServerClosed("server shut down"))
        self.bucket_cache.shutdown()
        self.session.bucket_cache = self._prev_bucket_cache
        self.session.join_build_cache = self._prev_join_build_cache
        fabric = getattr(self.session, "_fabric", None)
        if fabric is not None:
            fabric.detach_server(self)
        self.session.lifecycle_bus.unsubscribe(self._on_commit_event)
        if self.telemetry is not None:
            self.telemetry.close()
            self.telemetry = None
        if self.history is not None:
            self.history.close()  # flush/close the JSONL workload log
        if self._started:
            from hyperspace_tpu.exec import trace as exec_trace

            exec_trace.server_stopped()

    def __enter__(self) -> "QueryServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.shutdown()

    # -- submission ----------------------------------------------------------
    def submit(
        self,
        query: Any,
        timeout: Optional[float] = None,
        tenant: str = "default",
        trace_context: Optional[spans.TraceContext] = None,
    ) -> "Future":
        """Admit a query (SQL text or DataFrame) and return a Future yielding
        the collected batch (dict of numpy arrays, like ``collect()``).
        Raises :class:`AdmissionRejected` immediately when the queue is full
        and :class:`ServerClosed` after shutdown. ``tenant`` labels the
        request's SLO accounting and per-tenant completion counters.
        ``trace_context`` (or the ambient :func:`spans.current_context`)
        parents this request's span tree under a routing caller's trace."""
        if self._closed or not self._started:
            raise ServerClosed("server is not running (call start() or use as a context manager)")
        enabled = bool(self.session.hyperspace_enabled)
        query_text = query if isinstance(query, str) else type(query).__name__
        ctx = trace_context if trace_context is not None else spans.current_context()
        root = None
        if self.tracing_enabled and (ctx is None or ctx.sampled):
            root = spans.start_trace(
                "request",
                max_spans=self._trace_max_spans,
                server=self.server_name,
                query=query_text,
            )
            if ctx is not None:
                # cross-process parentage: the router's trace id + the span
                # that issued this hop, checkable after stitching
                root.attrs["trace_id"] = ctx.trace_id
                root.attrs["parent_span_id"] = ctx.span_id
        with spans.attach(root):
            plan, fp = self._parse(query)
        # pin the data version at admission: the token, the brand, and every
        # later resolution in the worker read through this snapshot, so a
        # refresh committing mid-flight never changes this request's answer
        snapshot = None
        if self.session.conf.lifecycle_snapshot_enabled:
            snapshot = SnapshotHandle.capture(self.session)
            # seqlock validation of the capture: the handle records the bus
            # sequence BEFORE reading the roster, so a commit landing during
            # the read (a local refresh, or a fabric watcher replaying a
            # remote one) leaves commit_seq ahead of the handle — the pin
            # may hold a torn half-old/half-new roster. Re-capture until the
            # sequence is stable across the whole read (bounded: under a
            # commit storm the freshest capture wins and is still a valid
            # roster at SOME commit point).
            bus = self.session.lifecycle_bus
            for _ in range(3):
                if bus.commit_seq == snapshot.commit_seq:
                    break
                self.registry.counter(
                    "hs_fabric_snapshot_retries_total",
                    "snapshot re-captures after a commit raced the roster read",
                    server=self.server_name,
                ).inc()
                snapshot = SnapshotHandle.capture(self.session)
        with snapshot_scope(snapshot):
            token = session_token(self.session, enabled)
            cost_class = "unknown"
            if self.history is not None:
                cost_class = classify_cost(
                    self.history.estimate_cost(fp.structure),
                    self._interactive_s, self._heavy_s, self._min_confidence,
                )
            brand = None
            if self.result_cache is not None:
                # submit-time data-version brand: index-log roster + source
                # snapshots; None (unsignable) bypasses the cache entirely
                brand = version_brand(self.session, plan, enabled)
        req = _Request(
            plan, fp, token, enabled, self.admission.deadline_for(timeout),
            root=root, tenant=tenant, query_text=query_text,
            cost_class=cost_class, brand=brand, snapshot=snapshot,
        )
        if brand is not None:
            hit = self.result_cache.get(fp, brand, plan=plan)
            if hit is not None:
                # serve from cache without entering the queue: counts toward
                # serving metrics and the SLO but NOT the profile history —
                # cache hits would corrupt the cost model's latency estimates
                req.future.set_result(hit)
                req.future.request_root = root
                latency = time.monotonic() - req.submitted_at
                self.metrics.observe(latency, tenant=tenant)
                if self.slo is not None:
                    self.slo.record(latency, error=False, tenant=tenant)
                return req.future
        try:
            self.admission.submit(req)  # raises AdmissionRejected on overflow
        except AdmissionRejected:
            from hyperspace_tpu.telemetry.events import ServingRejectionEvent, emit_event

            emit_event(
                self.session,
                ServingRejectionEvent(
                    queue_depth=self.admission.depth, queued=self.admission.queued
                ),
            )
            # a rejection is an SLO bad event and a flight-recorder capture:
            # load shedding must show up in the telemetry it will one day
            # be driven by
            if self.slo is not None:
                self.slo.record(0.0, error=True, tenant=tenant)
            if self.history is not None:
                self.history.record(fp.structure, 0.0, error=True, query=query_text)
            if self.flight is not None:
                self.flight.record(
                    "rejected", 0.0, fingerprint=fp.structure, query=query_text,
                    tenant=tenant, conf_deltas=self.session.conf.deltas(),
                )
            raise
        req.future.request_root = root  # span tree visible to the caller
        if self.prefetch_enabled:
            self._prefetch_hint(token, fp)
        return req.future

    def query(self, query: Any, timeout: Optional[float] = None, tenant: str = "default") -> Dict[str, Any]:
        """Blocking convenience wrapper around :meth:`submit`."""
        fut = self.submit(query, timeout=timeout, tenant=tenant)
        t = self.admission.default_timeout if timeout is None else timeout
        # Future.result timeout is a backstop; the worker resolves the future
        # with RequestTimeout at the deadline itself
        return fut.result(timeout=None if t is None else t + 5.0)

    def _on_commit_event(self, event) -> None:
        """Bus subscriber (see start): invalidate the SQL-text memo on any
        commit so repeated query text re-resolves against the post-commit
        source listing."""
        with self._sql_memo_lock:
            self._sql_memo.clear()

    def _parse(self, query: Any):
        if isinstance(query, str):
            with self._sql_memo_lock:
                hit = self._sql_memo.get(query)
            if hit is not None:
                return hit
            df = self.session.sql(query)
            plan = df.plan
            fp = plan_fingerprint(plan)
            with self._sql_memo_lock:
                if len(self._sql_memo) >= 1024:  # text memo is a bounded side-table
                    self._sql_memo.clear()
                self._sql_memo[query] = (plan, fp)
            return plan, fp
        plan = getattr(query, "plan", query)
        return plan, plan_fingerprint(plan)

    def _prefetch_hint(self, token, fp: Fingerprint) -> None:
        entry = self.plan_cache_entry(token, fp)
        if entry is None:
            return
        from hyperspace_tpu.plan import logical as L

        for leaf in L.collect(
            entry.template, lambda p: isinstance(p, (L.IndexScan, L.FileScan))
        ):
            if leaf.files:
                cols = (
                    leaf.file_columns
                    if getattr(leaf, "file_columns", None) is not None
                    else list(leaf.columns)
                )
                self.bucket_cache.prefetch(list(leaf.files), list(cols))

    def plan_cache_entry(self, token, fp: Fingerprint) -> Optional[CompiledPlan]:
        """Peek (no hit/miss accounting) at the template a request would use."""
        with self.plan_cache._lock:
            got = self.plan_cache._entries.get(("exact", token, fp.exact))
            if got is None:
                got = self.plan_cache._entries.get(("param", token, fp.structure))
        return got

    # -- worker loop ---------------------------------------------------------
    def _worker(self) -> None:
        while not self._stop.is_set():
            first = self.admission.take(timeout=0.05)
            if first is None:
                continue
            group = [first]
            if self.micro_batch_enabled and self.micro_batch_max > 1:
                waited = 0.0
                while len(group) < self.micro_batch_max:
                    nxt = self.admission.take_nowait()
                    if nxt is None:
                        if waited >= self.micro_batch_wait_s or self.admission.queued == 0:
                            break
                        time.sleep(min(0.001, self.micro_batch_wait_s - waited))
                        waited += 0.001
                        continue
                    group.append(nxt)
            self._process_group(group)

    def _process_group(self, group: List[_Request]) -> None:
        now = time.monotonic()
        for r in group:
            r.dequeued_at = now
            self.registry.histogram(
                "hs_admission_wait_seconds",
                "seconds a request waited in the admission queue before dispatch",
                tenant=r.tenant, cost_class=r.cost_class, server=self.server_name,
            ).observe(now - r.submitted_at)
        # coalesce by (token, structure); order within a key is preserved
        by_key: Dict[tuple, List[_Request]] = {}
        for r in group:
            by_key.setdefault(r.group_key, []).append(r)
        for reqs in by_key.values():
            self._process_same_key(reqs)

    def _process_same_key(self, reqs: List[_Request]) -> None:
        live = []
        for r in reqs:
            if r.expired():
                self.admission.expire(r)  # exactly-once timeout + seal
            else:
                live.append(r)
        if not live:
            return
        try:
            self._execute_requests(live)
        except Exception as exc:  # defensive: never kill a worker thread
            for r in live:
                self._fail(r, exc)

    def _execute_requests(self, reqs: List[_Request]) -> None:
        from hyperspace_tpu.exec.executor import Executor
        from hyperspace_tpu.reliability.retry import deadline_scope

        resolved = []  # (req, bound_plan, entry or None)
        for r in reqs:
            try:
                with spans.attach(r.root), spans.span("resolve-plan", cat="serving"):
                    with snapshot_scope(r.snapshot), deadline_scope(r.deadline):
                        resolved.append((r, *self._resolve(r)))
            except Exception as exc:
                self._fail(r, exc)

        # shared-scan micro-batch: >1 request on the same parameterized
        # template whose shape is a filter chain over one scan
        if len(resolved) > 1:
            entry = resolved[0][2]
            if (
                entry is not None
                and entry.parameterizable
                and all(e is entry for _, _, e in resolved)
            ):
                ops_leaf = shared_scan_ops(entry.template)
                if ops_leaf is not None:
                    ops, leaf = ops_leaf
                    t0 = time.perf_counter()
                    # same group key => same session token => same pinned
                    # roster, so the first request's snapshot covers all
                    # retry budget for the whole shared scan: the earliest
                    # deadline in the batch (conservative — a retry that
                    # would expire ANY member gives up instead)
                    group_deadlines = [r.deadline for r, _, _ in resolved if r.deadline is not None]
                    with self.session.hyperspace_scope(resolved[0][0].enabled), \
                            snapshot_scope(resolved[0][0].snapshot), \
                            deadline_scope(min(group_deadlines) if group_deadlines else None):
                        batches = execute_shared_scan(
                            self.session, ops, leaf, [b for _, b, _ in resolved]
                        )
                    t1 = time.perf_counter()
                    self.metrics.observe_batch(len(resolved))
                    for (r, _, e), batch in zip(resolved, batches):
                        # the scan ran ONCE for the whole group; each tree
                        # records its share as a pre-timed child
                        if r.root is not None:
                            spans.add_manual(
                                r.root, "execute-shared-scan", "serving", t0, t1,
                                batch_size=len(resolved),
                            )
                        self._finish(r, batch, e)
                    return

        for r, bound, entry in resolved:
            if r.expired():
                self.admission.expire(r)  # exactly-once timeout + seal
                continue
            try:
                with spans.attach(r.root), spans.span("execute", cat="serving"):
                    with self.session.hyperspace_scope(r.enabled), snapshot_scope(r.snapshot), \
                            deadline_scope(r.deadline):
                        out_cols = list(entry.output_columns) if entry is not None else list(bound.output_columns)
                        batch = Executor(self.session).execute(
                            bound, required_columns=out_cols, prepruned=entry is not None
                        )
                self._finish(r, batch, entry)
            except Exception as exc:
                self._fail(r, exc)

    def _resolve(self, r: _Request):
        """(bound plan, cache entry or None). A None entry means the plan was
        compiled ad hoc (cache disabled) and carries the request's own
        literals and aliases."""
        if not self.plan_cache_enabled:
            return self._compile(r), None
        hit = self.plan_cache.lookup(r.token, r.fp)
        if hit is not None:
            return hit[0], hit[1]
        template = self._compile(r)
        entry = self.plan_cache.insert(r.token, r.fp, template)
        return template, entry

    def _compile(self, r: _Request):
        """Optimize + prune once — the expensive work the cache amortizes."""
        from hyperspace_tpu.rules.apply import optimize_plan
        from hyperspace_tpu.rules.utils import prune_columns

        with self.session.hyperspace_scope(r.enabled):
            plan = optimize_plan(r.plan, self.session, enabled=r.enabled)
        try:
            return prune_columns(plan)
        except Exception:
            return plan

    def _finish(self, r: _Request, batch, entry: Optional[CompiledPlan]) -> None:
        if entry is not None and tuple(entry.output_columns) != tuple(r.fp.output_columns):
            # template carries the FIRST request's aliases; relabel
            # positionally to this request's output names
            batch = {
                want: batch[have]
                for want, have in zip(r.fp.output_columns, entry.output_columns)
            }
        else:
            batch = {c: batch[c] for c in r.fp.output_columns}
        if not r.future.done():
            if self.result_cache is not None and r.brand is not None:
                # store under the request's submit-time brand; arrays are
                # frozen by the cache, so the live result is read-only too —
                # a caller mutating served bytes now raises instead of
                # silently corrupting future hits
                self.result_cache.put(r.fp, r.brand, batch, plan=r.plan)
            rows = 0
            if batch:
                rows = int(len(next(iter(batch.values()))))
            # account BEFORE resolving the future: once query() returns, every
            # registry series for this request is already published, so a
            # caller may scrape /metrics immediately and see consistent state
            try:
                self.metrics.observe(time.monotonic() - r.submitted_at, tenant=r.tenant)
                self._seal(r, rows=rows)
            finally:
                r.future.set_result(batch)

    def _fail(self, r: _Request, exc: BaseException) -> None:
        if not r.future.done():
            try:
                self.metrics.observe(time.monotonic() - r.submitted_at, error=True, tenant=r.tenant)
                self._seal(r, error=type(exc).__name__)
            finally:
                r.future.set_exception(exc)

    def _seal(self, r: _Request, error: Optional[str] = None, rows: Optional[int] = None) -> None:
        """Completion hook: finish the request's span tree, publish its
        QueryProfile (on the future as ``.profile`` and in the bounded server
        history), fold it into the fingerprint-keyed ProfileHistory, account
        the SLO event, and flight-record slow/errored requests. Runs for
        every sealed request, traced or not — the intelligence layer does not
        require span tracing."""
        latency = time.monotonic() - r.submitted_at
        if self.sched_enabled and r.dequeued_at is not None:
            # replace the predicted charge taken at dispatch with the actual
            # service seconds so fair-share accounting self-corrects
            self.admission.observe_completion(
                r.tenant, time.monotonic() - r.dequeued_at, charged_s=r.sched_charge
            )
        profile = None
        if r.root is not None:
            profile = build_profile(
                r.root, query=str(r.root.attrs.get("query", "")), error=error
            )
            r.future.profile = profile
            self._profiles.append(profile)
        if self.history is not None:
            self.history.record(
                r.fp.structure,
                latency,
                rows=rows,
                bytes=(profile.total("bytes") or None) if profile is not None else None,
                error=error is not None,
                query=r.query_text,
            )
        if self.slo is not None:
            self.slo.record(latency, error=error is not None, tenant=r.tenant)
        if self.flight is not None and (
            error is not None or (self._slow_s is not None and latency >= self._slow_s)
        ):
            self.flight.record(
                "error" if error is not None else "slow",
                latency,
                fingerprint=r.fp.structure,
                query=r.query_text,
                tenant=r.tenant,
                profile=profile,
                conf_deltas=self.session.conf.deltas(),
            )

    # -- observability -------------------------------------------------------
    def last_profiles(self) -> List:
        """Most recent per-request ``QueryProfile``s (bounded by
        ``hyperspace.obs.profile.history``), oldest first."""
        return list(self._profiles)

    def last_slow_queries(self) -> List:
        """Flight-recorder entries (slow/errored/rejected requests), oldest
        first; empty when ``hyperspace.obs.slowQueryMs`` is 0."""
        return [] if self.flight is None else self.flight.last_slow_queries()

    def estimate_cost(self, query: Any):
        """Learned cost estimate for a query, SQL text, DataFrame, or
        fingerprint-structure hash: ``CostEstimate(latency_s, confidence,
        samples)`` from the fingerprint history, or None when the history is
        disabled or has never seen the fingerprint."""
        if self.history is None:
            return None
        if isinstance(query, str) and len(query) == 40 and all(
            c in "0123456789abcdef" for c in query
        ):
            return self.history.estimate_cost(query)  # already a structure hash
        _, fp = self._parse(query)
        return self.history.estimate_cost(fp.structure)

    def serve_telemetry(self, port: int = 0, host: str = "127.0.0.1"):
        """Start (or return) the HTTP telemetry endpoint for this server:
        ``/metrics`` (Prometheus 0.0.4), ``/statusz`` (JSON), ``/profilez``
        (fingerprint drill-down). ``port=0`` binds an ephemeral port —
        read ``server.telemetry.port``."""
        if self.telemetry is None:
            from hyperspace_tpu.obs.export import TelemetryEndpoint

            self.telemetry = TelemetryEndpoint(
                self.registry,
                host=host,
                port=port,
                status_fn=self.statusz,
                history=self.history,
                flight=self.flight,
            ).start()
        return self.telemetry

    def statusz(self) -> dict:
        """The ``/statusz`` body: serving stats + cache hit rates + SLO state
        + intelligence-layer summaries, one JSON-able dict."""
        out = {"server": self.server_name, "serving": self.stats()}
        if self.slo is not None:
            out["slo"] = self.slo.state()
        if self.history is not None:
            out["profileHistory"] = {
                "fingerprints": len(self.history),
                "evicted": self.history.evicted,
            }
        if self.flight is not None:
            out["slowQueries"] = self.flight.snapshot()
        return out

    def prometheus_text(self) -> str:
        """Prometheus exposition of this server's registry (the process-wide
        one unless metrics were conf'd off)."""
        return self.registry.prometheus_text()

    def stats(self, emit: bool = False) -> dict:
        snap = self.metrics.snapshot(
            admission=self.admission,
            plan_cache=self.plan_cache if self.plan_cache_enabled else None,
            bucket_cache=self.bucket_cache,
        )
        if self.result_cache is not None:
            snap["resultCache"] = self.result_cache.stats()
        if emit:
            from hyperspace_tpu.telemetry.events import ServingStatsEvent, emit_event

            emit_event(
                self.session,
                ServingStatsEvent(
                    queue_depth=snap["queue"]["queued"],
                    rejected=snap["queue"]["rejected"],
                    plan_cache_hit_rate=snap.get("planCache", {}).get("hitRate", 0.0),
                    bucket_cache_hit_rate=snap["bucketCache"]["hitRate"],
                    latency_p50=snap["latencySeconds"]["p50"],
                    latency_p95=snap["latencySeconds"]["p95"],
                    latency_p99=snap["latencySeconds"]["p99"],
                    completed=snap["completed"],
                )
            )
        return snap
