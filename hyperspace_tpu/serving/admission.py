"""Admission control: a bounded request queue with explicit backpressure.

A request that cannot be queued is *rejected immediately* with
``AdmissionRejected`` — the caller learns the system is saturated instead of
piling work onto an unbounded queue. Each request carries a deadline; workers
drop a request whose deadline passed while it sat in the queue (the client
already gave up) and resolve its future with ``RequestTimeout``.

Dead queued entries are also expired *eagerly*: a submit that finds the queue
full first sweeps out requests whose deadline has already lapsed, so dead
entries never hold queue slots and cause spurious ``AdmissionRejected`` for
live traffic. Expiry accounting is centralized in :meth:`expire` — guarded by
``future.done()`` so a request counts as a timeout exactly once no matter how
many paths (sweep, worker pop, pre-execution check) observe it.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Callable, Optional

from hyperspace_tpu.check.locks import named_lock


class AdmissionRejected(RuntimeError):
    """Queue full at submit time — back off and retry."""


class RequestTimeout(TimeoutError):
    """The request's deadline expired before a result was produced."""


class ServerClosed(RuntimeError):
    """Submit after shutdown."""


class AdmissionController:
    """Thread-safe bounded queue + rejection/timeout accounting."""

    def __init__(self, depth: int, default_timeout: Optional[float]):
        if depth <= 0:
            raise ValueError("queue depth must be positive")
        self.depth = int(depth)
        self.default_timeout = default_timeout
        self._q: "queue.Queue" = queue.Queue(maxsize=self.depth)
        self._lock = named_lock("serving.admission")
        self.submitted = 0
        self.rejected = 0
        self.timeouts = 0
        # called (outside any queue lock) with each eagerly-expired request so
        # the server can seal its telemetry; None = expiry only resolves the
        # future
        self.on_expired: Optional[Callable] = None

    def deadline_for(self, timeout: Optional[float]) -> Optional[float]:
        t = self.default_timeout if timeout is None else timeout
        return None if t is None else time.monotonic() + float(t)

    def submit(self, item) -> None:
        """Enqueue or reject — never blocks. A full queue is swept for
        already-expired entries before the rejection is final."""
        while True:
            try:
                self._q.put_nowait(item)
            except queue.Full:
                if self._purge_expired():
                    continue  # a slot was freed; retry the enqueue
                with self._lock:
                    self.rejected += 1
                raise AdmissionRejected(
                    f"serving queue full (depth={self.depth}); retry later"
                ) from None
            break
        with self._lock:
            self.submitted += 1

    def expire(self, item) -> bool:
        """Resolve an expired request exactly once: set ``RequestTimeout`` on
        its future, count the timeout, and fire ``on_expired``. Returns False
        (and does nothing) when the item has no future or is already done —
        the exactly-once guard every expiry path shares."""
        fut = getattr(item, "future", None)
        if fut is None or fut.done():
            return False
        fut.set_exception(RequestTimeout("deadline expired in queue"))
        self.record_timeout()
        cb = self.on_expired
        if cb is not None:
            try:
                cb(item)
            except Exception:
                pass  # telemetry must never break admission
        return True

    def _purge_expired(self) -> int:
        """Remove queued items whose deadline already lapsed. Items without an
        ``expired()`` predicate (or a future) are never touched."""
        dead = []
        with self._q.mutex:
            kept = [it for it in self._q.queue if not self._is_dead(it, dead)]
            if dead:
                self._q.queue.clear()
                self._q.queue.extend(kept)
                self._q.not_full.notify(len(dead))
        for it in dead:
            self.expire(it)
        return len(dead)

    @staticmethod
    def _is_dead(item, dead: list) -> bool:
        check = getattr(item, "expired", None)
        if callable(check) and getattr(item, "future", None) is not None and check():
            dead.append(item)
            return True
        return False

    def take(self, timeout: float = 0.1):
        """Dequeue one item for a worker; None on idle timeout."""
        try:
            return self._q.get(timeout=timeout)
        except queue.Empty:
            return None

    def take_nowait(self):
        try:
            return self._q.get_nowait()
        except queue.Empty:
            return None

    def record_timeout(self) -> None:
        with self._lock:
            self.timeouts += 1

    @property
    def queued(self) -> int:
        return self._q.qsize()

    def bind_registry(self, registry, **labels) -> None:
        """Publish this controller's accounting into an obs metrics registry
        as callback gauges: scrapes read the live counters themselves, so a
        Prometheus sample and ``stats()`` can never disagree."""
        registry.gauge(
            "hs_serving_queue_depth", "requests waiting in the admission queue",
            fn=lambda: self.queued, **labels,
        )
        registry.gauge(
            "hs_serving_queue_capacity", "admission queue bound",
            fn=lambda: self.depth, **labels,
        )
        registry.gauge(
            "hs_serving_rejected", "requests rejected at admission (queue full)",
            fn=lambda: self.rejected, **labels,
        )
        registry.gauge(
            "hs_serving_timeouts", "requests whose deadline expired",
            fn=lambda: self.timeouts, **labels,
        )
        registry.gauge(
            "hs_serving_submitted", "requests admitted",
            fn=lambda: self.submitted, **labels,
        )

    def stats(self) -> dict:
        with self._lock:
            return {
                "depth": self.depth,
                "queued": self.queued,
                "submitted": self.submitted,
                "rejected": self.rejected,
                "timeouts": self.timeouts,
            }
