"""Admission control: a bounded request queue with explicit backpressure.

A request that cannot be queued is *rejected immediately* with
``AdmissionRejected`` — the caller learns the system is saturated instead of
piling work onto an unbounded queue. Each request carries a deadline; workers
drop a request whose deadline passed while it sat in the queue (the client
already gave up) and resolve its future with ``RequestTimeout``.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Optional


class AdmissionRejected(RuntimeError):
    """Queue full at submit time — back off and retry."""


class RequestTimeout(TimeoutError):
    """The request's deadline expired before a result was produced."""


class ServerClosed(RuntimeError):
    """Submit after shutdown."""


class AdmissionController:
    """Thread-safe bounded queue + rejection/timeout accounting."""

    def __init__(self, depth: int, default_timeout: Optional[float]):
        if depth <= 0:
            raise ValueError("queue depth must be positive")
        self.depth = int(depth)
        self.default_timeout = default_timeout
        self._q: "queue.Queue" = queue.Queue(maxsize=self.depth)
        self._lock = threading.Lock()
        self.submitted = 0
        self.rejected = 0
        self.timeouts = 0

    def deadline_for(self, timeout: Optional[float]) -> Optional[float]:
        t = self.default_timeout if timeout is None else timeout
        return None if t is None else time.monotonic() + float(t)

    def submit(self, item) -> None:
        """Enqueue or reject — never blocks."""
        try:
            self._q.put_nowait(item)
        except queue.Full:
            with self._lock:
                self.rejected += 1
            raise AdmissionRejected(
                f"serving queue full (depth={self.depth}); retry later"
            ) from None
        with self._lock:
            self.submitted += 1

    def take(self, timeout: float = 0.1):
        """Dequeue one item for a worker; None on idle timeout."""
        try:
            return self._q.get(timeout=timeout)
        except queue.Empty:
            return None

    def take_nowait(self):
        try:
            return self._q.get_nowait()
        except queue.Empty:
            return None

    def record_timeout(self) -> None:
        with self._lock:
            self.timeouts += 1

    @property
    def queued(self) -> int:
        return self._q.qsize()

    def bind_registry(self, registry, **labels) -> None:
        """Publish this controller's accounting into an obs metrics registry
        as callback gauges: scrapes read the live counters themselves, so a
        Prometheus sample and ``stats()`` can never disagree."""
        registry.gauge(
            "hs_serving_queue_depth", "requests waiting in the admission queue",
            fn=self._q.qsize, **labels,
        )
        registry.gauge(
            "hs_serving_queue_capacity", "admission queue bound",
            fn=lambda: self.depth, **labels,
        )
        registry.gauge(
            "hs_serving_rejected", "requests rejected at admission (queue full)",
            fn=lambda: self.rejected, **labels,
        )
        registry.gauge(
            "hs_serving_timeouts", "requests whose deadline expired",
            fn=lambda: self.timeouts, **labels,
        )
        registry.gauge(
            "hs_serving_submitted", "requests admitted",
            fn=lambda: self.submitted, **labels,
        )

    def stats(self) -> dict:
        with self._lock:
            return {
                "depth": self.depth,
                "queued": self.queued,
                "submitted": self.submitted,
                "rejected": self.rejected,
                "timeouts": self.timeouts,
            }
