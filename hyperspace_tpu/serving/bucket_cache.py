"""Hot-bucket cache with asynchronous prefetch.

Covering-index buckets are immutable parquet files; under serving traffic the
same hot buckets are read by many requests. This cache keeps *decoded*
batches (file group + column set -> columnar batch) in a byte-budgeted LRU,
and prefetches groups it has just been told about on a small background pool
so the decode cost lands off the request path.

It layers above ``exec/io.py``'s per-file cache: the arrays stored here are
the same objects the io cache holds, so the marginal memory of an entry is
mostly the concat result, not a second copy of every column.
"""

from __future__ import annotations

import threading
from concurrent.futures import ThreadPoolExecutor
from typing import List, Optional, Tuple

from hyperspace_tpu.utils.lru import BytesLRU

from hyperspace_tpu.check.locks import named_lock


def _key(files: List[str], columns: Optional[List[str]]) -> Tuple:
    return (tuple(files), tuple(columns) if columns is not None else None)


class BucketCache:
    """Byte-capped LRU of decoded bucket batches + async prefetch."""

    def __init__(self, cap_bytes: int, prefetch_workers: int = 2):
        self._lru = BytesLRU(int(cap_bytes))
        self._prefetch_workers = int(prefetch_workers)
        self._pool: Optional[ThreadPoolExecutor] = None
        self._pool_lock = named_lock("serving.bucketCache.pool")
        self._inflight = set()
        self._inflight_lock = named_lock("serving.bucketCache.inflight")
        self.prefetch_issued = 0
        self.prefetch_completed = 0

    # -- synchronous read path ----------------------------------------------
    def read(self, files: List[str], columns: Optional[List[str]]):
        """Decoded batch for ``files``/``columns`` — cached, or decoded now
        and cached. Returns a fresh dict; the arrays inside are shared and
        frozen (same contract as the io cache)."""
        from hyperspace_tpu.exec.io import _batch_nbytes, read_parquet_batch

        k = _key(files, columns)
        got = self._lru.get(k)
        if got is not None:
            return dict(got)
        batch = read_parquet_batch(list(files), list(columns) if columns is not None else None)
        for a in batch.values():
            a.setflags(write=False)
        self._lru.put(k, dict(batch), _batch_nbytes(batch))
        return dict(batch)

    # -- async prefetch ------------------------------------------------------
    def prefetch(self, files: List[str], columns: Optional[List[str]]) -> bool:
        """Schedule a background decode if the group is neither cached nor
        already being fetched. Returns True when a fetch was issued."""
        k = _key(files, columns)
        if k in self._lru.keys():  # containment probe — keep hit/miss stats honest
            return False
        with self._inflight_lock:
            if k in self._inflight:
                return False
            self._inflight.add(k)

        def work():
            try:
                self.read(files, columns)
                self.prefetch_completed += 1
            except Exception:
                pass  # the request path will surface the real error
            finally:
                with self._inflight_lock:
                    self._inflight.discard(k)

        self.prefetch_issued += 1
        self._ensure_pool().submit(work)
        return True

    def _ensure_pool(self) -> ThreadPoolExecutor:
        with self._pool_lock:
            if self._pool is None:
                self._pool = ThreadPoolExecutor(
                    max_workers=self._prefetch_workers, thread_name_prefix="hs-prefetch"
                )
            return self._pool

    # -- lifecycle / stats ---------------------------------------------------
    def shutdown(self) -> None:
        with self._pool_lock:
            if self._pool is not None:
                self._pool.shutdown(wait=False)
                self._pool = None

    def clear(self) -> None:
        self._lru.clear()

    def purge_files(self, paths) -> int:
        """Drop every cached bucket group containing any of ``paths``
        (data-version commit invalidation); returns entries removed."""
        wanted = set(paths)
        if not wanted:
            return 0
        removed = 0
        for key in self._lru.keys():
            files = key[0]
            if any(f in wanted for f in files) and self._lru.discard(key):
                removed += 1
        return removed

    def bind_registry(self, registry, **labels) -> None:
        """Publish cache accounting as callback gauges (see
        ``AdmissionController.bind_registry`` for the equality rationale)."""
        registry.gauge("hs_bucket_cache_bytes", "decoded bytes resident", fn=lambda: self._lru.total_bytes, **labels)
        registry.gauge("hs_bucket_cache_hits", "bucket-cache hits", fn=lambda: self._lru.hits, **labels)
        registry.gauge("hs_bucket_cache_misses", "bucket-cache misses", fn=lambda: self._lru.misses, **labels)
        registry.gauge(
            "hs_bucket_cache_hit_rate", "hits / lookups",
            fn=lambda: self.stats()["hitRate"], **labels,
        )
        registry.gauge(
            "hs_bucket_cache_prefetch_issued", "prefetch tasks issued",
            fn=lambda: self.prefetch_issued, **labels,
        )
        registry.gauge(
            "hs_bucket_cache_prefetch_completed", "prefetch tasks completed",
            fn=lambda: self.prefetch_completed, **labels,
        )

    def stats(self) -> dict:
        total = self._lru.hits + self._lru.misses
        return {
            "bytes": self._lru.total_bytes,
            "capBytes": self._lru.cap,
            "hits": self._lru.hits,
            "misses": self._lru.misses,
            "evictions": self._lru.evictions,
            "hitRate": (self._lru.hits / total) if total else 0.0,
            "prefetchIssued": self.prefetch_issued,
            "prefetchCompleted": self.prefetch_completed,
        }
