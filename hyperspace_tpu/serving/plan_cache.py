"""Compiled-plan cache: canonical fingerprint -> optimized, pruned plan.

Two lookup tiers share one LRU:

- **exact** — (session token, structure hash, literal vector): the same query
  repeated verbatim returns the stored plan with zero rewriting;
- **parameterized** — (session token, structure hash): a query differing only
  in predicate literals binds its literals into the stored template
  (prepared-statement execution). A template is parameterized only when the
  optimizer's rewrite provably does not depend on the literal values — a
  data-skipping prune (``FileScan.via_index``) or a bucket prune
  (``IndexScan.pruned_buckets``) chose *files* from the literal, so those
  templates fall back to exact-only reuse. Subquery-bearing plans are also
  exact-only: the inner plan's result depends on its literals.

The session token folds in everything that can change what "compiled" means:
the hyperspace flag, the ACTIVE index set (name + log version), and the conf
knobs the rewrite rules read. Index lifecycle actions therefore invalidate
naturally — a refreshed index has a new log version, so old entries simply
stop being reachable and age out of the LRU.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Any, List, Optional, Tuple

from hyperspace_tpu.check.locks import named_lock
from hyperspace_tpu.plan import logical as L
from hyperspace_tpu.serving.fingerprint import (
    Fingerprint,
    Unparameterizable,
    bind_literals,
    plan_fingerprint,
    slot_mapping,
)


def session_token(session, enabled: bool) -> Tuple:
    """Hashable summary of the compilation environment."""
    if not enabled:
        return ("off",)
    from hyperspace_tpu.models import states

    try:
        idx = tuple(
            sorted((e.name, e.id) for e in session.index_manager.get_indexes([states.ACTIVE]))
        )
    except Exception:
        idx = ("indexes-unavailable",)
    conf = session.conf
    return (
        "on",
        idx,
        conf.hybrid_scan_enabled,
        conf.use_bucket_spec,
        conf.nested_column_enabled,
    )


def _literal_dependent_rewrite(plan: L.LogicalPlan) -> bool:
    """True when the optimized plan's *shape* encodes literal values — then a
    different literal could have produced a different file set, so the
    template must not be re-bound."""
    if L.collect(plan, lambda p: isinstance(p, L.FileScan) and p.via_index is not None):
        return True
    if L.collect(plan, lambda p: isinstance(p, L.IndexScan) and p.pruned_buckets is not None):
        return True
    return False


class CompiledPlan:
    """One cache entry: the optimized+pruned template and how to reuse it."""

    __slots__ = ("template", "fp", "parameterizable", "output_columns")

    def __init__(self, template: L.LogicalPlan, fp: Fingerprint, parameterizable: bool):
        self.template = template
        self.fp = fp
        self.parameterizable = parameterizable
        self.output_columns = tuple(template.output_columns)

    def bind(self, request_fp: Fingerprint) -> L.LogicalPlan:
        """Template plan with this request's literals bound in (raises
        ``Unparameterizable`` when the slots cannot be aligned)."""
        mapping = slot_mapping(self.fp, request_fp)
        values = [request_fp.literals[j] for j in mapping]
        if not values:
            return self.template
        return bind_literals(self.template, values)


class PlanCache:
    """Bounded LRU over compiled plans with hit/miss/eviction accounting.

    ``lookup`` and ``insert`` are separate so compilation (optimizer rewrite,
    potentially slow) runs outside the lock; a racing duplicate compile is
    benign — last insert wins.
    """

    def __init__(self, max_entries: int = 256):
        self.max_entries = int(max_entries)
        self._lock = named_lock("serving.planCache")
        self._entries: "OrderedDict[Tuple, Any]" = OrderedDict()
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        # hits split by tier, for telemetry / tests
        self.exact_hits = 0
        self.param_hits = 0

    # -- lookup --------------------------------------------------------------
    def lookup(self, token: Tuple, fp: Fingerprint) -> Optional[Tuple[L.LogicalPlan, CompiledPlan]]:
        """(bound plan, entry) on a hit, None on a miss."""
        exact_key = ("exact", token, fp.exact)
        param_key = ("param", token, fp.structure)
        with self._lock:
            got = self._entries.get(exact_key)
            if got is not None:
                self._entries.move_to_end(exact_key)
                self.hits += 1
                self.exact_hits += 1
                return got.template, got
            entry = self._entries.get(param_key)
        if entry is not None:
            try:
                bound = entry.bind(fp)
            except Unparameterizable:
                bound = None
            if bound is not None:
                with self._lock:
                    if param_key in self._entries:
                        self._entries.move_to_end(param_key)
                    self.hits += 1
                    self.param_hits += 1
                return bound, entry
        with self._lock:
            self.misses += 1
        return None

    # -- insert --------------------------------------------------------------
    def insert(self, token: Tuple, fp: Fingerprint, template: L.LogicalPlan) -> CompiledPlan:
        """Store a freshly compiled ``template`` for ``fp`` and return the
        entry. Decides the reuse tier here: parameterized when safe, exact
        otherwise."""
        parameterizable = not fp.has_subquery and not _literal_dependent_rewrite(template)
        entry = CompiledPlan(template, fp, parameterizable)
        if parameterizable:
            # re-fingerprint the template so its slot order/signatures match
            # what bind() walks (the optimizer may have reshaped the tree);
            # if its slots no longer align with the request's, fall back
            tfp = plan_fingerprint(template)
            entry.fp = tfp
            try:
                slot_mapping(tfp, fp)
            except Unparameterizable:
                entry.parameterizable = False
        key = (
            ("param", token, fp.structure)
            if entry.parameterizable
            else ("exact", token, fp.exact)
        )
        with self._lock:
            self._entries[key] = entry
            self._entries.move_to_end(key)
            while len(self._entries) > self.max_entries:
                self._entries.popitem(last=False)
                self.evictions += 1
        return entry

    # -- stats ---------------------------------------------------------------
    def bind_registry(self, registry, **labels) -> None:
        """Publish cache accounting as callback gauges (see
        ``AdmissionController.bind_registry`` for the equality rationale)."""
        registry.gauge("hs_plan_cache_entries", "compiled plans resident", fn=self.__len__, **labels)
        registry.gauge("hs_plan_cache_hits", "plan-cache hits", fn=lambda: self.hits, **labels)
        registry.gauge("hs_plan_cache_misses", "plan-cache misses", fn=lambda: self.misses, **labels)
        registry.gauge("hs_plan_cache_evictions", "plan-cache evictions", fn=lambda: self.evictions, **labels)
        registry.gauge(
            "hs_plan_cache_hit_rate", "hits / lookups",
            fn=lambda: self.stats()["hitRate"], **labels,
        )

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def stats(self) -> dict:
        with self._lock:
            total = self.hits + self.misses
            return {
                "entries": len(self._entries),
                "hits": self.hits,
                "misses": self.misses,
                "exactHits": self.exact_hits,
                "paramHits": self.param_hits,
                "evictions": self.evictions,
                "hitRate": (self.hits / total) if total else 0.0,
            }
