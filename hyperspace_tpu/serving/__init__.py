"""Query-serving runtime: concurrent request admission, compiled-plan
caching, micro-batched execution, and hot-bucket prefetch over one Session.

Entry point::

    from hyperspace_tpu.serving import QueryServer

    with QueryServer(session) as server:
        fut = server.submit("SELECT name FROM t WHERE price > 5")
        rows = fut.result()
        print(server.stats())

See docs/serving.md for the architecture and ``hyperspace.serving.*``
configuration keys.
"""

from hyperspace_tpu.serving.admission import (
    AdmissionController,
    AdmissionRejected,
    RequestTimeout,
    ServerClosed,
)
from hyperspace_tpu.serving.bucket_cache import BucketCache
from hyperspace_tpu.serving.fingerprint import (
    Fingerprint,
    Unparameterizable,
    bind_literals,
    canonical_form,
    plan_fingerprint,
)
from hyperspace_tpu.serving.metrics import ServingMetrics
from hyperspace_tpu.serving.plan_cache import CompiledPlan, PlanCache, session_token
from hyperspace_tpu.serving.result_cache import ResultCache, version_brand
from hyperspace_tpu.serving.scheduler import (
    COST_CLASSES,
    CostAwareScheduler,
    TokenBucket,
    classify_cost,
)
from hyperspace_tpu.serving.server import QueryServer

__all__ = [
    "QueryServer",
    "AdmissionController",
    "AdmissionRejected",
    "RequestTimeout",
    "ServerClosed",
    "BucketCache",
    "PlanCache",
    "CompiledPlan",
    "ServingMetrics",
    "Fingerprint",
    "plan_fingerprint",
    "canonical_form",
    "bind_literals",
    "Unparameterizable",
    "session_token",
    "CostAwareScheduler",
    "TokenBucket",
    "classify_cost",
    "COST_CLASSES",
    "ResultCache",
    "version_brand",
]
