"""Cost-aware, tenant-fair admission scheduling.

``CostAwareScheduler`` replaces the FIFO ``AdmissionController`` (same
surface — ``submit``/``take``/``take_nowait``/``stats``/``bind_registry`` —
so the worker loop is unchanged) with deficit-weighted fair queueing:

- **per-tenant sub-queues**, each a heap ordered by (predicted-cost class,
  deadline slack, arrival): cheap/interactive queries dispatch ahead of heavy
  scans *within* a tenant's share, and among equally-classed requests the
  tightest deadline goes first;
- **dispatch-time tenant selection**: the tenant with the smallest
  consumed-work / effective-weight ratio dequeues next, so a flooding heavy
  tenant cannot starve a light one — each tenant's share of worker seconds
  converges to its weight. A tenant waking from idle is normalized against
  the busiest floor so it cannot burst unboundedly to "catch up";
- **predicted-work load shedding**: admission sheds when the *confident*
  predicted seconds of queued work exceed ``sched.maxQueuedSeconds``
  (falling back to queue depth when the cost model has no confident answer),
  plus per-tenant **token buckets** bounding any one tenant's admission rate;
- **SLO-burn-driven priority**: a tenant whose own burn rate crossed
  ``burnBoostThreshold`` gets its weight multiplied by ``burnBoostFactor``
  (it needs worker seconds to recover); a tenant hogging the most work while
  *another* tenant burns gets divided by it (it is spending others' budget).

Every completion feeds actual service seconds back through
:meth:`observe_completion` (wired from ``QueryServer._seal``), so consumed
work — and with it the fair-share ordering — self-corrects as the cost
model's predictions meet reality.
"""

from __future__ import annotations

import heapq
import itertools
import threading
import time
from typing import Callable, Dict, Optional

from hyperspace_tpu.serving.admission import AdmissionController, AdmissionRejected

from hyperspace_tpu.check.locks import named_lock

__all__ = ["CostAwareScheduler", "TokenBucket", "classify_cost", "COST_CLASSES"]

#: dispatch order within a tenant: interactive first, heavy last; "unknown"
#: (no confident estimate) sits between standard and heavy — an unseen shape
#: must neither jump the line nor starve
COST_CLASSES = ("interactive", "standard", "unknown", "heavy")
# derived lookup table, written once at import — process-local by design
_CLASS_RANK = {c: i for i, c in enumerate(COST_CLASSES)}  # hscheck: disable=process-local-state


def classify_cost(
    estimate,
    interactive_s: float,
    heavy_s: float,
    min_confidence: float,
) -> str:
    """Map a ``CostEstimate`` (or None) to a cost class name."""
    if estimate is None or estimate.confidence < min_confidence:
        return "unknown"
    if estimate.latency_s <= interactive_s:
        return "interactive"
    if estimate.latency_s >= heavy_s:
        return "heavy"
    return "standard"


class TokenBucket:
    """Classic token bucket with an injectable clock (deterministic tests)."""

    def __init__(self, rate: float, burst: float, clock=time.monotonic):
        self.rate = float(rate)
        self.burst = float(burst)
        self.tokens = float(burst)
        # cumulative tokens ever acquired — the ledger the fabric sidecar
        # publishes so peer processes can debit their own buckets
        self.drained_total = 0.0
        self._clock = clock
        self._last = clock()
        self._lock = named_lock("serving.sched.tokenBucket")

    def try_acquire(self, n: float = 1.0) -> bool:
        with self._lock:
            now = self._clock()
            self.tokens = min(self.burst, self.tokens + (now - self._last) * self.rate)
            self._last = now
            if self.tokens >= n:
                self.tokens -= n
                self.drained_total += n
                return True
            return False

    def drain(self, n: float) -> None:
        """Debit tokens acquired *elsewhere* (a fabric peer's admissions)
        without counting them as our own: a per-tenant rate limit then holds
        globally instead of per process. Floors at empty — remote traffic
        can exhaust the bucket but never drive it into debt."""
        with self._lock:
            self.tokens = max(0.0, self.tokens - max(0.0, float(n)))


class _TenantState:
    __slots__ = ("name", "weight", "bucket", "heap", "consumed")

    def __init__(self, name: str, weight: float, bucket: Optional[TokenBucket]):
        self.name = name
        self.weight = max(1e-9, float(weight))
        self.bucket = bucket
        self.heap: list = []  # (class rank, deadline slack, seq, predicted_s, item)
        self.consumed = 0.0  # worker seconds charged to this tenant


class CostAwareScheduler(AdmissionController):
    """Drop-in ``AdmissionController`` with cost classes, weighted fair
    dispatch, predicted-work shedding, token buckets, and burn-rate priority.

    ``cost_fn(item) -> CostEstimate | None`` and
    ``burn_rate_fn(tenant) -> float`` are injected (the server wires them to
    ``ProfileHistory.estimate_cost`` and ``SloTracker.burn_rate``) so the
    scheduler itself is a pure, clock-injectable policy object.
    """

    def __init__(
        self,
        depth: int,
        default_timeout: Optional[float],
        interactive_s: float = 0.05,
        heavy_s: float = 0.5,
        min_confidence: float = 0.3,
        max_queued_seconds: float = 0.0,
        tenant_weights: Optional[Dict[str, float]] = None,
        tenant_rate: float = 0.0,
        tenant_burst: float = 32.0,
        burn_threshold: float = 2.0,
        burn_factor: float = 2.0,
        cost_fn: Optional[Callable] = None,
        burn_rate_fn: Optional[Callable[[str], float]] = None,
        clock=time.monotonic,
    ):
        super().__init__(depth=depth, default_timeout=default_timeout)
        self.interactive_s = float(interactive_s)
        self.heavy_s = float(heavy_s)
        self.min_confidence = float(min_confidence)
        self.max_queued_seconds = float(max_queued_seconds)
        self.tenant_weights = dict(tenant_weights or {})
        self.tenant_rate = float(tenant_rate)
        self.tenant_burst = float(tenant_burst)
        self.burn_threshold = float(burn_threshold)
        self.burn_factor = max(1.0, float(burn_factor))
        self.cost_fn = cost_fn
        self.burn_rate_fn = burn_rate_fn
        self._clock = clock
        self._cv = threading.Condition(threading.RLock())
        self._tenants: Dict[str, _TenantState] = {}
        self._seq = itertools.count()
        self._queued_n = 0
        self._queued_work = 0.0  # confident predicted seconds sitting queued
        self.shed: Dict[str, int] = {}
        self._registry = None
        self._labels: Dict[str, str] = {}

    # -- classification ------------------------------------------------------
    def classify(self, item) -> str:
        est = self.cost_fn(item) if self.cost_fn is not None else None
        return classify_cost(est, self.interactive_s, self.heavy_s, self.min_confidence)

    def _predicted(self, item) -> float:
        """Confident predicted seconds for the item; 0.0 when the model has
        no confident answer (it then contributes nothing to work-based
        shedding, which degrades toward the depth bound)."""
        est = self.cost_fn(item) if self.cost_fn is not None else None
        if est is None or est.confidence < self.min_confidence:
            return 0.0
        return max(0.0, float(est.latency_s))

    # -- submission ----------------------------------------------------------
    def submit(self, item) -> None:
        tenant = getattr(item, "tenant", "default")
        cls = getattr(item, "cost_class", None) or self.classify(item)
        predicted = self._predicted(item)
        with self._cv:
            self._sweep_expired_locked()
            if self._queued_n >= self.depth:
                self._shed("depth", f"serving queue full (depth={self.depth})")
            if (
                self.max_queued_seconds > 0
                and self._queued_work + predicted > self.max_queued_seconds
                and self._queued_n > 0
            ):
                self._shed(
                    "predicted-work",
                    f"predicted queued work {self._queued_work:.2f}s exceeds "
                    f"{self.max_queued_seconds:.2f}s",
                )
            st = self._tenant(tenant)
            if st.bucket is not None and not st.bucket.try_acquire():
                self._shed("rate", f"tenant {tenant!r} admission rate exceeded")
            if not st.heap:
                # waking from idle: never owed an unbounded catch-up burst
                st.consumed = max(st.consumed, self._min_consumed_locked())
            deadline = getattr(item, "deadline", None)
            slack = float("inf") if deadline is None else deadline
            heapq.heappush(
                st.heap, (_CLASS_RANK.get(cls, 2), slack, next(self._seq), predicted, item)
            )
            self._queued_n += 1
            self._queued_work += predicted
            with self._lock:
                self.submitted += 1
            self._cv.notify()

    def _shed(self, reason: str, msg: str) -> None:
        self.shed[reason] = self.shed.get(reason, 0) + 1
        with self._lock:
            self.rejected += 1
        if self._registry is not None:
            self._registry.counter(
                "hs_sched_shed_total",
                "requests shed at admission, by reason (depth, predicted-work, rate)",
                reason=reason,
                **self._labels,
            ).inc()
        raise AdmissionRejected(msg + "; retry later")

    # -- dispatch ------------------------------------------------------------
    def take(self, timeout: float = 0.1):
        deadline = time.monotonic() + timeout
        with self._cv:
            while True:
                item = self._pop_locked()
                if item is not None:
                    return item
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return None
                self._cv.wait(remaining)

    def take_nowait(self):
        with self._cv:
            return self._pop_locked()

    def _pop_locked(self):
        while True:
            best = None
            best_key = None
            for st in self._tenants.values():
                if not st.heap:
                    continue
                key = (st.consumed / self._effective_weight(st), st.name)
                if best_key is None or key < best_key:
                    best, best_key = st, key
            if best is None:
                return None
            _, _, _, predicted, item = heapq.heappop(best.heap)
            self._queued_n -= 1
            self._queued_work = max(0.0, self._queued_work - predicted)
            check = getattr(item, "expired", None)
            if callable(check) and check():
                self.expire(item)
                continue
            # charge predicted cost at dispatch so fairness reacts
            # immediately; observe_completion corrects it with actual seconds
            best.consumed += predicted
            if hasattr(item, "sched_charge"):
                item.sched_charge = predicted
            return item

    def observe_completion(self, tenant: str, actual_s: float, charged_s: float = 0.0) -> None:
        """Fold a completion's actual service seconds into the tenant's
        consumed work (replacing the predicted charge taken at dispatch)."""
        with self._cv:
            st = self._tenants.get(tenant)
            if st is not None:
                st.consumed = max(0.0, st.consumed + max(0.0, actual_s) - charged_s)

    # -- fabric coherence (hyperspace_tpu/fabric/coherence.py) ---------------
    def drained_tokens(self) -> Dict[str, float]:
        """Cumulative tokens each tenant's bucket has granted locally — the
        sidecar publishes this ledger so peers can :meth:`external_drain`."""
        with self._cv:
            return {
                name: st.bucket.drained_total
                for name, st in self._tenants.items()
                if st.bucket is not None
            }

    def external_drain(self, tenant: str, tokens: float) -> None:
        """Debit a peer process's admissions from the tenant's local bucket
        (no-op for tenants without rate limiting)."""
        with self._cv:
            st = self._tenant(tenant)
        if st.bucket is not None:
            st.bucket.drain(tokens)

    # -- fairness internals --------------------------------------------------
    def _tenant(self, name: str) -> _TenantState:
        st = self._tenants.get(name)
        if st is None:
            bucket = None
            if self.tenant_rate > 0:
                bucket = TokenBucket(self.tenant_rate, self.tenant_burst, clock=self._clock)
            st = _TenantState(name, self.tenant_weights.get(name, 1.0), bucket)
            self._tenants[name] = st
        return st

    def _min_consumed_locked(self) -> float:
        active = [s.consumed for s in self._tenants.values() if s.heap]
        return min(active) if active else 0.0

    def _effective_weight(self, st: _TenantState) -> float:
        w = st.weight
        if self.burn_rate_fn is None:
            return w
        try:
            own = float(self.burn_rate_fn(st.name))
        except Exception:
            return w
        if own >= self.burn_threshold:
            return w * self.burn_factor  # burning its own budget: help it recover
        others_burning = any(
            o is not st and self._other_burn(o) >= self.burn_threshold
            for o in self._tenants.values()
        )
        if others_burning and st.consumed >= max(
            (o.consumed for o in self._tenants.values()), default=0.0
        ):
            return w / self.burn_factor  # hogging work while others burn
        return w

    def _other_burn(self, st: _TenantState) -> float:
        try:
            return float(self.burn_rate_fn(st.name))
        except Exception:
            return 0.0

    # -- expiry --------------------------------------------------------------
    def _sweep_expired_locked(self) -> int:
        dead = []
        for st in self._tenants.values():
            if not st.heap:
                continue
            live = []
            for entry in st.heap:
                item = entry[4]
                check = getattr(item, "expired", None)
                if callable(check) and getattr(item, "future", None) is not None and check():
                    dead.append(item)
                    self._queued_n -= 1
                    self._queued_work = max(0.0, self._queued_work - entry[3])
                else:
                    live.append(entry)
            if dead and len(live) != len(st.heap):
                heapq.heapify(live)
                st.heap = live
        for item in dead:
            self.expire(item)
        return len(dead)

    # -- observability -------------------------------------------------------
    @property
    def queued(self) -> int:
        return self._queued_n

    @property
    def queued_work_seconds(self) -> float:
        return self._queued_work

    def bind_registry(self, registry, **labels) -> None:
        super().bind_registry(registry, **labels)
        self._registry = registry
        self._labels = dict(labels)
        registry.gauge(
            "hs_sched_queued_work_seconds",
            "confident predicted seconds of queued work",
            fn=lambda: self._queued_work, **labels,
        )

    def stats(self) -> dict:
        out = super().stats()
        with self._cv:
            out["shed"] = dict(self.shed)
            out["queuedWorkSeconds"] = round(self._queued_work, 6)
            out["tenants"] = {
                name: {
                    "queued": len(st.heap),
                    "consumedSeconds": round(st.consumed, 6),
                    "weight": st.weight,
                }
                for name, st in self._tenants.items()
            }
        return out
