"""Canonical plan fingerprints for the serving runtime.

A fingerprint is a stable hash of a logical plan's *semantics*:

- **alias-invariant** — output/intermediate column aliases are canonicalized
  to the expression that defines them, so ``SELECT price AS p ... WHERE p > 5``
  and ``SELECT price AS q ... WHERE q > 5`` share a fingerprint;
- **literal-parameterized** — comparison/IN literals in filter and join
  conditions become positional slots (``?0``, ``?1``, ...), so ``price > 5``
  and ``price > 9`` share a *structure* fingerprint and differ only in the
  bound literal vector. The plan cache compiles the structure once and binds
  literals per request (prepared-statement semantics).

Expression forms whose value changes plan *shape* rather than a runtime
argument (LIKE patterns, CAST targets, function names, subquery plans, LIMIT
counts) embed their values verbatim: differing values mean a different
structure hash, never a wrong cache share. The same conservatism applies to
any expression type this module does not explicitly canonicalize — its
``repr`` (which includes its values) is embedded, making sharing exact-only.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from hyperspace_tpu.plan import logical as L
from hyperspace_tpu.plan.expr import (
    BinaryOp,
    Col,
    Expr,
    In,
    InputFileName,
    IsNull,
    Lit,
    Not,
    SubqueryExpr,
)


class Unparameterizable(Exception):
    """Raised by literal binding when a template cannot accept new literals."""


def _lit_token(v: Any) -> str:
    """Stable, value-faithful token for a literal (numpy scalars, datetimes,
    strings, numbers). Used for exact-keying and slot matching."""
    import numpy as np

    if isinstance(v, np.generic):
        v = v.item()
    if isinstance(v, np.datetime64):
        return f"dt64:{v!s}"
    return f"{type(v).__name__}:{v!r}"


@dataclass
class _Canon:
    """Mutable state threaded through one canonicalization walk."""

    lits: List[Any] = field(default_factory=list)
    sigs: List[str] = field(default_factory=list)  # per-slot context signature
    has_subquery: bool = False


# --- expressions ------------------------------------------------------------


def _canon_expr(e: Expr, env: Dict[str, str], st: _Canon, exact: bool, path: str) -> str:
    """Canonical string for ``e``. ``env`` maps in-scope column names to their
    canonical tokens; ``exact`` embeds literal values instead of slots;
    ``path`` is the root-to-node trail inside this expression, recorded as the
    slot's context signature so binding can align template slots with request
    slots unambiguously."""
    if isinstance(e, Col):
        return env.get(e.name, f"ext:{e.name}")
    if isinstance(e, Lit):
        if exact:
            return f"lit[{_lit_token(e.value)}]"
        st.sigs.append(path)
        st.lits.append(e.value)
        return f"?{len(st.lits) - 1}"
    if isinstance(e, BinaryOp):
        # the opposite operand's canonical form joins the path so `a > ?` and
        # `b > ?` slots never share a signature
        l_anchor = _canon_expr(e.left, env, st, True, path) if isinstance(e.left, Col) else ""
        r_anchor = _canon_expr(e.right, env, st, True, path) if isinstance(e.right, Col) else ""
        lc = _canon_expr(e.left, env, st, exact, f"{path}/b:{e.op}:L:{r_anchor}")
        rc = _canon_expr(e.right, env, st, exact, f"{path}/b:{e.op}:R:{l_anchor}")
        return f"({lc} {e.op} {rc})"
    if isinstance(e, Not):
        return f"not({_canon_expr(e.child, env, st, exact, path + '/not')})"
    if isinstance(e, IsNull):
        return f"isnull({_canon_expr(e.child, env, st, exact, path + '/isnull')})"
    if isinstance(e, In):
        # child is exact-only (bind never rewrites it); values are slotted
        c = _canon_expr(e.child, env, st, True, path)
        vals = [
            _canon_expr(v, env, st, exact, f"{path}/in:{c}:{i}") for i, v in enumerate(e.values)
        ]
        return f"in({c};{','.join(vals)})"
    if isinstance(e, SubqueryExpr):
        # subquery literals are structural: the inner plan's rewrite (and its
        # result) depends on them, so sharing across differing values is wrong
        st.has_subquery = True
        inner, _env = _canon_plan(e.plan, st, exact=True)
        parts = [type(e).__name__, inner]
        for c in e.children():
            parts.append(_canon_expr(c, env, st, True, path + "/subq-child"))
        return f"subq[{';'.join(parts)}]"
    if isinstance(e, InputFileName):
        return "input_file_name()"
    # Case / Like / Cast / Func / correlated forms: canonicalize any column
    # references through children() for alias-invariance where possible, but
    # embed values exactly — no literal slots inside these subtrees.
    kids = list(e.children())
    if kids:
        inner = ",".join(_canon_expr(c, env, st, True, path + "/opq") for c in kids)
        extra = _expr_attrs(e)
        return f"{type(e).__name__}[{inner};{extra}]"
    return f"{type(e).__name__}[{e!r}]"


def _expr_attrs(e: Expr) -> str:
    """Value-bearing attributes of known opaque expression types (children
    are canonicalized separately)."""
    from hyperspace_tpu.plan.expr import Case, Cast, Func, Like

    if isinstance(e, Like):
        return f"pat={e.pattern!r}"
    if isinstance(e, Cast):
        return f"as={e.type_name}"
    if isinstance(e, Func):
        return f"fn={e.name}" if hasattr(e, "name") else "fn=?"
    if isinstance(e, Case):
        return f"branches={len(e.branches)},else={e.otherwise is not None}"
    return ""


# --- plans ------------------------------------------------------------------


def _canon_plan(plan: L.LogicalPlan, st: _Canon, exact: bool = False) -> Tuple[str, Dict[str, str]]:
    """Canonical string + alias environment (output name -> canonical token)
    for ``plan``. Children canonicalize first (post-order), then the node's
    own expressions — literal-binding walks in the same order."""
    if isinstance(plan, L.Scan):
        rel = plan.relation
        env = {c: c for c in plan.output_columns}
        return f"Scan[{rel.name};{rel.file_format};{','.join(plan.output_columns)}]", env

    if isinstance(plan, L.IndexScan):
        env = {c: c for c in plan.output_columns}
        pb = "" if plan.pruned_buckets is None else f";pb={sorted(plan.pruned_buckets)}"
        return (
            f"IndexScan[{plan.entry.name}#{plan.entry.id};{','.join(plan.columns)};"
            f"nfiles={len(plan.files)}{pb}]",
            env,
        )

    if isinstance(plan, L.FileScan):
        env = {c: c for c in plan.output_columns}
        h = hashlib.sha1("\x00".join(plan.files).encode()).hexdigest()[:12]
        return (
            f"FileScan[{h};{plan.file_format};{','.join(plan.columns)};via={plan.via_index}]",
            env,
        )

    if isinstance(plan, L.Filter):
        child, env = _canon_plan(plan.child, st, exact)
        cond = _canon_expr(plan.condition, env, st, exact, "F")
        return f"Filter[{cond}]({child})", env

    if isinstance(plan, L.Project):
        child, env = _canon_plan(plan.child, st, exact)
        cols = [env.get(c, f"ext:{c}") for c in plan.columns]
        out_env = {c: env.get(c, f"ext:{c}") for c in plan.columns}
        return f"Project[{','.join(cols)}]({child})", out_env

    if isinstance(plan, L.Compute):
        child, env = _canon_plan(plan.child, st, exact)
        out_env = dict(env)
        parts = []
        for n, e in plan.exprs:
            ce = _canon_expr(e, env, st, exact, f"C:{len(parts)}")
            out_env[n] = f"<{ce}>"
            parts.append(ce)
        return f"Compute[{';'.join(parts)}]({child})", out_env

    if isinstance(plan, L.Rename):
        child, env = _canon_plan(plan.child, st, exact)
        # pure aliasing: canonical form is the child's; only the env remaps
        out_env = {plan.mapping.get(c, c): env.get(c, f"ext:{c}") for c in plan.child.output_columns}
        return child, out_env

    if isinstance(plan, L.Join):
        lc, lenv = _canon_plan(plan.left, st, exact)
        rc, renv = _canon_plan(plan.right, st, exact)
        combined: Dict[str, str] = {}
        for k, v in lenv.items():
            combined[k] = f"L:{v}"
        for k, v in renv.items():
            combined[k] = f"B:{combined[k]}|R:{v}" if k in combined else f"R:{v}"
        cond = _canon_expr(plan.condition, combined, st, exact, "J")
        resid = (
            _canon_expr(plan.residual, _join_out_env(plan, lenv, renv), st, True, "Jr")
            if plan.residual is not None
            else ""
        )
        up = ""
        if plan.using_pairs:
            up = ";".join(f"{combined.get(a, a)}~{combined.get(b, b)}" for a, b in plan.using_pairs)
        out_env = _join_out_env(plan, lenv, renv)
        return f"Join[{plan.how};{cond};resid={resid};using={up}]({lc})({rc})", out_env

    if isinstance(plan, (L.Union, L.BucketUnion)):
        parts, env0 = [], None
        for c in plan.children():
            cc, cenv = _canon_plan(c, st, exact)
            parts.append(cc)
            if env0 is None:
                env0 = cenv
        tag = type(plan).__name__
        return f"{tag}[{';'.join(parts)}]", env0 or {}

    if isinstance(plan, L.SetOp):
        lc, lenv = _canon_plan(plan.left, st, exact)
        rc, _renv = _canon_plan(plan.right, st, exact)
        return f"SetOp[{plan.kind}]({lc})({rc})", lenv

    if isinstance(plan, L.Aggregate):
        child, env = _canon_plan(plan.child, st, exact)
        keys = [env.get(k, f"ext:{k}") for k in plan.keys]
        out_env = {k: env.get(k, f"ext:{k}") for k in plan.keys}
        parts = []
        for name, fn, col_ in plan.aggs:
            tok = f"{fn}({env.get(col_, col_) if col_ is not None else '*'})"
            out_env[name] = f"<{tok}#{len(parts)}>"
            parts.append(tok)
        return f"Aggregate[{','.join(keys)};{';'.join(parts)}]({child})", out_env

    if isinstance(plan, L.Window):
        child, env = _canon_plan(plan.child, st, exact)
        out_env = dict(env)
        parts = []
        for out, fn, arg, pcols, orders, cumulative in plan.specs:
            tok = (
                f"{fn}({env.get(arg, arg) if arg else ''})"
                f"p={[env.get(c, c) for c in (pcols or [])]}"
                f"o={[(env.get(c, c), a) for c, a in (orders or [])]}cum={bool(cumulative)}"
            )
            out_env[out] = f"<{tok}#{len(parts)}>"
            parts.append(tok)
        return f"Window[{';'.join(parts)}]({child})", out_env

    if isinstance(plan, L.Sort):
        child, env = _canon_plan(plan.child, st, exact)
        keys = [(env.get(c, f"ext:{c}"), bool(a)) for c, a in plan.keys]
        return f"Sort[{keys}]({child})", env

    if isinstance(plan, L.Limit):
        child, env = _canon_plan(plan.child, st, exact)
        # LIMIT count is structural: it changes result cardinality, and
        # nothing downstream re-binds it at run time
        return f"Limit[{plan.n}]({child})", env

    if isinstance(plan, L.Repartition):
        child, env = _canon_plan(plan.child, st, exact)
        bs = plan.bucket_spec
        return (
            f"Repartition[{bs.num_buckets};{list(bs.bucket_columns)};{list(bs.sort_columns)}]({child})",
            env,
        )

    # unknown node: positional fallback on describe() + children (exact-only
    # sharing — describe embeds the node's values)
    parts = []
    env_last: Dict[str, str] = {}
    for c in plan.children():
        cc, env_last = _canon_plan(c, st, exact)
        parts.append(cc)
    return f"{type(plan).__name__}[{plan.describe()}]({';'.join(parts)})", env_last


def _join_out_env(plan: L.Join, lenv: Dict[str, str], renv: Dict[str, str]) -> Dict[str, str]:
    out_names, rename = L.join_output_names(plan.left.output_columns, plan.right.output_columns)
    env: Dict[str, str] = {}
    for c in plan.left.output_columns:
        env[c] = f"L:{lenv.get(c, c)}"
    for c in plan.right.output_columns:
        env[rename.get(c, c)] = f"R:{renv.get(c, c)}"
    return env


# --- public surface ---------------------------------------------------------


@dataclass(frozen=True)
class Fingerprint:
    """Canonical identity of one query plan.

    ``structure`` hashes the literal-parameterized canonical form; plans that
    differ only in bound literals (or in column aliases) share it.
    ``literals`` is the slot-ordered literal vector; ``slot_sigs`` are the
    per-slot context signatures used to align template slots at bind time.
    """

    structure: str
    literals: Tuple[Any, ...]
    slot_sigs: Tuple[str, ...]
    output_columns: Tuple[str, ...]
    has_subquery: bool

    @property
    def exact(self) -> str:
        h = hashlib.sha1(self.structure.encode())
        for v in self.literals:
            h.update(b"\x00")
            h.update(_lit_token(v).encode())
        return h.hexdigest()


def plan_fingerprint(plan: L.LogicalPlan) -> Fingerprint:
    """Fingerprint ``plan``. Deterministic within a process for a fixed set of
    source relations (relation identity is path-based)."""
    st = _Canon()
    canon, _env = _canon_plan(plan, st)
    return Fingerprint(
        structure=hashlib.sha1(canon.encode()).hexdigest(),
        literals=tuple(st.lits),
        slot_sigs=tuple(st.sigs),
        output_columns=tuple(plan.output_columns),
        has_subquery=st.has_subquery,
    )


def canonical_form(plan: L.LogicalPlan) -> str:
    """The raw canonical string (debugging / tests)."""
    return _canon_plan(plan, _Canon())[0]


# --- literal binding --------------------------------------------------------


def slot_mapping(template_fp: Fingerprint, request_fp: Fingerprint) -> List[int]:
    """Map each *template* slot to the *request* slot it must be bound from.

    Matches by context signature alone (the request's literal VALUES differ
    from the template's by design — that's the point of parameterization).
    Strictness guards correctness: signatures must be unique on both sides
    and must cover each other exactly — any ambiguity (two slots in the same
    context) or a dropped/synthesized literal raises ``Unparameterizable``
    and the cache falls back to exact keying.
    """
    req: Dict[str, int] = {}
    for j, sig in enumerate(request_fp.slot_sigs):
        if sig in req:
            raise Unparameterizable(f"ambiguous request literal slot {sig!r}")
        req[sig] = j
    seen = set()
    mapping = []
    for sig in template_fp.slot_sigs:
        if sig in seen:
            raise Unparameterizable(f"ambiguous template literal slot {sig!r}")
        seen.add(sig)
        j = req.get(sig)
        if j is None:
            raise Unparameterizable(f"template literal {sig!r} not present in request")
        mapping.append(j)
    if len(seen) != len(req):
        # a request literal the template never consumes: the optimized plan
        # may have encoded it some other way — do not share
        raise Unparameterizable("request literal unused by template")
    return mapping


def _bind_expr(e: Expr, values: List[Any], pos: List[int]) -> Expr:
    """Rebuild ``e`` with slot-eligible literals replaced positionally from
    ``values``. Walk order MUST mirror ``_canon_expr``'s slot collection; the
    same node types participate, all others pass through untouched."""
    if isinstance(e, Lit):
        i = pos[0]
        pos[0] += 1
        return Lit(values[i])
    if isinstance(e, BinaryOp):
        return BinaryOp(e.op, _bind_expr(e.left, values, pos), _bind_expr(e.right, values, pos))
    if isinstance(e, Not):
        return Not(_bind_expr(e.child, values, pos))
    if isinstance(e, IsNull):
        return IsNull(_bind_expr(e.child, values, pos))
    if isinstance(e, In):
        return In(e.child, [_bind_expr(v, values, pos) for v in e.values])
    return e


def count_slots(e: Expr) -> int:
    """Number of slot-eligible literals ``_canon_expr``/``_bind_expr`` see in
    ``e`` (binding sanity check)."""
    if isinstance(e, Lit):
        return 1
    if isinstance(e, BinaryOp):
        return count_slots(e.left) + count_slots(e.right)
    if isinstance(e, (Not, IsNull)):
        return count_slots(e.child)
    if isinstance(e, In):
        return sum(count_slots(v) for v in e.values)
    return 0


def bind_literals(plan: L.LogicalPlan, slot_values: List[Any]) -> L.LogicalPlan:
    """Rebuild ``plan`` with its i-th literal slot bound to ``slot_values[i]``
    (template-slot order). Untouched subtrees keep identity, so cached scan
    nodes (and their tags) are shared across bound instances."""
    pos = [0]

    def walk(p: L.LogicalPlan) -> L.LogicalPlan:
        children = list(p.children())
        new_children = [walk(c) for c in children]
        q = p
        if any(nc is not c for nc, c in zip(new_children, children)):
            q = p.with_children(new_children)
        if isinstance(q, L.Filter):
            new_cond = _bind_expr(q.condition, slot_values, pos)
            q = L.Filter(new_cond, q.child)
        elif isinstance(q, L.Join):
            new_cond = _bind_expr(q.condition, slot_values, pos)
            q = L.Join(q.left, q.right, new_cond, q.how, q.residual, q.using_pairs)
        elif isinstance(q, L.Compute):
            new_exprs = [(n, _bind_expr(e, slot_values, pos)) for n, e in q.exprs]
            q = L.Compute(new_exprs, q.child)
        return q

    out = walk(plan)
    if pos[0] != len(slot_values):
        raise Unparameterizable(
            f"bound {pos[0]} slots but template has {len(slot_values)} literals"
        )
    return out
