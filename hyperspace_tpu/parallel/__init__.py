"""Mesh-sharded execution: mesh helpers, shard_map programs, HLO assertions.

Eagerly exported: the mesh/layout helpers and the compiled-HLO collective
assertions (pure jax + regex, no heavy imports). ``ShardedExecutor`` and the
collectives module load lazily — they import ``exec/device.py``'s program
machinery, which callers of a bare ``make_mesh`` should not pay for.
"""

from hyperspace_tpu.parallel.hlo_check import (
    assert_collectives,
    assert_shuffle_free,
    collective_counts,
    hlo_text_of,
)
from hyperspace_tpu.parallel.mesh import (
    DEFAULT_AXIS,
    device_of_bucket,
    get_shard_map,
    make_mesh,
    make_mesh_2d,
    mesh_fingerprint,
    replicated,
    sharded,
    sharded_2d,
)

__all__ = [
    "DEFAULT_AXIS",
    "ShardedExecutor",
    "assert_collectives",
    "assert_shuffle_free",
    "collective_counts",
    "device_of_bucket",
    "get_shard_map",
    "hlo_text_of",
    "make_mesh",
    "make_mesh_2d",
    "mesh_fingerprint",
    "replicated",
    "sharded",
    "sharded_2d",
]


def __getattr__(name):
    if name == "ShardedExecutor":
        from hyperspace_tpu.parallel.executor import ShardedExecutor

        return ShardedExecutor
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
