"""Compat shim — the compiled-HLO inspection helpers moved to
:mod:`hyperspace_tpu.check.hlo_lint`, where they grew into a declared
program-contract rule engine (collective budgets + forbidden-op patterns per
device-program family). Import sites keep working; new code should import
from ``hyperspace_tpu.check.hlo_lint`` and prefer ``verify_hlo`` /
``assert_contract`` over raw count assertions."""

from __future__ import annotations

from hyperspace_tpu.check.hlo_lint import (  # noqa: F401
    COLLECTIVE_OPS,
    SHUFFLE_OPS,
    assert_collectives,
    assert_shuffle_free,
    collective_counts,
    hlo_text_of,
)

__all__ = [
    "COLLECTIVE_OPS",
    "SHUFFLE_OPS",
    "assert_collectives",
    "assert_shuffle_free",
    "collective_counts",
    "hlo_text_of",
]
