"""Compiled-HLO collective inspection.

The framework's core architectural claims (SURVEY.md §2.9, mirroring the
reference's shuffle-freedom guarantees, ref: HS/index/covering/
JoinIndexRule.scala:604-618) are properties of the *compiled program*:

- the bucketed equi-join runs with NO data exchange (no all-to-all /
  all-gather / collective-permute — co-sharded buckets join device-locally;
  only the query's own aggregate may all-reduce),
- the distributed index build exchanges rows with exactly ONE all-to-all
  (the packed-plane exchange, ops/bucketize.py ``_exchange_packed``),
- the hierarchical DCN x ICI re-bucketing uses exactly TWO (one per phase).

These helpers scan ``jit(...).lower(...).compile().as_text()`` so the claims
are asserted from the HLO itself (``__graft_entry__.dryrun_multichip`` and
tests/test_hlo_collectives.py), not from reading the Python.
"""

from __future__ import annotations

import re
from typing import Dict

COLLECTIVE_OPS = (
    "all-to-all",
    "all-gather",
    "collective-permute",
    "all-reduce",
    "reduce-scatter",
)

# an HLO op application site: ` op-name(` or ` op-name-start(` — the result
# type before it may be a tuple containing spaces, so key on the call itself;
# operand mentions like `get-tuple-element(%all-to-all)` don't match (no
# following paren), and metadata op_name strings use underscores, not dashes.
# Async pairs (op-start/op-done) count once at -start.
_INSTR = re.compile(
    r"[\s)](" + "|".join(COLLECTIVE_OPS) + r")(-start|-done)?(?:\.\d+)?\("
)


def collective_counts(hlo_text: str) -> Dict[str, int]:
    """Occurrences of each collective op in compiled HLO text (async
    start/done pairs counted once)."""
    counts = {k: 0 for k in COLLECTIVE_OPS}
    for m in _INSTR.finditer(hlo_text):
        if m.group(2) == "-done":
            continue
        counts[m.group(1)] += 1
    return counts


def assert_collectives(hlo_text: str, expect: Dict[str, int], context: str = "") -> None:
    """Assert exact counts for the ops named in ``expect`` and ZERO for every
    other collective op."""
    got = collective_counts(hlo_text)
    for op in COLLECTIVE_OPS:
        want = expect.get(op, 0)
        assert got[op] == want, (
            f"{context or 'program'}: expected {want} x {op} in compiled HLO, "
            f"found {got[op]} (all counts: {got})"
        )


# ops that move row data between devices: their absence is the reference's
# shuffle-freedom claim (ref: JoinIndexRule.scala:604-618). all-reduce stays
# out of this set — a scalar reduction is not a data shuffle.
SHUFFLE_OPS = ("all-to-all", "all-gather", "collective-permute", "reduce-scatter")


def assert_shuffle_free(hlo_text: str, context: str = "") -> None:
    """Assert the compiled program exchanges NO row data between devices
    (no all-to-all / all-gather / collective-permute / reduce-scatter)."""
    got = collective_counts(hlo_text)
    bad = {op: got[op] for op in SHUFFLE_OPS if got[op]}
    assert not bad, (
        f"{context or 'program'}: expected a shuffle-free program but the "
        f"compiled HLO contains data-movement collectives {bad} "
        f"(all counts: {got})"
    )


def hlo_text_of(jitted, *args, **kwargs) -> str:
    """Compiled HLO text of a jitted callable for the given example
    arguments — the artifact the assertions above inspect."""
    return jitted.lower(*args, **kwargs).compile().as_text()
