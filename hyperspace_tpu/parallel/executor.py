"""ShardedExecutor: the conf-gated entry point of the mesh-sharded path.

One instance per (session, mesh shape): owns the 1-D ``("buckets",)`` mesh
the sharded programs run over, and is threaded (as the ``parallel=`` argument)
through ``exec/device.py``'s filter / grouped-aggregate entry points, which
switch from GSPMD jit to the explicit ``shard_map`` programs in
``parallel/collectives.py`` when it is present.

Gating (``ShardedExecutor.maybe``): ``hyperspace.parallel.enabled`` is the
default-off master switch — when off, ``maybe`` returns None and every caller
falls through to the byte-identical single-device path. The mesh spans
``hyperspace.parallel.mesh.devices`` devices (0 = all local devices) on the
session's bucket axis; chunks below ``hyperspace.parallel.minRows`` rows stay
on the single-device path even when the switch is on (per-shard padding and
the collective merge would dominate).

On CPU CI the mesh is emulated: conftest.py forces
``XLA_FLAGS=--xla_force_host_platform_device_count=8`` so the sharded =
single-device oracle tests (tests/test_mesh_exec.py) are tier-1.
"""

from __future__ import annotations

import time
from typing import Optional

from hyperspace_tpu.parallel.mesh import make_mesh, mesh_fingerprint


class ShardedExecutor:
    """Holds the execution mesh and the sharded-path metrics instruments."""

    def __init__(self, session, mesh=None):
        conf = session.conf
        if mesh is None:
            n = conf.parallel_mesh_devices
            mesh = make_mesh(n if n > 0 else None, axis=conf.mesh_axis)
        self.session = session
        self.mesh = mesh
        self.axis = mesh.axis_names[0]
        self.fingerprint = mesh_fingerprint(mesh)
        # the sharded path stages with THIS mesh's device count: align the
        # native decode fast path's buffer padding so staging stays zero-copy
        from hyperspace_tpu.exec import io as _io

        _io.set_staging_pad(int(mesh.devices.size))
        self.min_rows = conf.parallel_min_rows
        from hyperspace_tpu.obs.metrics import REGISTRY

        REGISTRY.gauge(
            "hs_mesh_devices",
            "Devices in the sharded-execution mesh (0 when the parallel path is off)",
        ).set(mesh.devices.size)

    # -- gating ---------------------------------------------------------------

    @classmethod
    def maybe(cls, session) -> Optional["ShardedExecutor"]:
        """The session's executor, or None when ``hyperspace.parallel.enabled``
        is off. Memoized on the session per mesh-shaping conf so repeated
        queries reuse one mesh (and its jit/device caches)."""
        conf = session.conf
        if not conf.parallel_enabled:
            return None
        key = (conf.parallel_mesh_devices, conf.mesh_axis)
        cached = getattr(session, "_parallel_executor", None)
        if cached is not None and cached[0] == key:
            return cached[1]
        px = cls(session)
        session._parallel_executor = (key, px)
        return px

    def rows_ok(self, n_rows: int) -> bool:
        return n_rows >= self.min_rows

    # -- metrics --------------------------------------------------------------

    def note_op(self, op: str) -> None:
        from hyperspace_tpu.obs.metrics import REGISTRY

        REGISTRY.counter(
            "hs_mesh_sharded_ops_total",
            "Operations executed through the mesh-sharded path",
            op=op,
        ).inc()

    def timed_call(self, op: str, fn, *args):
        """Run one sharded program synchronously, attributing its wall time
        (including the collective merge) to ``hs_mesh_collective_seconds_total``."""
        import jax

        from hyperspace_tpu.obs.metrics import REGISTRY

        self.note_op(op)
        t0 = time.perf_counter()
        out = fn(*args)
        jax.block_until_ready(out)
        REGISTRY.counter(
            "hs_mesh_collective_seconds_total",
            "Cumulative wall time of sharded programs incl. collective merges (seconds)",
        ).inc(time.perf_counter() - t0)
        return out

    # -- public execution API -------------------------------------------------

    def filter_mask(self, batch, condition, scan_key=None):
        """Sharded twin of ``device.device_filter_mask``."""
        from hyperspace_tpu.exec import device as D

        return D.device_filter_mask(
            self.session, batch, condition, scan_key=scan_key, parallel=self
        )

    def grouped_aggregate(
        self, batch, condition, group_keys, aggs, scan_key=None, *, max_groups, cap_floor
    ):
        """Sharded twin of ``device.device_grouped_aggregate``."""
        from hyperspace_tpu.exec import device as D

        return D.device_grouped_aggregate(
            self.session, batch, condition, group_keys, aggs, scan_key,
            max_groups=max_groups, cap_floor=cap_floor, parallel=self,
        )

    def grouped_stream(self, group_keys, aggs, *, max_groups, cap_floor, hint_key=None):
        """A ``GroupedAggStream`` whose chunk programs run sharded."""
        from hyperspace_tpu.exec import device as D

        return D.GroupedAggStream(
            self.session, group_keys, aggs,
            max_groups=max_groups, cap_floor=cap_floor, hint_key=hint_key,
            parallel=self,
        )
