"""Multi-process runtime startup (SURVEY.md §5.8 distributed backend).

The reference inherits Spark's cluster runtime; here a multi-host device
mesh comes up through ``jax.distributed``: every process calls
``initialize_from_env()`` before touching devices, then ``make_mesh_2d``
(parallel/mesh.py) aligns its ``dcn`` axis with process boundaries — so the
hierarchical re-bucketing exchange (ops/bucketize.rebucket_hierarchical)
keeps phase-1 ``all_to_all`` traffic on the fast intra-host/ICI links and
crosses the process (DCN) boundary exactly once per row.

Configuration, by env var or keyword:

  HS_COORDINATOR     ``host:port`` of process 0's coordinator service
                     (default ``127.0.0.1:29500``)
  HS_NUM_PROCESSES   world size
  HS_PROCESS_ID      this process's rank in [0, world size)

On a real TPU pod slice, ``jax.distributed.initialize()`` with no arguments
discovers all of this from the TPU metadata service; the env-var path exists
for CPU smoke tests and non-TPU clusters. A two-process localhost CPU run is
exercised by tests/test_multihost.py.
"""

from __future__ import annotations

import os
from typing import Optional

#: True only when THIS module called jax.distributed.initialize — shutdown()
#: must never tear down a runtime a launcher owns
_owns_runtime = False


def configured_from_env() -> bool:
    """True when the env explicitly configures a multi-process world (both
    HS_NUM_PROCESSES > 1 and HS_PROCESS_ID set)."""
    n = _int_env("HS_NUM_PROCESSES")
    return n is not None and n > 1 and _int_env("HS_PROCESS_ID") is not None


def initialize_from_env(
    coordinator: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> bool:
    """Initialize the multi-process JAX runtime from env/kwargs.

    Returns True when a multi-process runtime is up after the call — whether
    this call started it, a previous one did, or a launcher initialized
    jax.distributed itself; False in single-process mode. Idempotent."""
    global _owns_runtime
    if _owns_runtime or _jax_runtime_up():
        return True
    num_processes = num_processes if num_processes is not None else _int_env("HS_NUM_PROCESSES")
    if num_processes is None or num_processes <= 1:
        return False
    process_id = process_id if process_id is not None else _int_env("HS_PROCESS_ID")
    if process_id is None:
        raise ValueError("HS_PROCESS_ID must be set when HS_NUM_PROCESSES > 1")
    coordinator = coordinator or os.environ.get("HS_COORDINATOR", "127.0.0.1:29500")

    import jax

    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
    )
    _owns_runtime = True
    return True


def _jax_runtime_up() -> bool:
    try:
        from jax._src import distributed as _dist

        return _dist.global_state.client is not None
    except Exception:
        return False


def shutdown() -> None:
    """Tear down the runtime — only if this module started it."""
    global _owns_runtime
    if _owns_runtime:
        import jax

        jax.distributed.shutdown()
        _owns_runtime = False


def _int_env(name: str) -> Optional[int]:
    v = os.environ.get(name)
    return int(v) if v not in (None, "") else None
