"""Multi-process runtime startup (SURVEY.md §5.8 distributed backend).

The reference inherits Spark's cluster runtime; here a multi-host device
mesh comes up through ``jax.distributed``: every process calls
``initialize_from_env()`` before touching devices, then ``make_mesh_2d``
(parallel/mesh.py) aligns its ``dcn`` axis with process boundaries — so the
hierarchical re-bucketing exchange (ops/bucketize.rebucket_hierarchical)
keeps phase-1 ``all_to_all`` traffic on the fast intra-host/ICI links and
crosses the process (DCN) boundary exactly once per row.

Configuration, by env var or keyword:

  HS_COORDINATOR     ``host:port`` of process 0's coordinator service
                     (default ``127.0.0.1:29500``)
  HS_NUM_PROCESSES   world size
  HS_PROCESS_ID      this process's rank in [0, world size)

On a real TPU pod slice, ``jax.distributed.initialize()`` with no arguments
discovers all of this from the TPU metadata service; the env-var path exists
for CPU smoke tests and non-TPU clusters. A two-process localhost CPU run is
exercised by tests/test_multihost.py.
"""

from __future__ import annotations

import os
from typing import Optional

_initialized = False


def initialize_from_env(
    coordinator: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> bool:
    """Initialize the multi-process JAX runtime from env/kwargs.

    Returns True if ``jax.distributed.initialize`` ran, False when no
    multi-process configuration is present (single-process mode: a no-op so
    the same entry point works everywhere). Idempotent."""
    global _initialized
    if _initialized:
        return True
    num_processes = num_processes if num_processes is not None else _int_env("HS_NUM_PROCESSES")
    if num_processes is None or num_processes <= 1:
        return False
    if _jax_runtime_up():
        # a launcher already called jax.distributed.initialize() itself
        # (e.g. the no-argument TPU-pod path); don't initialize twice
        _initialized = True
        return True
    process_id = process_id if process_id is not None else _int_env("HS_PROCESS_ID")
    if process_id is None:
        raise ValueError("HS_PROCESS_ID must be set when HS_NUM_PROCESSES > 1")
    coordinator = coordinator or os.environ.get("HS_COORDINATOR", "127.0.0.1:29500")

    import jax

    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
    )
    _initialized = True
    return True


def _jax_runtime_up() -> bool:
    try:
        from jax._src import distributed as _dist

        return _dist.global_state.client is not None
    except Exception:
        return False


def shutdown() -> None:
    global _initialized
    if _initialized:
        import jax

        jax.distributed.shutdown()
        _initialized = False


def _int_env(name: str) -> Optional[int]:
    v = os.environ.get(name)
    return int(v) if v not in (None, "") else None
