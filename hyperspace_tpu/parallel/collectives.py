"""shard_map programs for the mesh-sharded execution path.

``exec/device.py`` compiles every program with plain ``jax.jit`` and lets
GSPMD partition it over the session mesh. The programs here are the explicit
alternative behind ``hyperspace.parallel.enabled``: a ``shard_map`` over the
1-D bucket axis runs the SAME fused filter / grouped-agg program body
per-shard, then merges per-shard partial-aggregate tables ON DEVICE with one
``all_gather`` + the shared segment-reduce merge core
(``device._merge_concat_parts``) — no host loop over shards, O(cap) bytes on
the interconnect instead of O(rows).

Signature parity is deliberate: each builder returns a program with exactly
the call convention of its single-device twin, so ``GroupedAggStream`` and
``device_filter_mask`` swap them in under the same jit cache (keyed by
``device._program_key``'s mode tag) with no other changes.
"""

from __future__ import annotations

from functools import partial

import numpy as np

from hyperspace_tpu.exec import device as D
from hyperspace_tpu.parallel.mesh import get_shard_map


def sharded_elementwise(mesh, axis, fn):
    """Wrap an elementwise program (predicate mask) in a shard_map over
    ``axis``: each device evaluates its own row block, outputs concatenate
    back to the global row order. No collectives — compiled HLO is
    shuffle-free (tests/test_hlo_collectives.py)."""
    from jax.sharding import PartitionSpec as P

    shard_map = get_shard_map()

    @partial(shard_map, mesh=mesh, in_specs=(P(axis), P()), out_specs=P(axis))
    def mapped(cols, lits):
        return fn(cols, lits)

    return mapped


def sharded_topk_chunk_program(mesh, axis, num_keys, cap):
    """Sharded twin of ``ops.sort.topk_chunk_fn``: same signature (one
    ``(num_keys + 1, P)`` plane matrix in, one ``(num_keys + 1, cap)``
    candidate matrix out), swapped in under ``device._program_key``'s
    ``shmap`` mode tag by ``TopKStream``.

    Per shard: one multi-operand ``lax.sort`` over the LOCAL plane rows and a
    static take/pad to ``cap`` candidates. Then EXACTLY one fixed-size
    ``all_gather`` of the per-shard candidate matrices — ``n_dev * cap``
    *candidates* on the interconnect, never rows — and a replicated final
    sort down to ``cap``. The trailing row-id plane makes the order total, so
    the result is bit-identical to the single-device program on the same
    matrix (registered HLO contract ``sharded-topk``)."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from hyperspace_tpu.ops.sort import _TOPK_SENTINEL, _take_cap

    shard_map = get_shard_map()
    n_dev = mesh.devices.size

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(None, axis),),
        out_specs=P(),
        check_rep=False,
    )
    def program(planes):
        local = lax.sort(
            tuple(planes[i] for i in range(num_keys + 1)),
            num_keys=num_keys + 1,
            is_stable=False,
        )
        mine = jnp.stack([_take_cap(o, cap, _TOPK_SENTINEL) for o in local])
        gathered = jax.lax.all_gather(mine, axis)  # (n_dev, K+1, cap)
        cat = jnp.transpose(gathered, (1, 0, 2)).reshape(num_keys + 1, n_dev * cap)
        merged = lax.sort(
            tuple(cat[i] for i in range(num_keys + 1)),
            num_keys=num_keys + 1,
            is_stable=False,
        )
        return jnp.stack([_take_cap(o, cap, _TOPK_SENTINEL) for o in merged])

    return program


def sharded_grouped_chunk_program(mesh, axis, pred_fn, key_specs, slot_specs, cap):
    """Sharded twin of ``device._grouped_chunk_program``: same signature
    ``program(cols, lits, n_valid, row_base)``, same outputs
    ``(n_groups, first-seen, key reps, state slots)``.

    Per shard: fused predicate + segment reduction over the local row block
    (rows arrive block-sharded by ``NamedSharding(P(axis))``, so device ``d``
    holds global rows ``[d*per, (d+1)*per)``). Then ONE all_gather of the
    per-shard partial tables (``n_dev * cap`` rows — group cardinality, not
    row count) and a replicated ``_merge_concat_parts`` pass; shard-major
    concat order IS ascending global-row order, so first-seen representatives
    match the single-device program bit-for-bit.

    Overflow: a shard whose LOCAL cardinality exceeded ``cap`` dropped groups
    in its own table, which can leave the merged count deceptively <= cap —
    the returned ``n_groups`` is maxed with every shard's local count so the
    caller's right-sizing loop re-runs at a larger capacity.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    shard_map = get_shard_map()
    n_dev = mesh.devices.size

    def program(cols, lits, n_valid, row_base):
        @partial(
            shard_map,
            mesh=mesh,
            in_specs=(P(axis), P(), P(), P()),
            out_specs=(P(), P(), P(), P()),
            check_rep=False,
        )
        def per_shard(cols_, lits_, n_valid_, row_base_):
            per = next(iter(cols_.values())).shape[0]
            d = jax.lax.axis_index(axis).astype(jnp.int64)
            gidx = d * per + jnp.arange(per, dtype=jnp.int64)
            valid = gidx < n_valid_
            mask = valid if pred_fn is None else (pred_fn(cols_, lits_) & valid)
            codes = [D._key_code(cols_[name], tag) for name, tag in key_specs]
            order, ms, ng_local, segs = D._segment_ids(codes, mask, cap)
            from jax import ops as jops

            rep = jops.segment_min(
                jnp.where(ms, order.astype(jnp.int64), jnp.int64(per)),
                segs, num_segments=cap, indices_are_sorted=True,
            )
            repc = jnp.clip(rep, 0, per - 1)
            # first-seen is a GLOBAL row index: local rep + shard base + chunk base
            fs_local = jnp.where(rep < per, rep + d * per + row_base_, D._FS_SENTINEL)
            keys_local = tuple(cols_[name][repc] for name, _ in key_specs)
            cols_sorted = {c: cols_[c][order] for _, c, _ in slot_specs if c is not None}
            slots_local = D._segment_reduce_slots(cols_sorted, ms, segs, cap, slot_specs)

            ng_all = jax.lax.all_gather(ng_local, axis)
            fs_all = jax.lax.all_gather(fs_local, axis).reshape(n_dev * cap)
            keys_all = tuple(
                jax.lax.all_gather(k, axis).reshape(n_dev * cap) for k in keys_local
            )
            slots_all = tuple(
                jax.lax.all_gather(s, axis).reshape(n_dev * cap) for s in slots_local
            )
            part_mask = (
                jnp.arange(cap, dtype=jnp.int64)[None, :] < ng_all[:, None]
            ).reshape(n_dev * cap)
            n_g, fs, key_out, slot_out = D._merge_concat_parts(
                key_specs, slot_specs, cap, keys_all, slots_all, fs_all, part_mask
            )
            n_g = jnp.maximum(n_g, jnp.max(ng_all))
            return n_g, fs, key_out, slot_out

        return per_shard(cols, lits, n_valid, row_base)

    return program


def sharded_fused_grouped_program(mesh, axis, pred_fn, key_specs, slot_specs, cap):
    """Sharded twin of ``device._fused_grouped_update_program``: the whole
    streamed fold — per-shard chunk select, the all_gather table merge AND
    the merge into the running (replicated) partial — as ONE program, so a
    chunk costs a single dispatch under ``hyperspace.exec.fusion.enabled``.

    Same signature as the single-device fused program:
    ``program(state_keys, state_slots, state_fs, state_n, cols, lits,
    n_valid, row_base) -> (n_b, n_m, n_out, fs_out, keys_out, slots_out)``.

    Overflow contract matches the single-device twin, with the sharded
    subtlety folded in: ``n_b`` is maxed with every shard's LOCAL cardinality
    (a shard over ``cap`` silently dropped groups in its own table), and any
    overflow makes every state output select the ORIGINAL state so the host
    can redo the chunk per-family.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    shard_map = get_shard_map()
    n_dev = mesh.devices.size

    def program(state_keys, state_slots, state_fs, state_n, cols, lits, n_valid, row_base):
        @partial(
            shard_map,
            mesh=mesh,
            in_specs=(P(), P(), P(), P(), P(axis), P(), P(), P()),
            out_specs=(P(), P(), P(), P(), P(), P()),
            check_rep=False,
        )
        def per_shard(state_keys_, state_slots_, state_fs_, state_n_, cols_, lits_, n_valid_, row_base_):
            per = next(iter(cols_.values())).shape[0]
            d = jax.lax.axis_index(axis).astype(jnp.int64)
            gidx = d * per + jnp.arange(per, dtype=jnp.int64)
            valid = gidx < n_valid_
            mask = valid if pred_fn is None else (pred_fn(cols_, lits_) & valid)
            codes = [D._key_code(cols_[name], tag) for name, tag in key_specs]
            order, ms, ng_local, segs = D._segment_ids(codes, mask, cap)
            from jax import ops as jops

            rep = jops.segment_min(
                jnp.where(ms, order.astype(jnp.int64), jnp.int64(per)),
                segs, num_segments=cap, indices_are_sorted=True,
            )
            repc = jnp.clip(rep, 0, per - 1)
            fs_local = jnp.where(rep < per, rep + d * per + row_base_, D._FS_SENTINEL)
            keys_local = tuple(cols_[name][repc] for name, _ in key_specs)
            cols_sorted = {c: cols_[c][order] for _, c, _ in slot_specs if c is not None}
            slots_local = D._segment_reduce_slots(cols_sorted, ms, segs, cap, slot_specs)

            ng_all = jax.lax.all_gather(ng_local, axis)
            fs_all = jax.lax.all_gather(fs_local, axis).reshape(n_dev * cap)
            keys_all = tuple(
                jax.lax.all_gather(k, axis).reshape(n_dev * cap) for k in keys_local
            )
            slots_all = tuple(
                jax.lax.all_gather(s, axis).reshape(n_dev * cap) for s in slots_local
            )
            part_mask = (
                jnp.arange(cap, dtype=jnp.int64)[None, :] < ng_all[:, None]
            ).reshape(n_dev * cap)
            n_b, fs_b, key_b, slot_b = D._merge_concat_parts(
                key_specs, slot_specs, cap, keys_all, slots_all, fs_all, part_mask
            )
            n_b = jnp.maximum(n_b, jnp.max(ng_all))
            # replicated merge into the running partial — identical body to
            # the single-device fused program's tail
            idx = jnp.arange(cap)
            smask = jnp.concatenate([idx < state_n_, idx < n_b])
            kcat = tuple(jnp.concatenate([a, b]) for a, b in zip(state_keys_, key_b))
            scat = tuple(jnp.concatenate([a, b]) for a, b in zip(state_slots_, slot_b))
            fs_cat = jnp.concatenate([state_fs_, fs_b])
            n_m, fs_m, key_m, slot_m = D._merge_concat_parts(
                key_specs, slot_specs, cap, kcat, scat, fs_cat, smask
            )
            ok = (n_b <= cap) & (n_m <= cap)
            n_out = jnp.where(ok, n_m, state_n_)
            fs_out = jnp.where(ok, fs_m, state_fs_)
            keys_out = tuple(jnp.where(ok, m, s) for m, s in zip(key_m, state_keys_))
            slots_out = tuple(jnp.where(ok, m, s) for m, s in zip(slot_m, state_slots_))
            return n_b, n_m, n_out, fs_out, keys_out, slots_out

        return per_shard(state_keys, state_slots, state_fs, state_n, cols, lits, n_valid, row_base)

    return program


def sharded_fused_topk_program(mesh, axis, num_keys, cap):
    """Sharded twin of ``ops.sort.fused_topk_fn``: per-shard chunk select +
    one all_gather + replicated merge WITH the running candidate state, one
    dispatch per chunk. Same signature as the single-device fused program:
    ``program(state, planes) -> (merged, cand)`` where ``state`` and both
    outputs are replicated ``(num_keys + 1, cap)`` matrices."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from hyperspace_tpu.ops.sort import _TOPK_SENTINEL, _take_cap

    shard_map = get_shard_map()
    n_dev = mesh.devices.size

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(), P(None, axis)),
        out_specs=(P(), P()),
        check_rep=False,
    )
    def program(state, planes):
        local = lax.sort(
            tuple(planes[i] for i in range(num_keys + 1)),
            num_keys=num_keys + 1,
            is_stable=False,
        )
        mine = jnp.stack([_take_cap(o, cap, _TOPK_SENTINEL) for o in local])
        gathered = jax.lax.all_gather(mine, axis)  # (n_dev, K+1, cap)
        cat = jnp.transpose(gathered, (1, 0, 2)).reshape(num_keys + 1, n_dev * cap)
        merged_chunk = lax.sort(
            tuple(cat[i] for i in range(num_keys + 1)),
            num_keys=num_keys + 1,
            is_stable=False,
        )
        cand = jnp.stack([_take_cap(o, cap, _TOPK_SENTINEL) for o in merged_chunk])
        both = jnp.concatenate([state, cand], axis=1)
        merged = lax.sort(
            tuple(both[i] for i in range(num_keys + 1)),
            num_keys=num_keys + 1,
            is_stable=False,
        )
        return jnp.stack([_take_cap(o, cap, _TOPK_SENTINEL) for o in merged]), cand

    return program
