"""Device-mesh helpers: bucket id ≡ device shard.

The reference's parallelism is Spark hash-partitioning ("bucketing"); here the
same layout is a 1-D ``jax.sharding.Mesh`` where bucket ``b`` lives on device
``b % n_devices`` — so a bucketed join needs no collective at all, and
re-bucketing is one ``all_to_all`` over ICI (SURVEY.md §2.9, §5.8).
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

DEFAULT_AXIS = "buckets"


def get_shard_map():
    """jax.shard_map with fallback to the pre-0.8 experimental location."""
    import jax

    try:
        return jax.shard_map
    except AttributeError:  # pragma: no cover - older jax
        from jax.experimental.shard_map import shard_map

        return shard_map


def make_mesh(n_devices: Optional[int] = None, axis: str = DEFAULT_AXIS) -> Mesh:
    """1-D mesh over the first ``n_devices`` local devices (all by default).

    Raises ``ValueError`` when the request oversubscribes the runtime — a
    silently truncated mesh would shard programs across fewer devices than
    the caller planned capacity for."""
    devices = jax.devices()
    if n_devices is not None:
        if n_devices < 1:
            raise ValueError(f"n_devices must be >= 1, got {n_devices}")
        if n_devices > len(devices):
            raise ValueError(
                f"requested a {n_devices}-device mesh but only {len(devices)} "
                f"devices are available ({devices[0].platform}); on CPU, raise the "
                "count with XLA_FLAGS=--xla_force_host_platform_device_count=N"
            )
        devices = devices[:n_devices]
    return Mesh(np.array(devices), (axis,))


def mesh_fingerprint(mesh: Mesh) -> str:
    """Stable identity of a mesh for program-cache keys: platform, device
    grid shape, and axis names. Two meshes with the same fingerprint compile
    to interchangeable executables (same partitioning), so single-device and
    sharded paths can share one skeleton cache keyed on
    ``(program skeleton, shape bucket, mesh fingerprint)``."""
    first = next(iter(mesh.devices.flat), None)
    platform = getattr(first, "platform", "none")
    shape = "x".join(str(s) for s in mesh.devices.shape)
    return f"{platform}:{shape}:{','.join(mesh.axis_names)}"


def device_of_bucket(bucket: int, n_devices: int) -> int:
    return bucket % n_devices

def sharded(mesh: Mesh, axis: Optional[str] = None) -> NamedSharding:
    axis = axis or mesh.axis_names[0]
    return NamedSharding(mesh, PartitionSpec(axis))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())


def make_mesh_2d(n_slices: Optional[int] = None, per_slice: Optional[int] = None) -> Mesh:
    """2-D (dcn, ici) mesh for multi-slice / multi-host topologies: the ici
    axis spans devices within a slice (fast interconnect), the dcn axis spans
    slices (data-center network). On a multi-host runtime the slice count
    defaults to ``jax.process_count()`` so the dcn axis aligns with host
    boundaries and XLA keeps phase-1 all_to_all traffic on ICI
    (SURVEY.md §5.8)."""
    devices = jax.devices()
    if n_slices is None:
        n_slices = max(1, jax.process_count())
    if per_slice is None:
        if len(devices) % n_slices:
            raise ValueError(
                f"{len(devices)} devices do not divide evenly into {n_slices} slices; "
                "pass per_slice explicitly"
            )
        per_slice = len(devices) // n_slices
    if n_slices * per_slice > len(devices):
        raise ValueError(
            f"requested {n_slices}x{per_slice} mesh but only {len(devices)} devices are available"
        )
    grid = np.array(devices[: n_slices * per_slice]).reshape(n_slices, per_slice)
    return Mesh(grid, ("dcn", "ici"))


def sharded_2d(mesh: Mesh) -> NamedSharding:
    """Row sharding of a 1-D array across every device of a 2-D mesh."""
    return NamedSharding(mesh, PartitionSpec(tuple(mesh.axis_names)))
