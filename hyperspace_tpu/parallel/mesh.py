"""Device-mesh helpers: bucket id ≡ device shard.

The reference's parallelism is Spark hash-partitioning ("bucketing"); here the
same layout is a 1-D ``jax.sharding.Mesh`` where bucket ``b`` lives on device
``b % n_devices`` — so a bucketed join needs no collective at all, and
re-bucketing is one ``all_to_all`` over ICI (SURVEY.md §2.9, §5.8).
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

DEFAULT_AXIS = "buckets"


def get_shard_map():
    """jax.shard_map with fallback to the pre-0.8 experimental location."""
    import jax

    try:
        return jax.shard_map
    except AttributeError:  # pragma: no cover - older jax
        from jax.experimental.shard_map import shard_map

        return shard_map


def make_mesh(n_devices: Optional[int] = None, axis: str = DEFAULT_AXIS) -> Mesh:
    devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return Mesh(np.array(devices), (axis,))


def device_of_bucket(bucket: int, n_devices: int) -> int:
    return bucket % n_devices

def sharded(mesh: Mesh, axis: Optional[str] = None) -> NamedSharding:
    axis = axis or mesh.axis_names[0]
    return NamedSharding(mesh, PartitionSpec(axis))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())
