"""Index SPI.

``Index`` is the derived-dataset interface every index kind implements
(ref: HS/index/Index.scala:32-168); ``IndexConfig`` is the user-facing config
SPI (ref: HS/index/IndexConfigTrait.scala:31-59); ``CreateContext`` carries
what the reference passes as ``IndexerContext`` (session, data path, file-id
tracker).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from hyperspace_tpu.models.log_entry import Content, DerivedDataset, FileIdTracker


class UpdateMode:
    """(ref: HS/index/Index.scala UpdateMode.{Merge,Overwrite})"""

    MERGE = "merge"
    OVERWRITE = "overwrite"


@dataclass
class CreateContext:
    """Context for index build/refresh operations
    (ref: ``IndexerContext`` in HS/index/Index.scala)."""

    session: Any
    index_data_path: str  # versioned data dir (v__=N) to write into
    file_id_tracker: FileIdTracker = field(default_factory=FileIdTracker)
    properties: Dict[str, str] = field(default_factory=dict)


class Index:
    """A derived dataset (ref: HS/index/Index.scala:32-168)."""

    kind: str = ""
    kind_abbr: str = ""

    @property
    def indexed_columns(self) -> List[str]:
        raise NotImplementedError

    @property
    def referenced_columns(self) -> List[str]:
        raise NotImplementedError

    @property
    def properties(self) -> Dict[str, Any]:
        raise NotImplementedError

    def with_new_properties(self, properties: Dict[str, Any]) -> "Index":
        raise NotImplementedError

    def to_derived_dataset(self) -> DerivedDataset:
        return DerivedDataset(self.kind, dict(self.properties))

    def write(self, ctx: CreateContext, df) -> None:
        """Build and persist index data for ``df`` into ``ctx.index_data_path``."""
        raise NotImplementedError

    def can_handle_deleted_files(self) -> bool:
        return False

    def optimize(self, ctx: CreateContext, files_to_optimize: List[str]) -> None:
        raise NotImplementedError

    def refresh_incremental(self, ctx: CreateContext, appended_df, deleted_files, previous_content: Content):
        """Returns (index, update_mode)."""
        raise NotImplementedError

    def refresh_full(self, ctx: CreateContext, df):
        """Returns the refreshed index."""
        raise NotImplementedError

    def stats(self) -> Dict[str, Any]:
        return {}


class IndexConfig:
    """User-facing index configuration (ref: HS/index/IndexConfigTrait.scala:31-59)."""

    @property
    def index_name(self) -> str:
        raise NotImplementedError

    @property
    def referenced_columns(self) -> List[str]:
        raise NotImplementedError

    def create_index(self, ctx: CreateContext, df, properties: Dict[str, str]) -> Index:
        """Resolve columns against ``df``, build index data, return the Index."""
        raise NotImplementedError
