"""DataSkippingIndex: per-source-file sketch table.

One row per source data file (keyed by ``_data_file_id``) holding sketch
aggregates (min/max, bloom filter, distinct value list) of chosen columns;
query-time file pruning translates predicates against the sketch table
(ref: HS/index/dataskipping/DataSkippingIndex.scala:35-179,
DataSkippingIndexConfig.scala:40-76, sketch/MinMaxSketch.scala:33-43).

Note the reference snapshot ships build/refresh/optimize but never registered
a query-rewrite rule (SURVEY.md §2.3); this framework implements the pruning
rule too (rules/dataskipping_rule.py).
"""

from __future__ import annotations

import math
import os
from typing import Any, Dict, List, Optional

import numpy as np
import pyarrow as pa
import pyarrow.dataset as pads
import pyarrow.parquet as pq

from hyperspace_tpu.utils.lru import BytesLRU

# sketch tables keyed by the log entry's recorded file identities;
# refresh/optimize produce new entries with new keys and invalidate naturally
_SKETCH_TABLE_CACHE = BytesLRU(int(os.environ.get("HS_SKETCH_CACHE_BYTES", 64 << 20)))

from hyperspace_tpu import config as C
from hyperspace_tpu.indexes import registry
from hyperspace_tpu.indexes.base import CreateContext, Index, IndexConfig, UpdateMode
from hyperspace_tpu.models.log_entry import Content, DerivedDataset
from hyperspace_tpu.plan.resolver import resolve_columns_against_schema


class Sketch:
    """Sketch SPI (ref: HS/index/dataskipping/sketch/Sketch.scala:33-78)."""

    kind = ""

    def __init__(self, expr: str):
        self.expr = expr  # column name (expression strings kept simple)

    @property
    def referenced_columns(self) -> List[str]:
        return [self.expr]

    def output_names(self) -> List[str]:
        raise NotImplementedError

    def aggregate(self, values: np.ndarray) -> List[Any]:
        """Compute this sketch's aggregates over one file's column values."""
        raise NotImplementedError

    def to_dict(self) -> Dict[str, Any]:
        return {"kind": self.kind, "expr": self.expr}

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "Sketch":
        kind = d["kind"]
        for sk in (MinMaxSketch, BloomFilterSketch, ValueListSketch, PartitionSketch):
            if sk.kind == kind:
                if kind == "BloomFilter":
                    return BloomFilterSketch(
                        d["expr"], d.get("fpp", 0.01), d.get("expectedItems", 10000), d.get("valueDtype")
                    )
                return sk(d["expr"])
        raise ValueError(f"Unknown sketch kind {kind!r}")

    def __eq__(self, other):
        return type(self) is type(other) and self.to_dict() == other.to_dict()

    def __hash__(self):
        return hash((self.kind, self.expr))

    def __repr__(self):
        return f"{self.kind}({self.expr})"


class MinMaxSketch(Sketch):
    """(ref: sketch/MinMaxSketch.scala:33-43)"""

    kind = "MinMax"

    def output_names(self) -> List[str]:
        return [f"MinMax_{self.expr}__min", f"MinMax_{self.expr}__max"]

    def aggregate(self, values: np.ndarray) -> List[Any]:
        if len(values) == 0:
            return [None, None]
        return [values.min(), values.max()]


class ValueListSketch(Sketch):
    """Distinct values per file — exact membership pruning
    (ref: dataskipping sketches; ValueListSketch exists in later reference versions)."""

    kind = "ValueList"
    MAX_VALUES = 1024

    def output_names(self) -> List[str]:
        return [f"ValueList_{self.expr}__values"]

    def aggregate(self, values: np.ndarray) -> List[Any]:
        uniq = np.unique(values)
        if len(uniq) > self.MAX_VALUES:
            return [None]  # too many distincts: no pruning signal
        return [uniq.tolist()]


class BloomFilterSketch(Sketch):
    """Bloom-filter membership per file. The filter is a fixed-size bit array
    stored as a list of uint64 words; membership tests run vectorized."""

    kind = "BloomFilter"

    def __init__(self, expr: str, fpp: float = 0.01, expected_items: int = 10000, value_dtype: Optional[str] = None):
        super().__init__(expr)
        self.fpp = float(fpp)
        self.expected_items = int(expected_items)
        # hashing is dtype-sensitive (float64 5.0 and int64 5 have different
        # bit patterns); the build-time column dtype is recorded so query
        # literals can be coerced before membership tests
        self.value_dtype = value_dtype
        m = max(64, int(-expected_items * math.log(fpp) / (math.log(2) ** 2)))
        self.num_bits = 1 << max(6, (m - 1).bit_length())  # power of two
        self.num_hashes = max(1, int(round(self.num_bits / expected_items * math.log(2))))

    def output_names(self) -> List[str]:
        return [f"BloomFilter_{self.expr}__bits"]

    @staticmethod
    def _canonicalize(values: np.ndarray) -> tuple:
        """Hashing is dtype-sensitive, and the same column can surface with
        different numpy dtypes per file (int64 vs float64 when one file holds
        a null, varying '<U{n}' widths). Canonicalize before hashing so every
        file — and every query literal — hashes identically:
        numerics → float64 (precision loss maps build and query the same way,
        so it can only add false *positives*, which are safe), datetimes →
        datetime64[ns], strings → object."""
        kind = values.dtype.kind
        if kind in ("i", "u", "b", "f"):
            return values.astype(np.float64), "float64"
        if kind == "M":
            return values.astype("datetime64[ns]"), "datetime64[ns]"
        return values.astype(object), "object"

    def _positions(self, values: np.ndarray) -> np.ndarray:
        from hyperspace_tpu.ops.encode import hash_input_uint32

        h1 = hash_input_uint32(values).astype(np.uint64)
        h2 = (h1 * np.uint64(0x9E3779B97F4A7C15)) >> np.uint64(32) | np.uint64(1)
        ks = np.arange(self.num_hashes, dtype=np.uint64)
        return ((h1[:, None] + ks[None, :] * h2[:, None]) % np.uint64(self.num_bits)).astype(np.int64)

    def aggregate(self, values: np.ndarray) -> List[Any]:
        values, dtype = self._canonicalize(values)
        self.value_dtype = dtype
        bits = np.zeros(self.num_bits // 64, dtype=np.uint64)
        pos = self._positions(values).reshape(-1)
        np.bitwise_or.at(bits, pos // 64, np.uint64(1) << (pos % np.uint64(64)).astype(np.uint64))
        return [bits.view(np.int64).tolist()]

    def might_contain(self, bits_words: List[int], value) -> bool:
        """Raises on a literal that cannot be coerced to the build dtype —
        callers treat that as unprunable."""
        if self.value_dtype == "object":
            arr = np.asarray([str(value)], dtype=object)
        elif self.value_dtype == "datetime64[ns]":
            arr = np.asarray([np.datetime64(value)]).astype("datetime64[ns]")
        else:
            arr = np.asarray([value]).astype(np.float64)
        bits = np.asarray(bits_words, dtype=np.int64).view(np.uint64)
        pos = self._positions(arr).reshape(-1)
        return bool(np.all((bits[pos // 64] >> (pos % np.uint64(64)).astype(np.uint64)) & np.uint64(1)))

    def to_dict(self) -> Dict[str, Any]:
        return {
            "kind": self.kind,
            "expr": self.expr,
            "fpp": self.fpp,
            "expectedItems": self.expected_items,
            "valueDtype": self.value_dtype,
        }


class PartitionSketch(Sketch):
    """Single partition value per file (for hive-partitioned sources)."""

    kind = "Partition"

    def output_names(self) -> List[str]:
        return [f"Partition_{self.expr}__value"]

    def aggregate(self, values: np.ndarray) -> List[Any]:
        uniq = np.unique(values)
        return [uniq[0] if len(uniq) == 1 else None]


def _restore_bound(value: float, dtype: np.dtype, lower: bool):
    """Map a float64 device-reduce result back to the column dtype.

    int64 values beyond 2**53 are not exactly representable in float64; a
    misrounded bound could wrongly *tighten* the sketch and prune a matching
    file. Bounds are therefore widened outward (min down, max up) whenever the
    round trip is inexact — widening only ever costs false positives, which
    data skipping tolerates by design.
    """
    if dtype.kind not in ("i", "u"):
        return dtype.type(value)
    iv = int(value)
    # strict: at exactly +-2**53 the float may itself be a rounded bound
    if float(iv) == value and abs(value) < 2**53:
        return iv
    # beyond 2**53 the f64 rounding error is up to ulp/2, which grows with
    # magnitude (512 at 2**62) — widen by a full ulp, clamped to the dtype
    slack = max(1, int(math.ulp(abs(value))))
    info = np.iinfo(dtype)
    return max(iv - slack, info.min) if lower else min(iv + slack, info.max)


class DataSkippingIndex(Index):
    kind = "DataSkippingIndex"
    kind_abbr = "DS"

    def __init__(self, sketches: List[Sketch], extra_properties: Optional[Dict[str, Any]] = None):
        self.sketches = list(sketches)
        self._extra = dict(extra_properties or {})

    @property
    def indexed_columns(self) -> List[str]:
        out: List[str] = []
        for s in self.sketches:
            for c in s.referenced_columns:
                if c not in out:
                    out.append(c)
        return out

    @property
    def referenced_columns(self) -> List[str]:
        return self.indexed_columns

    @property
    def properties(self) -> Dict[str, Any]:
        props = {"sketches": [s.to_dict() for s in self.sketches]}
        props.update(self._extra)
        return props

    def with_new_properties(self, properties: Dict[str, Any]) -> "DataSkippingIndex":
        extra = {k: v for k, v in properties.items() if k != "sketches"}
        return DataSkippingIndex(self.sketches, extra)

    @classmethod
    def from_derived_dataset(cls, dd: DerivedDataset) -> "DataSkippingIndex":
        extra = {k: v for k, v in dd.properties.items() if k != "sketches"}
        return cls([Sketch.from_dict(s) for s in dd.properties["sketches"]], extra)

    def can_handle_deleted_files(self) -> bool:
        return True  # rows are keyed by file id; deleted files' rows are dropped

    def stats(self) -> Dict[str, Any]:
        return {"sketches": [repr(s) for s in self.sketches]}

    # --- build (ref: DataSkippingIndex.index() :116-138) -------------------
    def write(self, ctx: CreateContext, df) -> None:
        from hyperspace_tpu.plan.logical import Scan

        assert isinstance(df.plan, Scan)
        relation = df.plan.relation
        cols = [c.name for c in resolve_columns_against_schema(self.indexed_columns, relation.schema)]
        rows = self._sketch_rows(relation, relation.all_file_infos(), cols, ctx)
        self._write_rows(rows, ctx.index_data_path)

    def _sketch_rows(self, relation, file_infos, cols: List[str], ctx: CreateContext) -> List[Dict[str, Any]]:
        from hyperspace_tpu.exec.io import read_parquet_batch

        part_cols = set(getattr(relation, "partition_columns", []) or []) & set(cols)
        file_cols = [c for c in cols if c not in part_cols]
        part_dtypes = dict(getattr(relation, "partition_dtypes", {}) or {})

        batches: List[Dict[str, np.ndarray]] = []
        rows: List[Dict[str, Any]] = []
        for fi in file_infos:
            fid = ctx.file_id_tracker.add_file(fi)
            if not file_cols:
                b = {}
                n = relation.arrow_dataset([fi.name]).count_rows()
            elif relation.physical_format == "parquet":
                b = read_parquet_batch([fi.name], file_cols)
                n = len(next(iter(b.values()))) if b else 0
            else:
                from hyperspace_tpu.sources import formats as F

                t = F.read_table(
                    fi.name, relation.physical_format, file_cols,
                    getattr(relation, "options", None),
                )
                b = {c: t.column(c).to_numpy(zero_copy_only=False) for c in file_cols}
                n = len(next(iter(b.values()))) if b else 0
            if part_cols:
                from hyperspace_tpu.sources import partitions as P

                values = relation.partition_values_for(fi.name)
                for c in part_cols:
                    b[c] = P.column_array(values.get(c), part_dtypes.get(c, np.dtype(object)), n)
            batches.append(b)
            rows.append({C.DATA_FILE_NAME_ID: fid})

        # numeric MinMax sketches aggregate on device: all files' segments in
        # one fused pallas min+max sweep (ops/kernels.segmented_min_max)
        device_minmax = [
            s
            for s in self.sketches
            if isinstance(s, MinMaxSketch)
            and batches
            and all(b[s.expr].dtype.kind in ("i", "u", "f") for b in batches)
        ]
        for s in device_minmax:
            from hyperspace_tpu.ops.kernels import segmented_min_max

            mins, maxs = segmented_min_max([b[s.expr] for b in batches])
            names = s.output_names()
            for i, row in enumerate(rows):
                dt = batches[i][s.expr].dtype
                row[names[0]] = None if np.isnan(mins[i]) else _restore_bound(mins[i], dt, lower=True)
                row[names[1]] = None if np.isnan(maxs[i]) else _restore_bound(maxs[i], dt, lower=False)

        host_sketches = [s for s in self.sketches if s not in device_minmax]
        for i, row in enumerate(rows):
            for s in host_sketches:
                col = batches[i][s.expr]
                for name, value in zip(s.output_names(), s.aggregate(col)):
                    row[name] = value
        return rows

    def _write_rows(self, rows: List[Dict[str, Any]], out_dir: str) -> None:
        os.makedirs(out_dir, exist_ok=True)
        if not rows:
            return
        names = list(rows[0])
        table = pa.table({n: [r[n] for r in rows] for n in names})
        pq.write_table(table, os.path.join(out_dir, "sketches-00000.parquet"))

    def read_sketch_table(self, entry) -> pa.Table:
        """Sketch tables are tiny (one row per source file) but consulted on
        every optimizer pass — cache them by the log entry's recorded file
        identities (FileInfo.key = name/size/mtime; no extra stat syscalls)
        so repeated queries don't re-read parquet."""
        key = tuple(fi.key for fi in entry.content.file_infos())
        got = _SKETCH_TABLE_CACHE.get(key)
        if got is None:
            got = pads.dataset(entry.content.files, format="parquet").to_table()
            _SKETCH_TABLE_CACHE.put(key, got, int(got.nbytes))
        return got


class DataSkippingIndexConfig(IndexConfig):
    """(ref: HS/index/dataskipping/DataSkippingIndexConfig.scala:40-76)"""

    def __init__(self, index_name: str, first_sketch: Sketch, *more_sketches: Sketch):
        if not index_name:
            raise ValueError("Index name must not be empty")
        sketches = [first_sketch, *more_sketches]
        if len(set(sketches)) != len(sketches):
            raise ValueError("Duplicate sketches are not allowed")
        self._name = index_name
        self._sketches = sketches

    @property
    def index_name(self) -> str:
        return self._name

    @property
    def referenced_columns(self) -> List[str]:
        out: List[str] = []
        for s in self._sketches:
            for c in s.referenced_columns:
                if c not in out:
                    out.append(c)
        return out

    def create_index(self, ctx: CreateContext, df, properties: Dict[str, str]) -> DataSkippingIndex:
        index = DataSkippingIndex(self._sketches, dict(properties))
        index.write(ctx, df)
        return index


registry.register(DataSkippingIndex.kind, DataSkippingIndex.from_derived_dataset)
