"""CoveringIndex — the flagship index.

A vertical slice (indexed + included columns) of the source data,
hash-bucketed on the indexed columns into ``num_buckets`` bucket files and
sorted by the indexed columns within each bucket, so that

  - filter queries scan only the index slice (and only the matching bucket,
    when bucket pruning applies), and
  - equi-joins on the indexed columns run without any shuffle.

(ref: HS/index/covering/CoveringIndex.scala:30-280,
 HS/index/covering/CoveringIndexConfig.scala:39-200)

The build replaces Spark's ``repartition(numBuckets, cols)`` shuffle +
per-partition sort + bucketed Parquet write
(ref: CoveringIndex.scala:54-69, DataFrameWriterExtensions.scala:50-68) with a
single jitted device program: encode -> hash -> ``bucket_sort_perm`` (XLA sort)
-> host gather -> per-bucket Parquet write. Optional lineage materializes a
``_data_file_id`` column mapping each index row to its source file
(ref: CoveringIndex.scala:227-279); here the id is attached at decode time
instead of via a broadcast join.

Bucket id is encoded in the data file name: ``part-<bucket>-<tag>.parquet``.
"""

from __future__ import annotations

import os
import re
import uuid
from typing import Any, Dict, List, Optional, Tuple

import numpy as np
import pyarrow as pa
import pyarrow.dataset as pads
import pyarrow.parquet as pq

from hyperspace_tpu import config as C
from hyperspace_tpu.indexes import registry
from hyperspace_tpu.indexes.base import CreateContext, Index, IndexConfig, UpdateMode
from hyperspace_tpu.models.log_entry import Content, DerivedDataset
from hyperspace_tpu.plan.logical import BucketSpec
from hyperspace_tpu.plan.resolver import resolve_columns_against_schema
from hyperspace_tpu.sources import schema as schema_codec

_BUCKET_FILE_RE = re.compile(r"part-(\d+)-")


def bucket_of_file(path: str) -> Optional[int]:
    m = _BUCKET_FILE_RE.match(os.path.basename(path))
    return int(m.group(1)) if m else None


def _bucket_file_name(bucket: int) -> str:
    return f"part-{bucket:05d}-{uuid.uuid4().hex[:12]}.parquet"


class CoveringIndex(Index):
    kind = "CoveringIndex"
    kind_abbr = "CI"

    def __init__(
        self,
        indexed_columns: List[str],
        included_columns: List[str],
        num_buckets: int,
        schema_json: str = "",
        lineage: bool = False,
        extra_properties: Optional[Dict[str, Any]] = None,
    ):
        self._indexed = list(indexed_columns)
        self._included = list(included_columns)
        self.num_buckets = int(num_buckets)
        self.schema_json = schema_json
        self.lineage = bool(lineage)
        self._extra = dict(extra_properties or {})

    # --- identity ----------------------------------------------------------
    @property
    def indexed_columns(self) -> List[str]:
        return list(self._indexed)

    @property
    def included_columns(self) -> List[str]:
        return list(self._included)

    @property
    def referenced_columns(self) -> List[str]:
        return self._indexed + self._included

    @property
    def properties(self) -> Dict[str, Any]:
        props = {
            "indexedColumns": self._indexed,
            "includedColumns": self._included,
            "numBuckets": self.num_buckets,
            "schemaJson": self.schema_json,
            C.LINEAGE_PROPERTY: str(self.lineage).lower(),
        }
        props.update(self._extra)
        return props

    def with_new_properties(self, properties: Dict[str, Any]) -> "CoveringIndex":
        extra = {k: v for k, v in properties.items()
                 if k not in ("indexedColumns", "includedColumns", "numBuckets", "schemaJson", C.LINEAGE_PROPERTY)}
        return CoveringIndex(self._indexed, self._included, self.num_buckets,
                             self.schema_json, self.lineage, extra)

    @classmethod
    def from_derived_dataset(cls, dd: DerivedDataset) -> "CoveringIndex":
        p = dd.properties
        extra = {k: v for k, v in p.items()
                 if k not in ("indexedColumns", "includedColumns", "numBuckets", "schemaJson", C.LINEAGE_PROPERTY)}
        return cls(
            list(p["indexedColumns"]),
            list(p.get("includedColumns", [])),
            int(p["numBuckets"]),
            p.get("schemaJson", ""),
            str(p.get(C.LINEAGE_PROPERTY, "false")).lower() == "true",
            extra,
        )

    def bucket_spec(self) -> BucketSpec:
        """(ref: HS/index/covering/CoveringIndex.scala:173-177)"""
        return BucketSpec(self.num_buckets, tuple(self._indexed), tuple(self._indexed))

    def can_handle_deleted_files(self) -> bool:
        return self.lineage

    def stats(self) -> Dict[str, Any]:
        return {
            "indexedColumns": self._indexed,
            "includedColumns": self._included,
            "numBuckets": self.num_buckets,
        }

    # --- build -------------------------------------------------------------
    def write(self, ctx: CreateContext, df) -> None:
        """Build index data for ``df`` into ``ctx.index_data_path``
        (ref: CoveringIndex.scala:54-69 write = repartition + saveWithBuckets)."""
        table = self._index_data_table(ctx, df)
        write_bucketed(table, self._indexed, self.num_buckets, ctx.index_data_path)
        self.schema_json = schema_codec.schema_to_json(table.schema)

    def _index_data_table(self, ctx: CreateContext, df) -> pa.Table:
        """The vertical slice (+ optional lineage column) as one arrow table
        (ref: createIndexData, CoveringIndex.scala:227-279)."""
        from hyperspace_tpu.plan.logical import Scan

        plan = df.plan
        if not isinstance(plan, Scan):
            raise ValueError(
                "createIndex expects a plain source scan (project/filter on top "
                "of a supported relation); got: " + type(plan).__name__
            )
        relation = plan.relation
        columns = [c.name for c in resolve_columns_against_schema(self.referenced_columns, relation.schema)]
        self._indexed = [c.name for c in resolve_columns_against_schema(self._indexed, relation.schema)]
        self._included = [c.name for c in resolve_columns_against_schema(self._included, relation.schema)]

        if not self.lineage:
            return relation.arrow_dataset().to_table(columns=columns)

        # lineage: attach _data_file_id per source file at decode time
        tables = []
        for fi in relation.all_file_infos():
            fid = ctx.file_id_tracker.add_file(fi)
            t = pads.dataset([fi.name], format=relation.physical_format).to_table(columns=columns)
            t = t.append_column(C.DATA_FILE_NAME_ID, pa.array(np.full(t.num_rows, fid, dtype=np.int64)))
            tables.append(t)
        return pa.concat_tables(tables)


def write_bucketed(table: pa.Table, bucket_sort_columns: List[str], num_buckets: int, out_dir: str) -> List[str]:
    """Device-accelerated bucketed + sorted Parquet write.

    The jitted kernel (ops/sort.bucket_sort_perm) computes the bucket of every
    row and the permutation clustering rows by bucket / sorting by key; the
    host then gathers once and writes one file per non-empty bucket.
    Returns written file paths.
    """
    import jax

    from hyperspace_tpu.exec.batch import table_to_batch
    from hyperspace_tpu.ops import encode
    from hyperspace_tpu.ops.sort import bucket_sort_perm

    os.makedirs(out_dir, exist_ok=True)
    if table.num_rows == 0:
        return []

    batch = table_to_batch(table.select(bucket_sort_columns))
    key_cols = [batch[c] for c in bucket_sort_columns]
    hash_inputs, sort_keys = encode.encode_key_columns(key_cols)

    perm, sorted_buckets = bucket_sort_perm(
        jax.device_put(hash_inputs), jax.device_put(sort_keys), num_buckets
    )
    perm = np.asarray(perm)

    permuted = table.take(pa.array(perm))
    # per-bucket row counts via the pallas histogram kernel (ops/kernels);
    # prefix sums of the counts are the bucket boundaries in the sorted order
    from hyperspace_tpu.ops.kernels import bucket_histogram

    counts = bucket_histogram(sorted_buckets, num_buckets)
    boundaries = np.concatenate([[0], np.cumsum(counts)])
    written = []
    for b in range(num_buckets):
        lo, hi = int(boundaries[b]), int(boundaries[b + 1])
        if hi <= lo:
            continue
        path = os.path.join(out_dir, _bucket_file_name(b))
        # uncompressed PLAIN is the index-file dialect: the native decoder
        # (hyperspace_tpu/native) mmaps these and memcpys column chunks into
        # device-feedable buffers with zero decompression work
        pq.write_table(permuted.slice(lo, hi - lo), path, use_dictionary=False, compression="NONE")
        written.append(path)
    return written


class CoveringIndexConfig(IndexConfig):
    """(ref: HS/index/covering/CoveringIndexConfig.scala:39-200)"""

    def __init__(self, index_name: str, indexed_columns: List[str], included_columns: Optional[List[str]] = None):
        if not index_name:
            raise ValueError("Index name must not be empty")
        if not indexed_columns:
            raise ValueError("indexed_columns must not be empty")
        included_columns = list(included_columns or [])
        lowered = [c.lower() for c in indexed_columns + included_columns]
        if len(set(lowered)) != len(lowered):
            raise ValueError("Duplicate columns across indexed/included columns are not allowed")
        self._name = index_name
        self._indexed = list(indexed_columns)
        self._included = included_columns

    @property
    def index_name(self) -> str:
        return self._name

    @property
    def indexed_columns(self) -> List[str]:
        return list(self._indexed)

    @property
    def included_columns(self) -> List[str]:
        return list(self._included)

    @property
    def referenced_columns(self) -> List[str]:
        return self._indexed + self._included

    def create_index(self, ctx: CreateContext, df, properties: Dict[str, str]) -> CoveringIndex:
        """(ref: CoveringIndexConfig createIndex :92-116)"""
        index = CoveringIndex(
            self._indexed,
            self._included,
            num_buckets=ctx.session.conf.num_buckets,
            lineage=ctx.session.conf.lineage_enabled,
            extra_properties=dict(properties),
        )
        index.write(ctx, df)
        return index

    def __repr__(self) -> str:
        return f"CoveringIndexConfig({self._name!r}, indexed={self._indexed}, included={self._included})"


registry.register(CoveringIndex.kind, CoveringIndex.from_derived_dataset)
