"""CoveringIndex — the flagship index.

A vertical slice (indexed + included columns) of the source data,
hash-bucketed on the indexed columns into ``num_buckets`` bucket files and
sorted by the indexed columns within each bucket, so that

  - filter queries scan only the index slice (and only the matching bucket,
    when bucket pruning applies), and
  - equi-joins on the indexed columns run without any shuffle.

(ref: HS/index/covering/CoveringIndex.scala:30-280,
 HS/index/covering/CoveringIndexConfig.scala:39-200)

The build replaces Spark's ``repartition(numBuckets, cols)`` shuffle +
per-partition sort + bucketed Parquet write
(ref: CoveringIndex.scala:54-69, DataFrameWriterExtensions.scala:50-68) with a
single jitted device program: encode -> hash -> ``bucket_sort_perm`` (XLA sort)
-> host gather -> per-bucket Parquet write. Optional lineage materializes a
``_data_file_id`` column mapping each index row to its source file
(ref: CoveringIndex.scala:227-279); here the id is attached at decode time
instead of via a broadcast join.

Bucket id is encoded in the data file name: ``part-<bucket>-<tag>.parquet``.
"""

from __future__ import annotations

import os
import re
import uuid
from typing import Any, Dict, List, Optional, Tuple

import numpy as np
import pyarrow as pa
import pyarrow.dataset as pads
import pyarrow.parquet as pq

from hyperspace_tpu import config as C
from hyperspace_tpu.indexes import registry
from hyperspace_tpu.indexes.base import CreateContext, Index, IndexConfig, UpdateMode
from hyperspace_tpu.models.log_entry import Content, DerivedDataset
from hyperspace_tpu.plan.logical import BucketSpec
from hyperspace_tpu.plan.resolver import resolve_columns_against_schema
from hyperspace_tpu.sources import schema as schema_codec

_BUCKET_FILE_RE = re.compile(r"part-(\d+)-")

#: Version of the bucket hash function the index's data files were
#: partitioned with. Bumped whenever ops/hashing changes bucket placement
#: (v2 = round-5 value-consistent int/float normalization). An index
#: stamped with an older version still serves correct index-only scans,
#: but the optimizer must not trust its bucket LAYOUT (no bucket pruning,
#: no shuffle-free joins) until a full refresh/optimize re-buckets it —
#: see rules/utils.transform_plan_to_use_index.
BUCKET_HASH_VERSION = 2
_BUCKET_HASH_VERSION_PROP = "bucketHashVersion"


def bucket_of_file(path: str) -> Optional[int]:
    m = _BUCKET_FILE_RE.match(os.path.basename(path))
    return int(m.group(1)) if m else None


def _bucket_file_name(bucket: int) -> str:
    return f"part-{bucket:05d}-{uuid.uuid4().hex[:12]}.parquet"


class CoveringIndex(Index):
    kind = "CoveringIndex"
    kind_abbr = "CI"

    def __init__(
        self,
        indexed_columns: List[str],
        included_columns: List[str],
        num_buckets: int,
        schema_json: str = "",
        lineage: bool = False,
        extra_properties: Optional[Dict[str, Any]] = None,
    ):
        self._indexed = list(indexed_columns)
        self._included = list(included_columns)
        self.num_buckets = int(num_buckets)
        self.schema_json = schema_json
        self.lineage = bool(lineage)
        self._extra = dict(extra_properties or {})

    # --- identity ----------------------------------------------------------
    @property
    def indexed_columns(self) -> List[str]:
        return list(self._indexed)

    @property
    def included_columns(self) -> List[str]:
        return list(self._included)

    @property
    def referenced_columns(self) -> List[str]:
        return self._indexed + self._included

    @property
    def properties(self) -> Dict[str, Any]:
        props = {
            "indexedColumns": self._indexed,
            "includedColumns": self._included,
            "numBuckets": self.num_buckets,
            "schemaJson": self.schema_json,
            C.LINEAGE_PROPERTY: str(self.lineage).lower(),
        }
        props.update(self._extra)
        return props

    def with_new_properties(self, properties: Dict[str, Any]) -> "CoveringIndex":
        extra = {k: v for k, v in properties.items()
                 if k not in ("indexedColumns", "includedColumns", "numBuckets", "schemaJson", C.LINEAGE_PROPERTY)}
        return CoveringIndex(self._indexed, self._included, self.num_buckets,
                             self.schema_json, self.lineage, extra)

    @classmethod
    def from_derived_dataset(cls, dd: DerivedDataset) -> "CoveringIndex":
        p = dd.properties
        extra = {k: v for k, v in p.items()
                 if k not in ("indexedColumns", "includedColumns", "numBuckets", "schemaJson", C.LINEAGE_PROPERTY)}
        return cls(
            list(p["indexedColumns"]),
            list(p.get("includedColumns", [])),
            int(p["numBuckets"]),
            p.get("schemaJson", ""),
            str(p.get(C.LINEAGE_PROPERTY, "false")).lower() == "true",
            extra,
        )

    def bucket_spec(self) -> BucketSpec:
        """(ref: HS/index/covering/CoveringIndex.scala:173-177)"""
        return BucketSpec(self.num_buckets, tuple(self._indexed), tuple(self._indexed))

    @property
    def bucket_hash_version(self) -> int:
        """Hash-function version the data files were bucketed with; entries
        predating the property default to 1 (the pre-normalization hash)."""
        return int(self._extra.get(_BUCKET_HASH_VERSION_PROP, 1))

    def can_handle_deleted_files(self) -> bool:
        return self.lineage

    def stats(self) -> Dict[str, Any]:
        return {
            "indexedColumns": self._indexed,
            "includedColumns": self._included,
            "numBuckets": self.num_buckets,
        }

    # --- build -------------------------------------------------------------
    def write(self, ctx: CreateContext, df) -> None:
        """Build index data for ``df`` into ``ctx.index_data_path``
        (ref: CoveringIndex.scala:54-69 write = repartition + saveWithBuckets).

        Without lineage the build is pipelined: only the key columns are
        decoded before the device program launches; the payload columns decode
        while the permutation rides back from the device."""
        from hyperspace_tpu.plan.logical import Scan

        # write() re-buckets ALL data (create, full refresh, overwrite-mode
        # incremental): the index is now consistent with the current hash
        self._extra[_BUCKET_HASH_VERSION_PROP] = str(BUCKET_HASH_VERSION)

        plan = df.plan
        if isinstance(plan, Scan) and not self.lineage:
            # STREAMING build: source files are decoded in groups of
            # ~batchRows rows and fed straight into the pipelined device
            # build, so host memory is bounded by O(2 chunks + largest
            # file), never by table size — the discipline that lets a
            # TPC-H SF100 (600M-row) build run on a bounded-RAM host. The
            # reference gets this for free from Spark's streaming executors
            # (ref: CoveringIndex.scala:54-69 repartition+saveWithBuckets);
            # here the build owns its own out-of-core chunking.
            relation = plan.relation
            resolved = self._resolve_all(ctx, relation.schema)
            columns = [r.normalized_name for r in resolved]
            key_res = [r for r in resolved if r.normalized_name in self._indexed]
            payload = [r for r in resolved if r.normalized_name not in self._indexed]
            batch_rows = ctx.session.conf.build_batch_rows
            files = [fi.name for fi in relation.all_file_infos()]
            # per-file reads lose the unified-dataset schema the one-shot
            # path had (Arrow casts/null-fills fragments against it); conform
            # every per-file projection to the resolved schema so sources
            # with per-file schema drift still build one consistent index
            key_schema = pa.schema([_arrow_field_for(r, relation.schema) for r in key_res])
            payload_schema = pa.schema(
                [_arrow_field_for(r, relation.schema) for r in payload]
            )

            def groups():
                # each file's dataset is constructed ONCE and serves both the
                # key and payload projections: for materialized formats
                # (avro/text) construction IS the decode, so reusing it keeps
                # the build at one decode per file (the group holds its
                # files' tables until the chunk is written — bounded by
                # group size, same O(chunk) discipline)
                pending_ds: List = []
                pending_keys: List[pa.Table] = []
                rows = 0

                def emit():
                    kt = (
                        pa.concat_tables(pending_keys)
                        if len(pending_keys) > 1
                        else pending_keys[0]
                    )
                    grp_ds = list(pending_ds)

                    def group_payload_fn() -> Optional[pa.Table]:
                        if not payload:
                            return None
                        parts = [
                            _project_conform(d, payload, payload_schema) for d in grp_ds
                        ]
                        return pa.concat_tables(parts) if len(parts) > 1 else parts[0]

                    return kt, group_payload_fn

                for f in files:
                    ds_f = relation.arrow_dataset([f])
                    kt = _project_conform(ds_f, key_res, key_schema)
                    # emit BEFORE a file that would cross batchRows: groups
                    # stay under the cap (only a single file larger than
                    # batchRows exceeds it, and that group slices evenly),
                    # so no group leaves a sliver chunk paying a full
                    # device launch for a handful of rows
                    if batch_rows and pending_ds and rows + kt.num_rows > batch_rows:
                        yield emit()
                        pending_ds, pending_keys, rows = [], [], 0
                    pending_ds.append(ds_f)
                    pending_keys.append(kt)
                    rows += kt.num_rows
                    if batch_rows and rows >= batch_rows:
                        yield emit()
                        pending_ds, pending_keys, rows = [], [], 0
                if pending_ds:
                    yield emit()

            # the distributed-vs-single-device decision needs TOTAL rows
            # (conf distributedMinRows), which streaming never sees at once;
            # parquet footers give it for free, other formats fall back to
            # sizing by the first chunk
            total_rows = None
            if relation.physical_format == "parquet":
                try:
                    total_rows = sum(pq.read_metadata(f).num_rows for f in files)
                except Exception:
                    total_rows = None

            write_bucketed_groups(
                groups(),
                self._indexed,
                self.num_buckets,
                ctx.index_data_path,
                column_order=columns,
                batch_rows=batch_rows,
                session=ctx.session,
                total_rows=total_rows,
            )
            schema = pa.schema([_arrow_field_for(r, relation.schema) for r in resolved])
            self.schema_json = schema_codec.schema_to_json(schema)
            return

        table = self._index_data_table(ctx, df)
        write_bucketed(
            table,
            self._indexed,
            self.num_buckets,
            ctx.index_data_path,
            batch_rows=ctx.session.conf.build_batch_rows,
            session=ctx.session,
        )
        self.schema_json = schema_codec.schema_to_json(table.schema)

    def _resolve_all(self, ctx: CreateContext, schema: pa.Schema):
        """Resolve indexed/included columns, normalizing nested paths with the
        ``__hs_nested.`` prefix; nested indexing is gated on conf
        (ref: CoveringIndexConfig nested normalization, ResolverUtils.scala:44-105).

        Names may arrive already normalized (refresh/optimize revive the index
        from its log entry) — strip the prefix before re-resolving against the
        source schema."""
        from hyperspace_tpu.plan.resolver import ResolvedColumn

        def denorm(names):
            return [ResolvedColumn.from_normalized(n).name for n in names]

        resolved = resolve_columns_against_schema(denorm(self.referenced_columns), schema)
        if any(r.is_nested for r in resolved):
            conf = getattr(getattr(ctx, "session", None), "conf", None)
            if conf is not None and not conf.nested_column_enabled:
                raise ValueError(
                    "Indexing nested columns requires "
                    f"{C.keys.NESTED_COLUMN_ENABLED}=true"
                )
        self._indexed = [r.normalized_name for r in resolve_columns_against_schema(denorm(self._indexed), schema)]
        self._included = [r.normalized_name for r in resolve_columns_against_schema(denorm(self._included), schema)]
        return resolved

    def _index_data_table(self, ctx: CreateContext, df) -> pa.Table:
        """The vertical slice (+ optional lineage column) as one arrow table
        (ref: createIndexData, CoveringIndex.scala:227-279)."""
        from hyperspace_tpu.plan.logical import Scan

        plan = df.plan
        if not isinstance(plan, Scan):
            raise ValueError(
                "createIndex expects a plain source scan (project/filter on top "
                "of a supported relation); got: " + type(plan).__name__
            )
        relation = plan.relation
        resolved = self._resolve_all(ctx, relation.schema)
        projection = _nested_projection(resolved)

        if not self.lineage:
            return relation.arrow_dataset().to_table(columns=projection)

        # lineage: attach _data_file_id per source file at decode time
        # (arrow_dataset so hive-partition columns resolve per file)
        tables = []
        for fi in relation.all_file_infos():
            fid = ctx.file_id_tracker.add_file(fi)
            t = relation.arrow_dataset([fi.name]).to_table(columns=projection)
            t = t.append_column(C.DATA_FILE_NAME_ID, pa.array(np.full(t.num_rows, fid, dtype=np.int64)))
            tables.append(t)
        return pa.concat_tables(tables)


def _project_conform(ds, resolved, schema: pa.Schema) -> pa.Table:
    """Project ``resolved`` columns out of one file's dataset and conform the
    result to the unified ``schema`` (cast drifted dtypes; null-fill columns
    the file predates). The one-shot build's single dataset did this
    implicitly via Arrow's unified dataset schema; per-file streaming reads
    must do it explicitly or schema-evolved sources crash mid-build."""
    try:
        t = ds.to_table(columns=_nested_projection(resolved))
    except (KeyError, pa.ArrowInvalid, pa.ArrowKeyError):
        # a projected column is missing from this file (schema evolution):
        # decode what the file has, extract what resolves (nested leaves via
        # struct_field — the normalized __hs_nested. name never matches a
        # physical column), and null-fill only what's genuinely absent
        import pyarrow.compute as pc

        full = ds.to_table()
        arrays = []
        for r, f in zip(resolved, schema):
            parts = r.name.split(".")
            arr = full.column(parts[0]) if parts[0] in full.column_names else None
            for seg in parts[1:]:
                if arr is None:
                    break
                try:
                    arr = pc.struct_field(arr, seg)
                except (KeyError, pa.ArrowInvalid, pa.ArrowKeyError, TypeError):
                    arr = None
            arrays.append(arr if arr is not None else pa.nulls(full.num_rows, f.type))
        return pa.table(dict(zip(schema.names, arrays))).cast(schema)
    if t.schema != schema:
        t = t.cast(schema)
    return t


def _nested_projection(resolved) -> Dict[str, Any]:
    """Arrow dataset projection dict: normalized output name -> field ref
    (nested paths project the struct leaf into a flat column)."""
    import pyarrow.compute as pc

    out: Dict[str, Any] = {}
    for r in resolved:
        out[r.normalized_name] = pc.field(*r.name.split(".")) if r.is_nested else pc.field(r.name)
    return out


def _arrow_field_for(resolved_col, schema: pa.Schema) -> pa.Field:
    """The (leaf) arrow field a resolved column projects to, named by its
    normalized (flat) name."""
    parts = resolved_col.name.split(".")
    field = schema.field(parts[0])
    for p in parts[1:]:
        field = field.type.field(p)
    return pa.field(resolved_col.normalized_name, field.type)


def write_bucketed(
    table: pa.Table,
    bucket_sort_columns: List[str],
    num_buckets: int,
    out_dir: str,
    payload_fn=None,
    column_order: Optional[List[str]] = None,
    batch_rows: Optional[int] = None,
    session=None,
    _chunks=None,
    _total_rows: Optional[int] = None,
) -> List[str]:
    """Device-accelerated bucketed + sorted Parquet write.

    One fused device program (ops/sort.bucket_sort_build: hash -> bucket ->
    multi-key sort -> Pallas histogram) returns the clustering permutation and
    per-bucket counts. The pipeline overlaps every host stage with the device
    round trip:

      decode keys -> launch device program -> async perm fetch
                      || payload_fn() decodes the non-key columns
      fetch done  -> per-bucket (arrow take + parquet write) in a thread pool
                     (both release the GIL in C++)

    ``table`` must hold at least ``bucket_sort_columns``; ``payload_fn``, if
    given, is called after the device launch and returns the remaining
    columns (row-aligned with ``table``) or None. ``column_order`` fixes the
    output column order.

    ``batch_rows`` (> 0) caps rows per device program: larger tables are
    processed in chunks, each writing its own sorted run per bucket (the
    multi-run state incremental refresh also produces; optimize compacts
    it). Returns written file paths — bucket order within each chunk,
    chunk-major with repeated bucket ids when chunking kicks in.

    When ``session`` is given and its mesh spans more than one device (and the
    table clears conf ``hyperspace.tpu.build.distributedMinRows``), each chunk
    runs the DISTRIBUTED program instead: rows shard across the mesh, hash on
    device, one ``all_to_all`` routes every row to its owning device
    (bucket % n_devices), and each device sorts its buckets locally — the
    TPU-native replacement for the reference's cluster-wide
    ``repartition(numBuckets, cols)`` shuffle (ref: CoveringIndex.scala:54-69).
    Exchange-capacity overflow (skew) retries with doubled slot capacity until
    the exchange fits. Bucket file contents are identical to the single-device
    build's (same rows, same within-bucket order).
    """
    import time as _time

    import jax

    from hyperspace_tpu.exec.batch import table_to_batch
    from hyperspace_tpu.ops import encode
    from hyperspace_tpu.ops.sort import bucket_sort_build, padded_size

    timing = os.environ.get("HS_BUILD_TIMING", "") == "1"

    os.makedirs(out_dir, exist_ok=True)
    n = table.num_rows
    if n == 0:
        return []

    mesh = None
    capacity_factor = 2.0
    if session is not None:
        m = session.mesh
        # streaming callers pass the true total (``table`` is only the first
        # chunk there); distributedMinRows gates on the BUILD size, not the
        # chunk size. The whole distributed build sits behind the default-off
        # hyperspace.parallel.* master switch: off means the byte-identical
        # single-logical-device build below.
        if (
            session.conf.parallel_enabled
            and session.conf.parallel_build_enabled
            and m.devices.size > 1
            and (_total_rows if _total_rows is not None else n)
            >= session.conf.distributed_build_min_rows
        ):
            mesh = m
            capacity_factor = session.conf.rebucket_capacity_factor

    def _launch(chunk: pa.Table) -> dict:
        """Host encode + device program dispatch + async d2h start. Returns
        the in-flight state; nothing here blocks on the device."""
        marks = {}
        t = _time.perf_counter()
        batch = table_to_batch(chunk.select(bucket_sort_columns))
        keys, kinds, host_hashes = encode.encode_sort_columns(
            [batch[c] for c in bucket_sort_columns]
        )
        if timing:
            marks["encode_keys"] = round(_time.perf_counter() - t, 3)
        t = _time.perf_counter()
        cn = chunk.num_rows
        np2 = padded_size(cn)
        dev_keys = [jax.device_put(np.pad(k, (0, np2 - cn))) for k in keys]
        dev_hashes = [jax.device_put(np.pad(h, (0, np2 - cn))) for h in host_hashes]
        perm, counts = bucket_sort_build(dev_keys, dev_hashes, kinds, num_buckets, cn)
        counts.copy_to_host_async()
        # the permutation comes back in pieces so bucket writes can start
        # while later pieces are still in flight (device->host is the narrow
        # link — on a tunneled chip by far the narrowest)
        n_pieces = min(8, max(1, np2 // (1 << 18)))
        piece_len = np2 // n_pieces
        pieces = [perm[i * piece_len : (i + 1) * piece_len] for i in range(n_pieces)]
        for p in pieces:
            p.copy_to_host_async()
        if timing:
            marks["pad_upload_launch"] = round(_time.perf_counter() - t, 3)
        return {"chunk": chunk, "np2": np2, "counts": counts, "pieces": pieces, "marks": marks}

    def _prepare_chunk(state: dict, chunk_payload_fn) -> pa.Table:
        """Shared host prep before bucket writes: attach the lazily-decoded
        payload columns, fix the output column order, and collapse to
        single-chunk columns so per-bucket takes don't re-resolve chunk
        offsets (a numpy-gather variant measured equal within noise; arrow
        take keeps string/date columns on one code path)."""
        chunk, marks = state["chunk"], state["marks"]
        t = _time.perf_counter()
        if chunk_payload_fn is not None:
            payload = chunk_payload_fn()
            if payload is not None:
                for name in payload.column_names:
                    chunk = chunk.append_column(payload.schema.field(name), payload.column(name))
        if timing:
            marks["payload_decode"] = round(_time.perf_counter() - t, 3)
        t = _time.perf_counter()
        if column_order:
            chunk = chunk.select(column_order)
        chunk = chunk.combine_chunks()
        if timing:
            marks["combine_chunks"] = round(_time.perf_counter() - t, 3)
        return chunk

    def _finish(state: dict, chunk_payload_fn) -> List[str]:
        """Drain the permutation and write the per-bucket sorted parquet
        files; host-heavy, overlapped with the NEXT chunk's device work."""
        np2 = state["np2"]
        marks = state["marks"]
        chunk = _prepare_chunk(state, chunk_payload_fn)
        t = _time.perf_counter()
        counts_np = np.asarray(state["counts"])
        boundaries = np.concatenate([[0], np.cumsum(counts_np)])
        if timing:
            marks["counts_wait"] = round(_time.perf_counter() - t, 3)
        t = _time.perf_counter()

        def _take_write(b: int, lo: int, hi: int) -> str:
            path = os.path.join(out_dir, _bucket_file_name(b))
            # uncompressed PLAIN is the index-file dialect: the native decoder
            # (hyperspace_tpu/native) mmaps these and memcpys column chunks
            # into device-feedable buffers with zero decompression work
            rows = chunk.take(pa.array(perm_np[lo:hi]))
            pq.write_table(rows, path, use_dictionary=False, compression="NONE")
            return path

        from concurrent.futures import ThreadPoolExecutor

        perm_np = np.empty(np2, dtype=np.int32)
        arrived = 0
        next_piece = 0
        futures = []
        pieces = state["pieces"]
        with ThreadPoolExecutor(max_workers=8) as ex:
            for b in range(num_buckets):
                lo, hi = int(boundaries[b]), int(boundaries[b + 1])
                if hi <= lo:
                    continue
                while arrived < hi:
                    piece = np.asarray(pieces[next_piece])  # blocks for this piece only
                    perm_np[arrived : arrived + piece.shape[0]] = piece
                    arrived += piece.shape[0]
                    next_piece += 1
                futures.append(ex.submit(_take_write, b, lo, hi))
            out = [f.result() for f in futures]
        if timing:
            marks["perm_drain_take_write"] = round(_time.perf_counter() - t, 3)
            # stderr: bench.py's stdout contract is exactly one JSON line.
            # (Coarse wall-clock marks complement session.profile()'s XLA
            # traces for machines without trace tooling; labels match.)
            import sys as _sys

            print(f"HS_BUILD_TIMING rows={chunk.num_rows} {marks}", file=_sys.stderr, flush=True)
        return out

    def _launch_mesh(chunk: pa.Table) -> dict:
        """Distributed variant of ``_launch``: shard the encoded key planes
        over the mesh, dispatch the exchange program, and start async fetches.
        The returned state carries the device inputs so ``_finish_mesh`` can
        retry with doubled capacity on exchange overflow."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        from hyperspace_tpu.ops.bucketize import _next_pow2, distributed_bucket_sort_build

        marks = {}
        t = _time.perf_counter()
        batch = table_to_batch(chunk.select(bucket_sort_columns))
        keys, kinds, host_hashes = encode.encode_sort_columns(
            [batch[c] for c in bucket_sort_columns]
        )
        if timing:
            marks["encode_keys"] = round(_time.perf_counter() - t, 3)
        t = _time.perf_counter()
        cn = chunk.num_rows
        n_dev = int(mesh.devices.size)
        per_dev = padded_size(-(-cn // n_dev))
        pad_n = per_dev * n_dev
        sharding = NamedSharding(mesh, P(mesh.axis_names[0]))
        dev_keys = [jax.device_put(np.pad(k, (0, pad_n - cn)), sharding) for k in keys]
        dev_hashes = [jax.device_put(np.pad(h, (0, pad_n - cn)), sharding) for h in host_hashes]
        row_idx = jax.device_put(np.arange(pad_n, dtype=np.int32), sharding)
        capacity = min(
            _next_pow2(int(per_dev / n_dev * capacity_factor)), _next_pow2(per_dev)
        )
        bkts, ridx, vld, ovf = distributed_bucket_sort_build(
            mesh, dev_keys, dev_hashes, kinds, row_idx, cn, num_buckets, capacity
        )
        for a in (ovf, bkts, ridx, vld):
            a.copy_to_host_async()
        if timing:
            marks["pad_upload_launch"] = round(_time.perf_counter() - t, 3)
        return {
            "chunk": chunk,
            "bkts": bkts,
            "ridx": ridx,
            "vld": vld,
            "ovf": ovf,
            "n_dev": n_dev,
            "capacity": capacity,
            "per_dev": per_dev,
            "retry": (dev_keys, dev_hashes, kinds, row_idx, cn),
            "marks": marks,
        }

    def _finish_mesh(state: dict, chunk_payload_fn) -> List[str]:
        """Drain the distributed program's outputs and write per-bucket sorted
        parquet files. Buckets live wholly on their owner device, so each
        device shard yields its own contiguous bucket runs."""
        from hyperspace_tpu.ops.bucketize import _next_pow2, distributed_bucket_sort_build

        marks = state["marks"]
        chunk = _prepare_chunk(state, chunk_payload_fn)
        t = _time.perf_counter()

        capacity, per_dev = state["capacity"], state["per_dev"]
        bkts, ridx, vld, ovf = state["bkts"], state["ridx"], state["vld"], state["ovf"]
        while int(np.asarray(ovf).sum()) > 0:
            # skew overflowed a destination's slots: double capacity and rerun
            # (a source holds per_dev rows total, so capacity == per_dev
            # always fits and the loop terminates)
            if capacity >= per_dev:
                raise RuntimeError(
                    "distributed build exchange overflow at full capacity "
                    f"(capacity={capacity}, per_dev={per_dev})"
                )
            capacity = min(_next_pow2(capacity * 2), _next_pow2(per_dev))
            dev_keys, dev_hashes, kinds, row_idx, cn = state["retry"]
            bkts, ridx, vld, ovf = distributed_bucket_sort_build(
                mesh, dev_keys, dev_hashes, kinds, row_idx, cn, num_buckets, capacity
            )
        bkts_np = np.asarray(bkts)
        ridx_np = np.asarray(ridx)
        vld_np = np.asarray(vld)
        if timing:
            marks["exchange_drain"] = round(_time.perf_counter() - t, 3)
        t = _time.perf_counter()

        def _take_write(b: int, indices: np.ndarray) -> str:
            path = os.path.join(out_dir, _bucket_file_name(b))
            rows = chunk.take(pa.array(indices))
            pq.write_table(rows, path, use_dictionary=False, compression="NONE")
            return path

        from concurrent.futures import ThreadPoolExecutor

        n_dev = state["n_dev"]
        shard_len = bkts_np.shape[0] // n_dev
        futures = []
        with ThreadPoolExecutor(max_workers=8) as ex:
            for d in range(n_dev):
                sl = slice(d * shard_len, (d + 1) * shard_len)
                v_d = vld_np[sl]
                nv = int(v_d.sum())  # valid rows sort to the shard's prefix
                if nv == 0:
                    continue
                b_v = bkts_np[sl][:nv]
                r_v = ridx_np[sl][:nv]
                bounds = np.searchsorted(b_v, np.arange(num_buckets + 1))
                for b in range(d, num_buckets, n_dev):
                    lo, hi = int(bounds[b]), int(bounds[b + 1])
                    if hi > lo:
                        futures.append(ex.submit(_take_write, b, r_v[lo:hi]))
            out = [f.result() for f in futures]
        if timing:
            marks["bucket_take_write"] = round(_time.perf_counter() - t, 3)
            import sys as _sys

            print(f"HS_BUILD_TIMING mesh rows={chunk.num_rows} {marks}", file=_sys.stderr, flush=True)
        return out

    launch, finish = (_launch_mesh, _finish_mesh) if mesh is not None else (_launch, _finish)

    if _chunks is not None:
        # write_bucketed_groups' streaming entry: the chunk iterator replaces
        # the single-table slicing entirely (``table`` only sized the mesh
        # decision above)
        return _pipelined_chunks(_chunks, launch, finish)

    if batch_rows is not None and batch_rows > 0 and n > batch_rows:
        # chunked build, software-pipelined one chunk deep: chunk k+1's
        # device program (and its d2h transfers) runs while chunk k's host
        # side drains and writes parquet. Each chunk writes its own sorted
        # run per bucket — the multi-run state incremental refresh also
        # produces (UpdateMode.Merge); the join path re-sorts lazily and
        # optimize compacts. Peak device footprint is two chunks
        # (~2x batchRows rows); payload decodes lazily per chunk slice.
        return _pipelined_chunks(
            _sliced_chunks(table, payload_fn, batch_rows), launch, finish
        )

    return finish(launch(table), payload_fn)


def _sliced_chunks(table: pa.Table, payload_fn, batch_rows: int):
    """Yield (key_chunk, chunk_payload_fn) slices of one materialized table;
    the payload (if any) decodes ONCE lazily and is sliced per chunk. Chunks
    are EQUAL-size (ceil division) rather than batch_rows + remainder, so no
    sliver chunk pays a full device launch for a handful of rows."""
    payload_cell: List[Optional[pa.Table]] = []

    def full_payload() -> Optional[pa.Table]:
        if not payload_cell:
            payload_cell.append(payload_fn() if payload_fn is not None else None)
        return payload_cell[0]

    n = table.num_rows
    n_chunks = max(1, -(-n // batch_rows))
    size = -(-n // n_chunks)
    for off in range(0, n, size):
        chunk_pf = None
        if payload_fn is not None:

            def chunk_pf(off=off):
                p = full_payload()
                return p.slice(off, size) if p is not None else None

        yield table.slice(off, size), chunk_pf


def _pipelined_chunks(chunks, launch, finish) -> List[str]:
    """Drive (key_chunk, payload_fn) pairs through the launch/finish pipeline
    one chunk deep: chunk k+1's device program runs while chunk k's host side
    drains and writes parquet."""
    paths: List[str] = []
    in_flight: Optional[tuple] = None
    for key_chunk, chunk_payload_fn in chunks:
        state = launch(key_chunk)
        if in_flight is not None:
            paths.extend(finish(*in_flight))
        in_flight = (state, chunk_payload_fn)
    if in_flight is not None:
        paths.extend(finish(*in_flight))
    return paths


def write_bucketed_groups(
    groups,
    bucket_sort_columns: List[str],
    num_buckets: int,
    out_dir: str,
    column_order: Optional[List[str]] = None,
    batch_rows: Optional[int] = None,
    session=None,
    total_rows: Optional[int] = None,
) -> List[str]:
    """Out-of-core variant of :func:`write_bucketed`: ``groups`` is an
    ITERABLE of ``(key_table, payload_fn)`` pairs (each key_table holds the
    bucket/sort columns for one group of source rows; ``payload_fn()``
    lazily decodes that group's remaining columns, row-aligned). Groups are
    consumed strictly in order and sliced to ``batch_rows`` chunks, so peak
    host memory is O(2 chunks + one group's payload) regardless of total
    table size. Each chunk writes its own sorted run per bucket — the
    multi-run state the reference's incremental refresh also produces
    (ref: actions/RefreshIncrementalAction.scala:115-128); optimize
    compacts runs.

    The build path streams source FILES through this (indexes/covering.py
    ``CoveringIndex.write``), which is what lets a TPC-H SF100 build run
    with bounded RAM; the reference inherits the same property from Spark's
    streaming executors (ref: CoveringIndex.scala:54-69)."""
    os.makedirs(out_dir, exist_ok=True)

    def flattened():
        for key_table, payload_fn in groups:
            kn = key_table.num_rows
            if kn == 0:
                continue
            if batch_rows is not None and 0 < batch_rows < kn:
                yield from _sliced_chunks(key_table, payload_fn, batch_rows)
            else:
                yield key_table, payload_fn

    flat = flattened()
    first = next(flat, None)
    if first is None:
        return []

    import itertools as _it

    # payload_fn/batch_rows are NOT passed: the _chunks stream already
    # carries per-chunk payload closures and was sliced above — write_bucketed
    # reads neither on the _chunks path (and must not re-slice)
    return write_bucketed(
        first[0],  # fallback sizer for the mesh decision when total_rows=None
        bucket_sort_columns,
        num_buckets,
        out_dir,
        column_order=column_order,
        session=session,
        _chunks=_it.chain([first], flat),
        _total_rows=total_rows,
    )


class CoveringIndexConfig(IndexConfig):
    """(ref: HS/index/covering/CoveringIndexConfig.scala:39-200)"""

    def __init__(self, index_name: str, indexed_columns: List[str], included_columns: Optional[List[str]] = None):
        if not index_name:
            raise ValueError("Index name must not be empty")
        if not indexed_columns:
            raise ValueError("indexed_columns must not be empty")
        included_columns = list(included_columns or [])
        lowered = [c.lower() for c in indexed_columns + included_columns]
        if len(set(lowered)) != len(lowered):
            raise ValueError("Duplicate columns across indexed/included columns are not allowed")
        self._name = index_name
        self._indexed = list(indexed_columns)
        self._included = included_columns

    @property
    def index_name(self) -> str:
        return self._name

    @property
    def indexed_columns(self) -> List[str]:
        return list(self._indexed)

    @property
    def included_columns(self) -> List[str]:
        return list(self._included)

    @property
    def referenced_columns(self) -> List[str]:
        return self._indexed + self._included

    def create_index(self, ctx: CreateContext, df, properties: Dict[str, str]) -> CoveringIndex:
        """(ref: CoveringIndexConfig createIndex :92-116)"""
        index = CoveringIndex(
            self._indexed,
            self._included,
            num_buckets=ctx.session.conf.num_buckets,
            lineage=ctx.session.conf.lineage_enabled,
            extra_properties=dict(properties),
        )
        index.write(ctx, df)
        return index

    def __repr__(self) -> str:
        return f"CoveringIndexConfig({self._name!r}, indexed={self._indexed}, included={self._included})"

    class Builder:
        """Fluent builder (ref: CoveringIndexConfig builder, :118-200)."""

        def __init__(self):
            self._name: Optional[str] = None
            self._indexed: List[str] = []
            self._included: List[str] = []

        def indexName(self, name: str) -> "CoveringIndexConfig.Builder":
            if self._name:
                raise ValueError("indexName is already set")
            self._name = name
            return self

        index_name = indexName

        def indexBy(self, *columns: str) -> "CoveringIndexConfig.Builder":
            self._indexed.extend(columns)
            return self

        index_by = indexBy

        def include(self, *columns: str) -> "CoveringIndexConfig.Builder":
            self._included.extend(columns)
            return self

        def create(self) -> "CoveringIndexConfig":
            if not self._name:
                raise ValueError("indexName must be set")
            return CoveringIndexConfig(self._name, self._indexed, self._included)

    @staticmethod
    def builder() -> "CoveringIndexConfig.Builder":
        return CoveringIndexConfig.Builder()


registry.register(CoveringIndex.kind, CoveringIndex.from_derived_dataset)
