"""Kind-string -> Index class registry, reviving the polymorphic
``derivedDataset`` payload of a log entry (the reference uses Jackson
polymorphic deserialization; ref: HS/index/LogEntry.scala:33-46,
com/fasterxml/jackson/.../ScalaObjectMapper.scala)."""

from __future__ import annotations

from typing import Callable, Dict

from hyperspace_tpu.indexes.base import Index
from hyperspace_tpu.models.log_entry import DerivedDataset, IndexLogEntry

_REGISTRY: Dict[str, Callable[[DerivedDataset], Index]] = {}


def register(kind: str, factory: Callable[[DerivedDataset], Index]) -> None:
    _REGISTRY[kind] = factory


def revive(dd: DerivedDataset) -> Index:
    if dd.kind not in _REGISTRY:
        # import built-ins lazily to avoid import cycles
        import hyperspace_tpu.indexes.covering  # noqa: F401
        import hyperspace_tpu.indexes.dataskipping  # noqa: F401
    if dd.kind not in _REGISTRY:
        raise ValueError(f"Unknown index kind {dd.kind!r}")
    return _REGISTRY[dd.kind](dd)


def index_of_entry(entry: IndexLogEntry) -> Index:
    return revive(entry.derived_dataset)
