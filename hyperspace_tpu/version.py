__version__ = "0.1.0"

# Index log schema version written into every log entry
# (ref: HS/index/LogEntry.scala:23-30 — versioned log-entry base).
INDEX_LOG_VERSION = "0.1"
