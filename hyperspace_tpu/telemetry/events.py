"""Telemetry: structured event taxonomy + pluggable sink.

Events are emitted around every lifecycle action and on index usage
(ref: HS/telemetry/HyperspaceEvent.scala:28-156); the sink class is loaded
from conf ``hyperspace.eventLoggerClass`` with a NoOp default
(ref: HS/telemetry/HyperspaceEventLogging.scala:30-68).
"""

from __future__ import annotations

import importlib
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional


@dataclass
class HyperspaceEvent:
    app_info: Dict[str, str] = field(default_factory=dict)
    message: str = ""
    timestamp: float = field(default_factory=time.time)

    @property
    def name(self) -> str:
        return type(self).__name__


@dataclass
class ActionEvent(HyperspaceEvent):
    index_name: str = ""
    state: str = ""  # "Started" / "Success" / "Failure"


@dataclass
class CreateActionEvent(ActionEvent):
    pass


@dataclass
class DeleteActionEvent(ActionEvent):
    pass


@dataclass
class RestoreActionEvent(ActionEvent):
    pass


@dataclass
class VacuumActionEvent(ActionEvent):
    pass


@dataclass
class RefreshActionEvent(ActionEvent):
    pass


@dataclass
class RefreshIncrementalActionEvent(ActionEvent):
    pass


@dataclass
class RefreshQuickActionEvent(ActionEvent):
    pass


@dataclass
class OptimizeActionEvent(ActionEvent):
    pass


@dataclass
class CancelActionEvent(ActionEvent):
    pass


@dataclass
class HyperspaceIndexUsageEvent(HyperspaceEvent):
    """Emitted when the optimizer applies indexes to a query
    (ref: HS/telemetry/HyperspaceEvent.scala HyperspaceIndexUsageEvent)."""

    index_names: List[str] = field(default_factory=list)
    plan_summary: str = ""


class EventLogger:
    def log_event(self, event: HyperspaceEvent) -> None:
        raise NotImplementedError


class NoOpEventLogger(EventLogger):
    def log_event(self, event: HyperspaceEvent) -> None:
        pass


class CollectingEventLogger(EventLogger):
    """In-memory sink for tests (ref: MockEventLogger in TestUtils.scala:93-121)."""

    def __init__(self) -> None:
        self.events: List[HyperspaceEvent] = []

    def log_event(self, event: HyperspaceEvent) -> None:
        self.events.append(event)

    def reset(self) -> None:
        self.events = []


_cached: Dict[str, EventLogger] = {}


def get_event_logger(session) -> EventLogger:
    cls_name: Optional[str] = session.conf.get("hyperspace.eventLoggerClass")
    if not cls_name:
        return _cached.setdefault("__noop__", NoOpEventLogger())
    if cls_name not in _cached:
        module_name, _, attr = cls_name.rpartition(".")
        _cached[cls_name] = getattr(importlib.import_module(module_name), attr)()
    return _cached[cls_name]
