"""Telemetry: structured event taxonomy + pluggable sink.

Events are emitted around every lifecycle action and on index usage
(ref: HS/telemetry/HyperspaceEvent.scala:28-156); the sink class is loaded
from conf ``hyperspace.eventLoggerClass`` with a NoOp default
(ref: HS/telemetry/HyperspaceEventLogging.scala:30-68).
"""

from __future__ import annotations

import importlib
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional


@dataclass
class HyperspaceEvent:
    app_info: Dict[str, str] = field(default_factory=dict)
    message: str = ""
    timestamp: float = field(default_factory=time.time)

    @property
    def name(self) -> str:
        return type(self).__name__


@dataclass
class ActionEvent(HyperspaceEvent):
    index_name: str = ""
    state: str = ""  # "Started" / "Success" / "Failure"


@dataclass
class CreateActionEvent(ActionEvent):
    pass


@dataclass
class DeleteActionEvent(ActionEvent):
    pass


@dataclass
class RestoreActionEvent(ActionEvent):
    pass


@dataclass
class VacuumActionEvent(ActionEvent):
    pass


@dataclass
class RefreshActionEvent(ActionEvent):
    pass


@dataclass
class RefreshIncrementalActionEvent(ActionEvent):
    pass


@dataclass
class RefreshQuickActionEvent(ActionEvent):
    pass


@dataclass
class OptimizeActionEvent(ActionEvent):
    pass


@dataclass
class CancelActionEvent(ActionEvent):
    pass


@dataclass
class HyperspaceIndexUsageEvent(HyperspaceEvent):
    """Emitted when the optimizer applies indexes to a query
    (ref: HS/telemetry/HyperspaceEvent.scala HyperspaceIndexUsageEvent)."""

    index_names: List[str] = field(default_factory=list)
    plan_summary: str = ""


@dataclass
class ServingStatsEvent(HyperspaceEvent):
    """Periodic serving-runtime snapshot (``QueryServer.stats(emit=True)``):
    queue pressure, cache effectiveness, and latency tail."""

    queue_depth: int = 0
    rejected: int = 0
    plan_cache_hit_rate: float = 0.0
    bucket_cache_hit_rate: float = 0.0
    latency_p50: Optional[float] = None
    latency_p95: Optional[float] = None
    latency_p99: Optional[float] = None
    completed: int = 0


@dataclass
class ServingRejectionEvent(HyperspaceEvent):
    """A request was rejected at admission (queue full, backpressure)."""

    queue_depth: int = 0
    queued: int = 0


class EventLogger:
    def log_event(self, event: HyperspaceEvent) -> None:
        raise NotImplementedError


class NoOpEventLogger(EventLogger):
    def log_event(self, event: HyperspaceEvent) -> None:
        pass


class CollectingEventLogger(EventLogger):
    """In-memory sink for tests (ref: MockEventLogger in TestUtils.scala:93-121).

    Thread-safe: the serving runtime logs from worker threads concurrently,
    and a bare ``list.append`` raced with ``reset``/snapshot reads."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.events: List[HyperspaceEvent] = []

    def log_event(self, event: HyperspaceEvent) -> None:
        with self._lock:
            self.events.append(event)

    def snapshot(self) -> List[HyperspaceEvent]:
        """Consistent copy for readers racing concurrent log_event calls."""
        with self._lock:
            return list(self.events)

    def reset(self) -> None:
        with self._lock:
            self.events = []


_cached: Dict[str, EventLogger] = {}
_cached_lock = threading.Lock()


def get_event_logger(session) -> EventLogger:
    cls_name: Optional[str] = session.conf.get("hyperspace.eventLoggerClass")
    with _cached_lock:
        if not cls_name:
            return _cached.setdefault("__noop__", NoOpEventLogger())
        if cls_name not in _cached:
            module_name, _, attr = cls_name.rpartition(".")
            _cached[cls_name] = getattr(importlib.import_module(module_name), attr)()
        return _cached[cls_name]
