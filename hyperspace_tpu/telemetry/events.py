"""Telemetry: structured event taxonomy + pluggable sink.

Events are emitted around every lifecycle action and on index usage
(ref: HS/telemetry/HyperspaceEvent.scala:28-156); the sink class is loaded
from conf ``hyperspace.eventLoggerClass`` with a NoOp default
(ref: HS/telemetry/HyperspaceEventLogging.scala:30-68).
"""

from __future__ import annotations

import importlib
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional


@dataclass
class HyperspaceEvent:
    app_info: Dict[str, str] = field(default_factory=dict)
    message: str = ""
    timestamp: float = field(default_factory=time.time)

    @property
    def name(self) -> str:
        return type(self).__name__


@dataclass
class ActionEvent(HyperspaceEvent):
    index_name: str = ""
    state: str = ""  # "Started" / "Success" / "Failure"


@dataclass
class CreateActionEvent(ActionEvent):
    pass


@dataclass
class DeleteActionEvent(ActionEvent):
    pass


@dataclass
class RestoreActionEvent(ActionEvent):
    pass


@dataclass
class VacuumActionEvent(ActionEvent):
    pass


@dataclass
class RefreshActionEvent(ActionEvent):
    pass


@dataclass
class RefreshIncrementalActionEvent(ActionEvent):
    pass


@dataclass
class RefreshQuickActionEvent(ActionEvent):
    pass


@dataclass
class OptimizeActionEvent(ActionEvent):
    pass


@dataclass
class CancelActionEvent(ActionEvent):
    pass


@dataclass
class HyperspaceIndexUsageEvent(HyperspaceEvent):
    """Emitted when the optimizer applies indexes to a query
    (ref: HS/telemetry/HyperspaceEvent.scala HyperspaceIndexUsageEvent)."""

    index_names: List[str] = field(default_factory=list)
    plan_summary: str = ""


@dataclass
class ServingStatsEvent(HyperspaceEvent):
    """Periodic serving-runtime snapshot (``QueryServer.stats(emit=True)``):
    queue pressure, cache effectiveness, and latency tail."""

    queue_depth: int = 0
    rejected: int = 0
    plan_cache_hit_rate: float = 0.0
    bucket_cache_hit_rate: float = 0.0
    latency_p50: Optional[float] = None
    latency_p95: Optional[float] = None
    latency_p99: Optional[float] = None
    completed: int = 0


@dataclass
class ServingRejectionEvent(HyperspaceEvent):
    """A request was rejected at admission (queue full, backpressure)."""

    queue_depth: int = 0
    queued: int = 0


class EventLogger:
    def log_event(self, event: HyperspaceEvent) -> None:
        raise NotImplementedError


class NoOpEventLogger(EventLogger):
    def log_event(self, event: HyperspaceEvent) -> None:
        pass


class CollectingEventLogger(EventLogger):
    """In-memory sink for tests (ref: MockEventLogger in TestUtils.scala:93-121).

    Thread-safe: the serving runtime logs from worker threads concurrently,
    and a bare ``list.append`` raced with ``reset``/snapshot reads."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.events: List[HyperspaceEvent] = []

    def log_event(self, event: HyperspaceEvent) -> None:
        with self._lock:
            self.events.append(event)

    def snapshot(self) -> List[HyperspaceEvent]:
        """Consistent copy for readers racing concurrent log_event calls."""
        with self._lock:
            return list(self.events)

    def reset(self) -> None:
        with self._lock:
            self.events = []


_cache_lock = threading.Lock()


def get_event_logger(session) -> EventLogger:
    """Resolve the session's event sink from conf ``hyperspace.eventLoggerClass``.

    Logger instances are cached *per session*, keyed by the configured class
    name — NOT in a module-level dict keyed by class name alone, which made
    two sessions configured with the same class silently share one sink and
    ignored mid-session conf changes. Repeated calls with an unchanged conf
    return the same instance (tests rely on that identity); changing the conf
    key resolves a fresh logger on the next call.
    """
    cls_name: Optional[str] = session.conf.get("hyperspace.eventLoggerClass")
    key = cls_name or "__noop__"
    with _cache_lock:
        cache: Dict[str, EventLogger] = getattr(session, "_event_logger_cache", None)
        if cache is None:
            cache = {}
            session._event_logger_cache = cache
        got = cache.get(key)
        if got is None:
            if not cls_name:
                got = NoOpEventLogger()
            else:
                module_name, _, attr = cls_name.rpartition(".")
                got = getattr(importlib.import_module(module_name), attr)()
            cache[key] = got
        return got


def emit_event(session, event: HyperspaceEvent) -> None:
    """Log ``event`` on the session's sink AND count it in the process-wide
    metrics registry (``hs_events_total{event=...}``) — telemetry events are
    just another metric emitter on the shared observability substrate."""
    if session.conf.obs_metrics_enabled:
        from hyperspace_tpu.obs.metrics import REGISTRY

        REGISTRY.counter(
            "hs_events_total", "telemetry events emitted", event=event.name
        ).inc()
    get_event_logger(session).log_event(event)
