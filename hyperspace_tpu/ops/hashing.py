"""Bucket hashing — identical on host (numpy) and device (jnp).

The bucket assignment ``bucket = mix(key columns) % num_buckets`` must agree
between index build, query-time bucket pruning (hash the filter literal), and
hybrid-scan re-bucketing of appended rows — these are three call sites of one
function, so both backends share the same 32-bit finalizer arithmetic.

Plays the role of Spark's ``HashPartitioning`` over bucket columns
(ref: HS/index/covering/CoveringIndex.scala:54-69 repartition;
HS/index/covering/CoveringIndexRuleUtils.scala:357-417 on-the-fly re-bucketing).
"""

from __future__ import annotations

import hashlib
from typing import List, Sequence

import numpy as np

_C1 = np.uint32(0x85EBCA6B)
_C2 = np.uint32(0xC2B2AE35)
_SEED = np.uint32(0x9747B28C)


def _mix32_np(h):
    h = h ^ (h >> np.uint32(16))
    h = h * _C1
    h = h ^ (h >> np.uint32(13))
    h = h * _C2
    h = h ^ (h >> np.uint32(16))
    return h


def _mix32_jnp(h):
    import jax.numpy as jnp

    h = h ^ (h >> jnp.uint32(16))
    h = h * jnp.uint32(0x85EBCA6B)
    h = h ^ (h >> jnp.uint32(13))
    h = h * jnp.uint32(0xC2B2AE35)
    h = h ^ (h >> jnp.uint32(16))
    return h


def combine_hashes_np(cols: Sequence[np.ndarray]) -> np.ndarray:
    """Combine per-column uint32 hash inputs into one row hash."""
    with np.errstate(over="ignore"):
        h = np.full(cols[0].shape, _SEED, dtype=np.uint32)
        for i, c in enumerate(cols):
            h = _mix32_np(h ^ _mix32_np(c.astype(np.uint32) + np.uint32((i * 0x9E3779B9) & 0xFFFFFFFF)))
        return h


def combine_hashes_jnp(cols) -> "jnp.ndarray":  # noqa: F821
    import jax.numpy as jnp

    h = jnp.full(cols[0].shape, jnp.uint32(0x9747B28C), dtype=jnp.uint32)
    for i, c in enumerate(cols):
        h = _mix32_jnp(h ^ _mix32_jnp(c.astype(jnp.uint32) + jnp.uint32((i * 0x9E3779B9) & 0xFFFFFFFF)))
    return h


def bucket_ids_np(hash_inputs: Sequence[np.ndarray], num_buckets: int) -> np.ndarray:
    return (combine_hashes_np(hash_inputs) % np.uint32(num_buckets)).astype(np.int32)


def bucket_ids_jnp(hash_inputs, num_buckets: int):
    import jax.numpy as jnp

    return (combine_hashes_jnp(hash_inputs) % jnp.uint32(num_buckets)).astype(jnp.int32)


def string_hash32(value: str) -> np.uint32:
    """Stable 32-bit hash input for a string value (md5-derived; the per-row
    hash then mixes it like any numeric input)."""
    digest = hashlib.md5(str(value).encode("utf-8")).digest()
    return np.uint32(int.from_bytes(digest[:4], "little"))


_NULL_STRING_SENTINEL = "\x00__hs_null__"


def string_hash32_array(values: np.ndarray) -> np.ndarray:
    """Vectorized over uniques: factorize, hash each unique once, gather.
    Nulls hash via a fixed sentinel so build-time and query-time bucket
    assignment agree."""
    from hyperspace_tpu.ops.encode import factorize_strings

    codes, uniques, null_mask = factorize_strings(values)
    table = np.array([string_hash32(u) for u in uniques], dtype=np.uint32)
    out = np.where(null_mask, string_hash32(_NULL_STRING_SENTINEL), table[np.clip(codes, 0, None)])
    return out.astype(np.uint32)


def numeric_hash32(arr: np.ndarray) -> np.ndarray:
    """uint32 hash input for numeric/datetime columns: fold the int64 bit
    pattern to 32 bits.

    VALUE-consistent across integer and float representations: a float that
    holds an integral value hashes as that int64 (3.0 hashes like 3), -0.0
    normalizes to +0.0, and NaN hashes via the canonical NaN pattern. This
    matters because a nullable int64 parquet column decodes as float64 —
    without normalization the SAME key value lands in different buckets on
    the two sides of a join (or between an int literal and the stored
    column), silently dropping matches. Mirrored bit-exactly on device in
    ops/sort._device_hash32."""
    if arr.dtype.kind == "f":
        with np.errstate(invalid="ignore"):
            v = arr.astype(np.float64) + 0.0  # -0.0 -> +0.0
            # < 2^63 strictly: every such integral float casts to int64
            # exactly (float64 granularity near 2^63 is 1024). Above 2^53
            # the FLOAT side has already rounded the value at decode, so
            # cross-representation consistency is inherently bounded by
            # float64 exactness — the guarantee here covers every integral
            # value float64 can represent.
            isint = np.isfinite(v) & (np.abs(v) < 2.0**63) & (v == np.floor(v))
            int_bits = np.where(isint, v, 0).astype(np.int64).view(np.uint64)
            f_norm = np.where(np.isnan(v), np.float64("nan"), v)
            bits = np.where(isint, int_bits, f_norm.view(np.uint64))
    elif arr.dtype.kind == "M":
        bits = arr.view("int64").astype(np.uint64)
    elif arr.dtype.kind == "b":
        bits = arr.astype(np.uint64)
    else:
        bits = arr.astype(np.int64).view(np.uint64)
    return ((bits ^ (bits >> np.uint64(32))) & np.uint64(0xFFFFFFFF)).astype(np.uint32)


def literal_hash32(value) -> np.uint32:
    """Hash input of a scalar literal — used for query-time bucket pruning
    (ref: FilterIndexRule useBucketSpec, HS/index/covering/FilterIndexRule.scala:162-167)."""
    if isinstance(value, str):
        return string_hash32(value)
    arr = np.asarray([value])
    return numeric_hash32(arr)[0]


def bucket_of_literals(values: List, num_buckets: int) -> int:
    """Bucket id for one composite key tuple (one value per bucket column)."""
    inputs = [np.asarray([literal_hash32(v)], dtype=np.uint32) for v in values]
    return int(bucket_ids_np(inputs, num_buckets)[0])
