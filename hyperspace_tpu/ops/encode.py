"""Host-side column encoding for device consumption.

TPU has no variable-length types, so every column is encoded to dense numerics
before ``device_put`` (SURVEY.md §7 "Variable-length data (strings) on TPU"):

  - ``hash_input``  — uint32 per row, feeds bucket hashing (ops/hashing.py)
  - ``sort_key``    — int64 per row whose ordering equals the column's natural
                      ordering (strings -> dictionary rank; floats -> an
                      order-preserving bit transform; ints/dates -> identity)
"""

from __future__ import annotations

from typing import Tuple

import numpy as np

from hyperspace_tpu.ops import hashing


def factorize_strings(arr: np.ndarray) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Null-aware string factorization — THE one implementation shared by
    build-time sort keys, bucket hashing, and query-time device encoding (so
    the three encodings can never diverge).

    Returns ``(codes, uniques, null_mask)``: ``codes`` is int64 ranks into the
    sorted ``uniques`` with -1 for nulls.
    """
    obj = arr.astype(object)
    null_mask = np.array([x is None for x in obj], dtype=bool)
    filled = np.where(null_mask, "", obj).astype(str)
    uniques, inverse = np.unique(filled, return_inverse=True)
    codes = inverse.astype(np.int64)
    codes[null_mask] = -1
    return codes, uniques, null_mask


def sort_key_int64(arr: np.ndarray) -> np.ndarray:
    """Order-preserving int64 key for any supported column dtype."""
    kind = arr.dtype.kind
    if kind in ("i", "u", "b"):
        return arr.astype(np.int64)
    if kind == "M":  # datetime64
        return arr.view("int64").astype(np.int64)
    if kind == "f":
        bits = arr.astype(np.float64).view(np.int64)
        # IEEE-754 total order: flip sign bit for positives, all bits for negatives
        return np.where(bits >= 0, bits ^ np.int64(-0x8000000000000000), ~bits)
    if kind in ("U", "S", "O"):
        codes, _, _ = factorize_strings(arr)  # nulls (-1) sort first
        return codes
    raise TypeError(f"Unsupported column dtype for sorting: {arr.dtype}")


_I64_MAX = np.iinfo(np.int64).max
_I64_MIN = np.iinfo(np.int64).min

#: rid/plane sentinel for padding rows in the top-k programs — sorts after
#: every real row (real planes are clipped below it, real rids are counts)
ORDER_PLANE_SENTINEL = _I64_MAX


def order_plane(arr: np.ndarray, asc: bool = True) -> np.ndarray:
    """Signed-comparison int64 order plane for ONE sort key column, matching
    the host ``Sort`` semantics (executor._key_codes): missing values
    (NaN/NaT/None) sort LAST in both directions, ``-0.0 == +0.0``, and the
    DESC plane is the negated ASC plane.

    This is deliberately NOT ``sort_key_int64``: that transform is
    order-preserving only under *unsigned* int64 comparison (its float branch
    maps positive floats below negative ones when compared signed), which is
    fine for the internally-consistent bucket layouts it feeds but wrong for
    ``lax.sort``'s signed total order. Here floats get the signed-safe
    transform (flip the magnitude bits of negatives), and every plane is
    clipped to ``[INT64_MIN+2, INT64_MAX-2]`` so DESC negation cannot
    overflow and ``INT64_MAX`` stays reserved for missing/padding. String
    planes are dense ranks over THIS array only — callers merging candidate
    sets across chunks must re-encode over the combined values
    (TopKStream handles this like GroupedAggStream._remap_string_key).
    """
    kind = arr.dtype.kind
    n = arr.shape[0]
    if kind in ("i", "b"):
        v = arr.astype(np.int64)
        missing = np.zeros(n, dtype=bool)
    elif kind == "u":
        v = np.minimum(arr, np.uint64(_I64_MAX - 2)).astype(np.int64)
        missing = np.zeros(n, dtype=bool)
    elif kind == "M":
        missing = np.isnat(arr)
        v = arr.view("int64").astype(np.int64)
    elif kind == "f":
        f = arr.astype(np.float64)
        missing = np.isnan(f)
        # collapse -0.0/+0.0 (np.unique ranks them equal) and park NaNs on a
        # fixed value before the bit transform (masked to MAX below anyway)
        f = np.where(missing | (f == 0.0), np.float64(0.0), f)
        bits = f.view(np.int64)
        v = np.where(bits >= 0, bits, bits ^ np.int64(_I64_MAX))
    elif kind in ("U", "S", "O"):
        obj = arr.astype(object)
        # same missing definition as the host sort path (None or float NaN)
        missing = np.array(
            [x is None or (isinstance(x, float) and x != x) for x in obj], dtype=bool
        )
        filled = np.where(missing, "", obj).astype(str)
        _, inverse = np.unique(filled, return_inverse=True)
        v = inverse.astype(np.int64)
    else:
        raise TypeError(f"Unsupported column dtype for ordering: {arr.dtype}")
    v = np.clip(v, _I64_MIN + 2, _I64_MAX - 2)
    if not asc:
        v = -v
    v[missing] = _I64_MAX
    return v


def hash_input_uint32(arr: np.ndarray) -> np.ndarray:
    """uint32 bucket-hash input for any supported column dtype."""
    if arr.dtype.kind in ("U", "S", "O"):
        return hashing.string_hash32_array(arr)
    return hashing.numeric_hash32(arr)


def encode_key_columns(columns) -> Tuple[np.ndarray, np.ndarray]:
    """Encode the ordered list of key columns.

    Returns ``(hash_inputs, sort_keys)`` with shapes (k, n) — uint32 and int64.
    """
    hash_inputs = np.stack([hash_input_uint32(c) for c in columns])
    sort_keys = np.stack([sort_key_int64(c) for c in columns])
    return hash_inputs, sort_keys


def encode_sort_columns(columns):
    """Per-column encoding for the fused build program (ops/sort.bucket_sort_build).

    Returns ``(keys, kinds, host_hashes)``:
      - ``keys``: one 1-D order key per column; int/date/bool columns whose
        values fit int32 are downcast (32-bit device sort is ~2x the speed of
        the emulated 64-bit one) — safe because the device widens back to the
        exact int64 value before hashing; string codes are always int32.
      - ``kinds``: dtype kind per column (``'s'`` for strings).
      - ``host_hashes``: uint32 hash planes for the string columns only —
        every other kind's hash input is reconstructed on device.
    """
    keys, kinds, host_hashes = [], [], []
    for c in columns:
        kind = c.dtype.kind
        if kind in ("U", "S", "O"):
            codes, _, _ = factorize_strings(c)
            keys.append(codes.astype(np.int32))
            kinds.append("s")
            host_hashes.append(hash_input_uint32(c))
            continue
        k = sort_key_int64(c)
        if kind != "f" and k.size and -(2**31) <= int(k.min()) and int(k.max()) < 2**31:
            k = k.astype(np.int32)
        keys.append(k)
        kinds.append(kind if kind in "iubMf" else "i")
    return keys, tuple(kinds), host_hashes
