"""Pallas TPU kernels for the framework's hot device ops.

Two kernels back the build paths (guide: /opt/skills/guides/pallas_guide.md):

- ``segmented_min_max`` — one-pass fused min+max over a (segments, width)
  matrix, the device program behind MinMaxSketch builds: one row per source
  file, padded to a rectangle, both aggregates in a single VMEM sweep
  (replaces the reference's per-file Spark aggregate jobs,
  ref: HS/index/dataskipping/sketch/MinMaxSketch.scala:33-43).
- ``bucket_histogram`` — rows-per-bucket counts for write planning and skew
  detection in the bucketed index build (the device analogue of counting
  Spark's shuffle partition sizes; ref: HS/index/covering/CoveringIndex.scala:54-69).

Off-TPU (CPU tests, virtual meshes) the kernels run in interpreter mode; the
numerics are identical.
"""

from __future__ import annotations

from functools import partial

import jax

from hyperspace_tpu.utils.x64 import ensure_x64


import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_LANES = 128
_SUBLANES = 8


def _use_interpret() -> bool:
    return jax.default_backend() != "tpu"


# ---------------------------------------------------------------------------
# segmented min/max
# ---------------------------------------------------------------------------
#
# Mosaic has no 64-bit types, so the kernel never sees f64: values are encoded
# host-side as order-preserving uint64 keys, split into two bias-corrected
# int32 planes (hi, lo), and the kernel keeps running lexicographic minima and
# maxima of (hi, lo) pairs — exact for the full f64 range. A third int32 plane
# masks padding / SQL nulls.

_I32_MAX = np.int32(2**31 - 1)
_I32_MIN = np.int32(-(2**31))


def _f64_to_orderable_u64(v: np.ndarray) -> np.ndarray:
    """Monotone f64 -> uint64 (NaNs must be excluded by the caller). The
    extreme keys 0 and 2**64-1 are unreachable (they'd require NaN bit
    patterns), so they are safe identity sentinels."""
    bits = np.ascontiguousarray(v, dtype=np.float64).view(np.uint64)
    neg = (bits >> np.uint64(63)).astype(bool)
    return np.where(neg, ~bits, bits | np.uint64(0x8000000000000000))


def _orderable_u64_to_f64(key: np.ndarray) -> np.ndarray:
    was_pos = (key >> np.uint64(63)).astype(bool)
    bits = np.where(was_pos, key & np.uint64(0x7FFFFFFFFFFFFFFF), ~key)
    return bits.view(np.float64)


def _split_hi_lo(key: np.ndarray):
    """uint64 -> (hi, lo) int32 planes whose signed lexicographic order equals
    the unsigned uint64 order (both halves are bias-flipped)."""
    hi = ((key >> np.uint64(32)).astype(np.uint32) ^ np.uint32(0x80000000)).view(np.int32)
    lo = ((key & np.uint64(0xFFFFFFFF)).astype(np.uint32) ^ np.uint32(0x80000000)).view(np.int32)
    return hi, lo


def _join_hi_lo(hi: np.ndarray, lo: np.ndarray) -> np.ndarray:
    h = (hi.view(np.uint32) ^ np.uint32(0x80000000)).astype(np.uint64)
    l = (lo.view(np.uint32) ^ np.uint32(0x80000000)).astype(np.uint64)
    return (h << np.uint64(32)) | l


def _lex_fold_min(run_h, run_l, cand_h, cand_l):
    """Merge a (rows,1) candidate pair into the running lexicographic min."""
    nh = jnp.minimum(run_h, cand_h)
    l1 = jnp.where(run_h == nh, run_l, _I32_MAX)
    l2 = jnp.where(cand_h == nh, cand_l, _I32_MAX)
    return nh, jnp.minimum(l1, l2)


def _lex_fold_max(run_h, run_l, cand_h, cand_l):
    nh = jnp.maximum(run_h, cand_h)
    l1 = jnp.where(run_h == nh, run_l, _I32_MIN)
    l2 = jnp.where(cand_h == nh, cand_l, _I32_MIN)
    return nh, jnp.maximum(l1, l2)


def _minmax_kernel(h_ref, l_ref, m_ref, minh_ref, minl_ref, maxh_ref, maxl_ref):
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _():
        minh_ref[:] = jnp.full_like(minh_ref, _I32_MAX)
        minl_ref[:] = jnp.full_like(minl_ref, _I32_MAX)
        maxh_ref[:] = jnp.full_like(maxh_ref, _I32_MIN)
        maxl_ref[:] = jnp.full_like(maxl_ref, _I32_MIN)

    valid = m_ref[:] != 0
    hi = h_ref[:]
    lo = l_ref[:]

    # -- tile-local lexicographic min over the lane axis --
    hi_mn = jnp.where(valid, hi, _I32_MAX)
    lo_mn = jnp.where(valid, lo, _I32_MAX)
    th = jnp.min(hi_mn, axis=1, keepdims=True)
    tl = jnp.min(jnp.where(hi_mn == th, lo_mn, _I32_MAX), axis=1, keepdims=True)
    nh, nl = _lex_fold_min(minh_ref[:], minl_ref[:], th, tl)
    minh_ref[:] = nh
    minl_ref[:] = nl

    # -- tile-local lexicographic max --
    hi_mx = jnp.where(valid, hi, _I32_MIN)
    lo_mx = jnp.where(valid, lo, _I32_MIN)
    th = jnp.max(hi_mx, axis=1, keepdims=True)
    tl = jnp.max(jnp.where(hi_mx == th, lo_mx, _I32_MIN), axis=1, keepdims=True)
    nh, nl = _lex_fold_max(maxh_ref[:], maxl_ref[:], th, tl)
    maxh_ref[:] = nh
    maxl_ref[:] = nl


@partial(jax.jit, static_argnames=("interpret",))
def _minmax_call(hi, lo, mask, interpret: bool):
    n_seg, width = hi.shape
    row_tile = _SUBLANES
    col_tile = min(width, 512)
    grid = (n_seg // row_tile, width // col_tile)
    blk = pl.BlockSpec((row_tile, col_tile), lambda i, j: (i, j), memory_space=pltpu.VMEM)
    out_blk = pl.BlockSpec((row_tile, 1), lambda i, j: (i, j - j), memory_space=pltpu.VMEM)
    return pl.pallas_call(
        _minmax_kernel,
        grid=grid,
        in_specs=[blk, blk, blk],
        out_specs=[out_blk] * 4,
        out_shape=[jax.ShapeDtypeStruct((n_seg, 1), jnp.int32)] * 4,
        interpret=interpret,
    )(hi, lo, mask)


# Cap on padded (rows x width) elements per device call; segments are split /
# grouped so one huge file can never force a dense n_files x max_rows matrix.
_MINMAX_CALL_ELEMS = 1 << 23


def segmented_min_max(segments):
    """Per-segment (min, max) of variable-length numeric segments.

    ``segments`` is a list of 1-D numpy arrays (one per source file). NaNs
    (SQL nulls) are ignored, matching Min/Max aggregate semantics. Returns
    (mins, maxs) as float64 numpy arrays of length ``len(segments)``;
    all-null/empty segments yield (nan, nan). Exact over the full f64 range
    (the kernel compares order-preserving 2x-int32 keys, not floats).

    Memory-bounded: oversized segments are split into pieces and pieces are
    batched into device calls of at most ``_MINMAX_CALL_ELEMS`` padded
    elements; per-piece results fold together exactly on the host (each piece
    result is already an exact element of the segment).
    """
    ensure_x64()
    n = len(segments)
    if n == 0:
        return np.empty(0), np.empty(0)

    max_piece = _MINMAX_CALL_ELEMS // _SUBLANES
    pieces = []  # (orig_idx, 1-D array)
    for i, s in enumerate(segments):
        s = np.asarray(s)
        if s.shape[0] <= max_piece:
            pieces.append((i, s))
        else:
            for off in range(0, s.shape[0], max_piece):
                pieces.append((i, s[off : off + max_piece]))

    mins = np.full(n, np.nan)
    maxs = np.full(n, np.nan)
    group: list = []
    group_w = 1

    def flush() -> None:
        nonlocal group, group_w
        if not group:
            return
        g_mins, g_maxs = _minmax_rect([p for _, p in group])
        for (idx, _), mn, mx in zip(group, g_mins, g_maxs):
            mins[idx] = np.fmin(mins[idx], mn)
            maxs[idx] = np.fmax(maxs[idx], mx)
        group, group_w = [], 1

    for idx, p in pieces:
        w = max(int(p.shape[0]), 1)
        new_w = max(group_w, w)
        rows = -(-(len(group) + 1) // _SUBLANES) * _SUBLANES
        if group and rows * new_w > _MINMAX_CALL_ELEMS:
            flush()
            new_w = w
        group.append((idx, p))
        group_w = new_w
    flush()
    return mins, maxs


def _minmax_rect(segments):
    """One dense (padded) device call. Internal; see ``segmented_min_max``."""
    n = len(segments)
    width = max(max((s.shape[0] for s in segments), default=1), 1)
    rows = -(-n // _SUBLANES) * _SUBLANES
    col_tile = min(512, -(-width // _LANES) * _LANES)
    width_p = -(-width // col_tile) * col_tile
    hi = np.zeros((rows, width_p), dtype=np.int32)
    lo = np.zeros((rows, width_p), dtype=np.int32)
    mask = np.zeros((rows, width_p), dtype=np.int32)
    for i, s in enumerate(segments):
        v = np.asarray(s, dtype=np.float64)
        ok = ~np.isnan(v)
        v = v[ok]
        if v.shape[0] == 0:
            continue
        h, l = _split_hi_lo(_f64_to_orderable_u64(v))
        hi[i, : v.shape[0]] = h
        lo[i, : v.shape[0]] = l
        mask[i, : v.shape[0]] = 1
    minh, minl, maxh, maxl = _minmax_call(
        jnp.asarray(hi), jnp.asarray(lo), jnp.asarray(mask), _use_interpret()
    )
    minh = np.asarray(minh)[:n, 0]
    minl = np.asarray(minl)[:n, 0]
    maxh = np.asarray(maxh)[:n, 0]
    maxl = np.asarray(maxl)[:n, 0]
    mins = _orderable_u64_to_f64(_join_hi_lo(minh, minl))
    maxs = _orderable_u64_to_f64(_join_hi_lo(maxh, maxl))
    # rows that stayed at the identity sentinels had no valid values at all
    empty = (minh == _I32_MAX) & (minl == _I32_MAX)
    mins = np.where(empty, np.nan, mins)
    maxs = np.where(empty, np.nan, maxs)
    return mins, maxs


# ---------------------------------------------------------------------------
# bucket histogram
# ---------------------------------------------------------------------------


def _hist_kernel(b_ref, out_ref):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    buckets = b_ref[:]  # (1, tile)
    nb = out_ref.shape[0]
    # one-hot compare against all bucket ids via a 2-D iota whose rows are the
    # ids; (1, tile) broadcasts over rows, the lane-axis reduce yields (nb, 1).
    # (No reindexing/transpose — Mosaic rejects gather-style relayouts.)
    ids = jax.lax.broadcasted_iota(jnp.int32, (nb, buckets.shape[1]), 0)
    eq = (ids == buckets).astype(jnp.int32)  # (nb, tile)
    # dtype pinned: with x64 enabled jnp.sum would promote to (Mosaic-less) i64
    out_ref[:] = out_ref[:] + jnp.sum(eq, axis=1, keepdims=True, dtype=jnp.int32)


@partial(jax.jit, static_argnames=("num_buckets", "interpret"))
def _hist_call(buckets, num_buckets: int, interpret: bool):
    n = buckets.shape[1]
    tile = min(n, 2048)
    grid = (n // tile,)
    return pl.pallas_call(
        _hist_kernel,
        grid=grid,
        in_specs=[pl.BlockSpec((1, tile), lambda i: (i - i, i), memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((num_buckets, 1), lambda i: (i - i, i - i), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((num_buckets, 1), jnp.int32),
        interpret=interpret,
    )(buckets)


def bucket_histogram(bucket_ids, num_buckets: int):
    """Rows per bucket. ``bucket_ids`` is a 1-D int array (host or device);
    out-of-range ids land in no bucket. Returns int32 numpy array (num_buckets,)."""
    ensure_x64()
    b = np.asarray(bucket_ids, dtype=np.int32)
    n = b.shape[0]
    if n == 0:
        return np.zeros(num_buckets, dtype=np.int32)
    tile = min(max(n, 1), 2048)
    n_p = -(-n // tile) * tile
    padded = np.full((1, n_p), -1, dtype=np.int32)  # -1 matches no bucket id
    padded[0, :n] = b
    nb_p = -(-num_buckets // _LANES) * _LANES
    out = _hist_call(jnp.asarray(padded), nb_p, _use_interpret())
    return np.asarray(out)[:num_buckets, 0]
