"""Pallas TPU kernels for the framework's hot device ops.

Two kernels back the build paths (guide: /opt/skills/guides/pallas_guide.md):

- ``segmented_min_max`` — one-pass fused min+max over a (segments, width)
  matrix, the device program behind MinMaxSketch builds: one row per source
  file, padded to a rectangle, both aggregates in a single VMEM sweep
  (replaces the reference's per-file Spark aggregate jobs,
  ref: HS/index/dataskipping/sketch/MinMaxSketch.scala:33-43).
- ``bucket_histogram`` — rows-per-bucket counts for write planning and skew
  detection in the bucketed index build (the device analogue of counting
  Spark's shuffle partition sizes; ref: HS/index/covering/CoveringIndex.scala:54-69).

Off-TPU (CPU tests, virtual meshes) the kernels run in interpreter mode; the
numerics are identical.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_LANES = 128
_SUBLANES = 8


def _use_interpret() -> bool:
    return jax.default_backend() != "tpu"


# ---------------------------------------------------------------------------
# segmented min/max
# ---------------------------------------------------------------------------


def _minmax_kernel(x_ref, min_ref, max_ref):
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _():
        min_ref[:] = jnp.full_like(min_ref, jnp.inf)
        max_ref[:] = jnp.full_like(max_ref, -jnp.inf)

    blk = x_ref[:]
    # NaN doubles as both padding and SQL-null; min/max ignore it
    valid = jnp.logical_not(jnp.isnan(blk))
    lo = jnp.where(valid, blk, jnp.inf)
    hi = jnp.where(valid, blk, -jnp.inf)
    min_ref[:] = jnp.minimum(min_ref[:], jnp.min(lo, axis=1, keepdims=True))
    max_ref[:] = jnp.maximum(max_ref[:], jnp.max(hi, axis=1, keepdims=True))


@partial(jax.jit, static_argnames=("interpret",))
def _minmax_call(x, interpret: bool):
    n_seg, width = x.shape
    row_tile = _SUBLANES
    col_tile = min(width, 512)
    grid = (n_seg // row_tile, width // col_tile)
    return pl.pallas_call(
        _minmax_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((row_tile, col_tile), lambda i, j: (i, j), memory_space=pltpu.VMEM)
        ],
        out_specs=[
            pl.BlockSpec((row_tile, 1), lambda i, j: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((row_tile, 1), lambda i, j: (i, 0), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n_seg, 1), x.dtype),
            jax.ShapeDtypeStruct((n_seg, 1), x.dtype),
        ],
        interpret=interpret,
    )(x)


def segmented_min_max(segments):
    """Per-segment (min, max) of variable-length numeric segments.

    ``segments`` is a list of 1-D numpy arrays (one per source file). NaNs
    (SQL nulls) are ignored, matching Min/Max aggregate semantics. Returns
    (mins, maxs) as float64 numpy arrays of length ``len(segments)``;
    all-null/empty segments yield (nan, nan).
    """
    n = len(segments)
    if n == 0:
        return np.empty(0), np.empty(0)
    width = max(max((s.shape[0] for s in segments), default=1), 1)
    rows = -(-n // _SUBLANES) * _SUBLANES
    col_tile = min(512, -(-width // _LANES) * _LANES)
    width_p = -(-width // col_tile) * col_tile
    mat = np.full((rows, width_p), np.nan, dtype=np.float64)
    for i, s in enumerate(segments):
        v = np.asarray(s, dtype=np.float64)
        mat[i, : v.shape[0]] = v
    mins, maxs = _minmax_call(jnp.asarray(mat), _use_interpret())
    mins = np.asarray(mins)[:n, 0].copy()
    maxs = np.asarray(maxs)[:n, 0].copy()
    # rows that stayed at the reduce identity had no valid values at all
    mins[np.isinf(mins)] = np.nan
    maxs[np.isinf(maxs)] = np.nan
    return mins, maxs


# ---------------------------------------------------------------------------
# bucket histogram
# ---------------------------------------------------------------------------


def _hist_kernel(b_ref, out_ref):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    buckets = b_ref[:]  # (1, tile)
    nb = out_ref.shape[1]
    # one-hot compare against all bucket ids, reduce over the tile axis (VPU)
    ids = jax.lax.broadcasted_iota(jnp.int32, (1, nb), 1)
    eq = (buckets[0, :, None] == ids[0, None, :]).astype(jnp.int32)  # (tile, nb)
    out_ref[:] = out_ref[:] + jnp.sum(eq, axis=0, keepdims=True)


@partial(jax.jit, static_argnames=("num_buckets", "interpret"))
def _hist_call(buckets, num_buckets: int, interpret: bool):
    n = buckets.shape[1]
    tile = min(n, 2048)
    grid = (n // tile,)
    return pl.pallas_call(
        _hist_kernel,
        grid=grid,
        in_specs=[pl.BlockSpec((1, tile), lambda i: (0, i), memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((1, num_buckets), lambda i: (0, 0), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((1, num_buckets), jnp.int32),
        interpret=interpret,
    )(buckets)


def bucket_histogram(bucket_ids, num_buckets: int):
    """Rows per bucket. ``bucket_ids`` is a 1-D int array (host or device);
    out-of-range ids land in no bucket. Returns int32 numpy array (num_buckets,)."""
    b = np.asarray(bucket_ids, dtype=np.int32)
    n = b.shape[0]
    if n == 0:
        return np.zeros(num_buckets, dtype=np.int32)
    tile = min(max(n, 1), 2048)
    n_p = -(-n // tile) * tile
    padded = np.full((1, n_p), -1, dtype=np.int32)  # -1 matches no bucket id
    padded[0, :n] = b
    nb_p = -(-num_buckets // _LANES) * _LANES
    out = _hist_call(jnp.asarray(padded), nb_p, _use_interpret())
    return np.asarray(out)[0, :num_buckets]
