"""Distributed re-bucketing: the TPU-native replacement for Spark's shuffle.

``rebucket`` moves each row to the device that owns its bucket
(``device = bucket % n_devices``) with ONE ``all_to_all`` over ICI inside a
``shard_map`` — replacing the JVM hash-shuffle behind
``repartition(numBuckets, cols)`` (ref: HS/index/covering/CoveringIndex.scala:54-69)
and the on-the-fly re-bucketing of appended data in hybrid scan
(ref: HS/index/covering/CoveringIndexRuleUtils.scala:357-417).

Rows are exchanged in fixed-capacity slots (static shapes for XLA): each
device reserves ``capacity`` rows for every destination; a validity mask marks
real rows. Capacity overflow is detected and reported so callers can retry
with a larger factor — the skew-handling strategy (SURVEY.md §7 hard parts).
"""

from __future__ import annotations

from functools import partial
from typing import Dict, List, Tuple

import jax

from hyperspace_tpu.utils.x64 import ensure_x64


import jax.numpy as jnp  # noqa: E402
from hyperspace_tpu.parallel.mesh import get_shard_map  # noqa: E402

shard_map = get_shard_map()
from jax.sharding import Mesh, PartitionSpec as P  # noqa: E402


def _stage_for_exchange(values, dest, n_dev: int, capacity: int, fill=0, valid=None):
    """Scatter local rows into a (n_dev, capacity) staging grid keyed by
    destination device; rows beyond capacity are dropped (and counted).
    ``valid`` (optional bool mask) excludes padding rows from the exchange —
    needed when staging the output of a previous exchange phase."""
    n_loc = dest.shape[0]
    if valid is not None:
        dest = jnp.where(valid, dest, n_dev)  # invalid rows -> scratch bin
    order = jnp.argsort(dest, stable=True)
    dest_sorted = dest[order]
    counts = jnp.bincount(dest, length=n_dev)  # scratch bin excluded
    offsets = jnp.cumsum(counts) - counts
    offsets_ext = jnp.concatenate([offsets, jnp.zeros((1,), offsets.dtype)])
    rank = jnp.arange(n_loc) - offsets_ext[jnp.minimum(dest_sorted, n_dev)]
    in_slot = (dest_sorted < n_dev) & (rank < capacity)
    slot = jnp.minimum(dest_sorted, n_dev - 1) * capacity + jnp.clip(rank, 0, capacity - 1)
    slot = jnp.where(in_slot, slot, n_dev * capacity)  # overflow/invalid -> scratch

    staged = []
    for v in values:
        v_sorted = v[order]
        buf = jnp.full((n_dev * capacity + 1,), fill, dtype=v.dtype)
        buf = buf.at[slot].set(v_sorted)
        staged.append(buf[:-1].reshape(n_dev, capacity))
    mask = jnp.zeros((n_dev * capacity + 1,), dtype=bool).at[slot].set(in_slot)
    return staged, mask[:-1].reshape(n_dev, capacity), counts


_UNSIGNED_BY_WIDTH = {1: "uint8", 2: "uint16", 4: "uint32"}


def _to_planes(v):
    """Split an array into bit-exact int32 planes (1 plane for <=32-bit
    dtypes, hi/lo planes for 64-bit) so a whole exchange can ride ONE
    all_to_all regardless of column dtypes. Sub-32-bit values travel as
    their BIT PATTERNS (bitcast to the same-width unsigned, zero-extended) —
    never value casts, so bfloat16/float16/float8 survive exactly."""
    from jax import lax

    dt = v.dtype
    if dt == jnp.bool_:
        return [v.astype(jnp.int32)]
    if dt.itemsize == 8:
        u = lax.bitcast_convert_type(v, jnp.uint64)
        hi = lax.bitcast_convert_type((u >> jnp.uint64(32)).astype(jnp.uint32), jnp.int32)
        lo = lax.bitcast_convert_type((u & jnp.uint64(0xFFFFFFFF)).astype(jnp.uint32), jnp.int32)
        return [hi, lo]
    if dt == jnp.int32:
        return [v]
    width = jnp.dtype(_UNSIGNED_BY_WIDTH[dt.itemsize])
    u = v if dt == width else lax.bitcast_convert_type(v, width)
    if dt.itemsize == 4:
        return [lax.bitcast_convert_type(u, jnp.int32)]
    return [u.astype(jnp.int32)]  # zero-extend the bit pattern


def _from_planes(planes, dt):
    """Inverse of ``_to_planes``."""
    from jax import lax

    dt = jnp.dtype(dt)
    if dt == jnp.bool_:
        return planes[0].astype(jnp.bool_)
    if dt.itemsize == 8:
        hi = lax.bitcast_convert_type(planes[0], jnp.uint32).astype(jnp.uint64)
        lo = lax.bitcast_convert_type(planes[1], jnp.uint32).astype(jnp.uint64)
        return lax.bitcast_convert_type((hi << jnp.uint64(32)) | lo, dt)
    if dt == jnp.int32:
        return planes[0]
    width = jnp.dtype(_UNSIGNED_BY_WIDTH[dt.itemsize])
    if dt.itemsize == 4:
        u = lax.bitcast_convert_type(planes[0], width)
    else:
        u = planes[0].astype(width)  # truncate back to the original bits
    return u if dt == width else lax.bitcast_convert_type(u, dt)


def _exchange_packed(staged, mask, axis):
    """The one-collective exchange: every staged (n_dev, capacity) buffer and
    the slot mask are split into bit-exact int32 planes, stacked into a single
    (n_dev, capacity, planes) tensor, exchanged with ONE tiled ``all_to_all``
    over ``axis``, and unpacked back to the original dtypes. One collective
    launch per exchange phase — the compiled-HLO property ``dryrun_multichip``
    and tests/test_hlo_collectives.py assert (SURVEY.md §2.9: build = one
    all-to-all; hierarchical = one per phase)."""
    dts = [v.dtype for v in staged]
    planes = []
    for v in staged:
        planes.extend(_to_planes(v))
    planes.extend(_to_planes(mask))
    packed = jnp.stack(planes, axis=-1)
    out = jax.lax.all_to_all(packed, axis, split_axis=0, concat_axis=0, tiled=True)
    out = out.reshape(-1, out.shape[-1])
    res, i = [], 0
    for dt in dts:
        k = 2 if jnp.dtype(dt).itemsize > 4 and dt != jnp.bool_ else 1
        res.append(_from_planes([out[:, i + j] for j in range(k)], dt))
        i += k
    out_mask = out[:, i].astype(jnp.bool_)
    return res, out_mask


def rebucket(
    mesh: Mesh,
    arrays: Dict[str, "jax.Array"],
    bucket_ids: "jax.Array",
    capacity: int,
) -> Tuple[Dict[str, "jax.Array"], "jax.Array", "jax.Array", "jax.Array"]:
    """Exchange rows so device ``d`` ends up holding exactly the rows with
    ``bucket % n_devices == d``.

    Args:
      mesh: 1-D device mesh; inputs must be sharded along its axis.
      arrays: name -> (n,) numeric arrays (row-aligned).
      bucket_ids: (n,) int32 bucket of each row.
      capacity: per-source-per-destination row slots (static).

    Returns:
      (out_arrays, out_buckets, valid_mask, overflow): each output has shape
      (n_devices * capacity,) per device shard — n_dev*n_dev*capacity global —
      with ``valid_mask`` marking real rows. ``overflow`` is the per-device
      count of rows dropped because a destination slot overflowed (callers
      must check it is all zero and retry with larger capacity otherwise).
    """
    ensure_x64()
    axis = mesh.axis_names[0]
    n_dev = mesh.shape[axis]
    names = list(arrays)
    values = [arrays[n] for n in names]

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(axis),) * (len(values) + 1),
        out_specs=(P(axis),) * (len(values) + 3),
    )
    def exchange(*args):
        *vals, buckets = args
        dest = (buckets % n_dev).astype(jnp.int32)
        # stage the bucket-id array together with the data columns: one
        # argsort/bincount/scatter pass serves all of them
        staged, mask, counts = _stage_for_exchange([*vals, buckets], dest, n_dev, capacity)
        sent = jnp.minimum(counts, capacity)
        overflow = jnp.sum(counts - sent)

        out, out_mask = _exchange_packed(staged, mask, axis)
        return (*out, out_mask, overflow[None])

    results = exchange(*values, bucket_ids)
    out_arrays = dict(zip(names, results[: len(names)]))
    out_buckets, valid, overflow = results[len(names)], results[len(names) + 1], results[len(names) + 2]
    return out_arrays, out_buckets, valid, overflow


def rebucket_and_sort(
    mesh: Mesh,
    arrays: Dict[str, "jax.Array"],
    hash_inputs: List["jax.Array"],
    sort_keys: List["jax.Array"],
    num_buckets: int,
    capacity: int,
):
    """Full distributed index-build step: hash -> all_to_all -> per-device
    stable sort by (bucket, sort keys). Invalid (padding) rows sort to the end.

    This is the device program the driver's ``dryrun_multichip`` compiles: the
    entire reference hot path (ref: SURVEY.md §3.1 boxed region) as one XLA
    computation over the mesh.
    """
    ensure_x64()
    from hyperspace_tpu.ops.hashing import bucket_ids_jnp
    from hyperspace_tpu.ops.sort import lex_argsort

    axis = mesh.axis_names[0]

    @partial(shard_map, mesh=mesh, in_specs=(P(axis),) * len(hash_inputs), out_specs=P(axis))
    def assign(*hi):
        return bucket_ids_jnp(list(hi), num_buckets)

    buckets = assign(*hash_inputs)
    n_keys = len(sort_keys)
    key_names = [f"__sk{i}" for i in range(n_keys)]
    all_arrays = {**arrays, **dict(zip(key_names, sort_keys))}
    out, out_buckets, valid, overflow = rebucket(mesh, all_arrays, buckets, capacity)

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(axis),) * (n_keys + 2 + len(arrays)),
        out_specs=(P(axis),) * (2 + len(arrays)),
    )
    def local_sort(buckets_, valid_, *cols):
        sort_cols = cols[:n_keys]
        data_cols = cols[n_keys:]
        # invalid rows last: sort primarily by ~valid, then bucket, then keys
        order = lex_argsort([(~valid_).astype(jnp.int32), buckets_] + list(sort_cols))
        return (buckets_[order], valid_[order], *[c[order] for c in data_cols])

    sorted_res = local_sort(out_buckets, valid, *[out[k] for k in key_names], *[out[k] for k in arrays])
    sorted_buckets, sorted_valid = sorted_res[0], sorted_res[1]
    sorted_arrays = dict(zip(list(arrays), sorted_res[2:]))
    return sorted_arrays, sorted_buckets, sorted_valid, overflow


def _next_pow2(x: int) -> int:
    return max(8, 1 << (max(int(x) - 1, 1)).bit_length())


from functools import lru_cache  # noqa: E402

from hyperspace_tpu.check import hlo_lint as _hlo_lint  # noqa: E402

# Declared HLO contracts for the build/exchange programs (SURVEY.md §2.9:
# build = exactly ONE all-to-all; hierarchical re-bucketing = one per phase).
# The single-phase contracts also apply to the plane-packed `rebucket`
# program — tests jit-wrap it and assert against "index-rebucket".
_hlo_lint.register_contract(
    "index-build-exchange",
    collectives={"all-to-all": (1, 1)},
    description="distributed index build: rows cross devices in exactly one plane-packed all-to-all",
)
_hlo_lint.register_contract(
    "index-rebucket",
    collectives={"all-to-all": (1, 1)},
    description="incremental re-bucketing: one plane-packed all-to-all",
)
_hlo_lint.register_contract(
    "hierarchical-exchange",
    collectives={"all-to-all": (2, 2)},
    description="2-D (dcn, ici) re-bucketing: one all-to-all per phase, rows cross DCN once",
)


@lru_cache(maxsize=64)
def _build_exchange_program(mesh: Mesh, kinds: Tuple[str, ...], num_buckets: int, capacity: int):
    """Jitted distributed index-build step for one (mesh, key kinds,
    num_buckets, capacity) class:

      per-device hash (device-reconstructed for numeric kinds, host plane for
      strings; bit-exact vs the single-device program ops/sort._build_sorted)
      -> bucket ids -> ONE all_to_all routing each row to its owner device
      (bucket % n_devices) -> per-device sort by (valid desc, bucket, keys...,
      global row index).

    Carrying the global row index instead of payload columns keeps the
    exchange narrow: the host gathers arbitrary-typed payload rows by index
    afterwards, exactly like the single-device build's permutation fetch.
    Replaces the reference's cluster-wide ``repartition(numBuckets, cols)``
    (ref: HS/index/covering/CoveringIndex.scala:54-69).
    """
    import jax.numpy as jnp
    from jax import lax

    from hyperspace_tpu.ops.hashing import bucket_ids_jnp
    from hyperspace_tpu.ops.sort import _device_hash32, lex_argsort

    axis = mesh.axis_names[0]
    n_dev = mesh.shape[axis]
    n_keys = len(kinds)
    n_str = sum(1 for k in kinds if k == "s")

    def run(keys, host_hashes, row_idx, n_valid):
        valid = row_idx < n_valid

        @partial(
            shard_map,
            mesh=mesh,
            in_specs=(P(axis),) * (n_keys + n_str + 2),
            out_specs=(P(axis), P(axis), P(axis), P(axis)),
        )
        def exchange(*args):
            ks = args[:n_keys]
            hh = args[n_keys : n_keys + n_str]
            ridx, vld = args[-2], args[-1]
            hash_cols = []
            hidx = 0
            for kind, key in zip(kinds, ks):
                if kind == "s":
                    hash_cols.append(hh[hidx])
                    hidx += 1
                else:
                    hash_cols.append(_device_hash32(kind, key))
            buckets = bucket_ids_jnp(hash_cols, num_buckets).astype(jnp.int32)
            dest = (buckets % n_dev).astype(jnp.int32)
            staged, mask, counts = _stage_for_exchange(
                [*ks, ridx, buckets], dest, n_dev, capacity, valid=vld
            )
            sent = jnp.minimum(counts, capacity)
            overflow = jnp.sum(counts - sent)
            outs, out_mask = _exchange_packed(staged, mask, axis)
            *out_keys, out_ridx, out_buckets = outs
            order = lex_argsort(
                [(~out_mask).astype(jnp.int32), out_buckets, *out_keys, out_ridx]
            )
            return (
                out_buckets[order],
                out_ridx[order],
                out_mask[order],
                overflow[None],
            )

        return exchange(*keys, *host_hashes, row_idx, valid)

    return jax.jit(run)


def distributed_bucket_sort_build(
    mesh: Mesh,
    keys: List["jax.Array"],
    host_hashes: List["jax.Array"],
    kinds: Tuple[str, ...],
    row_idx: "jax.Array",
    n_valid: int,
    num_buckets: int,
    capacity: int,
):
    """Run the distributed build step; see ``_build_exchange_program``.

    Inputs must be row-sharded over ``mesh`` and padded to a common length
    divisible by the device count; ``row_idx`` is the global row iota with
    padding rows >= ``n_valid`` (traced, so padding never recompiles).

    Returns device arrays ``(sorted_buckets, sorted_row_idx, valid, overflow)``
    each of per-device length ``n_devices * capacity``. Callers MUST check
    ``overflow.sum() == 0`` and retry with doubled capacity otherwise (the
    skew strategy — SURVEY.md §7 "hard parts").
    """
    ensure_x64()
    import numpy as np

    fn = _build_exchange_program(mesh, tuple(kinds), int(num_buckets), int(capacity))
    # no session conf reaches this layer: maybe_verify(None, ...) consults
    # the process-global default the most recent Session wired
    _hlo_lint.maybe_verify(
        None, "index-build-exchange",
        f"build-exchange[{num_buckets}/{capacity}]@{len(mesh.devices.flat)}",
        fn, (tuple(keys), tuple(host_hashes), row_idx, np.int64(n_valid)),
    )
    return fn(tuple(keys), tuple(host_hashes), row_idx, np.int64(n_valid))


def rebucket_hierarchical(
    mesh: Mesh,
    arrays: Dict[str, "jax.Array"],
    bucket_ids: "jax.Array",
    capacity_ici: int,
    capacity_dcn: int,
) -> Tuple[Dict[str, "jax.Array"], "jax.Array", "jax.Array", "jax.Array"]:
    """Two-phase re-bucketing over a 2-D (dcn, ici) mesh: rows first hop to
    their owner's *local position* within their own slice (all_to_all over
    ICI), then one hop across slices (all_to_all over DCN) — so each row
    crosses the slow inter-slice link exactly once and all position routing
    rides ICI (SURVEY.md §5.8 "cross-slice (DCN) handled by hierarchical
    all-to-all").

    Owner of bucket b on an (S, L) mesh: global device g = b % (S*L),
    slice s = g // L, local position l = g % L.

    Returns (out_arrays, out_buckets, valid_mask, overflow) per global device
    shard, like ``rebucket``; ``overflow`` sums drops from both phases.
    """
    ensure_x64()
    dcn_axis, ici_axis = mesh.axis_names
    S = mesh.shape[dcn_axis]
    L = mesh.shape[ici_axis]
    n_dev = S * L
    names = list(arrays)
    values = [arrays[n] for n in names]
    both = (dcn_axis, ici_axis)

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(both),) * (len(values) + 1),
        out_specs=(P(both),) * (len(values) + 3),
    )
    def exchange(*args):
        *vals, buckets = args
        owner = (buckets % n_dev).astype(jnp.int32)

        # -- phase 1 (ICI): route to the owner's local position in this slice
        dest_local = owner % L
        staged, mask, counts = _stage_for_exchange([*vals, buckets], dest_local, L, capacity_ici)
        sent = jnp.minimum(counts, capacity_ici)
        overflow = jnp.sum(counts - sent)
        mid, mid_mask = _exchange_packed(staged, mask, ici_axis)

        # -- phase 2 (DCN): route to the owner slice; local position is kept
        *mid_vals, mid_buckets = mid
        dest_slice = ((mid_buckets % n_dev) // L).astype(jnp.int32)
        staged2, mask2, counts2 = _stage_for_exchange(
            [*mid_vals, mid_buckets], dest_slice, S, capacity_dcn, valid=mid_mask
        )
        sent2 = jnp.minimum(counts2, capacity_dcn)
        overflow = overflow + jnp.sum(counts2 - sent2)
        out, out_mask = _exchange_packed(staged2, mask2, dcn_axis)
        *out_vals, out_buckets = out
        return (*out_vals, out_buckets, out_mask, overflow[None])

    results = exchange(*values, bucket_ids)
    out_arrays = dict(zip(names, results[: len(names)]))
    out_buckets, valid, overflow = results[len(names)], results[len(names) + 1], results[len(names) + 2]
    return out_arrays, out_buckets, valid, overflow
