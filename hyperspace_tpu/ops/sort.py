"""On-device bucketed-sort primitives — the index-build hot path.

Replaces the shuffle + per-partition sort of Spark's bucketed write
(``repartition(numBuckets, cols).sortWithinPartitions``;
ref: HS/index/covering/CoveringIndex.scala:54-69,
HS/index/DataFrameWriterExtensions.scala:50-68) with ONE fused XLA program:

  device hash -> bucket ids -> single multi-operand ``lax.sort``
  (bucket, key..., iota) -> permutation + per-bucket counts
  (counts via the Pallas histogram kernel, ops/kernels.bucket_histogram)

Design notes:
  - every operand is a *key* of the one ``lax.sort`` (iota last), so the
    order is total and no stable-sort or argsort-chaining passes are needed;
  - hash inputs for numeric/date columns are reconstructed ON DEVICE from the
    order-preserving sort keys (bit-exact vs the host ``numeric_hash32``), so
    only the key planes ride host->device; strings ship a host hash plane;
  - callers pad rows to a power of two and pass the true row count as a
    *traced* scalar — one compile serves every build of the same size class;
  - the permutation comes back as int32 and can be fetched asynchronously
    (``copy_to_host_async``) while the host prepares the gather.

int64 keys require x64; enabled lazily at first use via utils.x64.ensure_x64
so importing the library never mutates global JAX state (see
docs/configuration.md).
"""

from __future__ import annotations

from functools import partial
from typing import Sequence, Tuple

import jax
import jax.numpy as jnp  # noqa: E402

from hyperspace_tpu.utils.x64 import ensure_x64
import numpy as np  # noqa: E402
from jax import lax  # noqa: E402

_I64_SIGN = -0x8000000000000000


def lex_argsort(keys) -> "jnp.ndarray":
    """Stable argsort by ``keys[0]`` then ``keys[1]`` ... (most-significant
    first), as one multi-operand XLA sort with a trailing iota tiebreak."""
    ensure_x64()
    keys = list(keys)
    n = keys[0].shape[0]
    idx = lax.iota(jnp.int32, n)
    return lax.sort((*keys, idx), num_keys=len(keys) + 1, is_stable=False)[-1]


@partial(jax.jit, static_argnames=("num_buckets",))
def bucket_sort_perm(hash_inputs, sort_keys, num_buckets: int):
    """Assign buckets and produce the permutation that clusters rows by bucket
    and sorts by the indexed columns within each bucket.

    Args:
      hash_inputs: (k, n) uint32 per-column hash inputs of the bucket keys.
      sort_keys:   (k, n) order-preserving keys of the sort columns.
      num_buckets: static bucket count.

    Returns:
      (perm, sorted_buckets): ``perm`` (n,) row permutation; ``sorted_buckets``
      (n,) the bucket id of each permuted row (non-decreasing).
    """
    ensure_x64()
    from hyperspace_tpu.ops.hashing import bucket_ids_jnp

    buckets = bucket_ids_jnp(list(hash_inputs), num_buckets)
    n = buckets.shape[0]
    idx = lax.iota(jnp.int32, n)
    out = lax.sort(
        (buckets, *list(sort_keys), idx),
        num_keys=2 + len(list(sort_keys)),
        is_stable=False,
    )
    return out[-1], out[0]


def _device_hash32(kind: str, key):
    """Reconstruct the column's uint32 hash input from its order key —
    bit-exact vs the host ``hashing.numeric_hash32`` on the original values,
    INCLUDING its int/float value normalization (an integral float hashes
    as its int64 value; -0.0 as +0.0; NaN canonically): a nullable int64
    column decodes as float64, and the un-normalized bit-pattern hash once
    bucketed it apart from the int64 side of the same join."""
    v64 = key.astype(jnp.int64)
    if kind == "f":
        # invert the order-preserving transform back to the raw f64 bits
        raw = jnp.where(v64 < 0, v64 ^ jnp.int64(_I64_SIGN), ~v64)
        f = lax.bitcast_convert_type(raw, jnp.float64) + 0.0  # -0.0 -> +0.0
        isint = jnp.isfinite(f) & (jnp.abs(f) < 2.0**63) & (f == jnp.floor(f))
        int_bits = jnp.where(isint, f, 0).astype(jnp.int64)
        f_norm = jnp.where(jnp.isnan(f), jnp.float64(jnp.nan), f)
        bits_i = jnp.where(isint, int_bits, lax.bitcast_convert_type(f_norm, jnp.int64))
    else:  # i / u / b / M — the key IS the value (or its int64 view)
        bits_i = v64
    bits = lax.bitcast_convert_type(bits_i, jnp.uint64)
    return ((bits ^ (bits >> jnp.uint64(32))) & jnp.uint64(0xFFFFFFFF)).astype(jnp.uint32)


@partial(jax.jit, static_argnames=("num_buckets", "kinds", "interpret"))
def _build_sorted(keys, host_hashes, n_valid, num_buckets: int, kinds, interpret: bool):
    from hyperspace_tpu.ops.hashing import bucket_ids_jnp
    from hyperspace_tpu.ops.kernels import _hist_call

    hash_cols = []
    hidx = 0
    for kind, key in zip(kinds, keys):
        if kind == "s":
            hash_cols.append(host_hashes[hidx])
            hidx += 1
        else:
            hash_cols.append(_device_hash32(kind, key))
    buckets = bucket_ids_jnp(hash_cols, num_buckets)

    n = buckets.shape[0]
    idx = lax.iota(jnp.int32, n)
    # padding rows get the sentinel bucket ``num_buckets`` so they cluster
    # after every real bucket and fall outside the returned counts
    buckets = jnp.where(idx < n_valid, buckets, jnp.int32(num_buckets))
    out = lax.sort((buckets, *keys, idx), num_keys=2 + len(keys), is_stable=False)
    sorted_buckets, perm = out[0], out[-1]

    nb_p = -(-(num_buckets + 1) // 128) * 128
    counts = _hist_call(sorted_buckets[None, :], nb_p, interpret)[:, 0]
    return perm, counts[:num_buckets]


def bucket_sort_build(
    keys: Sequence,
    host_hashes: Sequence,
    kinds: Tuple[str, ...],
    num_buckets: int,
    n_valid: int,
):
    """The full device program of an index build over padded inputs.

    Args:
      keys: per-key-column 1-D device arrays (int32 or int64 order keys),
        all the same power-of-two length, padded past ``n_valid``.
      host_hashes: uint32 hash planes for the ``kinds == 's'`` columns, in
        order of appearance.
      kinds: per-column dtype kind characters (``i u b M f s``), static.
      num_buckets: static bucket count.
      n_valid: true row count (traced — padding amount never recompiles).

    Returns:
      (perm, counts) device arrays: int32 permutation of all padded rows
      (valid rows occupy positions [0, n_valid)) and int32 rows-per-bucket.
    """
    ensure_x64()
    interpret = jax.default_backend() != "tpu"
    return _build_sorted(
        tuple(keys), tuple(host_hashes), np.int32(n_valid), num_buckets, tuple(kinds), interpret
    )


def warm_build(n: int, kinds: Tuple[str, ...], key_dtypes: Sequence, num_buckets: int) -> None:
    """Pre-compile the build program for a given padded size class so the
    first real build at that size is a cache hit (first XLA compile of the
    sort is tens of seconds; see bench.py methodology)."""
    ensure_x64()
    keys = tuple(jnp.zeros(n, dtype=dt) for dt in key_dtypes)
    hh = tuple(jnp.zeros(n, dtype=jnp.uint32) for k in kinds if k == "s")
    perm, counts = bucket_sort_build(keys, hh, kinds, num_buckets, n)
    jax.block_until_ready((perm, counts))


def padded_size(n: int) -> int:
    """Power-of-two size class for ``n`` rows (min 8)."""
    return max(8, 1 << (max(n - 1, 1)).bit_length())


# --------------------------------------------------------------------------
# streaming device top-k (ORDER BY ... LIMIT k without materialization)
#
# Both programs operate on a (num_keys + 1, P) int64 "plane matrix": one
# signed-order NULLS-LAST plane per ORDER BY key (ops/encode.order_plane)
# plus a trailing global-row-id plane that makes the sort total — equal keys
# resolve by ascending row id, which IS the host stable-sort tie order.
# Padding rows carry ORDER_PLANE_SENTINEL in every plane (including the row
# id), so they cluster after all real rows and the host trims them by
# ``rid < sentinel``. No traced scalars: one compile per (key count,
# capacity, shape-bucket) triple, shared across every chunk of a stream.
# --------------------------------------------------------------------------

_TOPK_SENTINEL = np.int64(np.iinfo(np.int64).max)


def _take_cap(col, cap: int, sentinel):
    """First ``cap`` entries, sentinel-extended when the input is shorter
    (static shapes: the pad amount is a trace-time constant)."""
    p = col.shape[0]
    if p >= cap:
        return col[:cap]
    return jnp.concatenate([col, jnp.full(cap - p, sentinel, dtype=col.dtype)])


def topk_chunk_fn(num_keys: int, cap: int):
    """Builder for the per-chunk select-top-k program: one multi-operand
    ``lax.sort`` over the plane matrix, then the first ``cap`` rows of every
    plane. Returns a (num_keys + 1, cap) candidate matrix."""

    def run(planes):
        ensure_x64()
        ops = tuple(planes[i] for i in range(num_keys + 1))
        out = lax.sort(ops, num_keys=num_keys + 1, is_stable=False)
        return jnp.stack([_take_cap(o, cap, _TOPK_SENTINEL) for o in out])

    return run


def topk_merge_fn(num_keys: int, cap: int):
    """Builder for the pairwise candidate merge: concatenate two capacity-
    sized candidate matrices, sort, keep the first ``cap`` — the device-
    resident fold step of TopKStream (GroupedAggStream._merge analog)."""

    def run(a, b):
        ensure_x64()
        ops = tuple(
            jnp.concatenate([a[i], b[i]]) for i in range(num_keys + 1)
        )
        out = lax.sort(ops, num_keys=num_keys + 1, is_stable=False)
        return jnp.stack([o[:cap] for o in out])

    return run


def fused_topk_fn(num_keys: int, cap: int):
    """Whole-stage fold (``hyperspace.exec.fusion.enabled``): chunk select
    AND the merge with the running candidate state as ONE program, so a
    streamed chunk costs a single dispatch and the ``(num_keys + 1, cap)``
    state matrix can be donated for in-place buffer reuse.

    Returns ``(merged, cand)`` — the updated state plus the chunk's own
    candidate matrix (whose row-id plane tells the host which chunk rows to
    pool). Identical math to ``topk_chunk_fn`` then ``topk_merge_fn``, so
    results stay bit-identical to the per-family pair."""

    def run(state, planes):
        ensure_x64()
        ops = tuple(planes[i] for i in range(num_keys + 1))
        out = lax.sort(ops, num_keys=num_keys + 1, is_stable=False)
        cand = jnp.stack([_take_cap(o, cap, _TOPK_SENTINEL) for o in out])
        both = tuple(
            jnp.concatenate([state[i], cand[i]]) for i in range(num_keys + 1)
        )
        merged = lax.sort(both, num_keys=num_keys + 1, is_stable=False)
        return jnp.stack([o[:cap] for o in merged]), cand

    return run


# --- declared HLO contracts (hyperspace_tpu/check/hlo_lint.py), stated next
# to the program builders like exec/device.py's families ---------------------
from hyperspace_tpu.check import hlo_lint as _hlo_lint

_hlo_lint.register_contract(
    "topk-chunk",
    collectives={"all-gather": (0, None)},
    description=(
        "chunk select-top-k: one multi-operand sort over key planes; the "
        "GSPMD partitioner may gather fixed-size planes, never payload rows"
    ),
)
_hlo_lint.register_contract(
    "topk-merge",
    collectives={},
    description="pairwise top-k candidate merge: 2*cap fixed-size inputs, device-local, collective-free",
)
_hlo_lint.register_contract(
    "sharded-topk",
    collectives={"all-gather": (1, 1)},
    description=(
        "shard_map top-k chunk: per-shard select + EXACTLY one fixed-size "
        "all-gather of candidate planes (never rows), replicated final merge"
    ),
)
_hlo_lint.register_contract(
    "fused-stage-topk",
    collectives={"all-gather": (0, None)},
    description=(
        "whole-stage chunk select + state merge with donated candidate "
        "buffer: one executable per chunk"
    ),
    single_fusion=True,
)
_hlo_lint.register_contract(
    "fused-stage-topk-sharded",
    collectives={"all-gather": (1, 1)},
    description=(
        "shard_map whole-stage top-k fold: per-shard select, one fixed-size "
        "candidate all-gather, replicated merge with the running state"
    ),
    single_fusion=True,
)
