"""On-device sort primitives.

Replaces the per-partition sort of Spark's bucketed write
(``sortWithinPartitions``; ref: HS/index/DataFrameWriterExtensions.scala:50-68).
Lexicographic multi-key ordering is built from successive stable argsorts —
each pass is one XLA sort, fused and tiled by the compiler.

int64 keys require x64; enabled process-wide on import of this module (the
framework owns the process' JAX config the way Spark owns its executors).
"""

from __future__ import annotations

import jax

jax.config.update("jax_enable_x64", True)

import jax.numpy as jnp  # noqa: E402
from functools import partial  # noqa: E402


def lex_argsort(keys) -> "jnp.ndarray":
    """Stable argsort by ``keys[0]`` then ``keys[1]`` ... (most-significant
    first). ``keys`` is a (k, n) array or list of (n,) arrays."""
    keys = list(keys)
    order = jnp.argsort(keys[-1], stable=True)
    for key in reversed(keys[:-1]):
        order = order[jnp.argsort(key[order], stable=True)]
    return order


@partial(jax.jit, static_argnames=("num_buckets",))
def bucket_sort_perm(hash_inputs, sort_keys, num_buckets: int):
    """The index-build kernel: assign buckets, then produce the permutation
    that clusters rows by bucket and sorts by the indexed columns within each
    bucket — the device replacement for Spark's
    ``repartition(numBuckets, cols).sortWithinPartitions(cols)``
    (ref: HS/index/covering/CoveringIndex.scala:54-69).

    Args:
      hash_inputs: (k, n) uint32 per-column hash inputs of the bucket keys.
      sort_keys:   (k, n) int64 order-preserving keys of the sort columns.
      num_buckets: static bucket count.

    Returns:
      (perm, sorted_buckets): ``perm`` (n,) row permutation; ``sorted_buckets``
      (n,) the bucket id of each permuted row (non-decreasing).
    """
    from hyperspace_tpu.ops.hashing import bucket_ids_jnp

    buckets = bucket_ids_jnp(list(hash_inputs), num_buckets)
    perm = lex_argsort([buckets] + list(sort_keys))
    return perm, buckets[perm]

