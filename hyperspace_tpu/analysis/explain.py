"""explain API: run the optimizer with and without Hyperspace, show both
plans, highlight the differing subtrees, and list the indexes used
(ref: HS/index/plananalysis/PlanAnalyzer.scala:36-411).

Three display modes, as in the reference (ref: plananalysis/DisplayMode.scala:61-89):
``plaintext`` (markers stripped), ``console`` (differing subtrees suffixed with
``<----``), and ``html`` (``<b>`` highlights, ``<br/>`` newlines).
"""

from __future__ import annotations

from collections import Counter
from typing import Dict, List, Tuple

from hyperspace_tpu.plan import logical as L


class DisplayMode:
    """(ref: plananalysis/DisplayMode.scala)"""

    name = "plaintext"
    highlight_begin = ""
    highlight_end = ""
    newline = "\n"

    def wrap(self, text: str) -> str:
        return text


class PlainTextMode(DisplayMode):
    pass


class ConsoleMode(DisplayMode):
    name = "console"
    highlight_end = " <----"


class HTMLMode(DisplayMode):
    name = "html"
    highlight_begin = "<b>"
    highlight_end = "</b>"
    newline = "<br/>"

    def wrap(self, text: str) -> str:
        return "<pre>" + text + "</pre>"


_MODES = {
    "plaintext": PlainTextMode,
    "console": ConsoleMode,
    "html": HTMLMode,
}


def _subtree_strings(plan: L.LogicalPlan) -> set:
    return {p.pretty() for p in L.collect(plan, lambda p: True)}


def _pretty_highlighted(plan: L.LogicalPlan, other_subtrees: set, mode: DisplayMode) -> str:
    """Pretty-print ``plan``, highlighting each node whose subtree does not
    appear in the other plan — i.e. the differing region, while identical
    sub-plans (e.g. the untouched side of a join) stay unmarked
    (ref: PlanAnalyzer highlight of differing sub-plans)."""
    lines: List[str] = []

    def walk(p: L.LogicalPlan, indent: int) -> None:
        differs = p.pretty() not in other_subtrees
        line = "  " * indent + p.describe()
        if differs:
            line = mode.highlight_begin + line + mode.highlight_end
        lines.append(line)
        for c in p.children():
            walk(c, indent + 1)

    walk(plan, 0)
    return "\n".join(lines)


def _operator_counts(plan: L.LogicalPlan) -> Counter:
    from hyperspace_tpu.rules.apply import plans_including_subqueries

    return Counter(
        type(p).__name__
        for sub in plans_including_subqueries(plan)
        for p in L.collect(sub, lambda x: True)
    )


def physical_operator_stats(plan_with: L.LogicalPlan, plan_without: L.LogicalPlan) -> List[Tuple[str, int, int]]:
    """Per-operator (name, count with indexes, count without) rows for every
    operator whose count differs, plus all shared ones
    (ref: plananalysis/PhysicalOperatorAnalyzer.scala:30)."""
    cw = _operator_counts(plan_with)
    co = _operator_counts(plan_without)
    names = sorted(set(cw) | set(co))
    return [(n, cw.get(n, 0), co.get(n, 0)) for n in names]


def _used_indexes(plan: L.LogicalPlan) -> List[str]:
    from hyperspace_tpu.rules.apply import used_index_names

    return used_index_names(plan)


def _bucket_summary(plan: L.LogicalPlan) -> List[str]:
    from hyperspace_tpu.rules.apply import plans_including_subqueries

    out = []
    for p in plans_including_subqueries(plan):
        for node in L.collect(p, lambda x: isinstance(x, (L.IndexScan, L.BucketUnion))):
            out.append(node.describe())
    return out


def explain_string(df, session, verbose: bool = False, mode: str = "plaintext") -> str:
    """(ref: PlanAnalyzer.explainString :47-115 — builds the plan twice, runs
    the optimizer only (no execution), and diffs the trees)."""
    from hyperspace_tpu.rules.apply import ApplyHyperspace

    if mode not in _MODES:
        raise ValueError(f"Unsupported display mode {mode!r}; expected one of {sorted(_MODES)}")
    dm = _MODES[mode]()
    plan_without = df.plan
    plan_with = ApplyHyperspace(session).apply(plan_without)

    with_sub = _subtree_strings(plan_with)
    without_sub = _subtree_strings(plan_without)

    used = _used_indexes(plan_with)
    buf = []
    buf.append("=" * 64)
    buf.append("Plan with indexes:")
    buf.append(_pretty_highlighted(plan_with, without_sub, dm))
    buf.append("")
    buf.append("Plan without indexes:")
    buf.append(_pretty_highlighted(plan_without, with_sub, dm))
    buf.append("")
    buf.append("Indexes used:")
    if used:
        manager = session.index_manager
        for name in used:
            entry = manager.get_index(name)
            location = entry.content.files[0].rsplit("/", 2)[0] if entry and entry.content.files else ""
            buf.append(f"  {name}: {location}")
    else:
        buf.append("  (none)")
    if verbose:
        buf.append("")
        buf.append("Physical operator stats:")
        rows = physical_operator_stats(plan_with, plan_without)
        name_w = max([len("Physical Operator")] + [len(r[0]) for r in rows])
        buf.append(f"  {'Physical Operator':<{name_w}} | Hyperspace Disabled | Hyperspace Enabled | Difference")
        for n, w, o in rows:
            buf.append(f"  {n:<{name_w}} | {o:>19} | {w:>18} | {w - o:>10}")
        buf.append("")
        buf.append("Index-side operators:")
        for line in _bucket_summary(plan_with) or ["  (none)"]:
            buf.append(f"  {line}")
    buf.append("=" * 64)
    text = "\n".join(buf)
    if dm.newline != "\n":
        text = text.replace("\n", dm.newline)
    return dm.wrap(text)
