"""explain API: run the optimizer with and without Hyperspace, show both
plans, highlight the differing subtrees, and list the indexes used
(ref: HS/index/plananalysis/PlanAnalyzer.scala:36-411).
"""

from __future__ import annotations

from typing import List

from hyperspace_tpu.plan import logical as L


def _used_indexes(plan: L.LogicalPlan) -> List[str]:
    used = {s.entry.name for s in L.collect(plan, lambda p: isinstance(p, L.IndexScan))}
    used |= {
        s.via_index
        for s in L.collect(plan, lambda p: isinstance(p, L.FileScan))
        if s.via_index
    }
    return sorted(used)


def _bucket_summary(plan: L.LogicalPlan) -> List[str]:
    out = []
    for node in L.collect(plan, lambda p: isinstance(p, (L.IndexScan, L.BucketUnion))):
        out.append(node.describe())
    return out


def explain_string(df, session, verbose: bool = False) -> str:
    """(ref: PlanAnalyzer.explainString :47-115 — builds the plan twice, runs
    the optimizer only (no execution), and diffs the trees)."""
    from hyperspace_tpu.rules.apply import ApplyHyperspace

    plan_without = df.plan
    plan_with = ApplyHyperspace(session).apply(plan_without)

    used = _used_indexes(plan_with)
    buf = []
    buf.append("=" * 64)
    buf.append("Plan with indexes:")
    buf.append(plan_with.pretty())
    buf.append("")
    buf.append("Plan without indexes:")
    buf.append(plan_without.pretty())
    buf.append("")
    buf.append("Indexes used:")
    if used:
        manager = session.index_manager
        for name in used:
            entry = manager.get_index(name)
            location = entry.content.files[0].rsplit("/", 2)[0] if entry and entry.content.files else ""
            buf.append(f"  {name}: {location}")
    else:
        buf.append("  (none)")
    if verbose:
        buf.append("")
        buf.append("Physical operator stats (index-side operators):")
        for line in _bucket_summary(plan_with) or ["  (none)"]:
            buf.append(f"  {line}")
    buf.append("=" * 64)
    return "\n".join(buf)
