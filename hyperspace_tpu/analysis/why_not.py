"""whyNot API: report, per candidate index, why the optimizer did not apply
it to the given query
(ref: HS/index/plananalysis/CandidateIndexAnalyzer.scala:29-346).

Mechanism mirrors the reference: enable analysis mode, re-run the collector +
optimizer so the filter chain tags each entry with ``FilterReason``s, then
collect the tags into a table.
"""

from __future__ import annotations

from typing import List, Optional

from hyperspace_tpu.analysis import reasons as R
from hyperspace_tpu.models import states
from hyperspace_tpu.plan import logical as L


def why_not_string(df, session, index_name: Optional[str] = None, extended: bool = False) -> str:
    from hyperspace_tpu.rules.apply import ApplyHyperspace

    applier = ApplyHyperspace(session, analysis_enabled=True)
    indexes = session.index_manager.get_indexes([states.ACTIVE])
    if index_name is not None:
        missing = index_name not in {e.name for e in indexes}
        if missing:
            return f"Index {index_name!r} does not exist or is not ACTIVE."
    plan = df.plan
    new_plan = applier.apply(plan)
    applied = {s.entry.name for s in L.collect(new_plan, lambda p: isinstance(p, L.IndexScan))}

    scans = L.collect(plan, lambda p: isinstance(p, L.Scan))
    buf: List[str] = []
    buf.append("=" * 64)
    buf.append("whyNot report")
    buf.append(f"Applied indexes: {sorted(applied) or '(none)'}")
    buf.append("")
    header = f"{'Index':<24} {'Subplan':<28} Reason"
    buf.append(header)
    buf.append("-" * len(header))
    for entry in indexes:
        if index_name is not None and entry.name != index_name:
            continue
        if entry.name in applied:
            buf.append(f"{entry.name:<24} {'-':<28} (applied)")
            continue
        any_reason = False
        for scan in scans:
            tagged = entry.get_tag(L.plan_key(scan), R.FILTER_REASONS) or []
            for reason in tagged:
                any_reason = True
                text = str(reason) if extended else f"[{reason.code}] {reason.arg_str}"
                buf.append(f"{entry.name:<24} {scan.describe()[:28]:<28} {text}")
        if not any_reason:
            buf.append(f"{entry.name:<24} {'-':<28} [NO_CANDIDATE] not a candidate for any sub-plan")
    buf.append("=" * 64)
    return "\n".join(buf)
