"""whyNot API: report, per candidate index, why the optimizer did not apply
it to the given query
(ref: HS/index/plananalysis/CandidateIndexAnalyzer.scala:29-346).

Mechanism mirrors the reference: enable analysis mode, re-run the collector +
optimizer so the filter chain tags each entry with ``FilterReason``s, then
collect the tags into a table.
"""

from __future__ import annotations

from typing import List, Optional

from hyperspace_tpu.analysis import reasons as R
from hyperspace_tpu.models import states
from hyperspace_tpu.plan import logical as L


def why_not_string(df, session, index_name: Optional[str] = None, extended: bool = False) -> str:
    from hyperspace_tpu.rules.apply import ApplyHyperspace

    applier = ApplyHyperspace(session, analysis_enabled=True)
    indexes = session.index_manager.get_indexes([states.ACTIVE])
    if index_name is not None:
        missing = index_name not in {e.name for e in indexes}
        if missing:
            return f"Index {index_name!r} does not exist or is not ACTIVE."
    from hyperspace_tpu.rules.apply import plans_including_subqueries, used_index_names

    plan = df.plan
    new_plan = applier.apply(plan)
    applied = set(used_index_names(new_plan))
    scans = []
    for p in plans_including_subqueries(plan):
        scans.extend(L.collect(p, lambda x: isinstance(x, L.Scan)))
    # unique scans by plan key; disambiguate label collisions across distinct
    # scans (two datasets can share a directory basename)
    by_key = {}
    for s in scans:
        by_key.setdefault(L.plan_key(s), s)
    scans = list(by_key.values())
    labels = {}
    used_labels: dict = {}
    for s in scans:
        base = _subplan_label(s)
        ordinal = used_labels.get(base, 0)
        used_labels[base] = ordinal + 1
        labels[L.plan_key(s)] = base if ordinal == 0 else f"{base[:24]}#{ordinal + 1}"
    buf: List[str] = []
    buf.append("=" * 64)
    buf.append("whyNot report")
    buf.append(f"Applied indexes: {sorted(applied) or '(none)'}")
    buf.append("")
    header = f"{'Index':<24} {'Subplan':<28} Reason"
    buf.append(header)
    buf.append("-" * len(header))
    for entry in indexes:
        if index_name is not None and entry.name != index_name:
            continue
        if entry.name in applied:
            buf.append(f"{entry.name:<24} {'-':<28} (applied)")
            continue
        seen = set()
        for scan in scans:
            label = labels[L.plan_key(scan)]
            tagged = entry.get_tag(L.plan_key(scan), R.FILTER_REASONS) or []
            for reason in tagged:
                text = str(reason) if extended else f"[{reason.code}] {reason.arg_str}"
                row = (label, text)
                if row in seen:
                    continue
                seen.add(row)
                buf.append(f"{entry.name:<24} {label:<28} {text}")
        if not seen:
            buf.append(f"{entry.name:<24} {'-':<28} [NO_CANDIDATE] not a candidate for any sub-plan")
    buf.append("=" * 64)
    return "\n".join(buf)


def _subplan_label(scan: L.Scan) -> str:
    """Short, machine-stable label for a source sub-plan: the dataset's last
    path component (absolute temp paths would make golden files unstable)."""
    import os

    paths = getattr(scan.relation, "root_paths", None) or []
    base = os.path.basename(str(paths[0]).rstrip("/")) if paths else "?"
    return f"Scan({base})"[:28]
