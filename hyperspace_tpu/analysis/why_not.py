"""whyNot API: report, per candidate index, why the optimizer did not apply
it to the given query
(ref: HS/index/plananalysis/CandidateIndexAnalyzer.scala:29-346).

Mechanism mirrors the reference: enable analysis mode, re-run the collector +
optimizer so the filter chain tags each entry with ``FilterReason``s and the
rules tag their ranker winners with ``APPLICABLE_INDEX_RULES``, then render
the reference's four sections (applied / applicable-but-not-applied /
outdated / no-applicable-plan, ref: CandidateIndexAnalyzer.scala:178-255)
followed by the per-subplan reasons table.
"""

from __future__ import annotations

from typing import List, Optional

from hyperspace_tpu.analysis import reasons as R
from hyperspace_tpu.models import states
from hyperspace_tpu.plan import logical as L


def why_not_string(df, session, index_name: Optional[str] = None, extended: bool = False) -> str:
    from hyperspace_tpu.rules.apply import ApplyHyperspace

    applier = ApplyHyperspace(session, analysis_enabled=True)
    indexes = session.index_manager.get_indexes([states.ACTIVE])
    if index_name is not None:
        missing = index_name not in {e.name for e in indexes}
        if missing:
            return f"Index {index_name!r} does not exist or is not ACTIVE."
    from hyperspace_tpu.rules.apply import plans_including_subqueries, used_index_names

    # entries are shared across queries (TTL cache): wipe analysis tags from
    # previous runs or the sections misclassify indexes
    # (ref: CandidateIndexAnalyzer.scala:64-80 prepare/cleanupAnalysisTags)
    for entry in indexes:
        entry.unset_tag_for_all_plans(R.FILTER_REASONS)
        entry.unset_tag_for_all_plans(R.APPLICABLE_INDEX_RULES)
    plan = df.plan
    new_plan = applier.apply(plan)
    applied = set(used_index_names(new_plan))
    scans = []
    for p in plans_including_subqueries(plan):
        scans.extend(L.collect(p, lambda x: isinstance(x, L.Scan)))
    # unique scans by plan key; disambiguate label collisions across distinct
    # scans (two datasets can share a directory basename)
    by_key = {}
    for s in scans:
        by_key.setdefault(L.plan_key(s), s)
    scans = list(by_key.values())
    labels = {}
    used_labels: dict = {}
    for s in scans:
        base = _subplan_label(s)
        ordinal = used_labels.get(base, 0)
        used_labels[base] = ordinal + 1
        labels[L.plan_key(s)] = base if ordinal == 0 else f"{base[:24]}#{ordinal + 1}"

    selected = [e for e in indexes if index_name is None or e.name == index_name]

    # reasons per entry, deduplicated rows for the table AND the section logic
    rows = {}  # entry.name -> list of (label, reason)
    for entry in selected:
        seen = set()
        out = []
        for scan in scans:
            label = labels[L.plan_key(scan)]
            for reason in entry.get_tag(L.plan_key(scan), R.FILTER_REASONS) or []:
                if (label, reason.code, reason.arg_str) in seen:
                    continue
                seen.add((label, reason.code, reason.arg_str))
                out.append((label, reason))
        rows[entry.name] = out

    # "applicable, but not applied due to priority": a rule's ranker picked
    # the index for some sub-plan, but the score-based optimizer chose a
    # different rewrite (ref: CandidateIndexAnalyzer.scala:193-197)
    applicable_not_applied = sorted(
        e.name
        for e in selected
        if e.name not in applied
        and any(e.get_tag(L.plan_key(s), R.APPLICABLE_INDEX_RULES) for s in scans)
    )
    outdated = sorted(
        name
        for name, rs in rows.items()
        if name not in applied
        and name not in applicable_not_applied
        and any(r.code == "SOURCE_DATA_CHANGED" for _, r in rs)
    )
    no_applicable_plan = sorted(
        name
        for name, rs in rows.items()
        if name not in applied
        and name not in applicable_not_applied
        and name not in outdated
        and any(r.code not in ("COL_SCHEMA_MISMATCH", "SOURCE_DATA_CHANGED") for _, r in rs)
    )

    def names_section(buf: List[str], title: str, names) -> None:
        buf.append(title)
        for n in names:
            buf.append(f"- {n}")
        if not names:
            buf.append("- No such index found.")
        buf.append("")

    buf: List[str] = []
    buf.append("=" * 64)
    buf.append("whyNot report")
    buf.append("=" * 64)
    names_section(buf, "Applied indexes:", sorted(applied))
    names_section(
        buf, "Applicable indexes, but not applied due to priority:", applicable_not_applied
    )
    names_section(buf, "Non-applicable indexes - index is outdated:", outdated)
    names_section(buf, "Non-applicable indexes - no applicable query plan:", no_applicable_plan)

    header = f"{'Index':<24} {'Subplan':<28} Reason"
    buf.append(header)
    buf.append("-" * len(header))
    for entry in selected:
        if entry.name in applied:
            buf.append(f"{entry.name:<24} {'-':<28} (applied)")
            continue
        if not rows[entry.name]:
            buf.append(
                f"{entry.name:<24} {'-':<28} [NO_CANDIDATE] not a candidate for any sub-plan"
            )
            continue
        shown = 0
        for label, reason in rows[entry.name]:
            # non-extended drops schema-mismatch noise, like the reference's
            # table filter (CandidateIndexAnalyzer.scala:229-233)
            if not extended and reason.code == "COL_SCHEMA_MISMATCH":
                continue
            text = str(reason) if extended else f"[{reason.code}] {reason.arg_str}"
            buf.append(f"{entry.name:<24} {label:<28} {text}")
            shown += 1
        if not shown:
            buf.append(
                f"{entry.name:<24} {'-':<28} [COL_SCHEMA_MISMATCH] "
                "(details with extended=True)"
            )
    buf.append("=" * 64)
    buf.extend(_sort_elimination_lines(new_plan))
    for entry in indexes:
        entry.unset_tag_for_all_plans(R.FILTER_REASONS)
        entry.unset_tag_for_all_plans(R.APPLICABLE_INDEX_RULES)
    return "\n".join(buf)


def _sort_elimination_lines(new_plan: L.LogicalPlan) -> List[str]:
    """Per-Sort verdicts over the OPTIMIZED plan: eliminated in favor of the
    streamed sorted-run merge, or the reason it cannot fire (the planner half
    lives in plan/ordering.sort_run_eligibility; the executor records the
    same outcomes in dispatch traces)."""
    from hyperspace_tpu.plan import ordering as ORD
    from hyperspace_tpu.rules.apply import plans_including_subqueries

    lines: List[str] = []
    try:
        sorts = []
        for p in plans_including_subqueries(new_plan):
            sorts.extend(L.collect(p, lambda x: isinstance(x, L.Sort)))
        for s in sorts:
            keys = ", ".join(f"{c}{'' if a else ' DESC'}" for c, a in s.keys)
            leaf, _chain, reason = ORD.sort_run_eligibility(s)
            if leaf is not None:
                lines.append(
                    f"Sort({keys}): eliminated — streamed merge of sorted index runs"
                )
            elif reason is not None:
                lines.append(f"Sort({keys}): {R.sort_order_not_covered(reason)}")
            else:
                lines.append(
                    f"Sort({keys}): "
                    f"{R.sort_order_not_covered('child is not an index scan chain')}"
                )
    except Exception:
        return []
    if not lines:
        return []
    return ["Sort elimination:", "-" * len("Sort elimination:"), *lines, "=" * 64]


def _subplan_label(scan: L.Scan) -> str:
    """Short, machine-stable label for a source sub-plan: the dataset's last
    path component (absolute temp paths would make golden files unstable)."""
    import os

    paths = getattr(scan.relation, "root_paths", None) or []
    base = os.path.basename(str(paths[0]).rstrip("/")) if paths else "?"
    return f"Scan({base})"[:28]
