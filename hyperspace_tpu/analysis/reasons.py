"""whyNot filter reasons (ref: HS/index/plananalysis/FilterReason.scala:19-151
— 14 reason case classes with code + verbose strings)."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List


@dataclass(frozen=True)
class FilterReason:
    code: str
    args: tuple = ()
    verbose: str = ""

    @property
    def arg_str(self) -> str:
        return ", ".join(f"{k}={v}" for k, v in self.args)

    def __str__(self) -> str:
        return f"[{self.code}] {self.verbose or self.arg_str}"


def col_schema_mismatch(required, available) -> FilterReason:
    return FilterReason(
        "COL_SCHEMA_MISMATCH",
        (("requiredCols", ",".join(required)), ("availableCols", ",".join(available))),
        f"Index does not contain required columns. Required: {list(required)}, available: {list(available)}",
    )


def source_data_changed() -> FilterReason:
    return FilterReason("SOURCE_DATA_CHANGED", (), "Index signature does not match the current source data.")


def signature_provider_mismatch(recorded: str) -> FilterReason:
    return FilterReason(
        "SIGNATURE_PROVIDER_MISMATCH",
        (("recordedProvider", recorded),),
        f"Index was recorded under signature provider {recorded!r}; refresh the index to re-sign it.",
    )


def no_delete_support() -> FilterReason:
    return FilterReason("NO_DELETE_SUPPORT", (), "Index doesn't support deleted files (no lineage).")


def too_many_deleted(ratio: float, threshold: float) -> FilterReason:
    return FilterReason(
        "TOO_MANY_DELETED",
        (("deletedRatio", f"{ratio:.3f}"), ("threshold", f"{threshold}")),
        f"Deleted bytes ratio {ratio:.3f} exceeds threshold {threshold}.",
    )


def too_many_appended(ratio: float, threshold: float) -> FilterReason:
    return FilterReason(
        "TOO_MANY_APPENDED",
        (("appendedRatio", f"{ratio:.3f}"), ("threshold", f"{threshold}")),
        f"Appended bytes ratio {ratio:.3f} exceeds threshold {threshold}.",
    )


def no_first_indexed_col_cond(first_col: str, pred_cols) -> FilterReason:
    return FilterReason(
        "NO_FIRST_INDEXED_COL_COND",
        (("firstIndexedCol", first_col), ("predicateCols", ",".join(pred_cols))),
        f"The first indexed column {first_col!r} does not appear in the filter condition.",
    )


def missing_required_col(required, indexed_and_included) -> FilterReason:
    return FilterReason(
        "MISSING_REQUIRED_COL",
        (("requiredCols", ",".join(required)), ("indexCols", ",".join(indexed_and_included))),
        f"Index does not cover all required columns: required {list(required)}.",
    )


def no_filter_on_scan() -> FilterReason:
    return FilterReason("NO_FILTER_ON_SCAN", (), "Plan is not a filter over a supported scan.")


def not_eligible_join(reason: str) -> FilterReason:
    return FilterReason("NOT_ELIGIBLE_JOIN", (("reason", reason),), f"Join query is not eligible: {reason}.")


def not_all_join_cols_indexed(side: str, join_cols, indexed) -> FilterReason:
    return FilterReason(
        "NOT_ALL_JOIN_COLS_INDEXED",
        (("side", side), ("joinCols", ",".join(join_cols)), ("indexedCols", ",".join(indexed))),
        f"{side}: indexed columns {list(indexed)} must exactly match join columns {list(join_cols)}.",
    )


def missing_indexed_col(side: str, required, indexed) -> FilterReason:
    return FilterReason(
        "MISSING_INDEXED_COL",
        (("side", side), ("requiredIndexedCols", ",".join(required)), ("indexedCols", ",".join(indexed))),
        f"{side}: join columns {list(required)} not covered by indexed columns {list(indexed)}.",
    )


def no_avail_join_index_pair(side: str) -> FilterReason:
    return FilterReason(
        "NO_AVAIL_JOIN_INDEX_PAIR",
        (("side", side),),
        f"No compatible index pair found (failed on {side} side).",
    )


def another_index_applied(applied: str) -> FilterReason:
    return FilterReason(
        "ANOTHER_INDEX_APPLIED",
        (("appliedIndex", applied),),
        f"Another candidate index {applied!r} was chosen by the ranker.",
    )


def index_not_eligible(reason: str) -> FilterReason:
    return FilterReason("INDEX_NOT_ELIGIBLE", (("reason", reason),), reason)


def index_quarantined(name: str) -> FilterReason:
    """The reliability circuit breaker quarantined this index after repeated
    corrupt-data errors on its files (hyperspace_tpu/reliability/degrade.py);
    queries re-plan against source until a half-open probe reads clean."""
    return FilterReason(
        "INDEX_QUARANTINED",
        (("index", name),),
        f"Index {name!r} is quarantined after repeated corrupt reads; "
        "queries fall back to source until a clean probe un-quarantines it.",
    )


def sort_order_not_covered(reason: str) -> FilterReason:
    """Sort elimination (streamed merge of sorted index runs,
    plan/ordering.sort_run_eligibility) could not fire for a Sort node."""
    return FilterReason("SORT_ORDER_NOT_COVERED", (("reason", reason),), reason)


# Tag names (ref: HS/index/IndexLogEntryTags.scala:23-70)
FILTER_REASONS = "FILTER_REASONS"
COMMON_SOURCE_SIZE_IN_BYTES = "COMMON_SOURCE_SIZE_IN_BYTES"
HYBRIDSCAN_REQUIRED = "HYBRIDSCAN_REQUIRED"
HYBRIDSCAN_APPENDED = "HYBRIDSCAN_APPENDED"
HYBRIDSCAN_DELETED = "HYBRIDSCAN_DELETED"
APPLICABLE_INDEX_RULES = "APPLICABLE_INDEX_RULES"
