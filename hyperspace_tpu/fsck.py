"""``python -m hyperspace_tpu.fsck`` — fabric lake garbage collection.

Thin CLI shim over :func:`hyperspace_tpu.fabric.fsck.main` (which holds
the actual pass logic and its documentation)."""

from hyperspace_tpu.fabric.fsck import main

if __name__ == "__main__":
    raise SystemExit(main())
