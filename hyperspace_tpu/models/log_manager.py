"""Operation-log manager.

Numbered immutable JSON entries ``_hyperspace_log/0..n`` plus a
``latestStable`` snapshot file; writers race via create-exclusive semantics —
the first writer of a given id wins, later writers observe failure and abort
(optimistic concurrency; ref: HS/index/IndexLogManager.scala:34-195).
"""

from __future__ import annotations

import json
import os
from pathlib import Path
from typing import List, Optional

from hyperspace_tpu import config as C
from hyperspace_tpu.models import states
from hyperspace_tpu.models.log_entry import IndexLogEntry
from hyperspace_tpu.utils.file_utils import write_atomic, write_atomic_exclusive

LATEST_STABLE = "latestStable"

#: _read_classified statuses: distinguishing missing from corrupt is what
#: lets a torn trailing entry degrade to the prior version instead of
#: making the whole index silently vanish
READ_OK, READ_MISSING, READ_CORRUPT = "ok", "missing", "corrupt"


def _count_corrupt(index: str) -> None:
    from hyperspace_tpu.obs.metrics import REGISTRY

    REGISTRY.counter(
        "hs_log_corrupt_total",
        "operation-log entries that failed to parse (torn/corrupt writes)",
        index=index,
    ).inc()


class IndexLogManager:
    """Manages the operation log of one index (ref: HS/index/IndexLogManager.scala:57-195)."""

    def __init__(self, index_path: str):
        self.index_path = str(index_path)
        self.log_dir = os.path.join(self.index_path, C.HYPERSPACE_LOG_DIR)
        self.index_name = os.path.basename(os.path.normpath(self.index_path))

    def _path(self, log_id: int) -> str:
        return os.path.join(self.log_dir, str(log_id))

    def _read_classified(self, path: str):
        """``(entry, status)`` — status distinguishes a file that is absent
        (READ_MISSING) from one whose bytes don't parse (READ_CORRUPT, which
        bumps ``hs_log_corrupt_total`` and strikes the quarantine breaker)."""
        from hyperspace_tpu.reliability.degrade import QUARANTINE
        from hyperspace_tpu.reliability.faults import FAULTS
        from hyperspace_tpu.reliability.retry import with_retry

        def _load() -> bytes:
            with open(path, "rb") as f:
                raw = f.read()
            if FAULTS.active:
                raw = FAULTS.mangle_bytes("log.read", path, raw)
            return raw

        try:
            entry = IndexLogEntry.from_json(
                with_retry(_load, op="log.read").decode("utf-8")
            )
        except FileNotFoundError:
            return None, READ_MISSING
        except OSError:
            # unreadable, not provably torn: treated as missing (the prior
            # behavior), but a transient here never marks the entry corrupt
            return None, READ_MISSING
        except (json.JSONDecodeError, KeyError, UnicodeDecodeError, ValueError):
            _count_corrupt(self.index_name)
            if QUARANTINE.enabled:
                QUARANTINE.note_corrupt(path)
            return None, READ_CORRUPT
        if QUARANTINE.enabled:
            QUARANTINE.note_ok(path)
        return entry, READ_OK

    def _read(self, path: str) -> Optional[IndexLogEntry]:
        return self._read_classified(path)[0]

    def get_log(self, log_id: int) -> Optional[IndexLogEntry]:
        return self._read(self._path(log_id))

    def get_latest_id(self) -> Optional[int]:
        """Highest numeric log id present, or None
        (ref: HS/index/IndexLogManager.scala:88-100). Raw directory-listing
        semantics: writers derive the *next* id from this, so a torn trailing
        entry must still count — skipping it here would hand two writers the
        same id. Readers wanting the newest *readable* entry use
        :meth:`get_latest_log`, which walks past torn tails."""
        try:
            names = os.listdir(self.log_dir)
        except OSError:
            return None
        ids = [int(n) for n in names if n.isdigit()]
        return max(ids) if ids else None

    def get_latest_log(self) -> Optional[IndexLogEntry]:
        """Newest *readable* entry: a corrupt (torn) trailing entry degrades
        to the prior parseable version instead of reporting the index absent;
        a genuinely missing id keeps the old absent semantics."""
        latest = self.get_latest_id()
        if latest is None:
            return None
        for log_id in range(latest, -1, -1):
            entry, status = self._read_classified(self._path(log_id))
            if status == READ_CORRUPT:
                continue
            return entry  # READ_OK entry, or None for READ_MISSING
        return None

    def get_latest_stable_log(self) -> Optional[IndexLogEntry]:
        """Prefer the ``latestStable`` snapshot; if missing or unstable, scan
        backwards from the latest id for a stable-state entry
        (ref: HS/index/IndexLogManager.scala:102-127)."""
        snapshot = self._read(os.path.join(self.log_dir, LATEST_STABLE))
        if snapshot is not None and snapshot.state in states.STABLE_STATES:
            return snapshot
        latest = self.get_latest_id()
        if latest is None:
            return None
        for log_id in range(latest, -1, -1):
            entry = self.get_log(log_id)
            if entry is not None and entry.state in states.STABLE_STATES:
                return entry
        return None

    def get_index_versions(self, accepted_states: List[str]) -> List[int]:
        """Log ids of entries in the given states, newest first
        (ref: HS/index/IndexLogManager.scala:129-142)."""
        latest = self.get_latest_id()
        if latest is None:
            return []
        out = []
        for log_id in range(latest, -1, -1):
            entry = self.get_log(log_id)
            if entry is not None and entry.state in accepted_states:
                out.append(log_id)
        return out

    def write_log(self, log_id: int, entry: IndexLogEntry) -> bool:
        """Write entry at ``log_id`` iff no entry with that id exists yet.
        Returns False when another writer won (ref: HS/index/IndexLogManager.scala:178-194).

        When a fabric refresh lease is in scope (``fabric/lease.py``
        ``fence_scope``), its fencing token is verified first: a holder
        whose lease expired and was taken over raises ``LeaseLostError``
        here — the commit point — so a zombie writer can never land a log
        entry over its successor's."""
        entry.id = log_id
        data = entry.to_json().encode("utf-8")
        from hyperspace_tpu.reliability.faults import FAULTS

        if FAULTS.active:
            FAULTS.check("log.write", self._path(log_id))
        from hyperspace_tpu.fabric.lease import current_fence

        # fencing check adjacent to the write itself: everything slow (the
        # build, injected latency above) happens before the token is judged
        fence = current_fence()
        if fence is not None:
            fence.verify()
        return write_atomic_exclusive(self._path(log_id), data)

    def create_latest_stable_log(self, log_id: int) -> bool:
        """Snapshot entry ``log_id`` as ``latestStable``
        (ref: HS/index/IndexLogManager.scala:144-160)."""
        entry = self.get_log(log_id)
        if entry is None or entry.state not in states.STABLE_STATES:
            return False
        write_atomic(os.path.join(self.log_dir, LATEST_STABLE), entry.to_json().encode("utf-8"))
        return True

    def delete_latest_stable_log(self) -> bool:
        try:
            os.unlink(os.path.join(self.log_dir, LATEST_STABLE))
            return True
        except FileNotFoundError:
            return True
        except OSError:
            return False


class IndexLogManagerFactory:
    """Injection point so tests can substitute mock managers
    (ref: HS/index/factories.scala:23-53)."""

    def create(self, index_path: str) -> IndexLogManager:
        return IndexLogManager(index_path)
