"""Versioned index-data directory manager.

Index data for version N lives in ``<index_root>/v__=N/``
(ref: HS/index/IndexDataManager.scala:24-74).
"""

from __future__ import annotations

import os
import re
from typing import List, Optional

from hyperspace_tpu import config as C
from hyperspace_tpu.utils.file_utils import delete_recursively

_VERSION_RE = re.compile(re.escape(C.INDEX_VERSION_DIR_PREFIX) + r"=(\d+)$")


class IndexDataManager:
    def __init__(self, index_path: str):
        self.index_path = str(index_path)

    def version_path(self, version: int) -> str:
        return os.path.join(self.index_path, f"{C.INDEX_VERSION_DIR_PREFIX}={version}")

    def get_all_versions(self) -> List[int]:
        try:
            names = os.listdir(self.index_path)
        except OSError:
            return []
        out = []
        for n in names:
            m = _VERSION_RE.match(n)
            if m and os.path.isdir(os.path.join(self.index_path, n)):
                out.append(int(m.group(1)))
        return sorted(out)

    def get_latest_version(self) -> Optional[int]:
        versions = self.get_all_versions()
        return versions[-1] if versions else None

    def allocate_version(self) -> int:
        """Claim the next data version by creating its directory exclusively;
        two concurrent writers can never share a version dir (defense in
        depth under the operation log's optimistic concurrency)."""
        latest = self.get_latest_version()
        version = 0 if latest is None else latest + 1
        while True:
            try:
                os.makedirs(self.version_path(version), exist_ok=False)
                return version
            except FileExistsError:
                version += 1

    def delete_version(self, version: int) -> None:
        delete_recursively(self.version_path(version))


class IndexDataManagerFactory:
    def create(self, index_path: str) -> IndexDataManager:
        return IndexDataManager(index_path)
