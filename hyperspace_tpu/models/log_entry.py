"""Index metadata model.

The full schema of an index's on-storage metadata, mirroring the semantics of
the reference's ``IndexLogEntry`` (ref: HS/index/IndexLogEntry.scala:40-685):

  - ``FileInfo``     — one source/index file: name, size, mtime, stable id
  - ``Directory``    — compressed file tree (``from_leaf_files``/``merge``)
  - ``Content``      — a Directory tree rooted at an absolute path
  - ``Signature``    — provider-name + opaque fingerprint value
  - ``LogicalPlanFingerprint`` — the set of signatures of the source plan
  - ``Update``       — appended/deleted file trees (quick refresh / hybrid scan)
  - ``Relation``     — snapshot of the source relation (paths, data, schema,
                       file format, options)
  - ``Source``       — plan node wrapping Relation + fingerprint
  - ``IndexLogEntry``— one operation-log record (id, state, timestamp, the
                       derived-dataset payload, content tree, source snapshot)
  - ``FileIdTracker``— stable (name, size, mtime) → id assignment
                       (ref: HS/index/IndexLogEntry.scala:609-685)

Everything (de)serializes to plain-dict JSON; transient query-time state lives
in a ``tags`` dict that is never persisted (ref: IndexLogEntry tags :519-571).
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field
from typing import Any, Dict, Iterable, List, Optional, Tuple

from hyperspace_tpu import config as C

FileKey = Tuple[str, int, int]  # (absolute path, size, modified_time)


class FileInfo:
    """A single file's metadata. Equality/hash ignore ``file_id`` — two
    FileInfos are the same file iff (name, size, mtime) match
    (ref: HS/index/IndexLogEntry.scala:308-333)."""

    __slots__ = ("name", "size", "modified_time", "file_id")

    def __init__(self, name: str, size: int, modified_time: int, file_id: int = C.UNKNOWN_FILE_ID):
        self.name = name
        self.size = int(size)
        self.modified_time = int(modified_time)
        self.file_id = int(file_id)

    @classmethod
    def from_path(cls, path: str, file_id: int = C.UNKNOWN_FILE_ID) -> "FileInfo":
        st = os.stat(path)
        return cls(os.path.abspath(path), st.st_size, st.st_mtime_ns, file_id)

    @property
    def key(self) -> FileKey:
        return (self.name, self.size, self.modified_time)

    def __eq__(self, other: Any) -> bool:
        return isinstance(other, FileInfo) and self.key == other.key

    def __hash__(self) -> int:
        return hash(self.key)

    def __repr__(self) -> str:
        return f"FileInfo({self.name!r}, {self.size}, {self.modified_time}, id={self.file_id})"

    def to_dict(self) -> Dict[str, Any]:
        return {
            "name": self.name,
            "size": self.size,
            "modifiedTime": self.modified_time,
            "id": self.file_id,
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "FileInfo":
        return cls(d["name"], d["size"], d["modifiedTime"], d.get("id", C.UNKNOWN_FILE_ID))


@dataclass
class Directory:
    """A node of the compressed file tree. ``files`` hold leaf-file metadata
    with *basename* names; absolute paths are reconstructed by joining the
    names on the path from the root (ref: HS/index/IndexLogEntry.scala:123-284).
    """

    name: str
    files: List[FileInfo] = field(default_factory=list)
    subdirs: List["Directory"] = field(default_factory=list)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "name": self.name,
            "files": [f.to_dict() for f in self.files],
            "subDirs": [d.to_dict() for d in self.subdirs],
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "Directory":
        return cls(
            d["name"],
            [FileInfo.from_dict(f) for f in d.get("files", [])],
            [Directory.from_dict(s) for s in d.get("subDirs", [])],
        )

    def merge(self, other: "Directory") -> "Directory":
        """Merge two trees with the same root name
        (ref: HS/index/IndexLogEntry.scala:149-171)."""
        if self.name != other.name:
            raise ValueError(f"Merging directories with names {self.name!r} and {other.name!r} failed.")
        files = list(self.files)
        seen = {f.key for f in files}
        files.extend(f for f in other.files if f.key not in seen)
        by_name = {d.name: d for d in self.subdirs}
        merged_subdirs: List[Directory] = []
        other_names = set()
        for od in other.subdirs:
            other_names.add(od.name)
            if od.name in by_name:
                merged_subdirs.append(by_name[od.name].merge(od))
            else:
                merged_subdirs.append(od)
        merged_subdirs.extend(d for d in self.subdirs if d.name not in other_names)
        return Directory(self.name, files, sorted(merged_subdirs, key=lambda d: d.name))

    @classmethod
    def from_leaf_files(cls, files: Iterable[FileInfo]) -> "Directory":
        """Build the compressed tree from absolute-path leaf files
        (ref: HS/index/IndexLogEntry.scala:230-284). Root node is ``/``."""
        root = cls("/")
        index: Dict[str, Directory] = {"": root}

        def get_dir(path: str) -> Directory:
            if path in index:
                return index[path]
            parent_path, name = os.path.split(path)
            if parent_path == path:  # filesystem root
                return root
            parent = get_dir(parent_path.rstrip("/") if parent_path != "/" else "")
            node = cls(name)
            parent.subdirs.append(node)
            index[path] = node
            return node

        for f in files:
            parent = get_dir(os.path.dirname(os.path.abspath(f.name)).rstrip("/"))
            parent.files.append(FileInfo(os.path.basename(f.name), f.size, f.modified_time, f.file_id))
        _sort_tree(root)
        return root


def _sort_tree(d: Directory) -> None:
    d.files.sort(key=lambda f: f.name)
    d.subdirs.sort(key=lambda s: s.name)
    for s in d.subdirs:
        _sort_tree(s)


@dataclass
class Content:
    """A file tree rooted at the absolute root directory
    (ref: HS/index/IndexLogEntry.scala:40-121)."""

    root: Directory

    @property
    def files(self) -> List[str]:
        return [fi.name for fi in self.file_infos()]

    def file_infos(self) -> List[FileInfo]:
        """Leaf files with absolute-path names.

        The tree is never mutated after construction (merge/refresh build new
        Content objects), so the walk is memoized — the optimizer touches this
        on every candidate index per query, and re-joining every path
        dominated the rewrite pass before caching."""
        cached = self.__dict__.get("_file_infos")
        if cached is None:
            out: List[FileInfo] = []

            def walk(node: Directory, prefix: str) -> None:
                base = os.path.join(prefix, node.name) if prefix else node.name
                for f in node.files:
                    out.append(FileInfo(os.path.join(base, f.name), f.size, f.modified_time, f.file_id))
                for s in node.subdirs:
                    walk(s, base)

            walk(self.root, "")
            cached = self.__dict__["_file_infos"] = out
        return list(cached)

    @property
    def total_size(self) -> int:
        cached = self.__dict__.get("_total_size")
        if cached is None:
            cached = self.__dict__["_total_size"] = sum(f.size for f in self.file_infos())
        return cached

    def merge(self, other: "Content") -> "Content":
        return Content(self.root.merge(other.root))

    @classmethod
    def from_leaf_files(cls, files: Iterable[FileInfo]) -> "Content":
        return cls(Directory.from_leaf_files(files))

    @classmethod
    def from_directory(cls, path: str, tracker: Optional["FileIdTracker"] = None) -> "Content":
        """Scan ``path`` recursively, assigning ids via ``tracker``."""
        from hyperspace_tpu.utils.file_utils import walk_data_files

        infos: List[FileInfo] = []
        for fpath in walk_data_files(path):
            fi = FileInfo.from_path(fpath)
            if tracker is not None:
                fi.file_id = tracker.add_file(fi)
            infos.append(fi)
        if not infos:
            # Represent an empty content tree rooted at path itself.
            return cls(Directory.from_leaf_files([]))
        return cls.from_leaf_files(infos)

    def to_dict(self) -> Dict[str, Any]:
        return {"root": self.root.to_dict()}

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "Content":
        return cls(Directory.from_dict(d["root"]))


@dataclass(frozen=True)
class Signature:
    """(provider class name, fingerprint value)
    (ref: HS/index/IndexLogEntry.scala:335-336)."""

    provider: str
    value: str

    def to_dict(self) -> Dict[str, Any]:
        return {"provider": self.provider, "value": self.value}

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "Signature":
        return cls(d["provider"], d["value"])


@dataclass
class LogicalPlanFingerprint:
    """Signatures of the source logical plan
    (ref: HS/index/IndexLogEntry.scala:338-349)."""

    signatures: List[Signature]
    kind: str = "LogicalPlan"

    def to_dict(self) -> Dict[str, Any]:
        return {"kind": self.kind, "properties": {"signatures": [s.to_dict() for s in self.signatures]}}

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "LogicalPlanFingerprint":
        sigs = [Signature.from_dict(s) for s in d.get("properties", {}).get("signatures", [])]
        return cls(sigs, d.get("kind", "LogicalPlan"))


@dataclass
class Update:
    """Appended/deleted source files recorded by quick refresh
    (ref: HS/index/IndexLogEntry.scala:351-352)."""

    appended_files: Optional[Content] = None
    deleted_files: Optional[Content] = None

    def to_dict(self) -> Dict[str, Any]:
        return {
            "appendedFiles": self.appended_files.to_dict() if self.appended_files else None,
            "deletedFiles": self.deleted_files.to_dict() if self.deleted_files else None,
        }

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> Optional["Update"]:
        if not d:
            return None
        return cls(
            Content.from_dict(d["appendedFiles"]) if d.get("appendedFiles") else None,
            Content.from_dict(d["deletedFiles"]) if d.get("deletedFiles") else None,
        )


@dataclass
class Storage:
    """Source data snapshot: the content tree at index-build time plus any
    recorded update (ref: ``Hdfs`` at HS/index/IndexLogEntry.scala:354-377)."""

    content: Content
    update: Optional[Update] = None

    def to_dict(self) -> Dict[str, Any]:
        return {"content": self.content.to_dict(), "update": self.update.to_dict() if self.update else None}

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "Storage":
        return cls(Content.from_dict(d["content"]), Update.from_dict(d.get("update")))


@dataclass
class Relation:
    """Snapshot of the source relation
    (ref: HS/index/IndexLogEntry.scala:379-385)."""

    root_paths: List[str]
    data: Storage
    schema_json: str  # arrow schema serialized as JSON (see sources/schema.py)
    file_format: str
    options: Dict[str, str] = field(default_factory=dict)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "rootPaths": self.root_paths,
            "data": self.data.to_dict(),
            "dataSchemaJson": self.schema_json,
            "fileFormat": self.file_format,
            "options": self.options,
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "Relation":
        return cls(
            list(d["rootPaths"]),
            Storage.from_dict(d["data"]),
            d["dataSchemaJson"],
            d["fileFormat"],
            dict(d.get("options", {})),
        )


@dataclass
class Source:
    """The logged source plan: a single relation plus its fingerprint
    (ref: ``SparkPlan``/``Source`` at HS/index/IndexLogEntry.scala:387-406)."""

    relation: Relation
    fingerprint: LogicalPlanFingerprint

    def to_dict(self) -> Dict[str, Any]:
        return {
            "plan": {
                "kind": "Relation",
                "properties": {
                    "relations": [self.relation.to_dict()],
                    "fingerprint": self.fingerprint.to_dict(),
                },
            }
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "Source":
        props = d["plan"]["properties"]
        return cls(
            Relation.from_dict(props["relations"][0]),
            LogicalPlanFingerprint.from_dict(props["fingerprint"]),
        )


@dataclass
class DerivedDataset:
    """The index payload: a kind tag (e.g. ``CoveringIndex``) plus its
    kind-specific properties. Revived into a concrete ``Index`` via the
    registry in ``indexes/registry.py``
    (ref: the polymorphic ``derivedDataset`` of HS/index/IndexLogEntry.scala:408-430)."""

    kind: str
    properties: Dict[str, Any] = field(default_factory=dict)

    def to_dict(self) -> Dict[str, Any]:
        return {"kind": self.kind, "properties": self.properties}

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "DerivedDataset":
        return cls(d["kind"], dict(d.get("properties", {})))


class FileIdTracker:
    """Assigns stable, monotonically increasing ids to (name, size, mtime)
    keys across the lifetime of an index
    (ref: HS/index/IndexLogEntry.scala:609-685)."""

    def __init__(self) -> None:
        self._ids: Dict[FileKey, int] = {}
        self._max_id: int = C.UNKNOWN_FILE_ID

    @property
    def max_id(self) -> int:
        return self._max_id

    def file_to_id_map(self) -> Dict[FileKey, int]:
        return dict(self._ids)

    def add_file(self, fi: FileInfo) -> int:
        """Record ``fi``; returns its id. Existing key keeps its id; a known
        id (>= 0) on a new key is honored; otherwise a fresh id is assigned."""
        key = fi.key
        if key in self._ids:
            existing = self._ids[key]
            if fi.file_id != C.UNKNOWN_FILE_ID and fi.file_id != existing:
                raise ValueError(
                    f"Adding file {fi.name} with id {fi.file_id} conflicts with existing id {existing}."
                )
            return existing
        if fi.file_id == C.UNKNOWN_FILE_ID:
            self._max_id += 1
            self._ids[key] = self._max_id
        else:
            self._ids[key] = fi.file_id
            self._max_id = max(self._max_id, fi.file_id)
        return self._ids[key]

    def add_files(self, files: Iterable[FileInfo]) -> None:
        for f in files:
            f.file_id = self.add_file(f)

    def get_file_id(self, key: FileKey) -> Optional[int]:
        return self._ids.get(key)

    @classmethod
    def from_contents(cls, *contents: Content) -> "FileIdTracker":
        tracker = cls()
        for c in contents:
            for fi in c.file_infos():
                if fi.file_id != C.UNKNOWN_FILE_ID:
                    tracker.add_file(fi)
        return tracker


class LogEntry:
    """Versioned operation-log record base: id, state, timestamp
    (ref: HS/index/LogEntry.scala:23-46)."""

    def __init__(self, state: str, log_id: int = 0, timestamp: int = 0):
        self.state = state
        self.id = log_id
        self.timestamp = timestamp


class IndexLogEntry(LogEntry):
    """One full index-metadata record (ref: HS/index/IndexLogEntry.scala:408-572).

    ``tags`` is transient per-process state keyed by (plan_key, tag_name),
    used by optimizer rules and whyNot analysis
    (ref: IndexLogEntry tags :519-571); it is never serialized.
    """

    def __init__(
        self,
        name: str,
        derived_dataset: DerivedDataset,
        content: Content,
        source: Source,
        properties: Dict[str, Any],
        state: str = "",
        log_id: int = 0,
        timestamp: int = 0,
    ):
        super().__init__(state, log_id, timestamp)
        self.name = name
        self.derived_dataset = derived_dataset
        self.content = content
        self.source = source
        self.properties = dict(properties)
        self.tags: Dict[Tuple[Any, str], Any] = {}

    # --- derived accessors -------------------------------------------------
    @property
    def kind(self) -> str:
        return self.derived_dataset.kind

    @property
    def relation(self) -> Relation:
        return self.source.relation

    @property
    def signature(self) -> LogicalPlanFingerprint:
        return self.source.fingerprint

    def source_file_infos(self) -> List[FileInfo]:
        return self.relation.data.content.file_infos()

    def source_files_size(self) -> int:
        return self.relation.data.content.total_size

    def appended_files(self) -> List[FileInfo]:
        u = self.relation.data.update
        return u.appended_files.file_infos() if u and u.appended_files else []

    def deleted_files(self) -> List[FileInfo]:
        u = self.relation.data.update
        return u.deleted_files.file_infos() if u and u.deleted_files else []

    def file_id_tracker(self) -> FileIdTracker:
        tracker = FileIdTracker.from_contents(self.relation.data.content)
        u = self.relation.data.update
        if u:
            for c in (u.appended_files, u.deleted_files):
                if c:
                    for fi in c.file_infos():
                        if fi.file_id != C.UNKNOWN_FILE_ID:
                            tracker.add_file(fi)
        return tracker

    def has_lineage_column(self) -> bool:
        return str(self.derived_dataset.properties.get(C.LINEAGE_PROPERTY, "false")).lower() == "true"

    def has_parquet_as_source_format(self) -> bool:
        return (
            str(self.derived_dataset.properties.get(C.HAS_PARQUET_AS_SOURCE_FORMAT_PROPERTY, "false")).lower()
            == "true"
        )

    def with_next_id(self, next_id: int) -> "IndexLogEntry":
        self.id = next_id
        return self

    def copy_with_update(self, appended: List[FileInfo], deleted: List[FileInfo]) -> "IndexLogEntry":
        """Record appended/deleted files for query-time hybrid scan
        (ref: HS/index/IndexLogEntry.scala:460-475, used by RefreshQuickAction)."""
        new = IndexLogEntry.from_dict(self.to_dict())
        update = Update(
            Content.from_leaf_files(appended) if appended else None,
            Content.from_leaf_files(deleted) if deleted else None,
        )
        new.relation.data.update = update
        new.tags = {}
        return new

    # --- tags (transient) --------------------------------------------------
    def set_tag(self, plan_key: Any, tag: str, value: Any) -> None:
        self.tags[(plan_key, tag)] = value

    def get_tag(self, plan_key: Any, tag: str) -> Any:
        return self.tags.get((plan_key, tag))

    def unset_tag(self, plan_key: Any, tag: str) -> None:
        self.tags.pop((plan_key, tag), None)

    def unset_tag_for_all_plans(self, tag: str) -> None:
        """Drop a tag for every plan key (ref: IndexLogEntry
        ``unsetTagValueForAllPlan``, HS/index/IndexLogEntry.scala:560-565) —
        entries are shared across queries by the caching manager, so analysis
        tags must be wiped before each whyNot run."""
        for key in [k for k in self.tags if k[1] == tag]:
            self.tags.pop(key, None)

    # --- serialization -----------------------------------------------------
    def to_dict(self) -> Dict[str, Any]:
        return {
            "name": self.name,
            "derivedDataset": self.derived_dataset.to_dict(),
            "content": self.content.to_dict(),
            "source": self.source.to_dict(),
            "properties": self.properties,
            "state": self.state,
            "id": self.id,
            "timestamp": self.timestamp,
            "enabled": True,
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "IndexLogEntry":
        return cls(
            name=d["name"],
            derived_dataset=DerivedDataset.from_dict(d["derivedDataset"]),
            content=Content.from_dict(d["content"]),
            source=Source.from_dict(d["source"]),
            properties=dict(d.get("properties", {})),
            state=d.get("state", ""),
            log_id=d.get("id", 0),
            timestamp=d.get("timestamp", 0),
        )

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), indent=2, sort_keys=True)

    @classmethod
    def from_json(cls, text: str) -> "IndexLogEntry":
        return cls.from_dict(json.loads(text))

    def __eq__(self, other: Any) -> bool:
        return isinstance(other, IndexLogEntry) and self.to_dict() == other.to_dict()

    def __hash__(self) -> int:
        return hash((self.name, self.id, self.state))
