"""Resolves the Hyperspace system root and per-index paths.

The system path comes from conf ``hyperspace.system.path``; an index's
directory is looked up case-insensitively among existing children so that
``myIndex`` and ``MYINDEX`` refer to the same index
(ref: HS/index/PathResolver.scala:30-70).
"""

from __future__ import annotations

import os
from typing import List, Optional

from hyperspace_tpu.config import HyperspaceConf, INDEXES_DIR, keys


class PathResolver:
    def __init__(self, conf: HyperspaceConf):
        self.conf = conf

    @property
    def system_path(self) -> str:
        path = self.conf.system_path
        if not path:
            raise ValueError(
                f"Hyperspace system path is not set; set conf {keys.SYSTEM_PATH!r} "
                f"(the reference defaults to <warehouse>/{INDEXES_DIR})."
            )
        return str(path)

    def get_index_path(self, name: str) -> str:
        """Existing dir matching ``name`` case-insensitively, else the exact path."""
        root = self.system_path
        try:
            for child in os.listdir(root):
                if child.lower() == name.lower() and os.path.isdir(os.path.join(root, child)):
                    return os.path.join(root, child)
        except OSError:
            pass
        return os.path.join(root, name)

    def all_index_paths(self) -> List[str]:
        root = self.system_path
        try:
            return [
                os.path.join(root, child)
                for child in sorted(os.listdir(root))
                if os.path.isdir(os.path.join(root, child))
            ]
        except OSError:
            return []
