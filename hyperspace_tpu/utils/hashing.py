"""Hashing helpers (ref: HS/util/HashingUtils.scala:24-34 — md5Hex)."""

from __future__ import annotations

import hashlib
from typing import Any


def md5_hex(text: Any) -> str:
    return hashlib.md5(str(text).encode("utf-8")).hexdigest()
