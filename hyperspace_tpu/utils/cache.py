"""Small caching helpers.

``CacheWithTransform`` re-derives a parsed value only when the raw input
changes (ref: HS/util/CacheWithTransform.scala:31-45). ``TTLCache`` backs the
caching index collection manager (ref: HS/index/CachingIndexCollectionManager.scala:127-173).
"""

from __future__ import annotations

import time
from typing import Callable, Generic, Optional, Tuple, TypeVar

R = TypeVar("R")
T = TypeVar("T")


class CacheWithTransform(Generic[R, T]):
    def __init__(self, load_fn: Callable[[], R], transform_fn: Callable[[R], T]):
        self._load_fn = load_fn
        self._transform_fn = transform_fn
        self._cached: Optional[Tuple[R, T]] = None

    def load(self) -> T:
        raw = self._load_fn()
        if self._cached is not None and self._cached[0] == raw:
            return self._cached[1]
        value = self._transform_fn(raw)
        self._cached = (raw, value)
        return value


class TTLCache(Generic[T]):
    """Single-entry cache with creation-time-based expiry."""

    def __init__(self, expiry_seconds_fn: Callable[[], float]):
        self._expiry_seconds_fn = expiry_seconds_fn
        self._entry: Optional[Tuple[float, T]] = None

    def get(self) -> Optional[T]:
        if self._entry is None:
            return None
        created, value = self._entry
        if time.time() - created > self._expiry_seconds_fn():
            self._entry = None
            return None
        return value

    def set(self, value: T) -> None:
        self._entry = (time.time(), value)

    def clear(self) -> None:
        self._entry = None
