"""Minimal Avro Object Container File codec (read + write).

Iceberg stores its manifest lists and manifests as Avro container files; no
Avro library is available in this environment, so the framework carries its
own schema-driven binary codec. The reader is generic (decodes any record
schema found in the file header, so real Iceberg tables written by other
engines parse); the writer is sufficient for the manifests this framework
emits (null codec).

Format: magic "Obj\\x01", file-metadata map (avro.schema JSON + avro.codec),
16-byte sync marker, then blocks of <count><byte-size><payload><sync>.
Codecs: null, deflate, and snappy (raw block + big-endian CRC32 framing;
decompression via the native library's decoder, pure-Python fallback).
"""

from __future__ import annotations

import io
import json
import os
import struct
import zlib
from typing import Any, Dict, List, Optional, Tuple

MAGIC = b"Obj\x01"


def _snappy_decompress(blob: bytes) -> bytes:
    """Raw-snappy decompression: native (libhs_native) when available, else
    pyarrow's bundled snappy (an unconditional dependency of this package) —
    the uncompressed size comes from the raw-format varint preamble."""
    try:
        from hyperspace_tpu.native import NativeUnsupported
        from hyperspace_tpu.native import snappy_decompress as native_snappy

        try:
            return native_snappy(blob)
        except NativeUnsupported:
            pass
    except ImportError:
        pass
    import pyarrow as pa

    n, shift, i = 0, 0, 0
    while True:
        if i >= len(blob) or i >= 5:
            raise ValueError("snappy: bad length header")
        b = blob[i]
        i += 1
        n |= (b & 0x7F) << shift
        if not b & 0x80:
            break
        shift += 7
    if n > max(len(blob) * 256, 1 << 30):  # untrusted varint: cap allocation
        raise ValueError(f"snappy: implausible uncompressed length {n}")
    try:
        return pa.decompress(blob, decompressed_size=n, codec="snappy", asbytes=True)
    except (pa.lib.ArrowException, OSError) as e:  # ArrowIOError == OSError
        raise ValueError(f"snappy: malformed block ({e})")


# --------------------------------------------------------------------------
# binary primitives
# --------------------------------------------------------------------------


def _read_long(buf: io.BytesIO) -> int:
    """zigzag varint"""
    shift = 0
    accum = 0
    while True:
        b = buf.read(1)
        if not b:
            raise EOFError("unexpected end of avro data")
        byte = b[0]
        accum |= (byte & 0x7F) << shift
        if not (byte & 0x80):
            break
        shift += 7
    return (accum >> 1) ^ -(accum & 1)


def _write_long(out: io.BytesIO, n: int) -> None:
    n = (n << 1) ^ (n >> 63)  # zigzag
    while True:
        to_write = n & 0x7F
        n >>= 7
        if n:
            out.write(bytes([to_write | 0x80]))
        else:
            out.write(bytes([to_write]))
            break


def _read_bytes(buf: io.BytesIO) -> bytes:
    n = _read_long(buf)
    return buf.read(n)


def _write_bytes(out: io.BytesIO, b: bytes) -> None:
    _write_long(out, len(b))
    out.write(b)


# --------------------------------------------------------------------------
# schema-driven value codec
# --------------------------------------------------------------------------


def _decode(schema: Any, buf: io.BytesIO, names: Dict[str, Any]) -> Any:
    if isinstance(schema, str):
        t = schema
        if t in names:
            return _decode(names[t], buf, names)
        if t == "null":
            return None
        if t == "boolean":
            return buf.read(1)[0] != 0
        if t in ("int", "long"):
            return _read_long(buf)
        if t == "float":
            return struct.unpack("<f", buf.read(4))[0]
        if t == "double":
            return struct.unpack("<d", buf.read(8))[0]
        if t == "bytes":
            return _read_bytes(buf)
        if t == "string":
            return _read_bytes(buf).decode("utf-8")
        raise ValueError(f"Unknown avro type {t!r}")
    if isinstance(schema, list):  # union
        idx = _read_long(buf)
        return _decode(schema[idx], buf, names)
    t = schema["type"]
    if t == "record":
        full = schema.get("name", "")
        if full:
            names[full] = schema
        out = {}
        for f in schema["fields"]:
            out[f["name"]] = _decode(f["type"], buf, names)
        return out
    if t == "array":
        out_list: List[Any] = []
        while True:
            count = _read_long(buf)
            if count == 0:
                break
            if count < 0:
                _read_long(buf)  # block byte size, unused
                count = -count
            for _ in range(count):
                out_list.append(_decode(schema["items"], buf, names))
        return out_list
    if t == "map":
        out_map: Dict[str, Any] = {}
        while True:
            count = _read_long(buf)
            if count == 0:
                break
            if count < 0:
                _read_long(buf)
                count = -count
            for _ in range(count):
                k = _read_bytes(buf).decode("utf-8")
                out_map[k] = _decode(schema["values"], buf, names)
        return out_map
    if t == "fixed":
        if schema.get("name"):
            names[schema["name"]] = schema
        return buf.read(schema["size"])
    if t == "enum":
        if schema.get("name"):
            names[schema["name"]] = schema
        return schema["symbols"][_read_long(buf)]
    # logical types wrap a primitive in {"type": prim, "logicalType": ...}
    return _decode(t, buf, names)


def _encode(schema: Any, value: Any, out: io.BytesIO, names: Dict[str, Any]) -> None:
    if isinstance(schema, str):
        t = schema
        if t in names:
            return _encode(names[t], value, out, names)
        if t == "null":
            return
        if t == "boolean":
            out.write(b"\x01" if value else b"\x00")
            return
        if t in ("int", "long"):
            _write_long(out, int(value))
            return
        if t == "float":
            out.write(struct.pack("<f", float(value)))
            return
        if t == "double":
            out.write(struct.pack("<d", float(value)))
            return
        if t == "bytes":
            _write_bytes(out, bytes(value))
            return
        if t == "string":
            _write_bytes(out, str(value).encode("utf-8"))
            return
        raise ValueError(f"Unknown avro type {t!r}")
    if isinstance(schema, list):  # union: pick first matching branch
        for i, branch in enumerate(schema):
            if _matches(branch, value, names):
                _write_long(out, i)
                _encode(branch, value, out, names)
                return
        raise ValueError(f"No union branch of {schema} matches {value!r}")
    t = schema["type"]
    if t == "record":
        if schema.get("name"):
            names[schema["name"]] = schema
        for f in schema["fields"]:
            _encode(f["type"], value.get(f["name"]), out, names)
        return
    if t == "array":
        items = list(value or [])
        if items:
            _write_long(out, len(items))
            for it in items:
                _encode(schema["items"], it, out, names)
        _write_long(out, 0)
        return
    if t == "map":
        entries = dict(value or {})
        if entries:
            _write_long(out, len(entries))
            for k, v in entries.items():
                _write_bytes(out, str(k).encode("utf-8"))
                _encode(schema["values"], v, out, names)
        _write_long(out, 0)
        return
    if t == "fixed":
        out.write(bytes(value))
        return
    if t == "enum":
        _write_long(out, schema["symbols"].index(value))
        return
    _encode(t, value, out, names)


def _matches(schema: Any, value: Any, names: Dict[str, Any]) -> bool:
    if isinstance(schema, str):
        if schema in names:
            return _matches(names[schema], value, names)
        if schema == "null":
            return value is None
        if schema == "boolean":
            return isinstance(value, bool)
        if schema in ("int", "long"):
            return isinstance(value, int) and not isinstance(value, bool)
        if schema in ("float", "double"):
            return isinstance(value, (int, float)) and not isinstance(value, bool)
        if schema == "bytes":
            return isinstance(value, (bytes, bytearray))
        if schema == "string":
            return isinstance(value, str)
        return False
    if isinstance(schema, list):
        return any(_matches(b, value, names) for b in schema)
    t = schema["type"]
    if t == "record":
        return isinstance(value, dict)
    if t == "array":
        return isinstance(value, list)
    if t == "map":
        return isinstance(value, dict)
    if t in ("fixed",):
        return isinstance(value, (bytes, bytearray))
    if t == "enum":
        return isinstance(value, str)
    return _matches(t, value, names)


# --------------------------------------------------------------------------
# container file API
# --------------------------------------------------------------------------


def read_schema(path: str) -> Dict[str, Any]:
    """Parse only the container header (magic + metadata map) — no record
    blocks are read, so this is O(header) regardless of file size."""
    with open(path, "rb") as f:
        if f.read(4) != MAGIC:
            raise ValueError(f"{path!r} is not an Avro container file")
        meta: Dict[str, bytes] = {}
        while True:
            count = _read_long(f)
            if count == 0:
                break
            if count < 0:
                _read_long(f)
                count = -count
            for _ in range(count):
                k = _read_bytes(f).decode("utf-8")
                meta[k] = _read_bytes(f)
        return json.loads(meta["avro.schema"].decode("utf-8"))


def count_records(path: str) -> int:
    """Total record count from block headers only: each block starts with
    (count, byte-size); payloads are seeked past, never decompressed."""
    with open(path, "rb") as f:
        if f.read(4) != MAGIC:
            raise ValueError(f"{path!r} is not an Avro container file")
        while True:  # skip metadata map
            count = _read_long(f)
            if count == 0:
                break
            if count < 0:
                _read_long(f)
                count = -count
            for _ in range(count):
                _read_bytes(f)
                _read_bytes(f)
        f.read(16)  # sync marker
        total = 0
        while True:
            try:
                n = _read_long(f)
            except EOFError:
                break
            size = _read_long(f)
            f.seek(size + 16, 1)  # payload + sync marker
            total += n
        return total


def read_container(path: str) -> Tuple[Dict[str, Any], List[Any]]:
    """Read an Avro container file; returns (schema, records)."""
    with open(path, "rb") as f:
        data = f.read()
    buf = io.BytesIO(data)
    if buf.read(4) != MAGIC:
        raise ValueError(f"{path!r} is not an Avro container file")
    meta: Dict[str, bytes] = {}
    while True:
        count = _read_long(buf)
        if count == 0:
            break
        if count < 0:
            _read_long(buf)
            count = -count
        for _ in range(count):
            k = _read_bytes(buf).decode("utf-8")
            meta[k] = _read_bytes(buf)
    schema = json.loads(meta["avro.schema"].decode("utf-8"))
    codec = meta.get("avro.codec", b"null").decode("utf-8")
    sync = buf.read(16)

    records: List[Any] = []
    while buf.tell() < len(data):
        try:
            count = _read_long(buf)
        except EOFError:
            break
        size = _read_long(buf)
        payload = buf.read(size)
        if codec == "deflate":
            payload = zlib.decompress(payload, -15)
        elif codec == "snappy":
            # a raw snappy block followed by the 4-byte big-endian CRC32 of
            # the uncompressed data (Avro spec's snappy codec framing)
            crc = int.from_bytes(payload[-4:], "big")
            payload = _snappy_decompress(payload[:-4])
            if zlib.crc32(payload) & 0xFFFFFFFF != crc:
                raise ValueError(f"Avro snappy block CRC mismatch in {path!r}")
        elif codec != "null":
            raise ValueError(f"Unsupported avro codec {codec!r}")
        block = io.BytesIO(payload)
        names: Dict[str, Any] = {}
        for _ in range(count):
            records.append(_decode(schema, block, names))
        if buf.read(16) != sync:
            raise ValueError(f"Avro sync marker mismatch in {path!r}")
    return schema, records


def write_container(path: str, schema: Dict[str, Any], records: List[Any]) -> None:
    """Write records as a null-codec Avro container file."""
    out = io.BytesIO()
    out.write(MAGIC)
    meta = {"avro.schema": json.dumps(schema).encode("utf-8"), "avro.codec": b"null"}
    _write_long(out, len(meta))
    for k, v in meta.items():
        _write_bytes(out, k.encode("utf-8"))
        _write_bytes(out, v)
    _write_long(out, 0)
    sync = os.urandom(16)
    out.write(sync)

    payload = io.BytesIO()
    names: Dict[str, Any] = {}
    for r in records:
        _encode(schema, r, payload, names)
    body = payload.getvalue()
    _write_long(out, len(records))
    _write_long(out, len(body))
    out.write(body)
    out.write(sync)
    with open(path, "wb") as f:
        f.write(out.getvalue())
