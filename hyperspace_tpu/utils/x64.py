"""Session-scoped enablement of 64-bit JAX types.

The device execution layer needs int64 keys/sentinels and float64 sketch
bounds, which require ``jax_enable_x64``. Flipping that flag is process-wide,
so it must NOT happen as an import side effect (hostile to host applications
that embed this library); instead ``Session()`` and every device entry point
call :func:`ensure_x64` lazily, immediately before any tracing happens.

The flag is still global to the process once enabled — that is a JAX
constraint, documented in docs/configuration.md — but importing
``hyperspace_tpu`` alone no longer mutates global JAX state.
"""

from __future__ import annotations

import threading

_enabled = False
_lock = threading.Lock()


def ensure_x64() -> None:
    """Enable ``jax_enable_x64`` once, at first use of the device layer."""
    global _enabled
    if _enabled:
        return
    with _lock:
        if _enabled:
            return
        import jax

        jax.config.update("jax_enable_x64", True)
        _enabled = True
