"""Filesystem helpers.

The reference relies on the Hadoop FS API for atomic rename semantics
(ref: HS/util/FileUtils.scala, HS/index/IndexLogManager.scala:178-194).
Here we target POSIX local / fuse-mounted lake storage: the create-exclusive
primitive is ``os.link`` (fails if the target exists), giving the same
optimistic-concurrency guarantee.
"""

from __future__ import annotations

import os
import shutil
import tempfile
from pathlib import Path
from typing import Union

PathLike = Union[str, Path]


def write_atomic_exclusive(path: PathLike, data: bytes) -> bool:
    """Atomically create ``path`` with ``data`` iff it does not already exist.

    Returns True on success, False if the file already existed (another writer
    won the race). Mirrors the temp-file + atomic-rename protocol of
    HS/index/IndexLogManager.scala:178-194.
    """
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    fd, tmp = tempfile.mkstemp(prefix=f".{path.name}.", dir=str(path.parent))
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        try:
            os.link(tmp, str(path))  # atomic create-exclusive
            return True
        except FileExistsError:
            return False
    finally:
        try:
            os.unlink(tmp)
        except OSError:
            pass


def write_atomic(path: PathLike, data: bytes) -> None:
    """Atomically (over)write ``path`` with ``data`` via temp + rename."""
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    fd, tmp = tempfile.mkstemp(prefix=f".{path.name}.", dir=str(path.parent))
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, str(path))
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def walk_data_files(root: PathLike):
    """Yield data-file paths under ``root``, excluding hidden/meta entries
    (dot- or underscore-prefixed) at ANY depth — files and whole directories
    alike. The one DataPathFilter used by source listing and index-content
    scans (ref: HS/util/PathUtils.scala:33-39 DataPathFilter)."""
    import os

    for dirpath, dirs, names in os.walk(str(root)):
        dirs[:] = [d for d in dirs if not d.startswith((".", "_"))]
        for n in sorted(names):
            if not n.startswith((".", "_")):
                yield os.path.join(dirpath, n)


def delete_recursively(path: PathLike) -> None:
    path = Path(path)
    if path.is_dir():
        shutil.rmtree(path, ignore_errors=True)
    elif path.exists():
        path.unlink(missing_ok=True)


def directory_size(path: PathLike) -> int:
    """Total bytes of all files under ``path`` (ref: HS/util/FileUtils.scala)."""
    total = 0
    for root, _dirs, files in os.walk(str(path)):
        for name in files:
            try:
                total += os.path.getsize(os.path.join(root, name))
            except OSError:
                pass
    return total
