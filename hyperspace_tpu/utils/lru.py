"""Byte-capped LRU used by the scan/device caches.

One policy implementation shared by the host batch cache (exec/io.py) and the
HBM column cache (exec/device.py): get() refreshes recency, put() overwrites
existing keys (adjusting the byte count) and evicts least-recently-used
entries until the total fits the cap.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Any, Hashable, Optional


class BytesLRU:
    """Thread-safe: readers decode files concurrently (exec/io.py)."""

    def __init__(self, cap_bytes: int):
        self.cap = cap_bytes
        self._entries: "OrderedDict[Hashable, tuple]" = OrderedDict()
        self._bytes = 0
        self._lock = threading.Lock()
        # observability for >cap working sets (benchmarks record these to
        # show byte-capped eviction actually engaging at scale)
        self.evictions = 0
        self.hits = 0
        self.misses = 0

    def __len__(self) -> int:
        return len(self._entries)

    @property
    def total_bytes(self) -> int:
        return self._bytes

    def get(self, key: Hashable) -> Optional[Any]:
        with self._lock:
            got = self._entries.get(key)
            if got is None:
                self.misses += 1
                return None
            self.hits += 1
            self._entries.move_to_end(key)
            return got[0]

    def put(self, key: Hashable, value: Any, nbytes: int) -> None:
        if self.cap <= 0 or nbytes > self.cap:
            return
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old[1]
            self._entries[key] = (value, nbytes)
            self._bytes += nbytes
            while self._bytes > self.cap and self._entries:
                _, (_, nb) = self._entries.popitem(last=False)
                self._bytes -= nb
                self.evictions += 1

    def keys(self):
        with self._lock:
            return list(self._entries.keys())

    def discard(self, key: Hashable) -> bool:
        """Drop one entry if present (targeted invalidation on data-version
        commits); returns whether anything was removed."""
        with self._lock:
            got = self._entries.pop(key, None)
            if got is None:
                return False
            self._bytes -= got[1]
            return True

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._bytes = 0
