"""The user-facing ``Hyperspace`` facade
(ref: HS/Hyperspace.scala:27-231).

Maintenance operations run with the optimizer rule disabled so that index
builds never recursively consult indexes
(ref: Hyperspace.scala:193-200 withHyperspaceRuleDisabled).
"""

from __future__ import annotations

from typing import List, Optional

from hyperspace_tpu import config as C
from hyperspace_tpu.manager import CachingIndexCollectionManager
from hyperspace_tpu.models.log_entry import IndexLogEntry
from hyperspace_tpu.session import Session, get_session


class Hyperspace:
    def __init__(self, session: Optional[Session] = None):
        self.session = session or get_session()

    @property
    def _manager(self) -> CachingIndexCollectionManager:
        return self.session.index_manager

    # --- index management (ref: Hyperspace.scala:43-150) -------------------
    def create_index(self, df, index_config) -> IndexLogEntry:
        with self.session.with_hyperspace_disabled():
            return self._manager.create(df, index_config)

    def delete_index(self, name: str) -> IndexLogEntry:
        with self.session.with_hyperspace_disabled():
            return self._manager.delete(name)

    def restore_index(self, name: str) -> IndexLogEntry:
        with self.session.with_hyperspace_disabled():
            return self._manager.restore(name)

    def vacuum_index(self, name: str) -> IndexLogEntry:
        with self.session.with_hyperspace_disabled():
            return self._manager.vacuum(name)

    def cancel(self, name: str) -> IndexLogEntry:
        with self.session.with_hyperspace_disabled():
            return self._manager.cancel(name)

    def refresh_index(self, name: str, mode: str = C.REFRESH_MODE_FULL) -> IndexLogEntry:
        with self.session.with_hyperspace_disabled():
            return self._manager.refresh(name, mode)

    def optimize_index(self, name: str, mode: str = C.OPTIMIZE_MODE_QUICK) -> IndexLogEntry:
        with self.session.with_hyperspace_disabled():
            return self._manager.optimize(name, mode)

    # --- introspection (ref: Hyperspace.scala indexes/index/explain/whyNot) -
    def indexes(self):
        return self._manager.indexes()

    def index(self, name: str):
        return self._manager.index_stats(name, extended=True)

    def explain(self, df, verbose: bool = False, mode: str = "plaintext") -> str:
        """``mode`` is one of plaintext / console / html
        (ref: plananalysis/DisplayMode.scala:61-89)."""
        from hyperspace_tpu.analysis.explain import explain_string

        return explain_string(df, self.session, verbose, mode=mode)

    def why_not(self, df, index_name: Optional[str] = None, extended: bool = False) -> str:
        from hyperspace_tpu.analysis.why_not import why_not_string

        return why_not_string(df, self.session, index_name, extended)

    # --- reference-API aliases ---------------------------------------------
    # The reference's JVM/PySpark binding exposes camelCase method names
    # (ref: HS/Hyperspace.scala:27-231, python/hyperspace/hyperspace.py:9-192);
    # users migrating from it can keep their call sites. Thin delegating defs
    # so subclass overrides of the snake_case methods stay authoritative.
    def createIndex(self, df, index_config) -> IndexLogEntry:
        return self.create_index(df, index_config)

    def deleteIndex(self, name: str) -> IndexLogEntry:
        return self.delete_index(name)

    def restoreIndex(self, name: str) -> IndexLogEntry:
        return self.restore_index(name)

    def vacuumIndex(self, name: str) -> IndexLogEntry:
        return self.vacuum_index(name)

    def refreshIndex(self, name: str, mode: str = C.REFRESH_MODE_FULL) -> IndexLogEntry:
        return self.refresh_index(name, mode)

    def optimizeIndex(self, name: str, mode: str = C.OPTIMIZE_MODE_QUICK) -> IndexLogEntry:
        return self.optimize_index(name, mode)

    def whyNot(self, df, index_name: Optional[str] = None, extended: bool = False) -> str:
        return self.why_not(df, index_name, extended)
