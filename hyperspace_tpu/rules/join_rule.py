"""JoinIndexRule.

Replace both sides of an equi-join with compatible covering indexes so the
join executes with NO shuffle: both sides are pre-bucketed and pre-sorted on
the join keys, bucket i of the left lives with bucket i of the right
(ref: HS/index/covering/JoinIndexRule.scala:45-705).

Eligibility pipeline (mirrors the reference's filter chain):
  JoinPlanNodeFilter   — equi-join, CNF of col=col, linear children (:135-155)
  JoinAttributeFilter  — one-to-one left/right attribute mapping (:247-286)
  JoinColumnFilter     — per side: indexed cols == join cols, index covers all
                         required cols (:419-448)
  JoinRankFilter       — compatible (same key order) pairs; prefer equal
                         bucket counts, then more buckets (:554-601;
                         JoinIndexRanker.scala:52-92)

Score: 70 per side, scaled by hybrid coverage (:674-704).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from hyperspace_tpu.analysis import reasons as R
from hyperspace_tpu.models.log_entry import IndexLogEntry
from hyperspace_tpu.plan import logical as L
from hyperspace_tpu.plan.expr import extract_equi_join_keys
from hyperspace_tpu.rules.context import RuleContext
from hyperspace_tpu.rules.utils import (
    destructure_linear,
    hybrid_coverage_fraction,
    hybrid_thresholds_ok,
    transform_plan_to_use_index,
)

RULE_NAME = "JoinIndexRule"
# ceiling of the 70+70 coverage score below — the optimizer short-circuits
# rules that cannot beat the current best, keyed on this constant
MAX_SCORE = 140


def _attribute_mapping(
    pairs: List[Tuple[str, str]], left_cols: List[str], right_cols: List[str]
) -> Optional[Dict[str, str]]:
    """One-to-one mapping of left join cols -> right join cols
    (ref: JoinAttributeFilter :247-286). A dotted nested key belongs to the
    side whose output has its root struct column."""
    from hyperspace_tpu.plan.expr import column_root_member

    def member(name: str, side: List[str]) -> Optional[str]:
        return column_root_member(name, side)

    lset, rset = list(left_cols), list(right_cols)
    mapping: Dict[str, str] = {}
    reverse: Dict[str, str] = {}
    for a, b in pairs:
        al, bl = member(a, lset), member(b, rset)
        if al is not None and bl is not None:
            l, r = al, bl
        else:
            bl2, ar2 = member(b, lset), member(a, rset)
            if bl2 is not None and ar2 is not None:
                l, r = bl2, ar2
            else:
                return None
        if mapping.get(l, r) != r or reverse.get(r, l) != l:
            return None  # not one-to-one
        mapping[l] = r
        reverse[r] = l
    return mapping


def _side_candidates(
    ctx: RuleContext,
    side: str,
    scan: L.Scan,
    join_cols: List[str],
    required: List[str],
    entries: List[IndexLogEntry],
) -> List[IndexLogEntry]:
    """JoinColumnFilter (ref: :419-448)."""
    out = []
    from hyperspace_tpu.plan.expr import strip_nested_prefix

    join_set = {strip_nested_prefix(c).lower() for c in join_cols}
    for entry in entries:
        if entry.kind != "CoveringIndex":
            continue
        props = entry.derived_dataset.properties
        indexed = [str(c) for c in props.get("indexedColumns", [])]
        included = [str(c) for c in props.get("includedColumns", [])]
        exact = {strip_nested_prefix(c).lower() for c in indexed} == join_set
        if not ctx.tag_reason_if_failed(
            exact, entry, scan, lambda: R.not_all_join_cols_indexed(side, join_cols, indexed)
        ):
            continue
        covered = {strip_nested_prefix(c).lower() for c in indexed + included}
        covers = all(strip_nested_prefix(c).lower() in covered for c in required)
        if not ctx.tag_reason_if_failed(
            covers, entry, scan, lambda: R.missing_required_col(required, indexed + included)
        ):
            continue
        if not hybrid_thresholds_ok(ctx, entry, scan):
            continue
        out.append(entry)
    return out


def _compatible(l_entry: IndexLogEntry, r_entry: IndexLogEntry, mapping: Dict[str, str]) -> bool:
    """Same column order under the attribute mapping (ref: :554-601)."""
    l_indexed = [str(c) for c in l_entry.derived_dataset.properties.get("indexedColumns", [])]
    r_indexed = [str(c) for c in r_entry.derived_dataset.properties.get("indexedColumns", [])]
    if len(l_indexed) != len(r_indexed):
        return False
    from hyperspace_tpu.plan.expr import strip_nested_prefix

    lowered = {k.lower(): v.lower() for k, v in mapping.items()}
    return all(
        lowered.get(strip_nested_prefix(lc).lower()) == strip_nested_prefix(rc).lower()
        for lc, rc in zip(l_indexed, r_indexed)
    )


def _rank_pairs(
    ctx: RuleContext,
    pairs: List[Tuple[IndexLogEntry, IndexLogEntry]],
    l_scan: L.Scan,
    r_scan: L.Scan,
) -> Optional[Tuple[IndexLogEntry, IndexLogEntry]]:
    """JoinIndexRanker: equal bucket counts first, then more buckets, then
    common bytes under hybrid scan (ref: JoinIndexRanker.scala:52-92)."""
    if not pairs:
        return None

    def nb(e: IndexLogEntry) -> int:
        return int(e.derived_dataset.properties.get("numBuckets", 0))

    def common(e: IndexLogEntry, scan: L.Scan) -> int:
        return e.get_tag(L.plan_key(scan), R.COMMON_SOURCE_SIZE_IN_BYTES) or 0

    hybrid = ctx.session.conf.hybrid_scan_enabled

    def sort_key(p):
        l, r = p
        return (
            nb(l) == nb(r),
            common(l, l_scan) + common(r, r_scan) if hybrid else 0,
            nb(l) + nb(r),
        )

    return max(pairs, key=sort_key)


def apply_join_index_rule(
    ctx: RuleContext,
    plan: L.LogicalPlan,
    candidates: Dict[int, Tuple[L.Scan, List[IndexLogEntry]]],
) -> Tuple[L.LogicalPlan, int]:
    # any equi-join type qualifies — index substitution on the scan sides is
    # join-type-agnostic (ref: JoinPlanNodeFilter matches JoinWithoutHint with
    # a wildcard joinType, JoinIndexRule.scala:52-54)
    if not isinstance(plan, L.Join) or plan.how not in ("inner", "left", "right", "outer"):
        return plan, 0
    if plan.residual is not None:
        # non-equi ON residual: outside the rule's equi-CNF scope
        # (ref: JoinPlanNodeFilter, JoinIndexRule.scala:149-155)
        return plan, 0
    pairs = extract_equi_join_keys(plan.condition)
    if not pairs:
        return plan, 0
    l_parts = destructure_linear(plan.left)
    r_parts = destructure_linear(plan.right)
    if l_parts is None or r_parts is None:
        return plan, 0
    l_proj, l_cond, l_scan = l_parts
    r_proj, r_cond, r_scan = r_parts
    from hyperspace_tpu.plan.expr import contains_input_file_name

    if (l_cond is not None and contains_input_file_name(l_cond)) or (
        r_cond is not None and contains_input_file_name(r_cond)
    ):
        return plan, 0  # rewrite would change input_file_name() semantics
    lk, rk = L.plan_key(l_scan), L.plan_key(r_scan)
    if lk not in candidates or rk not in candidates:
        return plan, 0
    if lk == rk and l_scan is r_scan:
        pass  # self-join over the same scan object still works: same candidates

    mapping = _attribute_mapping(pairs, l_scan.output_columns, r_scan.output_columns)
    if mapping is None:
        return plan, 0

    def required_cols(proj, cond, scan, join_cols):
        req = list(proj) if proj is not None else list(scan.output_columns)
        if cond is not None:
            req += list(cond.references())
        req += join_cols
        return list(dict.fromkeys(req))

    l_join_cols = list(mapping.keys())
    r_join_cols = list(mapping.values())
    l_required = required_cols(l_proj, l_cond, l_scan, l_join_cols)
    r_required = required_cols(r_proj, r_cond, r_scan, r_join_cols)

    l_entries = _side_candidates(ctx, "left", l_scan, l_join_cols, l_required, candidates[lk][1])
    r_entries = _side_candidates(ctx, "right", r_scan, r_join_cols, r_required, candidates[rk][1])

    # candidate lists are per-scan (signature-matched), so an entry appearing
    # on both sides implies a self-join — no extra identity check needed
    compatible = [
        (le, re) for le in l_entries for re in r_entries if _compatible(le, re, mapping)
    ]
    best = _rank_pairs(ctx, compatible, l_scan, r_scan)
    if best is None:
        for e in l_entries:
            ctx.tag_reason_if_failed(False, e, l_scan, lambda: R.no_avail_join_index_pair("left"))
        for e in r_entries:
            ctx.tag_reason_if_failed(False, e, r_scan, lambda: R.no_avail_join_index_pair("right"))
        return plan, 0
    l_best, r_best = best
    ctx.tag_applicable_rule(l_best, l_scan, RULE_NAME)
    ctx.tag_applicable_rule(r_best, r_scan, RULE_NAME)

    new_left = transform_plan_to_use_index(ctx, l_best, plan.left, use_bucket_spec=True)
    new_right = transform_plan_to_use_index(ctx, r_best, plan.right, use_bucket_spec=True)
    new_plan = L.Join(
        new_left, new_right, plan.condition, plan.how, plan.residual, plan.using_pairs
    )
    score = int(70 * hybrid_coverage_fraction(l_best, l_scan) + 70 * hybrid_coverage_fraction(r_best, r_scan))
    return new_plan, max(score, 1)
