"""DataSkippingIndexRule: prune source files using per-file sketches.

The reference snapshot ships data-skipping index build/refresh/optimize but
never registered a query-side rule (its optimizer rule list is Filter/Join/
NoOp only — ref: HS/index/rules/ScoreBasedIndexPlanOptimizer.scala:30; the
predicate-translation groundwork lives in
HS/index/dataskipping/util/extractors.scala:42-199). This module implements
that missing rule: a ``Filter→Scan`` (optionally under ``Project``) keeps its
shape, but the Scan is replaced by a ``FileScan`` over only the source files
whose sketches say they *might* contain matching rows.

Sketch semantics are three-valued: for every (file, conjunct) the evaluator
answers "maybe contains matches" (keep) or "definitely not" (prune);
anything it cannot reason about keeps the file — pruning must never change
query results.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from hyperspace_tpu.analysis import reasons as R
from hyperspace_tpu.indexes.dataskipping import (
    BloomFilterSketch,
    DataSkippingIndex,
    MinMaxSketch,
    PartitionSketch,
    Sketch,
    ValueListSketch,
)
from hyperspace_tpu.models.log_entry import IndexLogEntry
from hyperspace_tpu.plan import logical as L
from hyperspace_tpu.plan.expr import BinaryOp, Col, Expr, In, Lit, Not
from hyperspace_tpu.rules.context import RuleContext
from hyperspace_tpu.rules.utils import destructure_linear

RULE_NAME = "DataSkippingIndexRule"
# ceiling of max(1, int(40 x pruned)) + 1 below (see score.py short-circuit)
MAX_SCORE = 41

_FLIP = {"<": ">", "<=": ">=", ">": "<", ">=": "<=", "=": "=", "!=": "!="}


def _null_mask(arr: np.ndarray) -> np.ndarray:
    if arr.dtype == object:
        return np.array([x is None for x in arr], dtype=bool)
    if arr.dtype.kind == "f":
        return np.isnan(arr)
    if arr.dtype.kind == "M":
        return np.isnat(arr)
    return np.zeros(arr.shape, dtype=bool)


def _cmp(arr: np.ndarray, op: str, lit) -> np.ndarray:
    """Elementwise compare treating nulls as False (caller decides whether a
    null aggregate keeps the file)."""
    nulls = _null_mask(arr)
    if arr.dtype == object:
        safe = np.where(nulls, lit, arr)
    else:
        safe = arr
    with np.errstate(invalid="ignore"):
        if op == "=":
            res = safe == lit
        elif op == "!=":
            res = safe != lit
        elif op == "<":
            res = safe < lit
        elif op == "<=":
            res = safe <= lit
        elif op == ">":
            res = safe > lit
        else:
            res = safe >= lit
    return np.asarray(res, dtype=bool) & ~nulls


class _SketchEvaluator:
    """Evaluates a predicate tree to a per-file keep mask over the sketch
    table. Returns None wherever pruning is impossible (keep everything)."""

    def __init__(self, sketches: List[Sketch], table_cols: Dict[str, np.ndarray], n_rows: int):
        self.by_col: Dict[str, List[Sketch]] = {}
        for s in sketches:
            self.by_col.setdefault(s.expr.lower(), []).append(s)
        self.cols = table_cols
        self.n = n_rows

    # -- per-sketch primitives ---------------------------------------------
    def _minmax(self, s: MinMaxSketch, op: str, lit) -> Optional[np.ndarray]:
        mn_name, mx_name = s.output_names()
        mn, mx = self.cols[mn_name], self.cols[mx_name]
        all_null = _null_mask(mn) | _null_mask(mx)
        if op == "=":
            keep = _cmp(mn, "<=", lit) & _cmp(mx, ">=", lit)
        elif op == "<":
            keep = _cmp(mn, "<", lit)
        elif op == "<=":
            keep = _cmp(mn, "<=", lit)
        elif op == ">":
            keep = _cmp(mx, ">", lit)
        elif op == ">=":
            keep = _cmp(mx, ">=", lit)
        elif op == "!=":
            # prune only files where every row equals lit (min == max == lit)
            keep = ~(_cmp(mn, "=", lit) & _cmp(mx, "=", lit))
        else:
            return None
        return keep | all_null  # a file with a null aggregate is kept

    def _valuelist(self, s: ValueListSketch, op: str, lit) -> Optional[np.ndarray]:
        (vname,) = s.output_names()
        values = self.cols[vname]
        if op != "=":
            return None
        out = np.ones(self.n, dtype=bool)
        for i, vals in enumerate(values):
            if vals is None:
                continue  # overflowed list: keep
            out[i] = bool(_cmp(np.asarray(vals), "=", lit).any())
        return out

    def _bloom(self, s: BloomFilterSketch, op: str, lit) -> Optional[np.ndarray]:
        if op != "=":
            return None
        (bname,) = s.output_names()
        bits = self.cols[bname]
        out = np.ones(self.n, dtype=bool)
        for i, words in enumerate(bits):
            if words is None:
                continue
            out[i] = s.might_contain(words, lit)
        return out

    def _partition(self, s: PartitionSketch, op: str, lit) -> Optional[np.ndarray]:
        (pname,) = s.output_names()
        vals = self.cols[pname]
        nulls = _null_mask(vals)
        if op not in _FLIP:
            return None
        return _cmp(vals, op, lit) | nulls  # mixed-partition file (null) kept

    def _col_op_lit(self, col_name: str, op: str, lit) -> Optional[np.ndarray]:
        masks = []
        for s in self.by_col.get(col_name.lower(), []):
            # incomparable literal/column dtypes (e.g. float column vs string
            # literal) must mean "unprunable", never an exception escaping to
            # ApplyHyperspace and cancelling unrelated rewrites
            try:
                if isinstance(s, MinMaxSketch):
                    m = self._minmax(s, op, lit)
                elif isinstance(s, ValueListSketch):
                    m = self._valuelist(s, op, lit)
                elif isinstance(s, BloomFilterSketch):
                    m = self._bloom(s, op, lit)
                elif isinstance(s, PartitionSketch):
                    m = self._partition(s, op, lit)
                else:
                    m = None
            except Exception:
                m = None
            if m is not None:
                masks.append(m)
        if not masks:
            return None
        out = masks[0]
        for m in masks[1:]:
            out = out & m  # every sketch must say "maybe"
        return out

    # -- tree walk ----------------------------------------------------------
    def eval(self, e: Expr) -> Optional[np.ndarray]:
        if isinstance(e, BinaryOp) and e.op == "AND":
            l, r = self.eval(e.left), self.eval(e.right)
            if l is None:
                return r
            if r is None:
                return l
            return l & r
        if isinstance(e, BinaryOp) and e.op == "OR":
            l, r = self.eval(e.left), self.eval(e.right)
            if l is None or r is None:
                return None  # one side unprunable -> whole OR unprunable
            return l | r
        if isinstance(e, BinaryOp) and e.op in _FLIP:
            left, right, op = e.left, e.right, e.op
            if isinstance(right, Col) and isinstance(left, Lit):
                left, right, op = right, left, _FLIP[op]
            if isinstance(left, Col) and isinstance(right, Lit):
                return self._col_op_lit(left.name, op, right.value)
            return None
        if isinstance(e, In) and isinstance(e.child, Col):
            masks = [self._col_op_lit(e.child.name, "=", v.value) for v in e.values]
            if any(m is None for m in masks) or not masks:
                return None
            out = masks[0]
            for m in masks[1:]:
                out = out | m
            return out
        if isinstance(e, Not):
            inner = e.child
            # push negation through the comparisons we understand
            if isinstance(inner, BinaryOp) and inner.op in ("=", "!=", "<", "<=", ">", ">="):
                neg = {"=": "!=", "!=": "=", "<": ">=", "<=": ">", ">": "<=", ">=": "<"}
                return self.eval(BinaryOp(neg[inner.op], inner.left, inner.right))
            return None
        return None


def prune_files(
    entry: IndexLogEntry, condition: Expr, current_files
) -> Optional[Tuple[List[str], int, int]]:
    """Evaluate ``condition`` against ``entry``'s sketch table.

    Returns (surviving file names, surviving bytes, total bytes), or None when
    no pruning is possible. Files unknown to the sketch table (hybrid-scan
    appends) are always kept.
    """
    index = DataSkippingIndex.from_derived_dataset(entry.derived_dataset)
    # cheap pre-check before any I/O: some sketched column must appear in the
    # predicate at all
    pred_cols = {c.lower() for c in condition.references()}
    if not any(s.expr.lower() in pred_cols for s in index.sketches):
        return None
    table = index.read_sketch_table(entry)
    if table.num_rows == 0:
        return None
    cols: Dict[str, np.ndarray] = {}
    for name in table.column_names:
        col = table.column(name)
        try:
            cols[name] = col.to_numpy(zero_copy_only=False)
        except Exception:
            cols[name] = np.asarray(col.to_pylist(), dtype=object)

    ev = _SketchEvaluator(index.sketches, cols, table.num_rows)
    mask = ev.eval(condition)
    if mask is None:
        return None

    import hyperspace_tpu.config as C

    fids = cols[C.DATA_FILE_NAME_ID].astype(np.int64)
    surviving_ids = set(fids[mask].tolist())
    indexed_by_key = {fi.key: fi.file_id for fi in entry.source_file_infos()}

    surviving: List[str] = []
    surviving_bytes = 0
    total_bytes = 0
    for fi in current_files:
        total_bytes += fi.size
        fid = indexed_by_key.get(fi.key)
        if fid is None or fid in surviving_ids:  # unknown (appended) -> keep
            surviving.append(fi.name)
            surviving_bytes += fi.size
    return surviving, surviving_bytes, total_bytes


def apply_data_skipping_rule(
    ctx: RuleContext,
    plan: L.LogicalPlan,
    candidates: Dict[int, Tuple[L.Scan, List[IndexLogEntry]]],
) -> Tuple[L.LogicalPlan, int]:
    """Try to prune the file set of a Filter→Scan sub-plan; returns
    (possibly-rewritten plan, score). Score = 40 x fraction of bytes pruned,
    deliberately below FilterIndexRule's 50 so a covering index wins when
    both apply (ref scoring scheme: HS/index/covering/FilterIndexRule.scala:170-193)."""
    parts = destructure_linear(plan)
    if parts is None:
        return plan, 0
    project_cols, condition, scan = parts
    if condition is None:
        return plan, 0
    key = L.plan_key(scan)
    if key not in candidates:
        return plan, 0
    _, entries = candidates[key]
    ds_entries = [e for e in entries if e.kind == DataSkippingIndex.kind]
    if not ds_entries:
        return plan, 0

    best: Optional[Tuple[IndexLogEntry, List[str], int, int]] = None
    for entry in ds_entries:
        # the optimizer visits both the Project and the Filter node of the
        # same sub-plan; cache per (scan, predicate, entry) so the sketch
        # table is read once per query
        cache_key = (key, id(condition), entry.name)
        if cache_key in ctx.scratch:
            pruned = ctx.scratch[cache_key]
        else:
            # missing/corrupt sketch data means "this entry can't prune" —
            # never an exception reaching ApplyHyperspace, which would cancel
            # unrelated rewrites for the whole query
            try:
                pruned = prune_files(entry, condition, scan.relation.all_file_infos())
            except Exception:
                pruned = None
            ctx.scratch[cache_key] = pruned
        if pruned is None:
            ctx.tag_reason_if_failed(
                False, entry, scan, lambda: R.index_not_eligible("predicate not prunable by sketches")
            )
            continue
        surviving, surviving_bytes, total_bytes = pruned
        if surviving_bytes >= total_bytes:
            ctx.tag_reason_if_failed(
                False, entry, scan, lambda: R.index_not_eligible("sketches pruned no files")
            )
            continue
        if best is None or surviving_bytes < best[2]:
            best = (entry, surviving, surviving_bytes, total_bytes)

    if best is None:
        return plan, 0
    entry, surviving, surviving_bytes, total_bytes = best
    ctx.tag_applicable_rule(entry, scan, RULE_NAME)

    required_out = project_cols if project_cols is not None else scan.output_columns
    needed = list(dict.fromkeys(list(required_out) + list(condition.references())))
    # resolve required names against the relation schema (case-insensitive)
    schema_names = {c.lower(): c for c in scan.output_columns}
    needed = [schema_names.get(c.lower(), c) for c in needed]

    rel = scan.relation
    pv = pd = None
    if getattr(rel, "partition_columns", None):
        pv = {f: rel.partition_values_for(f) for f in surviving}
        pd_ = getattr(rel, "partition_dtypes", None)
        pd = dict(pd_) if pd_ else None
    new_scan: L.LogicalPlan = L.FileScan(
        surviving,
        rel.physical_format,
        needed,
        via_index=entry.name,
        partition_values=pv,
        partition_dtypes=pd,
        format_options=getattr(rel, "options", None),
    )
    new_plan: L.LogicalPlan = L.Filter(condition, new_scan)
    if project_cols is not None:
        new_plan = L.Project(project_cols, new_plan)

    fraction_pruned = 1.0 - surviving_bytes / max(1, total_bytes)
    score = max(1, int(40 * fraction_pruned))
    # the optimizer keeps the NoOp-children path on score ties; the Project-
    # node rewrite must strictly beat the Filter-node rewrite it contains so
    # its column narrowing (read only predicate+projection columns) wins
    if project_cols is not None and len(needed) < len(scan.output_columns):
        score += 1
    return new_plan, score
