"""Score-based plan optimizer.

Memoized recursion: at each node, the best of (a) applying a rule to the whole
sub-tree rooted here, (b) keeping the node and optimizing children
independently (the NoOpRule path)
(ref: HS/index/rules/ScoreBasedIndexPlanOptimizer.scala:29-78; rules list =
FilterIndexRule :: JoinIndexRule :: NoOpRule).
"""

from __future__ import annotations

import time
from typing import Dict, Tuple

from hyperspace_tpu.obs import spans
from hyperspace_tpu.plan import logical as L
from hyperspace_tpu.rules.context import RuleContext
from hyperspace_tpu.rules.dataskipping_rule import apply_data_skipping_rule
from hyperspace_tpu.rules.filter_rule import apply_filter_index_rule
from hyperspace_tpu.rules.join_rule import apply_join_index_rule

from hyperspace_tpu.rules import dataskipping_rule as _ds
from hyperspace_tpu.rules import filter_rule as _fr
from hyperspace_tpu.rules import join_rule as _jr

# (rule, its maximum possible score) — tried highest-max first so the
# beaten-rule short-circuit bites as early as possible
RULES = (
    (apply_join_index_rule, _jr.MAX_SCORE),
    (apply_filter_index_rule, _fr.MAX_SCORE),
    (apply_data_skipping_rule, _ds.MAX_SCORE),
)

# linear-chain nodes: when the chain TOP destructures, a rule applied there
# requires a subset of the columns any lower application would (and sees a
# superset of the filter conjuncts), so it succeeds whenever a lower one
# does — re-evaluating rules below such a top is pure overhead on the
# per-query hot path. When the top does NOT destructure (e.g. a filter over
# a computed column pins the chain), interior nodes stay eligible.
_CHAIN_NODES = (L.Project, L.Filter, L.Compute)


class ScoreBasedIndexPlanOptimizer:
    def __init__(self, ctx: RuleContext):
        self.ctx = ctx
        self._memo: Dict[int, Tuple[L.LogicalPlan, int]] = {}
        self._multi_parent: set = set()
        # accumulated wall seconds per rule across the whole recursion — a
        # span per rule-per-node would explode the trace, so the tracer gets
        # one aggregate attr instead (surfaced in QueryProfile.rule_timings)
        self._rule_seconds: Dict[str, float] = {}

    def apply(self, plan: L.LogicalPlan, candidates) -> Tuple[L.LogicalPlan, int]:
        counts: Dict[int, int] = {}

        def walk(p: L.LogicalPlan) -> None:
            c = counts.get(id(p), 0) + 1
            counts[id(p)] = c
            if c == 1:
                for ch in p.children():
                    walk(ch)

        walk(plan)
        # a sub-plan with several parents (a CTE referenced N times) always
        # gets the full rule set and ONE memo entry, so the rewritten tree
        # keeps sharing a single object (the executor's shared-subplan memo
        # depends on that identity)
        self._multi_parent = {pid for pid, c in counts.items() if c > 1}
        result = self._rec(plan, candidates)
        sp = spans.current_span()
        if sp is not None and self._rule_seconds:
            sp.set(rule_timings=dict(self._rule_seconds))
        return result

    def _rec(
        self, plan: L.LogicalPlan, candidates, in_chain: bool = False
    ) -> Tuple[L.LogicalPlan, int]:
        if id(plan) in self._multi_parent:
            in_chain = False
        key = id(plan)
        if key in self._memo:
            return self._memo[key]

        # exhaustive mode for whyNot: every rule must run at every node so
        # the per-index disqualification reasons get collected
        analysis = self.ctx.analysis_enabled
        from hyperspace_tpu.rules.utils import destructure_linear

        chain_top = isinstance(plan, _CHAIN_NODES) and destructure_linear(plan) is not None

        # NoOp path: optimize children independently (score = sum)
        children = list(plan.children())
        best_plan, best_score = plan, 0
        if children:
            child_in_chain = chain_top and len(children) == 1
            new_children = []
            child_score = 0
            for c in children:
                nc, s = self._rec(c, candidates, in_chain=child_in_chain)
                new_children.append(nc)
                child_score += s
            if child_score > 0:
                best_plan, best_score = plan.with_children(new_children), child_score

        if analysis or not in_chain:
            timing = spans.current_span() is not None
            for rule, max_score in RULES:
                if max_score <= best_score and not analysis:
                    continue  # cannot beat the current best (ties keep it)
                if timing:
                    t0 = time.perf_counter()
                    transformed, score = rule(self.ctx, plan, candidates)
                    name = rule.__name__
                    self._rule_seconds[name] = self._rule_seconds.get(name, 0.0) + (
                        time.perf_counter() - t0
                    )
                else:
                    transformed, score = rule(self.ctx, plan, candidates)
                if score > best_score:
                    best_plan, best_score = transformed, score

        self._memo[key] = (best_plan, best_score)
        return best_plan, best_score
