"""Score-based plan optimizer.

Memoized recursion: at each node, the best of (a) applying a rule to the whole
sub-tree rooted here, (b) keeping the node and optimizing children
independently (the NoOpRule path)
(ref: HS/index/rules/ScoreBasedIndexPlanOptimizer.scala:29-78; rules list =
FilterIndexRule :: JoinIndexRule :: NoOpRule).
"""

from __future__ import annotations

from typing import Dict, Tuple

from hyperspace_tpu.plan import logical as L
from hyperspace_tpu.rules.context import RuleContext
from hyperspace_tpu.rules.dataskipping_rule import apply_data_skipping_rule
from hyperspace_tpu.rules.filter_rule import apply_filter_index_rule
from hyperspace_tpu.rules.join_rule import apply_join_index_rule

RULES = (apply_filter_index_rule, apply_join_index_rule, apply_data_skipping_rule)


class ScoreBasedIndexPlanOptimizer:
    def __init__(self, ctx: RuleContext):
        self.ctx = ctx
        self._memo: Dict[int, Tuple[L.LogicalPlan, int]] = {}

    def apply(self, plan: L.LogicalPlan, candidates) -> Tuple[L.LogicalPlan, int]:
        return self._rec(plan, candidates)

    def _rec(self, plan: L.LogicalPlan, candidates) -> Tuple[L.LogicalPlan, int]:
        key = id(plan)
        if key in self._memo:
            return self._memo[key]

        # NoOp path: optimize children independently (score = sum)
        children = list(plan.children())
        best_plan, best_score = plan, 0
        if children:
            new_children = []
            child_score = 0
            for c in children:
                nc, s = self._rec(c, candidates)
                new_children.append(nc)
                child_score += s
            if child_score > 0:
                best_plan, best_score = plan.with_children(new_children), child_score

        for rule in RULES:
            transformed, score = rule(self.ctx, plan, candidates)
            if score > best_score:
                best_plan, best_score = transformed, score

        self._memo[key] = (best_plan, best_score)
        return best_plan, best_score
