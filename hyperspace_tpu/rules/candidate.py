"""Candidate index collection.

Per source leaf (Scan), chain ``ColumnSchemaFilter`` then
``FileSignatureFilter`` (ref: HS/index/rules/CandidateIndexCollector.scala:28-60,
ColumnSchemaFilter.scala:28-45, FileSignatureFilter.scala:33-192).

``FileSignatureFilter`` is where Hybrid Scan eligibility is decided: when
exact signature match fails, compare file sets; appended/deleted byte ratios
must stay under thresholds, and deletes require lineage.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

from hyperspace_tpu.analysis import reasons as R
from hyperspace_tpu.models.log_entry import FileInfo, IndexLogEntry
from hyperspace_tpu.plan import logical as L
from hyperspace_tpu.rules.context import RuleContext
from hyperspace_tpu.sources.signatures import INDEX_SIGNATURE_PROVIDER, index_signature


def _referenced_columns(entry: IndexLogEntry) -> List[str]:
    """Kind-polymorphic referenced columns via the index registry (covering:
    indexed+included; data-skipping: sketched columns)."""
    from hyperspace_tpu.indexes import registry

    try:
        return [str(c) for c in registry.index_of_entry(entry).referenced_columns]
    except Exception:
        props = entry.derived_dataset.properties
        return [str(c) for c in props.get("indexedColumns", [])] + [
            str(c) for c in props.get("includedColumns", [])
        ]


def _quarantine_filter(ctx: RuleContext, scan: L.Scan, indexes: List[IndexLogEntry]) -> List[IndexLogEntry]:
    """Drop quarantined indexes (reliability circuit breaker) so their
    queries transparently re-plan against source. One attribute read when
    the breaker registry is disabled (the default)."""
    from hyperspace_tpu.reliability.degrade import QUARANTINE

    if not QUARANTINE.enabled:
        return indexes
    out = []
    for entry in indexes:
        name = str(entry.name)
        ok = not QUARANTINE.is_quarantined(name)
        if ctx.tag_reason_if_failed(ok, entry, scan, lambda: R.index_quarantined(name)):
            out.append(entry)
    return out


def _schema_filter(ctx: RuleContext, scan: L.Scan, indexes: List[IndexLogEntry]) -> List[IndexLogEntry]:
    """Index's referenced columns ⊆ relation output (ref: ColumnSchemaFilter.scala:29-44)."""
    out = []
    relation_cols = {c.lower() for c in scan.output_columns}

    def covered(name: str) -> bool:
        # nested index columns (__hs_nested.a.b) must fully resolve against
        # the relation schema — the root struct existing is not enough after
        # source schema evolution dropped the leaf
        from hyperspace_tpu.plan.expr import strip_nested_prefix
        from hyperspace_tpu.plan.resolver import resolve_columns_against_schema

        stripped = strip_nested_prefix(name)
        if stripped.lower() in relation_cols:
            return True
        if "." not in stripped or stripped.split(".")[0].lower() not in relation_cols:
            return False
        try:
            resolve_columns_against_schema([stripped], scan.relation.schema)
            return True
        except ValueError:
            return False
    for entry in indexes:
        referenced = _referenced_columns(entry)
        ok = all(covered(c) for c in referenced)
        if ctx.tag_reason_if_failed(
            ok, entry, scan, lambda: R.col_schema_mismatch(referenced, scan.output_columns)
        ):
            out.append(entry)
    return out


def _signature_filter(ctx: RuleContext, scan: L.Scan, indexes: List[IndexLogEntry]) -> List[IndexLogEntry]:
    """Signature equality, or Hybrid-Scan file-set comparison
    (ref: FileSignatureFilter.scala:49-191)."""
    conf = ctx.session.conf
    current_sig = index_signature(scan)
    current_files = {fi.key: fi for fi in scan.relation.all_file_infos()}
    total_bytes = sum(fi.size for fi in current_files.values())

    out = []
    for e in indexes:
        entry = scan.relation.closest_index(e)
        sig0 = entry.signature.signatures[0] if entry.signature.signatures else None
        if sig0 is not None and sig0.provider != INDEX_SIGNATURE_PROVIDER:
            # recorded under an older/incompatible provider: values are not
            # comparable — require a refresh rather than mis-reporting
            # "source data changed"
            ctx.tag_reason_if_failed(
                False, entry, scan, lambda: R.signature_provider_mismatch(sig0.provider)
            )
            continue
        if sig0 is not None and sig0.value == current_sig:
            entry.set_tag(L.plan_key(scan), R.COMMON_SOURCE_SIZE_IN_BYTES, entry.source_files_size())
            entry.set_tag(L.plan_key(scan), R.HYBRIDSCAN_REQUIRED, False)
            out.append(entry)
            continue

        if not conf.hybrid_scan_enabled:
            ctx.tag_reason_if_failed(False, entry, scan, R.source_data_changed)
            continue

        # Hybrid scan eligibility: file-level diff (ref: :108-191)
        indexed_files = {fi.key: fi for fi in entry.source_file_infos()}
        common_keys = current_files.keys() & indexed_files.keys()
        appended = [current_files[k] for k in current_files.keys() - indexed_files.keys()]
        deleted = [indexed_files[k] for k in indexed_files.keys() - current_files.keys()]
        common_bytes = sum(indexed_files[k].size for k in common_keys)
        if not common_keys:
            ctx.tag_reason_if_failed(False, entry, scan, R.source_data_changed)
            continue

        appended_bytes = sum(f.size for f in appended)
        deleted_bytes = sum(f.size for f in deleted)
        if deleted:
            # kind-polymorphic: covering indexes need the lineage column to
            # filter deleted rows; data-skipping handles deletes naturally
            # (it prunes over *current* files)
            from hyperspace_tpu.indexes import registry

            if not registry.index_of_entry(entry).can_handle_deleted_files():
                ctx.tag_reason_if_failed(False, entry, scan, R.no_delete_support)
                continue
            deleted_ratio = deleted_bytes / max(1, entry.source_files_size())
            if deleted_ratio > conf.hybrid_scan_deleted_ratio_threshold:
                ctx.tag_reason_if_failed(
                    False, entry, scan,
                    lambda: R.too_many_deleted(deleted_ratio, conf.hybrid_scan_deleted_ratio_threshold),
                )
                continue
        appended_ratio = appended_bytes / max(1, total_bytes)
        if appended_ratio > conf.hybrid_scan_appended_ratio_threshold:
            ctx.tag_reason_if_failed(
                False, entry, scan,
                lambda: R.too_many_appended(appended_ratio, conf.hybrid_scan_appended_ratio_threshold),
            )
            continue

        key = L.plan_key(scan)
        entry.set_tag(key, R.COMMON_SOURCE_SIZE_IN_BYTES, common_bytes)
        entry.set_tag(key, R.HYBRIDSCAN_REQUIRED, True)
        entry.set_tag(key, R.HYBRIDSCAN_APPENDED, [f.name for f in appended])
        entry.set_tag(key, R.HYBRIDSCAN_DELETED, [f.name for f in deleted])
        out.append(entry)
    return out


def collect_candidates(
    ctx: RuleContext, plan: L.LogicalPlan, indexes: List[IndexLogEntry]
) -> Dict[int, Tuple[L.Scan, List[IndexLogEntry]]]:
    """Map each Scan leaf (by plan key) to its eligible index entries
    (ref: CandidateIndexCollector.scala:49-59)."""
    out: Dict[int, Tuple[L.Scan, List[IndexLogEntry]]] = {}
    for scan in L.collect(plan, lambda p: isinstance(p, L.Scan)):
        eligible = _signature_filter(
            ctx, scan, _schema_filter(ctx, scan, _quarantine_filter(ctx, scan, indexes))
        )
        if eligible:
            out[L.plan_key(scan)] = (scan, eligible)
    return out
