"""ApplyHyperspace — the optimizer entry point.

Fetch ACTIVE indexes, collect per-scan candidates, run the score-based
rewrite; swallow all exceptions so index application can never break a query
(ref: HS/index/rules/ApplyHyperspace.scala:31-66). Recurses into uncorrelated
subquery expressions so indexes apply inside subqueries too (the reference
gets this for free from Catalyst walking the whole tree; explain golden
src/test/resources/expected/spark-2.4/subquery.txt).
"""

from __future__ import annotations

import logging
from typing import List, Optional, Tuple

from hyperspace_tpu.models import states
from hyperspace_tpu.plan import logical as L
from hyperspace_tpu.plan.expr import BinaryOp, Expr, IsNull, Not, SubqueryExpr
from hyperspace_tpu.rules.candidate import collect_candidates
from hyperspace_tpu.rules.context import RuleContext
from hyperspace_tpu.rules.score import ScoreBasedIndexPlanOptimizer
from hyperspace_tpu.obs import spans
from hyperspace_tpu.telemetry.events import HyperspaceIndexUsageEvent, emit_event

logger = logging.getLogger(__name__)


def iter_subquery_plans(plan: L.LogicalPlan):
    """Yield the inner plan of every subquery expression in ``plan``
    (recursively, including subqueries nested in subqueries)."""
    for node in L.collect(plan, lambda p: isinstance(p, L.Filter)):
        for sub in _collect_subqueries(node.condition):
            yield sub.plan
            yield from iter_subquery_plans(sub.plan)


def plans_including_subqueries(plan: L.LogicalPlan) -> List[L.LogicalPlan]:
    """``plan`` plus every subquery inner plan it carries — the single
    traversal helper every analysis over "the whole query" must use, so a new
    subquery host (if one is ever added) is handled in one place."""
    return [plan, *iter_subquery_plans(plan)]


def used_index_names(plan: L.LogicalPlan) -> List[str]:
    """Names of every index an (optimized) plan uses: covering-index scans
    plus data-skipping rewrites (FileScans tagged via_index), across the main
    plan and all subquery plans. Shared by telemetry, explain, and whyNot so
    the three reports can never disagree."""
    used = set()
    for p in plans_including_subqueries(plan):
        used |= {s.entry.name for s in L.collect(p, lambda x: isinstance(x, L.IndexScan))}
        used |= {
            s.via_index
            for s in L.collect(p, lambda x: isinstance(x, L.FileScan))
            if s.via_index
        }
    return sorted(used)


def _collect_subqueries(e: Expr) -> List[SubqueryExpr]:
    out: List[SubqueryExpr] = []
    if isinstance(e, SubqueryExpr):
        out.append(e)
    for c in e.children():
        out.extend(_collect_subqueries(c))
    return out


def optimize_plan(plan: L.LogicalPlan, session, enabled: Optional[bool] = None) -> L.LogicalPlan:
    """The one optimizer entry point shared by ad-hoc execution
    (``DataFrame.optimized_plan``) and the serving plan cache: apply the
    hyperspace rewrite when the toggle (or the explicit ``enabled`` override
    captured at request-submit time) says so, else hand the plan back."""
    if enabled is None:
        enabled = session.hyperspace_enabled
    if not enabled:
        return plan
    with spans.span("optimize", cat="plan"):
        return ApplyHyperspace(session).apply(plan)


class ApplyHyperspace:
    def __init__(self, session, analysis_enabled: bool = False):
        self.session = session
        self.ctx = RuleContext(session, analysis_enabled)

    def apply(self, plan: L.LogicalPlan) -> L.LogicalPlan:
        try:
            new_plan, _score = self.apply_with_score(plan)
            return new_plan
        except Exception:  # never break a query (ref: ApplyHyperspace.scala:59-63)
            logger.warning("Hyperspace rule application failed; falling back", exc_info=True)
            return plan

    def apply_with_score(self, plan: L.LogicalPlan):
        new_plan, score = self._rewrite(plan)
        if score == 0:
            return plan, 0
        names = used_index_names(new_plan)
        summary = new_plan.describe()
        sp = spans.current_span()
        if sp is not None:
            sp.set(indexes=names, plan=summary, score=score)
        emit_event(
            self.session,
            HyperspaceIndexUsageEvent(index_names=names, plan_summary=summary),
        )
        return new_plan, score

    def _rewrite(self, plan: L.LogicalPlan) -> Tuple[L.LogicalPlan, int]:
        original = plan
        indexes = self.session.index_manager.get_indexes([states.ACTIVE])
        if not indexes:
            return original, 0
        # stash the query's outermost ORDER BY requirement for the rankers:
        # an order-covering index lets the executor eliminate the Sort into a
        # streamed merge of sorted runs (plan/ordering.py), so equal-cost
        # candidates tie-break toward it
        from hyperspace_tpu.plan.ordering import required_ordering

        self.ctx.scratch["required_ordering"] = required_ordering(plan)
        plan, sub_score = self._rewrite_subqueries(plan)
        # normalize: push required columns down to the scans (Catalyst runs
        # ColumnPruning before the reference's rules; this IR does it here)
        from hyperspace_tpu.rules.utils import prune_columns_duplicating

        # per-reference duplication: each join side must be an independent
        # linear sub-plan for the rules to match (a self-join's two sides
        # are one object before this)
        pruned = prune_columns_duplicating(plan)
        with spans.span("collect-candidates", cat="plan") as csp:
            candidates = collect_candidates(self.ctx, pruned, indexes)
            csp.set(candidates=sum(len(ents) for _, ents in candidates.values()))
        if candidates:
            with spans.span("rewrite", cat="plan"):
                new_plan, score = ScoreBasedIndexPlanOptimizer(self.ctx).apply(pruned, candidates)
        else:
            new_plan, score = plan, 0
        if score == 0 and sub_score == 0:
            # nothing rewritten — hand back the untouched user plan so explain
            # shows no spurious diff and execution shape is unchanged
            return original, 0
        return (new_plan if score > 0 else plan), score + sub_score

    # --- subquery recursion ------------------------------------------------
    def _rewrite_subqueries(self, plan: L.LogicalPlan) -> Tuple[L.LogicalPlan, int]:
        """Rebuild Filter conditions whose subquery expressions gain index
        rewrites. Expression and plan nodes are only copied along changed
        paths; untouched subtrees keep their identity (and their tags)."""
        total = 0

        def rewrite_expr(e: Expr) -> Expr:
            nonlocal total
            if isinstance(e, SubqueryExpr):
                new_inner_plan, score = self._rewrite(e.plan)
                new_e = e
                if score > 0:
                    total += score
                    new_e = e.with_plan(new_inner_plan)
                if hasattr(e, "child"):
                    new_child = rewrite_expr(e.child)
                    if new_child is not e.child:
                        if new_e is e:
                            new_e = e.with_plan(e.plan)
                        new_e.child = new_child
                return new_e
            if isinstance(e, BinaryOp):
                nl, nr = rewrite_expr(e.left), rewrite_expr(e.right)
                if nl is not e.left or nr is not e.right:
                    return BinaryOp(e.op, nl, nr)
                return e
            if isinstance(e, Not):
                nc = rewrite_expr(e.child)
                return Not(nc) if nc is not e.child else e
            if isinstance(e, IsNull):
                nc = rewrite_expr(e.child)
                return IsNull(nc) if nc is not e.child else e
            return e

        def walk(p: L.LogicalPlan) -> L.LogicalPlan:
            children = list(p.children())
            new_children = [walk(c) for c in children]
            q = p
            if any(nc is not c for nc, c in zip(new_children, children)):
                q = p.with_children(new_children)
            if isinstance(q, L.Filter):
                new_cond = rewrite_expr(q.condition)
                if new_cond is not q.condition:
                    q = L.Filter(new_cond, q.child)
            return q

        return walk(plan), total
