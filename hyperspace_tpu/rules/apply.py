"""ApplyHyperspace — the optimizer entry point.

Fetch ACTIVE indexes, collect per-scan candidates, run the score-based
rewrite; swallow all exceptions so index application can never break a query
(ref: HS/index/rules/ApplyHyperspace.scala:31-66).
"""

from __future__ import annotations

import logging
from typing import List, Optional

from hyperspace_tpu.models import states
from hyperspace_tpu.plan import logical as L
from hyperspace_tpu.rules.candidate import collect_candidates
from hyperspace_tpu.rules.context import RuleContext
from hyperspace_tpu.rules.score import ScoreBasedIndexPlanOptimizer
from hyperspace_tpu.telemetry.events import HyperspaceIndexUsageEvent, get_event_logger

logger = logging.getLogger(__name__)


class ApplyHyperspace:
    def __init__(self, session, analysis_enabled: bool = False):
        self.session = session
        self.ctx = RuleContext(session, analysis_enabled)

    def apply(self, plan: L.LogicalPlan) -> L.LogicalPlan:
        try:
            new_plan, _score = self.apply_with_score(plan)
            return new_plan
        except Exception:  # never break a query (ref: ApplyHyperspace.scala:59-63)
            logger.warning("Hyperspace rule application failed; falling back", exc_info=True)
            return plan

    def apply_with_score(self, plan: L.LogicalPlan):
        original = plan
        indexes = self.session.index_manager.get_indexes([states.ACTIVE])
        if not indexes:
            return original, 0
        # normalize: push required columns down to the scans (Catalyst runs
        # ColumnPruning before the reference's rules; this IR does it here)
        from hyperspace_tpu.rules.utils import prune_columns

        plan = prune_columns(plan)
        candidates = collect_candidates(self.ctx, plan, indexes)
        if not candidates:
            return original, 0
        new_plan, score = ScoreBasedIndexPlanOptimizer(self.ctx).apply(plan, candidates)
        if score == 0:
            # nothing rewritten — hand back the untouched user plan so explain
            # shows no spurious diff and execution shape is unchanged
            return original, 0
        used = sorted(
            {s.entry.name for s in L.collect(new_plan, lambda p: isinstance(p, L.IndexScan))}
        )
        get_event_logger(self.session).log_event(
            HyperspaceIndexUsageEvent(index_names=used, plan_summary=new_plan.describe())
        )
        return new_plan, score
