"""Per-optimization rule context: session + analysis mode + reason tagging
(the reference uses thread-locals and entry tags;
ref: HS/index/rules/IndexFilter.scala:25-110, JoinIndexRule.scala:632-636).
"""

from __future__ import annotations

from typing import Optional

from hyperspace_tpu.analysis import reasons as R
from hyperspace_tpu.models.log_entry import IndexLogEntry
from hyperspace_tpu.plan.logical import LogicalPlan, plan_key


class RuleContext:
    def __init__(self, session, analysis_enabled: bool = False):
        self.session = session
        self.analysis_enabled = analysis_enabled
        # per-query scratch for rules that cache expensive work across the
        # optimizer's repeated visits (e.g. data-skipping prune results)
        self.scratch = {}

    def tag_reason_if_failed(
        self, passed: bool, entry: IndexLogEntry, plan: LogicalPlan, reason_fn
    ) -> bool:
        """``withFilterReasonTag`` (ref: IndexFilter.scala:36-109): when
        analysis is on and the check failed, append the reason to the entry's
        FILTER_REASONS tag for this (sub)plan."""
        if not passed and self.analysis_enabled:
            key = plan_key(plan)
            existing = entry.get_tag(key, R.FILTER_REASONS) or []
            existing.append(reason_fn())
            entry.set_tag(key, R.FILTER_REASONS, existing)
        return passed

    def tag_applicable_rule(self, entry: IndexLogEntry, plan: LogicalPlan, rule_name: str) -> None:
        if self.analysis_enabled:
            key = plan_key(plan)
            existing = entry.get_tag(key, R.APPLICABLE_INDEX_RULES) or []
            if rule_name not in existing:
                existing.append(rule_name)
            entry.set_tag(key, R.APPLICABLE_INDEX_RULES, existing)
