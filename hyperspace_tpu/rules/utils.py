"""Plan-transformation utilities shared by the covering-index rules
(ref: HS/index/covering/CoveringIndexRuleUtils.scala:55-288).

Two rewrite shapes:

  1. index-only scan — swap the source Scan for an IndexScan over the index's
     bucket files, optionally bucket-pruned (ref: :98-130);
  2. Hybrid Scan — index data + appended source files re-bucketed on the fly,
     merged with BucketUnion; rows from deleted source files are filtered out
     via the lineage column (ref: :146-288).
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from hyperspace_tpu import config as C
from hyperspace_tpu.analysis import reasons as R
from hyperspace_tpu.indexes.covering import CoveringIndex, bucket_of_file
from hyperspace_tpu.models.log_entry import IndexLogEntry
from hyperspace_tpu.plan import logical as L
from hyperspace_tpu.plan.expr import (
    Col,
    Expr,
    In,
    Lit,
    Not,
    extract_eq_literal,
    split_conjunctive,
)
from hyperspace_tpu.rules.context import RuleContext


def destructure_linear(plan: L.LogicalPlan) -> Optional[Tuple[Optional[List[str]], Optional[Expr], L.Scan]]:
    """Match any interleaving of Project / Filter nodes over a Scan; return
    (project_cols, condition, scan) — project_cols is the *outermost*
    projection (the sub-plan's output), condition the AND of all filters
    (the only sub-plan shape the rules accept;
    ref: FilterPlanNodeFilter / JoinPlanNodeFilter linearity checks; column
    pruning may stack an extra Project directly above the Scan)."""
    project_cols = None
    condition = None
    node = plan
    while True:
        if isinstance(node, L.Project):
            if project_cols is None:
                project_cols = list(node.columns)
            node = node.child
        elif isinstance(node, L.Compute):
            # computed columns need their input columns from the scan: swap
            # each computed name in the projection for the expression's
            # references (SQL expression SELECT items plan as Compute)
            exprs = dict(node.exprs)
            if condition is not None and set(condition.references()) & set(exprs):
                return None  # a filter over computed columns can't move below them
            if project_cols is not None:
                resolved: List[str] = []
                for c in project_cols:
                    resolved.extend(sorted(exprs[c].references()) if c in exprs else [c])
                project_cols = list(dict.fromkeys(resolved))
            node = node.child
        elif isinstance(node, L.Filter):
            condition = node.condition if condition is None else condition & node.condition
            node = node.child
        elif isinstance(node, L.Scan):
            return project_cols, condition, node
        else:
            return None


def hybrid_thresholds_ok(ctx: RuleContext, entry: IndexLogEntry, scan: L.Scan) -> bool:
    """Rule-time re-check of the hybrid-scan drift thresholds
    (``hyperspace.index.hybridscan.maxDeletedRatio`` /
    ``maxAppendedRatio``).

    The candidate gate (``candidate._signature_filter``) enforces these at
    collection time, but entries reach the rules through the TTL roster
    cache with tags computed under the conf *of that moment* — and both the
    conf and the source keep moving. Re-derive the byte ratios from the
    current file diff and gate against the current thresholds, so
    tightening a threshold (or drift accumulating past one) takes effect on
    the very next rewrite instead of after the cache expires."""
    conf = ctx.session.conf
    if not entry.get_tag(L.plan_key(scan), R.HYBRIDSCAN_REQUIRED):
        return True  # exact signature match: no drift to gate
    current = {fi.key: fi for fi in scan.relation.all_file_infos()}
    indexed = {fi.key: fi for fi in entry.source_file_infos()}
    appended_bytes = sum(current[k].size for k in current.keys() - indexed.keys())
    deleted_bytes = sum(indexed[k].size for k in indexed.keys() - current.keys())
    # same denominators as candidate._signature_filter
    if deleted_bytes:
        deleted_ratio = deleted_bytes / max(1, entry.source_files_size())
        if deleted_ratio > conf.hybrid_scan_deleted_ratio_threshold:
            ctx.tag_reason_if_failed(
                False, entry, scan,
                lambda: R.too_many_deleted(deleted_ratio, conf.hybrid_scan_deleted_ratio_threshold),
            )
            return False
    if appended_bytes:
        total_bytes = sum(fi.size for fi in current.values())
        appended_ratio = appended_bytes / max(1, total_bytes)
        if appended_ratio > conf.hybrid_scan_appended_ratio_threshold:
            ctx.tag_reason_if_failed(
                False, entry, scan,
                lambda: R.too_many_appended(appended_ratio, conf.hybrid_scan_appended_ratio_threshold),
            )
            return False
    return True


def pruned_buckets_for_predicate(
    condition: Optional[Expr], bucket_columns: Tuple[str, ...], num_buckets: int
) -> Optional[List[int]]:
    """Bucket pruning: an equality (or IN) conjunct on the single bucket
    column narrows the scan to specific buckets
    (ref: FilterIndexRule useBucketSpec, HS/index/covering/FilterIndexRule.scala:162-167)."""
    from hyperspace_tpu.ops.hashing import bucket_of_literals
    from hyperspace_tpu.plan.expr import strip_nested_prefix

    if condition is None or len(bucket_columns) != 1:
        return None
    key = strip_nested_prefix(bucket_columns[0]).lower()
    for term in split_conjunctive(condition):
        eq = extract_eq_literal(term)
        if eq is not None and strip_nested_prefix(eq[0]).lower() == key:
            return [bucket_of_literals([eq[1]], num_buckets)]
        if (
            isinstance(term, In)
            and isinstance(term.child, Col)
            and strip_nested_prefix(term.child.name).lower() == key
        ):
            return sorted({bucket_of_literals([v.value], num_buckets) for v in term.values})
    return None


def index_file_columns(entry: IndexLogEntry, output_cols: List[str]) -> Optional[List[str]]:
    """Map required output names (possibly dotted nested paths) onto the flat
    column names stored in the index files (__hs_nested.-prefixed for nested
    fields). None when every name maps to itself."""
    from hyperspace_tpu.plan.expr import strip_nested_prefix

    props = entry.derived_dataset.properties
    stored = [str(c) for c in props.get("indexedColumns", [])] + [
        str(c) for c in props.get("includedColumns", [])
    ]
    lookup = {strip_nested_prefix(s).lower(): s for s in stored}
    mapped = [lookup.get(strip_nested_prefix(c).lower(), c) for c in output_cols]
    return mapped if mapped != list(output_cols) else None


def index_files_for_buckets(entry: IndexLogEntry, buckets: Optional[List[int]]) -> List[str]:
    files = entry.content.files
    if buckets is None:
        return files
    # bucket ids are parsed from file names once per Content (immutable after
    # load); re-running the regex per query dominated bucket-pruned rewrites
    pairs = entry.content.__dict__.get("_file_buckets")
    if pairs is None or len(pairs) != len(files):
        pairs = entry.content.__dict__["_file_buckets"] = [(f, bucket_of_file(f)) for f in files]
    allowed = set(buckets)
    return [f for f, b in pairs if b in allowed]


def transform_plan_to_use_index(
    ctx: RuleContext,
    entry: IndexLogEntry,
    sub_plan: L.LogicalPlan,
    use_bucket_spec: bool,
) -> L.LogicalPlan:
    """Rewrite a linear sub-plan to scan the covering index instead of the
    source (ref: transformPlanToUseIndex, CoveringIndexRuleUtils.scala:55-83)."""
    parts = destructure_linear(sub_plan)
    assert parts is not None
    project_cols, condition, scan = parts
    required = project_cols if project_cols is not None else scan.output_columns
    if condition is not None:
        cond_refs = [c for c in condition.references()]
        required_all = list(dict.fromkeys(list(required) + cond_refs))
    else:
        required_all = list(required)

    index = CoveringIndex.from_derived_dataset(entry.derived_dataset)
    bucket_spec = index.bucket_spec()
    # an index whose data files were bucketed under an OLDER hash function
    # still serves correct index-only scans, but its bucket PLACEMENT can't
    # be trusted: no bucket pruning, no shuffle-free join layout (the
    # value-consistent-hash fix of round 5 is exactly such a version bump)
    from hyperspace_tpu.indexes.covering import BUCKET_HASH_VERSION

    trusted_layout = index.bucket_hash_version == BUCKET_HASH_VERSION
    use_bucket_spec = use_bucket_spec and trusted_layout
    hybrid = bool(entry.get_tag(L.plan_key(scan), R.HYBRIDSCAN_REQUIRED))
    file_cols = index_file_columns(entry, required_all)

    if not hybrid:
        buckets = (
            pruned_buckets_for_predicate(condition, bucket_spec.bucket_columns, bucket_spec.num_buckets)
            if use_bucket_spec
            else None
        )
        new_scan: L.LogicalPlan = L.IndexScan(
            entry,
            columns=required_all,
            bucket_spec=bucket_spec if use_bucket_spec else None,
            files=index_files_for_buckets(entry, buckets),
            pruned_buckets=buckets,
            file_columns=file_cols,
        )
    else:
        new_scan = _hybrid_scan_plan(
            ctx, entry, scan, required_all, bucket_spec, trusted_layout=trusted_layout
        )

    # canonical rebuild: every Filter sinks DIRECTLY above the scan (the
    # executor's device fast paths match that shape); Project and Compute
    # nodes re-apply above in their original relative order, with Projects
    # narrowed to the columns actually available and no-op Projects elided
    ops = []  # top-down chain ops
    node = sub_plan
    while not isinstance(node, L.Scan):
        if isinstance(node, L.Project):
            ops.append(("project", list(node.columns)))
        elif isinstance(node, L.Compute):
            ops.append(("compute", node.exprs))
        (node,) = node.children()

    out: L.LogicalPlan = new_scan
    if condition is not None:
        out = L.Filter(condition, out)
    for kind, payload in reversed(ops):  # innermost op first
        if kind == "compute":
            out = L.Compute(payload, out)
        else:
            avail = set(out.output_columns)
            cols = [c for c in payload if c in avail]
            if cols != list(out.output_columns):  # elide no-op projections
                out = L.Project(cols, out)
    if set(out.output_columns) != set(sub_plan.output_columns):
        out = L.Project(list(sub_plan.output_columns), out)
    return out


def _hybrid_scan_plan(
    ctx: RuleContext,
    entry: IndexLogEntry,
    scan: L.Scan,
    required: List[str],
    bucket_spec: L.BucketSpec,
    trusted_layout: bool = True,
) -> L.LogicalPlan:
    """Hybrid Scan: BucketUnion(index-minus-deleted, rebucketed-appended)
    (ref: CoveringIndexRuleUtils.scala:146-288)."""
    key = L.plan_key(scan)
    appended: List[str] = entry.get_tag(key, R.HYBRIDSCAN_APPENDED) or []
    deleted: List[str] = entry.get_tag(key, R.HYBRIDSCAN_DELETED) or []

    index_cols = list(required)
    if deleted and C.DATA_FILE_NAME_ID not in index_cols:
        index_cols = index_cols + [C.DATA_FILE_NAME_ID]

    index_side: L.LogicalPlan = L.IndexScan(
        entry,
        columns=index_cols,
        bucket_spec=bucket_spec if trusted_layout else None,
        file_columns=index_file_columns(entry, index_cols),
    )
    if deleted:
        tracker = entry.file_id_tracker()
        deleted_infos = {fi.name: fi for fi in entry.source_file_infos()}
        ids = []
        for name in deleted:
            fi = deleted_infos.get(name)
            if fi is not None and fi.file_id != C.UNKNOWN_FILE_ID:
                ids.append(fi.file_id)
            else:
                fid = next((v for k, v in tracker.file_to_id_map().items() if k[0] == name), None)
                if fid is not None:
                    ids.append(fid)
        # Not(In(_data_file_id, deletedIds)) (ref: :244-253)
        index_side = L.Filter(Not(In(Col(C.DATA_FILE_NAME_ID), [Lit(i) for i in ids])), index_side)
        index_side = L.Project(list(required), index_side)

    if not appended:
        return index_side

    rel = scan.relation
    pv = pd = None
    if getattr(rel, "partition_columns", None):
        pv = {f: rel.partition_values_for(f) for f in appended}
        pd_ = getattr(rel, "partition_dtypes", None)
        pd = dict(pd_) if pd_ else None
    appended_scan = L.FileScan(
        appended, rel.physical_format, list(required), partition_values=pv,
        partition_dtypes=pd, format_options=getattr(rel, "options", None),
    )
    if not trusted_layout:
        # stale bucket-hash version: the files still hold the right ROWS
        # (scan/filter correctness is untouched), but their bucket
        # placement predates the current hash function, so the plan must
        # not advertise a bucketed layout (no shuffle-free joins, no
        # bucket pruning) — a plain Union keeps results correct
        return L.Union([index_side, appended_scan])
    rebucketed = L.Repartition(bucket_spec, appended_scan)
    branches = [index_side, rebucketed]
    return L.BucketUnion(branches, bucket_spec)


def hybrid_coverage_fraction(entry: IndexLogEntry, scan: L.Scan) -> float:
    """commonBytes / currentTotalBytes — scales rule scores under hybrid scan
    (ref: FilterIndexRule score :170-193, JoinIndexRule score :674-704)."""
    key = L.plan_key(scan)
    if not entry.get_tag(key, R.HYBRIDSCAN_REQUIRED):
        return 1.0
    common = entry.get_tag(key, R.COMMON_SOURCE_SIZE_IN_BYTES) or 0
    total = sum(fi.size for fi in scan.relation.all_file_infos())
    return common / max(1, total)


def prune_columns(plan: L.LogicalPlan, needed=None) -> L.LogicalPlan:
    """Column pruning: push the set of columns the parent actually needs down
    to the scans, materialized as a Project directly above each Scan.

    The reference relies on Catalyst's ColumnPruning running *before* its
    rules, so JoinIndexRule sees minimal per-side required columns
    (ref: JoinIndexRule.scala:419-448 allRequiredCols over pruned plans);
    this IR has no separate optimizer, so ApplyHyperspace normalizes first.
    ``needed=None`` means "all columns".

    Sharing-preserving: a sub-plan referenced more than once (a CTE bound
    to one plan object) must remain ONE object after pruning, or the
    executor's shared-subtree memo stops deduplicating and the CTE
    re-executes once per reference. Shared roots act as barriers in a
    first pass that accumulates the UNION of columns every reference
    needs; each is then pruned once and swapped back in by identity.
    """
    shared = shared_subplan_ids(plan)
    if not shared:
        return _prune(plan, needed, None)

    return _prune_shared(plan, needed, shared)


def shared_subplan_ids(plan: L.LogicalPlan) -> set:
    """ids of sub-plans referenced more than once (a CTE bound to one plan
    object) — the single definition of "shared" used by both pruning here
    and the executor's shared-subtree memo."""
    counts: dict = {}

    def walk(p):
        c = counts.get(id(p), 0) + 1
        counts[id(p)] = c
        if c == 1:
            for ch in p.children():
                walk(ch)

    walk(plan)
    return {pid for pid, c in counts.items() if c > 1}


def prune_columns_duplicating(plan: L.LogicalPlan, needed=None) -> L.LogicalPlan:
    """Per-reference pruning: shared sub-plans (self-join sides, CTEs) are
    rebuilt independently per use with each use's own needed-set. This is
    what the INDEX RULES want — each join side must be an independent
    linear sub-plan to match and rewrite — at the cost of the executor's
    shared-subtree dedup. ApplyHyperspace uses this before rule matching;
    the executor's own pass uses the sharing-preserving prune_columns."""
    return _prune(plan, needed, None)


def _prune_shared(plan: L.LogicalPlan, needed, shared) -> L.LogicalPlan:

    acc: dict = {}  # id(shared node) -> union of needed sets (None = all)

    def note(p, need):
        if id(p) in acc:
            prev = acc[id(p)]
            acc[id(p)] = None if (need is None or prev is None) else prev | set(need)
        else:
            acc[id(p)] = None if need is None else set(need)

    top = _prune(plan, needed, (shared, note))
    if not acc:
        return top
    # prune each shared root with its accumulated union, to a FIXPOINT:
    # pruning one shared node can record new needs for another (a CTE that
    # reads a second CTE, in either tree order), so keep re-pruning any
    # node whose union grew since it was last pruned. Unions only grow and
    # are bounded by the column sets, so this terminates.
    preorder: list = []
    seen: set = set()

    def pre(p):
        if id(p) in seen:
            return
        seen.add(id(p))
        preorder.append(p)
        for ch in p.children():
            pre(ch)

    pre(plan)

    def frozen(s):
        return None if s is None else frozenset(s)

    replaced: dict = {}
    pruned_with: dict = {}
    while True:
        stale = [
            n for n in preorder
            if id(n) in acc and pruned_with.get(id(n), ()) != frozen(acc[id(n)])
        ]
        if not stale:
            break
        for node in stale:
            key = frozen(acc[id(node)])
            replaced[id(node)] = _prune(node, acc[id(node)], (shared, note), skip_self=True)
            pruned_with[id(node)] = key
    # swap pruned shared roots back in, preserving identity (memo by id).
    # A pruned shared node often CONTAINS its original (a barrier'd Scan
    # prunes to Project(cols, scan)); the in_progress guard keeps that
    # self-reference pointing at the original instead of recursing forever.
    memo: dict = {}
    in_progress: set = set()

    def swap(p):
        got = memo.get(id(p))
        if got is not None:
            return got
        if id(p) in in_progress:
            return p
        res = replaced.get(id(p), p)
        if res is p:
            new_children = [swap(ch) for ch in p.children()]
            if any(n is not o for n, o in zip(new_children, p.children())):
                res = p.with_children(new_children)
        else:
            in_progress.add(id(p))
            try:
                inner_children = [swap(ch) for ch in res.children()]
                if any(n is not o for n, o in zip(inner_children, res.children())):
                    res = res.with_children(inner_children)
            finally:
                in_progress.discard(id(p))
        memo[id(p)] = res
        return res

    return swap(top)


def _prune(plan: L.LogicalPlan, needed, barrier, skip_self: bool = False) -> L.LogicalPlan:
    if barrier is not None and not skip_self and id(plan) in barrier[0]:
        barrier[1](plan, needed)
        return plan  # shared root: record needs, prune later, keep identity
    if isinstance(plan, L.Project):
        child_needed = set()
        for c in plan.columns:
            child_needed.add(c)
        return L.Project(plan.columns, _prune(plan.child, child_needed, barrier))
    if isinstance(plan, L.Filter):
        child_needed = None if needed is None else set(needed) | set(plan.condition.references())
        (child,) = plan.children()
        return plan.with_children([_prune(child, child_needed, barrier)])
    if isinstance(plan, L.Compute):
        # a computed column needs its expression's inputs instead of itself
        if needed is None:
            child_needed = None
        else:
            exprs = dict(plan.exprs)
            child_needed = set()
            for c in needed:
                if c in exprs:
                    child_needed |= exprs[c].references()
                else:
                    child_needed.add(c)
        (child,) = plan.children()
        return plan.with_children([_prune(child, child_needed, barrier)])
    if isinstance(plan, L.Join):
        left_cols = set(plan.left.output_columns)
        right_cols = set(plan.right.output_columns)

        from hyperspace_tpu.plan.expr import column_root_member

        def on_side(c: str, side: set):
            # a dotted nested ref belongs to the side holding its root
            # struct; the RESOLVED (exact-cased) name is what the scans can
            # actually keep, so that is what gets recorded as needed
            return column_root_member(c, side)

        if needed is None:
            l_needed = r_needed = None
        else:
            def keep_renamed(c, l_needed, r_needed):
                # join_output_names repeats the '#r' suffix until unique, so a
                # doubly-renamed 'x#r#r' needs iterative stripping to find the
                # right-side source column. The rename is positional: it only
                # reproduces at execution if the LEFT side still emits every
                # shorter name in the chain ('x', 'x#r', ...), so keep those
                # too — pruning one would shift the suffix count.
                base, chain = c, []
                while base.endswith("#r"):
                    chain.append(base[:-2])
                    base = base[:-2]
                    if base in right_cols:
                        r_needed.add(base)
                        l_needed.update(x for x in chain if x in left_cols)
                        return True
                return False

            l_needed, r_needed = set(), set()
            for c in needed:
                # LEFT membership first: join_output_names passes left names
                # through verbatim, so an 'x#r' that exists on the left IS a
                # left column (a lower join's rename product) — the right
                # side's colliding 'x' renames PAST it to 'x#r#r'. Chain-
                # stripping first would misattribute it to the right side
                # (and mis-prune a 3-way join with thrice-repeated names).
                lr = on_side(c, left_cols)
                if lr is not None:
                    l_needed.add(lr)
                    continue
                if keep_renamed(c, l_needed, r_needed):
                    continue
                rr = on_side(c, right_cols)
                if rr is not None:
                    r_needed.add(rr)
            cond_refs = set(plan.condition.references())
            if plan.residual is not None:
                # residual refs use post-join names: map '#r' back to the
                # right-side source column like the needed loop above
                # (left-first, same reasoning)
                for c in plan.residual.references():
                    if on_side(c, left_cols) is not None:
                        cond_refs.add(c)
                    elif not keep_renamed(c, l_needed, r_needed):
                        cond_refs.add(c)
            for c in cond_refs:
                lr = on_side(c, left_cols)
                if lr is not None:
                    l_needed.add(lr)
                rr = on_side(c, right_cols)
                if rr is not None:
                    r_needed.add(rr)
        return L.Join(
            _prune(plan.left, l_needed, barrier),
            _prune(plan.right, r_needed, barrier),
            plan.condition,
            plan.how,
            plan.residual,
            plan.using_pairs,
        )
    if isinstance(plan, L.Scan):
        out = plan.output_columns
        if needed is None:
            return plan
        out_set = set(out)
        flat = {c for c in needed if c in out_set}
        # dotted refs survive pruning as their own projected columns (the
        # reference relies on Catalyst extracting nested field accesses)
        dotted = {c for c in needed if c not in out_set and "." in c and c.split(".")[0] in out_set}
        if not flat and not dotted:
            # a count(*)-only consumer needs the ROW COUNT: a zero-column
            # scan would report zero rows, so keep the narrowest thing we
            # have (Catalyst keeps a cheapest column here too)
            flat = {out[0]} if out else set()
        if flat | {d.split(".")[0] for d in dotted} < out_set or dotted:
            ordered = [c for c in out if c in flat] + sorted(dotted)
            if set(ordered) != out_set:
                return L.Project(ordered, plan)
        return plan
    if isinstance(plan, L.Union):
        return plan.with_children([_prune(c, needed, barrier) for c in plan.children()])
    if isinstance(plan, L.Aggregate):
        child_needed = set(plan.keys) | {c for _, _, c in plan.aggs if c is not None}
        (child,) = plan.children()
        return plan.with_children([_prune(child, child_needed, barrier)])
    if isinstance(plan, L.Window):
        produced = {s[0] for s in plan.specs}
        operands = set()
        for _out, _fn, arg, parts, orders, _cum in plan.specs:
            if arg is not None:
                operands.add(arg)
            operands |= set(parts)
            operands |= {c for c, _ in orders}
        child_needed = (
            None if needed is None else ({c for c in needed if c not in produced} | operands)
        )
        (child,) = plan.children()
        return plan.with_children([_prune(child, child_needed, barrier)])
    if isinstance(plan, L.Sort):
        child_needed = None if needed is None else set(needed) | {c for c, _ in plan.keys}
        (child,) = plan.children()
        return plan.with_children([_prune(child, child_needed, barrier)])
    if isinstance(plan, L.Limit):
        (child,) = plan.children()
        return plan.with_children([_prune(child, needed, barrier)])
    if isinstance(plan, L.Rename):
        inverse = {v: k for k, v in plan.mapping.items()}
        child_needed = None if needed is None else {inverse.get(c, c) for c in needed}
        (child,) = plan.children()
        return plan.with_children([_prune(child, child_needed, barrier)])
    # unknown node (set ops compare WHOLE rows, repartition/bucket-union
    # pass rows through): children keep all their columns, but still
    # recurse — nested Projects prune their own subtrees, and shared
    # sub-plans MUST be noted here or the sharing swap would substitute
    # replacements pruned for other (narrower) uses of the same object
    new_children = [_prune(c, None, barrier) for c in plan.children()]
    if any(n is not o for n, o in zip(new_children, plan.children())):
        return plan.with_children(new_children)
    return plan
