"""Plan-transformation utilities shared by the covering-index rules
(ref: HS/index/covering/CoveringIndexRuleUtils.scala:55-288).

Two rewrite shapes:

  1. index-only scan — swap the source Scan for an IndexScan over the index's
     bucket files, optionally bucket-pruned (ref: :98-130);
  2. Hybrid Scan — index data + appended source files re-bucketed on the fly,
     merged with BucketUnion; rows from deleted source files are filtered out
     via the lineage column (ref: :146-288).
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from hyperspace_tpu import config as C
from hyperspace_tpu.analysis import reasons as R
from hyperspace_tpu.indexes.covering import CoveringIndex, bucket_of_file
from hyperspace_tpu.models.log_entry import IndexLogEntry
from hyperspace_tpu.plan import logical as L
from hyperspace_tpu.plan.expr import (
    Col,
    Expr,
    In,
    Lit,
    Not,
    extract_eq_literal,
    split_conjunctive,
)
from hyperspace_tpu.rules.context import RuleContext


def destructure_linear(plan: L.LogicalPlan) -> Optional[Tuple[Optional[List[str]], Optional[Expr], L.Scan]]:
    """Match [Project] -> [Filter] -> Scan; return (project_cols, condition, scan)
    (the only sub-plan shape the rules accept;
    ref: FilterPlanNodeFilter / JoinPlanNodeFilter linearity checks)."""
    project_cols = None
    condition = None
    node = plan
    if isinstance(node, L.Project):
        project_cols = list(node.columns)
        node = node.child
    if isinstance(node, L.Filter):
        condition = node.condition
        node = node.child
    if isinstance(node, L.Scan):
        return project_cols, condition, node
    return None


def pruned_buckets_for_predicate(
    condition: Optional[Expr], bucket_columns: Tuple[str, ...], num_buckets: int
) -> Optional[List[int]]:
    """Bucket pruning: an equality (or IN) conjunct on the single bucket
    column narrows the scan to specific buckets
    (ref: FilterIndexRule useBucketSpec, HS/index/covering/FilterIndexRule.scala:162-167)."""
    from hyperspace_tpu.ops.hashing import bucket_of_literals

    if condition is None or len(bucket_columns) != 1:
        return None
    key = bucket_columns[0].lower()
    for term in split_conjunctive(condition):
        eq = extract_eq_literal(term)
        if eq is not None and eq[0].lower() == key:
            return [bucket_of_literals([eq[1]], num_buckets)]
        if isinstance(term, In) and isinstance(term.child, Col) and term.child.name.lower() == key:
            return sorted({bucket_of_literals([v.value], num_buckets) for v in term.values})
    return None


def index_files_for_buckets(entry: IndexLogEntry, buckets: Optional[List[int]]) -> List[str]:
    files = entry.content.files
    if buckets is None:
        return files
    allowed = set(buckets)
    return [f for f in files if bucket_of_file(f) in allowed]


def transform_plan_to_use_index(
    ctx: RuleContext,
    entry: IndexLogEntry,
    sub_plan: L.LogicalPlan,
    use_bucket_spec: bool,
) -> L.LogicalPlan:
    """Rewrite a linear sub-plan to scan the covering index instead of the
    source (ref: transformPlanToUseIndex, CoveringIndexRuleUtils.scala:55-83)."""
    parts = destructure_linear(sub_plan)
    assert parts is not None
    project_cols, condition, scan = parts
    required = project_cols if project_cols is not None else scan.output_columns
    if condition is not None:
        cond_refs = [c for c in condition.references()]
        required_all = list(dict.fromkeys(list(required) + cond_refs))
    else:
        required_all = list(required)

    index = CoveringIndex.from_derived_dataset(entry.derived_dataset)
    bucket_spec = index.bucket_spec()
    hybrid = bool(entry.get_tag(L.plan_key(scan), R.HYBRIDSCAN_REQUIRED))

    if not hybrid:
        buckets = (
            pruned_buckets_for_predicate(condition, bucket_spec.bucket_columns, bucket_spec.num_buckets)
            if use_bucket_spec
            else None
        )
        new_scan: L.LogicalPlan = L.IndexScan(
            entry,
            columns=required_all,
            bucket_spec=bucket_spec if use_bucket_spec else None,
            files=index_files_for_buckets(entry, buckets),
            pruned_buckets=buckets,
        )
    else:
        new_scan = _hybrid_scan_plan(ctx, entry, scan, required_all, bucket_spec)

    out: L.LogicalPlan = new_scan
    if condition is not None:
        out = L.Filter(condition, out)
    if project_cols is not None or set(out.output_columns) != set(required):
        out = L.Project(list(required), out)
    return out


def _hybrid_scan_plan(
    ctx: RuleContext,
    entry: IndexLogEntry,
    scan: L.Scan,
    required: List[str],
    bucket_spec: L.BucketSpec,
) -> L.LogicalPlan:
    """Hybrid Scan: BucketUnion(index-minus-deleted, rebucketed-appended)
    (ref: CoveringIndexRuleUtils.scala:146-288)."""
    key = L.plan_key(scan)
    appended: List[str] = entry.get_tag(key, R.HYBRIDSCAN_APPENDED) or []
    deleted: List[str] = entry.get_tag(key, R.HYBRIDSCAN_DELETED) or []

    index_cols = list(required)
    if deleted and C.DATA_FILE_NAME_ID not in index_cols:
        index_cols = index_cols + [C.DATA_FILE_NAME_ID]

    index_side: L.LogicalPlan = L.IndexScan(entry, columns=index_cols, bucket_spec=bucket_spec)
    if deleted:
        tracker = entry.file_id_tracker()
        deleted_infos = {fi.name: fi for fi in entry.source_file_infos()}
        ids = []
        for name in deleted:
            fi = deleted_infos.get(name)
            if fi is not None and fi.file_id != C.UNKNOWN_FILE_ID:
                ids.append(fi.file_id)
            else:
                fid = next((v for k, v in tracker.file_to_id_map().items() if k[0] == name), None)
                if fid is not None:
                    ids.append(fid)
        # Not(In(_data_file_id, deletedIds)) (ref: :244-253)
        index_side = L.Filter(Not(In(Col(C.DATA_FILE_NAME_ID), [Lit(i) for i in ids])), index_side)
        index_side = L.Project(list(required), index_side)

    if not appended:
        return index_side

    appended_scan = L.FileScan(appended, scan.relation.physical_format, list(required))
    rebucketed = L.Repartition(bucket_spec, appended_scan)
    branches = [index_side, rebucketed]
    return L.BucketUnion(branches, bucket_spec)


def hybrid_coverage_fraction(entry: IndexLogEntry, scan: L.Scan) -> float:
    """commonBytes / currentTotalBytes — scales rule scores under hybrid scan
    (ref: FilterIndexRule score :170-193, JoinIndexRule score :674-704)."""
    key = L.plan_key(scan)
    if not entry.get_tag(key, R.HYBRIDSCAN_REQUIRED):
        return 1.0
    common = entry.get_tag(key, R.COMMON_SOURCE_SIZE_IN_BYTES) or 0
    total = sum(fi.size for fi in scan.relation.all_file_infos())
    return common / max(1, total)
