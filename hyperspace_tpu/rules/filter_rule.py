"""FilterIndexRule.

Replace Project→Filter→Scan (or Filter→Scan) over source files with a scan of
a covering index, when:
  - the first indexed column appears in the filter predicate, and
  - the index covers every column the sub-plan needs
(ref: HS/index/covering/FilterIndexRule.scala:34-194 — FilterPlanNodeFilter,
FilterColumnFilter, FilterRankFilter; score :170-193).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from hyperspace_tpu.analysis import reasons as R
from hyperspace_tpu.models.log_entry import IndexLogEntry
from hyperspace_tpu.plan import logical as L
from hyperspace_tpu.rules.context import RuleContext
from hyperspace_tpu.rules.utils import (
    destructure_linear,
    hybrid_coverage_fraction,
    hybrid_thresholds_ok,
    transform_plan_to_use_index,
)

RULE_NAME = "FilterIndexRule"
# ceiling of the 50 x coverage score below (see score.py short-circuit)
MAX_SCORE = 50


def _filter_column_filter(
    ctx: RuleContext,
    scan: L.Scan,
    condition,
    required: List[str],
    candidates: List[IndexLogEntry],
) -> List[IndexLogEntry]:
    """(ref: FilterColumnFilter — first indexed col must appear in the
    predicate; index covers filter+project columns)."""
    from hyperspace_tpu.plan.expr import strip_nested_prefix

    out = []
    # nested refs/index columns compare on their un-prefixed dotted path
    pred_cols = {strip_nested_prefix(c).lower() for c in condition.references()}
    for entry in candidates:
        if entry.kind != "CoveringIndex":
            continue
        props = entry.derived_dataset.properties
        indexed = [str(c) for c in props.get("indexedColumns", [])]
        included = [str(c) for c in props.get("includedColumns", [])]
        first_ok = bool(indexed) and strip_nested_prefix(indexed[0]).lower() in pred_cols
        if not ctx.tag_reason_if_failed(
            first_ok, entry, scan, lambda: R.no_first_indexed_col_cond(indexed[0] if indexed else "", pred_cols)
        ):
            continue
        covered = {strip_nested_prefix(c).lower() for c in indexed + included}
        covers = all(strip_nested_prefix(c).lower() in covered for c in required)
        if not ctx.tag_reason_if_failed(
            covers, entry, scan, lambda: R.missing_required_col(required, indexed + included)
        ):
            continue
        if not hybrid_thresholds_ok(ctx, entry, scan):
            continue
        out.append(entry)
    return out


def _order_covers(entry: IndexLogEntry, required) -> bool:
    """Does the entry's within-bucket sort order (= its indexed columns)
    satisfy the query's ORDER BY requirement? Only all-ascending key lists
    that prefix the indexed columns qualify (plan/ordering's eligibility)."""
    if not required:
        return False
    if any(not asc for _, asc in required):
        return False
    props = entry.derived_dataset.properties
    indexed = [str(c).lower() for c in props.get("indexedColumns", [])]
    want = [str(c).lower() for c, _ in required]
    return indexed[: len(want)] == want


def _rank(ctx: RuleContext, scan: L.Scan, candidates: List[IndexLogEntry]) -> Optional[IndexLogEntry]:
    """FilterRankFilter: smallest index; under hybrid scan, largest common
    bytes (ref: HS/index/covering/FilterIndexRanker.scala:43-63). Equal-size
    candidates tie-break toward one whose sort order covers the query's
    ORDER BY (stashed by ApplyHyperspace), which unlocks the executor's
    sort-elimination merge — order-awareness never overrides the size rank,
    so reference ranking (and approved-plan goldens) are unchanged."""
    if not candidates:
        return None
    required = ctx.scratch.get("required_ordering")
    if ctx.session.conf.hybrid_scan_enabled:
        best = max(
            candidates,
            key=lambda e: (e.get_tag(L.plan_key(scan), R.COMMON_SOURCE_SIZE_IN_BYTES) or 0, -e.content.total_size),
        )
    else:
        best = min(
            candidates,
            key=lambda e: (e.content.total_size, not _order_covers(e, required), e.name),
        )
    if ctx.analysis_enabled:
        for e in candidates:
            if e is not best:
                ctx.tag_reason_if_failed(False, e, scan, lambda: R.another_index_applied(best.name))
    return best


def apply_filter_index_rule(
    ctx: RuleContext,
    plan: L.LogicalPlan,
    candidates: Dict[int, Tuple[L.Scan, List[IndexLogEntry]]],
) -> Tuple[L.LogicalPlan, int]:
    """Try to apply at ``plan``; returns (possibly-rewritten plan, score)."""
    parts = destructure_linear(plan)
    if parts is None:
        return plan, 0
    project_cols, condition, scan = parts
    if condition is None:
        return plan, 0  # FilterIndexRule requires a Filter node
    from hyperspace_tpu.plan.expr import contains_input_file_name

    if contains_input_file_name(condition):
        return plan, 0  # rewrite would change input_file_name() semantics
    key = L.plan_key(scan)
    if key not in candidates:
        return plan, 0
    _, entries = candidates[key]
    required_out = project_cols if project_cols is not None else scan.output_columns
    required = list(dict.fromkeys(list(required_out) + list(condition.references())))

    eligible = _filter_column_filter(ctx, scan, condition, required, entries)
    best = _rank(ctx, scan, eligible)
    if best is None:
        return plan, 0
    ctx.tag_applicable_rule(best, scan, RULE_NAME)

    new_plan = transform_plan_to_use_index(ctx, best, plan, ctx.session.conf.use_bucket_spec)
    score = int(50 * hybrid_coverage_fraction(best, scan))
    return new_plan, max(score, 1)
