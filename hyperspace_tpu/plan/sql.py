"""SQL front-end over the relational IR.

The reference's users drive Hyperspace through Spark SQL; this module gives
the same entry point without Spark: ``session.sql("SELECT ...")`` parses a
dialect covering the plan shapes the optimizer rules accept — linear scans,
CNF equi-joins, filters/projects/aggregates (ref: JoinPlanNodeFilter's own
restrictions, HS/index/covering/JoinIndexRule.scala:135-155) — and plans it
onto DataFrame operations, so every index rewrite, explain, and whyNot
surface applies to SQL queries unchanged.

Supported grammar (case-insensitive keywords) — the dialect covers the full
TPC-H 22 and TPC-DS 103 texts (tests/test_tpch_oracles.py,
tests/test_tpcds_oracles.py run them against pandas ground truth):

    [WITH name AS ( query ) [, name AS ( query )]*]
    SELECT [DISTINCT] <*| item [, item ...]>
    FROM <view | ( query )> [AS] [alias] [, <view> [alias]]*
    [ [INNER|LEFT|RIGHT|FULL] [OUTER] JOIN <view|(query)> [alias]
      ON <predicate, incl. non-equi residuals> ]*
    [WHERE <predicate>]
    [GROUP BY expr [, ...] | ROLLUP(...) | CUBE(...) | GROUPING SETS(...)]
    [HAVING <predicate, incl. subqueries>]
    [ORDER BY expr [ASC|DESC] [, ...]]      -- may reference non-projected cols
    [LIMIT n]
    query UNION [ALL] | INTERSECT | EXCEPT query   -- INTERSECT binds tighter

    item := expr [AS name]
    expr := comparisons (=, !=, <>, <, <=, >, >=), IN (...) / NOT IN,
            IN ( SELECT ... ) (null-aware), EXISTS ( SELECT ... ),
            ( SELECT ... ) scalar subqueries — correlated or not,
            IS [NOT] NULL, [NOT] BETWEEN x AND y, [NOT] LIKE 'pat%',
            NOT/AND/OR, arithmetic (+ - * / %), CASE WHEN ... END,
            CAST(expr AS type), EXTRACT(field FROM expr), grouping(col),
            SUM|MIN|MAX|AVG|COUNT([DISTINCT] expr | *), STDDEV[_SAMP],
            window functions: agg(expr) OVER (PARTITION BY ... ORDER BY ...
              [ROWS UNBOUNDED PRECEDING .. CURRENT ROW]),
              RANK() / DENSE_RANK() / ROW_NUMBER() OVER (...),
            literals: 123, 1.5, 'text', DATE '2024-01-31',
              INTERVAL 'n' DAY|MONTH|YEAR

Correlated subqueries (scalar, IN, EXISTS) are decorrelated into joins /
semi-join marks (plan/decorrelate.py) — the reference's golden scenario
(src/test/resources/expected/spark-3.1/subquery.txt) only exercises the
uncorrelated forms, but TPC-DS needs the general case (q1, q6, q30, q32,
q41, q81, q92 correlated-scalar; q16, q94 null-aware NOT EXISTS). Everything
plans onto the same ScalarSubquery/InSubquery/Join IR the dataframe API
builds, so every index rewrite, explain, and whyNot surface applies inside
subqueries unchanged (rules/apply.py recursion).
"""

from __future__ import annotations

import itertools
import re
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from hyperspace_tpu.plan.expr import (
    BinaryOp,
    Col,
    Expr,
    In,
    IsNull,
    Lit,
    Not,
    col,
    lit,
)


class SqlError(ValueError):
    pass


_TOKEN_RE = re.compile(
    r"""
    \s*(
        (?P<string>'(?:[^']|'')*')
      | (?P<number>\d+\.\d+|\.\d+|\d+)
      | (?P<bq>`[^`]*`)
      | (?P<ident>[A-Za-z_][A-Za-z0-9_]*(?:\.[A-Za-z_][A-Za-z0-9_]*)*)
      | (?P<op><=|>=|<>|!=|=|<|>|\(|\)|,|\*|\+|-|/|%|\|\|)
    )""",
    re.VERBOSE,
)

_KEYWORDS = {
    "select", "distinct", "from", "where", "group", "by", "having", "order", "limit", "join", "on",
    "inner", "left", "right", "full", "outer", "and", "or", "not", "in", "is",
    "null", "between", "as", "asc", "desc", "date", "count", "sum", "min",
    "max", "avg", "with", "case", "when", "then", "else", "end", "like",
    "union", "all", "exists", "interval", "cast", "over", "rollup",
    "intersect", "except",
}

#: OVER-clause words matched contextually (NOT reserved: a column named
#: "partition" or "row" stays a valid identifier everywhere else)
_OVER_WORDS = {"partition", "rows", "unbounded", "preceding", "current", "row"}

#: window-only function names (tokenize as plain identifiers)
_WINDOW_FNS = {"rank", "dense_rank", "row_number"}

# aggregate functions that tokenize as plain identifiers (not keywords)
_IDENT_AGGS = {"stddev_samp": "stddev_samp", "stddev": "stddev_samp"}

_AGG_FNS = ("count", "sum", "min", "max", "avg")


def _tokenize(text: str) -> List[Tuple[str, str]]:
    out: List[Tuple[str, str]] = []
    pos = 0
    while pos < len(text):
        if text[pos].isspace():
            pos += 1
            continue
        m = _TOKEN_RE.match(text, pos)
        if not m or m.start(1) != pos:
            raise SqlError(f"Cannot tokenize SQL at: {text[pos:pos+30]!r}")
        pos = m.end(1)
        if m.group("ident") is not None:
            word = m.group("ident")
            if "." not in word and word.lower() in _KEYWORDS:
                out.append(("kw", word.lower()))
            else:
                out.append(("ident", word))
        elif m.group("bq") is not None:
            out.append(("ident", m.group("bq")[1:-1]))
        elif m.group("string") is not None:
            out.append(("string", m.group("string")[1:-1].replace("''", "'")))
        elif m.group("number") is not None:
            out.append(("number", m.group("number")))
        else:
            out.append(("op", m.group("op")))
    return out


class _Parser:
    def __init__(self, tokens: List[Tuple[str, str]]):
        self.toks = tokens
        self.i = 0

    def peek(self, k: int = 0) -> Optional[Tuple[str, str]]:
        j = self.i + k
        return self.toks[j] if j < len(self.toks) else None

    def next(self) -> Tuple[str, str]:
        if self.i >= len(self.toks):
            raise SqlError("Unexpected end of SQL")
        t = self.toks[self.i]
        self.i += 1
        return t

    def accept_kw(self, *words: str) -> Optional[str]:
        t = self.peek()
        if t is not None and t[0] == "kw" and t[1] in words:
            self.i += 1
            return t[1]
        return None

    def expect_kw(self, word: str) -> None:
        if self.accept_kw(word) is None:
            raise SqlError(f"Expected {word.upper()} at {self._where()}")

    def accept_op(self, *ops: str) -> Optional[str]:
        t = self.peek()
        if t is not None and t[0] == "op" and t[1] in ops:
            self.i += 1
            return t[1]
        return None

    def expect_op(self, op: str) -> None:
        if self.accept_op(op) is None:
            raise SqlError(f"Expected {op!r} at {self._where()}")

    def expect_ident(self) -> str:
        t = self.next()
        if t[0] != "ident":
            raise SqlError(f"Expected identifier, got {t[1]!r}")
        return t[1]

    def _where(self) -> str:
        return " ".join(t[1] for t in self.toks[self.i : self.i + 4]) or "<end>"

    def at_end(self) -> bool:
        return self.i >= len(self.toks)

    def text_since(self, start: int) -> str:
        parts = []
        for kind, val in self.toks[start : self.i]:
            parts.append(f"'{val}'" if kind == "string" else val)
        return " ".join(parts)


# --- AST ------------------------------------------------------------------


class _AggCall(Expr):
    """Parse-time aggregate call marker (``SUM(expr)`` / ``COUNT(*)``);
    plan_query replaces it with a reference to an Aggregate output. Never
    evaluated."""

    def __init__(self, fn: str, arg: Optional[Expr], text: str):
        self.fn = fn
        self.arg = arg
        self.text = text  # source text of the argument, for default naming

    def children(self) -> Sequence[Expr]:
        return (self.arg,) if self.arg is not None else ()

    def eval(self, batch):
        raise SqlError(f"Aggregate {self.fn.upper()}() outside of an aggregation context")

    def __repr__(self) -> str:
        return f"{self.fn}({self.text})"


class _WindowCall(Expr):
    """Parse-time window-function marker (``fn(arg) OVER (...)``);
    plan_query replaces it with a reference to a Window node output."""

    def __init__(self, fn: str, arg: Optional[Expr], partition, orders, cumulative: bool, text: str):
        self.fn = fn
        self.arg = arg
        self.partition = list(partition)  # List[Expr]
        self.orders = list(orders)  # List[(Expr, asc)]
        self.cumulative = cumulative
        self.text = text

    def children(self) -> Sequence[Expr]:
        out = list(self.partition) + [e for e, _ in self.orders]
        if self.arg is not None:
            out.append(self.arg)
        return tuple(out)

    def eval(self, batch):
        raise SqlError(f"Unplanned window function {self.fn}()")

    def __repr__(self) -> str:
        return f"{self.fn}({self.text}) over (...)"


class _GroupingCall(Expr):
    """Parse-time ``grouping(col)`` marker (ROLLUP indicator: 1 when the
    column is rolled up in this output row, else 0)."""

    def __init__(self, arg: Expr, text: str):
        self.arg = arg
        self.text = text

    def children(self) -> Sequence[Expr]:
        return (self.arg,)

    def eval(self, batch):
        raise SqlError("grouping() outside of a ROLLUP context")

    def __repr__(self) -> str:
        return f"grouping({self.text})"


class _SubquerySelect(Expr):
    """Parse-time scalar-subquery marker (``( SELECT ... )``); plan_query
    plans the inner query and replaces this with a ScalarSubquery."""

    def __init__(self, query: "Query"):
        self.query = query

    def eval(self, batch):
        raise SqlError("Unplanned scalar subquery")

    def __repr__(self) -> str:
        return "(<subquery>)"


class _InQuery(Expr):
    """Parse-time ``expr IN ( SELECT ... )`` marker; plan_query plans the
    inner query and replaces this with an InSubquery."""

    def __init__(self, child: Expr, query: "Query"):
        self.child = child
        self.query = query

    def children(self) -> Sequence[Expr]:
        return (self.child,)

    def eval(self, batch):
        raise SqlError("Unplanned IN subquery")

    def __repr__(self) -> str:
        return f"({self.child!r} IN <subquery>)"


class _ExistsQuery(Expr):
    """Parse-time ``EXISTS ( SELECT ... )`` marker; binding decorrelates the
    inner query into an ExistsSubquery semi-join mark (NOT EXISTS rides the
    ordinary Not wrapper — EXISTS is two-valued, never unknown)."""

    def __init__(self, query: "Query"):
        self.query = query

    def eval(self, batch):
        raise SqlError("Unplanned EXISTS subquery")

    def __repr__(self) -> str:
        return "EXISTS(<subquery>)"


class SelectItem:
    def __init__(self, expr: Expr, alias: Optional[str], text: str):
        self.expr = expr
        self.alias = alias
        self.text = text  # source text, the default output name for expressions

    # -- parse-level introspection kept for compatibility ------------------
    @property
    def name(self) -> Optional[str]:
        """Column name when the item is a bare (possibly qualified) column."""
        return self.expr.name if isinstance(self.expr, Col) else None

    @property
    def agg(self) -> Optional[Tuple[str, Optional[str]]]:
        """(fn, column-or-None) when the item is a bare aggregate of a bare
        column (or COUNT(*))."""
        if isinstance(self.expr, _AggCall):
            a = self.expr.arg
            if a is None:
                return (self.expr.fn, None)
            if isinstance(a, Col):
                return (self.expr.fn, a.name)
        return None


class JoinClause:
    def __init__(self, table_ref: "TableRef", how: str, on: Expr):
        self.table_ref = table_ref
        self.how = how
        self.on = on  # full ON-clause expression (equi links extracted at plan time)

    @property
    def view(self):
        return self.table_ref.source

    @property
    def alias(self) -> str:
        return self.table_ref.alias


class TableRef:
    """A FROM-clause entry: a named view or a derived table (sub-select)."""

    def __init__(self, source, alias: str):
        self.source = source  # str view name | Query (derived table)
        self.alias = alias


class FromElement:
    """One comma-separated FROM element: a table ref plus any JOIN ... ON
    clauses chained directly onto it (TPC-DS mixes both styles:
    ``FROM a LEFT JOIN b ON (...), c, d``)."""

    def __init__(self, table_ref: TableRef, joins: List["JoinClause"]):
        self.table_ref = table_ref
        self.joins = joins


class Query:
    def __init__(self):
        self.ctes: List[Tuple[str, "Query"]] = []
        self.items: Optional[List[SelectItem]] = None  # None = SELECT *
        self.distinct = False
        self.from_elements: List[FromElement] = []
        self.where: Optional[Expr] = None
        self.group_by: List[str] = []
        self.group_sets: Optional[List[Tuple[int, ...]]] = None  # ROLLUP/CUBE/GROUPING SETS
        self.having: Optional[Expr] = None
        self.order_by: List[Tuple[Any, bool]] = []  # (column name | Expr, asc)
        self.limit: Optional[int] = None
        # set-operation chain: ("union", all?, rhs) | ("intersect"/"except", False, rhs)
        self.unions: List[Tuple[str, bool, "Query"]] = []

    # -- compatibility accessors (single-table queries) --------------------
    @property
    def table(self):
        return self.from_elements[0].table_ref.source if self.from_elements else ""

    @property
    def alias(self) -> str:
        return self.from_elements[0].table_ref.alias if self.from_elements else ""

    @property
    def joins(self) -> List["JoinClause"]:
        return [j for e in self.from_elements for j in e.joins]


def parse(text: str) -> Query:
    p = _Parser(_tokenize(text))
    ctes: List[Tuple[str, Query]] = []
    if p.accept_kw("with"):
        while True:
            name = p.expect_ident()
            p.expect_kw("as")
            p.expect_op("(")
            ctes.append((name, _parse_query(p)))
            p.expect_op(")")
            if not p.accept_op(","):
                break
    q = _parse_query(p)
    q.ctes = ctes
    if not p.at_end():
        raise SqlError(f"Unexpected trailing SQL: {p._where()}")
    return q


def _parse_query(p: _Parser) -> Query:
    # UNION and EXCEPT associate left at equal precedence; INTERSECT binds
    # tighter (handled inside the operand)
    q = _parse_union_operand(p)
    while True:
        if p.accept_kw("union"):
            all_ = p.accept_kw("all") is not None
            q.unions.append(("union", all_, _parse_union_operand(p)))
        elif p.accept_kw("except"):
            q.unions.append(("except", False, _parse_union_operand(p)))
        else:
            break
    if p.accept_kw("order"):
        p.expect_kw("by")
        q.order_by = [_parse_order_item(p)]
        while p.accept_op(","):
            q.order_by.append(_parse_order_item(p))
    if p.accept_kw("limit"):
        t = p.next()
        if t[0] != "number":
            raise SqlError("LIMIT expects a number")
        q.limit = int(t[1])
    return q


def _parse_union_operand(p: _Parser) -> Query:
    """A set-operation operand: a SELECT core (with INTERSECT chains, which
    bind tighter than UNION/EXCEPT) or a parenthesized (sub-)query."""
    q = _parse_intersect_operand(p)
    while p.accept_kw("intersect"):
        q.unions.append(("intersect", False, _parse_intersect_operand(p)))
    return q


def _parse_intersect_operand(p: _Parser) -> Query:
    if p.peek() == ("op", "(") and p.peek(1) == ("kw", "select"):
        p.i += 1
        q = _parse_query(p)
        p.expect_op(")")
        if q.order_by or q.limit is not None:
            # keep the inner ORDER BY/LIMIT scoped to the branch: wrap it as
            # a derived table so outer set-operation clauses attach outside
            outer = Query()
            outer.from_elements = [FromElement(TableRef(q, "__union_operand"), [])]
            return outer
        return q
    return _parse_select_core(p)


def _parse_select_core(p: _Parser) -> Query:
    q = Query()
    p.expect_kw("select")
    q.distinct = p.accept_kw("distinct") is not None
    if p.accept_op("*"):
        q.items = None
    else:
        q.items = [_parse_item(p)]
        while p.accept_op(","):
            q.items.append(_parse_item(p))
    p.expect_kw("from")
    q.from_elements = [_parse_from_element(p)]
    while p.accept_op(","):
        q.from_elements.append(_parse_from_element(p))
    if p.accept_kw("where"):
        q.where = _parse_or(p)
    if p.accept_kw("group"):
        p.expect_kw("by")
        nxt = p.peek()
        word = nxt[1].lower() if nxt is not None and nxt[0] in ("ident", "kw") else ""
        # cube/grouping are CONTEXTUAL words: only their full syntactic forms
        # (a following paren / SETS() list) commit, so columns with these
        # names stay valid GROUP BY keys
        if p.accept_kw("rollup"):
            p.expect_op("(")
            q.group_by = _parse_group_list(p)
            p.expect_op(")")
            k = len(q.group_by)
            q.group_sets = [tuple(range(j)) for j in range(k, -1, -1)]
        elif word == "cube" and p.peek(1) == ("op", "("):
            p.i += 1
            p.expect_op("(")
            q.group_by = _parse_group_list(p)
            p.expect_op(")")
            k = len(q.group_by)
            q.group_sets = [
                s
                for size in range(k, -1, -1)
                for s in itertools.combinations(range(k), size)
            ]
        elif (
            word == "grouping"
            and p.peek(1) is not None
            and p.peek(1)[1].lower() == "sets"
            and p.peek(2) == ("op", "(")
        ):
            p.i += 2
            p.expect_op("(")
            keys: List[Any] = []
            sets: List[Tuple[int, ...]] = []
            while True:
                names: List[Any] = []
                if p.accept_op("("):
                    if p.peek() != ("op", ")"):
                        names = _parse_group_list(p)
                    p.expect_op(")")
                else:  # a bare column is a one-element set (standard SQL)
                    names.append(_parse_group_item(p))
                idxs = []
                for nm in names:
                    if not isinstance(nm, str):
                        raise SqlError("GROUPING SETS keys must be plain columns")
                    if nm not in keys:
                        keys.append(nm)
                    idxs.append(keys.index(nm))
                sets.append(tuple(idxs))
                if not p.accept_op(","):
                    break
            p.expect_op(")")
            q.group_by = keys
            q.group_sets = sets
        else:
            q.group_by = _parse_group_list(p)
    if p.accept_kw("having"):
        q.having = _parse_or(p)
    return q


def _parse_group_list(p: _Parser) -> List[Any]:
    out = [_parse_group_item(p)]
    while p.accept_op(","):
        out.append(_parse_group_item(p))
    return out


def _parse_from_element(p: _Parser) -> FromElement:
    tref = _parse_table_ref(p)
    joins: List[JoinClause] = []
    while True:
        how = _parse_join_type(p)
        if how is None:
            break
        jref = _parse_table_ref(p)
        p.expect_kw("on")
        joins.append(JoinClause(jref, how, _parse_or(p)))
    return FromElement(tref, joins)


def _parse_table_ref(p: _Parser) -> TableRef:
    if p.accept_op("("):
        sub = _parse_query(p)
        p.expect_op(")")
        alias = _maybe_alias(p)
        if alias is None:
            raise SqlError("A derived table (sub-select in FROM) needs an alias")
        return TableRef(sub, alias)
    name = p.expect_ident()
    return TableRef(name, _maybe_alias(p) or name)


def _maybe_alias(p: _Parser) -> Optional[str]:
    p.accept_kw("as")
    t = p.peek()
    if t is not None and t[0] == "ident" and "." not in t[1]:
        p.i += 1
        return t[1]
    return None


def _parse_join_type(p: _Parser) -> Optional[str]:
    if p.accept_kw("join"):
        return "inner"
    for word, how in (("inner", "inner"), ("left", "left"), ("right", "right"), ("full", "outer")):
        if p.accept_kw(word):
            p.accept_kw("outer")
            p.expect_kw("join")
            return how
    return None


def _parse_item(p: _Parser) -> SelectItem:
    start = p.i
    e = _parse_or(p)
    text = p.text_since(start)
    alias = _maybe_alias(p)
    return SelectItem(e, alias, text)


def _parse_group_item(p: _Parser) -> Any:
    """A GROUP BY key: a (possibly qualified) column name, or an expression
    (e.g. ``substr(w_warehouse_name, 1, 20)``) keyed by its source text."""
    start = p.i
    e = _parse_or(p)
    if isinstance(e, Col):
        return e.name
    e._sql_text = p.text_since(start)
    return e


def _parse_order_item(p: _Parser) -> Tuple[Any, bool]:
    start = p.i
    e = _parse_or(p)
    key: Any
    if isinstance(e, Col):
        key = e.name
    elif isinstance(e, Lit) and isinstance(e.value, int):
        key = int(e.value)  # ordinal: ORDER BY 1 sorts by the first item
    else:
        key = e
        key._sql_text = p.text_since(start)  # for matching against item texts
    if p.accept_kw("desc"):
        return key, False
    p.accept_kw("asc")
    return key, True


def _strip_qualifier(name: str) -> str:
    return name.split(".", 1)[1] if "." in name else name


# --- predicate parsing (precedence: OR < AND < NOT < cmp < +- < */%) ------


def _parse_or(p: _Parser) -> Expr:
    e = _parse_and(p)
    while p.accept_kw("or"):
        e = e | _parse_and(p)
    return e


def _parse_and(p: _Parser) -> Expr:
    e = _parse_not(p)
    while p.accept_kw("and"):
        e = e & _parse_not(p)
    return e


def _parse_not(p: _Parser) -> Expr:
    if p.accept_kw("not"):
        return ~_parse_not(p)
    return _parse_cmp(p)


def _parse_cmp(p: _Parser) -> Expr:
    left = _parse_sum(p)
    if p.accept_kw("is"):
        negate = p.accept_kw("not") is not None
        p.expect_kw("null")
        e = left.is_null()
        return ~e if negate else e
    if p.accept_kw("between"):
        lo = _parse_sum(p)
        p.expect_kw("and")
        hi = _parse_sum(p)
        return (left >= lo) & (left <= hi)
    negate = False
    if p.accept_kw("not"):
        negate = True
    if p.accept_kw("like"):
        from hyperspace_tpu.plan.expr import Like

        t = p.next()
        if t[0] != "string":
            raise SqlError("LIKE expects a quoted pattern")
        e = Like(left, t[1])
        return ~e if negate else e
    if p.accept_kw("in"):
        p.expect_op("(")
        if p.peek() == ("kw", "select"):
            e: Expr = _InQuery(left, _parse_query(p))
            p.expect_op(")")
        else:
            elems = [_parse_or(p)]
            while p.accept_op(","):
                elems.append(_parse_or(p))
            p.expect_op(")")
            folded = [_const_fold(x) for x in elems]
            if all(isinstance(x, Lit) for x in folded):
                e = left.isin([x.value for x in folded])
            else:
                # non-constant elements: expand to an OR of equalities
                e = None
                for x in folded:
                    term = left == x
                    e = term if e is None else (e | term)
        return ~e if negate else e
    if negate:
        raise SqlError("NOT must be followed by IN here")
    op = p.accept_op("=", "!=", "<>", "<=", ">=", "<", ">")
    if op is None:
        return left  # bare boolean expression
    right = _parse_sum(p)
    if op == "=":
        return left == right
    if op in ("!=", "<>"):
        return left != right
    return {"<": left < right, "<=": left <= right, ">": left > right, ">=": left >= right}[op]


def _parse_sum(p: _Parser) -> Expr:
    from hyperspace_tpu.plan.expr import Func

    e = _parse_term(p)
    while True:
        op = p.accept_op("+", "-", "||")
        if op is None:
            return e
        rhs = _parse_term(p)
        if op == "||":
            e = Func("concat", [e, rhs])
        else:
            e = e + rhs if op == "+" else e - rhs


def _parse_term(p: _Parser) -> Expr:
    e = _parse_factor(p)
    while True:
        op = p.accept_op("*", "/", "%")
        if op is None:
            return e
        rhs = _parse_factor(p)
        e = {"*": e * rhs, "/": e / rhs, "%": e % rhs}[op]


def _accept_word(p: _Parser, word: str) -> bool:
    """Accept a contextual (non-reserved) word, whatever its token kind."""
    t = p.peek()
    if t is not None and t[0] in ("ident", "kw") and t[1].lower() == word:
        p.i += 1
        return True
    return False


def _expect_word(p: _Parser, word: str) -> None:
    if not _accept_word(p, word):
        raise SqlError(f"Expected {word.upper()} at {p._where()}")


def _parse_over(p: _Parser):
    """The OVER clause: ([PARTITION BY ...] [ORDER BY ...] [ROWS BETWEEN
    UNBOUNDED PRECEDING AND CURRENT ROW]). Any other frame spec errors."""
    p.expect_kw("over")
    p.expect_op("(")
    partition, orders, cumulative = [], [], False
    if _accept_word(p, "partition"):
        p.expect_kw("by")
        partition.append(_parse_sum(p))
        while p.accept_op(","):
            partition.append(_parse_sum(p))
    if p.accept_kw("order"):
        p.expect_kw("by")

        def item():
            e = _parse_sum(p)
            if p.accept_kw("desc"):
                return (e, False)
            p.accept_kw("asc")
            return (e, True)

        orders.append(item())
        while p.accept_op(","):
            orders.append(item())
    if _accept_word(p, "rows"):
        p.expect_kw("between")
        _expect_word(p, "unbounded")
        _expect_word(p, "preceding")
        p.expect_kw("and")
        _expect_word(p, "current")
        _expect_word(p, "row")
        if not orders:
            raise SqlError("A ROWS frame requires ORDER BY in the OVER clause")
        cumulative = True
    p.expect_op(")")
    return partition, orders, cumulative


def _maybe_window(p: _Parser, fn: str, arg: Optional[Expr], text: str) -> Expr:
    """An aggregate call becomes a window function when OVER follows."""
    if p.peek() == ("kw", "over"):
        partition, orders, cumulative = _parse_over(p)
        return _WindowCall(fn, arg, partition, orders, cumulative, text)
    return _AggCall(fn, arg, text)


def _parse_factor(p: _Parser) -> Expr:
    from hyperspace_tpu.plan.expr import Cast, Func

    if p.accept_op("("):
        if p.peek() == ("kw", "select"):
            sub = _SubquerySelect(_parse_query(p))
            p.expect_op(")")
            return sub
        e = _parse_or(p)
        p.expect_op(")")
        return e
    if p.accept_op("-"):
        return Lit(0) - _parse_factor(p)
    t = p.peek()
    if t is None:
        raise SqlError("Unexpected end of expression")
    if t[0] == "kw" and t[1] in _AGG_FNS and p.peek(1) == ("op", "("):
        fn = p.next()[1]
        p.expect_op("(")
        if p.accept_kw("distinct"):
            if fn not in ("count", "sum", "avg"):
                raise SqlError(f"{fn.upper()}(DISTINCT ...) is not supported")
            fn = f"{fn}_distinct"
        if p.accept_op("*"):
            if fn != "count":
                raise SqlError(f"{fn.upper()}(*) is not valid")
            p.expect_op(")")
            return _maybe_window(p, fn, None, "*")
        start = p.i
        arg = _parse_sum(p)
        text = p.text_since(start)
        p.expect_op(")")
        return _maybe_window(p, fn, arg, text)
    if t == ("kw", "case"):
        p.i += 1
        return _parse_case(p)
    if t == ("kw", "cast"):
        p.i += 1
        p.expect_op("(")
        e = _parse_or(p)
        p.expect_kw("as")
        tt = p.next()
        if tt[0] not in ("ident", "kw"):
            raise SqlError(f"Expected a type name after CAST(... AS, got {tt[1]!r}")
        type_name = tt[1]
        if p.accept_op("("):  # type parameters, e.g. decimal(7,2)
            while p.accept_op(")") is None:
                p.next()
        p.expect_op(")")
        return Cast(e, type_name)
    if t == ("kw", "interval"):
        p.i += 1
        num = p.next()
        if num[0] == "string" and num[1].lstrip("-").isdigit():
            pass  # TPC-H style: interval '3' month
        elif num[0] != "number":
            raise SqlError("INTERVAL expects a number")
        unit = p.next()[1].lower()
        if unit in ("day", "days"):
            return Lit(np.timedelta64(int(num[1]), "D"))
        if unit in ("month", "months", "mon"):
            return Lit(np.timedelta64(int(num[1]), "M"))
        if unit in ("year", "years"):
            return Lit(np.timedelta64(12 * int(num[1]), "M"))
        raise SqlError(f"INTERVAL unit {unit!r} is not supported (day/month/year)")
    if t == ("kw", "exists"):
        p.i += 1
        p.expect_op("(")
        if p.peek() != ("kw", "select"):
            raise SqlError("EXISTS expects a (SELECT ...) subquery")
        sub = _ExistsQuery(_parse_query(p))
        p.expect_op(")")
        return sub
    if t[0] in ("ident", "kw") and t[1].lower() == "extract" and p.peek(1) == ("op", "("):
        # EXTRACT(YEAR FROM expr) -> the equivalent date-part function
        p.i += 1
        p.expect_op("(")
        unit = p.next()[1].lower()
        _expect_word(p, "from")
        e = _parse_or(p)
        p.expect_op(")")
        if unit not in ("year", "month", "day", "quarter"):
            raise SqlError(f"EXTRACT unit {unit!r} is not supported")
        return Func(unit, [e])
    if t[0] == "ident" and "." not in t[1] and p.peek(1) == ("op", "("):
        name = p.next()[1]
        p.expect_op("(")
        if name.lower() in _WINDOW_FNS:
            p.expect_op(")")
            if p.peek() != ("kw", "over"):
                raise SqlError(f"{name}() requires an OVER clause")
            partition, orders, cumulative = _parse_over(p)
            if not orders:
                raise SqlError(f"{name}() requires ORDER BY in its OVER clause")
            return _WindowCall(name.lower(), None, partition, orders, cumulative, "")
        if name.lower() == "grouping":
            start = p.i
            arg = _parse_sum(p)
            text = p.text_since(start)
            p.expect_op(")")
            return _GroupingCall(arg, text)
        agg = _IDENT_AGGS.get(name.lower())
        if agg is not None:
            start = p.i
            arg = _parse_sum(p)
            text = p.text_since(start)
            p.expect_op(")")
            if p.peek() == ("kw", "over"):
                raise SqlError(f"{name}() window form is not supported")
            return _AggCall(agg, arg, text)
        args: List[Expr] = []
        if p.accept_op(")") is None:
            args.append(_parse_or(p))
            while p.accept_op(","):
                args.append(_parse_or(p))
            p.expect_op(")")
        if p.peek() == ("kw", "over"):
            raise SqlError(f"Window function {name}() is not supported")
        try:
            return Func(name, args)
        except ValueError as e:
            raise SqlError(str(e))
    if t[0] == "ident":
        p.i += 1
        return col(t[1])  # qualifiers resolve at plan time (alias map needed)
    return lit(_parse_literal_value(p))


def _parse_case(p: _Parser) -> Expr:
    from hyperspace_tpu.plan.expr import Case

    subject = None
    if p.peek() != ("kw", "when"):
        subject = _parse_or(p)
    branches = []
    while p.accept_kw("when"):
        c = _parse_or(p)
        if subject is not None:
            c = subject == c
        p.expect_kw("then")
        branches.append((c, _parse_or(p)))
    otherwise = None
    if p.accept_kw("else"):
        otherwise = _parse_or(p)
    p.expect_kw("end")
    if not branches:
        raise SqlError("CASE requires at least one WHEN branch")
    return Case(branches, otherwise)


def _const_fold(e: Expr) -> Expr:
    """Fold a reference-free expression (e.g. ``1999 + 1`` in an IN list)
    down to a literal; expressions with column references pass through."""
    if isinstance(e, Lit) or e.references():
        return e
    try:
        v = e.eval({})
    except Exception:
        return e
    return Lit(v.item() if hasattr(v, "item") else v)


def _parse_literal_value(p: _Parser) -> Any:
    t = p.next()
    if t[0] == "number":
        return float(t[1]) if "." in t[1] else int(t[1])
    if t[0] == "string":
        return t[1]
    if t == ("kw", "date"):
        s = p.next()
        if s[0] != "string":
            raise SqlError("DATE expects a quoted literal")
        return np.datetime64(s[1])
    if t == ("kw", "null"):
        return None
    if t[0] == "op" and t[1] == "-":
        v = _parse_literal_value(p)
        return -v
    raise SqlError(f"Expected a literal, got {t[1]!r}")


# --- expression utilities --------------------------------------------------


def _walk(e: Expr):
    yield e
    for c in e.children():
        yield from _walk(c)


def _map_expr(e: Expr, fn) -> Expr:
    """Top-down structural transform: ``fn(node)`` returning non-None
    replaces the node (no further descent); otherwise the node is rebuilt
    with transformed children. THE one rebuild-arm list — every marker
    substitution goes through here so no node shape gets missed."""
    out = fn(e)
    if out is not None:
        return out

    def rec(x):
        return _map_expr(x, fn)

    if isinstance(e, BinaryOp):
        return BinaryOp(e.op, rec(e.left), rec(e.right))
    if isinstance(e, Not):
        return Not(rec(e.child))
    if isinstance(e, IsNull):
        return IsNull(rec(e.child))
    if isinstance(e, In):
        return In(rec(e.child), list(e.values))
    if isinstance(e, _AggCall):
        return _AggCall(e.fn, rec(e.arg) if e.arg is not None else None, e.text)
    if isinstance(e, _WindowCall):
        return _WindowCall(
            e.fn,
            rec(e.arg) if e.arg is not None else None,
            [rec(x) for x in e.partition],
            [(rec(x), asc) for x, asc in e.orders],
            e.cumulative,
            e.text,
        )
    if isinstance(e, _GroupingCall):
        return _GroupingCall(rec(e.arg), e.text)
    if isinstance(e, _InQuery):
        return _InQuery(rec(e.child), e.query)
    from hyperspace_tpu.plan.expr import Case, Cast, Func, InSubquery, Like

    if isinstance(e, Case):
        return Case(
            [(rec(c), rec(v)) for c, v in e.branches],
            rec(e.otherwise) if e.otherwise is not None else None,
        )
    if isinstance(e, Cast):
        return Cast(rec(e.child), e.type_name)
    if isinstance(e, Func):
        return Func(e.name, [rec(a) for a in e.args])
    if isinstance(e, Like):
        return Like(rec(e.child), e.pattern)
    if isinstance(e, InSubquery):
        return InSubquery(rec(e.child), e.plan, e.session)
    from hyperspace_tpu.plan.expr import (
        CorrelatedInSubquery,
        CorrelatedScalarSubquery,
        ExistsSubquery,
    )

    if isinstance(e, CorrelatedScalarSubquery):
        return CorrelatedScalarSubquery(
            [rec(k) for k in e.outer_keys], e.plan, e.key_cols, e.value_col, e.default, e.session
        )
    if isinstance(e, ExistsSubquery):
        return ExistsSubquery(
            [rec(k) for k in e.outer_keys],
            e.plan,
            e.key_cols,
            e.residual,
            [(ph, rec(x)) for ph, x in e.residual_outer],
            e.session,
        )
    if isinstance(e, CorrelatedInSubquery):
        return CorrelatedInSubquery(
            rec(e.child), [rec(k) for k in e.outer_keys], e.plan, e.key_cols, e.value_col, e.session
        )
    return e


def _contains_agg(e: Expr) -> bool:
    return any(isinstance(x, _AggCall) for x in _walk(e))


def _rewrite(e: Expr, mapping: Dict[str, str]) -> Expr:
    """Column-reference rewrite across every node shape (incl. the
    parse-time markers) via the one generic transformer."""

    def leaf(x):
        if isinstance(x, Col):
            return Col(mapping.get(x.name, x.name))
        return None

    return _map_expr(e, leaf)


def _resolve_expr_refs(e: Expr, resolve) -> Expr:
    mapping = {}
    for ref in e.references():
        resolved = resolve(ref)
        if resolved != ref:
            mapping[ref] = resolved
    return _rewrite(e, mapping) if mapping else e


def _bind_subqueries(e: Expr, views, session, outer_resolve=None) -> Expr:
    """Replace parse-time subquery markers with planned subquery expressions
    over the same view namespace (CTEs included). Correlated scalar and
    EXISTS subqueries decorrelate (plan/decorrelate.py); ``outer_resolve``
    maps their outer references to actual outer-frame columns."""
    from hyperspace_tpu.plan.decorrelate import (
        decorrelate_exists,
        decorrelate_in,
        decorrelate_scalar,
        is_correlated,
    )
    from hyperspace_tpu.plan.expr import InSubquery, ScalarSubquery

    identity = outer_resolve if outer_resolve is not None else (lambda name: name)

    def leaf(x):
        if isinstance(x, _SubquerySelect):
            if is_correlated(x.query, views):
                return decorrelate_scalar(x.query, views, session, identity)
            return ScalarSubquery(plan_query(x.query, views).plan, session)
        if isinstance(x, _ExistsQuery):
            return decorrelate_exists(x.query, views, session, identity)
        if isinstance(x, _InQuery):
            child = _bind_subqueries(x.child, views, session, outer_resolve)
            if is_correlated(x.query, views):
                return decorrelate_in(child, x.query, views, session, identity)
            inner = plan_query(x.query, views)
            return InSubquery(child, inner.plan, session)
        return None

    return _map_expr(e, leaf)


def _case_map(e: Expr, available: List[str]) -> Tuple[Expr, List[str]]:
    """Resolve ``e``'s column references case-insensitively against the
    available columns; returns (rewritten expr, still-unknown refs)."""
    colset = set(available)
    lowered = {c.lower(): c for c in available}
    mapping: Dict[str, str] = {}
    unknown: List[str] = []
    for ref in e.references():
        if ref in colset:
            continue
        m = lowered.get(ref.lower())
        if m is not None:
            mapping[ref] = m
        else:
            unknown.append(ref)
    return (_rewrite(e, mapping) if mapping else e), sorted(unknown)


def _canonical_agg_name(fn: str, arg: Optional[Expr], text: str) -> str:
    if arg is None:
        return "count"
    if isinstance(arg, Col):
        return f"{fn}({_strip_qualifier(arg.name)})"
    return f"{fn}({text})"


# --- planning -------------------------------------------------------------


def plan_query(q: Query, views: Dict[str, "DataFrame"]) -> "DataFrame":  # noqa: F821
    if q.ctes:
        views = dict(views)
        for name, cq in q.ctes:
            views[name] = plan_query(cq, views)
    if q.unions:
        return _plan_union(q, views)
    return _plan_single(q, views)


def _plan_union(q: Query, views) -> "DataFrame":  # noqa: F821
    """UNION [ALL] chain: branches align by position (Spark semantics), a
    bare UNION deduplicates, and ORDER BY/LIMIT apply to the combined rows."""
    import copy

    from hyperspace_tpu.plan.dataframe import DataFrame
    from hyperspace_tpu.plan.logical import Rename, Union

    head = copy.copy(q)
    head.unions, head.order_by, head.limit = [], [], None
    df = _plan_single(head, views)
    base_cols = df.plan.output_columns
    for kind, all_, rhs in q.unions:
        # an operand may itself be a parenthesized query with nested chains
        f = plan_query(rhs, views)
        cols = f.plan.output_columns
        if len(cols) != len(base_cols):
            raise SqlError(
                f"{kind.upper()} inputs have {len(base_cols)} vs {len(cols)} output columns"
            )
        if cols != base_cols and kind == "union":
            mapping = {a: b for a, b in zip(cols, base_cols) if a != b}
            try:
                f = DataFrame(Rename(mapping, f.plan), f.session)
            except ValueError as e:
                raise SqlError(f"UNION column alignment failed: {e}")
        if kind == "union":
            df = DataFrame(Union([df.plan, f.plan]), df.session)
            if not all_:
                # left-associative: a bare UNION dedups the chain SO FAR
                # only; a later UNION ALL keeps its duplicates
                df = df.distinct()
        else:  # intersect / except align positionally inside the SetOp
            from hyperspace_tpu.plan.logical import SetOp

            df = DataFrame(SetOp(kind, df.plan, f.plan), df.session)
    if q.order_by:
        keys, asc = [], []
        out = set(base_cols)
        for k, a in q.order_by:
            if isinstance(k, int):
                if not (1 <= k <= len(base_cols)):
                    raise SqlError(f"ORDER BY position {k} is out of range")
                name = base_cols[k - 1]
            else:
                name = _strip_qualifier(k) if isinstance(k, str) else None
            if name is None or name not in out:
                raise SqlError("ORDER BY over a UNION must reference output columns")
            keys.append(name)
            asc.append(a)
        df = df.order_by(*keys, ascending=asc)
    if q.limit is not None:
        df = df.limit(q.limit)
    return df


def _plan_single(q: Query, views: Dict[str, "DataFrame"]) -> "DataFrame":  # noqa: F821
    from hyperspace_tpu.plan.dataframe import DataFrame
    from hyperspace_tpu.plan.logical import Compute, Rename

    df, alias_cols, session, where_rem = _plan_from(q, views)

    resolve_ref = _make_ref_resolver(df, alias_cols)

    def prep(e: Expr) -> Expr:
        return _bind_subqueries(_resolve_expr_refs(e, resolve_ref), views, session, resolve_ref)

    if where_rem is not None:
        where = prep(where_rem)
        for x in _walk(where):
            if isinstance(x, _AggCall):
                raise SqlError(
                    f"Aggregate {x.fn.upper()}() is not allowed in WHERE; use HAVING"
                )
            if isinstance(x, _WindowCall):
                raise SqlError("Window functions are not allowed in WHERE")
        df = df.filter(where)

    if q.items is None and any(
        c.startswith(("__cross", "__jk")) for c in df.plan.output_columns
    ):
        # SELECT * must not expose internal cross-join / computed join-key columns
        df = df.select(
            *[c for c in df.plan.output_columns if not c.startswith(("__cross", "__jk"))]
        )

    prepared = (
        [(it, prep(it.expr)) for it in q.items] if q.items is not None else None
    )
    having_e = prep(q.having) if q.having is not None else None
    if having_e is not None and any(isinstance(x, _WindowCall) for x in _walk(having_e)):
        raise SqlError("Window functions are not allowed in HAVING")

    is_agg = bool(q.group_by) or (
        prepared is not None and any(_contains_agg(e) for _, e in prepared)
    )
    if having_e is not None and not is_agg:
        raise SqlError("HAVING requires GROUP BY or aggregates in SELECT")

    renames: Dict[str, str] = {}
    names: List[str] = []  # projection, pre-rename

    canonical_out: Dict[str, str] = {}
    if is_agg:
        if prepared is None:
            raise SqlError("SELECT * cannot be combined with GROUP BY/aggregates")
        if q.group_sets is not None:
            df, names, canonical_out = _plan_rollup(
                q, df, prepared, having_e, resolve_ref, renames, session
            )
        else:
            df, names, canonical_out = _plan_aggregate(
                q, df, prepared, having_e, resolve_ref, renames, session
            )
    elif prepared is not None:
        exprs = [e for _, e in prepared]
        df, exprs = _plan_windows(df, exprs, session)
        prepared = [(it, e2) for (it, _), e2 in zip(prepared, exprs)]
        computes: List[Tuple[str, Expr]] = []
        for i, (it, e) in enumerate(prepared):
            if isinstance(e, Col):
                src = it.expr.name if isinstance(it.expr, Col) else e.name
                name = _resolve_select_name(src, df, alias_cols)
                names.append(name)
                if it.alias:
                    renames[name] = it.alias
                elif name.startswith("__win"):  # window item: name by text
                    renames[name] = it.text
            else:
                e, unknown = _case_map(e, df.plan.output_columns)
                if unknown:
                    raise SqlError(f"Unknown columns {unknown} in expression {it.text!r}")
                internal = f"__expr{i}"
                computes.append((internal, e))
                names.append(internal)
                renames[internal] = it.alias or it.text
        _surface_plain_names(q.items, names, renames)
        if computes:
            df = DataFrame(Compute(computes, df.plan), session)

    if q.distinct:
        if is_agg:
            raise SqlError("SELECT DISTINCT cannot be combined with GROUP BY/aggregates")
        if prepared is not None:
            df = df.select(*names)
            names = []
        df = df.distinct()

    # ORDER BY keys may reference output aliases, projected columns, or
    # non-projected columns (the latter sort before the projection drops
    # them, Spark-style)
    sort_specs: List[Tuple[str, bool]] = []
    extra_sort_cols: List[str] = []
    sort_exprs: List[Tuple[str, Expr]] = []
    if q.order_by:
        pre_cols = set(df.plan.output_columns)
        final_by_src = {n: renames.get(n, n) for n in names}
        aliases_set = set(renames.values())
        item_by_text: Dict[str, str] = {}
        if q.items is not None:
            for it_, nm_ in zip(q.items, names):
                item_by_text.setdefault(it_.text, renames.get(nm_, nm_))
        for name, asc in q.order_by:
            if isinstance(name, int):  # ordinal: 1-based SELECT item position
                positional = names if names else df.plan.output_columns  # SELECT *
                if not (1 <= name <= len(positional)):
                    raise SqlError(f"ORDER BY position {name} is out of range")
                nm = positional[name - 1]
                sort_specs.append((renames.get(nm, nm), asc))
                continue
            if not isinstance(name, str):
                # expression key: an aggregate call maps to its output
                # column; any other expression must repeat a SELECT item
                resolved_k = _resolve_expr_refs(name, resolve_ref)
                if isinstance(resolved_k, _AggCall):
                    canon = _canonical_agg_name(resolved_k.fn, resolved_k.arg, resolved_k.text)
                    n = canonical_out.get(canon, canon)
                else:
                    txt = getattr(name, "_sql_text", repr(name))
                    target = item_by_text.get(txt)
                    if target is not None:
                        sort_specs.append((target, asc))
                        continue
                    if any(isinstance(x, _WindowCall) for x in _walk(resolved_k)):
                        raise SqlError(
                            "Window functions in ORDER BY must appear as (or "
                            "alias) a SELECT item"
                        )
                    # general expression key: computed above the renamed
                    # frame (its references must name output columns) and
                    # projected away after the sort
                    internal = f"__sort{len(sort_exprs)}"
                    sort_exprs.append((internal, resolved_k))
                    sort_specs.append((internal, asc))
                    continue
            else:
                n = resolve_ref(name)
            if names and n in final_by_src:
                sort_specs.append((final_by_src[n], asc))
            elif n in aliases_set:
                sort_specs.append((n, asc))
            elif not names and n in pre_cols:  # SELECT * (or post-DISTINCT)
                # the Rename applies before the sort, so map aliased names
                sort_specs.append((renames.get(n, n), asc))
            elif names and n in pre_cols:
                extra_sort_cols.append(n)
                sort_specs.append((n, asc))
            else:
                raise SqlError(
                    f"ORDER BY column {name!r} is neither an output column "
                    f"nor available before the projection ({sorted(pre_cols)})"
                )

    if names:
        df = df.select(*names + [c for c in extra_sort_cols if c not in names])
    if renames:
        try:
            df = DataFrame(Rename(renames, df.plan), df.session)
        except ValueError as e:  # e.g. alias collides with another column
            raise SqlError(f"Invalid AS aliases: {e}")
    if sort_exprs:
        final_cols = set(df.plan.output_columns)
        for i_, (n_, e_) in enumerate(sort_exprs):
            e2, unknown = _case_map(e_, df.plan.output_columns)
            if unknown:
                raise SqlError(
                    f"ORDER BY expression references unknown columns {unknown} "
                    f"among {sorted(final_cols)}"
                )
            sort_exprs[i_] = (n_, e2)
        df = DataFrame(Compute(sort_exprs, df.plan), df.session)
    if sort_specs:
        df = df.order_by(*[n for n, _ in sort_specs], ascending=[a for _, a in sort_specs])
    if extra_sort_cols or sort_exprs:
        if names:
            final = [renames.get(n, n) for n in names]
            df = df.select(*final)
        else:
            df = df.select(*[c for c in df.plan.output_columns if not c.startswith("__sort")])
    if q.limit is not None:
        df = df.limit(q.limit)
    return df


def _plan_from(q: Query, views):
    """Plan the FROM clause: named views and derived tables, comma-separated
    entries joined by the equality predicates WHERE provides (the classic
    TPC-DS style ``FROM a, b WHERE a.k = b.k``), then explicit JOIN ... ON
    clauses. Returns (df, alias_cols, session, remaining WHERE predicate).

    alias_cols maps alias -> {lowercased source column -> its actual name in
    the joined frame}: join dedup renames right-side duplicates ('x' ->
    'x#r', 'x#r#r', ...; plan/logical.py join_output_names is the single
    source of truth), and the map keeps qualified references correct through
    any number of joins."""
    from hyperspace_tpu.plan.expr import split_conjunctive
    from hyperspace_tpu.plan.logical import join_output_names

    if not q.from_elements:
        raise SqlError("FROM clause is empty")

    def frame_of(tref: TableRef):
        if isinstance(tref.source, str):
            if tref.source not in views:
                raise SqlError(
                    f"Unknown table/view {tref.source!r}; register with create_or_replace_temp_view"
                )
            return views[tref.source]
        return plan_query(tref.source, views)

    jk = [0]  # unique suffixes for computed join-key columns

    def build_element(elem: FromElement):
        """One comma element: its table plus chained JOIN ... ON clauses.
        The ON expression is split into equality links (possibly expression
        keys, computed below the join) and a non-equi residual evaluated
        DURING the join (ON-clause semantics: for outer joins a failing
        pair null-extends — TPC-H q13's ``LEFT JOIN orders ON c_custkey =
        o_custkey AND o_comment NOT LIKE ...``). Returns (frame, local
        alias map)."""
        from hyperspace_tpu.plan.dataframe import DataFrame
        from hyperspace_tpu.plan.logical import Compute

        df_e = frame_of(elem.table_ref)
        amap: Dict[str, Dict[str, str]] = {
            elem.table_ref.alias.lower(): {c.lower(): c for c in df_e.plan.output_columns}
        }
        for j in elem.joins:
            right = frame_of(j.table_ref)
            ramap = {j.alias.lower(): {c.lower(): c for c in right.plan.output_columns}}
            links, residual_terms = [], []
            for term in split_conjunctive(_factor_or_common(j.on)):
                pair = None if _contains_marker(term) else _equi_link(
                    term, amap, df_e, right, ramap
                )
                if pair is not None:
                    links.append(pair)
                else:
                    residual_terms.append(term)
            if not links:
                raise SqlError(
                    f"JOIN ... ON for {j.alias!r} needs at least one equality "
                    "predicate linking the two sides"
                )
            condition: Optional[Expr] = None
            for ln, rn in links:
                if not isinstance(ln, str):
                    name = f"__jk{jk[0]}"
                    jk[0] += 1
                    df_e = DataFrame(Compute([(name, ln)], df_e.plan), df_e.session)
                    ln = name
                if not isinstance(rn, str):
                    name = f"__jk{jk[0]}"
                    jk[0] += 1
                    right = DataFrame(Compute([(name, rn)], right.plan), right.session)
                    rn = name
                term = col(ln) == col(rn)
                condition = term if condition is None else (condition & term)
            _, rename = join_output_names(df_e.plan.output_columns, right.plan.output_columns)
            residual: Optional[Expr] = None
            if residual_terms:
                if any(_contains_marker(t) for t in residual_terms):
                    raise SqlError("Subqueries/aggregates are not supported in JOIN ... ON")
                mapping: Dict[str, str] = {}
                left_lower = {c.lower(): c for c in df_e.plan.output_columns}
                right_lower = {c.lower(): c for c in right.plan.output_columns}
                for t in residual_terms:
                    for r in t.references():
                        got = _classify_two_sided(r, amap, ramap, left_lower, right_lower)
                        if got is None:
                            raise SqlError(f"Unknown column {r!r} in ON clause")
                        side, actual = got
                        if side == "ambiguous":
                            raise SqlError(f"Ambiguous column {r!r} in ON clause; qualify it")
                        # residual refs use POST-JOIN names: right side renamed
                        mapping[r] = rename.get(actual, actual) if side == "right" else actual
                for t in residual_terms:
                    t2 = _rewrite(t, mapping)
                    residual = t2 if residual is None else (residual & t2)
            if j.how == "inner" and residual is not None:
                # for inner joins the residual is equivalent to a post-join
                # filter — planning it that way keeps the join pure-equi, so
                # the bucketed/device join stack and JoinIndexRule still apply
                df_e = df_e.join(right, on=condition, how=j.how).filter(residual)
                residual = None
            else:
                df_e = df_e.join(right, on=condition, how=j.how, residual=residual)
            amap[j.alias.lower()] = {
                c.lower(): rename.get(c, c) for c in right.plan.output_columns
            }
        return df_e, amap

    built = [build_element(e) for e in q.from_elements]
    df, alias_cols = built[0]
    session = df.session

    conjuncts: Optional[List[Expr]] = None
    used: Set[int] = set()
    if len(built) > 1:
        where_n = _factor_or_common(q.where) if q.where is not None else None
        conjuncts = split_conjunctive(where_n) if where_n is not None else []
        _push_single_frame_conjuncts(built, conjuncts, used)
        _push_implied_disjunctions(built, conjuncts, used)
        df, alias_cols = built[0]
        pending = built[1:]
        while pending:
            progress = False
            for idx, (frame, amap_r) in enumerate(pending):
                links = []
                for ci, term in enumerate(conjuncts):
                    if ci in used:
                        continue
                    pair = _equi_link(term, alias_cols, df, frame, amap_r)
                    if pair is not None:
                        links.append((ci, pair))
                if not links:
                    continue
                from hyperspace_tpu.plan.dataframe import DataFrame
                from hyperspace_tpu.plan.logical import Compute

                condition: Optional[Expr] = None
                for ci, (ln, rn) in links:
                    used.add(ci)
                    # an expression key is computed as a hidden join-key
                    # column on its frame (Spark projects the expression
                    # below the SortMergeJoin the same way)
                    if not isinstance(ln, str):
                        name = f"__jk{jk[0]}"
                        jk[0] += 1
                        df = DataFrame(Compute([(name, ln)], df.plan), session)
                        ln = name
                    if not isinstance(rn, str):
                        name = f"__jk{jk[0]}"
                        jk[0] += 1
                        frame = DataFrame(Compute([(name, rn)], frame.plan), session)
                        rn = name
                    term = col(ln) == col(rn)
                    condition = term if condition is None else (condition & term)
                _, rename = join_output_names(df.plan.output_columns, frame.plan.output_columns)
                df = df.join(frame, on=condition, how="inner")
                for al, m in amap_r.items():
                    alias_cols[al] = {cl: rename.get(n, n) for cl, n in m.items()}
                pending.pop(idx)
                progress = True
                break
            if not progress:
                # a frame guaranteed to hold one row (global aggregate /
                # LIMIT 1 derived table, e.g. TPC-DS q28/q61/q88/q90) may
                # cross-join via a constant key without row explosion
                idx = next(
                    (i for i, (fr, _) in enumerate(pending) if _is_single_row(fr.plan)),
                    None,
                )
                if idx is None and _is_single_row(df.plan):
                    idx = 0
                if idx is not None:
                    frame, amap_r = pending.pop(idx)
                    df, rename = _cross_join(df, frame, session)
                    for al, m in amap_r.items():
                        alias_cols[al] = {cl: rename.get(n, n) for cl, n in m.items()}
                    progress = True
                    continue
                left_aliases = sorted(
                    al for _, m in pending for al in m
                )
                raise SqlError(
                    f"Cannot join {left_aliases}: no equality predicate in "
                    "WHERE links them to the other FROM tables (cartesian products "
                    "are not supported)"
                )

    if q.where is None:
        where_rem = None
    elif conjuncts is None:
        where_rem = q.where
    else:
        rest = [t for i, t in enumerate(conjuncts) if i not in used]
        where_rem = None
        for t in rest:
            where_rem = t if where_rem is None else (where_rem & t)
    return df, alias_cols, session, where_rem


def _is_single_row(plan) -> bool:
    """True when the plan provably yields at most one row (global aggregate
    or LIMIT 1, under any stack of projections)."""
    from hyperspace_tpu.plan import logical as L

    node = plan
    # Filter included: a filtered single-row frame is still <= 1 row (the
    # pushdown pass may wrap a global-aggregate derived table in a Filter)
    while isinstance(node, (L.Project, L.Rename, L.Compute, L.Sort, L.Filter)):
        (node,) = node.children()
    if isinstance(node, L.Limit):
        return node.n <= 1
    return isinstance(node, L.Aggregate) and not node.keys


def _cross_join(df, frame, session):
    """Cross join via a constant '__cross' key on both sides (the IR only
    has equi-joins); callers guarantee one side is single-row."""
    from hyperspace_tpu.plan.dataframe import DataFrame
    from hyperspace_tpu.plan.logical import Compute, join_output_names

    def with_key(f):
        if "__cross" in f.plan.output_columns:
            return f
        return DataFrame(Compute([("__cross", Lit(1))], f.plan), session)

    left, right = with_key(df), with_key(frame)
    _, rename = join_output_names(left.plan.output_columns, right.plan.output_columns)
    out = left.join(right, on=col("__cross") == col("__cross"), how="inner")
    return out, rename


def _split_disjunctive(e: Expr) -> List[Expr]:
    if isinstance(e, BinaryOp) and e.op == "OR":
        return _split_disjunctive(e.left) + _split_disjunctive(e.right)
    return [e]


def _and_all(terms: List[Expr]) -> Optional[Expr]:
    out: Optional[Expr] = None
    for t in terms:
        out = t if out is None else (out & t)
    return out


def _or_all(terms: List[Expr]) -> Optional[Expr]:
    out: Optional[Expr] = None
    for t in terms:
        out = t if out is None else (out | t)
    return out


def _contains_marker(e: Expr) -> bool:
    """True when the tree holds a parse-time marker (subquery, aggregate,
    window, grouping) that only ``prep()`` can bind later. Markers repr
    non-structurally (every subquery is ``<subquery>``) and carry no child
    references, so factoring and join-key extraction must leave them alone."""
    return any(
        isinstance(
            x, (_SubquerySelect, _InQuery, _ExistsQuery, _AggCall, _WindowCall, _GroupingCall)
        )
        for x in _walk(e)
    )


def _factor_or_common(e: Expr) -> Expr:
    """Pull conjuncts common to every OR branch above the OR:
    ``(c AND r1) OR (c AND r2) -> c AND (r1 OR r2)`` (Kleene-distributive, so
    three-valued semantics are preserved). TPC-DS q13/q48-style predicates
    repeat the equi-join conjuncts inside each OR block; factoring exposes
    them to the comma-FROM join linker, leaving the residual OR as a plain
    filter. Structural equality is by repr — conjuncts holding parse-time
    markers are never factored (their reprs are non-structural)."""
    from hyperspace_tpu.plan.expr import split_conjunctive

    if isinstance(e, BinaryOp) and e.op == "AND":
        return _factor_or_common(e.left) & _factor_or_common(e.right)
    if not (isinstance(e, BinaryOp) and e.op == "OR"):
        return e
    branches = [_factor_or_common(b) for b in _split_disjunctive(e)]
    conj_lists = [split_conjunctive(b) for b in branches]
    first = {repr(t): t for t in conj_lists[0] if not _contains_marker(t)}
    common_keys = set(first)
    for cl in conj_lists[1:]:
        common_keys &= {repr(t) for t in cl}
    if not common_keys:
        return _or_all(branches)
    common = [t for k, t in first.items() if k in common_keys]
    residuals: List[Optional[Expr]] = []
    for cl in conj_lists:
        taken: Set[str] = set()
        rest: List[Expr] = []
        for t in cl:
            k = repr(t)
            if k in common_keys and k not in taken:
                taken.add(k)  # remove one instance per common conjunct
                continue
            rest.append(t)
        residuals.append(_and_all(rest))
    if any(r is None for r in residuals):
        # a branch reduced to exactly the common part: the OR is implied
        return _and_all(common)
    return _and_all(common) & _or_all([r for r in residuals if r is not None])


def _frame_owner_fn(built):
    """Resolver shared by the pre-join pushdown passes: name -> (frame
    index, actual column) when the reference resolves into exactly one
    frame; None otherwise (unknown alias, or bare name in several)."""
    frame_lowers = [{c.lower(): c for c in fr.plan.output_columns} for fr, _ in built]

    def owner(name: str):
        if "." in name:
            qual, rest = name.split(".", 1)
            ql, rl = qual.lower(), rest.lower()
            hits = [
                (i, amap[ql][rl])
                for i, (_, amap) in enumerate(built)
                if ql in amap and rl in amap[ql]
            ]
            return hits[0] if len(hits) == 1 else None
        ln = name.lower()
        hits = [(i, low[ln]) for i, low in enumerate(frame_lowers) if ln in low]
        return hits[0] if len(hits) == 1 else None

    return owner


def _owned_rewrite(owner, sub):
    """(frame index, rewritten term) when every reference of ``sub`` resolves
    into ONE frame; None otherwise (or for marker terms / no references)."""
    if _contains_marker(sub):
        return None
    refs = sorted(sub.references())
    if not refs:
        return None
    target, mapping = None, {}
    for r in refs:
        got = owner(r)
        if got is None:
            return None
        i, cn = got
        if target is None:
            target = i
        elif target != i:
            return None
        mapping[r] = cn
    return target, _rewrite(sub, mapping)


def _push_single_frame_conjuncts(built, conjuncts, used) -> None:
    """Filter each FROM frame by the WHERE conjuncts that reference only that
    frame, BEFORE any join is built (Catalyst's PushDownPredicates role). An
    upper filter over an N-way self-join (TPC-DS q4/q11/q31: 4 references to
    one year_total CTE, distinguished only by per-reference year/channel
    predicates) otherwise materializes the unfiltered cross-growth first —
    quadratic-to-quartic row explosion that the filter then throws away."""
    owner = _frame_owner_fn(built)

    for ci, term in enumerate(conjuncts):
        if ci in used:
            continue
        got = _owned_rewrite(owner, term)
        if got is not None:
            target, rewritten = got
            fr, amap_r = built[target]
            built[target] = (fr.filter(rewritten), amap_r)
            used.add(ci)


def _push_implied_disjunctions(built, conjuncts, used) -> None:
    """Derive per-frame prefilters implied by a multi-frame disjunction
    (Catalyst's constraint-inference role for the CNF-conversion class of
    predicates): for ``(a1 AND ...) OR (a2 AND ...)``, when EVERY branch
    carries sub-terms referencing only frame F, the whole disjunction
    implies ``OR(branch F-parts)`` — under Kleene semantics a row whose
    every branch F-part is FALSE/UNKNOWN cannot make any branch TRUE, so
    filtering on the implied OR (which keeps only TRUE) drops no surviving
    row. The implied filter pushes BELOW the joins as a REDUNDANT
    prefilter; the original predicate still applies after them. TPC-DS/
    TPC-H q13/q19/q48-style demographic and address OR-blocks shrink
    their inputs ~10x this way."""
    from hyperspace_tpu.plan.expr import split_conjunctive

    owner = _frame_owner_fn(built)
    for ci, term in enumerate(conjuncts):
        if ci in used:
            continue
        branches = _split_disjunctive(term)
        if len(branches) < 2:
            continue
        branch_parts = []  # per branch: {frame index -> [rewritten terms]}
        eligible = None
        for b in branches:
            parts: Dict[int, List[Expr]] = {}
            for sub in split_conjunctive(b):
                got = _owned_rewrite(owner, sub)
                if got is not None:
                    parts.setdefault(got[0], []).append(got[1])
            branch_parts.append(parts)
            eligible = set(parts) if eligible is None else (eligible & set(parts))
            if not eligible:
                break
        if not eligible:
            continue
        for f in sorted(eligible):
            constraint = _or_all([_and_all(bp[f]) for bp in branch_parts])
            fr, amap_r = built[f]
            built[f] = (fr.filter(constraint), amap_r)


def _classify_two_sided(name: str, left_aliases, right_aliases, left_lower, right_lower):
    """Resolve an ON-clause / comma-FROM reference against the two join
    sides: ('left'|'right', actual column) on a unique resolution,
    ('ambiguous', None) for an unqualified name present on both sides, None
    when nothing resolves. The one resolver shared by equi-link extraction
    and residual reference rewriting (so the two can never drift)."""
    if "." in name:
        qual, rest = name.split(".", 1)
        ql = qual.lower()
        if ql in right_aliases:
            got = right_aliases[ql].get(rest.lower())
            return ("right", got) if got is not None else None
        if ql in left_aliases:
            got = left_aliases[ql].get(rest.lower())
            return ("left", got) if got is not None else None
        return None
    ln = name.lower()
    in_left, in_right = ln in left_lower, ln in right_lower
    if in_left and in_right:
        return ("ambiguous", None)
    if in_left:
        return ("left", left_lower[ln])
    if in_right:
        return ("right", right_lower[ln])
    return None


def _equi_link(term: Expr, alias_cols, left_df, right_frame, right_aliases):
    """If ``term`` is ``expr = expr`` with one side's references resolving
    entirely into the joined composite and the other's entirely into the
    candidate right frame (any of its aliases), return the
    (left key, right key) pair — each a column name (str) for bare columns,
    or the side's Expr rewritten to actual frame columns (the caller computes
    it as a join-key column, Spark-style projection under the join); else
    None. Covers TPC-DS q2 (``d_week_seq1 = d_week_seq2 - 53``) and q8
    (``substr(s_zip,1,2) = substr(ca_zip,1,2)``)."""
    if not (isinstance(term, BinaryOp) and term.op == "="):
        return None
    left_lower = {c.lower(): c for c in left_df.plan.output_columns}
    right_lower = {c.lower(): c for c in right_frame.plan.output_columns}

    def classify(name: str):
        got = _classify_two_sided(name, alias_cols, right_aliases, left_lower, right_lower)
        if got is None or got[0] == "ambiguous":
            return None  # absent or ambiguous: not a usable link side
        return got

    def classify_side(e: Expr):
        """(side, key) where key is a str column or a rewritten Expr; None
        when refs are absent, mixed-side, constant, or the side holds a
        parse-time marker (subquery/aggregate/window — bound later by prep,
        so the whole term must stay a WHERE filter, not become a join key)."""
        if isinstance(e, Col):
            got = classify(e.name)
            return got
        if _contains_marker(e):
            return None
        refs = sorted(e.references())
        if not refs:
            return None
        got = [classify(r) for r in refs]
        if any(g is None for g in got):
            return None
        sides = {g[0] for g in got}
        if len(sides) != 1:
            return None
        side = sides.pop()
        mapping = {r: g[1] for r, g in zip(refs, got)}
        return (side, _rewrite(e, mapping))

    a, b = classify_side(term.left), classify_side(term.right)
    if a is not None and b is not None and {a[0], b[0]} == {"left", "right"}:
        left = a if a[0] == "left" else b
        right = a if a[0] == "right" else b
        return left[1], right[1]
    return None


def _plan_windows(df, item_exprs, session):
    """Collect _WindowCall nodes from the item expressions, append ONE Window
    node computing them over ``df``, and return (df, substituted exprs).
    Window operands (argument, partition, order keys) must resolve to columns
    of ``df`` — expressions are pre-reduced by the caller (aggregate calls
    already replaced by their output columns)."""
    from hyperspace_tpu.plan.dataframe import DataFrame
    from hyperspace_tpu.plan.logical import Window

    cols_ = df.plan.output_columns
    lowered = {c.lower(): c for c in cols_}
    pre: List[Tuple[str, Expr]] = []

    def operand(e, what):
        if isinstance(e, Col):
            got = e.name if e.name in cols_ else lowered.get(e.name.lower())
            if got is not None:
                return got
        # expression operand (e.g. grouping-indicator arithmetic, CASE over
        # keys): computed below the Window node
        e2, unknown = _case_map(e, cols_)
        if unknown:
            raise SqlError(
                f"Window {what} references unknown columns {unknown} among {sorted(cols_)}"
            )
        name = f"__winop{len(pre)}"
        pre.append((name, e2))
        return name

    specs, mapping = [], {}
    for e in item_exprs:
        for node in _walk(e):
            if isinstance(node, _WindowCall) and id(node) not in mapping:
                out = f"__win{len(specs)}"
                arg = operand(node.arg, "argument") if node.arg is not None else None
                parts = tuple(operand(x, "PARTITION BY key") for x in node.partition)
                orders = tuple((operand(x, "ORDER BY key"), asc) for x, asc in node.orders)
                if node.fn in ("count", "sum", "min", "max", "avg") and orders and not node.cumulative:
                    raise SqlError(
                        f"{node.fn}() OVER (ORDER BY ...) needs an explicit "
                        "ROWS BETWEEN UNBOUNDED PRECEDING AND CURRENT ROW frame"
                    )
                specs.append((out, node.fn, arg, parts, orders, node.cumulative))
                mapping[id(node)] = Col(out)
    if not specs:
        return df, item_exprs
    if pre:
        from hyperspace_tpu.plan.logical import Compute

        df = DataFrame(Compute(pre, df.plan), session)
    df = DataFrame(Window(specs, df.plan), session)
    return df, [_substitute_windows(e, mapping) for e in item_exprs]


def _substitute_windows(e: Expr, mapping) -> Expr:
    return _map_expr(e, lambda x: mapping.get(id(x)))


def _plan_rollup(q, df, prepared, having_e, resolve_ref, renames, session):
    """GROUP BY ROLLUP / CUBE / GROUPING SETS: the union of one Aggregate
    per grouping set (ROLLUP = key prefixes, CUBE = all subsets, GROUPING
    SETS = the explicit list), absent keys NULL, with __grp{i} indicator
    columns feeding
    grouping() (ref: Spark's Rollup/grouping semantics, used by TPC-DS
    q5/q18/q22/q27/q36/q67/q70/q77/q80/q86). Windows and grouping()
    arithmetic apply over the UNION (cross-set partitions), matching Spark.
    Returns (df, projection names, canonical_out)."""
    from hyperspace_tpu.plan.dataframe import DataFrame
    from hyperspace_tpu.plan.logical import Aggregate, Compute, Union

    group_keys: List[str] = []
    parse_to_dedup: List[int] = []  # parse-time key position -> deduped index
    for g in q.group_by:
        if not isinstance(g, str):
            raise SqlError("ROLLUP/CUBE/GROUPING SETS keys must be plain columns")
        r = resolve_ref(g)
        lowered = [k.lower() for k in group_keys]
        if r.lower() not in lowered:
            parse_to_dedup.append(len(group_keys))
            group_keys.append(r)
        else:  # GROUP BY ROLLUP(a, A): both positions map to one key
            parse_to_dedup.append(lowered.index(r.lower()))
    group_sets = [
        tuple(sorted({parse_to_dedup[i] for i in s})) for s in q.group_sets
    ]
    k = len(group_keys)
    key_index = {g.lower(): i for i, g in enumerate(group_keys)}

    pre_computes: List[Tuple[str, Expr]] = []
    aggs: List[Tuple[str, str, Optional[str]]] = []
    agg_out_by_key: Dict[Tuple[str, str], str] = {}
    canonical_out: Dict[str, str] = {}

    def register(ac: _AggCall) -> str:
        key = (ac.fn, ac.text if ac.arg is not None else "*")
        got = agg_out_by_key.get(key)
        if got is not None:
            return got
        canonical = _canonical_agg_name(ac.fn, ac.arg, ac.text)
        if ac.arg is None:
            in_col = None
        elif isinstance(ac.arg, Col):
            in_col = ac.arg.name
        else:
            in_col = f"__aggin{len(pre_computes)}"
            a2, unknown = _case_map(ac.arg, df.plan.output_columns)
            if unknown:
                raise SqlError(f"Unknown columns {unknown} in aggregate {ac.text!r}")
            pre_computes.append((in_col, a2))
        aggs.append((canonical, ac.fn, in_col))
        agg_out_by_key[key] = canonical
        canonical_out[canonical] = canonical
        return canonical

    # sibling-item aliases of bare aggregates (a window may ORDER BY them)
    alias_to_expr = {
        it.alias.lower(): e for (it, e) in prepared if it.alias and isinstance(e, _AggCall)
    }

    def subst(e: Expr) -> Expr:
        def leaf(x):
            if isinstance(x, _AggCall):
                return Col(register(x))
            if isinstance(x, _GroupingCall):
                a = x.arg
                gi = key_index.get(a.name.lower()) if isinstance(a, Col) else None
                if gi is None:
                    raise SqlError(f"grouping() argument must be a ROLLUP key; got {x.text!r}")
                return Col(f"__grp{gi}")
            if isinstance(x, Col):
                ref = alias_to_expr.get(x.name.lower())
                if ref is not None:
                    return Col(register(ref))
            return None

        return _map_expr(e, leaf)

    item_exprs = [subst(e) for _, e in prepared]
    having2 = subst(having_e) if having_e is not None else None
    if not aggs:
        raise SqlError(
            "GROUP BY ROLLUP/CUBE/GROUPING SETS requires at least one aggregate in SELECT"
        )

    base = df
    if pre_computes:
        base = DataFrame(Compute(pre_computes, base.plan), session)

    # one frame per grouping set (longest prefix first), all with identical
    # output schemas: keys (NULL when rolled up) + aggregates + indicators
    out_order = group_keys + [out for out, _, _ in aggs] + [f"__grp{i}" for i in range(k)]
    frames = []
    for s in group_sets:
        in_set = set(s)
        skeys = [group_keys[i] for i in sorted(in_set)]
        f = DataFrame(Aggregate(skeys, aggs, base.plan), session)
        fills: List[Tuple[str, Expr]] = [
            (gk, Lit(None)) for i, gk in enumerate(group_keys) if i not in in_set
        ]
        fills += [(f"__grp{i}", Lit(0 if i in in_set else 1)) for i in range(k)]
        f = DataFrame(Compute(fills, f.plan), session)
        frames.append(f.select(*out_order).plan)
    df = DataFrame(Union(frames), session)

    if having2 is not None:
        h2, unknown = _case_map(having2, df.plan.output_columns)
        if unknown:
            raise SqlError(f"HAVING references unknown columns {unknown}")
        df = df.filter(h2)

    df, item_exprs = _plan_windows(df, item_exprs, session)

    names: List[str] = []
    computes: List[Tuple[str, Expr]] = []
    lowered = {c.lower(): c for c in df.plan.output_columns}
    for i, ((it, _), e) in enumerate(zip(prepared, item_exprs)):
        if isinstance(e, Col):
            n = e.name if e.name in df.plan.output_columns else lowered.get(e.name.lower())
            if n is None:
                raise SqlError(f"Column {e.name!r} must appear in ROLLUP keys or an aggregate")
            names.append(n)
            if it.alias and it.alias != n:
                renames[n] = it.alias
            elif n.startswith(("__grp", "__win")):
                renames[n] = it.alias or it.text
        else:
            e2, unknown = _case_map(e, df.plan.output_columns)
            if unknown:
                raise SqlError(f"Unknown columns {unknown} in expression {it.text!r}")
            internal = f"__expr{i}"
            computes.append((internal, e2))
            names.append(internal)
            renames[internal] = it.alias or it.text
    if computes:
        df = DataFrame(Compute(computes, df.plan), session)
    return df, names, canonical_out


def _plan_aggregate(q, df, prepared, having_e, resolve_ref, renames, session):
    """Plan the aggregate branch: pre-aggregate computes for expression
    arguments, the Aggregate node, HAVING, and post-aggregate computes for
    expressions over aggregate outputs. Returns (df, projection names)."""
    from hyperspace_tpu.plan.dataframe import DataFrame
    from hyperspace_tpu.plan.logical import Aggregate, Compute

    group_keys: List[str] = []
    group_computes: List[Tuple[str, Expr]] = []
    group_text_to_key: Dict[str, str] = {}
    for gi, g in enumerate(q.group_by):
        if isinstance(g, str):
            r = resolve_ref(g)
            if r.lower() not in {k.lower() for k in group_keys}:  # GROUP BY a, a
                group_keys.append(r)
            continue
        # expression group key (e.g. substr(col, 1, 20)): computed before the
        # aggregate; SELECT items with the same source text reuse it
        ge, unknown = _case_map(_resolve_expr_refs(g, resolve_ref), df.plan.output_columns)
        if unknown:
            raise SqlError(f"Unknown columns {unknown} in GROUP BY expression")
        name = f"__gk{gi}"
        group_computes.append((name, ge))
        group_keys.append(name)
        group_text_to_key[getattr(g, "_sql_text", "")] = name
    group_lower = {g.lower() for g in group_keys}

    pre_computes: List[Tuple[str, Expr]] = []
    aggs: List[Tuple[str, str, Optional[str]]] = []  # (out, fn, input col)
    agg_out_by_key: Dict[Tuple[str, str], str] = {}
    canonical_out: Dict[str, str] = {}
    taken_out: Set[str] = set(group_keys)

    def register(ac: _AggCall, preferred: Optional[str] = None) -> str:
        canonical = _canonical_agg_name(ac.fn, ac.arg, ac.text)
        key = (ac.fn, ac.text if ac.arg is not None else "*")
        if preferred is None and key in agg_out_by_key:
            return agg_out_by_key[key]
        if ac.arg is None:
            in_col = None
        elif isinstance(ac.arg, Col):
            in_col = ac.arg.name
        else:
            in_col = f"__aggin{len(pre_computes)}"
            arg, unknown = _case_map(ac.arg, df.plan.output_columns)
            if unknown:
                raise SqlError(f"Unknown columns {unknown} in aggregate {ac.text!r}")
            pre_computes.append((in_col, arg))
        out = preferred or canonical
        if out in taken_out:
            if preferred is None:
                return agg_out_by_key.get(key, canonical)
            raise SqlError(f"Duplicate output name {out!r}")
        taken_out.add(out)
        aggs.append((out, ac.fn, in_col))
        agg_out_by_key.setdefault(key, out)
        canonical_out.setdefault(canonical, out)
        return out

    def replace_aggs(e: Expr, preferred: Optional[str] = None) -> Expr:
        if isinstance(e, _AggCall):  # bare call: may claim the item alias
            return Col(register(e, preferred))

        def leaf(x):
            return Col(register(x)) if isinstance(x, _AggCall) else None

        return _map_expr(e, leaf)

    # first pass: items matching a GROUP BY expression's text reuse its
    # computed key; items that ARE bare aggregate calls claim their alias as
    # the aggregate's output name (matches the reference's Spark naming)
    item_exprs: List[Optional[Expr]] = [None] * len(prepared)
    for idx, (it, e) in enumerate(prepared):
        if not isinstance(e, Col) and it.text in group_text_to_key:
            item_exprs[idx] = Col(group_text_to_key[it.text])
        elif isinstance(e, _AggCall):
            item_exprs[idx] = Col(register(e, preferred=it.alias))
    for idx, (it, e) in enumerate(prepared):
        if item_exprs[idx] is None:
            item_exprs[idx] = replace_aggs(e)

    if having_e is not None:
        # HAVING may aggregate without SELECT doing so (keys-only GROUP BY,
        # TPC-H q18's inner ``SELECT l_orderkey ... GROUP BY l_orderkey
        # HAVING sum(l_quantity) > 300``): register its aggregates so the
        # Aggregate node computes them; the projection drops them after
        replace_aggs(having_e)
    if not aggs:
        if having_e is not None:
            raise SqlError("HAVING must reference at least one aggregate")
        # aggregate-less GROUP BY is DISTINCT over the group keys (a common
        # TPC-DS idiom, e.g. q82)
        if group_computes:
            df = DataFrame(Compute(group_computes, df.plan), session)
        names = []
        for (it, _), e in zip(prepared, item_exprs):
            if not isinstance(e, Col) or (
                e.name.lower() not in group_lower and e.name not in group_keys
            ):
                raise SqlError("Column must appear in GROUP BY or an aggregate")
            n = e.name if e.name in group_keys else next(
                g for g in group_keys if g.lower() == e.name.lower()
            )
            names.append(n)
            if it.alias and it.alias != n:
                renames[n] = it.alias
            elif n.startswith("__gk"):
                renames[n] = it.alias or it.text
        df = df.select(*names).distinct()
        return df, names, canonical_out

    if group_computes or pre_computes:
        df = DataFrame(Compute(group_computes + pre_computes, df.plan), session)
    df = DataFrame(Aggregate(group_keys, aggs, df.plan), session)

    if having_e is not None:

        def resolve_having(name: str) -> str:
            return canonical_out.get(name, name)

        having = _resolve_expr_refs(replace_aggs(having_e), resolve_having)
        unknown = sorted(set(having.references()) - set(df.plan.output_columns))
        if unknown:
            raise SqlError(
                f"HAVING references {unknown}, which are not among the "
                f"aggregate outputs {df.plan.output_columns}; add the "
                "aggregate to SELECT or alias it"
            )
        df = df.filter(having)

    df, item_exprs = _plan_windows(df, item_exprs, session)

    names: List[str] = []
    post_computes: List[Tuple[str, Expr]] = []
    for i, ((it, _), e) in enumerate(zip(prepared, item_exprs)):
        if isinstance(e, Col):
            n = e.name
            if n not in df.plan.output_columns:
                if n.lower() in group_lower:
                    n = next(g for g in group_keys if g.lower() == n.lower())
                else:
                    raise SqlError(
                        f"Column {n!r} must appear in GROUP BY or an aggregate"
                    )
            names.append(n)
            if it.alias and it.alias != n:
                renames[n] = it.alias
            elif n.startswith(("__gk", "__win")):  # internal name: use text
                renames[n] = it.alias or it.text
        else:
            e, unknown = _case_map(e, df.plan.output_columns)
            if unknown:
                raise SqlError(
                    f"Columns {unknown} in {it.text!r} must appear in GROUP BY or an aggregate"
                )
            internal = f"__aggexpr{i}"
            post_computes.append((internal, e))
            names.append(internal)
            renames[internal] = it.alias or it.text
    if post_computes:
        df = DataFrame(Compute(post_computes, df.plan), session)
    _surface_plain_names([it for it, _ in prepared], names, renames)
    return df, names, canonical_out


def _make_ref_resolver(df, alias_cols):
    """Resolve a possibly table-qualified name against the planned frame:
    ``alias.col`` maps through the alias's column map (which tracks join
    dedup renames); unqualified (or nested-path) names pass through."""

    def resolve(name: str) -> str:
        if "." in name:
            qual, rest = name.split(".", 1)
            mapping = alias_cols.get(qual.lower())
            if mapping is not None:
                return _map_qualified(mapping, qual, rest)
        return name

    return resolve


def _map_qualified(mapping: Dict[str, str], qual: str, rest: str) -> str:
    """Map an alias-qualified column through the alias's column map; a dotted
    remainder falls back to mapping the path root so nested-struct references
    (``t.addr.city``) keep working."""
    got = mapping.get(rest.lower())
    if got is not None:
        return got
    if "." in rest:
        root, path = rest.split(".", 1)
        mapped = mapping.get(root.lower())
        if mapped is not None:
            return f"{mapped}.{path}"
    raise SqlError(
        f"Column {rest!r} not found in table/alias {qual!r} "
        f"(has {sorted(mapping.values())})"
    )


def _surface_plain_names(items: List[SelectItem], names: List[str], renames: Dict[str, str]) -> None:
    """A qualified right-side duplicate resolves to its internal '#r' column;
    when the plain name is free in the final projection (after AS renames
    apply), surface it under the plain name the way Spark does
    (SELECT t3.x -> column "x"). Mutates ``renames`` in place."""
    for it, name in zip(items, names):
        if it.alias or it.agg is not None or "#r" not in name:
            continue
        plain = name.split("#r", 1)[0]
        taken = {renames.get(n, n) for n in names if n != name}
        if plain not in taken:
            renames[name] = plain


def _resolve_select_name(name: str, df, alias_cols) -> str:
    plain = _strip_qualifier(name)
    cols_ = df.plan.output_columns
    if "." in name:
        qual, rest = name.split(".", 1)
        mapping = alias_cols.get(qual.lower())
        if mapping is not None:
            return _map_qualified(mapping, qual, rest)
    if plain in cols_:
        return plain
    lowered = {c.lower(): c for c in cols_}
    if plain.lower() in lowered:
        return lowered[plain.lower()]
    raise SqlError(f"Unknown column {name!r} among {cols_}")


def run_sql(text: str, session) -> "DataFrame":  # noqa: F821
    from hyperspace_tpu.obs import spans

    with spans.span("parse", cat="plan"):
        q = parse(text)
    with spans.span("resolve", cat="plan"):
        return plan_query(q, session._temp_views)
