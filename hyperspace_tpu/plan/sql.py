"""Minimal SQL front-end over the relational IR.

The reference's users drive Hyperspace through Spark SQL; this module gives
the same entry point without Spark: ``session.sql("SELECT ...")`` parses a
deliberately small dialect (exactly the plan shapes the optimizer rules
accept — linear scans, CNF equi-joins, filters/projects/aggregates; ref:
JoinPlanNodeFilter's own restrictions, HS/index/covering/JoinIndexRule.scala:135-155)
and plans it onto DataFrame operations, so every index rewrite, explain, and
whyNot surface applies to SQL queries unchanged.

Supported grammar (case-insensitive keywords):

    SELECT [DISTINCT] <*| item [, item ...]>
    FROM <view> [AS] [alias]
    [ [INNER|LEFT|RIGHT|FULL] [OUTER] JOIN <view> [alias] ON a = b [AND ...] ]*
    [WHERE <predicate>]
    [GROUP BY col [, col ...]]
    [HAVING <predicate over aggregate outputs>]
    [ORDER BY col [ASC|DESC] [, ...]]
    [LIMIT n]

    item      := col | qualified.col | SUM|MIN|MAX|AVG|COUNT '(' col | '*' ')'  [AS name]
    predicate := comparisons (=, !=, <>, <, <=, >, >=), IN (...), IS [NOT] NULL,
                 BETWEEN x AND y, NOT/AND/OR, arithmetic (+ - * / %),
                 literals: 123, 1.5, 'text', DATE '2024-01-31'
"""

from __future__ import annotations

import re
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from hyperspace_tpu.plan.expr import Col, Expr, Lit, col, lit


class SqlError(ValueError):
    pass


_TOKEN_RE = re.compile(
    r"""
    \s*(
        (?P<string>'(?:[^']|'')*')
      | (?P<number>\d+\.\d+|\.\d+|\d+)
      | (?P<ident>[A-Za-z_][A-Za-z0-9_]*(?:\.[A-Za-z_][A-Za-z0-9_]*)?)
      | (?P<op><=|>=|<>|!=|=|<|>|\(|\)|,|\*|\+|-|/|%)
    )""",
    re.VERBOSE,
)

_KEYWORDS = {
    "select", "distinct", "from", "where", "group", "by", "having", "order", "limit", "join", "on",
    "inner", "left", "right", "full", "outer", "and", "or", "not", "in", "is",
    "null", "between", "as", "asc", "desc", "date", "count", "sum", "min",
    "max", "avg",
}

_AGG_FNS = ("count", "sum", "min", "max", "avg")


def _tokenize(text: str) -> List[Tuple[str, str]]:
    out: List[Tuple[str, str]] = []
    pos = 0
    while pos < len(text):
        if text[pos].isspace():
            pos += 1
            continue
        m = _TOKEN_RE.match(text, pos)
        if not m or m.start(1) != pos:
            raise SqlError(f"Cannot tokenize SQL at: {text[pos:pos+30]!r}")
        pos = m.end(1)
        if m.group("ident") is not None:
            word = m.group("ident")
            if "." not in word and word.lower() in _KEYWORDS:
                out.append(("kw", word.lower()))
            else:
                out.append(("ident", word))
        elif m.group("string") is not None:
            out.append(("string", m.group("string")[1:-1].replace("''", "'")))
        elif m.group("number") is not None:
            out.append(("number", m.group("number")))
        else:
            out.append(("op", m.group("op")))
    return out


class _Parser:
    def __init__(self, tokens: List[Tuple[str, str]]):
        self.toks = tokens
        self.i = 0

    def peek(self, k: int = 0) -> Optional[Tuple[str, str]]:
        j = self.i + k
        return self.toks[j] if j < len(self.toks) else None

    def next(self) -> Tuple[str, str]:
        if self.i >= len(self.toks):
            raise SqlError("Unexpected end of SQL")
        t = self.toks[self.i]
        self.i += 1
        return t

    def accept_kw(self, *words: str) -> Optional[str]:
        t = self.peek()
        if t is not None and t[0] == "kw" and t[1] in words:
            self.i += 1
            return t[1]
        return None

    def expect_kw(self, word: str) -> None:
        if self.accept_kw(word) is None:
            raise SqlError(f"Expected {word.upper()} at {self._where()}")

    def accept_op(self, *ops: str) -> Optional[str]:
        t = self.peek()
        if t is not None and t[0] == "op" and t[1] in ops:
            self.i += 1
            return t[1]
        return None

    def expect_op(self, op: str) -> None:
        if self.accept_op(op) is None:
            raise SqlError(f"Expected {op!r} at {self._where()}")

    def expect_ident(self) -> str:
        t = self.next()
        if t[0] != "ident":
            raise SqlError(f"Expected identifier, got {t[1]!r}")
        return t[1]

    def _where(self) -> str:
        return " ".join(t[1] for t in self.toks[self.i : self.i + 4]) or "<end>"

    def at_end(self) -> bool:
        return self.i >= len(self.toks)


# --- AST ------------------------------------------------------------------


class SelectItem:
    def __init__(self, name: Optional[str], alias: Optional[str], agg: Optional[Tuple[str, Optional[str]]]):
        self.name = name            # column (possibly qualified) for plain items
        self.alias = alias
        self.agg = agg              # (fn, column-or-None-for-*) for aggregates


class JoinClause:
    def __init__(self, view: str, alias: str, how: str, on: List[Tuple[str, str]]):
        self.view = view
        self.alias = alias
        self.how = how
        self.on = on


class Query:
    def __init__(self):
        self.items: Optional[List[SelectItem]] = None  # None = SELECT *
        self.distinct = False
        self.table = ""
        self.alias = ""
        self.joins: List[JoinClause] = []
        self.where: Optional[Expr] = None
        self.group_by: List[str] = []
        self.having: Optional[Expr] = None
        self.order_by: List[Tuple[str, bool]] = []
        self.limit: Optional[int] = None


def parse(text: str) -> Query:
    p = _Parser(_tokenize(text))
    q = Query()
    p.expect_kw("select")
    q.distinct = p.accept_kw("distinct") is not None
    if p.accept_op("*"):
        q.items = None
    else:
        q.items = [_parse_item(p)]
        while p.accept_op(","):
            q.items.append(_parse_item(p))
    p.expect_kw("from")
    q.table = p.expect_ident()
    q.alias = _maybe_alias(p) or q.table
    while True:
        how = _parse_join_type(p)
        if how is None:
            break
        view = p.expect_ident()
        alias = _maybe_alias(p) or view
        p.expect_kw("on")
        on = [_parse_on_eq(p)]
        while p.accept_kw("and"):
            on.append(_parse_on_eq(p))
        q.joins.append(JoinClause(view, alias, how, on))
    if p.accept_kw("where"):
        p.allow_agg = False
        q.where = _parse_or(p)
    if p.accept_kw("group"):
        p.expect_kw("by")
        q.group_by = [p.expect_ident()]
        while p.accept_op(","):
            q.group_by.append(p.expect_ident())
    if p.accept_kw("having"):
        p.allow_agg = True
        q.having = _parse_or(p)
        p.allow_agg = False
    if p.accept_kw("order"):
        p.expect_kw("by")
        q.order_by = [_parse_order_item(p)]
        while p.accept_op(","):
            q.order_by.append(_parse_order_item(p))
    if p.accept_kw("limit"):
        t = p.next()
        if t[0] != "number":
            raise SqlError("LIMIT expects a number")
        q.limit = int(t[1])
    if not p.at_end():
        raise SqlError(f"Unexpected trailing SQL: {p._where()}")
    return q


def _maybe_alias(p: _Parser) -> Optional[str]:
    p.accept_kw("as")
    t = p.peek()
    if t is not None and t[0] == "ident" and "." not in t[1]:
        p.i += 1
        return t[1]
    return None


def _parse_join_type(p: _Parser) -> Optional[str]:
    if p.accept_kw("join"):
        return "inner"
    for word, how in (("inner", "inner"), ("left", "left"), ("right", "right"), ("full", "outer")):
        if p.accept_kw(word):
            p.accept_kw("outer")
            p.expect_kw("join")
            return how
    return None


def _parse_item(p: _Parser) -> SelectItem:
    t = p.peek()
    if t is not None and t[0] == "kw" and t[1] in _AGG_FNS:
        fn = p.next()[1]
        p.expect_op("(")
        if p.accept_op("*"):
            arg = None
            if fn != "count":
                raise SqlError(f"{fn.upper()}(*) is not valid")
        else:
            arg = p.expect_ident()
        p.expect_op(")")
        alias = _maybe_alias(p)
        return SelectItem(None, alias, (fn, arg))
    name = p.expect_ident()
    alias = _maybe_alias(p)
    return SelectItem(name, alias, None)


def _parse_on_eq(p: _Parser) -> Tuple[str, str]:
    a = p.expect_ident()
    p.expect_op("=")
    b = p.expect_ident()
    return a, b


def _parse_order_item(p: _Parser) -> Tuple[str, bool]:
    name = p.expect_ident()
    if p.accept_kw("desc"):
        return name, False
    p.accept_kw("asc")
    return name, True


def _strip_qualifier(name: str) -> str:
    return name.split(".", 1)[1] if "." in name else name


# --- predicate parsing (precedence: OR < AND < NOT < cmp < +- < */%) ------


def _parse_or(p: _Parser) -> Expr:
    e = _parse_and(p)
    while p.accept_kw("or"):
        e = e | _parse_and(p)
    return e


def _parse_and(p: _Parser) -> Expr:
    e = _parse_not(p)
    while p.accept_kw("and"):
        e = e & _parse_not(p)
    return e


def _parse_not(p: _Parser) -> Expr:
    if p.accept_kw("not"):
        return ~_parse_not(p)
    return _parse_cmp(p)


def _parse_cmp(p: _Parser) -> Expr:
    left = _parse_sum(p)
    if p.accept_kw("is"):
        negate = p.accept_kw("not") is not None
        p.expect_kw("null")
        e = left.is_null()
        return ~e if negate else e
    if p.accept_kw("between"):
        lo = _parse_sum(p)
        p.expect_kw("and")
        hi = _parse_sum(p)
        return (left >= lo) & (left <= hi)
    negate = False
    if p.accept_kw("not"):
        negate = True
    if p.accept_kw("in"):
        p.expect_op("(")
        values = [_parse_literal_value(p)]
        while p.accept_op(","):
            values.append(_parse_literal_value(p))
        p.expect_op(")")
        e = left.isin(values)
        return ~e if negate else e
    if negate:
        raise SqlError("NOT must be followed by IN here")
    op = p.accept_op("=", "!=", "<>", "<=", ">=", "<", ">")
    if op is None:
        return left  # bare boolean expression
    right = _parse_sum(p)
    if op == "=":
        return left == right
    if op in ("!=", "<>"):
        return left != right
    return {"<": left < right, "<=": left <= right, ">": left > right, ">=": left >= right}[op]


def _parse_sum(p: _Parser) -> Expr:
    e = _parse_term(p)
    while True:
        op = p.accept_op("+", "-")
        if op is None:
            return e
        rhs = _parse_term(p)
        e = e + rhs if op == "+" else e - rhs


def _parse_term(p: _Parser) -> Expr:
    e = _parse_factor(p)
    while True:
        op = p.accept_op("*", "/", "%")
        if op is None:
            return e
        rhs = _parse_factor(p)
        e = {"*": e * rhs, "/": e / rhs, "%": e % rhs}[op]


def _parse_factor(p: _Parser) -> Expr:
    if p.accept_op("("):
        e = _parse_or(p)
        p.expect_op(")")
        return e
    if p.accept_op("-"):
        return Lit(0) - _parse_factor(p)
    t = p.peek()
    if t is None:
        raise SqlError("Unexpected end of expression")
    if t[0] == "kw" and t[1] in _AGG_FNS and p.peek(1) == ("op", "("):
        if not getattr(p, "allow_agg", False):
            raise SqlError(f"Aggregate {t[1].upper()}() is not allowed in WHERE; use HAVING")
        # aggregate call in a predicate (HAVING COUNT(*) > 1): reference the
        # aggregate's canonical output name; plan_query maps it to the actual
        # (possibly aliased) output column
        fn = p.next()[1]
        p.expect_op("(")
        if p.accept_op("*"):
            arg = None
            if fn != "count":
                raise SqlError(f"{fn.upper()}(*) is not valid")
        else:
            arg = p.expect_ident()
        p.expect_op(")")
        return col(_canonical_agg_name(fn, arg))
    if t[0] == "ident":
        p.i += 1
        return col(t[1])  # qualifiers resolve at plan time (alias map needed)
    return lit(_parse_literal_value(p))


def _canonical_agg_name(fn: str, arg: Optional[str]) -> str:
    return f"{fn}({_strip_qualifier(arg)})" if arg is not None else "count"


def _parse_literal_value(p: _Parser) -> Any:
    t = p.next()
    if t[0] == "number":
        return float(t[1]) if "." in t[1] else int(t[1])
    if t[0] == "string":
        return t[1]
    if t == ("kw", "date"):
        s = p.next()
        if s[0] != "string":
            raise SqlError("DATE expects a quoted literal")
        return np.datetime64(s[1])
    if t == ("kw", "null"):
        return None
    if t[0] == "op" and t[1] == "-":
        v = _parse_literal_value(p)
        return -v
    raise SqlError(f"Expected a literal, got {t[1]!r}")


# --- planning -------------------------------------------------------------


def plan_query(q: Query, views: Dict[str, "DataFrame"]) -> "DataFrame":  # noqa: F821
    if q.table not in views:
        raise SqlError(f"Unknown table/view {q.table!r}; register with create_or_replace_temp_view")
    df = views[q.table]
    # alias -> {lowercased source column -> its actual name in the joined
    # frame}. Join dedup renames right-side duplicates ('x' -> 'x#r', 'x#r#r',
    # ...; plan/logical.py join_output_names is the single source of truth),
    # and this map tracks those renames per alias so qualified references
    # stay correct through any number of joins.
    alias_cols: Dict[str, Dict[str, str]] = {
        q.alias.lower(): {c.lower(): c for c in df.plan.output_columns}
    }

    for j in q.joins:
        if j.view not in views:
            raise SqlError(f"Unknown table/view {j.view!r}")
        right = views[j.view]
        condition: Optional[Expr] = None
        left_cols = {c.lower() for c in df.plan.output_columns}
        for a, b in j.on:
            an, bn = _resolve_side(a, b, j.alias, alias_cols, left_cols, right)
            term = col(an) == col(bn)
            condition = term if condition is None else (condition & term)
        from hyperspace_tpu.plan.logical import join_output_names

        _, rename = join_output_names(df.plan.output_columns, right.plan.output_columns)
        df = df.join(right, on=condition, how=j.how)
        alias_cols[j.alias.lower()] = {
            c.lower(): rename.get(c, c) for c in right.plan.output_columns
        }

    resolve_ref = _make_ref_resolver(df, alias_cols)

    if q.where is not None:
        df = df.filter(_resolve_expr_refs(q.where, resolve_ref))

    renames: Dict[str, str] = {}
    agg_items = [it for it in (q.items or []) if it.agg is not None]
    if q.having is not None and not (agg_items or q.group_by):
        raise SqlError("HAVING requires GROUP BY or aggregates in SELECT")
    if agg_items or q.group_by:
        if q.items is None:
            raise SqlError("SELECT * cannot be combined with GROUP BY/aggregates")
        group_keys = [resolve_ref(g) for g in q.group_by]
        aggs = {}
        out_order: List[str] = []
        canonical_out: Dict[str, str] = {}  # canonical agg name -> output name
        for it in q.items:
            if it.agg is not None:
                fn, arg = it.agg
                arg = resolve_ref(arg) if arg is not None else None
                canonical = _canonical_agg_name(fn, arg)
                name = it.alias or canonical
                aggs[name] = (arg if arg is not None else "*", fn)
                out_order.append(name)
                canonical_out.setdefault(canonical, name)
            else:
                plain = resolve_ref(it.name)
                if plain.lower() not in {g.lower() for g in group_keys}:
                    raise SqlError(f"Column {plain!r} must appear in GROUP BY or an aggregate")
                out_order.append(plain)
                if it.alias:
                    renames[plain] = it.alias
        _surface_plain_names(q.items, out_order, renames)
        if not aggs:
            raise SqlError("GROUP BY requires at least one aggregate in SELECT")
        df = df.group_by(*group_keys).agg(**aggs) if group_keys else df.agg(**aggs)
        if q.having is not None:
            # HAVING COUNT(*) parses to the canonical agg name; map it onto
            # the actual (possibly aliased) output column
            def resolve_having(name: str) -> str:
                r = resolve_ref(name)
                return canonical_out.get(r, r)

            having = _resolve_expr_refs(q.having, resolve_having)
            unknown = sorted(set(having.references()) - set(df.plan.output_columns))
            if unknown:
                raise SqlError(
                    f"HAVING references {unknown}, which are not among the "
                    f"aggregate outputs {df.plan.output_columns}; add the "
                    "aggregate to SELECT or alias it"
                )
            df = df.filter(having)
        missing = [c for c in out_order if c not in df.plan.output_columns]
        if missing:
            raise SqlError(f"Unknown output columns {missing}")
        df = df.select(*out_order)
    elif q.items is not None:
        names = []
        for it in q.items:
            name = _resolve_select_name(it.name, df, alias_cols)
            names.append(name)
            if it.alias:
                renames[name] = it.alias
        _surface_plain_names(q.items, names, renames)
        df = df.select(*names)

    if q.distinct:
        if agg_items or q.group_by:
            raise SqlError("SELECT DISTINCT cannot be combined with GROUP BY/aggregates")
        df = df.distinct()

    if renames:
        from hyperspace_tpu.plan.dataframe import DataFrame
        from hyperspace_tpu.plan.logical import Rename

        try:
            df = DataFrame(Rename(renames, df.plan), df.session)
        except ValueError as e:  # e.g. alias collides with another column
            raise SqlError(f"Invalid AS aliases: {e}")

    if q.order_by:
        out_cols = df.plan.output_columns

        def order_key(name: str) -> str:
            n = resolve_ref(name)
            if n not in out_cols and renames.get(n) in out_cols:
                return renames[n]  # ORDER BY source name after AS
            return n

        df = df.order_by(*[order_key(n) for n, _ in q.order_by], ascending=[a for _, a in q.order_by])
    if q.limit is not None:
        df = df.limit(q.limit)
    return df


def _make_ref_resolver(df, alias_cols):
    """Resolve a possibly table-qualified name against the planned frame:
    ``alias.col`` maps through the alias's column map (which tracks join
    dedup renames); unqualified (or nested-path) names pass through."""

    def resolve(name: str) -> str:
        if "." in name:
            qual, rest = name.split(".", 1)
            mapping = alias_cols.get(qual.lower())
            if mapping is not None:
                got = mapping.get(rest.lower())
                if got is None:
                    raise SqlError(
                        f"Column {rest!r} not found in table/alias {qual!r} "
                        f"(has {sorted(mapping.values())})"
                    )
                return got
        return name

    return resolve


def _resolve_expr_refs(e: Expr, resolve) -> Expr:
    from hyperspace_tpu.plan.expr import rewrite_columns

    mapping = {}
    for ref in e.references():
        resolved = resolve(ref)
        if resolved != ref:
            mapping[ref] = resolved
    return rewrite_columns(e, mapping) if mapping else e


def _resolve_side(a: str, b: str, right_alias: str, alias_cols, left_cols, right) -> Tuple[str, str]:
    """Order an ON pair as (left column, right column) using qualifiers when
    present, else membership; left references map through the alias column
    map so keys renamed by an earlier join's dedup resolve correctly."""

    def side_of(name: str) -> Optional[str]:
        if "." in name:
            qual = name.split(".", 1)[0].lower()
            if qual == right_alias.lower():
                return "right"
            if qual in alias_cols:
                return "left"
        return None

    def left_name(name: str) -> str:
        if "." in name:
            qual, rest = name.split(".", 1)
            mapping = alias_cols.get(qual.lower())
            if mapping is not None and rest.lower() in mapping:
                return mapping[rest.lower()]
        return _strip_qualifier(name)

    sa, sb = side_of(a), side_of(b)
    if sa == "right" or sb == "left":
        a, b = b, a
    elif sa is None and sb is None:
        an_, bn_ = _strip_qualifier(a), _strip_qualifier(b)
        if an_.lower() not in left_cols and bn_.lower() in left_cols:
            a, b = b, a
    return left_name(a), _strip_qualifier(b)


def _surface_plain_names(items: List[SelectItem], names: List[str], renames: Dict[str, str]) -> None:
    """A qualified right-side duplicate resolves to its internal '#r' column;
    when the plain name is free in the final projection (after AS renames
    apply), surface it under the plain name the way Spark does
    (SELECT t3.x -> column "x"). Mutates ``renames`` in place."""
    for it, name in zip(items, names):
        if it.alias or it.agg is not None or "#r" not in name:
            continue
        plain = name.split("#r", 1)[0]
        taken = {renames.get(n, n) for n in names if n != name}
        if plain not in taken:
            renames[name] = plain


def _resolve_select_name(name: str, df, alias_cols) -> str:
    plain = _strip_qualifier(name)
    cols_ = df.plan.output_columns
    if "." in name:
        qual, rest = name.split(".", 1)
        mapping = alias_cols.get(qual.lower())
        if mapping is not None:
            got = mapping.get(rest.lower())
            if got is None:
                raise SqlError(
                    f"Column {rest!r} not found in table/alias {qual!r} "
                    f"(has {sorted(mapping.values())})"
                )
            return got
    if plain in cols_:
        return plain
    lowered = {c.lower(): c for c in cols_}
    if plain.lower() in lowered:
        return lowered[plain.lower()]
    raise SqlError(f"Unknown column {name!r} among {cols_}")


def run_sql(text: str, session) -> "DataFrame":  # noqa: F821
    return plan_query(parse(text), session._temp_views)
