"""Expression tree.

A deliberately small expression language — exactly what the optimizer rules
need: column refs, literals, comparisons, boolean connectives, arithmetic,
``isin``/``is_null``, and ``input_file_name()`` (used for lineage, ref:
HS/index/covering/CoveringIndex.scala:239-273). This replaces the slice of
Spark Catalyst expressions the reference operates on; scope intentionally kept
to what ``JoinPlanNodeFilter`` accepts (ref: HS/index/covering/JoinIndexRule.scala:135-155).

Expressions evaluate over a column batch: a dict ``name -> numpy array``.
Device-side evaluation compiles the same tree to jnp ops (see exec/device.py).
"""

from __future__ import annotations

import contextlib
import threading
from typing import Any, Dict, List, Optional, Sequence, Set

import numpy as np

INPUT_FILE_NAME = "__input_file_name"

# Nested-field normalization prefix (ref: util/ResolverUtils.scala:44-105).
NESTED_PREFIX = "__hs_nested."


def strip_nested_prefix(name: str) -> str:
    """``__hs_nested.a.b`` -> ``a.b`` (identity for flat names)."""
    return name[len(NESTED_PREFIX):] if name.startswith(NESTED_PREFIX) else name


def get_column(batch: Dict[str, np.ndarray], name: str) -> Optional[np.ndarray]:
    """Canonical possibly-nested batch lookup used by eval, select, and join
    key materialization: exact key, case-insensitive key, the flat
    ``__hs_nested.``-prefixed copy an index scan carries, then struct
    extraction for dotted paths. None when nothing resolves."""
    if name in batch:
        return batch[name]
    lowered = name.lower()
    for k, v in batch.items():
        if k.lower() == lowered:
            return v
    if "." in name:
        stripped = strip_nested_prefix(name)
        if not name.startswith(NESTED_PREFIX):
            pref = (NESTED_PREFIX + name).lower()
            for k, v in batch.items():
                if k.lower() == pref:
                    return v
        return extract_nested_from_batch(batch, stripped)
    return None


def column_root_member(name: str, available) -> Optional[str]:
    """Case-insensitive membership of a (possibly dotted) column name in a
    set of flat names: a dotted name belongs where its root struct column is.
    Returns the resolved name (root exact-cased) or None."""
    lowered = {a.lower(): a for a in available}
    hit = lowered.get(name.lower())
    if hit is not None:
        return hit
    if "." in name:
        root, _, rest = name.partition(".")
        base = lowered.get(root.lower())
        if base is not None:
            return f"{base}.{rest}"
    return None


def extract_nested_from_batch(batch: Dict[str, np.ndarray], dotted: str) -> Optional[np.ndarray]:
    """Materialize a nested struct field (``a.b.c``) from a batch whose root
    column holds per-row dicts (how arrow struct columns decode host-side).
    Case-insensitive per path segment. None when the path doesn't resolve."""
    parts = dotted.split(".")
    root = None
    for k in batch:
        if k.lower() == parts[0].lower():
            root = batch[k]
            break
    if root is None or root.dtype != object:
        return None

    _MISSING = object()

    def dig(value, segs):
        for s in segs:
            if value is None:
                return None  # null struct row: field value is null
            if not isinstance(value, dict):
                return _MISSING  # path goes through a non-struct: unresolvable
            hit = next((kk for kk in value if kk.lower() == s.lower()), None)
            if hit is None:
                return _MISSING
            value = value[hit]
        return value

    vals = [dig(v, parts[1:]) for v in root]
    if any(v is _MISSING for v in vals):
        return None
    arr = np.asarray(vals)
    if arr.dtype == object:
        try:
            arr = np.asarray(vals, dtype=np.float64)
        except (TypeError, ValueError):
            pass
    return arr


class Expr:
    """Base expression node. Python comparison operators build trees, so
    identity-based hashing is retained explicitly."""

    def references(self) -> Set[str]:
        out: Set[str] = set()
        self._collect_refs(out)
        return out

    def _collect_refs(self, out: Set[str]) -> None:
        for c in self.children():
            c._collect_refs(out)

    def children(self) -> Sequence["Expr"]:
        return ()

    def eval(self, batch: Dict[str, np.ndarray]) -> np.ndarray:
        raise NotImplementedError

    # -- operator sugar ----------------------------------------------------
    def __eq__(self, other: Any) -> "Expr":  # type: ignore[override]
        return BinaryOp("=", self, _wrap(other))

    def __ne__(self, other: Any) -> "Expr":  # type: ignore[override]
        return BinaryOp("!=", self, _wrap(other))

    def __lt__(self, other: Any) -> "Expr":
        return BinaryOp("<", self, _wrap(other))

    def __le__(self, other: Any) -> "Expr":
        return BinaryOp("<=", self, _wrap(other))

    def __gt__(self, other: Any) -> "Expr":
        return BinaryOp(">", self, _wrap(other))

    def __ge__(self, other: Any) -> "Expr":
        return BinaryOp(">=", self, _wrap(other))

    def __and__(self, other: Any) -> "Expr":
        return BinaryOp("AND", self, _wrap(other))

    def __or__(self, other: Any) -> "Expr":
        return BinaryOp("OR", self, _wrap(other))

    def __invert__(self) -> "Expr":
        return Not(self)

    def __add__(self, other: Any) -> "Expr":
        return BinaryOp("+", self, _wrap(other))

    def __sub__(self, other: Any) -> "Expr":
        return BinaryOp("-", self, _wrap(other))

    def __mul__(self, other: Any) -> "Expr":
        return BinaryOp("*", self, _wrap(other))

    def __truediv__(self, other: Any) -> "Expr":
        return BinaryOp("/", self, _wrap(other))

    def __mod__(self, other: Any) -> "Expr":
        return BinaryOp("%", self, _wrap(other))

    def isin(self, *values: Any) -> "Expr":
        if len(values) == 1 and hasattr(values[0], "plan") and hasattr(values[0], "session"):
            # col.isin(df): uncorrelated IN-subquery over a one-column frame
            return InSubquery(self, values[0].plan, values[0].session)
        if len(values) == 1 and isinstance(values[0], (list, tuple, set)):
            values = tuple(values[0])
        return In(self, [(_wrap(v)) for v in values])

    def is_null(self) -> "Expr":
        return IsNull(self)

    def is_not_null(self) -> "Expr":
        return Not(IsNull(self))

    def __hash__(self) -> int:
        return id(self)

    def __bool__(self) -> bool:
        raise TypeError(
            "Cannot convert Expr to bool; use & | ~ for boolean connectives."
        )


class Col(Expr):
    def __init__(self, name: str):
        self.name = name

    def _collect_refs(self, out: Set[str]) -> None:
        out.add(self.name)

    def eval(self, batch: Dict[str, np.ndarray]) -> np.ndarray:
        got = get_column(batch, self.name)
        if got is None:
            raise KeyError(f"Column {self.name!r} not found in batch with columns {list(batch)}")
        return got

    def __repr__(self) -> str:
        return f"col({self.name!r})"


class Lit(Expr):
    def __init__(self, value: Any):
        self.value = value

    def eval(self, batch: Dict[str, np.ndarray]) -> np.ndarray:
        return np.asarray(self.value)

    def __repr__(self) -> str:
        return f"lit({self.value!r})"


class InputFileName(Expr):
    """Evaluates to the source file path of each row
    (ref: Spark's input_file_name(), used at HS/index/covering/CoveringIndex.scala:250)."""

    def eval(self, batch: Dict[str, np.ndarray]) -> np.ndarray:
        if INPUT_FILE_NAME not in batch:
            raise KeyError("input_file_name() requires a scan that tracks source files")
        return batch[INPUT_FILE_NAME]

    def __repr__(self) -> str:
        return "input_file_name()"


_COMPARES = {"=", "!=", "<", "<=", ">", ">="}
_ARITH = {"+", "-", "*", "/", "%"}


def _coerce_compare(l, r):
    """SQL-style implicit casts for comparisons: a string literal against a
    date column becomes a date (``d_date <= '2000-03-11'``), and an object
    array holding SQL NULLs (None) compared with numbers becomes float with
    NaN (NaN comparisons are False, matching NULL-is-unknown filtering)."""
    l_, r_ = np.asarray(l), np.asarray(r)
    lk, rk = l_.dtype, r_.dtype
    if lk.kind == "M" and rk.kind in ("U", "S", "O"):
        return l, r_.astype(l_.dtype)
    if rk.kind == "M" and lk.kind in ("U", "S", "O"):
        return l_.astype(r_.dtype), r
    if lk == object and rk.kind in ("i", "u", "f"):
        return _object_nums_to_float(l_), r
    if rk == object and lk.kind in ("i", "u", "f"):
        return l, _object_nums_to_float(r_)
    return l, r


def _maybe_add_months(l, r, op: str):
    """Calendar month/year intervals: ``date '1993-10-01' + interval '3'
    month`` (TPC-H predicates). numpy cannot add a month timedelta to a
    day-unit datetime, so months are applied on the month view with the
    day-of-month preserved (clamped to the target month's length, SQL
    semantics). Returns None when neither operand is a month interval."""
    l_, r_ = np.asarray(l), np.asarray(r)

    def is_month_td(a):
        return a.dtype.kind == "m" and np.datetime_data(a.dtype)[0] == "M"

    if l_.dtype.kind == "M" and is_month_td(r_):
        date, months = l_, r_.astype(np.int64)
    elif r_.dtype.kind == "M" and is_month_td(l_) and op == "+":
        date, months = r_, l_.astype(np.int64)
    else:
        return None
    if op == "-":
        months = -months
    d = date.astype("datetime64[D]")
    m = d.astype("datetime64[M]")
    day_off = (d - m.astype("datetime64[D]")).astype(np.int64)
    nm = m + months.astype("timedelta64[M]")
    month_len = (
        (nm + np.timedelta64(1, "M")).astype("datetime64[D]") - nm.astype("datetime64[D]")
    ).astype(np.int64)
    day_off = np.minimum(day_off, month_len - 1)
    shifted = nm.astype("datetime64[D]") + day_off.astype("timedelta64[D]")
    if np.datetime_data(date.dtype)[0] in ("D", "M", "Y", "W"):
        return shifted
    # timestamp columns: preserve the time-of-day remainder and the dtype
    tod = date - d.astype(date.dtype)
    return shifted.astype(date.dtype) + tod


def _missing_mask(v) -> np.ndarray:
    """Missing-value mask under the framework convention: NaN for floats,
    NaT for datetimes, None for object arrays; all-False otherwise."""
    a = np.asarray(v)
    if a.dtype.kind == "f":
        return np.isnan(a)
    if a.dtype.kind == "M":
        return np.isnat(a)
    if a.dtype == object:
        try:
            import pandas as pd

            # C-speed elementwise missing check (None/NaN/NaT/pd.NA — a
            # compatible superset of the framework convention); the Python
            # loop was a per-row hotspot on string-heavy predicates
            return np.asarray(pd.isna(a.ravel()), dtype=bool).reshape(a.shape)
        except (TypeError, ValueError):  # exotic elements (nested arrays)
            return np.array(
                [x is None or (isinstance(x, float) and x != x) for x in a.ravel()],
                dtype=bool,
            ).reshape(a.shape)
    return np.zeros(a.shape, dtype=bool)


def _object_fill(type_name: str):
    """Neutral stand-in for NULL slots while converting an object array (the
    real NULLs are re-applied after the conversion; see Cast.eval)."""
    if type_name == "date":
        return "1970-01-01"
    if type_name in ("string", "char", "varchar", "text") or type_name.startswith(("char", "varchar")):
        return ""
    return 0


def _object_nums_to_float(arr: np.ndarray):
    """None -> NaN for numeric object arrays; non-numeric arrays unchanged."""
    try:
        return np.array(
            [np.nan if v is None else float(v) for v in arr.ravel()], dtype=np.float64
        ).reshape(arr.shape)
    except (TypeError, ValueError):
        return arr


class BinaryOp(Expr):
    def __init__(self, op: str, left: Expr, right: Expr):
        self.op = op
        self.left = left
        self.right = right

    def children(self) -> Sequence[Expr]:
        return (self.left, self.right)

    def eval(self, batch: Dict[str, np.ndarray]) -> np.ndarray:
        l = self.left.eval(batch)
        r = self.right.eval(batch)
        op = self.op
        if l is EMPTY_SCALAR or r is EMPTY_SCALAR:
            # a zero-row scalar subquery is SQL NULL: comparisons yield NULL
            # (three-valued), arithmetic propagates as NaN; a boolean NULL in
            # AND/OR still Kleene-combines with the other side below
            other = r if l is EMPTY_SCALAR else l
            shape = () if other is EMPTY_SCALAR else np.shape(other)
            null = NullableBool.all_null(shape)
            if op in ("AND", "OR"):
                l = null if l is EMPTY_SCALAR else l
                r = null if r is EMPTY_SCALAR else r
            elif op in ("=", "!=", "<", "<=", ">", ">="):
                return null
            else:
                # arithmetic on SQL NULL stays NULL: keep the sentinel so a
                # downstream comparison yields three-valued NULL, not False
                return EMPTY_SCALAR
        if op == "AND":
            return _kleene_and(l, r)
        if op == "OR":
            return _kleene_or(l, r)
        if isinstance(l, NullableBool) or isinstance(r, NullableBool):
            # boolean-typed NULL compared with = / != : stay null-aware
            lv, lu = _parts(l)
            rv, ru = _parts(r)
            if op == "=":
                return NullableBool(lv == rv, lu | ru)
            if op == "!=":
                return NullableBool(lv != rv, lu | ru)
            raise ValueError(f"Operator {op!r} undefined for boolean NULL operands")
        if op in _COMPARES:
            l, r = _coerce_compare(l, r)
            res = {
                "=": lambda: np.asarray(l == r),
                "!=": lambda: np.asarray(l != r),
                "<": lambda: np.asarray(l < r),
                "<=": lambda: np.asarray(l <= r),
                ">": lambda: np.asarray(l > r),
                ">=": lambda: np.asarray(l >= r),
            }[op]()
            # SQL NULL-is-unknown: a comparison touching NULL (NaN/NaT under
            # the framework's missing-value convention) is three-valued, not
            # definite — in particular NULL != x must not come out True
            unknown = _missing_mask(l) | _missing_mask(r)
            if np.any(unknown):
                return NullableBool(res & ~unknown, unknown)
            return res
        if op in ("+", "-"):
            mres = _maybe_add_months(l, r, op)
            if mres is not None:
                return mres
        # NULL semantics make 0/0 and NULL-operand arithmetic legitimate
        # (the NaN result IS the SQL NULL); numpy's RuntimeWarnings for them
        # are noise at this boundary, not a signal
        with np.errstate(divide="ignore", invalid="ignore"):
            if op == "+":
                return l + r
            if op == "-":
                return l - r
            if op == "*":
                return l * r
            if op == "/":
                return l / r
            if op == "%":
                return l % r
        raise ValueError(f"Unknown op {op!r}")

    def __repr__(self) -> str:
        return f"({self.left!r} {self.op} {self.right!r})"


class Not(Expr):
    def __init__(self, child: Expr):
        self.child = child

    def children(self) -> Sequence[Expr]:
        return (self.child,)

    def eval(self, batch: Dict[str, np.ndarray]) -> np.ndarray:
        return _kleene_not(self.child.eval(batch))

    def __repr__(self) -> str:
        return f"(NOT {self.child!r})"


class IsNull(Expr):
    def __init__(self, child: Expr):
        self.child = child

    def children(self) -> Sequence[Expr]:
        return (self.child,)

    def eval(self, batch: Dict[str, np.ndarray]) -> np.ndarray:
        v = self.child.eval(batch)
        if v is EMPTY_SCALAR:
            # IS NULL on a zero-row scalar subquery: true for every batch row
            n = next((c.shape[0] for c in batch.values() if getattr(c, "ndim", 0)), None)
            return np.ones((), dtype=bool) if n is None else np.ones(n, dtype=bool)
        if isinstance(v, NullableBool):
            return np.array(v.unknown)  # IS NULL of a three-valued boolean
        # one definition of "missing" everywhere: NaN, NaT, or None
        return _missing_mask(v)

    def __repr__(self) -> str:
        return f"({self.child!r} IS NULL)"


class In(Expr):
    def __init__(self, child: Expr, values: List[Lit]):
        self.child = child
        self.values = values

    def children(self) -> Sequence[Expr]:
        return (self.child, *self.values)

    def eval(self, batch: Dict[str, np.ndarray]) -> np.ndarray:
        v = self.child.eval(batch)
        vals = [x.value for x in self.values]
        return _in_semantics(v, vals)

    def __repr__(self) -> str:
        return f"({self.child!r} IN {[v.value for v in self.values]!r})"


def _in_semantics(v, vals):
    """SQL three-valued IN: TRUE on a non-NULL match; UNKNOWN when the child
    is NULL or any list value is NULL and nothing matched; FALSE otherwise.
    Shared by ``In`` (literal list) and ``InSubquery`` so host semantics match
    the device predicate compiler's Kleene pairs (exec/device.py)."""
    vals = np.asarray(vals) if not isinstance(vals, np.ndarray) else vals
    if vals.dtype == object or vals.dtype.kind in ("f", "M"):
        val_missing = _missing_mask(vals)
        has_null_value = bool(val_missing.any())
        non_null = vals[~val_missing]
    else:
        has_null_value = False
        non_null = vals
    res = np.isin(v, non_null)
    unknown = (_missing_mask(v) | has_null_value) & ~res
    if np.any(unknown):
        return NullableBool(res & ~unknown, unknown)
    return res


#: sentinel returned by a scalar subquery with zero rows (SQL NULL)
EMPTY_SCALAR = object()

# Per-execution subquery memoization: one outer collect() may evaluate the
# same condition more than once (partition pruning, then the row filter);
# the scope caches each subquery's result for the duration of the OUTERMOST
# execute so the inner plan runs once per query, never across queries (data
# may change between collects).
_subquery_scope = threading.local()


@contextlib.contextmanager
def subquery_scope():
    depth = getattr(_subquery_scope, "depth", 0)
    if depth == 0:
        _subquery_scope.cache = {}
    _subquery_scope.depth = depth + 1
    try:
        yield
    finally:
        _subquery_scope.depth -= 1
        if _subquery_scope.depth == 0:
            _subquery_scope.cache = None


class NullableBool:
    """Three-valued boolean result (Kleene logic): ``value`` where known,
    ``unknown`` marking SQL-NULL positions. Produced by comparisons against a
    zero-row scalar subquery; collapses to plain False at filter time
    (``as_bool_mask``), so NOT/AND/OR over NULL behave as SQL requires
    (NOT NULL = NULL, NULL OR TRUE = TRUE, NULL AND FALSE = FALSE)."""

    def __init__(self, value: np.ndarray, unknown: np.ndarray):
        self.value = np.asarray(value, dtype=bool)
        self.unknown = np.asarray(unknown, dtype=bool)

    @classmethod
    def all_null(cls, shape) -> "NullableBool":
        return cls(np.zeros(shape, dtype=bool), np.ones(shape, dtype=bool))


def as_bool_mask(x) -> np.ndarray:
    """Collapse an eval result to a definite boolean mask (NULL -> False)."""
    if isinstance(x, NullableBool):
        return x.value & ~x.unknown
    return np.asarray(x, dtype=bool)


def split_conjuncts(e: "Expr") -> List["Expr"]:
    """Flatten a tree of AND nodes into its conjunct list (a non-AND
    expression is its own single conjunct)."""
    if isinstance(e, BinaryOp) and e.op == "AND":
        return split_conjuncts(e.left) + split_conjuncts(e.right)
    return [e]


#: comparison operators a predicate atom may carry (plus "in" for IN-lists)
_ATOM_OPS = {"=", "!=", "<", "<=", ">", ">="}

_FLIP_OP = {"<": ">", "<=": ">=", ">": "<", ">=": "<=", "=": "=", "!=": "!="}


def comparison_atom(e: "Expr"):
    """``(column, op, value)`` for a simple comparison conjunct — a
    column-vs-literal comparison (normalized to column-on-the-left) or an
    IN-list over literals, which yields ``(column, "in", frozenset)``.
    None for anything else: the caller must treat the conjunct as opaque.
    Used by the serving result cache to decide predicate subsumption."""
    if isinstance(e, BinaryOp) and e.op in _ATOM_OPS:
        if isinstance(e.left, Col) and isinstance(e.right, Lit):
            return (e.left.name, e.op, _atom_value(e.right.value))
        if isinstance(e.left, Lit) and isinstance(e.right, Col):
            return (e.right.name, _FLIP_OP[e.op], _atom_value(e.left.value))
        return None
    if isinstance(e, In) and isinstance(e.child, Col) and all(
        isinstance(v, Lit) for v in e.values
    ):
        try:
            return (e.child.name, "in", frozenset(_atom_value(v.value) for v in e.values))
        except TypeError:
            return None  # unhashable literal: opaque
    return None


def _atom_value(v):
    """Unwrap numpy scalars so atom values compare with plain Python
    semantics."""
    return v.item() if isinstance(v, np.generic) else v


def _kleene_not(x):
    if isinstance(x, NullableBool):
        return NullableBool(~x.value, x.unknown)
    return np.logical_not(x)


def _parts(x):
    if isinstance(x, NullableBool):
        return x.value, x.unknown
    v = np.asarray(x, dtype=bool)
    return v, np.zeros(v.shape, dtype=bool)


def _kleene_and(l, r):
    if not isinstance(l, NullableBool) and not isinstance(r, NullableBool):
        return np.logical_and(l, r)
    lv, lu = _parts(l)
    rv, ru = _parts(r)
    known_false = (~lu & ~lv) | (~ru & ~rv)
    unknown = (lu | ru) & ~known_false
    return NullableBool(lv & rv & ~unknown, unknown)


def _kleene_or(l, r):
    if not isinstance(l, NullableBool) and not isinstance(r, NullableBool):
        return np.logical_or(l, r)
    lv, lu = _parts(l)
    rv, ru = _parts(r)
    known_true = (~lu & lv) | (~ru & rv)
    unknown = (lu | ru) & ~known_true
    return NullableBool(known_true, unknown)


def _to_value_array(v):
    """Collapse a three-valued boolean into a value array (NULL -> None) so
    non-boolean consumers (CAST, scalar functions, CASE values) see the same
    NULL-carrying column a projection would produce."""
    if isinstance(v, NullableBool):
        if np.any(v.unknown):
            out = v.value.astype(object)
            out[np.broadcast_to(v.unknown, v.value.shape)] = None
            return out
        return v.value
    return v


def _broadcast_rows(v, n: int) -> np.ndarray:
    v = np.asarray(_to_value_array(v))
    return np.broadcast_to(v, (n,)) if v.ndim == 0 else v


def _batch_rows(batch: Dict[str, np.ndarray]) -> int:
    for c in batch.values():
        if getattr(c, "ndim", 0):
            return c.shape[0]
    return 1


class Case(Expr):
    """SQL CASE WHEN ... THEN ... [ELSE ...] END; the unmatched default is
    SQL NULL (NaN for numeric results, None for strings)."""

    def __init__(self, branches, otherwise: Optional[Expr]):
        self.branches = [(c, v) for c, v in branches]
        self.otherwise = otherwise

    def children(self) -> Sequence[Expr]:
        out = []
        for c, v in self.branches:
            out.extend((c, v))
        if self.otherwise is not None:
            out.append(self.otherwise)
        return tuple(out)

    def eval(self, batch: Dict[str, np.ndarray]) -> np.ndarray:
        n = _batch_rows(batch)
        conds = [np.broadcast_to(as_bool_mask(c.eval(batch)), (n,)) for c, _ in self.branches]
        vals = [_broadcast_rows(v.eval(batch), n) for _, v in self.branches]
        otherwise = self.otherwise
        if isinstance(otherwise, Lit) and otherwise.value is None:
            otherwise = None  # ELSE NULL == no ELSE; keeps numeric dtype (NaN)
        if otherwise is not None:
            default = _broadcast_rows(otherwise.eval(batch), n)
        elif any(v.dtype.kind in ("U", "S", "O") for v in vals):
            default = np.full(n, None, dtype=object)
        else:
            default = np.full(n, np.nan)
        return np.select(conds, vals, default=default)

    def __repr__(self) -> str:
        parts = [f"WHEN {c!r} THEN {v!r}" for c, v in self.branches]
        if self.otherwise is not None:
            parts.append(f"ELSE {self.otherwise!r}")
        return f"CASE {' '.join(parts)} END"


class Like(Expr):
    """SQL LIKE with % (any run) and _ (any one char) wildcards."""

    def __init__(self, child: Expr, pattern: str):
        import re as _re

        self.child = child
        self.pattern = pattern
        rx = "^" + _re.escape(pattern).replace("%", ".*").replace("_", ".") + "$"
        self._rx = _re.compile(rx)

    def children(self) -> Sequence[Expr]:
        return (self.child,)

    def eval(self, batch: Dict[str, np.ndarray]):
        v = np.asarray(self.child.eval(batch))
        value = np.array(
            [x is not None and self._rx.match(str(x)) is not None for x in v.ravel()],
            dtype=bool,
        )
        unknown = _missing_mask(v).ravel()
        if unknown.any():  # NULL LIKE p is unknown (so NOT LIKE excludes it too)
            return NullableBool(value, unknown)
        return value

    def __repr__(self) -> str:
        return f"({self.child!r} LIKE {self.pattern!r})"


class Cast(Expr):
    """SQL CAST(expr AS type); types: int/bigint, double/float/decimal,
    date, string/char/varchar."""

    def __init__(self, child: Expr, type_name: str):
        self.child = child
        self.type_name = type_name.lower()

    def children(self) -> Sequence[Expr]:
        return (self.child,)

    def eval(self, batch: Dict[str, np.ndarray]) -> np.ndarray:
        v = np.asarray(_to_value_array(self.child.eval(batch)))
        t = self.type_name
        missing = _missing_mask(v)
        has_missing = bool(np.any(missing))
        if v.dtype == object and has_missing:
            # NULL-free view for the conversion; NULLs re-applied after
            v = np.where(missing, _object_fill(t), v)
        if t in ("int", "integer", "bigint", "smallint", "tinyint"):
            if has_missing:  # CAST(NULL AS int) is NULL: int64 can't hold it
                out = np.trunc(v.astype(np.float64))  # int cast truncates
                out[missing] = np.nan
                return out
            return v.astype(np.int64)
        if t in ("double", "float", "real") or t.startswith("decimal") or t.startswith("numeric"):
            out = v.astype(np.float64)
            if has_missing:
                out[missing] = np.nan
            return out
        if t == "date":
            out = v.astype("datetime64[D]")
            if has_missing:
                out[missing] = np.datetime64("NaT")
            return out
        if t in ("string", "char", "varchar", "text") or t.startswith(("char", "varchar")):
            out = v.astype(object)
            out = np.array([None if m else str(x) for x, m in zip(out.ravel(), missing.ravel())], dtype=object)
            return out.reshape(v.shape)
        raise ValueError(f"Unsupported CAST target type {self.type_name!r}")

    def __repr__(self) -> str:
        return f"CAST({self.child!r} AS {self.type_name})"


class Func(Expr):
    """Scalar SQL function call with a numpy evaluation per function."""

    SUPPORTED = (
        "substr", "substring", "coalesce", "nullif", "abs", "round", "floor",
        "ceil", "ceiling", "upper", "lower", "trim", "length", "concat",
        # date parts and arithmetic (Spark SQL functions lake queries lean on)
        "year", "month", "day", "dayofmonth", "quarter", "date_add", "date_sub",
        "datediff", "last_day", "trunc",
        # conditional / string utilities
        "if", "replace", "lpad", "rpad", "instr", "ltrim", "rtrim",
        "greatest", "least", "sign", "sqrt", "exp", "ln", "log", "power", "pow", "mod",
    )

    def __init__(self, name: str, args: Sequence[Expr]):
        self.name = name.lower()
        if self.name not in self.SUPPORTED:
            raise ValueError(f"Unsupported function {name!r}")
        self.args = list(args)
        if self.name == "trunc" and (len(self.args) < 2 or not isinstance(self.args[1], Lit)):
            # validated at construction so the SQL front-end surfaces a clean
            # SqlError instead of an eval-time failure
            raise ValueError("trunc(date, unit) requires a literal unit string")

    def children(self) -> Sequence[Expr]:
        return tuple(self.args)

    def eval(self, batch: Dict[str, np.ndarray]) -> np.ndarray:
        n = _batch_rows(batch)
        vals = [_broadcast_rows(a.eval(batch), n) for a in self.args]
        f = self.name
        if f in ("substr", "substring"):
            # SQL/Spark semantics: position 1-based, 0 treated like 1,
            # negative positions count from the end, and length applies from
            # the (possibly out-of-range) start position before clamping —
            # substring('abcde', -8, 3) is '' (not 'abc')
            s, start = vals[0], vals[1]
            ln = vals[2] if len(vals) > 2 else None
            out = []
            for i, x in enumerate(s):
                if x is None:
                    out.append(None)
                    continue
                text = str(x)
                pos = int(start[i]) if start.ndim else int(start)
                st = (pos - 1) if pos > 0 else (len(text) + pos if pos < 0 else 0)
                if ln is None:
                    en = len(text)
                else:
                    ll = int(ln[i]) if getattr(ln, "ndim", 0) else int(ln)
                    en = st + ll
                st_c = max(st, 0)
                out.append(text[st_c : max(en, st_c)])
            return np.array(out, dtype=object)
        if f == "coalesce":
            out = vals[0].astype(object, copy=True) if vals[0].dtype == object else vals[0].copy()
            for v in vals[1:]:
                if out.dtype.kind not in ("O", "f", "M"):
                    break
                miss = _missing_mask(out)
                if not miss.any():
                    break
                out = np.where(miss, v, out)
            return out
        if f == "nullif":
            a, b = vals
            eq = a == b
            if a.dtype.kind == "f":
                return np.where(eq, np.nan, a)
            out = a.astype(object)
            out[eq] = None
            return out
        if f == "abs":
            return np.abs(vals[0])
        if f == "round":
            d = 0
            if len(self.args) > 1:
                a1 = self.args[1]
                if isinstance(a1, Lit):
                    d = int(a1.value)
                elif getattr(vals[1], "size", 0):
                    d = int(np.asarray(vals[1]).ravel()[0])
            # SQL ROUND is HALF_UP (away from zero): round(2.5) = 3, while
            # np.round is banker's half-to-even (np.round(2.5) = 2)
            src = np.asarray(vals[0])
            v = src.astype(np.float64)
            scale = 10.0 ** d
            out = np.sign(v) * np.floor(np.abs(v) * scale + 0.5) / scale
            if src.dtype.kind in ("i", "u"):  # int in -> int out (Spark)
                return out.astype(src.dtype)
            return out
        if f == "floor":
            return np.floor(vals[0])
        if f in ("ceil", "ceiling"):
            return np.ceil(vals[0])
        if f == "upper":
            return np.array([None if x is None else str(x).upper() for x in vals[0]], dtype=object)
        if f == "lower":
            return np.array([None if x is None else str(x).lower() for x in vals[0]], dtype=object)
        if f == "trim":
            return np.array([None if x is None else str(x).strip() for x in vals[0]], dtype=object)
        if f == "length":
            # NULL in -> NULL out (NaN under the missing-value convention)
            return np.array(
                [np.nan if x is None else float(len(str(x))) for x in vals[0]], dtype=np.float64
            )
        if f == "concat":
            # SQL concat: any NULL operand -> NULL result
            missing = _missing_mask(vals[0])
            out = np.where(missing, "", vals[0].astype(str)).astype(object)
            for v in vals[1:]:
                m = _missing_mask(v)
                missing = missing | m
                out = np.char.add(out.astype(str), np.where(m, "", v.astype(str))).astype(object)
            if missing.any():
                out[missing] = None
            return out
        if f in ("year", "month", "day", "dayofmonth", "quarter"):
            d = np.asarray(vals[0]).astype("datetime64[D]")
            nat = np.isnat(d)
            y = d.astype("datetime64[Y]").astype(np.int64) + 1970
            if f == "year":
                out = y.astype(np.float64)
            else:
                mo = (d.astype("datetime64[M]").astype(np.int64) % 12) + 1
                if f == "month":
                    out = mo.astype(np.float64)
                elif f == "quarter":
                    out = ((mo - 1) // 3 + 1).astype(np.float64)
                else:  # day / dayofmonth
                    out = (d - d.astype("datetime64[M]").astype("datetime64[D]")).astype(
                        np.int64
                    ).astype(np.float64) + 1
            if nat.any():
                out[nat] = np.nan
            return out
        if f in ("date_add", "date_sub"):
            d = np.asarray(vals[0]).astype("datetime64[D]")
            nd = np.asarray(vals[1])
            delta = np.where(np.isnan(nd.astype(np.float64)), 0, nd).astype(np.int64)
            sign = 1 if f == "date_add" else -1
            out = d + (sign * delta).astype("timedelta64[D]")
            bad = np.isnat(d) | _missing_mask(nd)
            if bad.any():
                out[bad] = np.datetime64("NaT")
            return out
        if f == "datediff":
            a = np.asarray(vals[0]).astype("datetime64[D]")
            b = np.asarray(vals[1]).astype("datetime64[D]")
            out = (a - b).astype(np.int64).astype(np.float64)
            bad = np.isnat(a) | np.isnat(b)
            if bad.any():
                out[bad] = np.nan
            return out
        if f == "last_day":
            d = np.asarray(vals[0]).astype("datetime64[D]")
            m = d.astype("datetime64[M]")
            out = (m + np.timedelta64(1, "M")).astype("datetime64[D]") - np.timedelta64(1, "D")
            nat = np.isnat(d)
            if nat.any():
                out[nat] = np.datetime64("NaT")
            return out
        if f == "trunc":
            if len(self.args) < 2 or not isinstance(self.args[1], Lit):
                raise ValueError("trunc(date, unit) requires a literal unit string")
            d = np.asarray(vals[0]).astype("datetime64[D]")
            unit = str(self.args[1].value).lower()
            if unit in ("year", "yyyy", "yy"):
                out = d.astype("datetime64[Y]").astype("datetime64[D]")
            elif unit in ("month", "mon", "mm"):
                out = d.astype("datetime64[M]").astype("datetime64[D]")
            else:
                raise ValueError(f"trunc: unsupported unit {unit!r}")
            nat = np.isnat(d)
            if nat.any():
                out[nat] = np.datetime64("NaT")
            return out
        if f == "if":
            # vals[0] already holds the evaluated condition (NULL -> None
            # via _to_value_array); NULL conditions take the else arm
            c0 = vals[0]
            if c0.dtype == object:
                cond = np.array([v is not None and bool(v) for v in c0], dtype=bool)
            elif c0.dtype.kind == "f":
                cond = ~np.isnan(c0) & (c0 != 0)
            else:
                cond = c0.astype(bool)
            return np.where(cond, vals[1], vals[2])
        if f == "replace":
            # all arguments are per-row (columns or broadcast literals)
            repl = vals[2] if len(vals) > 2 else np.full(n, "", dtype=object)
            return np.array(
                [
                    None if (x is None or sr is None or rp is None)
                    else str(x).replace(str(sr), str(rp))
                    for x, sr, rp in zip(vals[0], vals[1], repl)
                ],
                dtype=object,
            )
        if f in ("lpad", "rpad"):
            pads = vals[2] if len(vals) > 2 else np.full(n, " ", dtype=object)
            widths = vals[1]
            out = []
            for x, w, p in zip(vals[0], widths, pads):
                if x is None or p is None or (isinstance(w, float) and w != w):
                    out.append(None)
                    continue
                s, width, pad = str(x), int(w), str(p)
                if len(s) >= width:
                    out.append(s[:width])
                else:
                    fill = (pad * width)[: width - len(s)] if pad else ""
                    out.append(fill + s if f == "lpad" else s + fill)
            return np.array(out, dtype=object)
        if f == "instr":
            return np.array(
                [
                    np.nan if (x is None or sr is None) else float(str(x).find(str(sr)) + 1)
                    for x, sr in zip(vals[0], vals[1])
                ],
                dtype=np.float64,
            )
        if f in ("ltrim", "rtrim"):
            strip = (lambda s: s.lstrip()) if f == "ltrim" else (lambda s: s.rstrip())
            return np.array(
                [None if x is None else strip(str(x)) for x in vals[0]], dtype=object
            )
        if f in ("greatest", "least"):
            pick = np.fmax if f == "greatest" else np.fmin
            out = np.asarray(vals[0], dtype=np.float64)
            for v in vals[1:]:
                out = pick(out, np.asarray(v, dtype=np.float64))
            return out
        if f == "sign":
            return np.sign(np.asarray(vals[0], dtype=np.float64))
        if f == "sqrt":
            # sqrt(negative) / log(0) / 0^-1 produce NaN/inf under SQL NULL
            # semantics on purpose; keep numpy's RuntimeWarnings out of user
            # output at this evaluation boundary
            with np.errstate(invalid="ignore"):
                return np.sqrt(np.asarray(vals[0], dtype=np.float64))
        if f == "exp":
            return np.exp(np.asarray(vals[0], dtype=np.float64))
        if f in ("ln", "log"):
            with np.errstate(divide="ignore", invalid="ignore"):
                if f == "log" and len(vals) > 1:  # log(base, expr), Spark-style
                    return np.log(np.asarray(vals[1], dtype=np.float64)) / np.log(
                        np.asarray(vals[0], dtype=np.float64)
                    )
                return np.log(np.asarray(vals[0], dtype=np.float64))
        if f in ("power", "pow"):
            with np.errstate(divide="ignore", invalid="ignore"):
                return np.power(np.asarray(vals[0], dtype=np.float64), vals[1])
        if f == "mod":
            # same boundary stance as the % operator above: MOD(x, 0) is
            # SQL NULL (NaN), not a numpy RuntimeWarning
            with np.errstate(divide="ignore", invalid="ignore"):
                return np.mod(vals[0], vals[1])
        raise ValueError(f"Unsupported function {self.name!r}")

    def __repr__(self) -> str:
        return f"{self.name}({', '.join(repr(a) for a in self.args)})"


class SubqueryExpr(Expr):
    """Uncorrelated subquery carrying an inner relational plan.

    The reference delegates subquery planning to Spark and its rules rewrite
    the *inner* scans transparently (explain golden
    src/test/resources/expected/spark-2.4/subquery.txt); here the IR carries
    the inner plan itself and ``ApplyHyperspace`` recurses into it, so index
    rewrites apply inside subqueries exactly as they do at top level.
    Correlated subqueries are out of scope (as are they for the reference's
    rules, which never see the correlation)."""

    def __init__(self, plan, session):
        self.plan = plan
        self.session = session

    def with_plan(self, plan) -> "SubqueryExpr":
        return type(self)(plan, self.session)

    def _values(self) -> np.ndarray:
        from hyperspace_tpu.exec.executor import Executor

        cache = getattr(_subquery_scope, "cache", None)
        if cache is not None and id(self) in cache:
            return cache[id(self)]
        out_cols = list(self.plan.output_columns)
        if len(out_cols) != 1:
            raise ValueError(f"subquery must return exactly one column, got {out_cols!r}")
        vals = Executor(self.session).execute(self.plan, required_columns=out_cols)[out_cols[0]]
        if cache is not None:
            cache[id(self)] = vals
        return vals

    def plan_summary(self) -> str:
        nodes: List[str] = []

        def walk(p) -> None:
            nodes.append(p.describe())
            for c in p.children():
                walk(c)

        walk(self.plan)
        return " / ".join(nodes)


class ScalarSubquery(SubqueryExpr):
    """Single-value subquery usable as a comparison operand
    (``col("a") == df2.filter(...).select("b").as_scalar()``)."""

    def eval(self, batch: Dict[str, np.ndarray]):
        v = self._values()
        if len(v) > 1:
            raise ValueError(f"scalar subquery returned {len(v)} rows, expected at most 1")
        if len(v) == 0:
            return EMPTY_SCALAR
        return np.asarray(v[0])

    def __repr__(self) -> str:
        return f"scalar-subquery[{self.plan_summary()}]"


class InSubquery(SubqueryExpr):
    """Semi-join membership test (``col("a").isin(df2.select("b"))``)."""

    def __init__(self, child: Expr, plan, session):
        super().__init__(plan, session)
        self.child = child

    def children(self) -> Sequence[Expr]:
        return (self.child,)

    def with_plan(self, plan) -> "InSubquery":
        return InSubquery(self.child, plan, self.session)

    def eval(self, batch: Dict[str, np.ndarray]) -> np.ndarray:
        return _in_semantics(self.child.eval(batch), np.asarray(self._values()))

    def __repr__(self) -> str:
        return f"({self.child!r} IN subquery[{self.plan_summary()}])"


class CorrelatedScalarSubquery(SubqueryExpr):
    """Decorrelated correlated scalar subquery (the reference gets these from
    Spark's RewriteCorrelatedScalarSubquery; TPC-DS q1/q6/q30/q32/q41/q81/q92).

    The inner plan is the subquery grouped by its correlation keys
    (``key_cols``) with the scalar item as ``value_col``; eval maps each
    outer row's correlation-key tuple to the group's value. A missing group
    (or a NULL outer key — equality with NULL never matches) yields
    ``default``: SQL NULL normally, 0 for a bare COUNT (the classic
    count-bug: COUNT over zero rows is 0, not NULL)."""

    def __init__(self, outer_keys, plan, key_cols, value_col: str, default, session):
        super().__init__(plan, session)
        self.outer_keys = list(outer_keys)
        self.key_cols = list(key_cols)
        self.value_col = value_col
        self.default = default  # None => SQL NULL

    def children(self) -> Sequence[Expr]:
        return tuple(self.outer_keys)

    def with_plan(self, plan) -> "CorrelatedScalarSubquery":
        return CorrelatedScalarSubquery(
            self.outer_keys, plan, self.key_cols, self.value_col, self.default, self.session
        )

    def _exec_inner(self):
        from hyperspace_tpu.exec.executor import Executor

        cache = getattr(_subquery_scope, "cache", None)
        if cache is not None and id(self) in cache:
            return cache[id(self)]
        cols = [*self.key_cols, self.value_col]
        got = Executor(self.session).execute(self.plan, required_columns=cols)
        if cache is not None:
            cache[id(self)] = got
        return got

    def eval(self, batch: Dict[str, np.ndarray]):
        import pandas as pd

        inner = self._exec_inner()
        n = _batch_rows(batch)
        knames = [f"__k{i}" for i in range(len(self.key_cols))]
        okeys = [_broadcast_rows(k.eval(batch), n) for k in self.outer_keys]
        left = pd.DataFrame({kn: k for kn, k in zip(knames, okeys)})
        left["__row"] = np.arange(n)
        right = pd.DataFrame({kn: np.asarray(inner[kc]) for kn, kc in zip(knames, self.key_cols)})
        right["__v"] = np.asarray(inner[self.value_col])
        # NULL correlation keys never match (pandas merge would match NaN=NaN)
        omiss = np.zeros(n, dtype=bool)
        for k in okeys:
            omiss |= _missing_mask(k)
        imiss = np.zeros(len(right), dtype=bool)
        for kc in self.key_cols:
            imiss |= _missing_mask(np.asarray(inner[kc]))
        if imiss.any():
            right = right[~imiss]
        merged = left.merge(right, on=knames, how="left", indicator=True)
        if len(merged) != n:
            raise ValueError(
                "correlated scalar subquery returned more than one row per correlation key"
            )
        merged = merged.sort_values("__row", kind="stable")
        vals = merged["__v"].to_numpy()
        missing = (merged["_merge"].to_numpy() == "left_only") | omiss
        if missing.any():
            fill = np.nan if self.default is None else self.default
            if vals.dtype == object:
                vals = vals.copy()
                vals[missing] = None if self.default is None else self.default
            elif np.issubdtype(vals.dtype, np.datetime64):
                # keep the datetime dtype — casting to float64 would leak raw
                # epoch numbers into downstream date comparisons
                vals = vals.copy()
                vals[missing] = (
                    np.datetime64("NaT") if self.default is None else self.default
                )
            else:
                vals = vals.astype(np.float64, copy=True)
                vals[missing] = fill
        return vals

    def __repr__(self) -> str:
        return f"correlated-scalar-subquery[keys={self.key_cols}; {self.plan_summary()}]"


def _correlation_frames(outer_keys, key_cols, inner, batch):
    """Shared scaffolding for the correlated subquery marks: broadcast and
    evaluate the outer correlation keys, build the outer (left) frame with a
    ``__row`` id, the inner (right) frame keyed by ``key_cols``, and the
    NULL-key masks (a NULL correlation key never matches on either side).
    Returns (n, left_df, right_df, outer_null_mask, inner_null_mask); right
    rows with NULL keys are already dropped, and ``inner_null_mask`` (over
    the UNFILTERED inner rows) lets callers align extra inner columns with
    the filtered right frame."""
    import pandas as pd

    n = _batch_rows(batch)
    okeys = [_broadcast_rows(k.eval(batch), n) for k in outer_keys]
    omiss = np.zeros(n, dtype=bool)
    for k in okeys:
        omiss |= _missing_mask(k)
    left = pd.DataFrame({kc: k for kc, k in zip(key_cols, okeys)})
    left["__row"] = np.arange(n)
    right = pd.DataFrame({kc: np.asarray(inner[kc]) for kc in key_cols})
    imiss = np.zeros(len(right), dtype=bool)
    for kc in key_cols:
        imiss |= _missing_mask(np.asarray(inner[kc]))
    if imiss.any():
        right = right[~imiss]
    return n, left, right, omiss, imiss


class ExistsSubquery(SubqueryExpr):
    """Decorrelated EXISTS mark (semi-join membership; the reference gets
    these from Spark's RewritePredicateSubquery as left-semi/anti joins;
    TPC-DS q10/q16/q35/q69/q94).

    ``outer_keys[i] = inner key_cols[i]`` are the equi-correlation pairs.
    ``residual`` (optional) is a predicate over the matched pair, referencing
    inner columns by their projected names and outer values through the
    ``residual_outer`` placeholder columns (q16/q94's
    ``cs1.cs_warehouse_sk <> cs2.cs_warehouse_sk``). EXISTS is two-valued —
    TRUE/FALSE, never unknown — so NOT EXISTS is the plain Not wrapper."""

    def __init__(self, outer_keys, plan, key_cols, residual, residual_outer, session):
        super().__init__(plan, session)
        self.outer_keys = list(outer_keys)
        self.key_cols = list(key_cols)
        self.residual = residual
        self.residual_outer = list(residual_outer)  # [(placeholder, outer Expr)]

    def children(self) -> Sequence[Expr]:
        return tuple(self.outer_keys) + tuple(e for _, e in self.residual_outer)

    def with_plan(self, plan) -> "ExistsSubquery":
        return ExistsSubquery(
            self.outer_keys, plan, self.key_cols, self.residual, self.residual_outer, self.session
        )

    def _exec_inner(self):
        from hyperspace_tpu.exec.executor import Executor

        cache = getattr(_subquery_scope, "cache", None)
        if cache is not None and id(self) in cache:
            return cache[id(self)]
        got = Executor(self.session).execute(
            self.plan, required_columns=list(self.plan.output_columns)
        )
        if cache is not None:
            cache[id(self)] = got
        return got

    def eval(self, batch: Dict[str, np.ndarray]) -> np.ndarray:
        inner = self._exec_inner()
        if not self.key_cols:
            # uncorrelated EXISTS: a constant row-existence mark
            any_row = any(getattr(c, "shape", (0,))[0] for c in inner.values())
            return np.full(_batch_rows(batch), bool(any_row))
        n, left, right, omiss, imiss = _correlation_frames(
            self.outer_keys, self.key_cols, inner, batch
        )
        for ph, e in self.residual_outer:
            left[ph] = _broadcast_rows(e.eval(batch), n)
        for c in inner:  # residual inner columns ride along
            if c not in self.key_cols and not c.startswith("__input"):
                col_ = np.asarray(inner[c])
                right[c] = col_[~imiss] if imiss.any() else col_
        merged = left.merge(right, on=self.key_cols, how="inner")
        mask = np.zeros(n, dtype=bool)
        if len(merged):
            if self.residual is not None:
                mbatch = {c: merged[c].to_numpy() for c in merged.columns}
                keep = as_bool_mask(self.residual.eval(mbatch))
                rows = merged["__row"].to_numpy()[keep]
            else:
                rows = merged["__row"].to_numpy()
            mask[np.unique(rows)] = True
        mask &= ~omiss  # a NULL correlation key can never match
        return mask

    def __repr__(self) -> str:
        res = f", residual={self.residual!r}" if self.residual is not None else ""
        return f"exists-subquery[keys={self.key_cols}{res}; {self.plan_summary()}]"


class CorrelatedInSubquery(SubqueryExpr):
    """Decorrelated correlated IN: ``x IN (SELECT v FROM ... WHERE
    outer.k = inner.k AND ...)`` with full three-valued SQL semantics per
    outer row over its correlation group S = {v of matching inner rows}:
    TRUE on a non-NULL match; UNKNOWN when nothing matched but S contains
    NULL, or x is NULL and S is non-empty; FALSE otherwise (including empty
    S, even for NULL x). NOT IN composes through Kleene Not (the reference
    gets this from Spark's null-aware anti join)."""

    def __init__(self, child: Expr, outer_keys, plan, key_cols, value_col: str, session):
        super().__init__(plan, session)
        self.child = child
        self.outer_keys = list(outer_keys)
        self.key_cols = list(key_cols)
        self.value_col = value_col

    def children(self) -> Sequence[Expr]:
        return (self.child, *self.outer_keys)

    def with_plan(self, plan) -> "CorrelatedInSubquery":
        return CorrelatedInSubquery(
            self.child, self.outer_keys, plan, self.key_cols, self.value_col, self.session
        )

    def _exec_inner(self):
        from hyperspace_tpu.exec.executor import Executor

        cache = getattr(_subquery_scope, "cache", None)
        if cache is not None and id(self) in cache:
            return cache[id(self)]
        cols = [*self.key_cols, self.value_col]
        got = Executor(self.session).execute(self.plan, required_columns=cols)
        if cache is not None:
            cache[id(self)] = got
        return got

    def eval(self, batch: Dict[str, np.ndarray]):
        inner = self._exec_inner()
        n, left, right, omiss, imiss = _correlation_frames(
            self.outer_keys, self.key_cols, inner, batch
        )
        x = _broadcast_rows(self.child.eval(batch), n)
        x_null = _missing_mask(x)
        left["__x"] = x
        vals = np.asarray(inner[self.value_col])
        vnull_all = _missing_mask(vals)
        if imiss.any():
            vals, vnull_all = vals[~imiss], vnull_all[~imiss]
        right["__v"] = vals
        right["__vnull"] = vnull_all
        value = np.zeros(n, dtype=bool)
        unknown = np.zeros(n, dtype=bool)
        if len(right):
            merged = left.merge(right, on=self.key_cols)
            if len(merged):
                mx = merged["__x"].to_numpy()
                mv = merged["__v"].to_numpy()
                vnull = merged["__vnull"].to_numpy(dtype=bool)
                both = ~(_missing_mask(mx) | vnull)
                pair_match = np.zeros(len(merged), dtype=bool)
                pair_match[both] = mx[both] == mv[both]
                rows = merged["__row"].to_numpy()
                np.logical_or.at(value, rows, pair_match)
                has_null_in_group = np.zeros(n, dtype=bool)
                np.logical_or.at(has_null_in_group, rows, vnull)
                nonempty = np.zeros(n, dtype=bool)
                nonempty[np.unique(rows)] = True
                unknown = ~value & (has_null_in_group | (x_null & nonempty))
        # NULL outer correlation key: the correlation equality is never true,
        # so S is empty -> definite FALSE
        value &= ~omiss
        unknown &= ~omiss
        if unknown.any():
            return NullableBool(value, unknown)
        return value

    def __repr__(self) -> str:
        return f"({self.child!r} IN correlated-subquery[keys={self.key_cols}; {self.plan_summary()}])"


def _wrap(x: Any) -> Expr:
    return x if isinstance(x, Expr) else Lit(x)


def col(name: str) -> Col:
    return Col(name)


def lit(value: Any) -> Lit:
    return Lit(value)


def input_file_name() -> InputFileName:
    return InputFileName()


# --- analysis helpers used by optimizer rules ------------------------------

def contains_input_file_name(e: Expr) -> bool:
    """True if the expression references input_file_name(). Index rewrites
    must bail out on such predicates: after the rewrite the function would
    evaluate to *index* file paths, silently changing results."""
    if isinstance(e, InputFileName):
        return True
    return any(contains_input_file_name(c) for c in e.children())


def split_conjunctive(e: Expr) -> List[Expr]:
    """Split a predicate on top-level ANDs (CNF split used by
    FilterIndexRule/JoinIndexRule; ref: HS/index/covering/JoinIndexRule.scala:149-155)."""
    if isinstance(e, BinaryOp) and e.op == "AND":
        return split_conjunctive(e.left) + split_conjunctive(e.right)
    return [e]


def extract_equi_join_keys(e: Expr) -> Optional[List[tuple]]:
    """If ``e`` is a conjunction of ``col = col`` terms, return the (left, right)
    column-name pairs; else None (ref: JoinPlanNodeFilter's equi-join CNF check,
    HS/index/covering/JoinIndexRule.scala:135-155)."""
    pairs = []
    for term in split_conjunctive(e):
        if isinstance(term, BinaryOp) and term.op == "=" and isinstance(term.left, Col) and isinstance(term.right, Col):
            pairs.append((term.left.name, term.right.name))
        else:
            return None
    return pairs


def extract_eq_literal(e: Expr) -> Optional[tuple]:
    """If ``e`` is ``col = lit`` or ``lit = col``, return (col_name, value)."""
    if isinstance(e, BinaryOp) and e.op == "=":
        if isinstance(e.left, Col) and isinstance(e.right, Lit):
            return (e.left.name, e.right.value)
        if isinstance(e.right, Col) and isinstance(e.left, Lit):
            return (e.right.name, e.left.value)
    return None


def rewrite_columns(e: Expr, mapping: Dict[str, str]) -> Expr:
    """Return a copy of ``e`` with column names rewritten via ``mapping``."""
    if isinstance(e, Col):
        return Col(mapping.get(e.name, e.name))
    if isinstance(e, BinaryOp):
        return BinaryOp(e.op, rewrite_columns(e.left, mapping), rewrite_columns(e.right, mapping))
    if isinstance(e, Not):
        return Not(rewrite_columns(e.child, mapping))
    if isinstance(e, IsNull):
        return IsNull(rewrite_columns(e.child, mapping))
    if isinstance(e, In):
        return In(rewrite_columns(e.child, mapping), list(e.values))
    if isinstance(e, InSubquery):
        return InSubquery(rewrite_columns(e.child, mapping), e.plan, e.session)
    if isinstance(e, CorrelatedScalarSubquery):
        return CorrelatedScalarSubquery(
            [rewrite_columns(k, mapping) for k in e.outer_keys],
            e.plan, e.key_cols, e.value_col, e.default, e.session,
        )
    if isinstance(e, ExistsSubquery):
        return ExistsSubquery(
            [rewrite_columns(k, mapping) for k in e.outer_keys],
            e.plan, e.key_cols, e.residual,
            [(ph, rewrite_columns(x, mapping)) for ph, x in e.residual_outer],
            e.session,
        )
    if isinstance(e, CorrelatedInSubquery):
        return CorrelatedInSubquery(
            rewrite_columns(e.child, mapping),
            [rewrite_columns(k, mapping) for k in e.outer_keys],
            e.plan, e.key_cols, e.value_col, e.session,
        )
    if isinstance(e, Case):
        return Case(
            [(rewrite_columns(c, mapping), rewrite_columns(v, mapping)) for c, v in e.branches],
            rewrite_columns(e.otherwise, mapping) if e.otherwise is not None else None,
        )
    if isinstance(e, Like):
        return Like(rewrite_columns(e.child, mapping), e.pattern)
    if isinstance(e, Cast):
        return Cast(rewrite_columns(e.child, mapping), e.type_name)
    if isinstance(e, Func):
        return Func(e.name, [rewrite_columns(a, mapping) for a in e.args])
    return e
