"""Column resolution.

Case-insensitive resolution of user column names against a plan's output,
including nested struct fields normalized with the ``__hs_nested.`` prefix
(ref: HS/util/ResolverUtils.scala:33-233 — ``ResolvedColumn`` normalization
:44-105, struct traversal :160-181, array/map rejection :185-195).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Sequence

import pyarrow as pa

from hyperspace_tpu.plan.expr import NESTED_PREFIX, Col, Expr, rewrite_columns


@dataclass(frozen=True)
class ResolvedColumn:
    """A resolved column; nested fields carry the normalization prefix in
    ``normalized_name`` (e.g. ``a.b`` -> ``__hs_nested.a.b``)."""

    name: str
    is_nested: bool = False

    @property
    def normalized_name(self) -> str:
        return (NESTED_PREFIX + self.name) if self.is_nested else self.name

    @classmethod
    def from_normalized(cls, normalized: str) -> "ResolvedColumn":
        if normalized.startswith(NESTED_PREFIX):
            return cls(normalized[len(NESTED_PREFIX):], True)
        return cls(normalized, False)


def _resolve_against_schema(name: str, schema: pa.Schema) -> Optional[ResolvedColumn]:
    for f in schema:
        if f.name.lower() == name.lower():
            return ResolvedColumn(f.name, False)
    # nested struct path a.b.c
    parts = name.split(".")
    if len(parts) > 1:
        field = None
        resolved_parts: List[str] = []
        fields = list(schema)
        for i, part in enumerate(parts):
            match = next((f for f in fields if f.name.lower() == part.lower()), None)
            if match is None:
                return None
            if pa.types.is_list(match.type) or pa.types.is_map(match.type):
                raise ValueError(f"Array/map field {match.name!r} cannot be indexed (ref: ResolverUtils.scala:185-195)")
            resolved_parts.append(match.name)
            field = match
            if i < len(parts) - 1:
                if not pa.types.is_struct(field.type):
                    return None
                fields = [field.type.field(j) for j in range(field.type.num_fields)]
        return ResolvedColumn(".".join(resolved_parts), True)
    return None


def resolve_column(name: str, available: Sequence[str]) -> Optional[str]:
    """Resolve ``name`` case-insensitively against flat column names; a
    dotted nested path resolves when its root column does (the remaining
    segments resolve at execution against the struct values)."""
    for a in available:
        if a.lower() == name.lower():
            return a
    if "." in name:
        root, _, rest = name.partition(".")
        for a in available:
            if a.lower() == root.lower():
                return f"{a}.{rest}"
    return None


def resolve_columns_against_schema(names: Sequence[str], schema: pa.Schema) -> List[ResolvedColumn]:
    out = []
    for n in names:
        r = _resolve_against_schema(n, schema)
        if r is None:
            raise ValueError(f"Column {n!r} could not be resolved against schema {schema.names}")
        out.append(r)
    return out


def resolve_expr(e: Expr, available: Sequence[str]) -> Expr:
    """Rewrite column refs in ``e`` to their resolved (exact-case) names."""
    mapping = {}
    for ref in e.references():
        resolved = resolve_column(ref, available)
        if resolved is None:
            raise ValueError(f"Column {ref!r} could not be resolved among {list(available)}")
        if resolved != ref:
            mapping[ref] = resolved
    return rewrite_columns(e, mapping) if mapping else e
