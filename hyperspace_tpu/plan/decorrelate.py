"""Subquery decorrelation: correlated scalar subqueries and EXISTS.

The reference inherits decorrelation from Spark Catalyst
(RewriteCorrelatedScalarSubquery / RewritePredicateSubquery rewrite them to
aggregated-join / semi-join plans before Hyperspace's rules ever run); this
framework owns its query surface, so the same rewrites live here:

- ``EXISTS (SELECT ... WHERE outer.a = inner.b AND <inner preds> [AND
  residual])`` becomes an ``ExistsSubquery`` mark: the inner query is planned
  *uncorrelated* (correlation conjuncts removed, needed columns projected,
  DISTINCT), and eval semi-joins the outer rows against it on the equi pairs,
  applying any non-equi residual per matched pair (TPC-DS q10/q16/q35/q69/q94).
- ``(SELECT agg(...) FROM ... WHERE outer.k = inner.k AND <inner preds>)``
  becomes a ``CorrelatedScalarSubquery``: the inner query is re-planned as
  GROUP BY the correlation keys with the scalar item as the value column;
  eval maps each outer key tuple to its group value, the count-bug handled by
  a 0 default for bare COUNTs (TPC-DS q1/q6/q30/q32/q41/q81/q92).

Correlation detection is scope-based: a reference is *inner* when it resolves
against the subquery's own FROM tables (qualified by an inner alias, or
unqualified and found in an inner table's columns — inner shadows outer, SQL
name resolution); anything else is an outer reference.
"""

from __future__ import annotations

import copy
from typing import Dict, List, Optional, Tuple

from hyperspace_tpu.plan.expr import (
    BinaryOp,
    Col,
    CorrelatedInSubquery,
    CorrelatedScalarSubquery,
    ExistsSubquery,
    Expr,
    split_conjunctive,
)


class _Unsupported(Exception):
    """Raised when a correlated shape falls outside the supported rewrites;
    surfaced to the user as SqlError by the caller."""


def _inner_scope(iq, views) -> Dict[str, Dict[str, str]]:
    """alias(lower) -> {column(lower) -> actual name} for every table in the
    inner query's FROM (including JOIN ... ON refs and derived tables)."""
    from hyperspace_tpu.plan.sql import SqlError, plan_query

    scope: Dict[str, Dict[str, str]] = {}
    for elem in iq.from_elements:
        for tref in [elem.table_ref] + [j.table_ref for j in elem.joins]:
            if isinstance(tref.source, str):
                if tref.source not in views:
                    raise SqlError(f"Unknown table/view {tref.source!r} in subquery")
                cols = views[tref.source].plan.output_columns
            else:
                cols = plan_query(tref.source, views).plan.output_columns
            scope[tref.alias.lower()] = {c.lower(): c for c in cols}
    return scope


def _classify_ref(name: str, scope) -> Tuple[str, str]:
    """('inner', actual) | ('outer', name). Inner shadows outer for
    unqualified names (SQL scoping); a qualifier naming an inner alias must
    resolve inside it."""
    if "." in name:
        qual, rest = name.split(".", 1)
        m = scope.get(qual.lower())
        if m is not None:
            got = m.get(rest.lower())
            if got is None:
                raise _Unsupported(f"column {name!r} not found in subquery alias {qual!r}")
            return ("inner", got)
        return ("outer", name)
    ln = name.lower()
    for m in scope.values():
        got = m.get(ln)
        if got is not None:
            return ("inner", got)
    return ("outer", name)


def _split_correlation(iq, views):
    """Split the inner WHERE into (inner-pure conjuncts, correlated
    conjuncts) after OR-factoring (q41 repeats its correlation conjunct in
    both OR branches; factoring lifts it to the top level)."""
    from hyperspace_tpu.plan.sql import _factor_or_common

    scope = _inner_scope(iq, views)
    inner_preds: List[Expr] = []
    correlated: List[Expr] = []
    if iq.where is not None:
        for term in split_conjunctive(_factor_or_common(iq.where)):
            sides = {_classify_ref(r, scope)[0] for r in term.references()}
            (correlated if "outer" in sides else inner_preds).append(term)
    return scope, inner_preds, correlated


def _side_of(e: Expr, scope) -> Optional[str]:
    """'inner' / 'outer' when every reference classifies the same way."""
    refs = e.references()
    if not refs:
        return None
    sides = {_classify_ref(r, scope)[0] for r in refs}
    return sides.pop() if len(sides) == 1 else None


def is_correlated(iq, views) -> bool:
    """True when any WHERE conjunct of ``iq`` references the outer scope."""
    try:
        _, _, correlated = _split_correlation(iq, views)
    except _Unsupported:
        return False
    return bool(correlated)


def _rewrite_names(e: Expr, mapping: Dict[str, str]) -> Expr:
    from hyperspace_tpu.plan.sql import _rewrite

    return _rewrite(e, mapping) if mapping else e


def _equi_pairs_and_residual(correlated, scope):
    """Partition correlated conjuncts into equi pairs
    [(outer Expr, inner Col name)] and residual conjuncts (kept whole)."""
    pairs: List[Tuple[Expr, str]] = []
    residual: List[Expr] = []
    for term in correlated:
        if isinstance(term, BinaryOp) and term.op == "=":
            ls, rs = _side_of(term.left, scope), _side_of(term.right, scope)
            if {ls, rs} == {"inner", "outer"}:
                outer_e = term.left if ls == "outer" else term.right
                inner_e = term.right if ls == "outer" else term.left
                if isinstance(inner_e, Col):
                    pairs.append((outer_e, _classify_ref(inner_e.name, scope)[1]))
                    continue
        residual.append(term)
    return pairs, residual


def decorrelate_exists(iq, views, session, outer_resolve) -> ExistsSubquery:
    """Build the ExistsSubquery mark for a (possibly correlated) EXISTS."""
    from hyperspace_tpu.plan.sql import (
        SelectItem,
        SqlError,
        _resolve_expr_refs,
        plan_query,
    )

    if iq.unions or iq.group_by or iq.having is not None:
        raise SqlError("EXISTS subqueries with set operations or GROUP BY are not supported")
    try:
        scope, inner_preds, correlated = _split_correlation(iq, views)
        pairs, residual_terms = _equi_pairs_and_residual(correlated, scope)
    except _Unsupported as e:
        raise SqlError(f"Unsupported EXISTS subquery: {e}")
    if not pairs and residual_terms:
        raise SqlError(
            "Correlated EXISTS needs at least one equality correlation "
            "(outer.col = inner.col) alongside non-equi predicates"
        )

    key_cols = [f"__k{i}" for i in range(len(pairs))]
    items = [
        SelectItem(Col(inner_name), kc, inner_name)
        for kc, (_, inner_name) in zip(key_cols, pairs)
    ]
    # residual conjuncts reference inner columns (projected as __v{i}) and
    # outer values (placeholder columns __exo{i} evaluated over the outer
    # batch at eval time)
    residual_outer: List[Tuple[str, Expr]] = []
    residual_expr: Optional[Expr] = None
    if residual_terms:
        mapping: Dict[str, str] = {}
        v_seen: Dict[str, str] = {}
        o_seen: Dict[str, str] = {}
        for term in residual_terms:
            for r in sorted(term.references()):
                side, actual = _classify_ref(r, scope)
                if side == "inner":
                    if actual not in v_seen:
                        v_seen[actual] = f"__v{len(v_seen)}"
                        items.append(SelectItem(Col(actual), v_seen[actual], actual))
                    mapping[r] = v_seen[actual]
                else:
                    if r not in o_seen:
                        o_seen[r] = f"__exo{len(o_seen)}"
                        residual_outer.append(
                            (o_seen[r], _resolve_expr_refs(Col(r), outer_resolve))
                        )
                    mapping[r] = o_seen[r]
        rewritten = [_rewrite_names(t, mapping) for t in residual_terms]
        for t in rewritten:
            residual_expr = t if residual_expr is None else (residual_expr & t)

    dq = copy.copy(iq)
    dq.ctes = []  # outer plan_query already folded CTEs into ``views``
    dq.items = items if items else None  # uncorrelated EXISTS: any row at all
    # EXISTS only needs distinct tuples of (keys + residual columns): dedup
    # bounds the eval-time merge at one row per combination
    dq.distinct = bool(items)
    dq.where = None
    w: Optional[Expr] = None
    for t in inner_preds:
        w = t if w is None else (w & t)
    dq.where = w
    dq.order_by, dq.limit = [], None
    inner_df = plan_query(dq, views)
    if not items:
        # uncorrelated EXISTS — mark is row-count > 0, keyless
        return ExistsSubquery([], inner_df.limit(1).plan, [], None, [], session)

    outer_keys = [_resolve_expr_refs(oe, outer_resolve) for oe, _ in pairs]
    return ExistsSubquery(
        outer_keys, inner_df.plan, key_cols, residual_expr, residual_outer, session
    )


def decorrelate_in(child: Expr, iq, views, session, outer_resolve) -> CorrelatedInSubquery:
    """Rewrite ``x IN (SELECT v FROM ... WHERE outer.k = inner.k ...)`` to a
    CorrelatedInSubquery (group membership with three-valued semantics; the
    reference inherits Spark's null-aware semi/anti join for this)."""
    from hyperspace_tpu.plan.sql import SelectItem, SqlError, _resolve_expr_refs, plan_query

    if iq.unions or iq.group_by or iq.having is not None or iq.items is None:
        raise SqlError(
            "Correlated IN subqueries with set operations, GROUP BY, or "
            "SELECT * are not supported"
        )
    if len(iq.items) != 1:
        raise SqlError("An IN subquery must select exactly one column")
    if iq.limit is not None:
        # unlike EXISTS (any row at all), LIMIT changes the membership set;
        # dropping it silently would change results
        raise SqlError("Correlated IN subqueries with LIMIT are not supported")
    from hyperspace_tpu.plan.sql import _contains_agg

    if _contains_agg(iq.items[0].expr):
        raise SqlError("Aggregates in correlated IN subqueries are not supported")
    try:
        scope, inner_preds, correlated = _split_correlation(iq, views)
        pairs, residual_terms = _equi_pairs_and_residual(correlated, scope)
    except _Unsupported as e:
        raise SqlError(f"Unsupported correlated IN subquery: {e}")
    if residual_terms or not pairs:
        raise SqlError(
            "Correlated IN subqueries support only equality correlation "
            "(outer.col = inner.col)"
        )
    key_cols = [f"__k{i}" for i in range(len(pairs))]
    dq = copy.copy(iq)
    dq.ctes = []
    dq.items = [
        SelectItem(Col(inner_name), kc, inner_name)
        for kc, (_, inner_name) in zip(key_cols, pairs)
    ] + [SelectItem(iq.items[0].expr, "__inval", iq.items[0].text)]
    dq.distinct = True  # membership: one row per distinct (keys, value) tuple
    w: Optional[Expr] = None
    for t in inner_preds:
        w = t if w is None else (w & t)
    dq.where = w
    dq.order_by, dq.limit = [], None
    inner_df = plan_query(dq, views)
    outer_keys = [_resolve_expr_refs(oe, outer_resolve) for oe, _ in pairs]
    return CorrelatedInSubquery(child, outer_keys, inner_df.plan, key_cols, "__inval", session)


def _empty_group_default(expr: Expr):
    """The scalar value the subquery's select expression takes over a
    zero-row group: COUNT aggregates are 0, every other aggregate is NULL,
    and the surrounding expression is folded over those (count(*)*2 -> 0,
    avg(x)+1 -> NULL, coalesce(count(x), 5) -> 0). None means SQL NULL."""
    import numpy as np

    from hyperspace_tpu.plan.expr import Lit
    from hyperspace_tpu.plan.sql import _AggCall, _map_expr

    def leaf(x):
        if isinstance(x, _AggCall):
            return Lit(0) if x.fn.startswith("count") else Lit(np.nan)
        return None

    probe = _map_expr(expr, leaf)
    try:
        v = np.asarray(probe.eval({}))
        if v.ndim != 0:
            return None
        item = v.item()
        if item is None or (isinstance(item, float) and item != item):
            return None
        return item
    except Exception:
        return None


def decorrelate_scalar(iq, views, session, outer_resolve) -> CorrelatedScalarSubquery:
    """Rewrite a correlated scalar subquery to GROUP BY its correlation keys."""
    from hyperspace_tpu.plan.sql import (
        SelectItem,
        SqlError,
        _AggCall,
        _contains_agg,
        _resolve_expr_refs,
        plan_query,
    )

    if iq.unions or iq.group_by or iq.having is not None or iq.items is None:
        raise SqlError(
            "Correlated scalar subqueries with set operations, GROUP BY, or "
            "SELECT * are not supported"
        )
    if len(iq.items) != 1:
        raise SqlError("A scalar subquery must select exactly one item")
    try:
        scope, inner_preds, correlated = _split_correlation(iq, views)
        pairs, residual_terms = _equi_pairs_and_residual(correlated, scope)
    except _Unsupported as e:
        raise SqlError(f"Unsupported correlated scalar subquery: {e}")
    if residual_terms:
        raise SqlError(
            "Correlated scalar subqueries support only equality correlation "
            "(outer.col = inner.col)"
        )
    item = iq.items[0]
    if not _contains_agg(item.expr):
        raise SqlError(
            "A correlated scalar subquery must aggregate (a bare correlated "
            "lookup can return multiple rows per outer row)"
        )

    key_cols = [f"__ck{i}" for i in range(len(pairs))]
    inner_names = [inner_name for _, inner_name in pairs]
    dq = copy.copy(iq)
    dq.ctes = []
    dq.items = [
        SelectItem(Col(n), kc, n) for kc, n in zip(key_cols, inner_names)
    ] + [SelectItem(item.expr, "__scalar", item.text)]
    dq.distinct = False
    w: Optional[Expr] = None
    for t in inner_preds:
        w = t if w is None else (w & t)
    dq.where = w
    dq.group_by = list(inner_names)
    dq.order_by, dq.limit = [], None
    inner_df = plan_query(dq, views)

    # the count-bug: COUNT over an empty group is 0, not NULL — and the whole
    # select expression may wrap it (count(*)*2, coalesce(count(x), 0)), so
    # the default is the expression evaluated over a zero-row group
    default = _empty_group_default(item.expr)
    outer_keys = [_resolve_expr_refs(oe, outer_resolve) for oe, _ in pairs]
    return CorrelatedScalarSubquery(
        outer_keys, inner_df.plan, key_cols, "__scalar", default, session
    )
