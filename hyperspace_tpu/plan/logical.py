"""Logical plan IR.

A minimal relational algebra — Scan / Filter / Project / Join / Union plus the
index-specific nodes the optimizer rewrites plans into: ``IndexScan`` (replaces
a source scan; ref: IndexHadoopFsRelation, HS/index/plans/logical/IndexHadoopFsRelation.scala:29-50),
``Repartition`` (on-the-fly re-bucketing of appended data; ref:
HS/index/covering/CoveringIndexRuleUtils.scala:357-417) and ``BucketUnion``
(partition-preserving union; ref: HS/index/plans/logical/BucketUnion.scala:31-68).

Scope is intentionally the slice of Catalyst the reference's rules accept:
linear plans of Project→Filter→Scan and equi-joins of such
(ref: HS/index/covering/JoinIndexRule.scala:135-155).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence, Tuple

from hyperspace_tpu.plan.expr import Expr


@dataclass(frozen=True)
class BucketSpec:
    """Hash-bucket layout of stored data: ``num_buckets`` buckets over
    ``bucket_columns``, rows sorted by ``sort_columns`` within each bucket
    (ref: Spark BucketSpec as used at HS/index/covering/CoveringIndex.scala:173-177)."""

    num_buckets: int
    bucket_columns: Tuple[str, ...]
    sort_columns: Tuple[str, ...]

    def to_dict(self) -> Dict[str, Any]:
        return {
            "numBuckets": self.num_buckets,
            "bucketColumns": list(self.bucket_columns),
            "sortColumns": list(self.sort_columns),
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "BucketSpec":
        return cls(d["numBuckets"], tuple(d["bucketColumns"]), tuple(d["sortColumns"]))


class LogicalPlan:
    """Base plan node. Nodes are immutable-by-convention; rewrites build new trees."""

    def children(self) -> Sequence["LogicalPlan"]:
        return ()

    @property
    def output_columns(self) -> List[str]:
        raise NotImplementedError

    def with_children(self, children: Sequence["LogicalPlan"]) -> "LogicalPlan":
        raise NotImplementedError

    def pretty(self, indent: int = 0) -> str:
        line = "  " * indent + self.describe()
        return "\n".join([line] + [c.pretty(indent + 1) for c in self.children()])

    def describe(self) -> str:
        return type(self).__name__

    def __repr__(self) -> str:
        return self.pretty()


class Scan(LogicalPlan):
    """Scan over a source relation (ref: Spark LogicalRelation over
    HadoopFsRelation; SPI: HS/index/sources/interfaces.scala:43-158)."""

    def __init__(self, relation: "FileBasedRelation"):  # noqa: F821
        self.relation = relation

    @property
    def output_columns(self) -> List[str]:
        return [f.name for f in self.relation.schema]

    def with_children(self, children: Sequence[LogicalPlan]) -> "Scan":
        assert not children
        return self

    def describe(self) -> str:
        return f"Scan({self.relation.name}, format={self.relation.file_format})"


class Filter(LogicalPlan):
    def __init__(self, condition: Expr, child: LogicalPlan):
        self.condition = condition
        self.child = child

    def children(self) -> Sequence[LogicalPlan]:
        return (self.child,)

    @property
    def output_columns(self) -> List[str]:
        return self.child.output_columns

    def with_children(self, children: Sequence[LogicalPlan]) -> "Filter":
        (child,) = children
        return Filter(self.condition, child)

    def describe(self) -> str:
        return f"Filter({self.condition!r})"


class Project(LogicalPlan):
    def __init__(self, columns: List[str], child: LogicalPlan):
        self.columns = list(columns)
        self.child = child

    def children(self) -> Sequence[LogicalPlan]:
        return (self.child,)

    @property
    def output_columns(self) -> List[str]:
        return list(self.columns)

    def with_children(self, children: Sequence[LogicalPlan]) -> "Project":
        (child,) = children
        return Project(self.columns, child)

    def describe(self) -> str:
        return f"Project({self.columns})"


class Compute(LogicalPlan):
    """Computed columns: appends ``name = expr`` outputs to the child's
    columns (SQL expressions in the SELECT list, aggregate-input expressions,
    post-aggregate arithmetic). The reference delegates expression projection
    to Spark's Project; index rewrite rules recurse through this node
    untouched, exactly as they do through Project."""

    def __init__(self, exprs: List[Tuple[str, "Expr"]], child: LogicalPlan):
        taken = set(child.output_columns)
        names = [n for n, _ in exprs]
        if len(set(names)) != len(names):
            raise ValueError(f"Duplicate computed column names: {names}")
        clash = [n for n in names if n in taken]
        if clash:
            raise ValueError(f"Computed columns {clash} collide with child outputs")
        self.exprs = [(n, e) for n, e in exprs]
        self.child = child

    def children(self) -> Sequence[LogicalPlan]:
        return (self.child,)

    @property
    def output_columns(self) -> List[str]:
        return self.child.output_columns + [n for n, _ in self.exprs]

    def with_children(self, children: Sequence[LogicalPlan]) -> "Compute":
        (child,) = children
        return Compute(self.exprs, child)

    def describe(self) -> str:
        parts = [f"{n}={e!r}" for n, e in self.exprs]
        return f"Compute({', '.join(parts)})"


def join_output_names(left_cols: List[str], right_cols: List[str]) -> Tuple[List[str], Dict[str, str]]:
    """Join output naming: right-side duplicates get a '#r' suffix, repeated
    until unique (a second join whose right side collides with an existing
    'x#r' yields 'x#r#r'). Returns (output names, right-col rename map) —
    the single source of truth for planning AND execution."""
    out = list(left_cols)
    taken = set(left_cols)
    rename: Dict[str, str] = {}
    for c in right_cols:
        name = c
        while name in taken:
            name = f"{name}#r"
        if name != c:
            rename[c] = name
        taken.add(name)
        out.append(name)
    return out, rename


class Join(LogicalPlan):
    """Equi-join. ``condition`` must be a conjunction of col = col terms
    (the only shape the reference's JoinIndexRule accepts,
    ref: HS/index/covering/JoinIndexRule.scala:149-155).

    ``residual`` carries any extra non-equi ON-clause predicate (TPC-H q13's
    ``LEFT JOIN orders ON c_custkey = o_custkey AND o_comment NOT LIKE ...``):
    it is evaluated over the matched pairs DURING the join — for outer joins
    a pair failing the residual null-extends instead of matching, which a
    post-join filter cannot express. References use post-join (renamed)
    column names. Index rules ignore joins with a residual (the reference's
    rules are equi-CNF-only too)."""

    def __init__(
        self,
        left: LogicalPlan,
        right: LogicalPlan,
        condition: Expr,
        how: str = "inner",
        residual: Optional[Expr] = None,
        using_pairs: Optional[List[Tuple[str, str]]] = None,
    ):
        self.left = left
        self.right = right
        self.condition = condition
        self.how = how
        self.residual = residual
        # (left key, right key) name pairs when the join came from a
        # USING-style dataframe ``on="k"``: Spark coalesces the key column
        # across sides, so a right/outer join's unmatched rows must show the
        # RIGHT side's key under the left name, not NULL. Execution paths
        # honor this; ON-condition joins leave it None (both keys retained
        # verbatim, qualified access).
        self.using_pairs = using_pairs

    def children(self) -> Sequence[LogicalPlan]:
        return (self.left, self.right)

    @property
    def output_columns(self) -> List[str]:
        out, _ = join_output_names(self.left.output_columns, self.right.output_columns)
        return out

    def with_children(self, children: Sequence[LogicalPlan]) -> "Join":
        left, right = children
        return Join(
            left, right, self.condition, self.how, self.residual, self.using_pairs
        )

    def describe(self) -> str:
        if self.residual is not None:
            return f"Join({self.condition!r}, how={self.how}, residual={self.residual!r})"
        return f"Join({self.condition!r}, how={self.how})"


class Union(LogicalPlan):
    def __init__(self, children_: List[LogicalPlan]):
        self._children = list(children_)

    def children(self) -> Sequence[LogicalPlan]:
        return tuple(self._children)

    @property
    def output_columns(self) -> List[str]:
        return self._children[0].output_columns

    def with_children(self, children: Sequence[LogicalPlan]) -> "Union":
        return Union(list(children))


class SetOp(LogicalPlan):
    """INTERSECT / EXCEPT set operations (distinct semantics, NULLs compare
    equal — SQL set-operation rules). Children align positionally; output
    schema is the left child's."""

    def __init__(self, kind: str, left: LogicalPlan, right: LogicalPlan):
        if kind not in ("intersect", "except"):
            raise ValueError(f"Unknown set operation {kind!r}")
        if len(left.output_columns) != len(right.output_columns):
            raise ValueError(
                f"{kind.upper()} inputs have {len(left.output_columns)} vs "
                f"{len(right.output_columns)} columns"
            )
        self.kind = kind
        self.left = left
        self.right = right

    def children(self) -> Sequence[LogicalPlan]:
        return (self.left, self.right)

    @property
    def output_columns(self) -> List[str]:
        return self.left.output_columns

    def with_children(self, children: Sequence[LogicalPlan]) -> "SetOp":
        left, right = children
        return SetOp(self.kind, left, right)

    def describe(self) -> str:
        return f"SetOp({self.kind})"


# --- index-side nodes (appear only in rewritten plans) ----------------------


class FileScan(LogicalPlan):
    """Scan of an explicit file list (used for the appended-files side of
    hybrid scan; ref: CoveringIndexRuleUtils' appended-data scan,
    HS/index/covering/CoveringIndexRuleUtils.scala:206-243)."""

    def __init__(
        self,
        files: List[str],
        file_format: str,
        columns: List[str],
        via_index: Optional[str] = None,
        partition_values: Optional[dict] = None,
        partition_dtypes: Optional[dict] = None,
        format_options: Optional[dict] = None,
    ):
        self.files = list(files)
        self.file_format = file_format
        self.columns = list(columns)
        # reader options of the source relation (e.g. csv delimiter/header)
        self.format_options = dict(format_options) if format_options else None
        # name of the index whose rewrite produced this scan (e.g. a
        # data-skipping prune), for explain/whyNot reporting
        self.via_index = via_index
        # hive-partition values per file ({file -> {col -> typed value}}) for
        # partition columns the requested ``columns`` include but the file
        # bytes do not carry
        self.partition_values = partition_values
        self.partition_dtypes = partition_dtypes

    @property
    def output_columns(self) -> List[str]:
        return list(self.columns)

    def with_children(self, children: Sequence[LogicalPlan]) -> "FileScan":
        assert not children
        return self

    def describe(self) -> str:
        via = f", Hyperspace(Type: DS, Name: {self.via_index})" if self.via_index else ""
        return f"FileScan({len(self.files)} files, format={self.file_format}{via})"


class IndexScan(LogicalPlan):
    """Scan of covering-index data files instead of source files.

    ``pruned_buckets`` — when bucket pruning applies (selective equality
    predicate on the first indexed column), only those buckets' files are read
    (ref: FilterIndexRule's useBucketSpec path,
    HS/index/covering/FilterIndexRule.scala:162-167).
    """

    def __init__(
        self,
        entry: "IndexLogEntry",  # noqa: F821
        columns: List[str],
        bucket_spec: Optional[BucketSpec],
        files: Optional[List[str]] = None,
        pruned_buckets: Optional[List[int]] = None,
        file_columns: Optional[List[str]] = None,
    ):
        self.entry = entry
        self.columns = list(columns)
        self.bucket_spec = bucket_spec
        self.files = files if files is not None else entry.content.files
        self.pruned_buckets = pruned_buckets
        # parallel to ``columns``: the flat column names inside the index
        # parquet files when they differ from the output names (nested fields
        # are stored under their __hs_nested.-prefixed flat name)
        self.file_columns = list(file_columns) if file_columns is not None else None

    @property
    def output_columns(self) -> List[str]:
        return list(self.columns)

    def file_column_of(self, output_col: str) -> str:
        if self.file_columns is None:
            return output_col
        try:
            return self.file_columns[self.columns.index(output_col)]
        except ValueError:
            return output_col

    def with_children(self, children: Sequence[LogicalPlan]) -> "IndexScan":
        assert not children
        return self

    def describe(self) -> str:
        extra = f", prunedBuckets={self.pruned_buckets}" if self.pruned_buckets is not None else ""
        n = self.bucket_spec.num_buckets if self.bucket_spec else None
        return (
            f"IndexScan(Hyperspace(Type: CI, Name: {self.entry.name}, "
            f"LogVersion: {self.entry.id}), buckets={n}{extra})"
        )


class Aggregate(LogicalPlan):
    """Hash aggregation: ``keys`` group-by columns (empty = global) and
    ``aggs`` as (output name, fn, input column) with fn in
    count/sum/min/max/avg — the slice of aggregation the dataframe facade
    offers around indexed scans (the reference delegates aggregation to
    Spark; index rewrites apply beneath this node untouched)."""

    FNS = (
        "count", "sum", "min", "max", "avg",
        "count_distinct", "sum_distinct", "avg_distinct", "stddev_samp",
    )

    def __init__(self, keys: List[str], aggs: List[tuple], child: LogicalPlan):
        self.keys = list(keys)
        self.aggs = [tuple(a) for a in aggs]
        for _, fn, _ in self.aggs:
            if fn not in self.FNS:
                raise ValueError(f"Unsupported aggregate fn {fn!r}; one of {self.FNS}")
        seen = set(self.keys)
        for name, _, _ in self.aggs:
            if name in seen:
                raise ValueError(f"Duplicate aggregate output name {name!r} (collides with a key or another aggregate)")
            seen.add(name)
        self.child = child

    def children(self) -> Sequence[LogicalPlan]:
        return (self.child,)

    @property
    def output_columns(self) -> List[str]:
        return self.keys + [name for name, _, _ in self.aggs]

    def with_children(self, children: Sequence[LogicalPlan]) -> "Aggregate":
        (child,) = children
        return Aggregate(self.keys, self.aggs, child)

    def describe(self) -> str:
        parts = [f"{name}={fn}({col_ or '*'})" for name, fn, col_ in self.aggs]
        return f"Aggregate(keys={self.keys}, [{', '.join(parts)}])"


class Window(LogicalPlan):
    """Window functions: appends one column per spec, preserving row count
    and order. Each spec is (out_name, fn, arg_col_or_None, partition_cols,
    order_keys, cumulative) with fn in rank/dense_rank/row_number/
    count/sum/min/max/avg; ``order_keys`` are (column, ascending) pairs;
    ``cumulative`` marks an explicit ROWS UNBOUNDED PRECEDING..CURRENT ROW
    frame for aggregate fns. (The reference delegates windows to Spark; the
    TPC-DS q12/q47/q51/q53-family shapes drive this surface.)"""

    FNS = ("rank", "dense_rank", "row_number", "count", "sum", "min", "max", "avg")

    def __init__(self, specs: List[tuple], child: LogicalPlan):
        taken = set(child.output_columns)
        for spec in specs:
            out, fn, arg, parts, orders, cumulative = spec
            if fn not in self.FNS:
                raise ValueError(f"Unsupported window fn {fn!r}; one of {self.FNS}")
            if out in taken:
                raise ValueError(f"Window output {out!r} collides with an existing column")
            taken.add(out)
        self.specs = [tuple(s) for s in specs]
        self.child = child

    def children(self) -> Sequence[LogicalPlan]:
        return (self.child,)

    @property
    def output_columns(self) -> List[str]:
        return self.child.output_columns + [s[0] for s in self.specs]

    def with_children(self, children: Sequence[LogicalPlan]) -> "Window":
        (child,) = children
        return Window(self.specs, child)

    def describe(self) -> str:
        parts = []
        for out, fn, arg, pcols, orders, cumulative in self.specs:
            over = []
            if pcols:
                over.append(f"partition by {list(pcols)}")
            if orders:
                over.append(f"order by {list(orders)}")
            if cumulative:
                over.append("rows unbounded preceding")
            parts.append(f"{out}={fn}({arg or ''}) over ({', '.join(over)})")
        return f"Window({'; '.join(parts)})"


class Rename(LogicalPlan):
    """Column renaming (SQL ``AS`` aliases). Purely cosmetic at the top of a
    plan: data and row order pass through, only names change (the reference
    delegates aliasing to Spark's analyzer)."""

    def __init__(self, mapping: dict, child: LogicalPlan):
        out = child.output_columns
        unknown = [k for k in mapping if k not in out]
        if unknown:
            raise ValueError(f"Cannot rename unknown columns {unknown} among {out}")
        renamed = [mapping.get(c, c) for c in out]
        if len(set(renamed)) != len(renamed):
            raise ValueError(f"Rename produces duplicate output names: {renamed}")
        self.mapping = dict(mapping)
        self.child = child

    def children(self) -> Sequence[LogicalPlan]:
        return (self.child,)

    @property
    def output_columns(self) -> List[str]:
        return [self.mapping.get(c, c) for c in self.child.output_columns]

    def with_children(self, children: Sequence[LogicalPlan]) -> "Rename":
        (child,) = children
        return Rename(self.mapping, child)

    def describe(self) -> str:
        return f"Rename({self.mapping})"


class Sort(LogicalPlan):
    """Order-by over (column, ascending) keys; host-side stable lexsort."""

    def __init__(self, keys: List[tuple], child: LogicalPlan):
        self.keys = [tuple(k) for k in keys]  # (column, ascending)
        self.child = child

    def children(self) -> Sequence[LogicalPlan]:
        return (self.child,)

    @property
    def output_columns(self) -> List[str]:
        return self.child.output_columns

    def with_children(self, children: Sequence[LogicalPlan]) -> "Sort":
        (child,) = children
        return Sort(self.keys, child)

    def describe(self) -> str:
        parts = [f"{c} {'ASC' if asc else 'DESC'}" for c, asc in self.keys]
        return f"Sort({', '.join(parts)})"


class Limit(LogicalPlan):
    def __init__(self, n: int, child: LogicalPlan):
        if n < 0:
            raise ValueError("limit must be non-negative")
        self.n = int(n)
        self.child = child

    def children(self) -> Sequence[LogicalPlan]:
        return (self.child,)

    @property
    def output_columns(self) -> List[str]:
        return self.child.output_columns

    def with_children(self, children: Sequence[LogicalPlan]) -> "Limit":
        (child,) = children
        return Limit(self.n, child)

    def describe(self) -> str:
        return f"Limit({self.n})"


class Repartition(LogicalPlan):
    """Hash-repartition child rows into ``bucket_spec`` buckets — injected on
    top of appended-data scans so hybrid scan can merge with index buckets.
    On TPU this lowers to on-device hashing + all-to-all over ICI
    (ref: RepartitionByExpression injection,
    HS/index/covering/CoveringIndexRuleUtils.scala:357-417)."""

    def __init__(self, bucket_spec: BucketSpec, child: LogicalPlan):
        self.bucket_spec = bucket_spec
        self.child = child

    def children(self) -> Sequence[LogicalPlan]:
        return (self.child,)

    @property
    def output_columns(self) -> List[str]:
        return self.child.output_columns

    def with_children(self, children: Sequence[LogicalPlan]) -> "Repartition":
        (child,) = children
        return Repartition(self.bucket_spec, child)

    def describe(self) -> str:
        return f"Repartition(n={self.bucket_spec.num_buckets}, cols={list(self.bucket_spec.bucket_columns)})"


class BucketUnion(LogicalPlan):
    """Union preserving bucket layout: all children share the same
    ``bucket_spec``; the i-th bucket of the output is the concatenation of the
    i-th buckets of the children — no reshuffle
    (ref: HS/index/plans/logical/BucketUnion.scala:31-68,
    HS/index/execution/BucketUnionExec.scala:52-121)."""

    def __init__(self, children_: List[LogicalPlan], bucket_spec: BucketSpec):
        self._children = list(children_)
        self.bucket_spec = bucket_spec

    def children(self) -> Sequence[LogicalPlan]:
        return tuple(self._children)

    @property
    def output_columns(self) -> List[str]:
        return self._children[0].output_columns

    def with_children(self, children: Sequence[LogicalPlan]) -> "BucketUnion":
        return BucketUnion(list(children), self.bucket_spec)

    def describe(self) -> str:
        return f"BucketUnion(n={self.bucket_spec.num_buckets})"


# --- traversal helpers ------------------------------------------------------

def collect(plan: LogicalPlan, predicate) -> List[LogicalPlan]:
    out = []
    if predicate(plan):
        out.append(plan)
    for c in plan.children():
        out.extend(collect(c, predicate))
    return out


def transform_up(plan: LogicalPlan, fn) -> LogicalPlan:
    new_children = [transform_up(c, fn) for c in plan.children()]
    if list(new_children) != list(plan.children()):
        plan = plan.with_children(new_children)
    return fn(plan)


def plan_key(plan: LogicalPlan) -> int:
    """Stable per-process identity used for tagging (the reference tags plan
    objects directly; ref: HS/index/IndexLogEntry.scala:519-571)."""
    return id(plan)
