"""Order-propagation analysis: when does stored sortedness satisfy a Sort?

Covering indexes are written bucketed AND sorted by the indexed columns
within each bucket (plan/logical.BucketSpec.sort_columns, the layout the
fused build program in ops/sort.py produces) — order the executor used to
recompute from scratch with a full host sort. This module is the planner
half of sort elimination: decide whether a ``Sort``'s requirement is
satisfied by the within-bucket order of the ``IndexScan`` underneath it, so
the executor can replace the O(n log n) sort with a streamed k-way merge of
already-sorted per-file runs (exec/executor._merge_sorted_runs).

Eligibility is deliberately strict; every rejection returns a *reason*
string that flows into dispatch traces and the QueryProfile why-not report
(analysis/why_not.py), mirroring the index-selection reason machinery.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from hyperspace_tpu.plan import logical as L

#: chain nodes that neither reorder rows nor rebind the sort-key columns
_ORDER_PRESERVING = (L.Filter, L.Project)


def _order_chain(plan: L.LogicalPlan):
    """Walk Filter/Project/Compute/Rename down to the scan (the ordering
    analog of executor._chain_to_scan; Compute/Rename are collected so the
    eligibility check can *name* them in its reason instead of silently
    missing the scan)."""
    chain: List[L.LogicalPlan] = []
    node = plan
    while isinstance(node, (L.Filter, L.Project, L.Compute, L.Rename)):
        chain.append(node)
        node = node.child
    return chain, node


def index_sort_order(leaf: L.LogicalPlan) -> List[Tuple[str, bool]]:
    """The within-bucket physical ordering an IndexScan's files carry:
    ascending over ``bucket_spec.sort_columns``, or [] when unknown.

    A plan-level ``bucket_spec`` is only attached under ``useBucketSpec``
    (it gates bucket *pruning*), but the data files are written sorted either
    way — so fall back to the log entry's own spec. Sortedness is advisory
    here regardless: the executor verifies every run and stable-repairs
    disagreement, so a wrong answer is impossible, only a slower merge."""
    if not isinstance(leaf, L.IndexScan):
        return []
    spec = getattr(leaf, "bucket_spec", None)
    if spec is None and getattr(leaf, "entry", None) is not None:
        try:
            from hyperspace_tpu.indexes.covering import CoveringIndex

            spec = CoveringIndex.from_derived_dataset(leaf.entry.derived_dataset).bucket_spec()
        except Exception:
            spec = None
    if spec is not None and spec.sort_columns:
        return [(str(c), True) for c in spec.sort_columns]
    return []


def required_ordering(plan: L.LogicalPlan) -> Optional[List[Tuple[str, bool]]]:
    """The outermost Sort requirement visible through Limit/Project wrappers
    — what the index ranker (rules/filter_rule._rank) can use as a
    tie-break toward order-covering candidates."""
    node = plan
    while isinstance(node, (L.Limit, L.Project)):
        node = node.child
    if isinstance(node, L.Sort) and node.keys:
        return [(str(c), bool(a)) for c, a in node.keys]
    return None


def sort_run_eligibility(sort_plan: L.Sort):
    """Can ``sort_plan`` be satisfied by merging the index's sorted runs?

    Returns ``(leaf, chain, None)`` on success, ``(None, None, reason)``
    when an index-backed chain exists but its order doesn't cover the sort,
    and ``(None, None, None)`` when the child isn't index-backed at all
    (nothing to explain — raw file scans carry no order)."""
    chain, leaf = _order_chain(sort_plan.child)
    if not isinstance(leaf, L.IndexScan):
        return None, None, None
    order = index_sort_order(leaf)
    if not order:
        return None, None, "index scan carries no within-bucket sort order"
    offenders = [type(nd).__name__ for nd in chain if not isinstance(nd, _ORDER_PRESERVING)]
    if offenders:
        return None, None, (
            f"{'/'.join(sorted(set(offenders)))} between Sort and the scan may rebind the key columns"
        )
    keys = [(str(c), bool(a)) for c, a in sort_plan.keys]
    if not keys:
        return None, None, "Sort has no keys"
    desc = [c for c, a in keys if not a]
    if desc:
        return None, None, (
            f"descending key(s) {desc} cannot ride the ascending index order"
        )
    want = [c.lower() for c, _ in keys]
    have = [c.lower() for c, _ in order]
    if want != have[: len(want)]:
        return None, None, (
            f"sort keys {want} are not a prefix of the index sort order {have}"
        )
    return leaf, chain, None
