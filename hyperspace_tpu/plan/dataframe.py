"""User-facing DataFrame facade.

A thin, lazy wrapper over the logical plan so that
``hs.create_index(df, CoveringIndexConfig(...))`` and queries have something to
operate on (SURVEY.md §7 stage 3). Collect triggers: optimizer rewrite (when
Hyperspace is enabled on the session) then physical execution.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Union as TUnion

import numpy as np

from hyperspace_tpu.plan import logical as L
from hyperspace_tpu.plan.expr import Col, Expr, col
from hyperspace_tpu.plan.resolver import resolve_column, resolve_expr


class DataFrame:
    def __init__(self, plan: L.LogicalPlan, session):
        self.plan = plan
        self.session = session

    # --- transformations ---------------------------------------------------
    def filter(self, condition: Expr) -> "DataFrame":
        resolved = resolve_expr(condition, self.plan.output_columns)
        return DataFrame(L.Filter(resolved, self.plan), self.session)

    where = filter

    def select(self, *columns: TUnion[str, Col]) -> "DataFrame":
        names = []
        for c in columns:
            name = c.name if isinstance(c, Col) else str(c)
            resolved = resolve_column(name, self.plan.output_columns)
            if resolved is None:
                raise ValueError(f"Column {name!r} not found among {self.plan.output_columns}")
            names.append(resolved)
        return DataFrame(L.Project(names, self.plan), self.session)

    def join(
        self,
        other: "DataFrame",
        on: TUnion[str, List[str], Expr],
        how: str = "inner",
        residual: Optional[Expr] = None,
    ) -> "DataFrame":
        """``residual`` carries a non-equi ON-clause predicate evaluated
        during the join (post-join column names) — for outer joins a failing
        pair null-extends instead of matching."""
        using_pairs = None
        if isinstance(on, Expr):
            condition = on
        else:
            keys = [on] if isinstance(on, str) else list(on)
            terms: Optional[Expr] = None
            using_pairs = []
            for k in keys:
                lk = resolve_column(k, self.plan.output_columns)
                rk = resolve_column(k, other.plan.output_columns)
                if lk is None or rk is None:
                    raise ValueError(f"Join key {k!r} must exist on both sides")
                term = col(lk) == col(rk)
                terms = term if terms is None else (terms & term)
                using_pairs.append((lk, rk))
            assert terms is not None
            condition = terms
        return DataFrame(
            L.Join(self.plan, other.plan, condition, how, residual, using_pairs),
            self.session,
        )

    def group_by(self, *keys: TUnion[str, Col]) -> "GroupedData":
        resolved = []
        for k in keys:
            name = k.name if isinstance(k, Col) else str(k)
            r = resolve_column(name, self.plan.output_columns)
            if r is None:
                raise ValueError(f"Column {name!r} not found among {self.plan.output_columns}")
            resolved.append(r)
        return GroupedData(self, resolved)

    groupBy = group_by

    def agg(self, **aggs) -> "DataFrame":
        """Global aggregates: ``df.agg(total=("v", "sum"), n=("*", "count"))``."""
        return GroupedData(self, []).agg(**aggs)

    def order_by(self, *keys: TUnion[str, Col], ascending: TUnion[bool, List[bool]] = True) -> "DataFrame":
        names = []
        for k in keys:
            name = k.name if isinstance(k, Col) else str(k)
            r = resolve_column(name, self.plan.output_columns)
            if r is None:
                raise ValueError(f"Column {name!r} not found among {self.plan.output_columns}")
            names.append(r)
        asc = [ascending] * len(names) if isinstance(ascending, bool) else list(ascending)
        if len(asc) != len(names):
            raise ValueError("ascending must be a bool or match the number of sort keys")
        return DataFrame(L.Sort(list(zip(names, asc)), self.plan), self.session)

    orderBy = sort = order_by

    def limit(self, n: int) -> "DataFrame":
        return DataFrame(L.Limit(n, self.plan), self.session)

    def distinct(self) -> "DataFrame":
        """Distinct rows over all output columns (grouped aggregation with
        the helper count projected away)."""
        cols = list(self.plan.output_columns)
        agg = L.Aggregate(cols, [("__distinct_count", "count", None)], self.plan)
        return DataFrame(L.Project(cols, agg), self.session)

    dropDuplicates = drop_duplicates = distinct

    def as_scalar(self) -> Expr:
        """This one-column frame as a scalar-subquery expression, usable as a
        comparison operand: ``df.filter(col("a") == other.select("b").as_scalar())``.
        Index rewrites apply inside the subquery (ref: the reference's
        `subquery` explain golden, src/test/resources/expected/spark-2.4/subquery.txt)."""
        from hyperspace_tpu.plan.expr import ScalarSubquery

        return ScalarSubquery(self.plan, self.session)

    asScalar = as_scalar

    def create_or_replace_temp_view(self, name: str) -> None:
        """Register this frame for ``session.sql()`` (Spark's temp-view role)."""
        self.session.register_view(name, self)

    createOrReplaceTempView = create_or_replace_temp_view

    # --- actions -----------------------------------------------------------
    def optimized_plan(self) -> L.LogicalPlan:
        from hyperspace_tpu.rules.apply import optimize_plan

        return optimize_plan(self.plan, self.session)

    def collect(self) -> Dict[str, np.ndarray]:
        """Execute and return columns as numpy arrays.

        Arrays may be read-only views of the scan cache (pass-through plans
        share decoded buffers across queries); ``np.copy`` one before
        mutating it in place.

        With ``hyperspace.obs.tracing.enabled`` and no trace already active
        in this context, the whole call is traced and the resulting
        ``QueryProfile`` is retrievable via ``session.last_query_profile()``.
        A trace already active (a QueryServer request, an outer traced block)
        just gains child spans instead of rooting a second tree.
        """
        from hyperspace_tpu.exec.executor import Executor
        from hyperspace_tpu.obs import spans

        conf = self.session.conf
        if not conf.obs_tracing_enabled or spans.current_span() is not None:
            plan = self.optimized_plan()
            return Executor(self.session).execute(plan, required_columns=plan.output_columns)

        from hyperspace_tpu.obs.profile import build_profile

        error = None
        with spans.trace("query", max_spans=conf.obs_trace_max_spans) as root:
            try:
                plan = self.optimized_plan()
                with spans.span("execute", cat="exec"):
                    return Executor(self.session).execute(
                        plan, required_columns=plan.output_columns
                    )
            except BaseException as e:
                error = type(e).__name__
                raise
            finally:
                profile = build_profile(root, query=self.plan.describe(), error=error)
                if conf.obs_profile_why_not:
                    try:
                        from hyperspace_tpu.analysis.why_not import why_not_string

                        profile.why_not = why_not_string(self, self.session)
                    except Exception:
                        pass
                self.session._last_profile = profile
                history = self.session.profile_history
                if history is not None:
                    try:
                        from hyperspace_tpu.serving.fingerprint import plan_fingerprint

                        history.record_profile(
                            plan_fingerprint(self.plan).structure, profile
                        )
                    except Exception:
                        pass  # the cost model must never fail a query

    def to_local_iterator(self):
        """Yield the result as a stream of column batches (dict of numpy
        arrays) without materializing the whole result — Spark's
        ``Dataset.toLocalIterator`` role. Plans whose root is a compatible
        bucketed join stream bucket-by-bucket; scan chains stream
        file-group-by-file-group; anything else yields one batch. Chunk
        dtypes may vary (a nullable int column is float64 only in chunks
        holding nulls)."""
        from hyperspace_tpu.exec.executor import Executor

        plan = self.optimized_plan()
        cols = plan.output_columns
        for chunk in Executor(self.session).execute_stream(plan):
            from hyperspace_tpu.exec import batch as B

            yield B.select(chunk, cols)

    toLocalIterator = to_local_iterator  # reference-API casing

    def to_arrow(self):
        from hyperspace_tpu.exec.batch import batch_to_table

        return batch_to_table(self.collect(), self.plan.output_columns)

    def to_pandas(self):
        return self.to_arrow().to_pandas()

    def count(self) -> int:
        from hyperspace_tpu.exec.batch import num_rows

        return num_rows(self.collect())

    @property
    def columns(self) -> List[str]:
        return self.plan.output_columns

    def explain(self) -> str:
        return self.plan.pretty()

    def __repr__(self) -> str:
        return f"DataFrame[{', '.join(self.plan.output_columns)}]"


class GroupedData:
    """``df.group_by(...)`` handle — terminal calls build an Aggregate node.

    ``agg`` takes ``out_name=(input_column, fn)`` pairs with fn in
    count/sum/min/max/avg; ``("*", "count")`` counts rows.
    """

    def __init__(self, df: DataFrame, keys: List[str]):
        self._df = df
        self._keys = keys

    def agg(self, **aggs) -> DataFrame:
        if not aggs:
            raise ValueError("agg() needs at least one aggregate")
        resolved_aggs = []
        available = self._df.plan.output_columns
        for out_name, (col_name, fn) in aggs.items():
            if col_name in ("*", None):
                if str(fn) != "count":
                    raise ValueError(f"('*', {fn!r}) is invalid — only ('*', 'count') counts rows")
                resolved_aggs.append((out_name, str(fn), None))
                continue
            r = resolve_column(str(col_name), available)
            if r is None:
                raise ValueError(f"Column {col_name!r} not found among {available}")
            resolved_aggs.append((out_name, str(fn), r))
        return DataFrame(L.Aggregate(self._keys, resolved_aggs, self._df.plan), self._df.session)

    def count(self) -> DataFrame:
        return self.agg(count=("*", "count"))

    def sum(self, column: str) -> DataFrame:
        return self.agg(**{f"sum({column})": (column, "sum")})

    def min(self, column: str) -> DataFrame:
        return self.agg(**{f"min({column})": (column, "min")})

    def max(self, column: str) -> DataFrame:
        return self.agg(**{f"max({column})": (column, "max")})

    def avg(self, column: str) -> DataFrame:
        return self.agg(**{f"avg({column})": (column, "avg")})

    mean = avg
